"""Variational warm starts for served tenants (arXiv:2405.08857).

In serving, burn-in is per-request latency: a tenant initialized from
PRIOR draws (the solo convention) spends its first recorded rows in an
overdispersed transient, which both wastes sweeps and — worse for the
``on_converged="evict"`` economics — poisons the streaming monitor's
early windows: the Sokal τ estimate over a window containing the
transient reads high, ESS reads low, and the eviction verdict lands
quanta after the chain actually mixed (serve_bench's evict arm
measures exactly this gap; docs/PERFORMANCE.md "Capacity per dollar").

A :class:`WarmStartFit` replaces the prior-draw init with draws from a
moment-matched Gaussian mixture fitted to a SHORT pilot run of the
tenant's own model (a few chains × a few dozen sweeps on the staging
thread — the 2405.08857 recipe with the cheap mixture standing in for
the flow; the ``kind`` registry below is the flow-ready seam: a future
normalizing-flow fit registers a new kind and rides the identical
journal/draw/replay plumbing). One mixture component per pilot chain
keeps multimodal hyper posteriors honest — chains that found different
modes become different components.

Determinism and recovery: the fit is summarized as small JSON-able
arrays and journaled in the tenant's manifest admit record
(serve/manifest.py), and the init draw is a ``numpy`` Philox stream
seeded from the request seed — so :meth:`ChainServer.recover` replays
a warm-started tenant's init bitwise WITHOUT re-running the pilot
(tests/test_recycle.py pins the replay).

Failure contract: warm starting is an optimization, never a
correctness dependency — any pilot/fit failure warns, emits a
``warm_start_degraded`` event and serves the tenant from the cold
prior init (the silent-degradation discipline of every GST_* arm).
"""

from __future__ import annotations

import time
import warnings
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from gibbs_student_t_tpu.models.parameter import KIND_NORMAL


def warm_flow_env() -> str:
    """Validated ``GST_WARM_FLOW`` (``auto`` when unset) — the
    normalizing-flow fit family (round 18, arXiv:2405.08857). Strict
    ``auto|1|0``: ``auto`` honors each spec's requested ``kind``,
    ``1`` upgrades every pilot fit to the masked-affine flow, ``0``
    degrades flow requests to the moment-matched mixture (the fit
    stays WARM — never cold; a ``warm_flow_degraded`` event names
    the downgrade)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_WARM_FLOW")


def resolve_fit_kind(requested: str,
                     env: Optional[str] = None) -> str:
    """The effective fit family for a pilot under ``GST_WARM_FLOW``:
    ``0`` → ``gmm`` always, ``1`` → ``flow`` always, ``auto`` → the
    spec's own ``kind``."""
    env = env if env is not None else warm_flow_env()
    if env == "0":
        return "gmm"
    if env == "1":
        return "flow"
    return requested


def warm_start_env() -> str:
    """Validated ``GST_WARM_START`` (``auto`` when unset) — the
    variational warm-start arm. Strict ``auto|1|0`` (the loud-typo
    contract): ``auto`` honors each request's ``warm_start`` field
    (no request, no pilot); ``1`` warm-starts EVERY tenant with the
    default spec (requests keep their own); ``0`` disables the arm —
    every tenant serves from the cold prior init, bitwise the
    pre-warm-start graph (requests degrade with an event, pinned)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_WARM_START")


@dataclass
class WarmStartSpec:
    """Per-tenant warm-start request (``TenantRequest.warm_start``).

    ``pilot_sweeps`` × ``pilot_chains`` bounds the pilot's compute
    (run once on the staging thread, overlapped with serving);
    ``burn_frac`` discards the pilot's own transient before moment
    matching; ``jitter_frac`` inflates each component's per-param
    std by a floor fraction of the prior scale so a degenerate pilot
    column can never collapse a component to a point mass."""

    pilot_sweeps: int = 64
    pilot_chains: int = 8
    burn_frac: float = 0.5
    jitter_frac: float = 0.02
    #: fit family (``"gmm"`` | ``"flow"``): ``flow`` trains a small
    #: masked-affine (RealNVP-style) flow on the pilot mixture
    #: (arXiv:2405.08857's recipe proper) instead of the per-chain
    #: moment match; ``GST_WARM_FLOW`` can force either family, and a
    #: flow-fit failure degrades to the mixture (warm either way)
    kind: str = "gmm"

    def __post_init__(self):
        if self.kind not in ("gmm", "flow"):
            raise ValueError(
                f"warm-start kind must be 'gmm' or 'flow', got "
                f"{self.kind!r}")
        if self.pilot_sweeps < 8:
            raise ValueError(f"pilot_sweeps must be >= 8, got "
                             f"{self.pilot_sweeps}")
        if self.pilot_chains < 1:
            raise ValueError(f"pilot_chains must be >= 1, got "
                             f"{self.pilot_chains}")
        if not 0.0 <= self.burn_frac < 1.0:
            raise ValueError(f"burn_frac must be in [0, 1), got "
                             f"{self.burn_frac}")
        if self.jitter_frac < 0.0:
            raise ValueError(f"jitter_frac must be >= 0, got "
                             f"{self.jitter_frac}")


@dataclass
class WarmStartFit:
    """A fitted init distribution: ``K`` diagonal-Gaussian components
    over the sampled parameter vector, plus the bookkeeping recovery
    replays from. ``kind`` names the fit family in the registry
    (``"gmm"`` today; a flow fit would add its own and carry its
    parameters the same journaled way)."""

    means: np.ndarray            # (K, p)
    stds: np.ndarray             # (K, p)
    weights: np.ndarray          # (K,)
    kind: str = "gmm"
    pilot_sweeps: int = 0
    pilot_chains: int = 0
    pilot_ms: float = 0.0
    meta: Dict = field(default_factory=dict)

    def draw_x0(self, nchains: int, seed: int,
                specs: np.ndarray) -> np.ndarray:
        """``(nchains, p)`` init draws from the mixture, clipped into
        the prior support (an out-of-support x0 has −inf prior and the
        MH blocks could never leave it). Deterministic in ``seed``
        (numpy Philox) — the bitwise recovery-replay contract."""
        rng = np.random.default_rng(
            np.random.SeedSequence([int(seed) & 0xFFFFFFFF, 0x57A7]))
        k = rng.choice(len(self.weights), size=nchains,
                       p=np.asarray(self.weights, np.float64)
                       / np.sum(self.weights))
        x = (np.asarray(self.means, np.float64)[k]
             + np.asarray(self.stds, np.float64)[k]
             * rng.standard_normal((nchains, self.means.shape[1])))
        return clip_to_support(x, specs)

    def to_json(self) -> Dict:
        return {
            "kind": self.kind,
            "means": np.asarray(self.means, np.float64).tolist(),
            "stds": np.asarray(self.stds, np.float64).tolist(),
            "weights": np.asarray(self.weights, np.float64).tolist(),
            "pilot_sweeps": int(self.pilot_sweeps),
            "pilot_chains": int(self.pilot_chains),
        }

    @classmethod
    def from_json(cls, d: Dict) -> "WarmStartFit":
        kind = d.get("kind", "gmm")
        if kind not in FIT_KINDS:
            raise ValueError(
                f"unknown warm-start fit kind {kind!r} "
                f"(known: {sorted(FIT_KINDS)})")
        tgt = FIT_KINDS[kind]
        if tgt is not cls:
            # kind dispatch: a journaled flow record reconstructs the
            # flow class even through the base entry point (the path
            # resolve_warm_start and recover() take)
            return tgt.from_json(d)
        return cls(means=np.asarray(d["means"], np.float64),
                   stds=np.asarray(d["stds"], np.float64),
                   weights=np.asarray(d["weights"], np.float64),
                   kind=kind,
                   pilot_sweeps=int(d.get("pilot_sweeps", 0)),
                   pilot_chains=int(d.get("pilot_chains", 0)))


#: fit-family registry — the flow-ready seam: each kind maps to its
#: reconstructing class (all journaled through the same admit-record
#: JSON; serve/manifest.py)
FIT_KINDS: Dict[str, type] = {"gmm": WarmStartFit}


@dataclass
class FlowWarmStartFit(WarmStartFit):
    """``kind="flow"``: a small masked-affine (RealNVP-style) flow
    trained on the pooled post-burn pilot samples — the 2405.08857
    recipe proper, riding the mixture's exact journal/draw/replay
    plumbing through :data:`FIT_KINDS`.

    The base-class ``means``/``stds`` are repurposed as the ``(1, p)``
    POOLED standardization stats (``weights == [1.0]``); ``flow``
    carries the coupling-layer parameters as float64 JSON lists.
    Training runs in plain jax on the staging thread (jitted full-batch
    Adam, fixed step count, ``PRNGKey``-seeded init — deterministic per
    pilot), but :meth:`draw_x0` is PURE NUMPY over the journaled
    float64 parameters: base Philox normals → coupling layers →
    de-standardize → :func:`clip_to_support`. JSON round-trip is exact
    for float64, so recovery replays the init bitwise without jax, the
    pilot, or the training loop (the same contract the mixture pins).
    """

    #: {"hidden": H, "layers": [{"mask", "W1", "b1", "W2", "b2"}, ...]}
    #: — float64 nested lists, JSON-exact
    flow: Dict = field(default_factory=dict)
    kind: str = "flow"

    def _forward_np(self, z: np.ndarray) -> np.ndarray:
        """Base normals ``(n, p)`` → standardized flow samples, pure
        float64 numpy (the replay-side transform)."""
        x = np.asarray(z, np.float64)
        p = x.shape[1]
        for lyr in self.flow["layers"]:
            m = np.asarray(lyr["mask"], np.float64)
            w1 = np.asarray(lyr["W1"], np.float64)
            b1 = np.asarray(lyr["b1"], np.float64)
            w2 = np.asarray(lyr["W2"], np.float64)
            b2 = np.asarray(lyr["b2"], np.float64)
            hid = np.tanh((x * m) @ w1 + b1)
            st = hid @ w2 + b2
            s = np.tanh(st[:, :p]) * (1.0 - m)
            t = st[:, p:] * (1.0 - m)
            x = m * x + (1.0 - m) * (x * np.exp(s) + t)
        return x

    def draw_x0(self, nchains: int, seed: int,
                specs: np.ndarray) -> np.ndarray:
        rng = np.random.default_rng(
            np.random.SeedSequence([int(seed) & 0xFFFFFFFF, 0x57A7]))
        z = rng.standard_normal((nchains, self.means.shape[1]))
        x = (np.asarray(self.means, np.float64)[0]
             + np.asarray(self.stds, np.float64)[0]
             * self._forward_np(z))
        return clip_to_support(x, specs)

    def to_json(self) -> Dict:
        d = super().to_json()
        d["flow"] = self.flow
        return d

    @classmethod
    def from_json(cls, d: Dict) -> "FlowWarmStartFit":
        fl = d.get("flow")
        if not fl or not fl.get("layers"):
            raise ValueError("flow fit record missing 'flow' payload")
        return cls(means=np.asarray(d["means"], np.float64),
                   stds=np.asarray(d["stds"], np.float64),
                   weights=np.asarray(d["weights"], np.float64),
                   kind="flow",
                   pilot_sweeps=int(d.get("pilot_sweeps", 0)),
                   pilot_chains=int(d.get("pilot_chains", 0)),
                   flow=fl)

    @classmethod
    def fit(cls, post: np.ndarray, gmm: WarmStartFit,
            spec: "WarmStartSpec", pilot_ms: float = 0.0,
            hidden: int = 16, steps: int = 300,
            lr: float = 5e-3) -> "FlowWarmStartFit":
        """Train the flow on pooled post-burn rows ``(rows, chains,
        p)``. Standardization stds are floored by the already-fitted
        mixture's per-param floors (so a stuck pilot column cannot
        blow up the standardized data), init is ``PRNGKey(0)`` with
        zeroed output layers (the flow STARTS as the identity — i.e.
        exactly the pooled-Gaussian fit — and training can only
        improve the NLL from there). Raises on non-finite training;
        the caller degrades to the mixture."""
        import jax
        import jax.numpy as jnp

        data = np.asarray(post, np.float64).reshape(-1, post.shape[-1])
        n, p = data.shape
        if n < 8:
            raise ValueError(
                f"flow fit needs >= 8 pooled pilot rows, got {n}")
        mu = data.mean(axis=0)
        sd = np.maximum(data.std(axis=0, ddof=1),
                        np.asarray(gmm.stds, np.float64).min(axis=0))
        zdata = jnp.asarray((data - mu) / sd, jnp.float32)

        nlayers = 2
        masks = [jnp.asarray((np.arange(p) % 2 == (l % 2)),
                             np.float32) for l in range(nlayers)]
        key = jax.random.PRNGKey(0)
        params = []
        for l in range(nlayers):
            key, sub = jax.random.split(key)
            # zero W2/b2 => s = t = 0 => identity init
            params.append((
                0.05 * jax.random.normal(sub, (p, hidden), jnp.float32),
                jnp.zeros((hidden,), jnp.float32),
                jnp.zeros((hidden, 2 * p), jnp.float32),
                jnp.zeros((2 * p,), jnp.float32)))

        def _nll(ps):
            x = zdata
            ld = jnp.zeros(x.shape[0], x.dtype)
            for m, (w1, b1, w2, b2) in zip(reversed(masks),
                                           reversed(ps)):
                hid = jnp.tanh((x * m) @ w1 + b1)
                st = hid @ w2 + b2
                s = jnp.tanh(st[:, :p]) * (1.0 - m)
                t = st[:, p:] * (1.0 - m)
                x = m * x + (1.0 - m) * ((x - t) * jnp.exp(-s))
                ld = ld - s.sum(axis=1)
            return jnp.mean(0.5 * jnp.sum(x * x, axis=1) - ld)

        b1m, b2m, eps = 0.9, 0.999, 1e-8
        tmap = jax.tree_util.tree_map

        def _step(carry, _):
            ps, m, v, i = carry
            loss, g = jax.value_and_grad(_nll)(ps)
            i = i + 1.0
            m = tmap(lambda a, b: b1m * a + (1 - b1m) * b, m, g)
            v = tmap(lambda a, b: b2m * a + (1 - b2m) * b * b, v, g)
            ps = tmap(
                lambda pp, a, b: pp - lr * (a / (1 - b1m ** i))
                / (jnp.sqrt(b / (1 - b2m ** i)) + eps),
                ps, m, v)
            return (ps, m, v, i), loss

        zeros = tmap(jnp.zeros_like, params)
        (params, _, _, _), losses = jax.lax.scan(
            jax.jit(_step), (params, zeros, zeros, 0.0),
            None, length=steps)
        final = float(losses[-1])
        if not np.isfinite(final):
            raise ValueError(f"flow training diverged (nll={final})")
        layers = []
        for m, (w1, b1, w2, b2) in zip(masks, params):
            arrs = [np.asarray(a, np.float64) for a in
                    (m, w1, b1, w2, b2)]
            if not all(np.isfinite(a).all() for a in arrs):
                raise ValueError("flow training produced non-finite "
                                 "parameters")
            layers.append(dict(zip(
                ("mask", "W1", "b1", "W2", "b2"),
                (a.tolist() for a in arrs))))
        return cls(
            means=mu[None, :], stds=sd[None, :],
            weights=np.ones(1), kind="flow",
            pilot_sweeps=gmm.pilot_sweeps,
            pilot_chains=gmm.pilot_chains, pilot_ms=pilot_ms,
            flow={"hidden": int(hidden), "layers": layers},
            meta={"nll": final, "steps": int(steps)})


FIT_KINDS["flow"] = FlowWarmStartFit


def clip_to_support(x: np.ndarray, specs: np.ndarray) -> np.ndarray:
    """Clip ``(..., p)`` parameter draws into each prior's support
    with a 1e-3-width inset on the bounded kinds (Uniform/LinearExp
    carry [a, b] bounds; Normal is unbounded —
    models/parameter.lnprior_specs)."""
    specs = np.asarray(specs, np.float64)
    kind = specs[:, 0].astype(int)
    a, b = specs[:, 1], specs[:, 2]
    bounded = kind != KIND_NORMAL
    inset = 1e-3 * (b - a)
    lo = np.where(bounded, a + inset, -np.inf)
    hi = np.where(bounded, b - inset, np.inf)
    return np.clip(np.asarray(x, np.float64), lo, hi)


def fit_from_rows(rows: np.ndarray, spec: WarmStartSpec,
                  prior_specs: np.ndarray,
                  pilot_ms: float = 0.0) -> WarmStartFit:
    """Moment-match the mixture from pilot x rows ``(rows, chains,
    p)``: the leading ``burn_frac`` rows are discarded and each
    chain's remainder becomes one diagonal-Gaussian component
    (uniform weights) — per-chain matching keeps separated pilot
    chains as separate components instead of averaging modes
    together. Shared by both pilot paths (the in-pool pilot and the
    standalone backend) so the fit cannot drift between them."""
    rows = np.asarray(rows, np.float64)
    burn = int(spec.burn_frac * rows.shape[0])
    post = rows[burn:]
    if post.shape[0] < 2:
        raise ValueError(
            f"pilot leaves {post.shape[0]} post-burn rows; need >= 2")
    means = post.mean(axis=0).astype(np.float64)       # (K, p)
    stds = post.std(axis=0, ddof=1).astype(np.float64)
    # per-param std floor: jitter_frac of the prior scale (bounded
    # kinds: the support width; Normal: sigma) so a stuck pilot
    # column still yields a usable component
    specs = np.asarray(prior_specs, np.float64)
    kind = specs[:, 0].astype(int)
    scale = np.where(kind == KIND_NORMAL, specs[:, 2],
                     specs[:, 2] - specs[:, 1])
    stds = np.maximum(stds, spec.jitter_frac * np.abs(scale))
    K = means.shape[0]
    gmm = WarmStartFit(
        means=means, stds=stds,
        weights=np.full(K, 1.0 / K),
        pilot_sweeps=rows.shape[0],
        pilot_chains=means.shape[0],
        pilot_ms=pilot_ms)
    eff = resolve_fit_kind(spec.kind)
    if eff != "flow":
        if spec.kind == "flow":
            # GST_WARM_FLOW=0 downgrade: still WARM (the mixture),
            # never cold — the server names it (warm_flow_degraded)
            gmm.meta["flow_degraded"] = "GST_WARM_FLOW=0"
        return gmm
    try:
        return FlowWarmStartFit.fit(post, gmm, spec,
                                    pilot_ms=pilot_ms)
    except Exception as e:  # degradation discipline: warm, not cold
        warnings.warn(f"flow warm-start fit failed "
                      f"({type(e).__name__}: {e}); degrading to the "
                      f"moment-matched mixture", RuntimeWarning)
        gmm.meta["flow_degraded"] = f"{type(e).__name__}: {e}"
        return gmm


def fit_warm_start(ma, config, spec: WarmStartSpec, seed: int,
                   dtype=None) -> WarmStartFit:
    """The STANDALONE pilot: a throwaway ``pilot_chains``-chain
    backend samples ``pilot_sweeps`` sweeps of the tenant's own
    (localized, padded) model in ``record="light"`` mode, then
    :func:`fit_from_rows` moment-matches the mixture.

    This path bakes the tenant model into the pilot trace, so EVERY
    DISTINCT MODEL PAYS A COMPILE — measured seconds per tenant on
    the 1-core host, which inverts the warm-start economics for a
    multi-tenant pool. It exists for the serial (reference) driver
    and solo/API use; the serving path runs the pilot ON the slot
    pool's one compiled operand-fed program instead
    (ChainServer._pool_pilot_fit — zero per-tenant recompiles, the
    serve stack's core invariant)."""
    import jax.numpy as jnp

    from gibbs_student_t_tpu.backends.jax_backend import JaxGibbs

    t0 = time.monotonic()
    pb = JaxGibbs(ma, config, nchains=spec.pilot_chains,
                  dtype=dtype or jnp.float32,
                  chunk_size=spec.pilot_sweeps, record="light",
                  tnt_block_size=None, use_pallas=False,
                  telemetry=False)
    res = pb.sample(niter=spec.pilot_sweeps, seed=seed)
    return fit_from_rows(np.asarray(res.chain), spec, ma.specs_np,
                         pilot_ms=(time.monotonic() - t0) * 1e3)


def resolve_warm_start(request_warm, env: Optional[str] = None):
    """The tenant's effective warm-start input under the env gate:
    ``None`` (cold), a :class:`WarmStartSpec` (fit at staging), or a
    :class:`WarmStartFit` (journaled — recovery replay). ``0``
    force-disables (requests degrade; the bitwise-off arm); ``1``
    defaults every tenant without a spec to ``WarmStartSpec()``."""
    env = env if env is not None else warm_start_env()
    if env == "0":
        return None
    if request_warm is None:
        return WarmStartSpec() if env == "1" else None
    if isinstance(request_warm, (WarmStartSpec, WarmStartFit)):
        return request_warm
    if isinstance(request_warm, dict):
        return WarmStartFit.from_json(request_warm)
    raise ValueError(
        f"warm_start must be a WarmStartSpec, a WarmStartFit (or its "
        f"JSON dict), or None, got {type(request_warm).__name__}")
