"""Variational warm starts for served tenants (arXiv:2405.08857).

In serving, burn-in is per-request latency: a tenant initialized from
PRIOR draws (the solo convention) spends its first recorded rows in an
overdispersed transient, which both wastes sweeps and — worse for the
``on_converged="evict"`` economics — poisons the streaming monitor's
early windows: the Sokal τ estimate over a window containing the
transient reads high, ESS reads low, and the eviction verdict lands
quanta after the chain actually mixed (serve_bench's evict arm
measures exactly this gap; docs/PERFORMANCE.md "Capacity per dollar").

A :class:`WarmStartFit` replaces the prior-draw init with draws from a
moment-matched Gaussian mixture fitted to a SHORT pilot run of the
tenant's own model (a few chains × a few dozen sweeps on the staging
thread — the 2405.08857 recipe with the cheap mixture standing in for
the flow; the ``kind`` registry below is the flow-ready seam: a future
normalizing-flow fit registers a new kind and rides the identical
journal/draw/replay plumbing). One mixture component per pilot chain
keeps multimodal hyper posteriors honest — chains that found different
modes become different components.

Determinism and recovery: the fit is summarized as small JSON-able
arrays and journaled in the tenant's manifest admit record
(serve/manifest.py), and the init draw is a ``numpy`` Philox stream
seeded from the request seed — so :meth:`ChainServer.recover` replays
a warm-started tenant's init bitwise WITHOUT re-running the pilot
(tests/test_recycle.py pins the replay).

Failure contract: warm starting is an optimization, never a
correctness dependency — any pilot/fit failure warns, emits a
``warm_start_degraded`` event and serves the tenant from the cold
prior init (the silent-degradation discipline of every GST_* arm).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np

from gibbs_student_t_tpu.models.parameter import KIND_NORMAL


def warm_start_env() -> str:
    """Validated ``GST_WARM_START`` (``auto`` when unset) — the
    variational warm-start arm. Strict ``auto|1|0`` (the loud-typo
    contract): ``auto`` honors each request's ``warm_start`` field
    (no request, no pilot); ``1`` warm-starts EVERY tenant with the
    default spec (requests keep their own); ``0`` disables the arm —
    every tenant serves from the cold prior init, bitwise the
    pre-warm-start graph (requests degrade with an event, pinned)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_WARM_START")


@dataclass
class WarmStartSpec:
    """Per-tenant warm-start request (``TenantRequest.warm_start``).

    ``pilot_sweeps`` × ``pilot_chains`` bounds the pilot's compute
    (run once on the staging thread, overlapped with serving);
    ``burn_frac`` discards the pilot's own transient before moment
    matching; ``jitter_frac`` inflates each component's per-param
    std by a floor fraction of the prior scale so a degenerate pilot
    column can never collapse a component to a point mass."""

    pilot_sweeps: int = 64
    pilot_chains: int = 8
    burn_frac: float = 0.5
    jitter_frac: float = 0.02

    def __post_init__(self):
        if self.pilot_sweeps < 8:
            raise ValueError(f"pilot_sweeps must be >= 8, got "
                             f"{self.pilot_sweeps}")
        if self.pilot_chains < 1:
            raise ValueError(f"pilot_chains must be >= 1, got "
                             f"{self.pilot_chains}")
        if not 0.0 <= self.burn_frac < 1.0:
            raise ValueError(f"burn_frac must be in [0, 1), got "
                             f"{self.burn_frac}")
        if self.jitter_frac < 0.0:
            raise ValueError(f"jitter_frac must be >= 0, got "
                             f"{self.jitter_frac}")


@dataclass
class WarmStartFit:
    """A fitted init distribution: ``K`` diagonal-Gaussian components
    over the sampled parameter vector, plus the bookkeeping recovery
    replays from. ``kind`` names the fit family in the registry
    (``"gmm"`` today; a flow fit would add its own and carry its
    parameters the same journaled way)."""

    means: np.ndarray            # (K, p)
    stds: np.ndarray             # (K, p)
    weights: np.ndarray          # (K,)
    kind: str = "gmm"
    pilot_sweeps: int = 0
    pilot_chains: int = 0
    pilot_ms: float = 0.0
    meta: Dict = field(default_factory=dict)

    def draw_x0(self, nchains: int, seed: int,
                specs: np.ndarray) -> np.ndarray:
        """``(nchains, p)`` init draws from the mixture, clipped into
        the prior support (an out-of-support x0 has −inf prior and the
        MH blocks could never leave it). Deterministic in ``seed``
        (numpy Philox) — the bitwise recovery-replay contract."""
        rng = np.random.default_rng(
            np.random.SeedSequence([int(seed) & 0xFFFFFFFF, 0x57A7]))
        k = rng.choice(len(self.weights), size=nchains,
                       p=np.asarray(self.weights, np.float64)
                       / np.sum(self.weights))
        x = (np.asarray(self.means, np.float64)[k]
             + np.asarray(self.stds, np.float64)[k]
             * rng.standard_normal((nchains, self.means.shape[1])))
        return clip_to_support(x, specs)

    def to_json(self) -> Dict:
        return {
            "kind": self.kind,
            "means": np.asarray(self.means, np.float64).tolist(),
            "stds": np.asarray(self.stds, np.float64).tolist(),
            "weights": np.asarray(self.weights, np.float64).tolist(),
            "pilot_sweeps": int(self.pilot_sweeps),
            "pilot_chains": int(self.pilot_chains),
        }

    @classmethod
    def from_json(cls, d: Dict) -> "WarmStartFit":
        kind = d.get("kind", "gmm")
        if kind not in FIT_KINDS:
            raise ValueError(
                f"unknown warm-start fit kind {kind!r} "
                f"(known: {sorted(FIT_KINDS)})")
        return cls(means=np.asarray(d["means"], np.float64),
                   stds=np.asarray(d["stds"], np.float64),
                   weights=np.asarray(d["weights"], np.float64),
                   kind=kind,
                   pilot_sweeps=int(d.get("pilot_sweeps", 0)),
                   pilot_chains=int(d.get("pilot_chains", 0)))


#: fit-family registry — the flow-ready seam: each kind maps to its
#: reconstructing class (all journaled through the same admit-record
#: JSON; serve/manifest.py)
FIT_KINDS: Dict[str, type] = {"gmm": WarmStartFit}


def clip_to_support(x: np.ndarray, specs: np.ndarray) -> np.ndarray:
    """Clip ``(..., p)`` parameter draws into each prior's support
    with a 1e-3-width inset on the bounded kinds (Uniform/LinearExp
    carry [a, b] bounds; Normal is unbounded —
    models/parameter.lnprior_specs)."""
    specs = np.asarray(specs, np.float64)
    kind = specs[:, 0].astype(int)
    a, b = specs[:, 1], specs[:, 2]
    bounded = kind != KIND_NORMAL
    inset = 1e-3 * (b - a)
    lo = np.where(bounded, a + inset, -np.inf)
    hi = np.where(bounded, b - inset, np.inf)
    return np.clip(np.asarray(x, np.float64), lo, hi)


def fit_from_rows(rows: np.ndarray, spec: WarmStartSpec,
                  prior_specs: np.ndarray,
                  pilot_ms: float = 0.0) -> WarmStartFit:
    """Moment-match the mixture from pilot x rows ``(rows, chains,
    p)``: the leading ``burn_frac`` rows are discarded and each
    chain's remainder becomes one diagonal-Gaussian component
    (uniform weights) — per-chain matching keeps separated pilot
    chains as separate components instead of averaging modes
    together. Shared by both pilot paths (the in-pool pilot and the
    standalone backend) so the fit cannot drift between them."""
    rows = np.asarray(rows, np.float64)
    burn = int(spec.burn_frac * rows.shape[0])
    post = rows[burn:]
    if post.shape[0] < 2:
        raise ValueError(
            f"pilot leaves {post.shape[0]} post-burn rows; need >= 2")
    means = post.mean(axis=0).astype(np.float64)       # (K, p)
    stds = post.std(axis=0, ddof=1).astype(np.float64)
    # per-param std floor: jitter_frac of the prior scale (bounded
    # kinds: the support width; Normal: sigma) so a stuck pilot
    # column still yields a usable component
    specs = np.asarray(prior_specs, np.float64)
    kind = specs[:, 0].astype(int)
    scale = np.where(kind == KIND_NORMAL, specs[:, 2],
                     specs[:, 2] - specs[:, 1])
    stds = np.maximum(stds, spec.jitter_frac * np.abs(scale))
    K = means.shape[0]
    return WarmStartFit(
        means=means, stds=stds,
        weights=np.full(K, 1.0 / K),
        pilot_sweeps=rows.shape[0],
        pilot_chains=means.shape[0],
        pilot_ms=pilot_ms)


def fit_warm_start(ma, config, spec: WarmStartSpec, seed: int,
                   dtype=None) -> WarmStartFit:
    """The STANDALONE pilot: a throwaway ``pilot_chains``-chain
    backend samples ``pilot_sweeps`` sweeps of the tenant's own
    (localized, padded) model in ``record="light"`` mode, then
    :func:`fit_from_rows` moment-matches the mixture.

    This path bakes the tenant model into the pilot trace, so EVERY
    DISTINCT MODEL PAYS A COMPILE — measured seconds per tenant on
    the 1-core host, which inverts the warm-start economics for a
    multi-tenant pool. It exists for the serial (reference) driver
    and solo/API use; the serving path runs the pilot ON the slot
    pool's one compiled operand-fed program instead
    (ChainServer._pool_pilot_fit — zero per-tenant recompiles, the
    serve stack's core invariant)."""
    import jax.numpy as jnp

    from gibbs_student_t_tpu.backends.jax_backend import JaxGibbs

    t0 = time.monotonic()
    pb = JaxGibbs(ma, config, nchains=spec.pilot_chains,
                  dtype=dtype or jnp.float32,
                  chunk_size=spec.pilot_sweeps, record="light",
                  tnt_block_size=None, use_pallas=False,
                  telemetry=False)
    res = pb.sample(niter=spec.pilot_sweeps, seed=seed)
    return fit_from_rows(np.asarray(res.chain), spec, ma.specs_np,
                         pilot_ms=(time.monotonic() - t0) * 1e3)


def resolve_warm_start(request_warm, env: Optional[str] = None):
    """The tenant's effective warm-start input under the env gate:
    ``None`` (cold), a :class:`WarmStartSpec` (fit at staging), or a
    :class:`WarmStartFit` (journaled — recovery replay). ``0``
    force-disables (requests degrade; the bitwise-off arm); ``1``
    defaults every tenant without a spec to ``WarmStartSpec()``."""
    env = env if env is not None else warm_start_env()
    if env == "0":
        return None
    if request_warm is None:
        return WarmStartSpec() if env == "1" else None
    if isinstance(request_warm, (WarmStartSpec, WarmStartFit)):
        return request_warm
    if isinstance(request_warm, dict):
        return WarmStartFit.from_json(request_warm)
    raise ValueError(
        f"warm_start must be a WarmStartSpec, a WarmStartFit (or its "
        f"JSON dict), or None, got {type(request_warm).__name__}")
