"""The mutating RPC edge: submit/progress/cancel/result over TCP.

ROADMAP item 1's first half shipped read-only (round 14, PR 11: the HTTP
status/metrics/trace endpoints + the fleet aggregator). This module is
the mutating half: a length-prefixed binary framing over stdlib
``socketserver`` (no new deps) carrying JSON-RPC-style control objects
with out-of-band numpy buffers, a :class:`RpcServer` mounted beside a
live :class:`~gibbs_student_t_tpu.serve.server.ChainServer`, and a
client-side :class:`RemoteChainServer` whose ``submit`` returns a
:class:`RemoteTenantHandle` mirroring the in-process handle API
(``progress()`` / ``cost()`` / ``result()`` / ``done()``), including
**streaming chunk delivery**: a submit with ``on_chunk`` keeps its
connection open and the server pushes one frame per drained quantum.

Framing
-------

::

    FRAME := MAGIC(2)=b"GW" | VER(1)=1 | KIND(1) | LEN(u32 BE) | PAYLOAD

``KIND`` is ``b"j"`` (PAYLOAD = one JSON object) or ``b"m"``
(composite: ``u32 BE json_len | json | buffers...``). A composite's
JSON body references its buffers positionally: ``{"$nd": i}`` marks a
numpy array (dtype/shape in the ``__buffers__`` table), ``{"$pkl": i}``
a pickled python object (the tenant model / the final ChainResult —
numpy pytrees, not JSON). Frames above ``GST_RPC_MAX_FRAME`` bytes
(default 256 MiB, strict positive-int validation) are rejected before
any allocation; a bad magic/version/kind or a short read raises
:class:`FrameError` — the server answers malformed input with one
error frame and closes the connection, and a disconnect mid-frame is
contained to that connection (pinned in tests/test_rpc.py).

Trust model: like the crash manifest (serve/manifest.py), the wire
carries **pickled model pytrees** — it is a same-trust-domain cluster
protocol (the Ray/Dask convention), not an internet-facing API. Bind
it to loopback or a private fabric; docs/SERVING.md "The wire".

Determinism: the PR 7 lane-position-independent draw contract means a
tenant's results depend only on its request (seed + model + budget),
never on which pool, lanes, or scheduling served it — so the SAME
request stream is bitwise-reproducible through any ``RemoteChainServer``
(request-replay determinism, pinned in tests/test_fleet.py). That is
what makes the fleet router's failover-by-resubmission sound.

Fault injection: the ``rpc_sever`` point (serve/faults.py) fires
per-request in the connection loop and per-chunk in the streaming
push; a firing closes the TCP connection abruptly — no error frame —
the severed-wire chaos arm.
"""

from __future__ import annotations

import json
import pickle
import queue as _queue
import socket
import socketserver
import struct
import threading
import time
import warnings
from typing import Callable, Dict, Optional

import numpy as np

from gibbs_student_t_tpu.serve import faults as _faults

MAGIC = b"GW"
VERSION = 1
KIND_JSON = b"j"
KIND_COMPOSITE = b"m"
_HEADER = struct.Struct(">2sccI")

#: default frame-size ceiling (bytes) when ``GST_RPC_MAX_FRAME`` unset
DEFAULT_MAX_FRAME = 256 * 1024 * 1024


class FrameError(ValueError):
    """A malformed, oversized, or truncated wire frame."""


class RpcError(RuntimeError):
    """A request that reached the server and was answered with an
    error frame (the remote failure, re-raised client-side)."""


def rpc_max_frame_env() -> int:
    """Validated ``GST_RPC_MAX_FRAME`` (bytes; the loud-typo contract
    of every GST_* gate): unset → 256 MiB, else a strict positive
    integer — the per-frame allocation ceiling both sides enforce
    BEFORE reading a payload. Validation is the registry's ``posint``
    kind (ops/registry.py)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_RPC_MAX_FRAME")


class Pickled:
    """Marks one value in an outgoing frame body for pickle transport
    (model pytrees, ChainResult — numpy trees JSON can't carry)."""

    def __init__(self, obj):
        self.obj = obj


# ---------------------------------------------------------------------------
# encode / decode (socket-free, unit-testable)
# ---------------------------------------------------------------------------

def encode_frame(body: dict) -> bytes:
    """One wire frame from a JSON-able body that may contain numpy
    arrays and :class:`Pickled` wrappers at any depth. Bodies with
    neither encode as a plain JSON frame."""
    buffers = []
    descrs = []

    def walk(v):
        if isinstance(v, Pickled):
            i = len(buffers)
            buffers.append(pickle.dumps(v.obj, protocol=4))
            descrs.append([None, None, len(buffers[-1])])
            return {"$pkl": i}
        if isinstance(v, np.ndarray):
            a = np.ascontiguousarray(v)
            i = len(buffers)
            buffers.append(a.tobytes())
            descrs.append([a.dtype.str, list(a.shape), len(buffers[-1])])
            return {"$nd": i}
        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, dict):
            return {str(k): walk(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [walk(x) for x in v]
        return v

    body = walk(body)
    if buffers:
        body["__buffers__"] = descrs
        jb = json.dumps(body, separators=(",", ":")).encode()
        payload = struct.pack(">I", len(jb)) + jb + b"".join(buffers)
        kind = KIND_COMPOSITE
    else:
        payload = json.dumps(body, separators=(",", ":")).encode()
        kind = KIND_JSON
    return _HEADER.pack(MAGIC, bytes([VERSION]), kind,
                        len(payload)) + payload


def decode_payload(kind: bytes, payload: bytes) -> dict:
    """The inverse of :func:`encode_frame` for one received payload."""
    if kind == KIND_JSON:
        body = json.loads(payload.decode())
        if not isinstance(body, dict):
            raise FrameError("frame body is not a JSON object")
        return body
    if kind != KIND_COMPOSITE:
        raise FrameError(f"unknown frame kind {kind!r}")
    if len(payload) < 4:
        raise FrameError("composite frame too short for its JSON length")
    (jlen,) = struct.unpack(">I", payload[:4])
    if 4 + jlen > len(payload):
        raise FrameError("composite JSON length exceeds the payload")
    body = json.loads(payload[4:4 + jlen].decode())
    if not isinstance(body, dict):
        raise FrameError("frame body is not a JSON object")
    descrs = body.pop("__buffers__", [])
    bufs = []
    off = 4 + jlen
    for d in descrs:
        dtype, shape, nbytes = d
        if off + nbytes > len(payload):
            raise FrameError("buffer table overruns the payload")
        raw = payload[off:off + nbytes]
        off += nbytes
        if dtype is None:
            bufs.append(("pkl", raw))
        else:
            bufs.append(("nd", np.frombuffer(
                raw, np.dtype(dtype)).reshape(shape).copy()))

    def walk(v):
        if isinstance(v, dict):
            if set(v) == {"$nd"} or set(v) == {"$pkl"}:
                key = "nd" if "$nd" in v else "pkl"
                i = v.get("$nd", v.get("$pkl"))
                if not isinstance(i, int) or not 0 <= i < len(bufs) \
                        or bufs[i][0] != key:
                    raise FrameError(f"dangling buffer reference {v}")
                kind_i, val = bufs[i]
                return (val if kind_i == "nd"
                        else pickle.loads(val))
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, list):
            return [walk(x) for x in v]
        return v

    return walk(body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise FrameError("connection closed mid-frame")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def send_frame(sock: socket.socket, body: dict,
               max_frame: Optional[int] = None) -> None:
    data = encode_frame(body)
    limit = max_frame if max_frame is not None else rpc_max_frame_env()
    if len(data) - _HEADER.size > limit:
        raise FrameError(
            f"outgoing frame of {len(data) - _HEADER.size} bytes "
            f"exceeds the {limit}-byte ceiling (GST_RPC_MAX_FRAME)")
    sock.sendall(data)


def recv_frame(sock: socket.socket,
               max_frame: Optional[int] = None) -> dict:
    """Read one frame; raises :class:`FrameError` on malformed input,
    an oversized declared length (rejected BEFORE allocating), or a
    peer that hung up mid-frame. A clean EOF before any header byte
    raises ``ConnectionError`` (the peer is simply done)."""
    first = sock.recv(1)
    if not first:
        raise ConnectionError("peer closed the connection")
    head = first + _recv_exact(sock, _HEADER.size - 1)
    magic, ver, kind, length = _HEADER.unpack(head)
    if magic != MAGIC:
        raise FrameError(f"bad magic {magic!r} (not a gst-rpc peer?)")
    if ver != bytes([VERSION]):
        raise FrameError(f"unsupported protocol version {ver!r}")
    if kind not in (KIND_JSON, KIND_COMPOSITE):
        raise FrameError(f"unknown frame kind {kind!r}")
    limit = max_frame if max_frame is not None else rpc_max_frame_env()
    if length > limit:
        raise FrameError(
            f"incoming frame declares {length} bytes, above the "
            f"{limit}-byte ceiling (GST_RPC_MAX_FRAME)")
    return decode_payload(kind, _recv_exact(sock, length))


# ---------------------------------------------------------------------------
# request (de)serialization
# ---------------------------------------------------------------------------

#: TenantRequest fields that ride the wire as plain JSON values
_REQ_SCALARS = ("niter", "nchains", "seed", "start_sweep", "spool_dir",
                "name", "on_divergence", "on_converged",
                "resume_spool", "trace_id", "priority",
                "deadline_sweeps")

#: MonitorSpec fields (all JSON-able)
_MON_FIELDS = ("params", "ess_target", "rhat_target", "every",
               "min_rows")


#: WarmStartSpec fields (all JSON-able; serve/warm.py)
_WARM_SPEC_FIELDS = ("pilot_sweeps", "pilot_chains", "burn_frac",
                     "jitter_frac")


def _request_body(request, include_model: bool = True,
                  digest: Optional[str] = None) -> dict:
    """A TenantRequest as a submit frame body (the callable ``on_chunk``
    stays client-side — its presence becomes ``stream``).
    ``include_model=False`` sends only ``ma_digest`` (the
    content-addressed model cache, ROADMAP 1c): the server resolves
    the model from its digest store, or answers ``need_model`` and the
    client falls back to a full submit."""
    if request.state is not None:
        raise ValueError(
            "TenantRequest.state cannot ride the submit wire; resume "
            "via spool_dir + the server-side recover() path")
    body = {"op": "submit",
            "stream": request.on_chunk is not None}
    if include_model:
        body["ma"] = Pickled(request.ma)
    if digest is not None:
        body["ma_digest"] = digest
    for f in _REQ_SCALARS:
        body[f] = getattr(request, f)
    if request.x0 is not None:
        body["x0"] = np.asarray(request.x0)
    if request.monitor is not None:
        body["monitor"] = {f: getattr(request.monitor, f)
                           for f in _MON_FIELDS}
    ws = request.warm_start
    if ws is not None:
        from gibbs_student_t_tpu.serve.warm import (
            WarmStartFit,
            WarmStartSpec,
        )

        if isinstance(ws, WarmStartSpec):
            body["warm_start"] = {"spec": {
                f: getattr(ws, f) for f in _WARM_SPEC_FIELDS}}
        elif isinstance(ws, WarmStartFit):
            body["warm_start"] = ws.to_json()   # journaled fit: replay
        elif isinstance(ws, dict):
            body["warm_start"] = ws
        else:
            raise ValueError(
                f"warm_start cannot ride the wire: "
                f"{type(ws).__name__}")
    return body


def _request_from_body(body: dict):
    from gibbs_student_t_tpu.serve.monitor import MonitorSpec
    from gibbs_student_t_tpu.serve.scheduler import TenantRequest

    kw = {f: body.get(f) for f in _REQ_SCALARS if body.get(f) is not None}
    mon = body.get("monitor")
    if mon is not None:
        mon = MonitorSpec(**{f: mon.get(f) for f in _MON_FIELDS
                             if mon.get(f) is not None})
    ws = body.get("warm_start")
    if isinstance(ws, dict) and "spec" in ws:
        from gibbs_student_t_tpu.serve.warm import WarmStartSpec

        ws = WarmStartSpec(**{f: ws["spec"][f]
                              for f in _WARM_SPEC_FIELDS
                              if f in ws["spec"]})
    # a fit dict passes through verbatim — serve/warm.py
    # resolve_warm_start reconstructs it at staging
    return TenantRequest(ma=body["ma"], x0=body.get("x0"),
                         monitor=mon, warm_start=ws, **kw)


def _tenant_error_body(err) -> dict:
    """A TenantError flattened for the wire (exceptions with custom
    ``__init__`` signatures don't round-trip pickle; the partial
    ChainResult does). A :class:`DeadlineExceeded` carries its
    subclass fields under ``kind`` so the client re-raises the SAME
    structured type (round 20)."""
    from gibbs_student_t_tpu.serve.scheduler import DeadlineExceeded

    body = {"op": "tenant_error", "tenant_id": err.tenant_id,
            "reason": err.reason, "where": err.where,
            "cause": (f"{type(err.cause).__name__}: {err.cause}"
                      if err.cause is not None else None),
            "partial": Pickled(err.partial)}
    if isinstance(err, DeadlineExceeded):
        body["kind"] = "deadline_exceeded"
        body["deadline_sweep"] = err.deadline_sweep
        body["served_sweeps"] = err.served_sweeps
    return body


def _tenant_error_from_body(body: dict):
    from gibbs_student_t_tpu.serve.scheduler import (
        DeadlineExceeded,
        TenantError,
    )

    if body.get("kind") == "deadline_exceeded":
        return DeadlineExceeded(body["tenant_id"],
                                body["deadline_sweep"],
                                body["served_sweeps"],
                                partial=body.get("partial"))
    return TenantError(body["tenant_id"], reason=body["reason"],
                       where=body.get("where") or "drain",
                       cause=(RuntimeError(body["cause"])
                              if body.get("cause") else None),
                       partial=body.get("partial"))


def _retry_after_body(err) -> dict:
    """A structured overload shed as a rejected frame body (round
    20): the client re-raises :class:`RetryAfter` with the same
    backoff/depth/tier signal the local submit call gets."""
    return {"op": "rejected",
            "error": f"{type(err).__name__}: {err}",
            "error_kind": "retry_after",
            "retry_after_s": err.retry_after_s,
            "queue_depth": err.queue_depth,
            "tier": err.tier, "shed_where": err.where}


def _rejected_error(reply: dict):
    """The exception a rejected frame resolves to: a structured
    :class:`RetryAfter` when the frame carries the overload signal,
    the historical bare RuntimeError otherwise."""
    if reply.get("error_kind") == "retry_after":
        from gibbs_student_t_tpu.serve.scheduler import RetryAfter

        return RetryAfter(reply.get("error") or "rejected",
                          retry_after_s=reply.get("retry_after_s"),
                          queue_depth=reply.get("queue_depth"),
                          tier=reply.get("tier"),
                          where=reply.get("shed_where") or "server")
    return RuntimeError(reply.get("error") or "rejected")


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------

class RpcServer:
    """The mutating wire mounted beside one ChainServer (duck-typed:
    anything with ``submit`` / ``cancel`` / ``status`` / ``healthz``
    and a ``_handles`` table serves — the test stubs ride the same
    class). Each connection gets its own daemon thread
    (``ThreadingTCPServer``); requests on one connection are handled
    sequentially, so a client may pipeline calls over one socket.

    ``on_shutdown`` (optional): the ``shutdown`` op's callback — the
    subprocess pool worker (serve/pool_main.py) passes one so a fleet
    router can retire a pool over the wire; without it the op answers
    an error frame."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 max_frame: Optional[int] = None,
                 on_shutdown: Optional[Callable] = None,
                 chunk_queue: int = 8, model_cache: int = 64):
        self.server = server
        self.max_frame = (max_frame if max_frame is not None
                          else rpc_max_frame_env())
        self._on_shutdown = on_shutdown
        self._chunk_queue = int(chunk_queue)
        self._warned = False
        # content-addressed model cache (ROADMAP 1c): digest → model
        # pytree, LRU-capped. A submit carrying both model and digest
        # registers; a digest-only submit resolves here or answers
        # ``need_model`` (the client then falls back to a full
        # submit) — resubmission and failover stop re-shipping (and
        # re-pickling) identical models over the wire.
        from collections import OrderedDict

        self._model_cache: "OrderedDict[str, object]" = OrderedDict()
        self._model_cache_cap = int(model_cache)
        self._model_lock = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                outer._serve_connection(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = _Server((host, port), _Handler)
        self.host, self.port = self._tcp.server_address[:2]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="gst-rpc",
            daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def close(self) -> None:
        """Stop accepting connections and join the acceptor.
        Idempotent; in-flight per-connection threads are daemons."""
        tcp, self._tcp = self._tcp, None
        if tcp is None:
            return
        tcp.shutdown()
        tcp.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- connection loop -----------------------------------------------

    def _serve_connection(self, sock: socket.socket) -> None:
        """One connection's request loop: a malformed frame answers
        one error frame then closes; a handler exception answers an
        error frame and the connection continues; an injected
        ``rpc_sever`` closes abruptly (no error frame) — the
        severed-wire chaos arm. Nothing here can fail the pool."""
        try:
            while True:
                try:
                    req = recv_frame(sock, self.max_frame)
                except ConnectionError:
                    return
                except FrameError as e:
                    self._try_send(sock, {"op": "error",
                                          "error": f"bad frame: {e}"})
                    return
                try:
                    _faults.fire("rpc_sever",
                                 tenant=req.get("name") or req.get("tenant"))
                except Exception:  # noqa: BLE001 - the fire IS the sever
                    return  # abrupt close, deliberately no error frame
                try:
                    if not self._dispatch(sock, req):
                        return
                except (BrokenPipeError, ConnectionError, OSError):
                    return
                except Exception as e:  # noqa: BLE001 - per-request
                    if not self._warned:
                        self._warned = True
                        warnings.warn(
                            f"rpc request {req.get('op')!r} failed "
                            f"({type(e).__name__}: {e}); connection "
                            "continues", RuntimeWarning)
                    self._try_send(sock, {
                        "op": "error",
                        "error": f"{type(e).__name__}: {e}"})
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _try_send(self, sock, body) -> None:
        try:
            send_frame(sock, body, self.max_frame)
        except (OSError, FrameError):
            pass

    def _lookup(self, key):
        """A handle by tenant id (int) or request name (latest wins —
        the /tenants endpoint convention)."""
        handles = getattr(self.server, "_handles", {})
        try:
            h = handles.get(int(key))
            if h is not None:
                return h
        except (TypeError, ValueError):
            pass
        found = None
        for h in handles.values():
            if h.request.name == key:
                found = h
        return found

    def _dispatch(self, sock, req: dict) -> bool:
        """Handle one request; returns False when the connection must
        close (stream finished / shutdown)."""
        op = req.get("op")
        if op == "submit":
            return self._op_submit(sock, req)
        if op in ("progress", "cost", "cancel", "result"):
            h = self._lookup(req.get("tenant"))
            if h is None:
                send_frame(sock, {"op": "error", "error":
                                  f"unknown tenant {req.get('tenant')!r}"},
                           self.max_frame)
                return True
            if op == "progress":
                send_frame(sock, {"op": "ok", "progress": h.progress()},
                           self.max_frame)
            elif op == "cost":
                send_frame(sock, {"op": "ok", "cost": h.cost()},
                           self.max_frame)
            elif op == "cancel":
                send_frame(sock, {"op": "ok",
                                  "cancelled": bool(
                                      self.server.cancel(h))},
                           self.max_frame)
            else:
                self._send_result(sock, h, req.get("timeout"))
            return True
        if op == "status":
            send_frame(sock, {"op": "ok", "status": self.server.status()},
                       self.max_frame)
            return True
        if op == "reset":
            # the serve_bench warmup boundary, over the wire: zero the
            # run-level aggregates so a fleet bench's timed window
            # excludes each pool's compile/warmup quanta
            self.server.reset_counters()
            send_frame(sock, {"op": "ok"}, self.max_frame)
            return True
        if op == "healthz":
            send_frame(sock, {"op": "ok",
                              "healthz": self.server.healthz()},
                       self.max_frame)
            return True
        if op == "time":
            # NTP-style clock sampling (round 19): the worker's wall
            # clock, read as late as possible — the fleet stitcher
            # brackets this with local timestamps and corrects pool
            # span timelines by the min-RTT offset
            # (obs/aggregate.py ``estimate_clock_offset``)
            send_frame(sock, {"op": "ok", "t": time.time()},
                       self.max_frame)
            return True
        if op == "trace":
            # the worker's Chrome trace document, over the wire — the
            # fleet stitcher's fallback when a pool worker has no HTTP
            # port (GET /trace is the cheap path when it does)
            send_frame(sock, {"op": "ok",
                              "trace": self.server._trace_doc()},
                       self.max_frame)
            return True
        if op == "shutdown":
            if self._on_shutdown is None:
                send_frame(sock, {"op": "error",
                                  "error": "shutdown not armed"},
                           self.max_frame)
                return True
            send_frame(sock, {"op": "ok"}, self.max_frame)
            self._on_shutdown()
            return False
        send_frame(sock, {"op": "error", "error": f"unknown op {op!r}"},
                   self.max_frame)
        return True

    def _send_result(self, sock, h, timeout) -> None:
        """The ``result`` reply: the ChainResult pickled whole, or the
        structured tenant-error / rejection / timeout frames."""
        from gibbs_student_t_tpu.serve.scheduler import TenantError

        try:
            res = h.result(timeout=timeout)
        except TimeoutError as e:
            send_frame(sock, {"op": "timeout", "error": str(e)},
                       self.max_frame)
            return
        except TenantError as e:
            send_frame(sock, _tenant_error_body(e), self.max_frame)
            return
        except RuntimeError as e:
            from gibbs_student_t_tpu.serve.scheduler import RetryAfter

            body = (_retry_after_body(e) if isinstance(e, RetryAfter)
                    else {"op": "rejected", "error": str(e)})
            send_frame(sock, body, self.max_frame)
            return
        send_frame(sock, {"op": "result", "result": Pickled(res)},
                   self.max_frame)

    def _op_submit(self, sock, req: dict) -> bool:
        """Admit one remote tenant. A streaming submit dedicates the
        connection: the reply frame is followed by one ``chunk`` frame
        per drained quantum (pushed from this connection thread; the
        drain worker only enqueues — a slow client backpressures
        exactly like a slow local ``on_chunk`` callback) and ends with
        the result/tenant_error/rejected frame."""
        stream = bool(req.get("stream"))
        chunks: Optional[_queue.Queue] = None
        # content-addressed model resolution (ROADMAP 1c): a
        # digest-only submit reuses the cached pytree; a miss answers
        # ``need_model`` (the client retries with the model attached)
        digest = req.get("ma_digest")
        if req.get("ma") is None:
            with self._model_lock:
                ma = (self._model_cache.get(digest)
                      if digest is not None else None)
                if ma is not None:
                    self._model_cache.move_to_end(digest)
            if ma is None:
                send_frame(sock, {"op": "need_model",
                                  "digest": digest}, self.max_frame)
                return True
            req["ma"] = ma
        elif digest is not None:
            with self._model_lock:
                self._model_cache[digest] = req["ma"]
                self._model_cache.move_to_end(digest)
                while len(self._model_cache) > self._model_cache_cap:
                    self._model_cache.popitem(last=False)
        try:
            request = _request_from_body(req)
        except Exception as e:  # noqa: BLE001 - reject, don't kill conn
            send_frame(sock, {"op": "rejected",
                              "error": f"{type(e).__name__}: {e}"},
                       self.max_frame)
            return True
        if stream:
            chunks = _queue.Queue(maxsize=self._chunk_queue)
            detached = threading.Event()

            def on_chunk(handle, sweep_end, records):
                # Block (the backpressure contract) only while the
                # push loop below still drains the queue. Once the
                # connection is gone — client disconnect, injected
                # sever — ``detached`` turns this callback into a
                # no-op: a dead wire must never wedge the pool's
                # shared drain worker behind a full queue.
                while not detached.is_set():
                    try:
                        chunks.put((sweep_end, records), timeout=0.2)
                        return
                    except _queue.Full:
                        continue

            request.on_chunk = on_chunk
        try:
            h = self.server.submit(request, timeout=req.get("timeout"))
        except Exception as e:  # noqa: BLE001 - queue-full / validation
            from gibbs_student_t_tpu.serve.scheduler import RetryAfter

            body = (_retry_after_body(e) if isinstance(e, RetryAfter)
                    else {"op": "rejected",
                          "error": f"{type(e).__name__}: {e}"})
            send_frame(sock, body, self.max_frame)
            return True
        send_frame(sock, {"op": "ok", "tenant_id": h.tenant_id},
                   self.max_frame)
        if not stream:
            return True
        # -- dedicated streaming push loop ------------------------------
        try:
            while True:
                try:
                    sweep_end, records = chunks.get(timeout=0.05)
                except _queue.Empty:
                    if h.done() and chunks.empty():
                        break
                    continue
                try:
                    _faults.fire("rpc_sever",
                                 tenant=request.name
                                 if request.name is not None
                                 else h.tenant_id)
                except Exception:  # noqa: BLE001 - abrupt sever
                    return False
                send_frame(sock, {"op": "chunk", "sweep_end": sweep_end,
                                  "records": {f: np.asarray(a)
                                              for f, a in
                                              records.items()}},
                           self.max_frame)
        finally:
            # every exit — clean finish, sever, or a send_frame error
            # on a dead client — detaches the callback; the tenant
            # keeps running and its result stays fetchable by id
            detached.set()
        self._send_result(sock, h, req.get("timeout"))
        return False


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

class RemoteTenantHandle:
    """Caller-facing proxy for a tenant submitted over the wire —
    the :class:`~gibbs_student_t_tpu.serve.scheduler.TenantHandle`
    surface (``progress()`` / ``cost()`` / ``result()`` / ``done()``)
    backed by RPC calls. ``result()`` caches; a streamed handle's
    reader thread fills the cache as the final frame arrives."""

    def __init__(self, client: "RemoteChainServer", tenant_id: int,
                 request, streamed: bool = False):
        self.client = client
        self.tenant_id = tenant_id
        self.request = request
        self._result = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        # a streamed handle's outcome arrives on ITS connection (the
        # reader thread), after every chunk frame — result() must wait
        # for that, not race it over a side-channel call, or a caller
        # could observe the result before the last on_chunk fired
        self._streamed = streamed

    def _body(self, op: str, **extra) -> dict:
        """A control-frame body for this tenant; carries the job's
        ``trace_id`` (when one was minted) so every
        progress/cost/cancel/result frame is correlatable with the
        fleet trace (round 19 — the server ignores unknown keys)."""
        body = {"op": op, "tenant": self.tenant_id}
        tid = getattr(self.request, "trace_id", None)
        if tid is not None:
            body["trace_id"] = tid
        body.update(extra)
        return body

    def progress(self) -> Dict[str, object]:
        return self.client._call(self._body("progress"))["progress"]

    def cost(self) -> Dict[str, object]:
        return self.client._call(self._body("cost"))["cost"]

    @property
    def status(self) -> str:
        if self._done.is_set():
            if self._error is None:
                return "done"
            from gibbs_student_t_tpu.serve.scheduler import TenantError

            return ("failed" if isinstance(self._error, TenantError)
                    else "rejected")
        return str(self.progress().get("status"))

    def done(self) -> bool:
        if self._done.is_set():
            return True
        return self.progress().get("status") in ("done", "failed",
                                                 "rejected")

    def cancel(self) -> bool:
        return self.client.cancel(self)

    def _resolve(self, body: dict) -> None:
        """Terminal frame → cached outcome (reader thread / result)."""
        op = body.get("op")
        if op == "result":
            self._result = body["result"]
        elif op == "tenant_error":
            self._error = _tenant_error_from_body(body)
        elif op == "timeout":
            raise TimeoutError(body.get("error") or "result timeout")
        elif op == "rejected":
            self._error = _rejected_error(body)
        else:
            raise RpcError(body.get("error") or f"unexpected reply {op!r}")
        self._done.set()

    def result(self, timeout: Optional[float] = None):
        """Block until the remote job completes and return its
        ChainResult; raises the reconstructed TenantError (partial
        attached) / rejection — the in-process ``result()`` contract
        over the wire."""
        if not self._done.is_set():
            if self._streamed:
                # the stream delivers chunks-then-outcome in order;
                # wait for its reader instead of racing it
                if not self._done.wait(timeout):
                    raise TimeoutError(
                        f"tenant {self.tenant_id} stream not done")
            else:
                body = self.client._call(
                    self._body("result", timeout=timeout),
                    sock_timeout=(None if timeout is None
                                  else timeout + 30.0))
                self._resolve(body)
        if self._error is not None:
            raise self._error
        return self._result


class RemoteChainServer:
    """A :class:`ChainServer`-shaped client for one remote pool.

    ``submit(request)`` mirrors the in-process call: the tenant model
    rides the wire pickled, and the returned
    :class:`RemoteTenantHandle` exposes ``progress()/cost()/result()``.
    A request with ``on_chunk`` set streams: a dedicated connection
    stays open and a reader thread invokes the callback locally with
    each drained quantum's materialized records (handle, sweep_end,
    records — the local signature). Control calls open one connection
    each (submit/progress/cancel are rare next to a quantum).
    """

    def __init__(self, address, timeout: float = 30.0,
                 max_frame: Optional[int] = None):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self.address = tuple(address)
        self.timeout = timeout
        self.max_frame = (max_frame if max_frame is not None
                          else rpc_max_frame_env())
        self._streams: list = []
        # content-addressed submit (ROADMAP 1c): pickled-model digests
        # by object identity (strong refs pin ids valid; bounded), and
        # the digests this server has confirmed holding — repeat
        # submits of one model (the closed-loop bench, failover
        # replay) skip both the re-pickle and the model bytes
        self._digest_cache: Dict[int, tuple] = {}
        self._server_has: set = set()

    # -- plumbing -------------------------------------------------------

    def _connect(self, sock_timeout: Optional[float]) -> socket.socket:
        sock = socket.create_connection(self.address,
                                        timeout=self.timeout)
        sock.settimeout(sock_timeout if sock_timeout is not None
                        else self.timeout)
        return sock

    def _call(self, body: dict,
              sock_timeout: Optional[float] = None) -> dict:
        """One request/reply exchange on a fresh connection; error
        frames re-raise as :class:`RpcError`."""
        sock = self._connect(sock_timeout)
        try:
            send_frame(sock, body, self.max_frame)
            reply = recv_frame(sock, self.max_frame)
        finally:
            try:
                sock.close()
            except OSError:
                pass
        if reply.get("op") == "error":
            raise RpcError(reply.get("error") or "remote error")
        return reply

    # -- the ChainServer-shaped surface ---------------------------------

    def _digest_of(self, ma) -> str:
        key = id(ma)
        hit = self._digest_cache.get(key)
        if hit is not None and hit[0] is ma:
            return hit[1]
        import hashlib

        digest = hashlib.sha256(
            pickle.dumps(ma, protocol=4)).hexdigest()
        if len(self._digest_cache) > 128:
            self._digest_cache.clear()
        self._digest_cache[key] = (ma, digest)
        return digest

    def submit(self, request,
               timeout: Optional[float] = None) -> RemoteTenantHandle:
        """Queue a job on the remote pool; ``timeout`` bounds the
        remote admission-queue wait (the backpressure contract). A
        model the server already holds (by content digest) rides the
        wire as its digest alone; a ``need_model`` reply falls back
        to a full submit — so the first submission is one round trip
        either way and repeats skip the model bytes."""
        digest = self._digest_of(request.ma)
        omit = digest in self._server_has
        body = _request_body(request, include_model=not omit,
                             digest=digest)
        body["timeout"] = timeout
        if not body["stream"]:
            reply = self._call(body)
            if reply.get("op") == "need_model":
                self._server_has.discard(digest)
                body = _request_body(request, digest=digest)
                body["timeout"] = timeout
                reply = self._call(body)
            if reply.get("op") == "rejected":
                raise _rejected_error(reply)
            self._server_has.add(digest)
            return RemoteTenantHandle(self, reply["tenant_id"], request)
        # streaming: the connection outlives the call
        sock = self._connect(None)
        try:
            send_frame(sock, body, self.max_frame)
            reply = recv_frame(sock, self.max_frame)
            if reply.get("op") == "need_model":
                # digest miss on a fresh server: retry with the model
                # on the same connection (the server answered and
                # kept it open)
                self._server_has.discard(digest)
                body = _request_body(request, digest=digest)
                body["timeout"] = timeout
                send_frame(sock, body, self.max_frame)
                reply = recv_frame(sock, self.max_frame)
        except BaseException:
            sock.close()
            raise
        if reply.get("op") in ("rejected", "error"):
            sock.close()
            if reply.get("op") == "rejected":
                raise _rejected_error(reply)
            raise RuntimeError(reply.get("error"))
        self._server_has.add(digest)
        h = RemoteTenantHandle(self, reply["tenant_id"], request,
                               streamed=True)
        t = threading.Thread(target=self._stream_reader,
                             args=(sock, h, request.on_chunk),
                             name="gst-rpc-stream", daemon=True)
        t.start()
        self._streams.append((sock, t))
        return h

    @staticmethod
    def _stream_reader(sock, h: RemoteTenantHandle,
                       on_chunk: Callable) -> None:
        """Consume chunk frames until the terminal frame (or a severed
        connection, which resolves the handle to an error — a client
        must never hang on a dead wire)."""
        try:
            while True:
                # the client's configured ceiling, not the env default
                # — chunk/result frames obey the same limit as _call
                body = recv_frame(sock, h.client.max_frame)
                if body.get("op") == "chunk":
                    try:
                        on_chunk(h, body["sweep_end"], body["records"])
                    except Exception:  # noqa: BLE001 - client callback
                        pass  # local callback bugs never kill the stream
                    continue
                try:
                    h._resolve(body)
                except (TimeoutError, RpcError) as e:
                    h._error = e
                    h._done.set()
                return
        except (FrameError, ConnectionError, OSError) as e:
            h._error = ConnectionError(
                f"stream severed: {type(e).__name__}: {e}")
            h._done.set()
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def cancel(self, handle: RemoteTenantHandle) -> bool:
        return bool(self._call(handle._body("cancel"))["cancelled"])

    def status(self) -> dict:
        return self._call({"op": "status"})["status"]

    def healthz(self) -> dict:
        return self._call({"op": "healthz"})["healthz"]

    def server_time(self):
        """One NTP-style clock sample against the remote worker:
        ``(t0, ts, t1)`` — local wall time at send, the server's wall
        time, local wall time at receive. A handful of these through
        ``obs/aggregate.py estimate_clock_offset`` yields the pool's
        clock offset (min-RTT sample) for fleet trace stitching."""
        t0 = time.time()
        ts = float(self._call({"op": "time"})["t"])
        return (t0, ts, time.time())

    def trace(self) -> Optional[dict]:
        """The remote worker's Chrome trace document (None when the
        worker runs with spans disabled) — the stitcher's RPC fallback
        when the worker exposes no HTTP ``/trace``."""
        return self._call({"op": "trace"})["trace"]

    def reset_counters(self) -> None:
        """Zero the remote pool's run-level aggregates (the bench
        warmup boundary, over the wire)."""
        self._call({"op": "reset"})

    def shutdown(self) -> None:
        """Ask the remote worker process to retire (pool_main arms
        the callback; a bare RpcServer answers an error)."""
        self._call({"op": "shutdown"})

    def close(self) -> None:
        """Drop any live stream connections (their handles resolve to
        severed-connection errors if still pending)."""
        streams, self._streams = self._streams, []
        for sock, t in streams:
            try:
                sock.close()
            except OSError:
                pass
            t.join(timeout=2.0)
