"""Durable server manifest: the crash-recovery log of a ChainServer.

A long-running serving process dies — OOM killer, node preemption,
plain ``kill -9`` — and the question is what survives. Per-tenant
*records* already do (the spool files + rolling state checkpoint,
utils/spool.py). What did NOT survive before this module is the
*server's* knowledge of who was running: which tenants were admitted,
with what budgets/seeds/policies, and how far their checkpoints got.
The manifest closes that gap with the same append-only JSONL record
discipline as the run ledger (obs/ledger.py): each record is one
compact JSON line written by a single ``os.write`` on an ``O_APPEND``
descriptor and fsync'd, so a crash can at worst leave one torn final
line, which the reader skips.

Record kinds (each carries ``t`` unix seconds; schema in
docs/OBSERVABILITY.md):

- ``server``  — one per ChainServer epoch: pool geometry (nlanes,
  quantum, group, record mode/thin, heterogeneous) so ``recover``
  rebuilds an identical pool. The template model + config are pickled
  beside the log (``server.pkl``) — they are numpy pytrees, not JSON.
- ``admit``   — tenant admission: id, name, seed, niter, nchains,
  start_sweep, spool_dir, on_divergence, on_converged, the monitor
  spec (JSON fields — recovery re-arms convergence eviction, so a
  failed-over ``on_converged='evict'`` tenant still evicts at its
  convergence boundary), and (for spooled tenants) the pickled model
  file recovery re-reads.
- ``checkpoint`` — after every spool append: the tenant's resume point
  (``next_sweep``) — the generation counter recovery resumes from.
- ``done``    — tenant finalized (status ``done`` or ``failed``).
- ``fault`` / ``quarantine`` / ``reinit`` — the containment events,
  mirrored here so a post-mortem needs only the manifest.

Multiple server epochs append to one log (a recovered server keeps
writing where the dead one stopped); records are implicitly scoped to
the latest preceding ``server`` record, and recovery resolves the
outstanding set per *spool directory* — the stable identity of a
logical job across epochs.

Writes are non-fatal by the same argument as ledger appends: one
bounded retry, then warn-and-continue — a bookkeeping write must never
take down the serving loop it describes (the tenants' own records are
on the spool path, which keeps its own fsync discipline).
"""

from __future__ import annotations

import json
import os
import pickle
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

MANIFEST_NAME = "manifest.jsonl"
SERVER_PICKLE = "server.pkl"
#: content-addressed model store subdirectory (ROADMAP 1c): one
#: ``<sha256>.pkl`` per distinct tenant model, referenced by digest
#: from admit records — resubmission and failover stop re-appending
#: identical pickles to the manifest directory
MODELS_DIR = "models"


def _append_line(path: str, record: Dict[str, Any]) -> None:
    """The ledger append discipline (single fsync'd O_APPEND write),
    made non-fatal: one retry on an OSError-class failure, then
    warn-and-continue."""
    from gibbs_student_t_tpu.obs.metrics import _jsonable

    line = (json.dumps(_jsonable(record), separators=(",", ":"))
            + "\n").encode()
    for attempt in (0, 1):
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                         0o644)
            try:
                os.write(fd, line)
                os.fsync(fd)
            finally:
                os.close(fd)
            return
        except OSError as e:  # EINTR/ENOSPC-class transients
            if attempt:
                warnings.warn(
                    f"server manifest append failed twice "
                    f"({type(e).__name__}: {e}); record dropped — "
                    f"recovery may lose this event", RuntimeWarning,
                    stacklevel=2)


def read_manifest(manifest_dir: str) -> List[Dict[str, Any]]:
    """Every parseable manifest record in file order (torn final lines
    skipped — the obs/ledger reader tolerance)."""
    from gibbs_student_t_tpu.obs.ledger import read_ledger

    return read_ledger(os.path.join(manifest_dir, MANIFEST_NAME))


class ServerManifest:
    """Writer handle for one ChainServer's manifest directory."""

    def __init__(self, manifest_dir: str):
        self.dir = manifest_dir
        os.makedirs(manifest_dir, exist_ok=True)
        self.path = os.path.join(manifest_dir, MANIFEST_NAME)
        # epoch index = how many server records precede ours
        self.epoch = sum(1 for r in read_manifest(manifest_dir)
                         if r.get("kind") == "server")

    def record(self, kind: str, **fields) -> None:
        rec = {"kind": kind, "t": round(time.time(), 3)}
        rec.update(fields)
        _append_line(self.path, rec)

    # -- server epoch ---------------------------------------------------

    def record_server(self, template_ma, config,
                      pool_kwargs: Dict[str, Any]) -> None:
        """Start an epoch: pickle the template/config (pytrees, not
        JSON-able) and log the pool geometry."""
        tmp = os.path.join(self.dir, SERVER_PICKLE + ".tmp")
        with open(tmp, "wb") as fh:
            pickle.dump({"template_ma": template_ma, "config": config},
                        fh)
        os.replace(tmp, os.path.join(self.dir, SERVER_PICKLE))
        self.record("server", epoch=self.epoch, **pool_kwargs)

    # -- tenants --------------------------------------------------------

    def store_model(self, model) -> Tuple[str, str]:
        """Content-addressed model store (ROADMAP 1c): pickle the
        model, hash it, and persist ONE ``models/<digest>.pkl`` blob
        per distinct model — a resubmitted or failed-over tenant's
        admit references the digest instead of appending another
        pickle, so the manifest directory stops growing linearly in
        admissions of the same model. Returns ``(digest,
        relative_path)``; the write is atomic and skipped on a digest
        hit."""
        import hashlib

        blob = pickle.dumps(model, protocol=4)
        digest = hashlib.sha256(blob).hexdigest()
        rel = os.path.join(MODELS_DIR, digest + ".pkl")
        path = os.path.join(self.dir, rel)
        if not os.path.exists(path):
            os.makedirs(os.path.join(self.dir, MODELS_DIR),
                        exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, path)
        return digest, rel

    def record_admit(self, tenant_id: int, request,
                     model=None, warm=None) -> None:
        model_file = model_digest = None
        if model is not None:
            model_digest, model_file = self.store_model(model)
        mon = getattr(request, "monitor", None)
        self.record(
            "admit", tenant=tenant_id, name=request.name,
            seed=request.seed, niter=request.niter,
            nchains=request.nchains, start_sweep=request.start_sweep,
            spool_dir=request.spool_dir,
            on_divergence=request.on_divergence,
            on_converged=getattr(request, "on_converged", "none"),
            monitor=(None if mon is None else {
                "params": (None if mon.params is None
                           else [p if isinstance(p, str) else int(p)
                                 for p in mon.params]),
                "ess_target": mon.ess_target,
                "rhat_target": mon.rhat_target,
                "every": mon.every, "min_rows": mon.min_rows}),
            model_file=model_file, model_digest=model_digest,
            warm=warm, trace_id=getattr(request, "trace_id", None),
            priority=getattr(request, "priority", 1),
            deadline_sweeps=getattr(request, "deadline_sweeps", None))

    def record_checkpoint(self, tenant_id: int, next_sweep: int) -> None:
        self.record("checkpoint", tenant=tenant_id,
                    next_sweep=next_sweep)

    def record_done(self, tenant_id: int, status: str,
                    sweeps: int) -> None:
        self.record("done", tenant=tenant_id, status=status,
                    sweeps=sweeps)

    def compact(self, keep_lost: bool = True) -> int:
        """Rewrite this manifest as its compacted snapshot (see
        :func:`compact_manifest`); the writer keeps appending to the
        same path afterwards. Returns the number of records kept."""
        return compact_manifest(self.dir, keep_lost=keep_lost)


def load_server_state(manifest_dir: str) -> Tuple[object, object,
                                                  Dict[str, Any]]:
    """(template_ma, config, pool_kwargs-from-latest-server-record)."""
    with open(os.path.join(manifest_dir, SERVER_PICKLE), "rb") as fh:
        blob = pickle.load(fh)
    server_recs = [r for r in read_manifest(manifest_dir)
                   if r.get("kind") == "server"]
    if not server_recs:
        raise ValueError(
            f"manifest at {manifest_dir!r} has no server record")
    kw = {k: v for k, v in server_recs[-1].items()
          if k not in ("kind", "t", "epoch", "compacted",
                       "compacted_from")}
    return blob["template_ma"], blob["config"], kw


def outstanding_tenants(manifest_dir: str) -> Tuple[List[Dict[str, Any]],
                                                    List[Dict[str, Any]]]:
    """Resolve the recovery set: tenants admitted but never finalized.

    Returns ``(recoverable, lost)`` admit-record lists. A tenant is
    *outstanding* when its latest admit (per spool_dir for spooled
    tenants, per (epoch, tenant id) otherwise) has no matching ``done``
    in the same epoch; it is *recoverable* when it was spooled with a
    pickled model (in-memory tenants' drained records died with the
    process — they are reported as lost, not silently dropped)."""
    epoch = -1
    # keyed by logical identity; values (admit_record, done_seen)
    jobs: Dict[object, List] = {}
    for r in read_manifest(manifest_dir):
        kind = r.get("kind")
        if kind == "server":
            epoch += 1
        elif kind == "admit":
            key = r.get("spool_dir") or ("mem", epoch, r.get("tenant"))
            jobs[key] = [dict(r, epoch=epoch), False]
        elif kind == "done":
            for key, v in jobs.items():
                if (v[0].get("tenant") == r.get("tenant")
                        and v[0]["epoch"] == epoch):
                    v[1] = True
    recoverable, lost = [], []
    for v in jobs.values():
        rec, done = v
        if done:
            continue
        if rec.get("spool_dir") and rec.get("model_file"):
            recoverable.append(rec)
        else:
            lost.append(rec)
    return recoverable, lost


def load_tenant_model(manifest_dir: str, admit_record: Dict[str, Any]):
    with open(os.path.join(manifest_dir, admit_record["model_file"]),
              "rb") as fh:
        return pickle.load(fh)


def compact_manifest(manifest_dir: str, keep_lost: bool = True) -> int:
    """Rewrite ``manifest.jsonl`` as its minimal recovery-equivalent
    snapshot: ONE ``server`` record (the latest epoch's geometry,
    stamped ``compacted=true`` + the dropped-record count) followed by
    every OUTSTANDING tenant's admit and its latest checkpoint.
    Unreferenced ``model_*.pkl`` blobs are deleted.

    The journal grows without bound in steady state — every admission
    of a spooled tenant pickles its model beside the log, and a
    long-lived pool accumulates epochs of finished tenants a recovery
    must parse past — so a failed-over pool's cold start pays for dead
    history. Compaction preserves exactly the recovery-relevant
    state: ``outstanding_tenants`` + ``load_server_state`` over the
    compacted file answer identically to the full journal, so
    ``ChainServer.recover`` from either is **bitwise the same run**
    (pinned in tests/test_fleet.py). Containment history (fault /
    quarantine / reinit records) is postmortem evidence, not recovery
    state, and is dropped — the flight recorder owns that story.

    ``keep_lost=False`` additionally drops LOST admits (in-memory
    tenants whose records died with a crashed process): only the
    ``recover()``-time compaction passes it — recovery has already
    surfaced those jobs on ``lost_tenants`` (and at fleet scope the
    router replays them elsewhere), so keeping their admits would
    just re-report the same loss at every future recovery, forever.

    Atomic: written to a temp file and ``os.replace``d, so a crash
    mid-compaction leaves the full journal in place. Returns the
    number of records in the compacted file."""
    records = read_manifest(manifest_dir)
    server_recs = [r for r in records if r.get("kind") == "server"]
    if not server_recs:
        return 0   # nothing to compact (empty/foreign dir)
    recoverable, lost = outstanding_tenants(manifest_dir)
    outstanding = recoverable + (lost if keep_lost else [])
    # latest checkpoint per outstanding (epoch, tenant) pair — the
    # resume point recovery reads. Epochs are tracked the same way
    # outstanding_tenants walks them.
    latest_ckpt: Dict[Any, Dict[str, Any]] = {}
    epoch = -1
    for r in records:
        kind = r.get("kind")
        if kind == "server":
            epoch += 1
        elif kind == "checkpoint":
            latest_ckpt[(epoch, r.get("tenant"))] = r
    head = dict(server_recs[-1])
    head["compacted"] = True
    head["compacted_from"] = len(records)
    head["epoch"] = 0
    out: List[Dict[str, Any]] = [head]
    keep_models = set()
    for rec in outstanding:
        admit = {k: v for k, v in rec.items() if k != "epoch"}
        out.append(admit)
        if rec.get("model_file"):
            keep_models.add(rec["model_file"])
        ck = latest_ckpt.get((rec["epoch"], rec.get("tenant")))
        if ck is not None:
            out.append(ck)
    path = os.path.join(manifest_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    from gibbs_student_t_tpu.obs.metrics import _jsonable

    with open(tmp, "w") as fh:
        for r in out:
            fh.write(json.dumps(_jsonable(r), separators=(",", ":"))
                     + "\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    for name in os.listdir(manifest_dir):
        if (name.startswith("model_") and name.endswith(".pkl")
                and name not in keep_models):
            try:
                os.unlink(os.path.join(manifest_dir, name))
            except OSError:
                pass
    # the content-addressed store (ROADMAP 1c): digests no
    # outstanding admit references are dead weight too
    mdir = os.path.join(manifest_dir, MODELS_DIR)
    if os.path.isdir(mdir):
        for name in os.listdir(mdir):
            if (name.endswith(".pkl")
                    and os.path.join(MODELS_DIR, name)
                    not in keep_models):
                try:
                    os.unlink(os.path.join(mdir, name))
                except OSError:
                    pass
    return len(out)
