"""The slot pool: one compiled chunk program, per-lane call-time operands.

A :class:`SlotPool` owns ``nlanes`` lanes, each an independent chain
whose model (dataset + priors), fused-MH constants, philox chain key,
sweep offset and active flag are CALL-TIME OPERANDS of a single jitted
chunk function — the same trick ``parallel/ensemble.py`` plays for
grouped ensembles, extended to the per-model fast-draw paths via the
backend's ``operand_mode`` and the native ``*_lanes`` kernels
(ops/linalg.py ``tnt_gram_lanes`` / ``fused_hyper_draws(gid=...)``).
Writing a tenant into its lanes is a host-side numpy slice assignment;
the program never retraces, so admission latency is buffer writes plus
one device upload (obs/introspect.py compile records pin exactly ONE
compile for the pool's lifetime — tests/test_serve.py).

Lane state is DEVICE-RESIDENT between quanta (round 11): the chunk
program donates its ``ChainState`` argument (the ``GST_DONATE_CHUNK``
discipline extended to serving), so a quantum with no admissions pays
zero state roundtrips — the state buffers ping-pong inside XLA. The
host numpy mirror (``_state_np``) is pulled lazily, only when an
admission needs to slice-write tenant chains in or a spool checkpoint
needs host arrays; :meth:`dispatch_quantum` re-uploads it (as a COPY,
so donation can never alias the canonical host buffers) on the next
boundary. Drains that outlive the next dispatch (the pipelined
executor's deferred flush) read a ``snapshot`` device copy taken
before the donated buffers are consumed — the ``snapshot_fn`` ordering
contract of ``backends.jax_backend.chunked_sweep_loop``.

Under ``GST_SERVE_SCATTER`` (round 21, default on) the boundary writes
that used to force that lazy pull become DEVICE-RESIDENT too: while
the canonical state is on device, admissions (:meth:`write_tenant`),
recovery (:meth:`reinit_lanes`) and fault injection
(:meth:`poison_lanes`) apply their deltas as fixed-shape jitted lane
scatters — the delta rides as a small call-time operand plus a
lane-index vector, and the full state never materializes on the host —
while checkpoint reads (:meth:`tenant_state`) gather only the owning
tenant's lane rows. On CPU this removes the mirror bounce from the
admission path (measured in serve_bench's admission A/B); over PCIe it
is the difference between a per-admit transfer proportional to the
TENANT and one proportional to the POOL. ``GST_SERVE_SCATTER=0`` keeps
every write on the PR-19 pull/slice-write/re-upload path verbatim, and
scatter-on is pinned bitwise against it (tests/test_serve.py): the
scatter is a pure copy into the same buffers the bounce would rebuild,
and untouched lanes' device→host→device roundtrip is bit-preserving.

RNG and keying are bit-compatible with ``JaxGibbs.sample``: a tenant's
lane ``k`` carries ``random.split(PRNGKey(seed), nchains)[k]`` and each
sweep folds in the tenant-local sweep index, so a solo tenant's chains
are bit-identical to the same seed run through the single-model
backend (the gates-off guarantee extends to serving; pinned in
tests/test_serve.py).
"""

from __future__ import annotations

import functools
import time as _time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, random

from gibbs_student_t_tpu.backends.jax_backend import (
    NBLOCKS,
    ChainState,
    FusedConsts,
    JaxGibbs,
    record_tuple,
)
from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.models.pta import ModelArrays
from gibbs_student_t_tpu.obs.telemetry import telemetry_init, telemetry_update
from gibbs_student_t_tpu.parallel.ensemble import (
    _localize_names,
    pad_model_arrays,
)

#: Admission granularity in lanes: the f32 SIMD tile width of the
#: native lanes kernels (native/src/gst_kernels.h ``Lanes<float>::W``),
#: which is also a multiple of the f64 width — per-lane constants must
#: be uniform within every aligned tile, so tenants are admitted in
#: whole groups of this many lanes.
GROUP_LANES = 16

#: gid of lanes not owned by any tenant (whole free groups). Free
#: groups keep whatever constants last occupied them; their lanes are
#: inactive, their outputs discarded, and their state frozen by the
#: active mask, so stale constants are harmless.
FREE_GID = -1


def serve_scatter_env() -> str:
    """Validated ``GST_SERVE_SCATTER`` (``auto`` when unset) — the
    device-resident admission path. Strict ``auto|1|0`` (the loud-typo
    contract of every GST_* gate); ``auto`` resolves to ON — the
    scatter writes the same bytes the host bounce would rebuild, on
    every platform, so chains/spools/recovery are bitwise identical
    on/off. ``0`` keeps the pull/slice-write/re-upload bounce (the A/B
    arm and the bitwise reference)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_SERVE_SCATTER")


def serve_scatter_enabled() -> bool:
    """Resolved ``GST_SERVE_SCATTER`` (see :func:`serve_scatter_env`).
    Snapshotted ONCE at pool construction, the ``adapt_scan_enabled``
    discipline — flipping the env var after a pool exists has no
    effect on it."""
    from gibbs_student_t_tpu.ops import registry

    on, _forced = registry.mode3("GST_SERVE_SCATTER")
    return bool(on)


def _scatter_state_tree(state: ChainState, lanes, delta: dict):
    """``state`` with ``delta[f]`` scatter-written into ``state.f`` at
    the given lane rows — the jitted device-side admission write. The
    delta's key SET is part of the pytree structure, so each distinct
    write shape (full admission, the reinit subset, the poison x-only
    delta) compiles once per lane count and is a cheap fixed-shape
    scatter thereafter."""
    repl = {f: getattr(state, f).at[lanes].set(v)
            for f, v in delta.items()}
    return state._replace(**repl)


def _gather_state_tree(state: ChainState, lanes):
    """One tenant's lane rows of the device state — the narrow
    checkpoint-read gather (scalar leaves pass through)."""
    return jax.tree.map(
        lambda a: a[lanes] if getattr(a, "ndim", 0) else a, state)


class TenantSlot:
    """Book-keeping for one admitted tenant (host side only)."""

    def __init__(self, tenant_id: int, lanes: np.ndarray, nchains: int,
                 niter: int, start_sweep: int, n_real: int, seed: int):
        self.tenant_id = tenant_id
        self.lanes = lanes            # (ceil(nchains/G)*G,) lane indices
        self.nchains = nchains        # real chains; lanes[nchains:] pad
        self.niter = niter
        self.start_sweep = start_sweep
        self.done_sweeps = 0          # tenant-local sweeps served so far
        self.n_real = n_real
        self.seed = seed
        # an eviction request (ChainServer.cancel) landing while a
        # quantum is in flight: the lane freezes at the NEXT boundary
        self.cancelled = False
        # tenant-scoped fault containment (ChainServer, supervised):
        # a failed tenant freezes and releases exactly like a cancel,
        # but its handle resolves to a structured TenantError
        self.failed = False
        self.fail_where: str = ""
        self.fail_cause = None
        # lane-health bookkeeping (on_divergence policies)
        self.quarantined: set = set()   # tenant-chain indices frozen
        self.n_reinits = 0

    @property
    def chain_lanes(self) -> np.ndarray:
        return self.lanes[:self.nchains]

    @property
    def remaining(self) -> int:
        return self.niter - self.done_sweeps


class SlotPool:
    """``nlanes`` single-chain lanes behind ONE compiled chunk program.

    ``quantum`` is the scheduling granularity in sweeps: every
    :meth:`run_quantum` advances all active lanes by exactly that many
    sweeps (tenants' ``niter`` must be multiples of it, so the static
    chunk length never changes and the program never recompiles).
    ``template_ma`` fixes the pool's model STRUCTURE — shapes (every
    tenant's TOA axis is padded to the pool ``n`` with masked rows),
    basis size, parameter structure, Schur split, prior kinds; tenants
    must match it (the scheduler validates at admission).
    """

    def __init__(self, template_ma: ModelArrays, config: GibbsConfig,
                 nlanes: int = 1024, quantum: int = 25,
                 group: int = GROUP_LANES, dtype=jnp.float32,
                 record: str = "compact8", record_thin: int = 1,
                 heterogeneous: bool = False,
                 telemetry: bool = True, metrics=None, spans=None):
        """``heterogeneous=True`` stacks row-masked models so tenants
        with FEWER TOAs than the pool axis can ride the same operand
        buffers (suffix padding, exactly the ensemble convention). The
        default homogeneous pool requires every tenant to match the
        pool ``n`` and keeps the statistical TOA count a trace-time
        integer — the configuration under which a solo tenant's chains
        are BIT-identical to ``JaxGibbs.sample`` (a traced mask's
        float-typed count rounds ``n * outlier_mean`` differently;
        heterogeneous pools agree in law, not bits)."""
        if group % GROUP_LANES:
            raise ValueError(
                f"group ({group}) must be a multiple of {GROUP_LANES} "
                "— the native lanes kernels require per-lane constants "
                "uniform within every aligned SIMD tile "
                "(native/src/gst_kernels.h)")
        if nlanes % group:
            raise ValueError(f"nlanes ({nlanes}) must be a multiple of "
                             f"the admission group ({group})")
        if config.mh.adapt_cov:
            raise ValueError(
                "the serve slot pool does not support population-"
                "covariance adaptation (adapt_cov): proposal factors "
                "couple chains across one tenant's population, which "
                "has no lane-local form")
        self.nlanes = nlanes
        self.quantum = quantum
        self.group = group
        self.metrics = metrics
        # pool-level executor spans (obs/spans.SpanRecorder, optional):
        # the operand upload and the chunk-call handoff are the two
        # host steps a dispatch pays — tracing them attributes a slow
        # boundary to uploads vs the program call in the swimlane view
        self._spans = spans
        self.heterogeneous = bool(heterogeneous)
        tmpl = _localize_names(template_ma)
        if tmpl.row_mask is not None:
            raise ValueError("template_ma must be an unpadded model "
                             "(its n defines the pool TOA axis)")
        if self.heterogeneous:
            (tmpl_model,) = pad_model_arrays([tmpl], n_to=tmpl.n)
        else:
            tmpl_model = tmpl
        self.template = JaxGibbs(
            tmpl_model, config, nchains=nlanes, dtype=dtype,
            chunk_size=quantum, record=record, record_thin=record_thin,
            tnt_block_size=None, use_pallas=False, telemetry=telemetry,
            metrics=metrics, operand_mode=True)
        t = self.template
        if quantum % t.record_thin:
            raise ValueError(f"quantum ({quantum}) must be a multiple "
                             f"of record_thin ({t.record_thin})")
        self.n_pool = tmpl.n
        self.dtype = dtype
        # ---- host-authoritative lane buffers --------------------------
        # stacked per-lane model: every lane starts as the template
        stack = jax.tree.map(
            lambda a: np.repeat(np.asarray(a)[None], nlanes, axis=0),
            tmpl_model)
        self._mas_np: ModelArrays = stack
        self._keys_np = np.zeros((nlanes, 2), np.uint32)
        self._offsets_np = np.zeros(nlanes, np.int32)
        self._active_np = np.zeros(nlanes, bool)
        self._gid_np = np.full(nlanes, FREE_GID, np.int32)
        self._fc_np = self._template_consts_stack()
        self._state_np = jax.tree.map(np.array, t.init_state(seed=0))
        self._dirty = True
        self._mas_dev = None
        self._fc_dev = None
        # device-resident lane state (GST_DONATE_CHUNK extended to
        # serving): between quanta the canonical state lives on device
        # and the chunk donates it; the host mirror is pulled lazily
        # for admission writes and checkpoint reads
        from gibbs_student_t_tpu.backends.jax_backend import (
            donate_resolved,
        )

        self._donate = donate_resolved()
        self._state_dev = None        # latest post-quantum device state
        self._host_valid = True       # _state_np mirrors the canon
        # device-resident admission (GST_SERVE_SCATTER, resolved once —
        # the adapt_scan_enabled discipline): boundary writes landing
        # while the canon is device-resident go through the jitted
        # scatter below instead of pulling the mirror; `0` keeps every
        # write on the host-bounce path verbatim (bitwise pin)
        self.scatter = serve_scatter_enabled()
        # plain jax.jit (no introspect label): the one-compile pin
        # counts only `serve_pool_chunk*` programs, and these small
        # scatter/gather programs recompile per admitted lane count
        self._scatter_fn = jax.jit(
            _scatter_state_tree,
            donate_argnums=(0,) if self._donate else ())
        self._gather_fn = jax.jit(_gather_state_tree)
        self._admit_bytes: list = []  # operand bytes moved per admit
        # adaptive block scans (serve/adapt.py, GST_ADAPT_SCAN):
        # resolved ONCE at pool construction — when on, the chunk
        # carries a per-lane (NBLOCKS,) block-enable operand riding its
        # own host-authoritative buffer; when off, the chunk is built
        # WITHOUT the operand, so the gates-off lowered graph is the
        # pre-adaptive one verbatim (bitwise pin, tests/test_adapt.py)
        from gibbs_student_t_tpu.serve.adapt import adapt_scan_enabled

        self.adaptive = adapt_scan_enabled()
        self._bg_np = np.ones((nlanes, NBLOCKS), np.float32)
        # separate dirty flag: gate redraws at drain boundaries must
        # not trigger the (expensive) full mas+consts re-upload
        self._bg_dirty = self.adaptive
        self._bg_dev = None
        # the ONE compiled chunk program
        from gibbs_student_t_tpu.obs.introspect import introspect_jit

        donate = (0,) if self._donate else ()
        self._chunk = introspect_jit(
            jax.jit(self._make_chunk(), static_argnames=("length",),
                    donate_argnums=donate),
            label=f"serve_pool_chunk_l{nlanes}",
            registry=lambda: self.metrics,
            static_argnames=("length",),
            donate_argnums=donate)

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------

    def _template_consts_stack(self) -> FusedConsts:
        """Per-lane fused-MH constant buffers, initialized to the
        template's own constants (free lanes keep them)."""
        t = self.template
        L = self.nlanes

        def rep(a):
            return (None if a is None
                    else np.repeat(np.asarray(a, np.float32)[None], L,
                                   axis=0))

        wc = t._white_consts
        hc = t._fuse_consts if t._fuse_consts is not None else t._hyper_consts
        return FusedConsts(
            white_rows=rep(wc.rows) if wc is not None else None,
            white_specs=rep(wc.specs) if wc is not None else None,
            hyper_K=rep(hc.K) if hc is not None else None,
            hyper_sel=rep(hc.phi_sel) if hc is not None else None,
            hyper_phiinv_static=(rep(hc.phiinv_static)
                                 if hc is not None else None),
            hyper_logdet_phi_static=(
                np.full(L, hc.logdet_phi_static, np.float32)
                if hc is not None else None),
            hyper_specs=rep(hc.specs) if hc is not None else None,
            gid=self._gid_np,
        )

    def _make_chunk(self):
        t = self.template
        fields = t._record_fields
        casts = t._record_casts
        thin = t.record_thin
        use_tele = t._telemetry

        def lane_chunk(ma_l, fc_l, state, chain_key, offset, bg_l=None,
                       *, length):
            # mirrors the single-model chunk fn (backends/jax_backend
            # _make_chunk_fn one_chain) with the model and fused consts
            # as traced per-lane operands and a per-lane sweep offset
            def one(j, c):
                s, tl = c
                s = t._sweep(s, random.fold_in(chain_key, j), ma=ma_l,
                             sweep=j, fused=fc_l, block_gates=bg_l)
                return s, (telemetry_update(tl, s) if use_tele else tl)

            def body(carry, i0):
                st, tl = carry
                rec = record_tuple(st, fields, casts)
                if thin == 1:
                    st, tl = one(i0, (st, tl))
                else:
                    st, tl = lax.fori_loop(
                        0, thin, lambda j, c: one(i0 + j, c), (st, tl))
                return (st, tl), rec

            (st, tl), recs = lax.scan(
                body, (state, telemetry_init(t.dtype)),
                offset + jnp.arange(0, length, thin))
            if use_tele:
                tl = tl._replace(logpost=t._logpost_chain(st, ma=ma_l))
            return st, recs, tl

        def freeze_inactive(sts, states, active):
            # freeze empty slots: their draws are discarded and their
            # parked state carries over bitwise, so a stale model in a
            # free group can never poison a future admission
            def keep(new, old):
                m = active.reshape((-1,) + (1,) * (new.ndim - 1))
                return jnp.where(m, new, old)

            return jax.tree.map(keep, sts, states)

        def chunk(states, mas, fcs, keys, offsets, active, length):
            sts, recs, tl = jax.vmap(
                functools.partial(lane_chunk, length=length)
            )(mas, fcs, states, keys, offsets)
            sts = freeze_inactive(sts, states, active)
            return sts, (recs, tl if use_tele else None)

        def chunk_adaptive(states, mas, fcs, keys, offsets, active,
                           bgs, length):
            # the block-gates operand threads to _sweep exactly as the
            # other per-lane operands do; an all-ones row is the
            # full-rate systematic scan (value-identical to `chunk`)
            sts, recs, tl = jax.vmap(
                functools.partial(lane_chunk, length=length)
            )(mas, fcs, states, keys, offsets, bgs)
            sts = freeze_inactive(sts, states, active)
            return sts, (recs, tl if use_tele else None)

        return chunk_adaptive if self.adaptive else chunk

    # ------------------------------------------------------------------
    # lane writes (host-side buffer writes — never a recompile)
    # ------------------------------------------------------------------

    def _pull_state(self) -> None:
        """Make the host state mirror current (device -> host when the
        canonical copy is device-resident). Blocks until the last
        dispatched quantum's state is computed."""
        if not self._host_valid:
            self._state_np = jax.tree.map(np.array, self._state_dev)
            self._host_valid = True

    def _state_nbytes(self) -> int:
        """Byte size of one full state plane (array leaves) — what the
        host bounce moves each way when it pulls/re-uploads the
        mirror. Shapes never change, so the (possibly stale) mirror is
        a valid ruler."""
        return sum(int(np.asarray(a).nbytes)
                   for a in jax.tree_util.tree_leaves(self._state_np)
                   if np.asarray(a).ndim)

    def _scatter_state(self, lanes: np.ndarray, delta: dict) -> int:
        """Apply a boundary write as a jitted device scatter into the
        canonical device-resident state — the mirror is never
        materialized and ``_host_valid`` stays False. ``delta`` values
        are freshly-built private host arrays (never views of live
        canonical buffers), so handing them to jax directly keeps the
        torn-operand discipline of :meth:`dispatch_quantum`. Returns
        the operand bytes moved."""
        lanes_d = jnp.asarray(np.array(lanes, np.int32, copy=True))
        delta_d = {f: jnp.asarray(v) for f, v in delta.items()}
        self._state_dev = self._scatter_fn(self._state_dev, lanes_d,
                                           delta_d)
        return (int(lanes_d.nbytes)
                + sum(int(np.asarray(v).nbytes) for v in delta.values()))

    def write_tenant(self, slot: TenantSlot, ma_padded: ModelArrays,
                     backend: JaxGibbs, state: ChainState) -> None:
        """Admit a tenant into its lanes: slice-assign its model,
        fused-MH constants, chain keys, offsets and state into the
        host lane buffers. The STATE plane goes as a device scatter
        instead when ``GST_SERVE_SCATTER`` is on and the canon is
        device-resident (the other planes are host-authoritative
        operand buffers either way — they upload on the next dispatch
        regardless of the gate). ``backend`` is the tenant's throwaway
        construction backend (structure already validated)."""
        lanes = slot.lanes
        k = slot.nchains
        # model arrays (the localized+padded tenant model)
        self._mas_np = jax.tree.map(
            lambda buf, val: _assign(buf, lanes, np.asarray(val)),
            self._mas_np, ma_padded)
        # fused-MH constants from the tenant's backend
        wc = backend._white_consts
        hc = (backend._fuse_consts if backend._fuse_consts is not None
              else backend._hyper_consts)
        fc = self._fc_np
        if fc.white_rows is not None and wc is not None:
            fc.white_rows[lanes] = np.asarray(wc.rows, np.float32)
            fc.white_specs[lanes] = np.asarray(wc.specs, np.float32)
        if fc.hyper_K is not None and hc is not None:
            fc.hyper_K[lanes] = np.asarray(hc.K, np.float32)
            fc.hyper_sel[lanes] = np.asarray(hc.phi_sel, np.float32)
            fc.hyper_phiinv_static[lanes] = np.asarray(
                hc.phiinv_static, np.float32)
            fc.hyper_logdet_phi_static[lanes] = np.float32(
                hc.logdet_phi_static)
            fc.hyper_specs[lanes] = np.asarray(hc.specs, np.float32)
        # keys: exactly the single-model backend's chain key schedule,
        # so lane k of the tenant IS chain k of a solo run
        keys = np.asarray(random.split(random.PRNGKey(slot.seed),
                                       slot.nchains))
        self._keys_np[lanes[:k]] = keys
        self._keys_np[lanes[k:]] = 0  # pad lanes: parked
        self._offsets_np[lanes] = slot.start_sweep
        self._active_np[lanes[:k]] = True
        self._active_np[lanes[k:]] = False
        self._gid_np[lanes] = slot.tenant_id
        # state: tenant chains into their lanes; pad lanes keep a copy
        # of chain 0 (finite, discarded)
        st = jax.tree.map(np.array, state)

        def padded(val):
            val = np.asarray(val)
            if len(lanes) > k:
                return np.concatenate(
                    [val, np.repeat(val[:1], len(lanes) - k, axis=0)])
            return val

        delta = {
            f: padded(getattr(st, f))
            for f in type(self._state_np)._fields
            if np.asarray(getattr(self._state_np, f)).ndim}
        if self.scatter and not self._host_valid:
            moved = self._scatter_state(lanes, delta)
        else:
            pulled = not self._host_valid
            self._pull_state()
            for f, val in delta.items():
                _assign(np.asarray(getattr(self._state_np, f)),
                        lanes, val)
            moved = sum(int(v.nbytes) for v in delta.values())
            if pulled:
                # the bounce's real cost: the full mirror comes down
                # AND goes back up on the next dispatch
                moved += 2 * self._state_nbytes()
        self._admit_bytes.append(moved)
        if self.adaptive:
            # a fresh tenant always starts at the full-rate systematic
            # scan; the server's policy thins it later, per boundary
            self._bg_np[lanes] = 1.0
            self._bg_dirty = True
        self._dirty = True

    def evict(self, slot: TenantSlot) -> None:
        """Free a tenant's lanes: deactivate and mark the groups free.
        Constants/state stay parked (frozen by the active mask) until
        the next admission overwrites them."""
        self._active_np[slot.lanes] = False
        self._gid_np[slot.lanes] = FREE_GID
        if self.adaptive:
            self._bg_np[slot.lanes] = 1.0  # parked lanes: inert anyway
            self._bg_dirty = True
        self._dirty = True

    def set_block_gates(self, lanes: np.ndarray,
                        gates: np.ndarray) -> None:
        """Write a tenant's per-block enable vector into its lanes —
        the adaptive-scan boundary update (serve/adapt.py). A host
        numpy slice write plus one small operand upload on the next
        dispatch; never touches the mas/consts upload path and never
        recompiles. No-op on a non-adaptive pool (the chunk has no
        gates operand to feed)."""
        if not self.adaptive:
            return
        self._bg_np[np.asarray(lanes, int)] = np.asarray(
            gates, np.float32)
        self._bg_dirty = True

    def quarantine_lanes(self, lanes: np.ndarray) -> None:
        """Mask diverged lanes inactive WITHOUT freeing their groups:
        the lanes stop advancing (state frozen by the active mask,
        draws discarded) but stay owned by their tenant, so its result
        shape is unchanged and its surviving chains are untouched
        bitwise. The group frees normally at eviction."""
        self._active_np[np.asarray(lanes, int)] = False
        self._dirty = True

    def poison_lanes(self, lanes: np.ndarray) -> None:
        """Force NaN into the given lanes' parameter state — the
        deterministic ``lane_nan`` fault-injection arm (serve/faults).
        The in-kernel telemetry's sticky diverged flag picks it up on
        the next quantum exactly as a real numerical divergence."""
        lanes = np.asarray(lanes, int)
        if self.scatter and not self._host_valid:
            x = np.asarray(self._state_np.x)  # shape/dtype ruler only
            self._scatter_state(lanes, {"x": np.full(
                (len(lanes),) + x.shape[1:], np.nan, x.dtype)})
            return
        self._pull_state()
        np.asarray(self._state_np.x)[lanes] = np.nan

    def reinit_lanes(self, lanes: np.ndarray, fresh: ChainState,
                     fresh_idx: np.ndarray) -> None:
        """Replace diverged lanes' state with ``fresh[fresh_idx]``
        chains (a prior re-draw from the tenant's backend — the solo
        ``reinit_diverged`` recovery path) and re-activate them.
        Healthy lanes stay bitwise untouched, and the re-drawn lanes
        KEEP their adapted MH jump scales / covariance factors —
        exactly ``backends.jax_backend.merge_reinit``'s contract (a
        zeroed scale would run un-adapted forever after)."""
        lanes = np.asarray(lanes, int)
        if self.scatter and not self._host_valid:
            delta = {}
            for f in type(self._state_np)._fields:
                if f in ("mh_log_scale", "mh_cov_chol"):
                    continue  # adapted scales survive (solo pin)
                if np.asarray(getattr(self._state_np, f)).ndim == 0:
                    continue
                # fancy indexing copies: the delta is private
                delta[f] = np.asarray(getattr(fresh, f))[fresh_idx]
            self._scatter_state(lanes, delta)
        else:
            self._pull_state()
            for f in type(self._state_np)._fields:
                if f in ("mh_log_scale", "mh_cov_chol"):
                    continue  # adapted scales survive re-init (solo pin)
                buf = np.asarray(getattr(self._state_np, f))
                if buf.ndim == 0:
                    continue
                buf[lanes] = np.asarray(getattr(fresh, f))[fresh_idx]
        self._active_np[lanes] = True
        self._dirty = True

    def tenant_state(self, slot: TenantSlot) -> ChainState:
        """The tenant's current chain state (host arrays) — the
        checkpoint payload for the per-tenant spool. Under the scatter
        gate this gathers ONLY the owning tenant's lane rows from the
        device state (a narrow fixed-shape jitted gather; the mirror
        stays un-materialized and ``_host_valid`` stays False), so a
        mid-run checkpoint no longer forces — or pays for — a
        full-pool ``device_get``. Values are bitwise the mirror slice:
        both are pure copies of the same device rows."""
        if self.scatter and not self._host_valid:
            lanes = jnp.asarray(np.array(slot.chain_lanes, np.int32,
                                         copy=True))
            rows = self._gather_fn(self._state_dev, lanes)
            return jax.tree.map(np.array, rows)
        self._pull_state()
        return jax.tree.map(lambda a: a[slot.chain_lanes],
                            self._state_np)

    def tenant_state_from(self, snap, slot: TenantSlot) -> ChainState:
        """One tenant's slice of a state ``snapshot`` returned by
        :meth:`dispatch_quantum` — the deferred-drain checkpoint
        payload (the snapshot was device-copied BEFORE the next
        dispatch could donate the underlying buffers)."""
        return jax.tree.map(lambda a: np.asarray(a)[slot.chain_lanes],
                            snap)

    # ------------------------------------------------------------------
    # the quantum
    # ------------------------------------------------------------------

    def dispatch_quantum(self, snapshot: bool = False):
        """Dispatch one quantum WITHOUT materializing anything: uploads
        dirty operand buffers, calls the ONE compiled program (state
        donated under ``GST_DONATE_CHUNK``) and keeps the returned
        state device-resident. Returns ``(records, telemetry, snap)``
        device handles for a deferred drain; ``records[i]`` is
        ``(nlanes, rows, ...)`` in wire dtypes. With ``snapshot=True``
        the post-quantum state is additionally device-copied before any
        LATER dispatch can donate its buffers — the flush-before-
        checkpoint-reuse ordering the spool path requires (PR 3's
        ``snapshot_fn`` discipline); ``snap`` is None otherwise."""
        # every upload below hands jax a SYNCHRONOUS private numpy
        # copy (np.array under our control, completed before the call
        # returns). jax's own host-to-device copy can be deferred —
        # and the canonical lane buffers keep mutating at boundaries
        # while a quantum is in flight (admission slice-assigns, the
        # offsets increment below, eviction's mask flip), so a lazy
        # (or zero-copy) device view of a live buffer hands the
        # in-flight program torn operands. Measured failure mode: a
        # quantum consuming PARTIALLY-INCREMENTED offsets draws the
        # NEXT quantum's philox streams for some lanes — caught by the
        # pipelined-vs-serial bitwise pins. The serial loop never saw
        # this only because its blocking state pull serialized every
        # write behind the compute.
        def up(a, dtype=None):
            return jnp.asarray(np.array(a, dtype=dtype, copy=True))

        if self._dirty:
            t_up0 = _time.monotonic()
            self._mas_dev = jax.tree.map(
                lambda a: (up(a, np.dtype(self.dtype))
                           if np.issubdtype(np.asarray(a).dtype,
                                            np.floating)
                           else up(a)),
                self._mas_np)
            fc = self._fc_np
            self._fc_dev = FusedConsts(*[
                None if a is None else up(a)
                for a in fc[:-1]
            ], gid=up(self._gid_np))
            self._dirty = False
            if self._spans is not None:
                self._spans.record("operand_upload", "dispatch", t_up0,
                                   _time.monotonic() - t_up0)
        if self.adaptive and self._bg_dirty:
            # clear-then-copy: a boundary write racing this copy is at
            # worst re-uploaded next quantum, never silently dropped
            self._bg_dirty = False
            self._bg_dev = up(self._bg_np)
        if self._host_valid:
            # the private copy additionally keeps donation honest: the
            # program may reuse its state input buffers, never
            # _state_np's
            state_in = jax.tree.map(up, self._state_np)
        else:
            state_in = self._state_dev
        t_call0 = _time.monotonic()
        if self.adaptive:
            sts, (recs, tl) = self._chunk(
                state_in, self._mas_dev, self._fc_dev,
                up(self._keys_np), up(self._offsets_np),
                up(self._active_np), self._bg_dev, length=self.quantum)
        else:
            sts, (recs, tl) = self._chunk(
                state_in, self._mas_dev, self._fc_dev,
                up(self._keys_np), up(self._offsets_np),
                up(self._active_np), length=self.quantum)
        if self._spans is not None:
            self._spans.record("chunk_call", "dispatch", t_call0,
                               _time.monotonic() - t_call0)
        self._state_dev = sts
        self._host_valid = False
        self._offsets_np[self._active_np] += self.quantum
        snap = jax.tree.map(jnp.copy, sts) if snapshot else None
        return recs, tl, snap

    def run_quantum(self):
        """The serial form of :meth:`dispatch_quantum`: advance every
        lane by ``quantum`` sweeps and pull the state back to host
        before returning — the pre-pipelining contract (the bitwise
        reference path of the pipelined executor's drain-ordering
        pins). Returns ``(records, telemetry)``."""
        recs, tl, _ = self.dispatch_quantum()
        self._pull_state()
        return recs, tl

    # ------------------------------------------------------------------
    # record plumbing
    # ------------------------------------------------------------------

    def materialize(self, recs) -> list:
        """Undo the wire casts for a quantum's records: returns host
        float arrays, one per record field, each ``(nlanes, rows, ...)``
        (the single-model backend's ``_materialize`` with the pool's
        padded TOA count)."""
        host = jax.device_get(recs)
        return self.template._materialize(host, n_last=self.n_pool)

    def tenant_records(self, host: list, slot: TenantSlot) -> dict:
        """One tenant's slice of a materialized quantum:
        ``{field: (rows, nchains, ...)}`` with per-TOA fields trimmed
        back to the tenant's real TOA count."""
        out = {}
        for f, arr in zip(self.template._record_fields, host):
            a = np.swapaxes(arr[slot.chain_lanes], 0, 1)
            if (slot.n_real != self.n_pool
                    and f in ("z", "alpha", "pout")):
                a = a[..., :slot.n_real]
            out[f] = a
        return out

    # -- deferred (wire-dtype) record plumbing --------------------------
    # The per-quantum drain used to materialize ALL nlanes to float32
    # and then fancy-index-copy each tenant's lanes — ~3x the record
    # bytes in host memory traffic, every quantum, on the serving hot
    # path. In-memory tenants now accumulate their lanes' NARROW wire
    # slices per quantum and materialize ONCE at finalize; only
    # spool/on_chunk consumers (whose contract is materialized
    # records) pay the per-quantum cast, and only for THEIR lanes.

    def wire_host(self, recs) -> list:
        """A quantum's records pulled to host in WIRE dtypes (no
        casts), one array per field, each ``(nlanes, rows, ...)``."""
        return list(jax.device_get(recs))

    def tenant_wire(self, wire: list, slot: TenantSlot) -> dict:
        """One tenant's lanes sliced out of a wire-dtype quantum:
        ``{field: (nchains, rows, ...)}`` COPIES (the backing quantum
        buffers are released after the drain)."""
        lanes = slot.chain_lanes
        lo, hi = int(lanes[0]), int(lanes[-1]) + 1
        contig = hi - lo == len(lanes)
        out = {}
        for f, arr in zip(self.template._record_fields, wire):
            a = arr[lo:hi] if contig else arr[lanes]
            out[f] = np.array(a)
        return out

    def materialize_tenant(self, cols: dict, n_real: int) -> dict:
        """Materialize a tenant's accumulated wire chunks: undo the
        transport casts, reorder to the record convention
        ``{field: (rows, nchains, ...)}`` and trim per-TOA fields back
        to the tenant's real TOA count. Applying the identical casts
        to a lane SLICE (here) or the full lane axis (materialize) is
        elementwise-identical, so the deferred path is bitwise the
        eager one."""
        fields = self.template._record_fields
        host = self.template._materialize([cols[f] for f in fields],
                                          n_last=self.n_pool)
        out = {}
        for f, arr in zip(fields, host):
            a = np.swapaxes(arr, 0, 1)
            if n_real != self.n_pool and f in ("z", "alpha", "pout"):
                a = a[..., :n_real]
            out[f] = a
        return out

    def tenant_quantum_records(self, wire: list,
                               slot: TenantSlot) -> dict:
        """One tenant's MATERIALIZED records for one quantum (the
        spool / on_chunk payload): the wire slice cast on demand."""
        return self.materialize_tenant(self.tenant_wire(wire, slot),
                                       slot.n_real)

    def tenant_wire_device(self, recs, slot: TenantSlot) -> dict:
        """Device-side compaction-gather twin of :meth:`wire_host` +
        :meth:`tenant_wire`: the tenant's lanes are gathered into a
        compact ``(nchains, rows, ...)`` buffer ON DEVICE and only
        those bytes come to host — the accelerator drain arm (over
        PCIe the full-lane ``wire_host`` pull is nlanes/nchains times
        the traffic; on CPU the two are within noise, which is what
        serve_bench's wire A/B records). Values are bitwise the
        host-slice path: a gather is a pure copy of the same rows."""
        lanes = jnp.asarray(np.array(slot.chain_lanes, np.int32,
                                     copy=True))
        out = {}
        for f, arr in zip(self.template._record_fields, recs):
            out[f] = np.asarray(jax.device_get(arr[lanes]))
        return out

    # ------------------------------------------------------------------
    # probe / stats surface (serve_top, fleet_status, serve_bench)
    # ------------------------------------------------------------------

    def admission_stats(self) -> dict:
        """Admission data-plane counters for the serve_bench
        ``admission`` block: which write path the pool resolved and
        the operand bytes each admit moved (scatter: delta + lane
        index; bounce: delta, plus the full mirror down AND back up
        when the canon was device-resident)."""
        n = len(self._admit_bytes)
        return {
            "scatter": bool(self.scatter),
            "admits": n,
            "bytes_per_admit": (float(np.mean(self._admit_bytes))
                                if n else None),
            "bytes_total": int(np.sum(self._admit_bytes)) if n else 0,
        }

    def backend_info(self) -> dict:
        """The pool's resolved execution backend for status/fleet rows:
        the jax platform this pool's one compiled program runs on plus
        the native-FFI probe verdict (native/ffi.py ``status()`` — the
        probe-recorded reason when kernels degraded)."""
        from gibbs_student_t_tpu.native import ffi as nffi

        return {"platform": str(jax.default_backend()),
                "native": nffi.status(),
                "scatter": bool(self.scatter)}


def _assign(buf: np.ndarray, lanes: np.ndarray, val: np.ndarray):
    """Slice-assign ``val`` (broadcast over the lane axis when it has
    no leading lane dimension) into ``buf[lanes]``; non-array pytree
    leaves (static metadata) pass through untouched."""
    buf = np.asarray(buf)
    if buf.ndim == 0:
        return buf
    if val.shape == buf.shape[1:]:
        buf[lanes] = val[None]
    else:
        buf[lanes] = val
    return buf
