"""Subprocess pool worker: one ChainServer behind the RPC + HTTP wire.

The fleet router (serve/router.py) shards tenants across N pools; the
"per-host subprocesses first" substrate is this module — ``python -m
gibbs_student_t_tpu.serve.pool_main --dir POOLDIR`` builds a
:class:`~gibbs_student_t_tpu.serve.server.ChainServer` from the pool
directory's pickled spec, mounts the mutating RPC edge
(serve/rpc.py) and the read-only HTTP endpoints (obs/http.py, via
``http_port=0``), journals to ``POOLDIR/manifest`` (the crash-recovery
manifest the router's failover contract rides), and drives quanta on
the main thread until a ``shutdown`` RPC or a signal.

Startup handshake: once everything is mounted the worker atomically
writes ``POOLDIR/ready.json`` — ``{pid, rpc_port, http_port, obs_dir,
recovered, lost}`` — which the spawner polls for. ``--recover`` boots
through :meth:`ChainServer.recover` instead of the spec: outstanding
spooled tenants resume from their last checkpoint (bitwise the
uninterrupted run — the PR 12 contract, now at fleet scope) and
``ready.json.recovered`` maps each logical job key (request name, else
spool_dir) to its new tenant id so the router can re-point routed
handles at the resurrected pool.

Chaos: ``--faults`` arms a JSON list of serve/faults.py FaultSpec
dicts in THIS process (fault state is process-local); the worker fires
the ``pool_kill`` point at every quantum boundary, so
``{"point": "pool_kill", "after": N, "action": "kill"}`` dies at a
deterministic quantum — the dead-pool arm of the fleet chaos tier.

The pool spec (``POOLDIR/spec.pkl``, written by the router's
spawn path) is ``{"template_ma", "config", "kwargs"}`` — the
ChainServer constructor arguments minus the wiring this module owns
(manifest/http/obs directories).
"""

from __future__ import annotations

import argparse
import json
import os
import pickle
import sys
import time


READY_NAME = "ready.json"
SPEC_NAME = "spec.pkl"


def _write_ready(pool_dir: str, doc: dict) -> None:
    tmp = os.path.join(pool_dir, READY_NAME + ".tmp")
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, os.path.join(pool_dir, READY_NAME))


def write_spec(pool_dir: str, template_ma, config, kwargs: dict) -> None:
    """The spawner's half of the handshake (router-side import is
    cheap: no jax needed to pickle a spec)."""
    os.makedirs(pool_dir, exist_ok=True)
    tmp = os.path.join(pool_dir, SPEC_NAME + ".tmp")
    with open(tmp, "wb") as fh:
        pickle.dump({"template_ma": template_ma, "config": config,
                     "kwargs": dict(kwargs)}, fh)
    os.replace(tmp, os.path.join(pool_dir, SPEC_NAME))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", required=True,
                    help="pool directory (spec.pkl in; ready.json, "
                         "manifest/, obs/ out)")
    ap.add_argument("--recover", action="store_true",
                    help="boot via ChainServer.recover() from the pool "
                         "directory's manifest instead of the spec")
    ap.add_argument("--faults", default=None,
                    help="JSON list of FaultSpec dicts to arm in this "
                         "process (the fleet chaos tier)")
    args = ap.parse_args(argv)
    pool_dir = os.path.abspath(args.dir)
    os.makedirs(pool_dir, exist_ok=True)
    t_boot = time.monotonic()

    # arm BOTH cold-start caches before anything traces: the per-host
    # AOT compile cache (a respawned/warm worker loads the ~5.5 s
    # chunk program instead of recompiling it) and the gates cache
    # beside it (probe outcomes + first-trace autotune decisions — a
    # recovered pool re-derives nothing; docs/PERFORMANCE.md "Cold
    # starts")
    from gibbs_student_t_tpu.ops import registry as _registry

    cache_info = _registry.enable_persistent_cache()

    from gibbs_student_t_tpu.serve import faults as _faults

    if args.faults:
        specs = json.loads(args.faults)
        _faults.install(*[_faults.FaultSpec(**d) for d in specs])

    from gibbs_student_t_tpu.serve.rpc import RpcServer
    from gibbs_student_t_tpu.serve.server import ChainServer

    manifest_dir = os.path.join(pool_dir, "manifest")
    obs_dir = os.path.join(pool_dir, "obs")
    recovered_map, lost = {}, []
    t_build = time.monotonic()
    if args.recover:
        srv, handles = ChainServer.recover(
            manifest_dir, http_port=0, obs_dir=obs_dir)
        recovered_map = {str(k): h.tenant_id
                         for k, h in handles.items()}
        lost = [r.get("name") or r.get("spool_dir") or r.get("tenant")
                for r in srv.lost_tenants]
    else:
        with open(os.path.join(pool_dir, SPEC_NAME), "rb") as fh:
            spec = pickle.load(fh)
        srv = ChainServer(spec["template_ma"], spec["config"],
                          manifest_dir=manifest_dir, http_port=0,
                          obs_dir=obs_dir, **spec["kwargs"])
    t_ready = time.monotonic()

    def on_shutdown():
        srv._stop.set()   # run(idle_exit=False) returns at the boundary

    rpc = RpcServer(srv, on_shutdown=on_shutdown)
    # persist what this boot derived (probes, compile walls, linalg
    # impl choices) so the NEXT spawn/respawn/recover is warm; written
    # before ready so the spawner's handshake sees a complete cache
    _registry.save_gate_cache()
    ready_doc = ({
        "pid": os.getpid(),
        "rpc_port": rpc.port,
        "http_port": (srv.http.port if srv.http is not None else None),
        "obs_dir": obs_dir,
        "manifest_dir": manifest_dir,
        "recovered": recovered_map,
        "lost": lost,
        # the cold-start evidence block the fleet bench / perf_report
        # gates read: wall breakdown + the registry's fresh-vs-cached
        # decision counters (zero fresh on a warm boot)
        "coldstart": {
            "recover": bool(args.recover),
            "boot_s": round(t_build - t_boot, 3),
            "build_s": round(t_ready - t_build, 3),
            "cache": cache_info,
            "registry": _registry.stats(),
        },
    })
    _write_ready(pool_dir, ready_doc)

    seen = {"q": 0}

    def on_quantum(server):
        # the dead-pool injection point: fires once per COMPLETED
        # quantum (the driver hook also ticks on idle polls, which
        # must not advance a fault spec's deterministic count);
        # action="kill" dies here, exactly like a node loss mid-serving
        q = server.quanta
        while seen["q"] < q:
            seen["q"] += 1
            _faults.fire("pool_kill")
        if seen["q"] > 0 and "registry_first_dispatch" not in \
                ready_doc["coldstart"]:
            # the first dispatched quantum just completed: the chunk
            # program's compile (AOT-cached or fresh) and its
            # trace-time dispatch decisions are now in the registry —
            # refresh the handshake file with the post-dispatch
            # counters (what the coldstart bench/gates read) and
            # persist the autotune store so even an impolitely killed
            # worker leaves a warm cache behind
            ready_doc["coldstart"]["registry_first_dispatch"] = \
                _registry.stats()
            _registry.save_gate_cache()
            _write_ready(pool_dir, ready_doc)

    # drive quanta on the main thread until retired over the wire; the
    # RPC submit path feeds the admission queue from its own threads
    srv.run(idle_exit=False, on_quantum=on_quantum)
    rpc.close()
    srv.close()
    # refresh the persisted autotune store with anything the serving
    # epoch added (new program signatures from admitted tenants)
    _registry.save_gate_cache()
    return 0


if __name__ == "__main__":
    sys.exit(main())
