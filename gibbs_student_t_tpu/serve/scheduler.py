"""Tenant requests, handles, and the bounded admission queue — the
serve subsystem's policy layer (docs/SERVING.md "Scheduling &
overload").

The scheduler side is deliberately host-only and thread-safe-but-
simple: a bounded queue with block/reject backpressure and pluggable
ordering. The default order is FIFO with first-fit admission (the
server scans past a head job that does not currently fit so a small
job can backfill free groups — classic continuous-batching behavior).
A server running the ``priority`` policy installs
:func:`schedule_score` as the queue's ``score``: pops become
best-score-first over ``(effective priority, deadline slack, arrival
seq)`` — which degenerates bitwise to the historical FIFO/first-fit
order when every request carries the defaults (equal priority, no
deadline → the arrival-seq tiebreak decides). Per-tenant handles
stream chunk callbacks and deliver the final :class:`ChainResult`.

Overload semantics: a bounded queue under the ``reject`` policy sheds
with :class:`RetryAfter` (a structured ``QueueFull`` carrying
``retry_after_s`` + ``queue_depth``), and a deadline-armed tenant
preempted past its deadline resolves with :class:`DeadlineExceeded`
(a structured ``TenantError``) — a shed or expired job's ``result()``
always raises promptly instead of hanging.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from gibbs_student_t_tpu.models.pta import ModelArrays


class QueueFull(RuntimeError):
    """Raised by ``submit`` under the ``reject`` backpressure policy
    when the admission queue is at capacity."""


class RetryAfter(QueueFull):
    """Structured overload shed (docs/SERVING.md "Scheduling &
    overload"): the queue (or the fleet router) is at capacity, the
    job was NOT accepted, and the caller should retry after
    ``retry_after_s`` seconds. Subclasses :class:`QueueFull` so every
    existing reject-policy handler keeps working; the extra fields
    make the signal actionable instead of a bare string:

    - ``retry_after_s``: the shedder's estimate of when capacity
      frees (from the live admission-latency percentiles when it has
      them, a fixed floor otherwise); None when it has no estimate.
    - ``queue_depth``: queued + staged jobs at the shed point (the
      fleet router reports the MINIMUM across live pools — the best
      door that still refused).
    - ``tier``: the rejected request's priority class.
    - ``where``: ``"server"`` (pool admission queue) or ``"router"``
      (fleet-wide shed).
    """

    def __init__(self, msg: str, retry_after_s=None, queue_depth=None,
                 tier=None, where: str = "server"):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.queue_depth = queue_depth
        self.tier = tier
        self.where = where


class TenantError(RuntimeError):
    """A tenant-scoped serving failure (docs/SERVING.md "Failure
    semantics"): raised by ``TenantHandle.result()`` when a fault was
    contained to this tenant — its lanes froze and released at a
    quantum boundary while every co-resident tenant kept serving.

    ``cause`` is the original exception (also chained as
    ``__cause__``); ``partial`` the :class:`ChainResult` built from
    the records drained before the fault — a bitwise prefix of the
    fault-free run (the cancel contract), or None when nothing was
    drained. ``where`` names the failing stage (``drain``,
    ``callback``, ``spool``, ``divergence``, ``worker``, ``close``).
    """

    def __init__(self, tenant_id: int, reason: str,
                 where: str = "drain", cause=None, partial=None):
        super().__init__(f"tenant {tenant_id} failed [{where}]: "
                         f"{reason}")
        self.tenant_id = tenant_id
        self.reason = reason
        self.where = where
        self.cause = cause
        self.partial = partial
        if cause is not None:
            self.__cause__ = cause


class DeadlineExceeded(TenantError):
    """A deadline-armed tenant whose budget can no longer be served
    in time: preempted (or re-scored at requeue) past
    ``deadline_sweep``, the server resolves the handle with this
    structured error instead of parking the continuation in a queue
    it can never usefully leave — ``result()`` raises promptly (the
    shed-job contract, satellite of round 20). ``partial`` carries
    the spooled prefix served before the deadline (a bitwise prefix
    of the uninterrupted run, the PR 15 cancel contract), so the
    caller keeps every sweep it paid for."""

    def __init__(self, tenant_id: int, deadline_sweep: int,
                 served_sweeps: int, partial=None):
        super().__init__(
            tenant_id,
            reason=(f"deadline at sweep {deadline_sweep} passed with "
                    f"{served_sweeps} sweep(s) served"),
            where="deadline", partial=partial)
        self.deadline_sweep = int(deadline_sweep)
        self.served_sweeps = int(served_sweeps)


#: Valid ``TenantRequest.on_divergence`` policies. ``none`` keeps the
#: historical behavior (diverged chains stream post-divergence noise,
#: flagged only by telemetry/health); the active policies need pool
#: telemetry and a supervised server (validated at submit).
DIVERGENCE_POLICIES = ("none", "fail", "quarantine", "reinit")

#: Valid ``TenantRequest.on_converged`` policies (ROADMAP item 4c).
#: ``none`` serves the full ``niter`` budget; ``evict`` frees the
#: tenant's lanes at the first quantum boundary after its streaming
#: monitor's armed targets hold (``converged_at``) — the cancel
#: machinery, so the result is the served prefix with status ``done``
#: — turning convergence speed directly into pool capacity (the freed
#: groups backfill from the queue at the same boundary). Requires a
#: monitor with at least one armed target (validated at submit).
CONVERGED_POLICIES = ("none", "evict")


@dataclass
class TenantRequest:
    """One job for the slot pool.

    ``niter`` must be a multiple of the pool quantum (validated at
    submit — the static chunk length is what makes admission
    recompile-free). ``spool_dir`` streams the tenant's chunks to a
    per-tenant spool directory with a rolling state checkpoint
    (utils/spool.py) instead of accumulating in memory; ``state`` +
    ``start_sweep`` resume a checkpointed tenant (utils/spool.py
    ``load_spool_state``) — the per-sweep fold-in keying makes the
    continuation identical to an unbroken run.

    ``on_divergence`` selects the tenant's lane-health policy when the
    in-kernel sticky diverged flags fold into per-lane health at a
    quantum boundary (supervised servers with telemetry only):
    ``none`` streams on (historical behavior), ``fail`` fails the
    tenant with a structured :class:`TenantError`, ``quarantine``
    freezes diverged lanes and continues on the survivors, ``reinit``
    re-draws diverged lanes from the prior (the solo
    ``reinit_diverged`` recovery path, serving-side).

    ``monitor`` (a :class:`~gibbs_student_t_tpu.serve.monitor.
    MonitorSpec`) arms streaming convergence monitoring: the drain
    worker folds each quantum's chain rows into an online ESS /
    split-R-hat view surfaced through :meth:`TenantHandle.progress`,
    with ``converged_at`` landing in the tenant's result stats and the
    server's SLO surface (docs/OBSERVABILITY.md "Live serving
    observability").
    """

    ma: ModelArrays
    niter: int
    nchains: int = 16
    seed: int = 0
    x0: Optional[np.ndarray] = None
    state: object = None
    start_sweep: int = 0
    spool_dir: Optional[str] = None
    #: wire-safe resume (round 18, the live-migration path): the
    #: SERVER loads ``state``/``start_sweep`` from ``spool_dir``'s
    #: rolling checkpoint at submit — a state pytree never rides the
    #: RPC submit frame (rpc.py rejects it by design). When
    #: ``start_sweep`` is also set, the loaded checkpoint must sit at
    #: exactly that sweep (the migration fencing cross-check) or the
    #: submit is rejected loudly.
    resume_spool: bool = False
    on_chunk: Optional[Callable] = None   # (handle, sweep_end, records)
    name: Optional[str] = None
    on_divergence: str = "none"
    monitor: object = None                # serve/monitor.MonitorSpec
    #: convergence-eviction policy (``none`` | ``evict``): with
    #: ``evict``, the tenant releases its lanes at the first boundary
    #: after the armed monitor targets hold instead of serving the
    #: full budget — sweeps the pool would spend past convergence
    #: become backfill capacity (ROADMAP 4c; docs/SERVING.md)
    on_converged: str = "none"
    #: variational warm start (ROADMAP 4b; serve/warm.py): a
    #: ``WarmStartSpec`` fits a moment-matched Gaussian mixture on a
    #: short staged pilot and inits the chains from it instead of the
    #: prior (burn-in is per-request latency in serving); a
    #: ``WarmStartFit`` (or its journaled JSON dict) replays a
    #: previous fit bitwise — the manifest-recovery path. ``None``
    #: keeps the cold prior init; ``GST_WARM_START`` gates the arm
    #: globally (0 degrades every request to cold, pinned).
    warm_start: object = None
    #: adaptive block scan (ROADMAP 4; serve/adapt.py,
    #: arXiv:1808.09047): an ``AdaptScanSpec`` thins this tenant's
    #: CONVERGED conditional blocks (per-block min-ESS from the
    #: streaming monitor) to a learned random-scan selection
    #: probability at drain boundaries — sweeps stop re-sampling
    #: blocks whose marginals already delivered their ESS. Requires a
    #: monitor with an ESS target (validated at submit).
    #: ``GST_ADAPT_SCAN`` gates the arm globally (``0`` disables every
    #: request AND removes the pool operand — bitwise pre-adaptive
    #: graph, pinned; ``1`` arms every eligible tenant with the
    #: default policy).
    adapt_scan: object = None
    #: fleet trace-context propagation (round 19): an opaque
    #: correlation id minted by the FleetRouter at submit and carried
    #: on the RPC submit frame. The server tags every span it records
    #: for this tenant with it, so router-side placement/failover/
    #: migration spans and pool-side staging/dispatch/drain spans
    #: stitch into one per-job trace (``FleetRouter.export_trace``).
    #: Purely observational — never touches chain math (PR 1 rule).
    trace_id: Optional[str] = None
    #: priority class (round 20, docs/SERVING.md "Scheduling &
    #: overload"): LOWER is more important — 0 interactive, 1
    #: standard (the default), 2+ batch. Any non-negative int. Under
    #: a ``scheduler="priority"`` server, ordering pops
    #: best-priority-first (with an aging boost bounding starvation)
    #: and a higher tier's arrival may losslessly preempt the
    #: lowest-tier SPOOLED running tenant (the checkpoint/
    #: ``resume_spool`` machinery — final chains bitwise identical to
    #: an uninterrupted run). Rides the RPC submit frame.
    priority: int = 1
    #: deadline, in sweeps from this request's ``start_sweep``
    #: (None = no deadline): arms slack-aware ordering —
    #: ``slack = sweeps_to_deadline − est_sweeps_to_target`` (the
    #: live monitor's estimate when armed, the remaining budget
    #: otherwise) — so the tightest job pops first within its tier.
    #: A deadline-armed tenant preempted past its deadline resolves
    #: with :class:`DeadlineExceeded` instead of requeueing.
    deadline_sweeps: Optional[int] = None


class TenantHandle:
    """Caller-facing view of a submitted job."""

    def __init__(self, tenant_id: int, request: TenantRequest):
        self.tenant_id = tenant_id
        self.request = request
        self.status = "queued"
        self.error: Optional[str] = None
        self.submitted_t = time.monotonic()
        self.admitted_t: Optional[float] = None
        self.first_result_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        self.sweeps_done = 0
        self.chunks_streamed = 0
        # streaming convergence monitor (serve/monitor.TenantMonitor),
        # attached at admission when the request armed one; the server
        # detaches it (with a warning event) if it ever raises
        self._monitor = None
        self._cols: Dict[str, List[np.ndarray]] = {}
        self._tele_stats: Dict[str, np.ndarray] = {}
        self._result = None
        self._builder = None
        self._build_lock = threading.Lock()
        self._done = threading.Event()
        # per-tenant health report (obs/health.py verdicts over the
        # accumulated telemetry + serving lane-health counters),
        # attached at finalize; None when the pool ran telemetry-off
        self.health: Optional[Dict] = None
        self._tenant_error: Optional[TenantError] = None
        # per-tenant cost accounting (round 14): each quantum's
        # dispatch wall time attributed across co-resident tenants by
        # active-lane share. Written by exactly one thread (the drain
        # worker / the serial driver); readers see GIL-atomic floats.
        self.cost_device_ms = 0.0
        self.cost_lane_quanta = 0
        # per-stage device ms (round 15: the in-kernel stage timers'
        # per-quantum deltas, attributed by the same active-lane
        # share). Empty when the pool runs timers-off.
        self.cost_stage_ms: Dict[str, float] = {}
        # recycling Gibbs bookkeeping (round 17; parallel/recycle.py):
        # partial-scan rows the drain tagged for this tenant (0 with
        # the gate off). Single-writer like the cost counters.
        self.recycled_rows = 0
        # warm-start summary ({kind, pilot_sweeps, pilot_ms, ...} /
        # {"degraded": reason} / None cold) — attached at staging
        self.warm: Optional[Dict] = None
        # adaptive-scan summary (round 18, serve/adapt.py): latest
        # per-block selection probabilities + drawn gates, written by
        # the drain worker at each boundary update; None when the
        # tenant runs the full-rate systematic scan
        self.adapt: Optional[Dict] = None
        # scheduling state (round 20): arrival sequence within the
        # admission queue (the FIFO tiebreak of schedule_score),
        # the aging anchor (survives a preemption requeue, unlike
        # submitted_t which restarts the continuation's admission SLO
        # leg), the ABSOLUTE deadline sweep (start_sweep +
        # deadline_sweeps at FIRST submit — continuations keep it),
        # and how many times this tenant was preempted
        self._queue_seq = -1
        self._age_t = self.submitted_t
        self._deadline_sweep: Optional[int] = None
        self.preemptions = 0

    # -- lifecycle (server side) ---------------------------------------

    def _stream(self, sweep_end: int, records: Dict[str, np.ndarray]):
        """Per-quantum bookkeeping + the streaming callback. Record
        STORAGE no longer happens here: in-memory tenants accumulate
        narrow wire-dtype lane slices (``_append_wire``, materialized
        once at finalize) instead of per-quantum float copies — the
        serving drain's biggest host cost."""
        self.sweeps_done = sweep_end - self.request.start_sweep
        self.chunks_streamed += 1
        if self.first_result_t is None:   # the SLO admit->first-result leg
            self.first_result_t = time.monotonic()
        if self.request.on_chunk is not None:
            from gibbs_student_t_tpu.serve import faults

            faults.fire("callback",
                        tenant=self.request.name
                        if self.request.name is not None
                        else self.tenant_id)
            self.request.on_chunk(self, sweep_end, records)

    def _append_wire(self, wire_cols: Dict[str, np.ndarray]):
        for f, a in wire_cols.items():
            self._cols.setdefault(f, []).append(a)

    def _finish(self, result):
        self._result = result
        self.finished_t = time.monotonic()
        self.status = "done"
        self._done.set()

    def _finish_lazy(self, builder):
        """Complete the tenant with a DEFERRED result builder: the
        sweeps are served and the wire-dtype records delivered, but
        the float materialization + concatenation happen on the first
        ``result()`` call, on the CALLER's thread — decode-on-consume,
        so result assembly never steals serving cycles from the
        drain worker."""
        self._builder = builder
        self.finished_t = time.monotonic()
        self.status = "done"
        self._done.set()

    def _fail(self, why: str):
        self.error = why
        self.finished_t = time.monotonic()
        self.status = "rejected"
        self._done.set()

    def _fail_shed(self, err: "RetryAfter"):
        """Complete the handle with an overload shed: the job was
        never admitted, and ``result()`` raises the same structured
        :class:`RetryAfter` the submit call does — a shed job can
        never hang a waiter (the dead-client-wedge class, submit
        side)."""
        self._tenant_error = err
        self.error = str(err)
        self.finished_t = time.monotonic()
        self.status = "rejected"
        self._done.set()

    def _fail_tenant(self, err: TenantError):
        """Complete the handle with a CONTAINED tenant failure: the
        tenant ran (unlike ``_fail``'s pre-admission rejection) and
        ``result()`` raises the structured :class:`TenantError`
        carrying the cause and the partial results drained before the
        fault."""
        self._tenant_error = err
        self.error = str(err)
        self.finished_t = time.monotonic()
        self.status = "failed"
        self._done.set()

    def _add_cost(self, device_ms: float, lane_quanta: int) -> None:
        """Fold one quantum's attributed share (single-writer: the
        drain worker, or the serial driver's one thread)."""
        self.cost_device_ms += device_ms
        self.cost_lane_quanta += int(lane_quanta)

    def _add_stage_cost(self, stage_ms: Dict[str, float]) -> None:
        """Fold one quantum's per-stage device-time share (same
        single-writer discipline as :meth:`_add_cost`)."""
        for name, ms in stage_ms.items():
            self.cost_stage_ms[name] = \
                self.cost_stage_ms.get(name, 0.0) + ms

    # -- caller side ----------------------------------------------------

    def cost(self) -> Dict[str, object]:
        """The tenant's cost block (docs/OBSERVABILITY.md "The
        observability wire"): ``device_ms`` — this tenant's
        active-lane share of every quantum's dispatch wall time (the
        shares across co-resident tenants sum to the measured dispatch
        wall); ``lane_quanta`` — active chain-lanes × quanta consumed;
        ``ess_per_core_s`` — monitored min-ESS per attributed core
        second (None unmonitored / before the first evaluation): the
        throughput-per-compute economics ROADMAP item 4's eviction
        policy and item 1's router place by."""
        ess_min = None
        if self._monitor is not None:
            ess_min = self._monitor.snapshot().get("ess_min")
        core_s = self.cost_device_ms / 1e3
        c = {
            "device_ms": round(self.cost_device_ms, 3),
            "lane_quanta": int(self.cost_lane_quanta),
            "ess_per_core_s": (
                round(float(ess_min) / core_s, 3)
                if isinstance(ess_min, (int, float)) and core_s > 0
                else None),
        }
        if self.cost_stage_ms:
            # the deep-profiling split of device_ms (round 15): this
            # tenant's active-lane share of each in-kernel stage's
            # per-quantum device time
            c["stage_device_ms"] = {
                k: round(v, 3)
                for k, v in sorted(self.cost_stage_ms.items())}
        return c

    @property
    def admission_ms(self) -> Optional[float]:
        if self.admitted_t is None:
            return None
        return (self.admitted_t - self.submitted_t) * 1e3

    @property
    def first_result_ms(self) -> Optional[float]:
        """Admit -> first drained records latency (the SLO leg)."""
        if self.admitted_t is None or self.first_result_t is None:
            return None
        return (self.first_result_t - self.admitted_t) * 1e3

    @property
    def converged_at(self) -> Optional[int]:
        """Sweep index at which the armed convergence targets first
        held (streaming monitor), None while unconverged/unmonitored."""
        return (None if self._monitor is None
                else self._monitor.converged_at)

    def slack_sweeps(self) -> Optional[float]:
        """Deadline slack in sweeps (None when no deadline is armed):
        ``sweeps_to_deadline − est_sweeps_to_target``, the live
        monitor's estimate when it has one (its snapshot is a cheap
        dict copy), the remaining budget otherwise. Negative = the
        deadline is already unservable at the current rate."""
        if self._deadline_sweep is None:
            return None
        pos = self.request.start_sweep + self.sweeps_done
        to_deadline = self._deadline_sweep - pos
        est = None
        if self._monitor is not None:
            est = self._monitor.snapshot().get("est_sweeps_to_target")
        if not isinstance(est, (int, float)):
            est = self.request.niter - self.sweeps_done
        return float(to_deadline - est)

    def progress(self) -> Dict[str, object]:
        """Live per-tenant progress: scheduling state plus — when the
        request armed a :class:`~gibbs_student_t_tpu.serve.monitor.
        MonitorSpec` — the streaming convergence view (``rows``,
        per-param ``ess``/``rhat`` and their aggregates, ``ess_per_s``,
        ``est_sweeps_to_target``, ``converged_at``). Callable from any
        thread, before, during and after the run."""
        p: Dict[str, object] = {
            "tenant_id": self.tenant_id,
            "name": self.request.name,
            "status": self.status,
            "nchains": self.request.nchains,
            "sweeps_done": self.sweeps_done,
            "niter": self.request.niter,
        }
        if self._monitor is not None:
            p.update(self._monitor.snapshot())
        if self.request.trace_id is not None:
            p["trace_id"] = self.request.trace_id
        p["priority"] = int(getattr(self.request, "priority", 1))
        if self._deadline_sweep is not None:
            p["deadline_sweep"] = int(self._deadline_sweep)
            p["slack_sweeps"] = self.slack_sweeps()
        if self.preemptions:
            p["preemptions"] = int(self.preemptions)
        p["cost"] = self.cost()
        if self.recycled_rows:
            p["recycled_rows"] = int(self.recycled_rows)
        if self.warm is not None:
            p["warm"] = dict(self.warm)
        if self.adapt is not None:
            p["adapt"] = dict(self.adapt)
        return p

    @property
    def throughput_sweeps_per_s(self) -> Optional[float]:
        """Chain-sweeps per second over the tenant's residency."""
        if self.admitted_t is None or self.finished_t is None:
            return None
        dt = self.finished_t - self.admitted_t
        return (self.request.nchains * self.sweeps_done / dt
                if dt > 0 else None)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the job completes and return its
        :class:`ChainResult`; raises on rejection."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"tenant {self.tenant_id} not done (status "
                f"{self.status!r}); drive ChainServer.step()/run()")
        if self._tenant_error is not None:
            raise self._tenant_error
        if self.error is not None:
            raise RuntimeError(
                f"tenant {self.tenant_id} rejected: {self.error}")
        if self._result is None and self._builder is not None:
            with self._build_lock:
                if self._result is None:
                    self._result = self._builder()
                    self._builder = None
        return self._result


def schedule_score(handle: TenantHandle, now: Optional[float] = None,
                   age_boost_s: Optional[float] = None) -> tuple:
    """The priority scheduler's pop order — LOWER pops first:
    ``(effective_priority, deadline_slack, arrival_seq)``.

    - ``effective_priority``: the request's tier minus one boost per
      ``age_boost_s`` seconds waited (the starvation bound — a
      low-tier job left queued long enough outranks fresh high-tier
      arrivals; ``None``/0 disables aging).
    - ``deadline_slack``: :meth:`TenantHandle.slack_sweeps` (``+inf``
      without a deadline), so within a tier the tightest job pops
      first and deadline-armed jobs outrank open-ended ones.
    - ``arrival_seq``: the queue's insertion counter — with equal
      tiers and no deadlines the whole score degenerates to exactly
      the historical FIFO order (the stability pin).
    """
    req = handle.request
    pr = float(getattr(req, "priority", 1))
    if age_boost_s:
        t = now if now is not None else time.monotonic()
        waited = t - getattr(handle, "_age_t", handle.submitted_t)
        if waited > 0:
            pr -= int(waited / age_boost_s)
    slack = handle.slack_sweeps()
    return (pr, float("inf") if slack is None else slack,
            handle._queue_seq)


class AdmissionQueue:
    """Bounded queue with first-fit scanning and block/reject
    backpressure. ``score`` (None = historical FIFO) orders every pop
    best-score-first: the server's ``priority`` policy installs
    :func:`schedule_score` here, and because the score's final
    tiebreak is the insertion sequence, default requests (equal
    priority, no deadline) still pop in exact arrival order."""

    def __init__(self, maxsize: int = 64, policy: str = "block",
                 score=None):
        if policy not in ("block", "reject"):
            raise ValueError(
                f"backpressure policy must be 'block' or 'reject', "
                f"got {policy!r}")
        self.maxsize = maxsize
        self.policy = policy
        #: Optional ``handle -> orderable`` key; pops take the MINIMUM
        self.score = score
        self._q: List[TenantHandle] = []
        self._seq = 0
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)

    def depth_by_tier(self) -> Dict[int, int]:
        """Queued jobs per priority class (the per-tier queue-depth
        signal on ``/status`` and the fleet snapshot)."""
        with self._lock:
            out: Dict[int, int] = {}
            for h in self._q:
                tier = int(getattr(h.request, "priority", 1))
                out[tier] = out.get(tier, 0) + 1
            return out

    def put(self, handle: TenantHandle,
            timeout: Optional[float] = None) -> None:
        with self._not_full:
            if len(self._q) >= self.maxsize:
                if self.policy == "reject":
                    raise QueueFull(
                        f"admission queue at capacity ({self.maxsize})")
                if not self._not_full.wait_for(
                        lambda: len(self._q) < self.maxsize,
                        timeout=timeout):
                    raise QueueFull(
                        f"admission queue still full after {timeout}s")
            handle._queue_seq = self._seq
            self._seq += 1
            self._q.append(handle)

    def put_displaced(self, handle: TenantHandle) -> None:
        """Requeue a preempted tenant's continuation, bypassing the
        capacity check: displaced load was already admitted once —
        shedding it here would break the lossless-preemption contract
        — and bounding it by ``maxsize`` would let a full queue turn a
        preemption into data loss. The continuation still competes by
        score (it keeps its aging anchor, so it carries its waited
        time into the next pop)."""
        with self._not_full:
            handle._queue_seq = self._seq
            self._seq += 1
            self._q.append(handle)

    def _pop_best(self, candidates) -> Optional[TenantHandle]:
        """Pop the best-scored (or first, FIFO) of ``candidates`` —
        (index, handle) pairs into ``_q``. Caller holds the lock."""
        best = None
        if self.score is None:
            for i, h in candidates:
                best = (i, h)
                break
        else:
            best_key = None
            for i, h in candidates:
                key = self.score(h)
                if best_key is None or key < best_key:
                    best, best_key = (i, h), key
        if best is None:
            return None
        self._q.pop(best[0])
        self._not_full.notify()
        return best[1]

    def pop_first_fit(self, fits) -> Optional[TenantHandle]:
        """Remove and return the best-ordered queued job for which
        ``fits(handle)`` is true (first-fit backfill under FIFO,
        best-score-fit under a scored queue), else None."""
        with self._not_full:
            return self._pop_best(
                (i, h) for i, h in enumerate(self._q) if fits(h))

    def pop_next(self) -> Optional[TenantHandle]:
        """Non-blocking ordered pop — the pipelined executor's staging
        thread takes jobs in queue order (arrival under FIFO, score
        under ``priority``) and prepares them ahead of placement
        (first-fit happens later, over the PREPARED window, so queue
        order is the preparation order, not the admission order)."""
        with self._not_full:
            return self._pop_best(enumerate(self._q))

    def snapshot(self) -> List[TenantHandle]:
        """A read-only view of the queued handles in order — the pilot
        batcher peeks it to find co-pending warm-start requests whose
        pilots can ride the same staging wave (serve/server.py
        ``_warm_fit_for``); the handles stay queued."""
        with self._lock:
            return list(self._q)

    def remove(self, handle: TenantHandle) -> bool:
        """Drop a specific queued job (cancellation before admission).
        Returns False when it is no longer queued."""
        with self._not_full:
            for i, h in enumerate(self._q):
                if h is handle:
                    self._q.pop(i)
                    self._not_full.notify()
                    return True
            return False
