"""Continuous-batching chain serving: the 1024-lane sweep as a
multi-tenant slot pool.

The flagship AOT-compiled chunk program historically served exactly one
caller per process — all throughput beyond one tenant's needs was
wasted, and every new job paid a full cold compile (ROADMAP item 1).
This package turns the lane axis into a slot pool the way LLM inference
servers batch decode steps: a request queue admits independent jobs
(different datasets / priors / seeds / sweep counts) into free lane
groups mid-flight, evicts finished tenants, and streams per-tenant
posterior chunks + telemetry back incrementally.

The enabling refactor lives in backends/jax_backend.py
(``operand_mode``) and ops/linalg.py (the ``*_lanes`` dispatchers):
per-lane configuration that the single-model path bakes as trace-time
literals — dataset constants, prior hypers, fused-MH constant arrays,
philox chain keys, per-tenant sweep offsets, the active-lane mask —
becomes call-time operands of ONE compiled chunk program, so admitting
a tenant is a host-side buffer write, never a recompile. The native
FFI megastage and TNT Gram kernels accept the same operands through
their lanes variants under the tile-uniform group-id contract
(native/src/gst_kernels.h; admission is SIMD-tile-granular).

See docs/SERVING.md for the architecture and the
operand-vs-baked-constant table.
"""

from gibbs_student_t_tpu.serve.monitor import MonitorSpec, TenantMonitor
from gibbs_student_t_tpu.serve.pool import GROUP_LANES, SlotPool
from gibbs_student_t_tpu.serve.router import FleetRouter, spawn_fleet
from gibbs_student_t_tpu.serve.rpc import RemoteChainServer, RpcServer
from gibbs_student_t_tpu.serve.scheduler import (
    DeadlineExceeded,
    RetryAfter,
    TenantError,
    TenantHandle,
    TenantRequest,
)
from gibbs_student_t_tpu.serve.server import ChainServer
from gibbs_student_t_tpu.serve.warm import WarmStartFit, WarmStartSpec

__all__ = [
    "WarmStartSpec",
    "WarmStartFit",
    "GROUP_LANES",
    "SlotPool",
    "TenantRequest",
    "TenantHandle",
    "TenantError",
    "RetryAfter",
    "DeadlineExceeded",
    "ChainServer",
    "MonitorSpec",
    "TenantMonitor",
    "RpcServer",
    "RemoteChainServer",
    "FleetRouter",
    "spawn_fleet",
]
