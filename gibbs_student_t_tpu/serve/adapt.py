"""Adaptive block scans for the slot pool (arXiv:1808.09047).

A systematic Gibbs scan re-samples every conditional block every
sweep, but in a served pool the streaming monitor KNOWS which blocks'
marginals have already delivered their requested effective sample
size: continuing to spend full-rate sweeps on a converged white-noise
block buys statistics nobody asked for, on lanes whose wall time is
the pool's capacity currency. The adaptive scan thins a converged
block to a LEARNED selection probability instead — the random-scan
form of the hybrid scans in arXiv:1808.09047 — while unconverged
blocks keep full rate, and a floor probability guarantees no block
ever fully starves (the chain must remain irreducible: every
conditional keeps a positive selection probability, so the sampler
stays a valid random-scan Gibbs composition targeting the same
posterior).

Plumbing: ``backends/jax_backend._sweep`` takes the per-lane
``(NBLOCKS,)`` 0/1 enable vector as a traced operand (``block_gates``)
and gates each block's draw branchlessly — computed and discarded,
key schedule untouched — the exact mechanism the pool's active mask
already uses. The pool carries the vector in a host-authoritative
lane buffer (``SlotPool.set_block_gates``: a numpy slice write + one
operand upload, never a recompile), and the server redraws each
monitored tenant's gates at drain boundaries from a deterministic
host RNG seeded by ``(seed, tenant, sweep)`` — replayable, like every
other serving decision. ``GST_ADAPT_SCAN=0`` builds the pool without
the operand: the pre-adaptive lowered graph, bitwise (pinned).

Only blocks with monitored x-columns (white/hyper) ever thin — they
are the blocks whose per-block ESS the monitor can actually measure;
the θ/z/α/ν conditionals and the coefficient draw stay full-rate
(the b-draw's gate additionally ties to hyper's; see
``jax_backend.BLOCK_B``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

#: Conditional-block order of the sweep — MUST mirror
#: ``backends.jax_backend.BLOCK_NAMES`` (kept numpy-light here so the
#: monitor/tools side never imports jax; pinned equal in
#: tests/test_adapt.py).
BLOCK_NAMES = ("white", "hyper", "b", "theta", "z", "alpha", "df")
NBLOCKS = len(BLOCK_NAMES)
BLOCK_WHITE, BLOCK_HYPER = 0, 1
#: blocks the policy may thin (monitored x-evidence exists)
THINNABLE = (BLOCK_WHITE, BLOCK_HYPER)


def adapt_scan_env() -> str:
    """Validated ``GST_ADAPT_SCAN`` (``auto`` when unset) — strict
    ``auto|1|0``. ``auto``/``1`` build the pool chunk with the
    block-gates operand (all-ones until a policy thins a tenant —
    value-identical to the gates-off chunk); ``auto`` honors each
    request's ``adapt_scan`` spec while ``1`` arms every monitored
    tenant with the default policy; ``0`` omits the operand — the
    pre-adaptive lowered graph and chains, bitwise (pinned)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_ADAPT_SCAN")


def adapt_scan_enabled() -> bool:
    """Pool-construction verdict: does the chunk carry the block-gates
    operand? (Resolved once per pool through the registry's
    probe→validate→record surface.)"""
    from gibbs_student_t_tpu.ops import registry

    enabled, _ = registry.mode3("GST_ADAPT_SCAN")
    return enabled


@dataclass
class AdaptScanSpec:
    """Per-tenant adaptive-scan policy (``TenantRequest.adapt_scan``).

    ``ess_target`` is the per-block convergence threshold (min ESS
    over the block's monitored columns); ``None`` inherits the
    tenant's armed ``MonitorSpec.ess_target`` — submit validates that
    at least one of the two is armed. ``floor`` is the minimum
    selection probability of a thinned block (irreducibility: no
    block ever fully starves). A converged block's selection
    probability is ``clip(ess_target / ess_block, floor, 1)`` — the
    more surplus ESS a block has delivered, the harder it thins."""

    ess_target: Optional[float] = None
    floor: float = 0.1

    def __post_init__(self):
        if not 0.0 < self.floor <= 1.0:
            raise ValueError(
                f"adapt_scan floor must be in (0, 1], got {self.floor}")
        if self.ess_target is not None and self.ess_target <= 0:
            raise ValueError(
                f"adapt_scan ess_target must be > 0, got "
                f"{self.ess_target}")


def resolve_adapt_scan(request_adapt, monitor_spec,
                       env: Optional[str] = None):
    """The tenant's effective adaptive-scan policy under the env gate:
    ``0`` disables every request (the bitwise-off arm), ``1`` arms
    every tenant whose monitor has an ESS target with the default
    spec, ``auto`` honors the per-request spec. Returns the
    :class:`AdaptScanSpec` or None (full-rate scan)."""
    env = env if env is not None else adapt_scan_env()
    if env == "0":
        return None
    spec = request_adapt
    if spec is None and env == "1":
        if monitor_spec is None or monitor_spec.ess_target is None:
            return None          # nothing to measure convergence by
        spec = AdaptScanSpec()
    if spec is None:
        return None
    if not isinstance(spec, AdaptScanSpec):
        raise ValueError(
            f"adapt_scan must be a serve.adapt.AdaptScanSpec or None, "
            f"got {type(spec).__name__}")
    return spec


def param_blocks(param_idx, white_indices,
                 hyper_indices) -> np.ndarray:
    """Map monitored parameter indices to their conditional block:
    ``BLOCK_WHITE`` / ``BLOCK_HYPER`` / ``-1`` (unmapped — a column
    no thinnable block owns). The mapping is pure model structure
    (``ModelArrays.white_indices`` / ``hyper_indices``), computed once
    at admission."""
    w = {int(i) for i in np.asarray(white_indices).ravel()}
    h = {int(i) for i in np.asarray(hyper_indices).ravel()}
    out = np.full(len(param_idx), -1, int)
    for j, p in enumerate(np.asarray(param_idx, int)):
        if int(p) in w:
            out[j] = BLOCK_WHITE
        elif int(p) in h:
            out[j] = BLOCK_HYPER
    return out


def selection_probs(block_ess: Dict[int, float], ess_target: float,
                    floor: float) -> np.ndarray:
    """Per-block selection probabilities from the monitor's per-block
    min-ESS verdicts: unconverged (or unmeasured) blocks stay at 1;
    a block whose ESS cleared the target thins to
    ``clip(target / ess, floor, 1)`` — the learned random-scan rate
    that would have been just enough."""
    probs = np.ones(NBLOCKS, np.float64)
    for bi in THINNABLE:
        ess = block_ess.get(bi)
        if ess is None or not np.isfinite(ess) or ess < ess_target:
            continue
        probs[bi] = float(np.clip(ess_target / ess, floor, 1.0))
    return probs


def draw_gates(probs: np.ndarray, seed: int, tenant_id: int,
               sweep: int) -> np.ndarray:
    """One ``(NBLOCKS,)`` 0/1 enable vector: independent Bernoulli
    draws from a counter-based host stream seeded by
    ``(seed, tenant, sweep)`` — deterministic, so a replayed request
    (or a recovered pool) makes the identical thinning decisions at
    the identical boundaries."""
    rng = np.random.default_rng(np.random.SeedSequence(
        [int(seed) & 0xFFFFFFFF, int(tenant_id) & 0xFFFFFFFF,
         int(sweep) & 0xFFFFFFFF, 0xADA7]))
    u = rng.random(NBLOCKS)
    probs = np.asarray(probs, np.float64)
    return (u < probs).astype(np.float32)
