"""PTA: the model seam, and ModelArrays: its frozen device-ready form.

The reference sampler consumes its entire model through six calls on an
``enterprise`` PTA object (SURVEY.md §1 L3->L4; reference gibbs.py:29,
154-161, 209-210, 235-236, 268-269, 297-304):

    pta.get_residuals()[0]         -> y      (n,)
    pta.get_basis(params)[0]       -> T      (n, m)
    pta.get_ndiag(params)[0]       -> Nvec0  (n,)
    pta.get_phiinv(params, logdet) -> phiinv (m,) [+ logdet]
    pta.params                     -> parameter objects (name/sample/logpdf)

:class:`PTA` reproduces that contract on our first-party signal layer; its
``freeze()`` produces :class:`ModelArrays` — plain arrays plus static
metadata — which both backends evaluate through the array-namespace-generic
functions below (``xp`` is ``numpy`` for the oracle backend and
``jax.numpy`` inside the jitted TPU kernel, so the math is written once).

Freezing applies a global time rescale (default: seconds -> microseconds).
The reference works in seconds, where white variances are ~1e-14 and
prior precisions span ~40 decades; in microseconds every quantity lands
within float32 range, which is what makes the TPU fast path viable
(SURVEY.md §7 "hard parts: float64").
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from gibbs_student_t_tpu.models.parameter import Constant, Parameter, lnprior_specs
from gibbs_student_t_tpu.models.signals import (
    ConstPhi,
    EcorrPhi,
    FYR,
    ImproperPhi,
    PowerlawPhi,
    SignalModel,
)

LN10 = float(np.log(10.0))


# ---------------------------------------------------------------------------
# Frozen phi blocks
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class PowerlawBlock:
    start: int
    stop: int
    freqs: np.ndarray        # (k,) per-column frequencies
    df: float
    idx_log10A: int          # index into x, or -1 if constant
    const_log10A: float
    idx_gamma: int
    const_gamma: float


@dataclasses.dataclass(frozen=True)
class EcorrBlock:
    start: int
    stop: int
    col_group: Tuple[int, ...]   # group per column
    idx: Tuple[int, ...]         # index into x or -1, per group
    const: np.ndarray            # (G,) log10 values for constants


@dataclasses.dataclass(frozen=True)
class ImproperBlock:
    start: int
    stop: int


@dataclasses.dataclass(frozen=True)
class ConstBlock:
    start: int
    stop: int
    phi: np.ndarray          # (k,) fixed scaled variances


# Pytree registrations: array-valued fields are leaves (so pulsar ensembles
# can be stacked/sharded and passed as jit operands); index structure and
# shapes are static metadata. ``hash`` on metadata is what jit keys
# compilation on, so everything meta must be hashable.
jax.tree_util.register_dataclass(
    PowerlawBlock,
    data_fields=["freqs", "df", "const_log10A", "const_gamma"],
    meta_fields=["start", "stop", "idx_log10A", "idx_gamma"],
)
jax.tree_util.register_dataclass(
    EcorrBlock, data_fields=["const"],
    meta_fields=["start", "stop", "col_group", "idx"],
)
jax.tree_util.register_dataclass(
    ImproperBlock, data_fields=[], meta_fields=["start", "stop"],
)
jax.tree_util.register_dataclass(
    ConstBlock, data_fields=["phi"], meta_fields=["start", "stop"],
)


# ---------------------------------------------------------------------------
# ModelArrays
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ModelArrays:
    """One pulsar's frozen model. All times scaled by ``time_scale``
    (variances by ``time_scale**2``); parameters keep their reference
    semantics (e.g. log10_equad is still log10 *seconds*)."""

    name: str
    y: np.ndarray                    # (n,) scaled residuals
    T: np.ndarray                    # (n, m) combined basis
    sigma2: np.ndarray               # (n,) scaled toaerr^2
    efac_masks: np.ndarray           # (Ge, n)
    efac_idx: Tuple[int, ...]        # per group, -1 => constant
    efac_const: np.ndarray           # (Ge,)
    equad_masks: np.ndarray          # (Gq, n)
    equad_idx: Tuple[int, ...]
    equad_const: np.ndarray          # log10 seconds
    phi_blocks: Tuple
    param_names: Tuple[str, ...]
    prior_specs: np.ndarray          # (p, 4) kind/a/b/init
    # (n,) bool: True for real TOA rows, False for suffix padding rows
    # added by parallel.ensemble.pad_model_arrays so heterogeneous
    # per-pulsar TOA counts can stack. None means every row is real.
    row_mask: Optional[np.ndarray] = None
    time_scale: float = 1e6

    @property
    def n(self) -> int:
        return self.y.shape[0]

    @property
    def m(self) -> int:
        return self.T.shape[1]

    @property
    def nparam(self) -> int:
        return len(self.param_names)

    # Substring-based index groups, the reference's coordinate-block
    # convention (reference gibbs.py:64-77).
    def _match(self, subs) -> np.ndarray:
        return np.array(
            [i for i, nm in enumerate(self.param_names)
             if any(s in nm for s in subs)],
            dtype=int,
        )

    @property
    def hyper_indices(self) -> np.ndarray:
        return self._match(("ecorr", "log10_A", "gamma"))

    @property
    def white_indices(self) -> np.ndarray:
        return self._match(("efac", "equad"))

    @property
    def specs_np(self) -> np.ndarray:
        return np.asarray(self.prior_specs)

    def x_init(self, rng: np.random.Generator | None = None) -> np.ndarray:
        """Draw x0 from the priors (reference run_sims.py:111)."""
        rng = rng or np.random.default_rng()
        specs = self.specs_np
        kind = specs[:, 0].astype(int)
        a, b = specs[:, 1], specs[:, 2]
        u = rng.uniform(size=self.nparam)
        x = np.where(kind == 1, a + b * rng.standard_normal(self.nparam),
                     a + (b - a) * u)
        return np.asarray(x, dtype=np.float64)


jax.tree_util.register_dataclass(
    ModelArrays,
    data_fields=["y", "T", "sigma2", "efac_masks", "efac_const",
                 "equad_masks", "equad_const", "phi_blocks", "prior_specs",
                 "row_mask"],
    meta_fields=["name", "efac_idx", "equad_idx", "param_names",
                 "time_scale"],
)


# --- xp-generic evaluation --------------------------------------------------

def _pval(x, idx, const, xp):
    """Parameter-or-constant lookup, batched-safe: value of x[idx] where
    idx >= 0 else const."""
    idx = xp.asarray(idx)
    safe = xp.clip(idx, 0, None)
    return xp.where(idx >= 0, x[safe], xp.asarray(const))


def ndiag(ma: ModelArrays, x, xp=np):
    """White-noise variances Nvec0(x) (scaled), the get_ndiag seam
    (reference gibbs.py:154,209,235,268,297): sum over selection groups of
    (efac*sigma)^2 plus 10^(2 log10_equad)."""
    efac = _pval(x, ma.efac_idx, ma.efac_const, xp)
    nv = (efac[:, None] ** 2 * ma.efac_masks * ma.sigma2[None, :]).sum(axis=0)
    if len(ma.equad_idx):
        equad = _pval(x, ma.equad_idx, ma.equad_const, xp)
        scaled = 10.0 ** (2.0 * equad) * ma.time_scale ** 2
        nv = nv + (scaled[:, None] * ma.equad_masks).sum(axis=0)
    return nv


def static_phi_columns(ma: ModelArrays) -> np.ndarray:
    """Boolean mask over the m basis columns whose prior precision does
    not depend on the sampled parameter vector: improper/constant blocks,
    plus powerlaw/ecorr blocks pinned to constants. These columns keep
    the same ``Sigma`` contribution across every hyper-MH proposal in a
    sweep, so the hyper block can Schur-eliminate them once per sweep
    and factor only the varying columns per evaluation
    (backends/jax_backend.py)."""
    mask = np.zeros(ma.m, dtype=bool)
    for blk in ma.phi_blocks:
        if isinstance(blk, (ImproperBlock, ConstBlock)):
            mask[blk.start:blk.stop] = True
        elif isinstance(blk, PowerlawBlock):
            if blk.idx_log10A < 0 and blk.idx_gamma < 0:
                mask[blk.start:blk.stop] = True
        elif isinstance(blk, EcorrBlock):
            if all(i < 0 for i in blk.idx):
                mask[blk.start:blk.stop] = True
    return mask


def phiinv_logdet(ma: ModelArrays, x, xp=np):
    """Prior precision diag phi^-1(x) (scaled) and logdet phi, the
    get_phiinv seam (reference gibbs.py:155,298). Improper (timing) blocks
    contribute exactly zero to both (see signals.ImproperPhi)."""
    pieces = []
    logdet = xp.asarray(0.0)
    s2 = ma.time_scale ** 2
    for blk in ma.phi_blocks:
        k = blk.stop - blk.start
        if isinstance(blk, ImproperBlock):
            pieces.append(xp.zeros(k))
        elif isinstance(blk, ConstBlock):
            phi = xp.asarray(blk.phi)
            pieces.append(1.0 / phi)
            logdet = logdet + xp.sum(xp.log(phi))
        elif isinstance(blk, PowerlawBlock):
            log10A = (x[blk.idx_log10A] if blk.idx_log10A >= 0
                      else blk.const_log10A)
            gamma = (x[blk.idx_gamma] if blk.idx_gamma >= 0
                     else blk.const_gamma)
            # log phi to keep the full dynamic range; exponentiate the
            # *negative* for phiinv.
            logphi = (2.0 * log10A * LN10
                      - np.log(12.0 * np.pi ** 2)
                      + (gamma - 3.0) * np.log(FYR)
                      - gamma * xp.log(xp.asarray(blk.freqs))
                      + xp.log(xp.asarray(blk.df)) + np.log(s2))
            pieces.append(xp.exp(-logphi))
            logdet = logdet + xp.sum(logphi)
        elif isinstance(blk, EcorrBlock):
            ec = _pval(x, blk.idx, blk.const, xp)
            logphi_g = 2.0 * ec * LN10 + np.log(s2)
            logphi = logphi_g[xp.asarray(blk.col_group)]
            pieces.append(xp.exp(-logphi))
            logdet = logdet + xp.sum(logphi)
        else:  # pragma: no cover
            raise TypeError(f"unknown phi block {type(blk)}")
    if not pieces:
        return xp.zeros(0), logdet
    return xp.concatenate(pieces), logdet


def lnprior(ma: ModelArrays, x, xp=np):
    """Sum of parameter log-priors, the get_lnprior seam
    (reference gibbs.py:337-339). Single xp-generic implementation shared
    by the oracle and the jitted kernel."""
    return xp.sum(lnprior_specs(xp.asarray(ma.prior_specs), x, xp))


# ---------------------------------------------------------------------------
# PTA
# ---------------------------------------------------------------------------

class PTA:
    """Aggregate of per-pulsar :class:`SignalModel`s exposing the reference
    sampler's six-call contract (reference run_sims.py:83)."""

    def __init__(self, models: Sequence[SignalModel], time_scale: float = 1e6):
        self.models = list(models)
        self.time_scale = time_scale
        self._frozen: List[ModelArrays] | None = None

    @property
    def params(self) -> List[Parameter]:
        seen: Dict[str, Parameter] = {}
        for model in self.models:
            for p in model.params:
                seen.setdefault(p.name, p)
        return [seen[k] for k in sorted(seen)]

    @property
    def param_names(self) -> List[str]:
        return [p.name for p in self.params]

    def map_params(self, xs) -> Dict[str, float]:
        return {p.name: x for p, x in zip(self.params, xs)}

    # -- freezing -----------------------------------------------------------

    def freeze(self) -> List[ModelArrays]:
        if self._frozen is None:
            order = {nm: i for i, nm in enumerate(self.param_names)}
            self._frozen = [
                _freeze_model(model, order, self.param_names, self.params,
                              self.time_scale)
                for model in self.models
            ]
        return self._frozen

    def frozen(self, idx: int = 0) -> ModelArrays:
        return self.freeze()[idx]

    # -- the six-call seam (host-side, reference units: seconds) ------------

    def _x(self, params: Dict[str, float]) -> np.ndarray:
        return np.array([params[nm] for nm in self.param_names])

    def get_residuals(self):
        return [m.psr.residuals for m in self.models]

    def get_basis(self, params=None):
        return [ma.T for ma in self.freeze()]

    def get_ndiag(self, params: Dict[str, float]):
        x = self._x(params)
        s2 = self.time_scale ** 2
        return [ndiag(ma, x, np) / s2 for ma in self.freeze()]

    def get_phiinv(self, params: Dict[str, float], logdet: bool = False):
        x = self._x(params)
        s2 = self.time_scale ** 2
        out = []
        for ma in self.freeze():
            pinv, ld = phiinv_logdet(ma, x, np)
            # unscale: phi_s2 = phi_scaled / s2 -> phiinv_s2 = phiinv * s2;
            # logdet in seconds^2 units drops the m*log(s2) offset, but only
            # over proper (finite-prior) columns.
            nfinite = sum(
                blk.stop - blk.start for blk in ma.phi_blocks
                if not isinstance(blk, ImproperBlock)
            )
            if logdet:
                out.append((pinv * s2, ld - nfinite * np.log(s2)))
            else:
                out.append(pinv * s2)
        return out

    def get_lnprior(self, xs) -> float:
        return float(sum(p.get_logpdf(x) for p, x in zip(self.params, xs)))


def _freeze_model(model: SignalModel, order: Dict[str, int],
                  all_names: List[str], all_params: List[Parameter],
                  time_scale: float) -> ModelArrays:
    psr = model.psr
    scale2 = time_scale ** 2

    def pidx(p) -> Tuple[int, float]:
        if isinstance(p, Constant):
            return -1, p.value
        return order[p.name], 0.0

    efac_masks, efac_idx, efac_const = [], [], []
    equad_masks, equad_idx, equad_const = [], [], []
    bases, blocks = [], []
    col = 0
    for inst in model.instances:
        for kind, mask, p in inst.white_specs():
            i, c = pidx(p)
            if kind == "efac":
                efac_masks.append(mask)
                efac_idx.append(i)
                efac_const.append(c)
            else:
                equad_masks.append(mask)
                equad_idx.append(i)
                equad_const.append(c)
        bb = inst.basis_block()
        if bb is None:
            continue
        basis, spec = bb
        k = basis.shape[1]
        start, stop = col, col + k
        col = stop
        bases.append(basis)
        if isinstance(spec, PowerlawPhi):
            ia, ca = pidx(spec.log10_A)
            ig, cg = pidx(spec.gamma)
            blocks.append(PowerlawBlock(start, stop, spec.freqs, spec.df,
                                        ia, ca, ig, cg))
        elif isinstance(spec, EcorrPhi):
            idx, const = [], []
            for p in spec.params:
                i, c = pidx(p)
                idx.append(i)
                const.append(c)
            blocks.append(EcorrBlock(start, stop,
                                     tuple(int(g) for g in spec.col_group),
                                     tuple(idx), np.asarray(const)))
        elif isinstance(spec, ImproperPhi):
            blocks.append(ImproperBlock(start, stop))
        elif isinstance(spec, ConstPhi):
            blocks.append(ConstBlock(start, stop, spec.phi * scale2))
        else:  # pragma: no cover
            raise TypeError(f"unknown phi spec {type(spec)}")

    # An efac-free model leaves raw radiometer noise out of N (enterprise
    # semantics); guard against that foot-gun by adding a unit-efac group.
    if not efac_masks:
        efac_masks.append(np.ones(psr.n))
        efac_idx.append(-1)
        efac_const.append(1.0)

    T = (np.concatenate(bases, axis=1) if bases
         else np.zeros((psr.n, 0)))
    specs = np.array([p.spec() for p in all_params], dtype=np.float64)
    if specs.size == 0:
        specs = np.zeros((0, 4))

    return ModelArrays(
        name=psr.name,
        y=psr.residuals * time_scale,
        T=T,
        sigma2=psr.toaerrs ** 2 * scale2,
        efac_masks=np.asarray(efac_masks),
        efac_idx=tuple(efac_idx),
        efac_const=np.asarray(efac_const),
        equad_masks=(np.asarray(equad_masks) if equad_masks
                     else np.zeros((0, psr.n))),
        equad_idx=tuple(equad_idx),
        equad_const=np.asarray(equad_const),
        phi_blocks=tuple(blocks),
        param_names=tuple(all_names),
        prior_specs=specs,
        time_scale=time_scale,
    )
