"""Sampling parameters: name / sample / logpdf objects.

The sampler-facing contract is the three-method seam the reference consumes
from ``enterprise.signals.parameter`` (reference gibbs.py:56-58,339;
run_sims.py:111): ``.name``, ``.sample()``, ``.get_logpdf(x)``. Families
cover the reference's usage (Uniform, Constant — reference run_sims.py:57-58,
67) plus Normal and LinearExp for model-building parity.

Each parameter also exposes a ``spec()`` 4-tuple ``(kind, a, b, init)`` so
the frozen model can evaluate all priors vectorized on device
(models/pta.py, backends/jax_backend.py).
"""

from __future__ import annotations

import numpy as np

# Integer prior kinds for the vectorized on-device lnprior.
KIND_UNIFORM = 0
KIND_NORMAL = 1
KIND_LINEAREXP = 2

_LN10 = float(np.log(10.0))


class Parameter:
    """Abstract sampled parameter."""

    def __init__(self, name: str = ""):
        self.name = name

    def with_name(self, name: str) -> "Parameter":
        clone = self.__class__.__new__(self.__class__)
        clone.__dict__.update(self.__dict__)
        clone.name = name
        return clone

    def sample(self, rng: np.random.Generator | None = None) -> float:
        raise NotImplementedError

    def get_logpdf(self, x: float) -> float:
        raise NotImplementedError

    def spec(self):
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{self.__class__.__name__}({self.name!r})"


class Uniform(Parameter):
    def __init__(self, pmin: float, pmax: float, name: str = ""):
        super().__init__(name)
        self.pmin = float(pmin)
        self.pmax = float(pmax)

    def sample(self, rng=None) -> float:
        rng = rng or np.random.default_rng()
        return float(rng.uniform(self.pmin, self.pmax))

    def get_logpdf(self, x: float) -> float:
        if self.pmin <= x <= self.pmax:
            return -float(np.log(self.pmax - self.pmin))
        return -np.inf

    def spec(self):
        return (KIND_UNIFORM, self.pmin, self.pmax,
                0.5 * (self.pmin + self.pmax))


class Normal(Parameter):
    def __init__(self, mu: float, sigma: float, name: str = ""):
        super().__init__(name)
        self.mu = float(mu)
        self.sigma = float(sigma)

    def sample(self, rng=None) -> float:
        rng = rng or np.random.default_rng()
        return float(rng.normal(self.mu, self.sigma))

    def get_logpdf(self, x: float) -> float:
        z = (x - self.mu) / self.sigma
        return float(-0.5 * z * z - np.log(self.sigma)
                     - 0.5 * np.log(2 * np.pi))

    def spec(self):
        return (KIND_NORMAL, self.mu, self.sigma, self.mu)


class LinearExp(Parameter):
    """Prior uniform in 10**x over [pmin, pmax] (enterprise's LinearExp)."""

    def __init__(self, pmin: float, pmax: float, name: str = ""):
        super().__init__(name)
        self.pmin = float(pmin)
        self.pmax = float(pmax)

    def sample(self, rng=None) -> float:
        rng = rng or np.random.default_rng()
        u = rng.uniform(10 ** self.pmin, 10 ** self.pmax)
        return float(np.log10(u))

    def get_logpdf(self, x: float) -> float:
        if self.pmin <= x <= self.pmax:
            return float(x * _LN10
                         + np.log(_LN10 / (10 ** self.pmax - 10 ** self.pmin)))
        return -np.inf

    def spec(self):
        return (KIND_LINEAREXP, self.pmin, self.pmax,
                0.5 * (self.pmin + self.pmax))


class Constant:
    """Fixed model value; not part of the sampled vector (mirrors
    ``enterprise.signals.parameter.Constant``, reference run_sims.py:57)."""

    def __init__(self, value: float, name: str = ""):
        self.value = float(value)
        self.name = name

    def __repr__(self) -> str:
        return f"Constant({self.value})"


def lnprior_specs(specs, x, xp=np):
    """Vectorized lnprior over a spec table (kind, a, b, init), written
    once for both backends: ``xp`` is ``numpy`` on the host oracle path and
    ``jax.numpy`` inside the jitted kernel. Returns per-parameter logpdfs;
    callers sum."""
    kind = specs[:, 0].astype(int)
    a, b = specs[:, 1], specs[:, 2]
    out = xp.full(x.shape, -xp.inf)
    inb = (x >= a) & (x <= b)
    u = kind == KIND_UNIFORM
    out = xp.where(u & inb, -xp.log(xp.where(u, b - a, 1.0)), out)
    nrm = kind == KIND_NORMAL
    z = (x - a) / xp.where(nrm, b, 1.0)
    out = xp.where(nrm, -0.5 * z * z - xp.log(xp.where(nrm, b, 1.0))
                   - 0.5 * np.log(2 * np.pi), out)
    lexp = kind == KIND_LINEAREXP
    denom = xp.where(lexp, 10.0 ** b - 10.0 ** a, 1.0)
    out = xp.where(lexp & inb, x * _LN10 + xp.log(_LN10 / denom), out)
    return out
