"""Model layer: parameters, signal algebra, the PTA seam, frozen arrays.

First-party replacement for the slice of ``enterprise`` the reference
consumes (SURVEY.md §1 L3->L4, §2.2): parameter objects, white-noise and
basis-GP signals, and a ``PTA`` object exposing exactly the six-call
contract the sampler uses — plus ``ModelArrays``, the device-ready frozen
bundle the TPU backend runs on.
"""

from gibbs_student_t_tpu.models.parameter import (
    Constant,
    LinearExp,
    Normal,
    Uniform,
)
from gibbs_student_t_tpu.models.signals import (
    BasisGP,
    EcorrBasisModel,
    EquadNoise,
    FourierBasisGP,
    MeasurementNoise,
    Selection,
    TimingModel,
    by_backend,
    no_selection,
    powerlaw,
    svd_tm_basis,
    tm_prior,
)
from gibbs_student_t_tpu.models.pta import PTA, ModelArrays

__all__ = [
    "Uniform", "Normal", "Constant", "LinearExp",
    "MeasurementNoise", "EquadNoise", "EcorrBasisModel", "FourierBasisGP",
    "BasisGP", "TimingModel", "Selection", "no_selection", "by_backend",
    "powerlaw", "svd_tm_basis", "tm_prior",
    "PTA", "ModelArrays",
]
