"""Signal algebra: white-noise and basis-GP building blocks.

First-party replacement for the ``enterprise.signals`` surface the reference
builds its model from (reference run_sims.py:57-76; notebook cell 2):
``MeasurementNoise`` (efac), ``EquadNoise``, ``EcorrBasisModel``,
``FourierBasisGP``, ``BasisGP``/``TimingModel``, ``Selection``, and the
``powerlaw`` spectrum. Templates compose with ``+`` and are instantiated on
a :class:`~gibbs_student_t_tpu.data.pulsar.Pulsar`, exactly like the
reference's ``s = ef + eq + rn + tm; s(psr)`` idiom.

Bases in scope are parameter-independent (Fourier, SVD timing, ecorr
quantization), so each instance exposes a static ``basis`` plus a *phi
spec* — a typed description of how its prior variances depend on sampled
parameters — that the freeze step (models/pta.py) turns into device arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from gibbs_student_t_tpu.data.pulsar import Pulsar
from gibbs_student_t_tpu.models.parameter import Constant, Parameter, Uniform

FYR = 1.0 / (365.25 * 86400.0)


# ---------------------------------------------------------------------------
# Selections
# ---------------------------------------------------------------------------

class Selection:
    """Partition of TOAs into named groups, each with its own noise
    parameter instance (mirrors ``enterprise.signals.selections``,
    reference run_sims.py:61)."""

    def __init__(self, fn: Callable[[Pulsar], Dict[str, np.ndarray]]):
        self.fn = fn

    def __call__(self, psr: Pulsar) -> Dict[str, np.ndarray]:
        return self.fn(psr)


def no_selection(psr: Pulsar) -> Dict[str, np.ndarray]:
    return {"": np.ones(psr.n, dtype=bool)}


def by_backend(psr: Pulsar) -> Dict[str, np.ndarray]:
    groups: Dict[str, np.ndarray] = {}
    backends = np.asarray(psr.backend_flags)
    for be in sorted(set(backends.tolist())):
        groups[str(be)] = backends == be
    return groups


def _named(psr_name: str, group: str, suffix: str) -> str:
    parts = [psr_name] + ([group] if group else []) + [suffix]
    return "_".join(parts)


# ---------------------------------------------------------------------------
# Phi specs — typed prior-variance descriptions consumed by the freeze step
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PowerlawPhi:
    """phi_k = A^2/(12 pi^2) * fyr^(gamma-3) * f_k^-gamma * df  (seconds^2),
    the standard PTA powerlaw convention (reference run_sims.py:67)."""
    freqs: np.ndarray          # per-column frequency (each f repeated sin/cos)
    df: float                  # frequency bin width 1/T_span
    log10_A: object            # Parameter or Constant
    gamma: object


@dataclasses.dataclass
class EcorrPhi:
    """phi_col = 10^(2*log10_ecorr_g(col)) (seconds^2) for epoch-averaged
    white noise (notebook cell 2's EcorrBasisModel)."""
    col_group: np.ndarray      # (k,) int — group index per basis column
    params: List[object]       # per-group Parameter or Constant (log10 s)


@dataclasses.dataclass
class ImproperPhi:
    """Flat (improper) prior on the block: phi -> infinity, phiinv = 0 and no
    logdet contribution. Exact-limit form of the reference's 1e40 timing
    prior (reference run_sims.py:27-29) — the 1e-40 precision and constant
    logdet of the reference affect the posterior by strictly nothing, and
    the exact limit is what makes float32 viable on TPU (SURVEY.md §7)."""


@dataclasses.dataclass
class ConstPhi:
    """Fixed prior variances (BasisGP with a constant prior function)."""
    phi: np.ndarray


# ---------------------------------------------------------------------------
# Signal instances
# ---------------------------------------------------------------------------

class SignalInstance:
    params: List[Parameter]

    # white-noise pieces: list of (kind, mask, Parameter|Constant)
    def white_specs(self) -> List:
        return []

    # basis piece: (basis (n,k), phi spec) or None
    def basis_block(self):
        return None


class _WhiteInstance(SignalInstance):
    def __init__(self, kind: str, psr: Pulsar, param_tpl, selection: Selection,
                 suffix: str):
        self.kind = kind
        self.params = []
        self._specs = []
        for group, mask in selection(psr).items():
            name = _named(psr.name, group, suffix)
            if isinstance(param_tpl, Constant):
                p = Constant(param_tpl.value, name)
            else:
                p = param_tpl.with_name(name)
                self.params.append(p)
            self._specs.append((kind, mask.astype(np.float64), p))

    def white_specs(self):
        return self._specs


class _BasisInstance(SignalInstance):
    def __init__(self, basis: np.ndarray, phi_spec, params: List[Parameter]):
        self.basis = basis
        self.phi_spec = phi_spec
        self.params = params

    def basis_block(self):
        return (self.basis, self.phi_spec)


# ---------------------------------------------------------------------------
# Signal templates (user-facing constructors)
# ---------------------------------------------------------------------------

class SignalTemplate:
    def __call__(self, psr: Pulsar) -> SignalInstance:
        raise NotImplementedError

    def __add__(self, other) -> "SignalCollection":
        return SignalCollection([self]) + other


class SignalCollection(SignalTemplate):
    def __init__(self, templates: Sequence[SignalTemplate]):
        self.templates = list(templates)

    def __add__(self, other):
        if isinstance(other, SignalCollection):
            return SignalCollection(self.templates + other.templates)
        return SignalCollection(self.templates + [other])

    def __call__(self, psr: Pulsar) -> "SignalModel":
        return SignalModel([t(psr) for t in self.templates], psr)


class SignalModel:
    """All signal instances for one pulsar — the per-pulsar model object
    aggregated by :class:`~gibbs_student_t_tpu.models.pta.PTA`."""

    def __init__(self, instances: List[SignalInstance], psr: Pulsar):
        self.instances = instances
        self.psr = psr

    @property
    def params(self) -> List[Parameter]:
        out = []
        for inst in self.instances:
            out.extend(inst.params)
        return out


class MeasurementNoise(SignalTemplate):
    """N += (efac * toaerr)^2 per selection group (reference run_sims.py:63)."""

    def __init__(self, efac=None, selection: Optional[Selection] = None):
        self.efac = efac if efac is not None else Uniform(0.1, 10.0)
        self.selection = selection or Selection(no_selection)

    def __call__(self, psr: Pulsar):
        return _WhiteInstance("efac", psr, self.efac, self.selection, "efac")


class EquadNoise(SignalTemplate):
    """N += 10^(2*log10_equad) per selection group (reference run_sims.py:64)."""

    def __init__(self, log10_equad=None, selection: Optional[Selection] = None):
        self.log10_equad = (log10_equad if log10_equad is not None
                            else Uniform(-10.0, -5.0))
        self.selection = selection or Selection(no_selection)

    def __call__(self, psr: Pulsar):
        return _WhiteInstance("equad", psr, self.log10_equad, self.selection,
                              "log10_equad")


@dataclasses.dataclass
class PowerlawSpectrum:
    log10_A: object
    gamma: object


def powerlaw(log10_A=None, gamma=None) -> PowerlawSpectrum:
    """Powerlaw PSD factory (reference run_sims.py:67's ``utils.powerlaw``)."""
    return PowerlawSpectrum(
        log10_A if log10_A is not None else Uniform(-18.0, -12.0),
        gamma if gamma is not None else Uniform(0.0, 7.0),
    )


def fourier_basis(toas: np.ndarray, components: int):
    """Standard PTA Fourier design matrix: interleaved sin/cos pairs at
    f_k = k / T_span (enterprise's createfourierdesignmatrix_red)."""
    tspan = toas.max() - toas.min()
    k = np.arange(1, components + 1)
    f = k / tspan
    arg = 2 * np.pi * f[None, :] * (toas - toas.min())[:, None]
    F = np.empty((len(toas), 2 * components))
    F[:, 0::2] = np.sin(arg)
    F[:, 1::2] = np.cos(arg)
    return F, np.repeat(f, 2), 1.0 / tspan


class FourierBasisGP(SignalTemplate):
    """Fourier-basis Gaussian process with a parametrized spectrum
    (reference run_sims.py:68)."""

    def __init__(self, spectrum: PowerlawSpectrum, components: int = 30,
                 name: str = "red_noise"):
        self.spectrum = spectrum
        self.components = components
        self.name = name

    def __call__(self, psr: Pulsar):
        F, freqs, df = fourier_basis(psr.toas, self.components)
        params = []

        def bind(p, suffix):
            if isinstance(p, Constant):
                return Constant(p.value, _named(psr.name, self.name, suffix))
            bound = p.with_name(_named(psr.name, self.name, suffix))
            params.append(bound)
            return bound

        spec = PowerlawPhi(
            freqs=freqs,
            df=df,
            log10_A=bind(self.spectrum.log10_A, "log10_A"),
            gamma=bind(self.spectrum.gamma, "gamma"),
        )
        return _BasisInstance(F, spec, params)


def create_quantization_matrix(toas: np.ndarray, dt: float = 600.0,
                               nmin: int = 2):
    """Epoch quantization matrix U (n x n_epochs): U[i,j] = 1 iff TOA i falls
    in epoch j; epochs are runs of TOAs separated by < ``dt`` seconds, kept
    only when they contain >= ``nmin`` TOAs (enterprise's
    create_quantization_matrix semantics)."""
    isort = np.argsort(toas)
    groups = []
    current = [isort[0]]
    for idx in isort[1:]:
        if toas[idx] - toas[current[-1]] < dt:
            current.append(idx)
        else:
            groups.append(current)
            current = [idx]
    groups.append(current)
    groups = [g for g in groups if len(g) >= nmin]
    U = np.zeros((len(toas), len(groups)))
    for j, g in enumerate(groups):
        U[g, j] = 1.0
    epoch_toas = np.array([toas[g].mean() for g in groups])
    return U, epoch_toas


class EcorrBasisModel(SignalTemplate):
    """Epoch-correlated white noise as a basis GP over the quantization
    matrix (notebook cell 2). Each selection group gets its own
    ``log10_ecorr`` parameter applied to the epochs it owns."""

    def __init__(self, log10_ecorr=None, selection: Optional[Selection] = None,
                 dt: float = 600.0, nmin: int = 2):
        self.log10_ecorr = (log10_ecorr if log10_ecorr is not None
                            else Uniform(-10.0, -5.0))
        self.selection = selection or Selection(no_selection)
        self.dt = dt
        self.nmin = nmin

    def __call__(self, psr: Pulsar):
        groups = self.selection(psr)
        bases, col_group, bound = [], [], []
        params: List[Parameter] = []
        for gi, (gname, mask) in enumerate(groups.items()):
            if not mask.any():
                continue
            sub_toas = psr.toas[mask]
            U_sub, _ = create_quantization_matrix(sub_toas, self.dt, self.nmin)
            if U_sub.shape[1] == 0:
                continue
            U = np.zeros((psr.n, U_sub.shape[1]))
            U[np.flatnonzero(mask), :] = U_sub
            bases.append(U)
            col_group.extend([len(bound)] * U.shape[1])
            name = _named(psr.name, gname, "log10_ecorr")
            if isinstance(self.log10_ecorr, Constant):
                bound.append(Constant(self.log10_ecorr.value, name))
            else:
                p = self.log10_ecorr.with_name(name)
                params.append(p)
                bound.append(p)
        if not bases:
            basis = np.zeros((psr.n, 0))
            spec = EcorrPhi(np.zeros(0, dtype=int), [])
        else:
            basis = np.concatenate(bases, axis=1)
            spec = EcorrPhi(np.asarray(col_group, dtype=int), bound)
        return _BasisInstance(basis, spec, params)


# --- timing model ----------------------------------------------------------

def svd_tm_basis(Mmat: np.ndarray):
    """Left singular vectors of the timing design matrix, unit weights —
    numerically-conditioned timing basis (reference run_sims.py:22-25)."""
    u, s, _ = np.linalg.svd(Mmat, full_matrices=False)
    return u, np.ones_like(s)


def tm_prior(weights: np.ndarray):
    """Improper flat prior on timing coefficients. The reference uses
    ``weights * 1e40`` (run_sims.py:27-29); we take the exact limit (see
    :class:`ImproperPhi`)."""
    return ImproperPhi()


class BasisGP(SignalTemplate):
    """Generic fixed-basis GP: ``basis_fn(Mmat) -> (basis, weights)`` and
    ``prior_fn(weights) -> phi spec | array`` (reference run_sims.py:73)."""

    def __init__(self, prior_fn: Callable = tm_prior,
                 basis_fn: Callable = svd_tm_basis):
        self.prior_fn = prior_fn
        self.basis_fn = basis_fn

    def __call__(self, psr: Pulsar):
        basis, weights = self.basis_fn(psr.Mmat)
        spec = self.prior_fn(weights)
        if isinstance(spec, np.ndarray):
            spec = ConstPhi(spec)
        return _BasisInstance(basis, spec, [])


def TimingModel() -> BasisGP:
    """SVD-basis timing model with improper flat prior (notebook cell 2)."""
    return BasisGP(tm_prior, svd_tm_basis)
