"""gibbs_student_t_tpu — a TPU-native framework for robust (Student-t /
Gaussian-mixture) Gibbs sampling of pulsar-timing-array noise models.

A ground-up JAX/XLA re-design with the capabilities of the reference
``aniwl/gibbs_student_t`` (blocked Metropolis-within-Gibbs sampler for PTA
outlier analysis; see /root/reference/gibbs.py). Where the reference is a
single-chain CPU NumPy code sitting on enterprise/libstempo/LAPACK, this
framework is:

- **pure-functional**: the sampler sweep is a pure function over an explicit
  chain-state pytree, ``jit``-compiled once;
- **chain data-parallel**: ``vmap`` over 1000+ independent chains per chip;
- **device-parallel**: ``shard_map`` over a ``jax.sharding.Mesh`` for
  multi-chain / multi-pulsar ensembles, with XLA collectives for cross-chain
  diagnostics only (chains are independent);
- **self-contained**: first-party par/tim ingestion, timing-model basis,
  signal/PTA model layer, and simulator replace enterprise + libstempo/tempo2.

Layout:
  data/      host-side NumPy ingestion + simulation (par/tim, design matrix)
  models/    parameters, signal algebra, PTA seam, frozen ModelArrays
  backends/  SamplerBackend seam: NumPy oracle + JAX TPU kernel
  ops/       numerics: safe Cholesky, distributions, structured covariance
  parallel/  mesh/sharding helpers, cross-chain diagnostics
  utils/     RNG trees, chain storage/spooling, checkpointing
"""

__version__ = "0.1.0"

from gibbs_student_t_tpu.config import GibbsConfig, MHConfig
from gibbs_student_t_tpu.models.pta import PTA, ModelArrays

__all__ = ["GibbsConfig", "MHConfig", "PTA", "ModelArrays", "__version__"]
