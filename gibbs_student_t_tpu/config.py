"""Configuration dataclasses for the sampler.

The reference spreads configuration across constructor kwargs
(reference gibbs.py:9-11) and hard-coded constants in the drivers
(reference run_sims.py:32-35, 57-76) with the MH step-size table duplicated
inline in two methods (reference gibbs.py:92-94, 125-127). Here every knob is
a frozen dataclass so configs hash, print, and thread through jit as static
arguments.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

# Likelihood families of the reference (gibbs.py:50, 187-189, 206-208):
#   gaussian : plain Gaussian likelihood, z == 0 throughout
#   t        : Student-t via per-TOA auxiliary inverse-gamma scales, z == 1
#   mixture  : Gaussian/Gaussian outlier mixture with Bernoulli indicators
#   vvh17    : Vallisneri & van Haasteren (2017) uniform-in-phase outlier model
MODELS = ("gaussian", "t", "mixture", "vvh17")

THETA_PRIORS = ("beta", "uniform")


@dataclasses.dataclass(frozen=True)
class MHConfig:
    """Random-walk Metropolis jump kernel shared by the white and hyper blocks.

    Mirrors the jump structure of reference gibbs.py:88-97 and 121-130: a
    scale drawn from a discrete mixture, one uniformly-chosen coordinate per
    step, sigma proportional to the size of the parameter group.
    """

    n_white_steps: int = 20       # reference gibbs.py:121
    n_hyper_steps: int = 10       # reference gibbs.py:88
    sigma_per_param: float = 0.05  # reference gibbs.py:92,125
    scale_sizes: Tuple[float, ...] = (0.1, 0.5, 1.0, 3.0, 10.0)
    scale_probs: Tuple[float, ...] = (0.1, 0.15, 0.5, 0.15, 0.1)
    # Opt-in Robbins-Monro step-size adaptation (JAX backend): for the
    # first ``adapt_until`` sweeps, each chain's per-block log jump scale
    # moves by eta_t * (acc - target_accept), eta_t = (t+1)^-adapt_decay,
    # then freezes — the chain is ordinary (valid) MH from that sweep on,
    # so set burn >= adapt_until when analyzing. The reference's fixed
    # scales (gibbs.py:92-94,125-127) sit at ~0.95 white acceptance on
    # the flagship model — far above the ~0.44 optimum for
    # one-coordinate random-walk MH — so adaptation buys mixing speed
    # without touching the model. 0 (default) reproduces the reference's
    # fixed-scale behavior exactly.
    adapt_until: int = 0
    target_accept: float = 0.44
    adapt_decay: float = 0.66
    # Opt-in population-covariance proposals (JAX backend, requires
    # adapt_until > 0): while adapting, the proposal direction becomes a
    # draw from the EMPIRICAL COVARIANCE of each coordinate block across
    # the chain population (re-estimated at chunk boundaries, shrunk
    # toward its diagonal, frozen together with the scales at
    # adapt_until). A thousand parallel chains make the estimate
    # essentially free and unbiased by single-chain autocorrelation —
    # an axis the reference's one-chain design cannot exploit. Joint
    # proposals target the multivariate RWM optimum (~0.234) instead of
    # the one-coordinate 0.44.
    adapt_cov: bool = False
    cov_target_accept: float = 0.234
    cov_shrinkage: float = 0.1
    # Opt-in multiple-try Metropolis (JAX backend): each MH step draws
    # ``mtm_tries`` iid candidates from the (symmetric) jump kernel,
    # selects one by importance weight (posterior density, Gumbel-max),
    # draws ``mtm_tries - 1`` reference points around the selected
    # candidate, and accepts on the weight-sum ratio (Liu, Liang & Wong
    # 2000, MTM(II) with w = pi). Trades (2K-1)x likelihood evaluations
    # per step for larger accepted moves — a fit for the fused kernels'
    # precomputed-draw shape where per-evaluation arithmetic is far
    # below the VPU roofline (docs/PERFORMANCE.md). 0 (default)
    # disables; values >= 2 run the XLA closure path (the fused
    # single-try Pallas kernels are bypassed while MTM is on).
    # ``mtm_blocks`` selects which MH blocks use MTM — the white block's
    # likelihood evaluations are cheap (elementwise) while the hyper
    # block's each pay a factorization, so the cost/benefit differs
    # sharply per block; the per-block A/B (tools/adapt_ess.py --mtm)
    # is what decides where in-kernel fusion would pay.
    mtm_tries: int = 0
    mtm_blocks: Tuple[str, ...] = ("white", "hyper")


@dataclasses.dataclass(frozen=True)
class GibbsConfig:
    """Model flags of the reference ``Gibbs.__init__`` (gibbs.py:9-51)."""

    model: str = "gaussian"
    tdf: int = 4                   # Student-t degrees of freedom (initial/fixed)
    outlier_mean: float = 0.01     # `m`, a-priori outlier probability
    vary_df: bool = True
    theta_prior: str = "beta"
    vary_alpha: bool = True
    alpha: float = 1e10            # fixed alpha when vary_alpha=False
    pspin: float | None = None     # spin period (s), needed by model='vvh17'
    df_max: int = 30               # df grid 1..df_max (reference gibbs.py:248)
    # Outlier-indicator initialization. "model" reproduces the reference
    # (gibbs.py:50-51: z starts at 1 for t/mixture/vvh17). "zeros" starts
    # the outlier models at z == 0 — in the dominant all-inlier posterior
    # mode. The reference init puts vvh17 (fixed alpha=1e10) into a
    # METASTABLE all-outlier mode on outlier-contaminated data: with every
    # TOA inflated by alpha, the coefficient draw is prior-dominated,
    # residuals are huge, p_in underflows, and q -> 1 keeps z pinned at 1
    # for O(10^3)+ sweeps until a red-noise-amplitude excursion lets the
    # unflagging cascade start (measured: NumPy oracle escapes at sweep
    # ~1700 (seed 3) or not within 8000 (seed 11); the f32 JAX kernel at
    # sweeps ~70-150). Both settle in the same good mode; "zeros" skips
    # the trap, which the distributional gates rely on (tools/j1713_gate).
    # Not meaningful for model='t', where z == 1 is structural (the
    # auxiliary-scale mixture representation, reference gibbs.py:206-208).
    z_init: str = "model"
    mh: MHConfig = dataclasses.field(default_factory=MHConfig)
    # Cholesky jitter added to Sigma's (preconditioned) diagonal. Plays the
    # role of the reference's SVD->QR fallback / -inf guard
    # (gibbs.py:168-178, 320-324) in branchless form.
    jitter: float = 1e-6

    def __post_init__(self):
        if self.model not in MODELS:
            raise ValueError(f"model must be one of {MODELS}, got {self.model!r}")
        if self.theta_prior not in THETA_PRIORS:
            raise ValueError(
                f"theta_prior must be one of {THETA_PRIORS}, got {self.theta_prior!r}"
            )
        if self.model == "vvh17" and self.pspin is None:
            raise ValueError("model='vvh17' requires pspin (spin period in s)")
        if self.z_init not in ("model", "zeros"):
            raise ValueError(
                f"z_init must be 'model' or 'zeros', got {self.z_init!r}")
        if self.z_init == "zeros" and self.model == "t":
            raise ValueError(
                "z_init='zeros' is invalid for model='t': z == 1 is "
                "structural there (every TOA carries an auxiliary "
                "inverse-gamma scale, reference gibbs.py:206-208), and "
                "update_z never redraws it")
        if self.mh.mtm_tries not in (0,) and self.mh.mtm_tries < 2:
            raise ValueError(
                f"MHConfig.mtm_tries must be 0 (off) or >= 2, got "
                f"{self.mh.mtm_tries}")
        if not set(self.mh.mtm_blocks) <= {"white", "hyper"}:
            raise ValueError(
                f"MHConfig.mtm_blocks must be a subset of "
                f"('white', 'hyper'), got {self.mh.mtm_blocks!r}")
        if self.mh.mtm_tries >= 2 and not self.mh.mtm_blocks:
            raise ValueError(
                "MHConfig.mtm_tries is set but mtm_blocks is empty — "
                "MTM would silently never run; select ('white',), "
                "('hyper',) or both")
        if self.mh.adapt_cov and self.mh.adapt_until <= 0:
            raise ValueError(
                "MHConfig.adapt_cov requires adapt_until > 0 (the "
                "population covariance is estimated while adapting and "
                "frozen at adapt_until)")

    def with_adapt(self, adapt_until: int,
                   adapt_cov: bool = False) -> "GibbsConfig":
        """This config with MH jump-scale adaptation for the first
        ``adapt_until`` sweeps (the drivers' ``--adapt`` flag; see
        MHConfig), optionally with population-covariance proposals
        (``--adapt-cov``). Shared so bench.py and run_sims.py cannot
        drift."""
        return dataclasses.replace(
            self, mh=dataclasses.replace(self.mh,
                                         adapt_until=adapt_until,
                                         adapt_cov=adapt_cov))

    def with_mtm(self, tries: int,
                 blocks: Tuple[str, ...] = ("white", "hyper"),
                 ) -> "GibbsConfig":
        """This config with multiple-try Metropolis proposals (the
        drivers' ``--mtm`` flag; see MHConfig.mtm_tries/mtm_blocks)."""
        return dataclasses.replace(
            self, mh=dataclasses.replace(self.mh, mtm_tries=tries,
                                         mtm_blocks=tuple(blocks)))

    @property
    def is_outlier_model(self) -> bool:
        return self.model in ("mixture", "vvh17")

    @property
    def z_init_ones(self) -> bool:
        # reference gibbs.py:50-51: z starts at 1 for t/mixture/vvh17
        # (unless z_init='zeros' opts into the dominant-mode start)
        if self.z_init == "zeros":
            return False
        return self.model in ("t", "mixture", "vvh17")
