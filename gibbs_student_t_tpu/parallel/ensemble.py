"""Multi-pulsar, multi-chain ensembles sharded over a device mesh.

The reference's batch driver iterates pulsars and model configs in one
sequential process (reference run_sims.py:80-113; 300k sweeps end to end).
Here the pulsar ensemble and the chain population are a 2-D ``Mesh``:
pulsars shard one axis, chains the other, each device sweeping its
``(local_pulsars, local_chains)`` block independently — per-pulsar
likelihoods are independent in this model family (reference gibbs.py:28-29
hard-codes a single pulsar), so the sweep needs no communication at all;
``psum`` collectives appear only in the cross-chain R-hat diagnostic
(parallel/diagnostics.py). This realizes BASELINE.json config 5 (32-pulsar
ensemble across a v5e-8 slice).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import random
from jax.sharding import Mesh, PartitionSpec as P

from gibbs_student_t_tpu.parallel.compat import shard_map

from gibbs_student_t_tpu.backends.base import ChainResult
from gibbs_student_t_tpu.backends.jax_backend import (
    ChainState,
    FusedConsts,
    JaxGibbs,
    chunked_sweep_loop,
    merge_reinit,
    record_tuple,
)
from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.models.pta import ModelArrays
from gibbs_student_t_tpu.obs.telemetry import (
    Telemetry,
    TelemetryAccumulator,
    telemetry_init,
    telemetry_update,
)


def _localize_names(ma: ModelArrays) -> ModelArrays:
    """Strip the pulsar-name prefix from parameter names so every pulsar's
    static metadata (and therefore pytree structure) is identical and the
    ensembles can stack."""
    prefix = ma.name + "_"
    local = tuple(
        nm[len(prefix):] if nm.startswith(prefix) else nm
        for nm in ma.param_names
    )
    return dataclasses.replace(ma, name="ensemble", param_names=local)


def pad_model_arrays(mas: Sequence[ModelArrays],
                     n_to: Optional[int] = None) -> List[ModelArrays]:
    """Pad each pulsar's TOA axis to a common length with masked rows.

    A real PTA has per-pulsar TOA counts; stacking needs equal shapes.
    Suffix rows are appended with zero residual/basis/variance and
    ``row_mask=False`` — the sweep pins their ``nvec`` to 1 and their
    ``z``/``alpha`` to 0/1 so they contribute exactly nothing to any
    reduction (same mechanism as the blocked-TNT padding,
    backends/jax_backend.py), and per-pulsar statistical TOA counts come
    from ``sum(row_mask)``. Basis size and parameter structure must still
    match — those encode the signal model, not the data size.
    """
    def local_names(ma):
        # single source of truth for the localization convention
        return _localize_names(ma).param_names

    n_max = max(ma.n for ma in mas) if n_to is None else n_to
    m0, p0 = mas[0].m, local_names(mas[0])
    out = []
    for ma in mas:
        if ma.m != m0:
            raise ValueError(
                f"cannot pad pulsar {ma.name!r}: basis size {ma.m} != "
                f"{m0}; ensembles need identical signal composition "
                "(equal Fourier components and timing columns)")
        if local_names(ma) != p0:
            raise ValueError(
                f"cannot pad pulsar {ma.name!r}: parameter structure "
                f"{local_names(ma)} != {p0}; ensembles need identical "
                "signal composition per pulsar")
        if ma.n > n_max:
            raise ValueError(f"pulsar {ma.name!r} has n={ma.n} > n_to={n_max}")
        pad = n_max - ma.n
        mask = np.concatenate([np.ones(ma.n, dtype=bool),
                               np.zeros(pad, dtype=bool)])
        if ma.row_mask is not None:
            mask[:ma.n] = np.asarray(ma.row_mask, dtype=bool)
        out.append(dataclasses.replace(
            ma,
            y=np.concatenate([ma.y, np.zeros(pad)]),
            T=np.concatenate([ma.T, np.zeros((pad, ma.m))]),
            sigma2=np.concatenate([ma.sigma2, np.zeros(pad)]),
            efac_masks=np.concatenate(
                [ma.efac_masks, np.zeros((ma.efac_masks.shape[0], pad))],
                axis=1),
            equad_masks=np.concatenate(
                [ma.equad_masks, np.zeros((ma.equad_masks.shape[0], pad))],
                axis=1),
            row_mask=mask,
        ))
    return out


def localized_padded(mas: Sequence[ModelArrays]) -> List[ModelArrays]:
    """Per-pulsar models localized (name prefixes stripped) and padded
    to a common TOA length, structure-validated — the pre-stack form.
    The unrolled ensemble path consumes this list directly (each entry
    bakes into its own pulsar's trace as constants); the grouped path
    stacks it."""
    if len({ma.n for ma in mas}) > 1 or any(
            ma.row_mask is not None for ma in mas):
        # pad_model_arrays gives every pulsar a row_mask, so the pytrees
        # stack uniformly even for the already-max-length ones
        mas = pad_model_arrays(mas)
    locs = [_localize_names(ma) for ma in mas]
    treedef0 = jax.tree.structure(locs[0])
    for ma in locs[1:]:
        if jax.tree.structure(ma) != treedef0:
            raise ValueError(
                "pulsar models have different structure; ensembles need "
                "identical signal composition per pulsar")
    return locs


def stack_model_arrays(mas: Sequence[ModelArrays]) -> ModelArrays:
    """Stack per-pulsar frozen models along a new leading pulsar axis.

    Heterogeneous TOA counts are padded to the maximum via
    :func:`pad_model_arrays`; basis size and parameter structure must
    match (they encode the signal model itself).
    """
    return jax.tree.map(lambda *xs: np.stack(xs), *localized_padded(mas))


class EnsembleGibbs:
    """(pulsars x chains) Gibbs populations on a 2-D device mesh.

    Each pulsar keeps an independent parameter vector (the model family has
    no cross-pulsar terms); sampling runs ``shard_map``-ed over
    ``mesh = ('pulsar', 'chain')``, falling back to plain ``vmap`` without
    a mesh. ``record`` takes the same modes as ``JaxGibbs``
    ("compact"/"compact8"/"full"/"light"), with the identical wire casts and
    double-buffered device->host flushes.

    Two step forms exist (``unroll``): the GROUPED form traces one
    program with the per-pulsar models/fused-MH constants as traced
    operands (required when the pulsar axis is sharded across devices),
    and the UNROLLED form Python-loops per-pulsar backends whose
    constants bake into the trace as XLA literals — the exact
    single-model kernel shape per pulsar, closing the measured 2.0x
    grouped-path per-chain-sweep gap on device (VERDICT r4 #1;
    A/B via ``GST_ENSEMBLE_UNROLL`` / tools/ensemble_bench.py
    ``--unroll``). ``'auto'`` unrolls when the pulsar mesh axis is
    unsharded and the ensemble is small enough (<= 8 pulsars) that the
    duplicated traces compile acceptably.
    """

    def __init__(self, mas: Sequence[ModelArrays], config: GibbsConfig,
                 nchains: int = 64, mesh: Optional[Mesh] = None,
                 dtype=jnp.float32, chunk_size: int = 50,
                 record: str = "compact8", record_thin: int = 1,
                 unroll: bool | str = "auto",
                 telemetry: bool = True, metrics=None):
        """``telemetry``/``metrics`` as in ``JaxGibbs``: the in-kernel
        ``Telemetry`` pytree rides each (pulsar, chain) population's
        chunk scan — sharded with the state when a mesh is present —
        and drains with the record flush; aggregates land in
        ``ChainResult.stats`` under ``tele_*`` keys with leading
        ``(npulsars, nchains)`` axes (``select_pulsar`` slices them)."""
        self.npulsars = len(mas)
        self.nchains = nchains
        self.mesh = mesh
        self.chunk_size = chunk_size
        self.record = record
        # per-pulsar REAL TOA counts, before stacking pads to n_max:
        # ChainResult.select_pulsar uses these to cut the padding back
        # off saved per-pulsar chains (reference run_sims.py:118-124
        # saves exactly n rows per pulsar)
        self.n_toa = np.array([
            int(np.asarray(ma.row_mask).sum()) if ma.row_mask is not None
            else ma.n
            for ma in mas])
        self._per_pulsar = localized_padded(mas)
        self.stacked = jax.tree.map(lambda *xs: np.stack(xs),
                                    *self._per_pulsar)
        # template backend: holds config/dtype and the sweep kernel; its own
        # frozen model is pulsar 0 (never used when ma is passed explicitly)
        # tnt_block_size=None: the ensemble sweeps per-pulsar models passed
        # as traced pytrees, which must stay unpadded — auto-blocking would
        # pad the template's own model and break state shapes for large
        # pulsars (blocked/Pallas reductions are the single-model backend's
        # stress path, not the ensemble's).
        self.template = JaxGibbs(_localize_names(mas[0]), config,
                                 nchains=nchains, dtype=dtype,
                                 chunk_size=chunk_size, record=record,
                                 record_thin=record_thin,
                                 tnt_block_size=None, use_pallas=False)
        self.dtype = dtype
        # Stacked per-pulsar fused-MH constants (VERDICT r3 missing #2 /
        # docs/FUTURE.md #1): with these threaded through the step as
        # traced operands, every pulsar's white/hyper MH block reaches
        # the same fused Pallas kernels as the single-model path
        # (grouped grid in ops/pallas_white.py, per-lane constant planes
        # in ops/pallas_hyper.py). None when the blocks are unavailable
        # (float64) or the pulsars' static structure diverges.
        # lazy cache for the per-pulsar baked backends (see the
        # _pulsar_backends property)
        self._pulsar_backends_cache: Optional[List[JaxGibbs]] = None
        self._unrolled = self._resolve_unroll(unroll)
        # the stacked traced-consts bundle feeds only the grouped step;
        # the unrolled form bakes per-pulsar consts inside its backends,
        # so building the stack there would duplicate every pulsar's
        # white/hyper constant construction for dead host memory
        self._fused_consts = (None if self._unrolled
                              else self._build_fused_consts())
        self._telemetry = bool(telemetry)
        self.metrics = metrics
        # compile introspection on the sharded chunk program, same as
        # the single-model backend (obs/introspect.py)
        from gibbs_student_t_tpu.obs.introspect import introspect_jit

        # donated chunk buffers, same policy and env gate as the
        # single-model backend (the template resolved GST_DONATE_CHUNK)
        self._donate = self.template._donate
        donate = (0,) if self._donate else ()
        self._step = introspect_jit(
            self._build_step(),
            label=(f"ensemble_{'unrolled' if self._unrolled else 'grouped'}"
                   f"_chunk_p{self.npulsars}_c{nchains}"),
            registry=lambda: self.metrics,
            static_argnames=("length",),
            donate_argnums=donate)
        # per-pulsar population-covariance re-estimation at chunk
        # boundaries (MHConfig.adapt_cov): the single-model update
        # vmapped over the pulsar axis — the stacked models share one
        # parameter layout, so the template's static block indices apply
        # to every pulsar's (nchains, p) population independently.
        self._prop_cov_fn = (jax.jit(jax.vmap(self.template._prop_cov_update))
                             if config.mh.adapt_cov else None)
        self.last_state = None

    # -- construction -------------------------------------------------------

    @property
    def _pulsar_backends(self) -> List[JaxGibbs]:
        """Per-pulsar fully-baked backends: each bakes ITS pulsar's
        model and fused-MH constants into the trace exactly like the
        single-model flagship path (constants are numpy -> XLA
        literals, the r03 kernel shape). The UNROLLED step Python-loops
        these under vmap/shard_map instead of tracing one grouped
        program with per-pulsar constants as operands — the fix for the
        measured 2.0x grouped-path per-chain gap on device (VERDICT r4
        #1 / docs/FUTURE.md #1). Also the construction source for
        init_state. Built lazily: a grouped-path ensemble that resumes
        from a checkpointed state never pays the P constructions."""
        if self._pulsar_backends_cache is None:
            self._pulsar_backends_cache = [
                JaxGibbs(ma_p, self.template.config,
                         nchains=self.nchains, dtype=self.dtype,
                         chunk_size=self.chunk_size,
                         tnt_block_size=None, use_pallas=False)
                for ma_p in self._per_pulsar]
        return self._pulsar_backends_cache

    def _resolve_unroll(self, unroll) -> bool:
        """Pick the step form. Baked-consts unrolling requires every
        device to run the SAME program (shard_map traces once), so it is
        only valid when the pulsar mesh axis is unsharded; 'auto' also
        caps the trace duplication at 8 pulsars (compile time scales
        with the unroll count). ``GST_ENSEMBLE_UNROLL=0/1`` overrides
        the 'auto' resolution ONLY — an explicit ``unroll=`` argument
        always wins, so per-arm A/B harnesses (tools/ensemble_attrib.py)
        measure the form they asked for regardless of the caller's
        environment."""
        from gibbs_student_t_tpu.ops import registry

        # validated whenever SET, even when an explicit unroll=
        # argument means it won't be consulted: a typo'd override
        # must fail loudly, not silently measure the wrong arm
        # (ADVICE r5; the registry's enum01 kind)
        env = registry.value("GST_ENSEMBLE_UNROLL")
        if env != "" and unroll == "auto":
            unroll = env == "1"
        mesh_ok = (self.mesh is None
                   or self.mesh.shape.get("pulsar", 1) == 1)
        if unroll == "auto":
            return mesh_ok and self.npulsars <= 8
        if unroll and not mesh_ok:
            raise ValueError(
                "unroll=True needs the pulsar mesh axis unsharded "
                "(size 1): baked per-pulsar constants cannot differ "
                "across devices inside one shard_map program; use "
                "unroll=False or 'auto' for pulsar-sharded meshes")
        return bool(unroll)

    def _build_fused_consts(self) -> Optional[FusedConsts]:
        """Per-pulsar fused-MH constant arrays, stacked on a leading
        pulsar axis — or None when any pulsar cannot share the
        template's kernel structure (the step then keeps the XLA
        closure path for every block the constants are missing for)."""
        t = self.template
        if t._white_block is None and t._hyper_block is None:
            return None
        per_pulsar = self._per_pulsar
        wrows = wspecs = None
        if t._white_block is not None:
            from gibbs_student_t_tpu.ops.pallas_white import (
                build_white_consts,
            )

            wcs = [build_white_consts(ma_p, row_mask=ma_p.row_mask)
                   for ma_p in per_pulsar]
            # a structure mismatch disables only THIS block's fused
            # path (fields stay None); the other block keeps its kernel
            if all(wc.var == t._white_consts.var for wc in wcs):
                wrows = np.stack([wc.rows for wc in wcs])
                wspecs = np.stack([wc.specs for wc in wcs])
        hK = hsel = hpis = hlds = hspecs = None
        if t._hyper_block is not None:
            from gibbs_student_t_tpu.ops.pallas_hyper import (
                build_hyper_consts,
            )

            cols = (t._schur[1] if t._schur is not None
                    else np.arange(t._ma.m))
            hcs = [build_hyper_consts(ma_p, cols) for ma_p in per_pulsar]
            if all(hc.hyp_idx == t._hyper_consts.hyp_idx for hc in hcs):
                hK = np.stack([hc.K for hc in hcs])
                hsel = np.stack([hc.phi_sel for hc in hcs])
                hpis = np.stack([hc.phiinv_static for hc in hcs])
                hlds = np.asarray([hc.logdet_phi_static for hc in hcs],
                                  np.float32)
                hspecs = np.stack([hc.specs for hc in hcs])
        if wrows is None and hK is None:
            return None
        cast = (lambda a: None if a is None
                else jnp.asarray(a, self.dtype))
        return FusedConsts(
            white_rows=cast(wrows), white_specs=cast(wspecs),
            hyper_K=cast(hK), hyper_sel=cast(hsel),
            hyper_phiinv_static=cast(hpis),
            hyper_logdet_phi_static=cast(hlds),
            hyper_specs=cast(hspecs))

    def init_state(self, seed: int = 0) -> ChainState:
        """Batched state with leading (npulsars, nchains) axes.

        Each pulsar's state comes from its properly-constructed
        single-model backend (same config/dtype/chunking as the
        template), so constructor invariants — row-mask handling, no
        block padding on ensemble slices — hold by construction."""
        states = [gb.init_state(seed=seed * 1000 + pi)
                  for pi, gb in enumerate(self._pulsar_backends)]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *states)

    def chain_keys(self, seed: int):
        keys = random.split(random.PRNGKey(seed),
                            self.npulsars * self.nchains)
        return keys.reshape(self.npulsars, self.nchains, *keys.shape[1:])

    # -- the sharded step ---------------------------------------------------

    def _build_step(self):
        template = self.template
        fields = template._record_fields
        casts = template._record_casts
        thin = template.record_thin
        use_tele = self._telemetry
        # telemetry leaves shard exactly like the state: per (pulsar,
        # chain) scalars
        tele_spec = (Telemetry(*(P("pulsar", "chain"),)
                               * len(Telemetry._fields))
                     if use_tele else None)

        if self._unrolled:
            # UNROLLED step: a Python loop over the per-pulsar baked
            # backends. Every pulsar's sweep is the exact single-model
            # trace (its model and fused-MH constants are XLA literals,
            # ops/pallas_white.py G==1 shape) — nothing is passed as a
            # traced per-pulsar operand. Valid because the pulsar mesh
            # axis is unsharded here (_resolve_unroll); chains still
            # shard over the mesh's 'chain' axis when one exists.
            backends = self._pulsar_backends

            def baked_chunk(gb_p, state, chain_key, offset, length):
                def one(j, c):
                    s, t = c
                    s = gb_p._sweep(s, random.fold_in(chain_key, j),
                                    sweep=j)
                    return s, (telemetry_update(t, s) if use_tele else t)

                def body(carry, i0):
                    st, tl = carry
                    rec = record_tuple(st, fields, casts)
                    if thin == 1:
                        st, tl = one(i0, (st, tl))
                    else:
                        st, tl = jax.lax.fori_loop(
                            0, thin,
                            lambda j, c: one(i0 + j, c), (st, tl))
                    return (st, tl), rec

                (st, tl), recs = jax.lax.scan(
                    body, (state, telemetry_init(self.dtype)),
                    offset + jnp.arange(0, length, thin))
                if use_tele:
                    tl = tl._replace(logpost=gb_p._logpost_chain(st))
                return st, recs, tl

            def step_unrolled(states, keys, offset, length):
                def run(st_block, key_block):
                    outs = []
                    for pi, gb_p in enumerate(backends):
                        st_p = jax.tree.map(lambda a, i=pi: a[i],
                                            st_block)
                        outs.append(jax.vmap(functools.partial(
                            baked_chunk, gb_p, offset=offset,
                            length=length))(st_p, key_block[pi]))
                    st, recs, tl = jax.tree.map(
                        lambda *xs: jnp.stack(xs), *outs)
                    return st, (recs, tl if use_tele else None)

                if self.mesh is None:
                    return run(states, keys)
                specs_state = jax.tree.map(
                    lambda _: P("pulsar", "chain"), states)
                key_spec = P("pulsar", "chain")
                out_rec_spec = tuple(P("pulsar", "chain") for _ in fields)
                return shard_map(
                    run, mesh=self.mesh,
                    in_specs=(specs_state, key_spec),
                    out_specs=(specs_state, (out_rec_spec, tele_spec)),
                    check_vma=False,
                )(states, keys)

            return jax.jit(step_unrolled, static_argnames=("length",),
                           donate_argnums=(
                               (0,) if self.template._donate else ()))

        # grouped traced-consts form: the stacked model rides as a jit
        # operand (cast here, AFTER the unrolled early-return, so the
        # baked path never allocates the device copy)
        stacked = jax.tree.map(
            lambda a: jnp.asarray(a, dtype=self.dtype)
            if np.issubdtype(np.asarray(a).dtype, np.floating) else a,
            self.stacked)

        def local_chunk(ma_p, fc_p, state, chain_key, offset, length):
            # scan over recorded rows, inner loop over the thin sweeps
            # between them — same structure and keying as the
            # single-model chunk fn (backends/jax_backend.py)
            def one(j, c):
                s, t = c
                s = template._sweep(s, random.fold_in(chain_key, j),
                                    ma=ma_p, sweep=j, fused=fc_p)
                return s, (telemetry_update(t, s) if use_tele else t)

            def body(carry, i0):
                st, tl = carry
                # same compact device-side transport casts as the
                # single-model backend
                rec = record_tuple(st, fields, casts)
                if thin == 1:
                    st, tl = one(i0, (st, tl))
                else:
                    st, tl = jax.lax.fori_loop(
                        0, thin, lambda j, c: one(i0 + j, c), (st, tl))
                return (st, tl), rec

            (st, tl), recs = jax.lax.scan(
                body, (state, telemetry_init(self.dtype)),
                offset + jnp.arange(0, length, thin))
            if use_tele:
                tl = tl._replace(
                    logpost=template._logpost_chain(st, ma=ma_p))
            return st, recs, tl

        def step(stacked_ma, fc, states, keys, offset, length):
            def run(ma_block, fc_block, st_block, key_block):
                def per_pulsar(ma_p, fc_p, st_p, keys_p):
                    return jax.vmap(
                        functools.partial(local_chunk, ma_p, fc_p,
                                          offset=offset, length=length)
                    )(st_p, keys_p)

                st, recs, tl = jax.vmap(per_pulsar)(
                    ma_block, fc_block, st_block, key_block)
                return st, (recs, tl if use_tele else None)

            if self.mesh is None:
                return run(stacked_ma, fc, states, keys)
            specs_ma = jax.tree.map(lambda _: P("pulsar"), stacked_ma)
            specs_fc = jax.tree.map(lambda _: P("pulsar"), fc)
            specs_state = jax.tree.map(lambda _: P("pulsar", "chain"),
                                       states)
            key_spec = P("pulsar", "chain")
            out_rec_spec = tuple(P("pulsar", "chain") for _ in fields)
            # check_vma=False: the sweep body is collective-free (chains
            # and pulsars are independent), and the vma checker rejects
            # unvarying fori_loop carries (fresh accept counters) inside a
            # manual region.
            return shard_map(
                run, mesh=self.mesh,
                in_specs=(specs_ma, specs_fc, specs_state, key_spec),
                out_specs=(specs_state, (out_rec_spec, tele_spec)),
                check_vma=False,
            )(stacked_ma, fc, states, keys)

        return jax.jit(functools.partial(step, stacked,
                                         self._fused_consts),
                       static_argnames=("length",),
                       donate_argnums=((0,) if self.template._donate
                                       else ()))

    # -- sampling -----------------------------------------------------------

    def sample(self, niter: int, seed: int = 0,
               state: Optional[ChainState] = None,
               start_sweep: int = 0,
               spool_dir: Optional[str] = None,
               reinit_diverged: bool = False) -> ChainResult:
        """Run ``niter`` sweeps for every (pulsar, chain) population.

        Feature parity with ``JaxGibbs.sample`` (VERDICT r2 weak #4):
        ``spool_dir`` streams each chunk to native append-only spool
        files + a state checkpoint so host memory stays O(chunk) and a
        killed run resumes from the last chunk boundary;
        ``reinit_diverged`` re-draws numerically dead (pulsar, chain)
        populations from the prior at chunk boundaries (cumulative count
        in ``stats['n_reinits']``). Spooled arrays keep the rectangular
        padded TOA axis; ``select_pulsar`` trims via ``stats['n_toa']``.
        """
        if niter < 1:
            raise ValueError(f"niter must be >= 1, got {niter}")
        thin = self.template.record_thin
        if niter % thin:
            raise ValueError(f"niter ({niter}) must be a multiple of "
                             f"record_thin ({thin})")
        if start_sweep % thin:
            raise ValueError(f"start_sweep ({start_sweep}) must land on "
                             f"a recorded sweep (multiple of {thin})")
        resume = start_sweep > 0
        if state is None:
            state = self.init_state(seed)
        elif self._donate:
            # the step donates its state argument; protect the caller's
            # object with one up-front copy (see JaxGibbs.sample)
            state = jax.tree.map(jnp.copy, state)
        keys = self.chain_keys(seed)
        spool = None
        if spool_dir is not None:
            from gibbs_student_t_tpu.utils.spool import ChainSpool

            spool = ChainSpool(spool_dir, seed, resume=resume,
                               resume_at=start_sweep if resume else None,
                               record_mode=self.template.record_mode,
                               record_thin=thin,
                               extra_meta={"n_toa": self.n_toa.tolist()})
        records = []
        fields = self.template._record_fields
        n_reinits0 = (int(spool.load_run_stats().get("n_reinits", 0))
                      if spool is not None and resume else 0)
        tele_acc = TelemetryAccumulator() if self._telemetry else None

        def flush(recs, chunk_state, sweep_end, n_reinits):
            recs, tl = recs
            if tele_acc is not None and tl is not None:
                summary = tele_acc.add(jax.device_get(tl))
                if self.metrics is not None:
                    tele_acc.emit_chunk(self.metrics, sweep_end, summary)
            # n_last: ensemble records are padded to n_max (stacked
            # models), not the template pulsar's own TOA count
            host = self.template._materialize(
                jax.device_get(recs), n_last=int(self.stacked.y.shape[-1]))
            if spool is not None:
                # (P, C, rows, ...) -> (rows, P, C, ...): spool rows are
                # RECORDED rows (one per record_thin sweeps), exactly
                # like the single-model backend
                spool.append(
                    {f: np.moveaxis(host[i], 2, 0)
                     for i, f in enumerate(fields)},
                    chunk_state, sweep_end,
                    run_stats=({"n_reinits": n_reinits}
                               if reinit_diverged else None))
            else:
                records.append(host)

        # double-buffering/sequential-reinit orchestration shared with
        # JaxGibbs.sample (backends/jax_backend.py chunked_sweep_loop)
        mh = self.template.config.mh
        state, n_reinits = chunked_sweep_loop(
            state, niter, self.chunk_size, start_sweep,
            step_fn=lambda st, off, ln: self._step(st, keys, off,
                                                   length=ln),
            flush_fn=flush,
            pre_chunk_fn=self._prop_cov_fn,
            pre_chunk_until=mh.adapt_until if mh.adapt_cov else 0,
            reinit_fn=((lambda st, end: self._reinit_diverged(
                st, seed=seed + 7919 * end)) if reinit_diverged else None),
            n_reinits=n_reinits0,
            snapshot_fn=((lambda st: jax.tree.map(jnp.copy, st))
                         if self._donate and spool is not None else None))
        self.last_state = state
        if spool is not None:
            spool.close()
            from gibbs_student_t_tpu.utils.spool import load_spool

            res = load_spool(spool_dir)
        else:
            # (P, C, len, ...) -> (len, P, C, ...)
            cols = {
                f: np.concatenate([np.moveaxis(r[i], 2, 0)
                                   for r in records])
                for i, f in enumerate(fields)
            }
            res = self.template._to_result(cols)
        res.stats["n_toa"] = self.n_toa
        if reinit_diverged:
            res.stats["n_reinits"] = np.asarray(n_reinits)
        if tele_acc is not None and not tele_acc.empty:
            res.stats.update(tele_acc.stats())
        return res

    def sample_until(self, rhat_target: float = 1.01,
                     max_sweeps: int = 20000, check_every: int = 500,
                     seed: int = 0, state: Optional[ChainState] = None,
                     min_sweeps: int = 0,
                     min_ess: Optional[float] = None,
                     **sample_kwargs) -> ChainResult:
        """Ensemble convergence stopping: sample until EVERY pulsar's
        every parameter clears ``rhat_target`` (split-R-hat over that
        pulsar's chain axis) and, with ``min_ess``, holds that many
        pooled effective samples. Same loop and result semantics as
        ``JaxGibbs.sample_until`` (backends/jax_backend.py); the R-hat
        arrays in stats are shaped (npulsars, p)."""
        from gibbs_student_t_tpu.backends.jax_backend import (
            _ess_per_param,
            _rhat_per_param,
            _sample_until_loop,
        )

        def rhat_of(window):
            # window: (rows, npulsars, nchains, p) -> (npulsars, p)
            return np.array([_rhat_per_param(window[:, pl])
                             for pl in range(window.shape[1])])

        def ess_of(window):
            return np.array([_ess_per_param(window[:, pl])
                             for pl in range(window.shape[1])])

        def sample_fn(length, st, start):
            return self.sample(niter=length, seed=seed, state=st,
                               start_sweep=start, **sample_kwargs)

        return _sample_until_loop(
            sample_fn, lambda: self.last_state,
            self.template.record_thin, rhat_of, rhat_target,
            max_sweeps, check_every, min_sweeps, state,
            spool_mode=bool(sample_kwargs.get("spool_dir")),
            ess_of=ess_of, min_ess=min_ess)

    # -- divergence recovery ------------------------------------------------

    @staticmethod
    @jax.jit
    def _diverged_mask_device(state: ChainState):
        """(npulsars, nchains) bool of numerically dead populations —
        the ensemble form of JaxGibbs._diverged_mask_device (only the
        mask crosses to host)."""
        def bad(a):
            return ~jnp.isfinite(a).reshape(
                a.shape[0], a.shape[1], -1).all(axis=2)

        return (bad(state.x) | bad(state.b) | bad(state.theta[..., None])
                | bad(state.alpha) | bad(state.df[..., None])
                | (state.alpha <= 0).reshape(
                    state.alpha.shape[0], state.alpha.shape[1], -1
                ).any(axis=2))

    def diverged_mask(self, state: ChainState) -> np.ndarray:
        state = jax.tree.map(jnp.asarray, state)
        return np.asarray(self._diverged_mask_device(state))

    def _reinit_diverged(self, state: ChainState, seed: int
                         ) -> tuple:
        """Replace dead (pulsar, chain) entries with fresh prior draws;
        healthy populations are untouched bitwise (chain-level elastic
        recovery, SURVEY.md §5)."""
        bad = self.diverged_mask(state)
        n_bad = int(bad.sum())
        if n_bad == 0:
            return state, 0
        return merge_reinit(state, bad, self.init_state(seed=seed),
                            batch_ndim=2), n_bad
