"""Recycling Gibbs: partial-scan states as extra posterior rows.

Recycling Gibbs (arXiv:1611.07056) observes that a systematic-scan
Gibbs sampler leaves the target invariant after EVERY block update, not
just at scan boundaries — so the intermediate ("partial-scan") states
the sweep already computes are valid posterior samples, and averaging
estimators over all of them can only lower variance (the paper's Thm 1
Rao-Blackwellization argument over the scan ordering).

This sampler's scan updates each recorded field in exactly one block
per sweep (backends/jax_backend.py ``_sweep``: white-x → hyper-x → b →
θ → z → α → ν), which has a consequence this module exploits and its
docs are honest about:

- **The partial-scan states are free.** A mid-scan state's fields are
  each equal to the SAME field in an adjacent recorded scan-end row:
  blocks already updated this sweep carry the next row's value, blocks
  not yet updated carry the previous row's. The recycled rows are
  therefore *reconstructed* from the recorded chain — zero extra
  kernel work, zero extra wire bytes (the reason recycling is
  "near-free" for systematic scans).
- **Per-parameter marginals gain no new draws.** Each coordinate takes
  one new value per sweep whether or not partial states are kept, so
  per-param ESS is unchanged (pinned in tests/test_recycle.py) — the
  streaming monitor's per-param ESS verdicts deliberately ignore
  recycled rows. The genuine variance reduction is on **cross-block
  functionals** (e.g. outlier-count × noise-amplitude moments): the
  recycled stream averages over combinations like (x', z) that the
  scan-end stream never materializes, which is exactly the estimator
  family the paper's experiments improve.

The serve drain tags recycled rows with a row-class array
(``ROW_SCAN_END`` / ``ROW_RECYCLED``) so spool / ``on_chunk`` / result
consumers keep their sweep-aligned contracts untouched and opt into
the interleaved view through :func:`interleave` /
:func:`recycled_result`.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from gibbs_student_t_tpu.backends.jax_backend import (
    RECYCLE_EARLY_FIELDS,
    RECYCLE_LATE_FIELDS,
)

#: row-class codes (uint8): a recorded scan-end state vs a
#: reconstructed partial-scan ("recycled") state
ROW_SCAN_END = 0
ROW_RECYCLED = 1

#: result-field name → record-field name (utils/spool._CHAIN_KEYS,
#: inverted) for :func:`recycled_result`
_RESULT_KEYS = {
    "chain": "x", "bchain": "b", "zchain": "z", "thetachain": "theta",
    "alphachain": "alpha", "dfchain": "df", "poutchain": "pout",
}


def row_class_pattern(rows: int, carry_in: bool) -> np.ndarray:
    """The (2*rows-1(+1),) uint8 row-class tag for one drained quantum
    of ``rows`` scan-end rows: scan-end rows interleaved with the
    recycled mid-scan rows BETWEEN them. ``carry_in`` prepends the
    boundary mid-row that straddles the previous quantum's last row
    (the cross-quantum tail the serve drain carries) — the recycled
    stream is then a strict prefix of an uninterrupted run's (the
    cancel/evict contract, tests/test_recycle.py)."""
    if rows < 1:
        return np.zeros(0, np.uint8)
    out = np.zeros(2 * rows - 1 + (1 if carry_in else 0), np.uint8)
    out[(1 if carry_in else 0) + 1::2] = ROW_RECYCLED
    if carry_in:
        out[0] = ROW_RECYCLED
    return out


def interleave(cols: Dict[str, np.ndarray],
               prev_tail: Optional[Dict[str, np.ndarray]] = None,
               ) -> Tuple[Dict[str, np.ndarray], np.ndarray,
                          Dict[str, np.ndarray]]:
    """Build the recycled (interleaved) view of one span of rows-major
    records ``{field: (rows, nchains, ...)}``.

    Returns ``(cols_out, row_class, tail)``: ``cols_out`` has
    ``2*rows-1`` rows (``+1`` with a ``prev_tail``) alternating
    scan-end and recycled partial-scan states; ``row_class`` tags them;
    ``tail`` is the last scan-end row per field — feed it back as the
    next span's ``prev_tail`` to keep the stream seamless across
    quantum boundaries. A recycled row takes EARLY-group fields (x, b,
    acceptance — updated before the partial-scan point) from the NEXT
    scan-end row and LATE-group fields (θ, z, α, pout, ν) from the
    PREVIOUS one. Fields outside both groups (unknown extras) follow
    the late group (conservative: a consumer sees them change only at
    scan boundaries)."""
    fields = list(cols)
    rows = len(next(iter(cols.values()))) if fields else 0
    if rows == 0:
        return dict(cols), np.zeros(0, np.uint8), dict(prev_tail or {})
    carry = prev_tail is not None and bool(prev_tail)
    out = {}
    for f, a in cols.items():
        a = np.asarray(a)
        n_out = 2 * rows - 1 + (1 if carry else 0)
        buf = np.empty((n_out,) + a.shape[1:], a.dtype)
        base = 0
        if carry:
            # boundary mid-row: early fields from THIS span's first
            # row, late fields from the previous span's final row
            buf[0] = (a[0] if f in RECYCLE_EARLY_FIELDS
                      else prev_tail[f])
            base = 1
        buf[base::2] = a
        if rows > 1:
            if f in RECYCLE_EARLY_FIELDS:
                buf[base + 1::2] = a[1:]
            else:
                buf[base + 1::2] = a[:-1]
        out[f] = buf
    tail = {f: np.array(np.asarray(a)[-1]) for f, a in cols.items()}
    return out, row_class_pattern(rows, carry), tail


def recycle_weights(row_class: np.ndarray) -> np.ndarray:
    """Per-row weights of the recycling estimator over an interleaved
    stream: uniform over all partial-scan states (the paper's equal-
    weight average over the scan ordering), normalized to sum to 1.
    Shaped for broadcasting against ``(rows, ...)`` windows."""
    row_class = np.asarray(row_class)
    n = row_class.shape[0]
    if n == 0:
        return np.zeros(0)
    return np.full(n, 1.0 / n)


def weighted_moments(window: np.ndarray, weights: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Weighted (mean, variance) over the leading row axis — the
    recycling estimator's moment form (weights from
    :func:`recycle_weights`). Plain uniform weights reproduce
    ``window.mean(axis=0)`` / ``window.var(axis=0)`` exactly."""
    window = np.asarray(window, np.float64)
    w = np.asarray(weights, np.float64)
    w = w / w.sum()
    wb = w.reshape((-1,) + (1,) * (window.ndim - 1))
    mean = (wb * window).sum(axis=0)
    var = (wb * (window - mean) ** 2).sum(axis=0)
    return mean, var


def functional_ess(values: np.ndarray) -> float:
    """ESS of a scalar functional's sample stream ``(rows,)`` or
    ``(rows, nchains)`` — evaluate a cross-block functional on the
    interleaved stream vs the scan-end stream to measure the recycling
    multiplier (tools/serve_bench.py's recycle block)."""
    from gibbs_student_t_tpu.parallel.diagnostics import (
        effective_sample_size,
    )

    return effective_sample_size(np.asarray(values, np.float64))


def recycled_result(res) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """The interleaved recycled view of a finished
    ``ChainResult``: ``({field: (rows', nchains, ...)}, row_class)``
    over every non-empty chain field. The result's own arrays are
    untouched (the result contract: chain arrays are scan-end rows,
    bitwise identical with the gate off)."""
    cols = {}
    for res_key, field in _RESULT_KEYS.items():
        a = np.asarray(getattr(res, res_key))
        if a.size:
            cols[field] = a
    return interleave(cols)[:2]
