"""Multi-host (DCN-tier) execution: initialization and hybrid meshes.

The reference has no distributed backend at all (SURVEY.md §2.3 — no
NCCL/MPI/sockets; one process, one chain). This framework's communication
backend is XLA's: collectives are compiled into the program and ride ICI
within a slice and DCN across hosts. This module is the process-level
runtime around that — the moral equivalent of the reference ecosystem's
``torch.distributed``/NCCL bootstrap, but as thin coordination glue, since
the data plane belongs to XLA.

Placement policy for this workload (SURVEY.md §2.3): chains are
embarrassingly parallel and all-reduce only in diagnostics, so the
``chain`` axis lives on ICI (within-slice); pulsar ensembles have *no*
cross-pulsar terms, so the ``pulsar`` axis is the one that may span DCN —
its collectives are diagnostics-only and latency-tolerant.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Bring up the JAX distributed runtime for multi-host execution.

    Arguments default to the standard env vars
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``
    or a cloud-TPU metadata environment, in which case
    ``jax.distributed.initialize`` auto-detects everything). Returns True
    if a multi-process runtime was initialized, False for the
    single-process fallback — callers can treat both uniformly because a
    1-host "ensemble" is just the degenerate mesh.
    """
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes in (None, 1):
        return False  # single host, nothing to coordinate
    try:
        # coordinator_address may legitimately be None here: on cloud-TPU /
        # Slurm / GKE, jax auto-detects unset params from the cluster env.
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError) as e:
        if coordinator_address is None:
            raise ValueError(
                f"num_processes={num_processes} with no coordinator "
                "address and no detectable cluster environment — pass "
                "coordinator_address= or set JAX_COORDINATOR_ADDRESS"
            ) from e
        raise
    return True


def make_hybrid_mesh(ici_axes: Dict[str, int],
                     dcn_axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Mesh whose ``dcn_axes`` span hosts and ``ici_axes`` stay in-slice.

    ``make_hybrid_mesh({'chain': 8}, {'pulsar': 4})`` on a 4-host x
    8-chip pod slice places each pulsar group on one host (collectives
    across pulsars cross DCN — diagnostics only) and shards chains over
    the chips of that host (ICI). Falls back to a plain mesh when running
    single-process (dcn product must then be 1 or divide the local device
    count).
    """
    dcn_axes = dcn_axes or {}
    n_proc = jax.process_count()
    axis_names = tuple(dcn_axes.keys()) + tuple(ici_axes.keys())
    if n_proc > 1:
        from jax.experimental import mesh_utils

        dcn_shape = tuple(dcn_axes.values()) + (1,) * len(ici_axes)
        ici_shape = (1,) * len(dcn_axes) + tuple(ici_axes.values())
        try:
            devices = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=jax.devices())
        except ValueError:
            # Platforms without ICI-slice structure (multi-process CPU —
            # the fake-cluster test rig — or single-slice pods): the
            # process is the DCN granule.
            devices = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=jax.devices(),
                process_is_granule=True)
        return Mesh(devices, axis_names)
    # single process: all axes are local; order DCN-first so the slowest
    # axis varies slowest exactly as it would across hosts
    shape = tuple(dcn_axes.values()) + tuple(ici_axes.values())
    devices = jax.devices()
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh {dict(**dcn_axes, **ici_axes)} needs "
            f"{int(np.prod(shape))} devices, have {len(devices)}")
    return Mesh(np.asarray(devices).reshape(shape), axis_names)


def local_shard(n_items: int, axis_size: int,
                axis_index: Optional[int] = None) -> slice:
    """Contiguous slice of ``n_items`` owned by this host along a DCN axis
    — the per-process data-loading contract (each host reads only its own
    pulsars' par/tim files; arrays then enter the sharded computation via
    ``jax.make_array_from_process_local_data``).
    """
    if axis_index is None:
        axis_index = jax.process_index() % axis_size
    per = -(-n_items // axis_size)
    start = axis_index * per
    return slice(start, min(start + per, n_items))
