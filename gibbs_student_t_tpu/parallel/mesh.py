"""Mesh construction helpers."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes: Dict[str, int],
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``Mesh`` with named axes, e.g. ``{'pulsar': 2, 'chain': 4}``.

    The axis product must equal the device count. Device order follows
    ``jax.devices()`` reshaped row-major, which keeps the fastest-varying
    axis (put ``'chain'`` last) on ICI-adjacent devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = tuple(axes.values())
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh {axes} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axes.keys()))
