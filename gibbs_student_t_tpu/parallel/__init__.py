"""Device parallelism: meshes, sharded ensemble sampling, diagnostics.

The reference has no distributed execution at all (SURVEY.md §2.3 — no
NCCL/MPI/multiprocessing; a single sequential loop). The workload's
parallel structure is chains x pulsars, both embarrassingly parallel; the
TPU-native mapping is a ``jax.sharding.Mesh`` over ``('pulsar', 'chain')``
with ``shard_map``, XLA inserting collectives only for cross-chain
diagnostics (R-hat/ESS), which ride ICI — never for the sweep itself.
"""

from gibbs_student_t_tpu.parallel.mesh import make_mesh
from gibbs_student_t_tpu.parallel.ensemble import EnsembleGibbs, stack_model_arrays
from gibbs_student_t_tpu.parallel.diagnostics import (
    effective_sample_size,
    gelman_rubin,
    split_rhat,
)
from gibbs_student_t_tpu.parallel.multihost import (
    initialize_distributed,
    local_shard,
    make_hybrid_mesh,
)

__all__ = [
    "make_mesh",
    "EnsembleGibbs",
    "stack_model_arrays",
    "effective_sample_size",
    "gelman_rubin",
    "split_rhat",
    "initialize_distributed",
    "local_shard",
    "make_hybrid_mesh",
]
