"""Convergence diagnostics: ESS, Gelman-Rubin R-hat, collective variants.

The reference tracks no diagnostics at all — not even MH acceptance
(SURVEY.md §5). With a chain axis on device, cross-chain statistics are
where the ``effective-samples/sec`` north-star metric comes from; the
``*_collective`` form runs inside ``shard_map`` with a ``psum`` over the
sharded chain axis (the only collective in the framework — chains are
otherwise independent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def autocorr_time_batch(x: np.ndarray, c: float = 5.0) -> np.ndarray:
    """Integrated autocorrelation times of ``(niter, k)`` chains (Sokal
    windowing), one batched FFT over all ``k`` columns.

    The convergence-stopping loop calls this every ``check_every``
    sweeps on up to nchains x nparams columns; the per-column Python
    loop it replaces paid one small rfft/irfft pair per column
    (~17k FFT calls per check at 1024 chains x 17 params)."""
    x = np.asarray(x, dtype=np.float64)
    n, k = x.shape
    # Column blocks bound the peak footprint: the FFT intermediates are
    # O(n x block) float64/complex128, and an unblocked call at the
    # scale this exists for (1024 chains x 17 params x long windows)
    # would spike several GB on the 1-core host. ~70 FFT calls instead
    # of ~17k still amortizes away the per-call overhead.
    block = max(1, min(k, (1 << 22) // max(n, 1)))  # ~32 MB per buffer
    out = np.empty(k)
    for j0 in range(0, k, block):
        xb = x[:, j0:j0 + block]
        kb = xb.shape[1]
        scale = np.abs(xb).max(axis=0)
        xb = xb - xb.mean(axis=0)
        # FFT autocorrelation, all columns of the block at once
        f = np.fft.rfft(xb, n=2 * n, axis=0)
        acf = np.fft.irfft(f * np.conj(f), axis=0)[:n]
        a0 = acf[0].copy()
        # Constant column: tau := 1. The check is a RELATIVE threshold,
        # not a0 == 0 — centering a constant column leaves
        # O(n*eps*scale) summation residue (whose acf is perfectly
        # correlated noise that would report tau ~ n), and whether it
        # cancels exactly depends on the mean's summation order over
        # the strided axis.
        dead = a0 <= n * (64 * np.finfo(np.float64).eps * scale) ** 2
        acf /= np.where(dead, 1.0, a0)
        tau = 2.0 * np.cumsum(acf, axis=0) - 1.0
        window = np.arange(n)[:, None] >= c * tau
        has = window.any(axis=0)
        idx = np.where(has, np.argmax(window, axis=0), n - 1)
        taus = np.maximum(tau[idx, np.arange(kb)], 1.0)
        out[j0:j0 + block] = np.where(dead, 1.0, taus)
    return out


def autocorr_time(x: np.ndarray, c: float = 5.0) -> float:
    """Integrated autocorrelation time of a 1-D chain (Sokal windowing)."""
    x = np.asarray(x, dtype=np.float64)
    return float(autocorr_time_batch(x[:, None], c)[0])


def ess_per_param(window: np.ndarray,
                  row_class: np.ndarray | None = None) -> np.ndarray:
    """(p,) total effective sample size per parameter over a
    (rows, nchains, p) window: chains pooled, each discounted by its
    autocorrelation time, all nchains*p columns in one batched FFT.

    ``row_class`` (parallel/recycle.py) marks recycled partial-scan
    rows in an interleaved window; they are DROPPED here before the
    autocorrelation pass. Each coordinate updates once per scan, so a
    recycled row duplicates its per-param value from an adjacent
    scan-end row — keeping duplicates would double the row count AND
    the measured τ, an estimator no-op paid for with a 2× FFT
    (recycling buys cross-block moments, never per-param ESS; see
    recycle.py's module docs, pinned in tests/test_recycle.py)."""
    window = np.asarray(window, dtype=np.float64)
    if row_class is not None:
        from gibbs_student_t_tpu.parallel.recycle import ROW_SCAN_END

        window = window[np.asarray(row_class) == ROW_SCAN_END]
    rows, nchains, p = window.shape
    taus = autocorr_time_batch(window.reshape(rows, nchains * p))
    return (rows / taus).reshape(nchains, p).sum(axis=0)


def effective_sample_size(chains: np.ndarray) -> float:
    """ESS of ``(niter,)`` or ``(niter, nchains)`` samples: pooled over
    independent chains, each discounted by its autocorrelation time."""
    chains = np.atleast_2d(np.asarray(chains, dtype=np.float64).T).T
    taus = autocorr_time_batch(chains)
    return float((chains.shape[0] / taus).sum())


def gelman_rubin_per_param(chains: np.ndarray) -> np.ndarray:
    """(p,) potential scale reduction R-hat over ``(niter, nchains, p)``
    samples — one vectorized pass over the parameter axis. The scalar
    :func:`gelman_rubin` is this with ``p == 1`` (pinned equal in
    tests/test_obs.py), so the per-parameter loop ``obs/health.py`` and
    the serving convergence monitor used to pay is a single reduction."""
    chains = np.asarray(chains, dtype=np.float64)
    n = chains.shape[0]
    means = chains.mean(axis=0)                       # (m, p)
    W = chains.var(axis=0, ddof=1).mean(axis=0)       # (p,)
    B = n * means.var(axis=0, ddof=1)                 # (p,)
    var_plus = (n - 1) / n * W + B / n
    return np.sqrt(var_plus / W)


def gelman_rubin(chains: np.ndarray) -> float:
    """Potential scale reduction R-hat over ``(niter, nchains)`` samples."""
    chains = np.asarray(chains, dtype=np.float64)
    return float(gelman_rubin_per_param(chains[:, :, None])[0])


def split_rhat_per_param(window: np.ndarray,
                         row_class: np.ndarray | None = None
                         ) -> np.ndarray:
    """(p,) split-R-hat over a ``(rows, nchains, p)`` window: every
    chain halved (within-chain drift shows up as cross-half spread),
    all parameters in one batched :func:`gelman_rubin_per_param`.
    ``row_class`` drops recycled partial-scan rows first (the
    :func:`ess_per_param` duplicate argument — per-param spread gains
    nothing from rows whose per-param values repeat their
    neighbours')."""
    window = np.asarray(window, dtype=np.float64)
    if row_class is not None:
        from gibbs_student_t_tpu.parallel.recycle import ROW_SCAN_END

        window = window[np.asarray(row_class) == ROW_SCAN_END]
    n = window.shape[0] // 2
    split = np.concatenate([window[:n], window[n:2 * n]], axis=1)
    return gelman_rubin_per_param(split)


def split_rhat(chains: np.ndarray) -> float:
    """Rank-normalization-free split-R-hat: halves each chain to detect
    within-chain drift."""
    chains = np.asarray(chains, dtype=np.float64)
    return float(split_rhat_per_param(chains[:, :, None])[0])


def rhat_collective(x, axis_name: str):
    """Per-parameter R-hat across a device-sharded chain axis, computed with
    ``psum`` collectives inside ``shard_map``.

    ``x`` is ``(local_chains, niter)`` samples of one scalar parameter on
    this device; the chain axis is sharded over ``axis_name``.
    """
    n = x.shape[1]
    local_means = x.mean(axis=1)                      # (local_chains,)
    local_vars = x.var(axis=1, ddof=1)
    m = jax.lax.psum(x.shape[0] * jnp.ones(()), axis_name)
    mean_sum = jax.lax.psum(local_means.sum(), axis_name)
    grand = mean_sum / m
    W = jax.lax.psum(local_vars.sum(), axis_name) / m
    B = n * jax.lax.psum(((local_means - grand) ** 2).sum(),
                         axis_name) / (m - 1.0)
    var_plus = (n - 1.0) / n * W + B / n
    return jnp.sqrt(var_plus / W)
