"""Convergence diagnostics: ESS, Gelman-Rubin R-hat, collective variants.

The reference tracks no diagnostics at all — not even MH acceptance
(SURVEY.md §5). With a chain axis on device, cross-chain statistics are
where the ``effective-samples/sec`` north-star metric comes from; the
``*_collective`` form runs inside ``shard_map`` with a ``psum`` over the
sharded chain axis (the only collective in the framework — chains are
otherwise independent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def autocorr_time(x: np.ndarray, c: float = 5.0) -> float:
    """Integrated autocorrelation time of a 1-D chain (Sokal windowing)."""
    x = np.asarray(x, dtype=np.float64)
    n = len(x)
    x = x - x.mean()
    # FFT autocorrelation
    f = np.fft.rfft(x, n=2 * n)
    acf = np.fft.irfft(f * np.conj(f))[:n]
    if acf[0] == 0:
        return 1.0
    acf /= acf[0]
    tau = 2.0 * np.cumsum(acf) - 1.0
    window = np.arange(n) >= c * tau
    idx = np.argmax(window) if window.any() else n - 1
    return float(max(tau[idx], 1.0))


def effective_sample_size(chains: np.ndarray) -> float:
    """ESS of ``(niter,)`` or ``(niter, nchains)`` samples: pooled over
    independent chains, each discounted by its autocorrelation time."""
    chains = np.atleast_2d(np.asarray(chains, dtype=np.float64).T).T
    ess = 0.0
    for k in range(chains.shape[1]):
        tau = autocorr_time(chains[:, k])
        ess += chains.shape[0] / tau
    return float(ess)


def gelman_rubin(chains: np.ndarray) -> float:
    """Potential scale reduction R-hat over ``(niter, nchains)`` samples."""
    chains = np.asarray(chains, dtype=np.float64)
    n, m = chains.shape
    means = chains.mean(axis=0)
    W = chains.var(axis=0, ddof=1).mean()
    B = n * means.var(ddof=1)
    var_plus = (n - 1) / n * W + B / n
    return float(np.sqrt(var_plus / W))


def split_rhat(chains: np.ndarray) -> float:
    """Rank-normalization-free split-R-hat: halves each chain to detect
    within-chain drift."""
    chains = np.asarray(chains, dtype=np.float64)
    n = chains.shape[0] // 2
    split = np.concatenate([chains[:n], chains[n:2 * n]], axis=1)
    return gelman_rubin(split)


def rhat_collective(x, axis_name: str):
    """Per-parameter R-hat across a device-sharded chain axis, computed with
    ``psum`` collectives inside ``shard_map``.

    ``x`` is ``(local_chains, niter)`` samples of one scalar parameter on
    this device; the chain axis is sharded over ``axis_name``.
    """
    n = x.shape[1]
    local_means = x.mean(axis=1)                      # (local_chains,)
    local_vars = x.var(axis=1, ddof=1)
    m = jax.lax.psum(x.shape[0] * jnp.ones(()), axis_name)
    mean_sum = jax.lax.psum(local_means.sum(), axis_name)
    grand = mean_sum / m
    W = jax.lax.psum(local_vars.sum(), axis_name) / m
    B = n * jax.lax.psum(((local_means - grand) ** 2).sum(),
                         axis_name) / (m - 1.0)
    var_plus = (n - 1.0) / n * W + B / n
    return jnp.sqrt(var_plus / W)
