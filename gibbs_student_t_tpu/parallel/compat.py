"""Version-tolerant ``shard_map``.

The installed jax moved ``shard_map`` twice: old releases expose it only
as ``jax.experimental.shard_map`` (replication checking spelled
``check_rep``), newer ones promote it to ``jax.shard_map`` and rename the
flag ``check_vma``. Every in-repo caller goes through this wrapper so the
sharded ensemble (parallel/ensemble.py) and the collective-diagnostic
tests import cleanly on either API.
"""

from __future__ import annotations

import functools

try:  # jax >= 0.6: top-level export, check_vma spelling
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


@functools.wraps(_shard_map)
def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``shard_map`` with the new-API surface on any installed jax.

    ``check_vma`` maps onto the installed API's replication-check flag
    (``check_rep`` on pre-promotion releases — same semantics, renamed).
    """
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})


__all__ = ["shard_map"]
