"""Streaming chain output: spool sampler records to disk chunk by chunk.

The reference accumulates every chain array in RAM for the whole run and
writes once at the end (reference gibbs.py:344-350, run_sims.py:118-124) —
a killed 10k-sweep run loses everything, and a 1024-chain run would hold
``niter x nchains x n`` floats live. A :class:`ChainSpool` instead appends
each device chunk to native append-only spool files (``native.SpoolWriter``)
and checkpoints the state pytree, so host memory stays O(chunk) and a
killed run resumes from the last chunk boundary.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np

from gibbs_student_t_tpu.backends.base import ChainResult
from gibbs_student_t_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

_CHAIN_KEYS = {
    "x": "chain", "b": "bchain", "z": "zchain", "theta": "thetachain",
    "alpha": "alphachain", "df": "dfchain", "pout": "poutchain",
}


class ChainSpool:
    """Directory of per-field spool files plus a rolling state checkpoint."""

    def __init__(self, path: str, seed: int, resume: bool = False,
                 resume_at: Optional[int] = None,
                 record_mode: Optional[str] = None,
                 record_thin: int = 1,
                 recycle: Optional[bool] = None,
                 extra_meta: Optional[Dict] = None,
                 fault_key=None):
        """``resume=True`` appends to an existing spool directory (after a
        kill: ``load_spool_state`` -> ``sample(state=..., start_sweep=...,
        spool_dir=...)``) instead of truncating it. ``resume_at`` is the
        checkpointed sweep index being resumed from; rows past it (orphans
        from a crash mid-append) are truncated away before appending.
        ``record_mode`` is persisted in ``meta.json`` so a spooled run's
        transport quantization (record="compact") stays discoverable; a
        resume with a different mode is rejected. ``recycle`` persists
        the serving recycle tagging (parallel/recycle.py) the same way:
        the spool always stores SCAN-END rows only (recycled rows are
        reconstructible, so storing them would double every byte for
        nothing), but a consumer reconstructing the recycled stream
        must know the run's mode — so a resume that flips it
        mid-stream is rejected (``None`` skips the check: solo runs
        predating the flag). ``fault_key`` is the
        serve fault-injection identity (serve/faults.py): when set, the
        ``spool_io`` / ``kill_before_checkpoint`` /
        ``kill_after_checkpoint`` injection points arm inside
        :meth:`append` — deterministic stand-ins for a disk-full error
        and a process kill straddling the checkpoint write."""
        from gibbs_student_t_tpu import native

        if not native.available():
            raise RuntimeError(
                "chain spooling needs the native library (make -C native)")
        self._native = native
        self.path = path
        self.seed = seed
        self.resume = resume
        self.resume_at = resume_at
        self.record_mode = record_mode
        self.recycle = recycle
        # spool rows are RECORDED sweeps: with thinning, one row per
        # record_thin sweeps — sweep-indexed bookkeeping (base/resume_at)
        # divides by this to reach row counts
        self.record_thin = int(record_thin)
        # JSON-able run-level metadata (e.g. the ensemble's per-pulsar
        # real TOA counts) replayed into ChainResult.stats by load_spool
        self.extra_meta = extra_meta
        self.fault_key = fault_key
        self._writers: Optional[Dict[str, object]] = None
        os.makedirs(path, exist_ok=True)

    def append(self, records: Dict[str, np.ndarray], state, sweep: int,
               run_stats: Optional[Dict] = None) -> None:
        """``records[field]`` is ``(chunk_len, nchains, ...)``; ``sweep`` is
        the index of the first sweep *after* this chunk (the resume point).
        ``run_stats`` (e.g. the running re-init count) is persisted
        alongside the checkpoint so resumed runs keep cumulative
        counters."""
        if self.fault_key is not None:
            from gibbs_student_t_tpu.serve import faults as _faults

            _faults.fire("spool_io", tenant=self.fault_key)
        if self._writers is None:
            meta_path = os.path.join(self.path, "meta.json")
            chunk_len = len(next(iter(records.values())))
            keep_rows = None
            if self.resume and os.path.exists(meta_path):
                with open(meta_path) as fh:
                    meta = json.load(fh)
                if meta["fields"] != sorted(records):
                    raise ValueError(
                        f"resume record fields {sorted(records)} do not "
                        f"match the spooled run's {meta['fields']}; use "
                        "the same record= mode to resume")
                prior_mode = meta.get("record_mode")
                if (self.record_mode is not None and prior_mode is not None
                        and prior_mode != self.record_mode):
                    raise ValueError(
                        f"resume record mode {self.record_mode!r} does not "
                        f"match the spooled run's {prior_mode!r}")
                if meta.get("record_thin", 1) != self.record_thin:
                    raise ValueError(
                        f"resume record_thin {self.record_thin} does not "
                        f"match the spooled run's "
                        f"{meta.get('record_thin', 1)}")
                prior_rec = meta.get("recycle")
                if (self.recycle is not None and prior_rec is not None
                        and bool(prior_rec) != bool(self.recycle)):
                    raise ValueError(
                        f"resume recycle={bool(self.recycle)} does not "
                        f"match the spooled run's {bool(prior_rec)}; a "
                        "mid-stream flip would desync downstream "
                        "row-class reconstruction "
                        "(parallel/recycle.py)")
                base = meta.get("base", 0)
                if self.resume_at is not None:
                    if (self.resume_at - base) % self.record_thin:
                        raise ValueError(
                            f"resume_at={self.resume_at} is not on a "
                            f"recorded-sweep boundary (base {base}, "
                            f"thin {self.record_thin})")
                    keep_rows = (self.resume_at - base) // self.record_thin
                    if keep_rows < 0:
                        raise ValueError(
                            f"resume_at={self.resume_at} predates the "
                            f"spool's first sweep ({base})")
            else:
                base = sweep - chunk_len * self.record_thin
                with open(meta_path, "w") as fh:
                    json.dump({"fields": sorted(records),
                               "seed": self.seed, "base": base,
                               "record_mode": self.record_mode,
                               "record_thin": self.record_thin,
                               "recycle": self.recycle,
                               "extra": self.extra_meta or {}}, fh)
            self._writers = {
                f: self._native.SpoolWriter(
                    os.path.join(self.path, f + ".spool"),
                    trailing_shape=a.shape[1:], dtype=a.dtype,
                    append=self.resume, keep_rows=keep_rows)
                for f, a in records.items()
            }
        for f, a in records.items():
            self._writers[f].append(a)
            self._writers[f].flush()
        if self.fault_key is not None:
            from gibbs_student_t_tpu.serve import faults as _faults

            # records are flushed but the checkpoint is NOT yet: a kill
            # here leaves orphan rows past the last checkpoint, which
            # resume truncates (the crash-recovery "before" arm)
            _faults.fire("kill_before_checkpoint", tenant=self.fault_key)
        save_checkpoint(os.path.join(self.path, "state.npz"), state,
                        sweep, self.seed)
        if self.fault_key is not None:
            from gibbs_student_t_tpu.serve import faults as _faults

            # checkpoint written: a kill here resumes from THIS quantum
            # boundary (the "after" arm)
            _faults.fire("kill_after_checkpoint", tenant=self.fault_key)
        if run_stats is not None:
            tmp = os.path.join(self.path, "run_stats.json.tmp")
            with open(tmp, "w") as fh:
                json.dump(run_stats, fh)
            os.replace(tmp, os.path.join(self.path, "run_stats.json"))

    def close(self) -> None:
        if self._writers is not None:
            for w in self._writers.values():
                w.close()
            self._writers = None

    def load_run_stats(self) -> Dict:
        """Persisted cumulative run counters from a prior (interrupted)
        run in this spool directory, or {} for a fresh one."""
        return load_run_stats(self.path)


def load_run_stats(path: str) -> Dict:
    stats_path = os.path.join(path, "run_stats.json")
    if not os.path.exists(stats_path):
        return {}
    with open(stats_path) as fh:
        return json.load(fh)


def load_spool(path: str) -> ChainResult:
    """Reassemble a :class:`ChainResult` from a spool directory (including
    the readable prefix of an interrupted run)."""
    from gibbs_student_t_tpu import native

    with open(os.path.join(path, "meta.json")) as fh:
        meta = json.load(fh)
    cols = {f: native.read_spool(os.path.join(path, f + ".spool"))
            for f in meta["fields"]}
    # A kill mid-append can leave fields at different lengths; trim to the
    # common prefix so every array stays sweep-aligned.
    nmin = min(len(a) for a in cols.values())
    cols = {f: a[:nmin] for f, a in cols.items()}
    chains = {_CHAIN_KEYS[f]: cols.pop(f)
              for f in list(cols) if f in _CHAIN_KEYS}
    # fields not spooled (record="light" runs) come back empty
    empty = np.zeros((0,))
    for key in _CHAIN_KEYS.values():
        chains.setdefault(key, empty)
    if meta.get("record_mode") is not None:
        cols["record_mode"] = np.asarray(meta["record_mode"])
    if meta.get("record_thin", 1) != 1:
        cols["record_thin"] = np.asarray(meta["record_thin"])
    for k, v in meta.get("extra", {}).items():
        cols[k] = np.asarray(v)
    return ChainResult(**chains, stats=cols)


def load_spool_prefix(path: str, field: str, upto_sweep: int):
    """``(rows, base)`` for one spooled field: its recorded rows
    strictly below sweep ``upto_sweep`` (orphans from a crash
    mid-append excluded, exactly as a resume truncates them) plus the
    spool's base sweep — the prefix a resumed tenant's convergence
    monitor backfills from. ``None`` when the field was never spooled
    (record="light" runs) or no meta exists yet."""
    from gibbs_student_t_tpu import native

    meta_path = os.path.join(path, "meta.json")
    fpath = os.path.join(path, field + ".spool")
    if not (os.path.exists(meta_path) and os.path.exists(fpath)):
        return None
    with open(meta_path) as fh:
        meta = json.load(fh)
    if field not in meta.get("fields", []):
        return None
    base = meta.get("base", 0)
    keep = (upto_sweep - base) // meta.get("record_thin", 1)
    if keep <= 0:
        return None
    rows = native.read_spool(fpath)
    return rows[:min(keep, len(rows))], base


def load_spool_state(path: str):
    """(state, next_sweep, seed) from a spool directory's checkpoint."""
    return load_checkpoint(os.path.join(path, "state.npz"))
