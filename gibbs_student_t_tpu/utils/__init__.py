"""Utilities: chain persistence, checkpointing, timing."""

from gibbs_student_t_tpu.utils.checkpoint import load_checkpoint, save_checkpoint
from gibbs_student_t_tpu.utils.timing import BlockTimer

__all__ = ["save_checkpoint", "load_checkpoint", "BlockTimer"]
