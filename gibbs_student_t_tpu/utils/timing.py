"""Wall-clock instrumentation.

The reference's only observability is a progress line every 100 sweeps
(reference gibbs.py:382-385). ``BlockTimer`` adds per-block wall timing with
``block_until_ready`` fencing so device work is attributed correctly; it
is also the wall-clock source of the metrics registry
(``obs.metrics.MetricsRegistry.timer`` — ``registry.time(...)`` delegates
here and mirrors durations into histograms), so bench breakdowns and
telemetry snapshots share one timing implementation. XLA-level traces
live in ``obs/tracing.py`` (``trace_to`` / per-block ``gibbs/*`` spans).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict

import jax


class BlockTimer:
    def __init__(self):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    def time(self, name: str, fn, *args, **kwargs):
        """Run ``fn`` and attribute its device time to ``name``."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        self.totals[name] += dt
        self.counts[name] += 1
        return out

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {"total_s": self.totals[name], "calls": self.counts[name],
                   "mean_s": self.totals[name] / max(self.counts[name], 1)}
            for name in self.totals
        }

    def report(self) -> str:
        lines = []
        for name, s in sorted(self.summary().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{name:24s} {s['total_s']:8.3f}s "
                         f"({s['calls']}x, {s['mean_s'] * 1e3:.2f} ms)")
        return "\n".join(lines)
