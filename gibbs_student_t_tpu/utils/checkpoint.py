"""Checkpoint/resume for the sampler state pytree.

The reference has no checkpointing: a killed 10k-sweep run loses
everything (SURVEY.md §5; chains live in RAM, reference gibbs.py:344-350,
written once at the end, run_sims.py:118-124). Here the full sampler state
is the small per-chain :class:`ChainState` pytree plus a sweep counter, so
a checkpoint is one host transfer and one ``.npz``; resume is exact because
sweep keys derive from ``fold_in(chain_key, sweep_index)``
(tests/test_jax_backend.py::test_resume_matches_unbroken_run).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import numpy as np

from gibbs_student_t_tpu.backends.jax_backend import ChainState


def save_checkpoint(path: str, state: ChainState, sweep: int,
                    seed: int) -> None:
    arrays = {f: np.asarray(getattr(state, f)) for f in ChainState._fields}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez(tmp, sweep=sweep, seed=seed, **arrays)
    os.replace(tmp, path)  # atomic: no torn checkpoints on kill


def load_checkpoint(path: str) -> Tuple[ChainState, int, int]:
    """Returns (state, next_sweep_index, seed)."""
    with np.load(path) as data:
        state = ChainState(**{f: data[f] for f in ChainState._fields})
        return state, int(data["sweep"]), int(data["seed"])
