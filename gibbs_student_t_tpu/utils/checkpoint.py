"""Checkpoint/resume for the sampler state pytree.

The reference has no checkpointing: a killed 10k-sweep run loses
everything (SURVEY.md §5; chains live in RAM, reference gibbs.py:344-350,
written once at the end, run_sims.py:118-124). Here the full sampler state
is the small per-chain :class:`ChainState` pytree plus a sweep counter, so
a checkpoint is one host transfer and one ``.npz``; resume is exact because
sweep keys derive from ``fold_in(chain_key, sweep_index)``
(tests/test_jax_backend.py::test_resume_matches_unbroken_run).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import numpy as np

from gibbs_student_t_tpu.backends.jax_backend import ChainState


def save_checkpoint(path: str, state: ChainState, sweep: int,
                    seed: int) -> None:
    arrays = {f: np.asarray(getattr(state, f)) for f in ChainState._fields}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez(tmp, sweep=sweep, seed=seed, **arrays)
    os.replace(tmp, path)  # atomic: no torn checkpoints on kill


def load_checkpoint(path: str) -> Tuple[ChainState, int, int]:
    """Returns (state, next_sweep_index, seed).

    Checkpoints written before a ChainState field existed load with that
    field at its neutral value (currently: ``mh_log_scale`` zeros — the
    un-adapted jump scale), so old spools/checkpoints stay resumable."""
    with np.load(path) as data:
        vals = {}
        for f in ChainState._fields:
            if f in data:
                vals[f] = data[f]
            elif f == "mh_log_scale":
                vals[f] = np.zeros(data["x"].shape[:-1] + (2,),
                                   data["x"].dtype)
            elif f == "mh_cov_chol":
                # pre-adapt_cov checkpoint: the feature was off (it did
                # not exist), so the neutral empty factor is correct
                vals[f] = np.zeros(data["x"].shape[:-1] + (0,),
                                   data["x"].dtype)
            else:
                raise KeyError(f"checkpoint {path} lacks field {f!r}")
        state = ChainState(**vals)
        return state, int(data["sweep"]), int(data["seed"])
