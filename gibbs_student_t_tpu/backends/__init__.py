"""Sampler backends behind the SamplerBackend plugin seam.

Two implementations of the same blocked MH-within-Gibbs kernel
(reference gibbs.py:342-385):

- ``numpy``: single-chain host oracle, a cleaned Python-3 equivalent of the
  reference sampler — the correctness baseline for KS gates;
- ``jax``: the TPU-native jit+vmap kernel running many chains data-parallel.
"""

from gibbs_student_t_tpu.backends.base import ChainResult, SamplerBackend, get_backend
from gibbs_student_t_tpu.backends.numpy_backend import NumpyGibbs
from gibbs_student_t_tpu.backends.jax_backend import JaxGibbs

__all__ = ["SamplerBackend", "ChainResult", "get_backend", "NumpyGibbs", "JaxGibbs"]
