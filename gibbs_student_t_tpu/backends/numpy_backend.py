"""NumPy oracle backend: single-chain blocked MH-within-Gibbs on the host.

A cleaned, Python-3, explicitly-seeded equivalent of the reference sampler
(reference gibbs.py:8-385) running against :class:`ModelArrays` instead of
an enterprise PTA. This is the correctness oracle for the TPU kernel's KS
gates (SURVEY.md §4) and the ``--backend=cpu`` side of the plugin seam.

Deliberate deviations from the reference, all behavior-preserving or
bug-fixing (SURVEY.md §2.1 notes):

- the basis-coefficient draw always runs after the hyper block; the
  reference gates it on a buggy broadcast compare (gibbs.py:373) whose
  *intent* was "redraw iff the MH block moved" — always-redrawing is the
  plain Gibbs kernel and is what the guard reduces to in practice;
- ``b`` is drawn via Cholesky instead of SVD — identical conditional
  distribution N(Sigma^-1 d, Sigma^-1) (gibbs.py:169-180), without the
  TPU-hostile SVD;
- Python-2 latent bugs (``map`` consumed as list, gibbs.py:226,248) fixed;
- acceptance rates are counted (the reference tracks none, SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import scipy.linalg as sl
from scipy.special import gammaln

from gibbs_student_t_tpu.backends.base import ChainResult, SamplerBackend
from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.models.pta import ModelArrays, lnprior, ndiag, phiinv_logdet


class NumpyGibbs(SamplerBackend):
    def __init__(self, ma: ModelArrays, config: GibbsConfig):
        super().__init__(ma, config)
        if ma.row_mask is not None and not np.all(ma.row_mask):
            raise ValueError(
                "NumpyGibbs does not support padded models; pass the "
                "unpadded per-pulsar ModelArrays (padding exists only "
                "for stacking ensembles on device)")
        cfg = config
        n = ma.n
        self._z = (np.ones(n) if cfg.z_init_ones else np.zeros(n))
        self._alpha = (np.ones(n) if cfg.vary_alpha
                       else np.full(n, cfg.alpha))
        self._theta = cfg.outlier_mean
        self._pout = np.zeros(n)
        self._b = np.zeros(ma.m)
        self.tdf = cfg.tdf
        # per-sweep cache of TNT = T^T N^-1 T and d = T^T N^-1 y
        # (reference gibbs.py:38-39,302-304)
        self._TNT = None
        self._d = None
        # pspin in scaled time units so the vvh17 uniform-in-phase density
        # theta/pspin matches the scaled Gaussian densities
        self._pspin = (cfg.pspin * ma.time_scale
                       if cfg.pspin is not None else None)

    # -- likelihoods --------------------------------------------------------

    def _nvec(self, x: np.ndarray) -> np.ndarray:
        return self._alpha ** self._z * ndiag(self.ma, x)

    def get_lnlikelihood_white(self, x: np.ndarray) -> float:
        """Conditional-on-b Gaussian likelihood (reference gibbs.py:262-284)."""
        nvec = self._nvec(x)
        yred = self.ma.y - self.ma.T @ self._b
        return float(-0.5 * (np.sum(np.log(nvec)) + np.sum(yred ** 2 / nvec)))

    def _update_cache(self, nvec: np.ndarray) -> None:
        if self._TNT is None:
            T = self.ma.T
            self._TNT = T.T @ (T / nvec[:, None])
            self._d = T.T @ (self.ma.y / nvec)

    def get_lnlikelihood(self, x: np.ndarray) -> float:
        """b-marginalized likelihood (reference gibbs.py:288-329)."""
        nvec = self._nvec(x)
        self._update_cache(nvec)
        phiinv, logdet_phi = phiinv_logdet(self.ma, x)
        loglike = -0.5 * (np.sum(np.log(nvec))
                          + np.sum(self.ma.y ** 2 / nvec))
        Sigma = self._TNT + np.diag(phiinv)
        try:
            cf = sl.cho_factor(Sigma)
            expval = sl.cho_solve(cf, self._d)
        except np.linalg.LinAlgError:
            return -np.inf
        logdet_sigma = np.sum(2 * np.log(np.diag(cf[0])))
        return float(loglike + 0.5 * (self._d @ expval - logdet_sigma
                                      - logdet_phi))

    def get_lnprior(self, x: np.ndarray) -> float:
        return float(lnprior(self.ma, x))

    def get_lnlikelihood_df(self, df: float) -> float:
        """Discrete-df conditional (reference gibbs.py:331-335)."""
        n = self.ma.n
        a = self._alpha
        return float(-(df / 2) * np.sum(np.log(a) + 1 / a)
                     + n * (df / 2) * np.log(df / 2)
                     - n * gammaln(df / 2))

    # -- conditional updates ------------------------------------------------

    def _mh_block(self, x: np.ndarray, ind: np.ndarray, nsteps: int,
                  loglike_fn, rng: np.random.Generator):
        """Random-walk MH on one coordinate block
        (reference gibbs.py:80-143)."""
        mh = self.config.mh
        accepted = 0
        if len(ind) == 0:
            return x, 0.0
        lnlike0 = loglike_fn(x)
        lnprior0 = self.get_lnprior(x)
        xnew = x.copy()
        sigma = mh.sigma_per_param * len(ind)
        for _ in range(nsteps):
            q = xnew.copy()
            scale = rng.choice(mh.scale_sizes, p=mh.scale_probs)
            par = rng.choice(ind)
            q[par] += rng.standard_normal() * sigma * scale
            lnlike1 = loglike_fn(q)
            lnprior1 = self.get_lnprior(q)
            if (lnlike1 + lnprior1) - (lnlike0 + lnprior0) > np.log(rng.random()):
                xnew = q
                lnlike0, lnprior0 = lnlike1, lnprior1
                accepted += 1
        return xnew, accepted / nsteps

    def update_white_params(self, x, rng):
        return self._mh_block(x, self.ma.white_indices,
                              self.config.mh.n_white_steps,
                              self.get_lnlikelihood_white, rng)

    def update_hyper_params(self, x, rng):
        return self._mh_block(x, self.ma.hyper_indices,
                              self.config.mh.n_hyper_steps,
                              self.get_lnlikelihood, rng)

    def update_b(self, x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        """Conditional coefficient draw b ~ N(Sigma^-1 d, Sigma^-1)
        (reference gibbs.py:145-182), via Cholesky: mean = Sigma^-1 d,
        fluctuation = L^-T xi."""
        nvec = self._nvec(x)
        self._update_cache(nvec)
        phiinv, _ = phiinv_logdet(self.ma, x)
        Sigma = self._TNT + np.diag(phiinv)
        try:
            L = sl.cholesky(Sigma, lower=True)
        except np.linalg.LinAlgError:
            L = sl.cholesky(Sigma + 1e-6 * np.eye(self.ma.m)
                            * np.diag(Sigma).max(), lower=True)
        mean = sl.cho_solve((L, True), self._d)
        xi = rng.standard_normal(self.ma.m)
        fluct = sl.solve_triangular(L, xi, lower=True, trans="T")
        return mean + fluct

    def update_theta(self, rng: np.random.Generator) -> float:
        """Beta draw of the outlier fraction (reference gibbs.py:185-198)."""
        cfg = self.config
        if not cfg.is_outlier_model:
            return self._theta
        n = self.ma.n
        if cfg.theta_prior == "beta":
            mk, k1mm = n * cfg.outlier_mean, n * (1 - cfg.outlier_mean)
        else:
            mk, k1mm = 1.0, 1.0
        return float(rng.beta(np.sum(self._z) + mk,
                              n - np.sum(self._z) + k1mm))

    def update_z(self, x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        """Bernoulli outlier indicators (reference gibbs.py:201-226)."""
        cfg = self.config
        if not cfg.is_outlier_model:
            return self._z
        nvec0 = ndiag(self.ma, x)
        mean = self.ma.T @ self._b
        resid = self.ma.y - mean
        p_in = _norm_pdf(resid, np.sqrt(nvec0))
        if cfg.model == "vvh17":
            top = np.full(self.ma.n, self._theta / self._pspin)
        else:
            p_out = _norm_pdf(resid, np.sqrt(self._alpha * nvec0))
            top = self._theta * p_out
        bot = top + (1 - self._theta) * p_in
        with np.errstate(invalid="ignore"):  # 0/0 -> NaN -> 1 below
            q = top / bot
        q[np.isnan(q)] = 1.0
        self._pout = q
        return (rng.random(self.ma.n) < np.minimum(q, 1.0)).astype(np.float64)

    def update_alpha(self, x: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        """Per-TOA inverse-gamma auxiliary scales (reference gibbs.py:229-242)."""
        cfg = self.config
        if np.sum(self._z) >= 1 and cfg.vary_alpha:
            nvec0 = ndiag(self.ma, x)
            resid = self.ma.y - self.ma.T @ self._b
            top = (resid ** 2 * self._z / nvec0 + self.tdf) / 2
            bot = rng.gamma((self._z + self.tdf) / 2)
            return top / bot
        return self._alpha

    def update_df(self, rng: np.random.Generator) -> float:
        """Discrete dof draw on the grid 1..df_max (reference gibbs.py:244-259)."""
        cfg = self.config
        if not cfg.vary_df:
            return self.tdf
        grid = np.arange(1, cfg.df_max + 1)
        logp = np.array([self.get_lnlikelihood_df(df) for df in grid])
        p = np.exp(logp - logp.max())
        p /= p.sum()
        return float(rng.choice(grid, p=p))

    # -- driver -------------------------------------------------------------

    def sample(self, x0: np.ndarray, niter: int, seed: int = 0,
               rng: Optional[np.random.Generator] = None,
               progress: bool = False) -> ChainResult:
        """The sweep driver (reference gibbs.py:342-385)."""
        rng = rng or np.random.default_rng(seed)
        ma = self.ma
        chain = np.zeros((niter, len(x0)))
        bchain = np.zeros((niter, ma.m))
        zchain = np.zeros((niter, ma.n))
        alphachain = np.zeros((niter, ma.n))
        poutchain = np.zeros((niter, ma.n))
        thetachain = np.zeros(niter)
        dfchain = np.zeros(niter)
        acc_white = np.zeros(niter)
        acc_hyper = np.zeros(niter)

        xnew = np.asarray(x0, dtype=np.float64).copy()
        import time

        tstart = time.time()
        for ii in range(niter):
            chain[ii] = xnew
            bchain[ii] = self._b
            zchain[ii] = self._z
            thetachain[ii] = self._theta
            alphachain[ii] = self._alpha
            dfchain[ii] = self.tdf
            poutchain[ii] = self._pout

            self._TNT = None
            self._d = None

            xnew, acc_white[ii] = self.update_white_params(xnew, rng)
            xnew, acc_hyper[ii] = self.update_hyper_params(xnew, rng)
            self._b = self.update_b(xnew, rng)
            self._theta = self.update_theta(rng)
            self._z = self.update_z(xnew, rng)
            self._alpha = self.update_alpha(xnew, rng)
            self.tdf = self.update_df(rng)

            if progress and ii % 100 == 0 and ii > 0:
                print(f"\rFinished {ii / niter * 100:g} percent in "
                      f"{time.time() - tstart:g} seconds.", end="", flush=True)
        if progress:
            print()

        return ChainResult(
            chain=chain, bchain=bchain, zchain=zchain,
            thetachain=thetachain, alphachain=alphachain,
            poutchain=poutchain, dfchain=dfchain,
            stats={"acc_white": acc_white, "acc_hyper": acc_hyper},
        )


def _norm_pdf(x: np.ndarray, sigma: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * (x / sigma) ** 2) / (np.sqrt(2 * np.pi) * sigma)
