"""JAX TPU backend: the jit+vmap blocked MH-within-Gibbs kernel.

TPU-native re-design of the reference sweep (reference gibbs.py:342-385).
One sweep is a pure function ``(ChainState, key) -> ChainState``; chains are
data-parallel via ``vmap`` (the north-star 1024-chains/chip axis,
BASELINE.json); sweeps advance under ``lax.scan`` in fixed-size chunks whose
records are spooled to host between chunks, which doubles as the
checkpoint surface (SURVEY.md §5).

Design choices vs. the reference, per SURVEY.md §7:

- the 20-step white and 10-step hyper Metropolis inner loops
  (gibbs.py:88,121) are ``lax.fori_loop``s with branchless masked
  accepts — per-chain data-dependent control flow cannot branch under jit;
- the random scale-mixture/coordinate jump (gibbs.py:91-97) becomes
  ``categorical`` + dynamic-index scatter;
- the per-sweep ``TNT``/``d`` cache (gibbs.py:38-39,302-304) becomes plain
  dataflow: computed once after the white block, threaded to the hyper
  block and coefficient draw;
- all LAPACK factorizations are the diagonally-preconditioned Cholesky of
  ``ops/linalg.py``; non-PD matrices yield NaN -> -inf -> MH rejection,
  replacing try/except fallbacks (gibbs.py:168-178,320-324);
- ``update_alpha``'s data-dependent gate ``sum(z) >= 1`` (gibbs.py:234)
  is a ``where`` mask; ``update_z``'s NaN clamp (gibbs.py:224) is a
  ``where``.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, random
from jax.scipy.special import gammaln, logsumexp

from gibbs_student_t_tpu.backends.base import (
    META_STATS,
    ChainResult,
    SamplerBackend,
)
from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.models.pta import (
    ModelArrays,
    lnprior,
    ndiag,
    phiinv_logdet,
    static_phi_columns,
)
from gibbs_student_t_tpu.obs.telemetry import (
    combine_tele_stats,
    telemetry_init,
    telemetry_update,
    TelemetryAccumulator,
)
from gibbs_student_t_tpu.obs.tracing import block_span

from gibbs_student_t_tpu.ops.linalg import (
    backward_solve,
    beta_fractional,
    fuse_stages_env,
    fused_hyper_draws,
    masked_chisq,
    masked_gamma_v2,
    nchol_env,
    nhyper_env,
    nresid_active,
    nresid_env,
    nwhite_env,
    precond_quad_logdet,
    precond_quad_logdet_hoisted,
    residual_matvec,
    residual_matvec_lanes,
    robust_precond_draw,
    schur_eliminate,
    tnt_gram_lanes,
    vchol_env,
)
from gibbs_student_t_tpu.ops.rng import key_bits
from gibbs_student_t_tpu.ops.tnt import (
    auto_block_size,
    matvec_blocked,
    pad_rows,
    tnt_products,
)


def _bdraw_reuse_env() -> str:
    """Validated ``GST_BDRAW_REUSE`` (``auto`` when unset) — the
    b-draw's block-assembled-factor gate. Strict ``auto|1|0``, raising
    whenever the variable is set to anything else (the same loud-typo
    contract as ``GST_VCHOL`` / ``GST_ENSEMBLE_UNROLL``)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_BDRAW_REUSE")


def _donate_env() -> str:
    """Validated ``GST_DONATE_CHUNK`` (``auto`` when unset) — donation
    of the chunk functions' state buffers. Strict ``auto|1|0``."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_DONATE_CHUNK")


def donate_resolved() -> bool:
    """The chunk-donation verdict (``auto`` → ON, the round-11
    serving default) — EXCEPT in a process whose persistent AOT
    compile cache is armed (ops/registry.enable_persistent_cache: the
    serve pool workers, failover respawns, ``recover()``): a donated
    executable DESERIALIZED from the cache loses its input/output
    aliasing contract on this jaxlib and corrupts the heap (measured:
    both pools of a fleet arm segfaulting in glibc malloc at tenant
    admission — ops/registry.aot_cache_armed). ``auto`` therefore
    degrades to OFF there, recorded with the reason; an explicit
    ``1`` still forces donation (the A/B hatch), ``0`` disables as
    ever. Donation never changes chains — only buffer reuse — so the
    bitwise serving pins hold on either resolution."""
    from gibbs_student_t_tpu.ops import registry

    env = _donate_env()
    if env == "0":
        return False
    if env == "1":
        return True
    if registry.aot_cache_armed():
        registry.record(
            "GST_DONATE_CHUNK", value=env, enabled=False, forced=False,
            reason="degraded: AOT cache armed — deserialized donated "
                   "executables corrupt the heap on this jaxlib")
        return False
    registry.record("GST_DONATE_CHUNK", value=env, enabled=True,
                    forced=False, reason="auto: on")
    return True


def _fast_gamma_env() -> str:
    """Validated ``GST_FAST_GAMMA`` (``auto`` when unset) — the alpha
    update's chi-square gamma construction. Strict ``auto|1|0``;
    ``auto`` resolves per-platform at construction time: ON for
    non-TPU backends, where ``random.gamma``'s per-element rejection
    While-loop is the single largest cost of the whole sweep (measured
    1.76 s for a (1024, 130) draw on the graded CPU host,
    tools/cpu_microbench.py — more than ALL linear algebra combined);
    OFF on TPU, where the native sampler costs ~0.5 ms and staying on
    it keeps chains bit-identical with earlier rounds."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_FAST_GAMMA")


def _hyper_hoist_env() -> str:
    """Validated ``GST_HYPER_HOIST`` (``auto`` when unset) — the hyper
    MH loop's per-sweep hoisting of proposal-invariant work (the
    matrix block's diagonal, the fused equilibrated-matrix build that
    skips materializing ``S0 + diag(phiinv)`` per proposal). Strict
    ``auto|1|0``; ``auto`` resolves ON for the CPU backend (where the
    closure-path hyper loop is the production path) and OFF elsewhere.
    The hoist is a pure reassociation-free restructuring: chains are
    bit-identical on/off (pinned in tests/test_nchol.py)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_HYPER_HOIST")


def _fast_beta_env() -> str:
    """Validated ``GST_FAST_BETA`` (``auto`` when unset) — the theta
    draw's exact chi-square construction (``Beta(a, b) = chi2_2a /
    (chi2_2a + chi2_2b)`` from one disjointly-masked normal pool),
    replacing ``random.beta``'s two per-element rejection loops when
    the prior pseudo-counts are half-integral. Strict ``auto|1|0``;
    ``auto`` resolves ON off-TPU (the GST_FAST_GAMMA pattern — the
    rejection loop is a CPU cost). Draws a different (equally exact)
    stream than ``random.beta``, so it is gated separately from
    GST_HYPER_HOIST, whose on/off contract is bit-identical chains."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_FAST_BETA")


def _fast_gamma_v2_env() -> str:
    """Validated ``GST_FAST_GAMMA_V2`` (``auto`` when unset) — the
    alpha update's **v2** gamma construction (``Gamma(k/2) =
    -log prod U + odd * 0.5 N^2`` on counter-based philox streams; see
    ops/linalg.masked_gamma_v2). Engages only within the fast-gamma
    path (``GST_FAST_GAMMA``); strict ``auto|1|0``. ``auto`` resolves
    ON when the native draw kernels are available on CPU (where the v2
    kernel replaces the erfinv-bound normal pool) and OFF otherwise —
    the jnp philox twin alone does not beat the chi-square arm.
    Forcing ``1`` takes v2 regardless (jnp twin when the kernel is
    absent: same distribution, silent degradation)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_FAST_GAMMA_V2")


def _fast_theta_env() -> str:
    """Validated ``GST_FAST_THETA`` (``auto`` when unset) — the theta
    draw's native fractional-Beta path (in-kernel Marsaglia-Tsang,
    ops/linalg.beta_fractional), covering the flagship beta prior whose
    fractional pseudo-counts the half-integer ``GST_FAST_BETA``
    construction measured out. Strict ``auto|1|0``; ``auto`` resolves
    ON when the fast-beta pool is unavailable AND the native kernels
    are present on CPU. Draws a different (equally exact) stream than
    ``random.beta``."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_FAST_THETA")


class ChainState(NamedTuple):
    """Per-chain sampler state — the full pytree a checkpoint needs
    (SURVEY.md §5 'checkpoint/resume')."""

    x: jnp.ndarray        # (p,) sampled parameters
    b: jnp.ndarray        # (m,) basis coefficients
    z: jnp.ndarray        # (n,) outlier indicators
    alpha: jnp.ndarray    # (n,) variance scales
    theta: jnp.ndarray    # () outlier fraction
    df: jnp.ndarray       # () Student-t dof
    pout: jnp.ndarray     # (n,) outlier probabilities (derived metric)
    acc_white: jnp.ndarray  # () last-sweep acceptance rate
    acc_hyper: jnp.ndarray  # ()
    # (2,) log jump-scale multipliers [white, hyper] — identically 0
    # (scale 1, the reference's fixed table) unless MHConfig.adapt_until
    # enables Robbins-Monro adaptation. The numpy default keeps
    # hand-built states (tests) valid without triggering device init at
    # import time.
    mh_log_scale: jnp.ndarray = np.zeros(2, np.float32)
    # (2, p, p) per-block proposal-direction Cholesky factors [white,
    # hyper], zero-padded outside each block's coordinates — empty (and
    # unused) unless MHConfig.adapt_cov enables population-covariance
    # proposals. Re-estimated across the chain population at chunk
    # boundaries while adapting, frozen at adapt_until.
    mh_cov_chol: jnp.ndarray = np.zeros(0, np.float32)


class FusedConsts(NamedTuple):
    """Per-model constant ARRAYS of the fused MH blocks, as a pytree.

    The single-model backend bakes these into the trace as host
    constants (``JaxGibbs._white_consts`` / ``_hyper_consts``); the
    ensemble stacks them along a leading pulsar axis and threads them
    through ``vmap``/``shard_map`` as traced operands so every pulsar
    reaches the same fused kernels (ops/pallas_white.py grouped grid,
    ops/pallas_hyper.py per-lane constant planes). The STATIC structure
    (``WhiteConsts.var``, ``HyperConsts.hyp_idx``, prior kinds) must be
    identical across pulsars — parallel/ensemble.py validates that at
    construction and falls back to the closure path otherwise. Fields
    are None when the corresponding block is unavailable (float64, no
    white/hyper params, v > MAX_PALLAS_V)."""

    white_rows: jnp.ndarray | None       # (R, n) / (P, R, n)
    white_specs: jnp.ndarray | None      # (3, p) / (P, 3, p)
    hyper_K: jnp.ndarray | None          # (1+nk, v) / (P, 1+nk, v)
    hyper_sel: jnp.ndarray | None        # (v,) / (P, v)
    hyper_phiinv_static: jnp.ndarray | None   # (v,) / (P, v)
    hyper_logdet_phi_static: jnp.ndarray | None  # () / (P,)
    hyper_specs: jnp.ndarray | None      # (3, p) / (P, 3, p)
    # serve slot pool only (serve/pool.py): per-lane tenant group ids
    # under the tile-uniform admission contract — the operand that lets
    # the native lanes kernels (tnt_lanes, fused_hyper_lanes) pick each
    # tile's constants. None for the single-model and ensemble paths.
    gid: jnp.ndarray | None = None


_RECORD_FIELDS = ("x", "b", "z", "theta", "alpha", "df", "pout",
                  "acc_white", "acc_hyper")

# The systematic-scan block order of ``_sweep`` (white-x → hyper-x →
# b → θ → z → α → ν) splits the recorded fields at the partial-scan
# point AFTER the coefficient draw: a mid-scan state carries the NEW
# values of everything the scan has already updated and the OLD values
# of everything it has not. Recycling Gibbs (arXiv:1611.07056;
# parallel/recycle.py) reconstructs those partial-scan states from
# adjacent recorded rows — these two groups are the reconstruction
# rule, and they must track ``_sweep``'s block order if it ever
# changes (pinned in tests/test_recycle.py against a tiny run).
RECYCLE_EARLY_FIELDS = ("x", "b", "acc_white", "acc_hyper")
RECYCLE_LATE_FIELDS = ("z", "theta", "alpha", "df", "pout")

# Adaptive block scans (serve/adapt.py; arXiv:1808.09047): indices
# into ``_sweep``'s per-lane block-enable operand, one per conditional
# block in the systematic-scan order above. The b-draw's effective
# gate is tied to the hyper gate (``BLOCK_HYPER & BLOCK_B``) on every
# path: the fused megastage draws b jointly with — and conditioned
# on — the proposed hyper x, so a kept b under a discarded x would
# condition on a value the chain never took.
BLOCK_WHITE, BLOCK_HYPER, BLOCK_B = 0, 1, 2
BLOCK_THETA, BLOCK_Z, BLOCK_ALPHA, BLOCK_DF = 3, 4, 5, 6
NBLOCKS = 7
BLOCK_NAMES = ("white", "hyper", "b", "theta", "z", "alpha", "df")

# record="compact": device->host transport dtypes for the bulky recorded
# fields. z is exactly 0/1 so it is bit-packed (8 indicators per byte,
# lossless — unpacked bit-exactly on host); pout is a probability
# (float16 keeps ~3 decimal digits); b/alpha need float32 *range*
# (alpha spans many decades) so bfloat16. Host arrays are re-materialized
# as float32 — the cast exists only on the wire, where chain recording is
# bandwidth-bound (~200 MB per 100-sweep chunk at 1024 chains otherwise;
# the relay link runs tens of MB/s, docs/PERFORMANCE.md).
_PACKBITS = "packbits"
_U8PROB = "u8prob"

_COMPACT_CASTS = {"z": _PACKBITS, "pout": jnp.float16,
                  "b": jnp.bfloat16, "alpha": jnp.bfloat16}

# record="compact8": compact plus pout quantized to uint8 (levels of
# 1/255 — ~2.4 decimal digits on a probability whose downstream use is
# thresholded outlier maps, analysis.py). Halves the pout wire bytes on
# top of compact; opt-in because it is the lossiest tier.
_COMPACT8_CASTS = dict(_COMPACT_CASTS, pout=_U8PROB)


def _pack_bits(a):
    """Little-endian bit-pack a 0/1 array along its last axis:
    (..., n) -> (..., ceil(n/8)) uint8. Lossless for the z indicator
    chains; the host side restores exactly with
    ``np.unpackbits(..., bitorder='little')`` (``_unpack_bits``)."""
    n = a.shape[-1]
    pad = (-n) % 8
    b = jnp.asarray(a, jnp.uint8)
    if pad:
        b = jnp.concatenate(
            [b, jnp.zeros(b.shape[:-1] + (pad,), jnp.uint8)], axis=-1)
    b = b.reshape(b.shape[:-1] + ((n + pad) // 8, 8))
    w = jnp.left_shift(jnp.uint32(1), jnp.arange(8, dtype=jnp.uint32))
    return (b.astype(jnp.uint32) * w).sum(axis=-1).astype(jnp.uint8)


def _unpack_bits(h, n):
    """Host-side inverse of ``_pack_bits``: (..., ceil(n/8)) uint8 ->
    (..., n) float32 of exact 0/1 values."""
    bits = np.unpackbits(np.asarray(h, np.uint8), axis=-1,
                         bitorder="little")
    return bits[..., :n].astype(np.float32)


def record_tuple(st, fields, casts):
    """One sweep's record in wire dtypes — shared by the single-model
    chunk functions below and the ensemble's sharded chunk
    (parallel/ensemble.py), so the compact transport rules live in
    exactly one place (``_COMPACT_CASTS``)."""
    out = []
    for f in fields:
        v = getattr(st, f)
        c = casts.get(f) if casts else None
        if c is _PACKBITS:
            v = _pack_bits(v)
        elif c is _U8PROB:
            v = jnp.clip(jnp.round(v * 255.0), 0, 255).astype(jnp.uint8)
        elif c is not None:
            v = v.astype(c)
        out.append(v)
    return tuple(out)


def chunked_sweep_loop(state, niter, chunk_size, start_sweep,
                       step_fn, flush_fn, reinit_fn=None, n_reinits=0,
                       pre_chunk_fn=None, pre_chunk_until=0,
                       snapshot_fn=None):
    """The chunk-orchestration loop shared by ``JaxGibbs.sample`` and
    ``EnsembleGibbs.sample`` (parallel/ensemble.py) so the flush
    machinery cannot drift between them.

    ``step_fn(state, offset, length) -> (state, recs)`` advances one
    chunk; ``flush_fn(recs, chunk_state, sweep_end, n_reinits)`` moves a
    chunk's records to host (spool or in-memory); ``reinit_fn(state,
    sweep_end) -> (state, n_bad)``, when given, repairs diverged chains
    at each chunk boundary. ``pre_chunk_fn(state) -> state``, when
    given, runs before each chunk whose offset is below
    ``pre_chunk_until`` — the population-covariance re-estimation hook
    (MHConfig.adapt_cov), shared here so its boundary semantics cannot
    drift between the two samplers. Without ``reinit_fn``, flushes are
    double-buffered: chunk k+1 is dispatched before the blocking pull of
    chunk k's records, overlapping transfer with compute (crash window:
    up to two chunks — see ``JaxGibbs.sample``). With it, flushes are
    sequential (the divergence scan needs each post-chunk state on
    host). ``snapshot_fn``, when given, is applied to the state stored
    for a DEFERRED flush: with donated chunk buffers the next dispatch
    consumes chunk k's state buffers before its flush runs, so a flush
    that reads the state (the spool checkpoint) gets a copy taken while
    the buffers were still live. Returns ``(state, n_reinits)``."""
    done = 0
    pending = None
    while done < niter:
        length = min(chunk_size, niter - done)
        if pre_chunk_fn is not None and start_sweep + done < pre_chunk_until:
            state = pre_chunk_fn(state)
        state, recs = step_fn(state, start_sweep + done, length)
        done += length
        if reinit_fn is not None:
            state, n_bad = reinit_fn(state, start_sweep + done)
            n_reinits += n_bad
            flush_fn(recs, state, start_sweep + done, n_reinits)
        else:
            if pending is not None:
                flush_fn(*pending, n_reinits)
            pending = (recs,
                       state if snapshot_fn is None else snapshot_fn(state),
                       start_sweep + done)
    if pending is not None:
        flush_fn(*pending, n_reinits)
    return state, n_reinits


def _ess_per_param(window):
    """(p,) total effective sample size per parameter over a
    (rows, nchains, p) window (all chains pooled; one batched FFT for
    all nchains*p autocorrelations — the per-column loop this replaces
    measurably ate into the convergence-stopping win at 1024 chains,
    VERDICT r3 weak #6)."""
    from gibbs_student_t_tpu.parallel.diagnostics import ess_per_param

    return ess_per_param(window)


def _rhat_per_param(window):
    """(p,) split-R-hat per parameter over a (rows, nchains, p) window."""
    from gibbs_student_t_tpu.parallel.diagnostics import split_rhat

    return np.array([split_rhat(window[..., pi])
                     for pi in range(window.shape[-1])])


def _sample_until_loop(sample_fn, last_state_fn, record_thin, rhat_of,
                       rhat_target, max_sweeps, check_every, min_sweeps,
                       state, spool_mode, ess_of=None, min_ess=None):
    """Shared convergence-stopping loop behind ``JaxGibbs.sample_until``
    and ``EnsembleGibbs.sample_until`` — segments of ``check_every``
    sweeps until ``rhat_of`` (computed on the second half of the
    accumulated chains) clears ``rhat_target`` everywhere, and (when
    ``min_ess`` is set) ``ess_of`` reports at least ``min_ess``
    effective samples for EVERY parameter in the same window.

    ``sample_fn(length, state, start_sweep) -> ChainResult`` runs one
    segment; ``spool_mode`` means each segment's result is already the
    reloaded FULL history (utils/spool.py), so only the latest is kept
    (and its counters are cumulative); otherwise segments are
    concatenated, with per-call ``n_reinits`` summed."""
    if check_every % record_thin or (check_every // record_thin) < 8:
        raise ValueError(
            "check_every must be a multiple of record_thin covering "
            ">= 8 recorded rows, or the split-R-hat window degenerates"
            f" (got {check_every} at record_thin={record_thin})")
    if max_sweeps < 1:
        raise ValueError(f"max_sweeps must be >= 1, got {max_sweeps}")
    if max_sweeps % record_thin:
        # fail now, not at the final partial segment after hours of work
        raise ValueError(
            f"max_sweeps ({max_sweeps}) must be a multiple of "
            f"record_thin ({record_thin})")
    segments = []
    history = []
    ess_history = []
    tele_segs = []  # per-segment tele_* stats (sweep-weighted merge below)
    done = 0
    converged = False

    def window_of(segs, total_rows):
        """Rows [total_rows//2:] without re-concatenating the full
        history every check (only the tail segments that overlap)."""
        start = total_rows // 2
        out, r0 = [], 0
        for s in segs:
            r1 = r0 + s.shape[0]
            if r1 > start:
                out.append(s[max(0, start - r0):])
            r0 = r1
        return np.concatenate(out)

    res = None
    while done < max_sweeps:
        length = min(check_every, max_sweeps - done)
        res = sample_fn(length, state, done)
        state = last_state_fn()
        done += length
        tele_segs.append({k: v for k, v in res.stats.items()
                          if k.startswith("tele_")})
        if spool_mode:
            total_rows = res.chain.shape[0]
            window = res.chain[total_rows // 2:]
        else:
            segments.append(res)
            total_rows = sum(s.chain.shape[0] for s in segments)
            window = window_of([s.chain for s in segments], total_rows)
        # second half of the accumulated run: the usual split-R-hat
        # convention folds early-transient sweeps out of the window
        rhat = rhat_of(window)
        history.append(rhat)
        ess = None
        if min_ess is not None:
            ess = ess_of(window)
            ess_history.append(ess)
        if done >= max(min_sweeps, 2 * check_every) and (
                rhat < rhat_target).all() and (
                min_ess is None or (ess >= min_ess).all()):
            converged = True
            break
    if spool_mode:
        out = res  # already the full history, cumulative counters
    else:
        cols = {}
        for f in dataclasses.fields(ChainResult):
            if f.name == "stats":
                continue
            arrs = [getattr(s, f.name) for s in segments]
            cols[f.name] = (np.concatenate(arrs) if arrs[0].size
                            else arrs[0])
        stats = {}
        for k in segments[0].stats:
            v0 = segments[0].stats[k]
            if k.startswith("tele_"):
                continue  # merged below with sweep-count weighting
            if k == "n_reinits":
                # per-call counters: the run's total is the sum
                stats[k] = np.asarray(sum(
                    int(s.stats[k]) for s in segments))
            elif k in META_STATS or np.ndim(v0) == 0:
                stats[k] = v0
            else:
                stats[k] = np.concatenate([s.stats[k] for s in segments])
        out = ChainResult(**cols, stats=stats)
    # in spool mode each segment's result is the reloaded FULL history
    # but its tele_* stats cover only that call's chunks, so the merge
    # is identical in both modes
    out.stats.update(combine_tele_stats(tele_segs))
    out.stats["rhat_history"] = np.stack(history)
    out.stats["rhat"] = history[-1]
    if ess_history:
        out.stats["ess_history"] = np.stack(ess_history)
        out.stats["ess"] = ess_history[-1]
    out.stats["converged"] = np.asarray(converged)
    return out


def merge_reinit(state, bad, fresh, batch_ndim: int):
    """Replace the ``bad``-masked leading-axis entries of ``state`` with
    ``fresh`` draws; healthy entries stay bitwise identical. ``bad`` has
    ``batch_ndim`` leading batch axes ((nchains,) for the single-model
    backend, (npulsars, nchains) for ensembles).

    The adapted MH jump scales (and population-covariance proposal
    factors) survive re-init: a chain diverges in its x/b/alpha state,
    not its (bounded) step sizes, and Robbins-Monro may already be
    frozen — a zeroed scale would silently run the rest of the sampling
    un-adapted."""
    fresh = fresh._replace(mh_log_scale=state.mh_log_scale,
                           mh_cov_chol=state.mh_cov_chol)
    mask = jnp.asarray(bad)
    return jax.tree.map(
        lambda cur, fr: jnp.where(
            mask.reshape(mask.shape + (1,) * (cur.ndim - batch_ndim)),
            fr, cur),
        state, fresh)


class JaxGibbs(SamplerBackend):
    """Many-chain Gibbs sampler; ``sample`` returns ``(niter, nchains, ...)``
    chains like a stacked version of the reference's attribute arrays."""

    supports_chains = True

    def __init__(self, ma: ModelArrays, config: GibbsConfig,
                 nchains: int = 64, dtype=jnp.float32,
                 chunk_size: int = 100,
                 tnt_block_size: int | str | None = "auto",
                 record: str = "compact8",
                 record_thin: int = 1,
                 use_pallas: bool | str = "auto",
                 pallas_interpret: bool = False,
                 hyper_schur: bool | str = "auto",
                 telemetry: bool = True,
                 metrics=None,
                 operand_mode: bool = False):
        """``tnt_block_size`` selects the TOA reduction: ``None`` dense,
        an int for a ``lax.scan`` over row blocks (the 1e5-TOA stress path,
        BASELINE.json config 4; TOA axis zero-padded to a block multiple),
        ``"auto"`` picks by TOA count. ``record`` picks the chain
        recording mode: ``"compact8"`` (default) records every field
        but moves the bulky ones device->host in narrow transport
        dtypes — z bit-packed 8-per-byte (exact: values are 0/1), pout
        as uint8 (1/255 steps — a diagnostic probability whose
        downstream consumers are 0.5/0.9 thresholds, analysis.py), b
        and alpha as bfloat16 (float32 range — alpha spans decades —
        ~2-3 significant digits) — then re-materializes float32 host
        arrays, ~3x fewer bytes than full (the sampled parameter chains
        x/theta/df and acceptance stats are always exact float32). The
        default is the cheapest tier that preserves every downstream
        use; measured 2.25x wall-clock on the transport-bound flagship
        (docs/PERFORMANCE.md). ``"compact"`` keeps pout at float16
        (~3 decimal digits); ``"full"`` transports everything in
        float32 bit-exactly; ``"light"`` records only the
        O(1)-per-sweep fields
        (x, theta, df, acceptance) — at stress scale the per-TOA chains
        (z, alpha, pout) dominate host transfer.
        ``record_thin=t`` records every t-th sweep (the state *before*
        sweeps 0, t, 2t, ...), cutting device->host record bytes t-fold
        while every sweep still runs with identical keying — row k of a
        thinned result is bit-identical to row k*t of an unthinned run.
        The reference records every sweep and its analyses thin
        afterwards; here thinning can happen before the wire because
        transport, not compute, gates wall time through this relay
        (docs/PERFORMANCE.md roofline). ``chunk_size`` and ``niter``
        must be multiples of t; downstream row counts (e.g. ``burn``)
        are in recorded rows.
        ``use_pallas`` routes the blocked TNT reduction through the fused
        Pallas TPU kernel (ops/pallas_tnt.py), batched over all chains
        between the vmapped sweep stages; ``"auto"`` resolves to False —
        the hardware A/B measured the XLA scan faster in every regime
        where the blocked path is active (artifacts/pallas_tnt_tpu_r02):
        the kernel is kept opt-in for A/B only. ``pallas_interpret`` runs the
        kernel in interpreter mode (CPU testing). ``hyper_schur``
        pre-eliminates the phi-static basis columns (timing block,
        constant-pinned GPs) from the hyper-MH factorization once per
        sweep (ops/linalg.py schur_eliminate) — exact block algebra;
        with ``jitter>0`` the regularization lands on the sub-blocks'
        own equilibrated diagonals rather than full Sigma's, a same-order
        perturbation. ``"auto"`` enables it when at least 8 static
        columns exist; ``True`` raises if the split is degenerate.

        Env overrides (``GST_HYPER_SCHUR``, ``GST_PALLAS_CHOL``,
        ``GST_UNROLLED_CHOL``, ``GST_PALLAS_WHITE``,
        ``GST_PALLAS_HYPER``) are consulted at construction/trace time
        and baked into the compiled sweep: set them *before* constructing
        the backend; flipping them afterwards does not affect an existing
        instance (ops/linalg.py ``_pallas_chol_mode``). The white/hyper
        flags gate the fused whole-MH-block kernels (ops/pallas_white.py,
        ops/pallas_hyper.py), both ``auto``-on for TPU backends.

        ``operand_mode`` (the serve slot pool, serve/pool.py) marks
        this backend as a TEMPLATE whose sweeps receive per-lane traced
        models: the per-model fast-draw gates (``GST_FAST_BETA`` /
        ``GST_FAST_THETA`` / ``GST_FUSE_STAGES``) then treat a traced
        ``ma`` with serve fused-consts (``FusedConsts.gid``) exactly
        like the frozen model — constants become call-time operands of
        ONE compiled chunk program instead of trace literals, so
        admitting a tenant never recompiles. The template's OWN model
        defines the static structure (shapes, Schur split, prior
        kinds, hyp_idx); tenants must match it (validated at admission
        by the serve scheduler).

        ``telemetry`` (default on) carries the in-kernel ``Telemetry``
        pytree through each chunk's scan — per-block MH accept sums,
        per-chain non-finite divergence counters, chunk-end
        log-posterior (obs/telemetry.py) — drained to host with the
        record flush (no extra device syncs; updates never touch the
        RNG stream, so chains are bit-identical either way). Aggregates
        land in ``ChainResult.stats`` under ``tele_*`` keys. ``metrics``
        optionally attaches an ``obs.metrics.MetricsRegistry``: each
        chunk then also increments its counters and appends one
        ``chunk`` event to the registry's JSONL sink."""
        super().__init__(ma, config)
        self.nchains = nchains
        self.dtype = dtype
        self.chunk_size = chunk_size
        self._operand_mode = bool(operand_mode)
        if record not in ("full", "compact", "compact8", "light"):
            raise ValueError("record must be 'full', 'compact', "
                             f"'compact8' or 'light', got {record!r}")
        self._record_mode = record
        if record_thin < 1:
            raise ValueError(f"record_thin must be >= 1, got {record_thin}")
        if chunk_size % record_thin:
            raise ValueError(
                f"chunk_size ({chunk_size}) must be a multiple of "
                f"record_thin ({record_thin}) so chunk boundaries land "
                "on recorded sweeps")
        self.record_thin = int(record_thin)
        self._record_fields = (_RECORD_FIELDS if record != "light" else
                               ("x", "theta", "df", "acc_white", "acc_hyper"))
        # compact transport only applies to float32 runs: an explicit
        # float64 run asked for full precision and must get bit-exact
        # float64 chains back (the casts would silently narrow them)
        self._record_casts = {}
        if dtype == jnp.float32:
            if record == "compact":
                self._record_casts = _COMPACT_CASTS
            elif record == "compact8":
                self._record_casts = _COMPACT8_CASTS
        if tnt_block_size == "auto":
            tnt_block_size = auto_block_size(ma.n)
        self._block_size = tnt_block_size
        # A model may arrive pre-padded (an ensemble slice from
        # parallel.ensemble.pad_model_arrays): its row_mask marks the real
        # TOA rows. Padding must be suffix-form so recorded per-TOA chains
        # trim back by simple slicing (_trim).
        base_mask = None
        self._n_real = ma.n
        if ma.row_mask is not None:
            base_mask = np.asarray(ma.row_mask, dtype=bool)
            self._n_real = int(base_mask.sum())
            if not base_mask[:self._n_real].all():
                raise ValueError(
                    "ModelArrays.row_mask must be suffix padding "
                    "(all real rows before all padded rows)")
        y, T, sigma2 = ma.y, ma.T, ma.sigma2
        efac_masks, equad_masks = ma.efac_masks, ma.equad_masks
        self._n_pad = 0
        if tnt_block_size is not None:
            T, y, self._n_pad = pad_rows(np.asarray(T), np.asarray(y),
                                         tnt_block_size)
            if self._n_pad:
                # Padded rows: zero basis/residual/masks. ndiag() gives 0
                # there; the sweep forces their nvec to 1 (row mask), so
                # they contribute nothing to any reduction (ops/tnt.py).
                pad = self._n_pad
                sigma2 = np.concatenate([sigma2, np.zeros(pad)])
                efac_masks = np.concatenate(
                    [efac_masks, np.zeros((efac_masks.shape[0], pad))],
                    axis=1)
                equad_masks = np.concatenate(
                    [equad_masks, np.zeros((equad_masks.shape[0], pad))],
                    axis=1)
        # dtype-cast copy of the frozen model so every kernel array (and the
        # constants XLA embeds) live in the compute precision
        self._ma = dataclasses.replace(
            ma,
            y=np.asarray(y, dtype=dtype),
            T=np.asarray(T, dtype=dtype),
            sigma2=np.asarray(sigma2, dtype=dtype),
            efac_masks=np.asarray(efac_masks, dtype=dtype),
            efac_const=np.asarray(ma.efac_const, dtype=dtype),
            equad_masks=np.asarray(equad_masks, dtype=dtype),
            equad_const=np.asarray(ma.equad_const, dtype=dtype),
            row_mask=None,  # padding state lives in self._row_mask
        )
        if base_mask is None and not self._n_pad:
            self._row_mask = None
        else:
            bm = (base_mask if base_mask is not None
                  else np.ones(ma.n, dtype=bool))
            self._row_mask = jnp.asarray(
                np.concatenate([bm, np.zeros(self._n_pad, dtype=bool)]))
        # Schur pre-elimination of the phi-static basis columns in the
        # hyper MH (timing block + any constant-pinned GP blocks): their
        # Sigma contribution is proposal-independent, so eliminating them
        # once per sweep shrinks the per-evaluation factorization from m
        # to the varying-column count. Exact block algebra — identical
        # likelihood values up to rounding.
        smask = static_phi_columns(self._ma)
        n_static = int(smask.sum())
        if hyper_schur == "auto":
            from gibbs_student_t_tpu.ops import registry

            env = registry.value("GST_HYPER_SCHUR")
            if env is not None:  # bench fallback-ladder override
                hyper_schur = (env not in ("0", "false", "")
                               and 0 < n_static < self._ma.m)
            else:
                hyper_schur = 8 <= n_static < self._ma.m
            registry.record(
                "GST_HYPER_SCHUR", value=env, enabled=bool(hyper_schur),
                reason=f"auto: n_static={n_static} of m={self._ma.m}")
        elif hyper_schur and not 0 < n_static < self._ma.m:
            raise ValueError(
                "hyper_schur needs both static and varying phi columns "
                f"(static={n_static} of m={self._ma.m})")
        self._schur = ((np.flatnonzero(smask), np.flatnonzero(~smask))
                       if hyper_schur else None)
        self._pallas_interpret = pallas_interpret
        if use_pallas == "auto":
            # Measured, not assumed: the blocked regime (n >= 16384, the
            # only one where this dispatch matters) is exactly where the
            # Pallas TNT lost the on-chip A/B to the XLA scan
            # (artifacts/pallas_tnt_tpu_r02.json), and at the 1e5-TOA
            # stress shape with block 4096 it VMEM-OOMs outright
            # (artifacts/BENCH_STRESS_r03.err). Auto therefore always
            # takes the XLA scan; pass use_pallas=True for A/B.
            use_pallas = False
        elif use_pallas and self._block_size is None:
            raise ValueError("use_pallas requires a tnt_block_size")
        self._use_pallas = bool(use_pallas)
        self._pspin = (config.pspin * ma.time_scale
                       if config.pspin is not None else 1.0)
        # Fused white-noise MH block (ops/pallas_white.py): the whole
        # 20-step block as one Pallas launch on TPU, dispatched through
        # custom_vmap like the Cholesky kernel. Built only for the
        # float32 frozen-model path; ``GST_PALLAS_WHITE`` (same
        # trace-time snapshot semantics as GST_PALLAS_CHOL) gates the
        # actual kernel use inside the dispatcher.
        self._white_block = None
        self._white_block_lanes = None
        self._white_mtm_block = None
        self._white_consts = None
        if dtype == jnp.float32 and len(self._ma.white_indices):
            from gibbs_student_t_tpu.ops.pallas_white import (
                build_white_consts,
                make_white_block,
            )

            wc = build_white_consts(
                self._ma,
                None if self._row_mask is None else np.asarray(
                    self._row_mask))
            self._white_consts = wc
            # only the static structure is baked in; the constant arrays
            # travel per call, so ensembles can substitute traced
            # per-pulsar constants (parallel/ensemble.py)
            self._white_block = make_white_block(wc.var)
            if self._operand_mode:
                from gibbs_student_t_tpu.ops.pallas_white import (
                    make_white_block_lanes,
                )

                # serve slot pool: per-lane consts + the tile-uniform
                # gid route the native white_mh_lanes kernel — the one
                # lanes-path MH stage that previously had no native
                # twin and fell back to the grouped XLA loop
                self._white_block_lanes = make_white_block_lanes(wc.var)
            if (config.mh.mtm_tries >= 2
                    and "white" in config.mh.mtm_blocks):
                from gibbs_student_t_tpu.ops.pallas_white import (
                    make_white_mtm_block,
                )

                # the multiple-try twin: the per-block A/B showed
                # white-block MTM is the arm whose extra evaluations
                # are cheap enough to fuse (docs/PERFORMANCE.md)
                self._white_mtm_block = make_white_mtm_block(wc.var)
        # Fused hyper MH block (ops/pallas_hyper.py): the 10-step
        # marginalized-likelihood block as one Pallas launch, with the
        # Schur block (or TNT) resident in VMEM across all proposals.
        # ``GST_PALLAS_HYPER`` gates the kernel inside the dispatcher.
        # Unlike the white block's always-on dispatcher, this one is only
        # built when the mode resolves enabled at CONSTRUCTION time:
        # with it off, the closure path still routes factorizations
        # through the Pallas Cholesky dispatch (ops/linalg.py), which is
        # what a GST_PALLAS_HYPER=0 A/B arm should measure.
        self._hyper_block = None
        self._hyper_consts = None
        if dtype == jnp.float32 and len(self._ma.hyper_indices):
            from gibbs_student_t_tpu.ops.linalg import _nhyper_mode
            from gibbs_student_t_tpu.ops.pallas_hyper import (
                _pallas_hyper_mode,
                build_hyper_consts,
                make_hyper_block,
            )

            from gibbs_student_t_tpu.ops.pallas_hyper import MAX_PALLAS_V

            cols = (self._schur[1] if self._schur is not None
                    else np.arange(self._ma.m))
            # Models past the kernel's VMEM bound keep the closure path
            # (whose factorizations still reach the Pallas Cholesky) —
            # the dispatcher's XLA fallback would route them through the
            # plain expander instead.
            want_pallas = (_pallas_hyper_mode()[0]
                           and len(cols) <= MAX_PALLAS_V)
            # Native CPU arm (GST_NHYPER): same block dispatcher, the
            # whole 10-step loop as one FFI custom call. Availability
            # is checked HERE so a forced-but-unavailable gate keeps
            # the closure path — exactly the gates-off graph.
            want_native = (_nhyper_mode()[0] and len(cols) <= 160
                           and self._ma.nparam <= 64)
            if want_pallas:
                self._hyper_consts = build_hyper_consts(self._ma, cols)
                self._hyper_block = make_hyper_block(
                    self._hyper_consts.hyp_idx, config.jitter)
            elif want_native:
                try:
                    self._hyper_consts = build_hyper_consts(self._ma,
                                                            cols)
                    self._hyper_block = make_hyper_block(
                        self._hyper_consts.hyp_idx, config.jitter)
                except ValueError:
                    # unsupported prior kinds: the fused-prior tables
                    # cannot represent this model — closure path
                    self._hyper_consts = None
                    self._hyper_block = None
        self._telemetry = bool(telemetry)
        self.metrics = metrics
        # GST_VCHOL / GST_NCHOL are consulted at trace time inside the
        # linalg dispatch; validating here too makes a typo'd value
        # fail at construction, before any compile work (satellite
        # contract: raise whenever set, independent of which path wins)
        vchol_env()
        nchol_env()
        # b-draw block-factor reuse (exact block algebra, ops/linalg.py
        # schur_eliminate docstring): only available on the Schur path,
        # auto-on there — it replaces the 4-level stacked-jitter full-m
        # factorization with one robust v-block factorization plus two
        # block substitutions, on every backend TPU included. The
        # A-block factor is shared with the hyper MH, whose failure
        # semantics are already reject-all; with reuse on, a non-PD A
        # also poisons the b-draw (NaN b -> divergence machinery)
        # instead of the old full-Sigma robust rescue — the measured
        # cost of that rescue was 4 m x m factorizations per sweep on
        # every chain for a corner only near-singular f32 transients
        # ever hit (reinit_diverged recovers those).
        renv = _bdraw_reuse_env()
        self._bdraw_reuse = (self._schur is not None
                             if renv == "auto" else renv == "1")
        # alpha-update gamma draw: the shape parameter (z + df)/2 is
        # always half-integer (z in {0,1}, df on the integer grid
        # 1..df_max), so Gamma(k/2) == 0.5 * chi^2_k == half the sum of
        # k squared standard normals — an EXACT construction with no
        # rejection loop. Platform-adaptive (docstring of
        # _fast_gamma_env); draws a different (equally exact) stream
        # than random.gamma, so flipping it changes chains in value but
        # not in law (tests/test_vchol.py pins the distribution).
        genv = _fast_gamma_env()
        self._fast_gamma = ((jax.default_backend() not in ("tpu", "axon"))
                            if genv == "auto" else genv == "1")
        # hyper-MH hoist: per-sweep precomputation of the proposal-
        # invariant pieces of the marginalized likelihood (bit-identical
        # on/off — see _hyper_hoist_env). auto -> on for CPU, where the
        # closure-path loop is the production path.
        henv = _hyper_hoist_env()
        self._hyper_hoist = ((jax.default_backend() == "cpu")
                             if henv == "auto" else henv == "1")
        # theta draw: exact chi-square Beta construction. Engages only
        # when BOTH doubled pseudo-counts are integers (the chi-square
        # identity is exact only for half-integer shapes; e.g. the
        # uniform prior's a = sz + 1 always is, a beta prior's
        # n * outlier_mean may not be) and the normal pool stays small;
        # everything else keeps random.beta.
        benv = _fast_beta_env()
        fast_beta = ((jax.default_backend() not in ("tpu", "axon"))
                     if benv == "auto" else benv == "1")
        self._beta_pool = None
        if fast_beta and config.is_outlier_model:
            n_stat = self._n_real
            if config.theta_prior == "beta":
                mk = n_stat * config.outlier_mean
                k1mm = n_stat * (1.0 - config.outlier_mean)
            else:
                mk = k1mm = 1.0
            pool = 2.0 * (n_stat + mk + k1mm)
            if (abs(2.0 * mk - round(2.0 * mk)) < 1e-9
                    and abs(2.0 * k1mm - round(2.0 * k1mm)) < 1e-9
                    and pool <= 8192.0):
                self._beta_pool = int(round(pool))
        # round-9 draw/fusion gates. GST_NWHITE/GST_NHYPER are
        # consulted inside the block dispatchers at trace time but
        # validated here too (loud-typo contract at construction);
        # GST_FAST_GAMMA_V2 / GST_FAST_THETA / GST_FUSE_STAGES resolve
        # NOW, with availability checked so a forced-but-unavailable
        # gate silently keeps the previous graph.
        nwhite_env()
        nhyper_env()
        nresid_env()
        g2env = _fast_gamma_v2_env()
        tenv = _fast_theta_env()
        fenv = fuse_stages_env()
        from gibbs_student_t_tpu.ops.linalg import _native_draws_ok

        draws_native = _native_draws_ok()
        # alpha draw v2: -log prod U + odd-parity Box-Muller plane on
        # philox streams (ops/linalg.masked_gamma_v2) — engages within
        # the fast-gamma path only; auto needs the native kernel (the
        # jnp twin alone does not beat the chi-square arm's erfinv
        # pool, tools/cpu_microbench.py gamma_{erfinv,v2})
        self._fast_gamma_v2 = (self._fast_gamma
                               and (draws_native if g2env == "auto"
                                    else g2env == "1"))
        self._gamma_jmax = (int(max(config.df_max, config.tdf)) + 1) // 2
        # theta draw for FRACTIONAL pseudo-counts: native
        # Marsaglia-Tsang beta (the flagship prior GST_FAST_BETA
        # measured out); half-integer priors keep the chi-square pool
        self._fast_theta = (config.is_outlier_model
                            and self._beta_pool is None
                            and (draws_native if tenv == "auto"
                                 else tenv == "1"))
        # hyper+draws megastage (GST_FUSE_STAGES): schur + the whole
        # hyper MH block + the b-draw as ONE multi-stage FFI dispatch
        self._fuse_consts = None
        mtm_hyper = (config.mh.mtm_tries >= 2
                     and "hyper" in config.mh.mtm_blocks)
        if (fenv != "0" and draws_native and self._schur is not None
                and self._bdraw_reuse and not mtm_hyper
                and dtype == jnp.float32
                and len(self._ma.hyper_indices)
                and self._ma.nparam <= 64
                and len(self._schur[1]) <= 160):
            if self._hyper_consts is not None:
                self._fuse_consts = self._hyper_consts
            else:
                from gibbs_student_t_tpu.ops.pallas_hyper import (
                    build_hyper_consts,
                )

                try:
                    self._fuse_consts = build_hyper_consts(
                        self._ma, self._schur[1])
                except ValueError:
                    self._fuse_consts = None  # unsupported prior kinds
            if (self._fuse_consts is not None
                    and len(self._fuse_consts.hyp_idx) > 16):
                self._fuse_consts = None
        self._fuse_stages = self._fuse_consts is not None
        # donated chunk buffers: chunk k's ChainState input buffers are
        # reused for chunk k+1's outputs instead of re-allocating
        # ~per-chunk state each dispatch. sample() defends the caller's
        # state object with ONE up-front copy per call; the
        # double-buffered spool flush snapshots the checkpoint state
        # before the next dispatch invalidates it (chunked_sweep_loop
        # snapshot_fn). auto -> on.
        self._donate = donate_resolved()
        # the chunk program goes through the explicit lower->compile
        # introspection path (obs/introspect.py): same compile count as
        # plain jit, but compile wall time + XLA cost/memory analyses
        # land in the process log (and, via the registry getter, as
        # `compile` events when a MetricsRegistry is attached)
        from gibbs_student_t_tpu.obs.introspect import introspect_jit

        donate = (0,) if self._donate else ()
        self._chunk_fn = introspect_jit(
            jax.jit(self._make_chunk_fn(), static_argnames=("length",),
                    donate_argnums=donate),
            label=f"jaxgibbs_chunk_c{nchains}",
            registry=lambda: self.metrics,
            static_argnames=("length",),
            donate_argnums=donate)
        self._prop_cov_fn = (jax.jit(self._prop_cov_update)
                             if config.mh.adapt_cov else None)
        self.last_state: Optional[ChainState] = None

    # ------------------------------------------------------------------
    # state construction
    # ------------------------------------------------------------------

    def init_state(self, x0: Optional[np.ndarray] = None,
                   seed: int = 0) -> ChainState:
        ma, cfg = self._ma, self.config
        rng = np.random.default_rng(seed)
        if x0 is None:
            x0 = np.stack([ma.x_init(rng) for _ in range(self.nchains)])
        x0 = np.asarray(x0, dtype=self.dtype)
        if x0.ndim == 1:
            x0 = np.broadcast_to(x0, (self.nchains, len(x0))).copy()
        n, m, c = ma.n, ma.m, self.nchains
        z0 = jnp.full((c, n), 1.0 if cfg.z_init_ones else 0.0,
                      dtype=self.dtype)
        alpha0 = jnp.full((c, n), 1.0 if cfg.vary_alpha else cfg.alpha,
                          dtype=self.dtype)
        if self._row_mask is not None:
            # padded TOA rows never count as outliers and carry unit scale
            z0 = jnp.where(self._row_mask, z0, 0.0)
            alpha0 = jnp.where(self._row_mask, alpha0, 1.0)
        if cfg.mh.adapt_cov:
            # start from the identity on each block's coordinates: the
            # first chunk-boundary estimate replaces it almost at once
            p = ma.nparam
            L0 = np.zeros((2, p, p), dtype=np.float64)
            for k, ind in enumerate((ma.white_indices, ma.hyper_indices)):
                L0[k, ind, ind] = 1.0
            cov0 = jnp.broadcast_to(jnp.asarray(L0, self.dtype),
                                    (c, 2, p, p))
        else:
            cov0 = jnp.zeros((c, 0), dtype=self.dtype)
        return ChainState(
            x=jnp.asarray(x0),
            b=jnp.zeros((c, m), dtype=self.dtype),
            z=z0,
            alpha=alpha0,
            theta=jnp.full((c,), cfg.outlier_mean, dtype=self.dtype),
            df=jnp.full((c,), float(cfg.tdf), dtype=self.dtype),
            pout=jnp.zeros((c, n), dtype=self.dtype),
            acc_white=jnp.zeros((c,), dtype=self.dtype),
            acc_hyper=jnp.zeros((c,), dtype=self.dtype),
            mh_log_scale=jnp.zeros((c, 2), dtype=self.dtype),
            mh_cov_chol=cov0,
        )

    # ------------------------------------------------------------------
    # single-chain sweep
    # ------------------------------------------------------------------

    def _lnprior(self, x):
        return lnprior(self._ma, x, jnp)

    def _mh_draws(self, key, ind: np.ndarray, nsteps: int, jump_scale,
                  cov_chol=None):
        """All of one MH block's randomness, drawn up front as dense
        ``(nsteps, p)`` jump vectors plus log-uniform accept draws.

        Default: the reference's jump kernel — one random coordinate per
        step with the discrete scale mixture folded in (reference
        gibbs.py:91-97/124-130), built one-hot by iota comparison
        (scatters lower poorly on TPU). With ``cov_chol`` (a (p, p)
        block-embedded Cholesky factor, MHConfig.adapt_cov), the step
        direction becomes ``L @ xi`` — a joint proposal shaped by the
        chain population's empirical covariance.

        Batching the draws replaces ~4 threefry dispatches *per step*
        with 4 per block — and hands the fused MH kernels the identical
        random stream the XLA loops consume, so kernel-on/off A/Bs
        differ only by reduction order."""
        mh = self.config.mh
        sigma = mh.sigma_per_param * len(ind) * jump_scale
        sizes = jnp.asarray(mh.scale_sizes, dtype=self.dtype)
        logits = jnp.log(jnp.asarray(mh.scale_probs, dtype=self.dtype))
        kc, kp, kn, ku = random.split(key, 4)
        scales = sizes[random.categorical(kc, logits, shape=(nsteps,))]
        p = self._ma.nparam
        if cov_chol is None:
            pars = jnp.asarray(ind)[
                random.randint(kp, (nsteps,), 0, len(ind))]
            jumps = (random.normal(kn, (nsteps,), dtype=self.dtype)
                     * sigma * scales)
            cols = jnp.arange(p)
            dx = jnp.where(cols[None, :] == pars[:, None],
                           jumps[:, None], jnp.zeros((), self.dtype))
        else:
            xi = random.normal(kn, (nsteps, p), dtype=self.dtype)
            dx = (sigma * scales)[:, None] * (xi @ cov_chol.T)
        logus = jnp.log(random.uniform(ku, (nsteps,), dtype=self.dtype))
        return dx, logus

    def _mh_block(self, x, key, ind: np.ndarray, nsteps: int, loglike_fn,
                  jump_scale=1.0, cov_chol=None, lnprior_fn=None):
        """Branchless random-walk Metropolis on a coordinate block
        (reference gibbs.py:80-143). ``jump_scale`` multiplies the jump
        sigma (the chain's adapted log-scale, exp'd; exactly 1 when
        adaptation is off — the per-step ``scale`` drawn in ``_mh_draws``
        is the discrete mixture draw, a different thing); ``cov_chol``
        switches to population-covariance joint proposals.
        ``lnprior_fn`` overrides the prior evaluation — the traced
        per-lane/per-pulsar model's priors when the sweep runs on an
        operand model instead of the backend's own frozen one."""
        dx, logus = self._mh_draws(key, ind, nsteps, jump_scale, cov_chol)
        lnprior_fn = lnprior_fn or self._lnprior

        ll0 = loglike_fn(x)
        lp0 = lnprior_fn(x)

        def body(i, carry):
            x, ll0, lp0, acc = carry
            q = x + dx[i]
            ll1 = loglike_fn(q)
            lp1 = lnprior_fn(q)
            accept = (ll1 + lp1) - (ll0 + lp0) > logus[i]
            x = jnp.where(accept, q, x)
            ll0 = jnp.where(accept, ll1, ll0)
            lp0 = jnp.where(accept, lp1, lp0)
            return (x, ll0, lp0, acc + accept)

        x, _, _, acc = lax.fori_loop(
            0, nsteps, body,
            (x, ll0, lp0, jnp.zeros((), dtype=self.dtype)))
        return x, acc / nsteps

    def _mtm_draws(self, key, ind: np.ndarray, nsteps: int,
                   jump_scale=1.0, cov_chol=None):
        """All of one MTM block's randomness: per step, K candidate
        jumps, K-1 reference jumps, K Gumbel selection draws, one
        log-uniform accept draw — one key schedule shared by the XLA
        closure block and the fused white-MTM kernel, so kernel on/off
        runs consume identical streams (the ``_mh_draws`` discipline).
        The log-uniform draws the two ``_mh_draws`` calls also produce
        are discarded — unused trace outputs, so XLA dead-code-
        eliminates the threefry work."""
        K = self.config.mh.mtm_tries
        kc, kr, kg, ku = random.split(key, 4)
        dx, _ = self._mh_draws(kc, ind, nsteps * K, jump_scale, cov_chol)
        dx = dx.reshape(nsteps, K, -1)
        dxr, _ = self._mh_draws(kr, ind, nsteps * (K - 1), jump_scale,
                                cov_chol)
        dxr = dxr.reshape(nsteps, K - 1, -1)
        gumb = random.gumbel(kg, (nsteps, K), dtype=self.dtype)
        logus = jnp.log(random.uniform(ku, (nsteps,), dtype=self.dtype))
        return dx, dxr, gumb, logus

    def _mtm_block(self, x, key, ind: np.ndarray, nsteps: int,
                   loglike_fn, jump_scale=1.0, cov_chol=None,
                   lnprior_fn=None):
        """Multiple-try Metropolis on a coordinate block
        (MHConfig.mtm_tries; MTM(II) of Liu, Liang & Wong 2000 with
        importance weights w = pi, valid because the jump kernel is
        symmetric — coordinate/scale choices are position-independent
        and the Gaussian jump is centered).

        Per step: K iid candidates from the same jump kernel as
        ``_mh_block``, one selected by Gumbel-max on its log posterior
        weight, K-1 reference points drawn around the SELECTED
        candidate plus the current point itself, accept on
        ``logsumexp(candidate weights) - logsumexp(reference weights)``.
        All randomness precomputed up front (``_mtm_draws``), (2K-1)
        likelihood evaluations per step."""
        dx, dxr, gumb, logus = self._mtm_draws(key, ind, nsteps,
                                               jump_scale, cov_chol)
        lnprior_fn = lnprior_fn or self._lnprior

        def w(q):
            return loglike_fn(q) + lnprior_fn(q)

        w_batch = jax.vmap(w)
        wx0 = w(x)

        def body(i, carry):
            x, wx, acc = carry
            cands = x[None, :] + dx[i]                     # (K, p)
            lw = w_batch(cands)                            # (K,)
            j = jnp.argmax(lw + gumb[i])                   # Gumbel-max
            y = cands[j]
            refs = y[None, :] + dxr[i]                     # (K-1, p)
            lwr = jnp.concatenate([w_batch(refs), wx[None]])
            num = logsumexp(lw)
            den = logsumexp(lwr)
            delta = num - den
            # -inf - -inf = NaN (every weight dead on both sides) must
            # reject, same as the single-try blocks' NaN semantics
            accept = jnp.where(jnp.isnan(delta), False, delta > logus[i])
            x = jnp.where(accept, y, x)
            wx = jnp.where(accept, lw[j], wx)
            return (x, wx, acc + accept)

        x, _, acc = lax.fori_loop(
            0, nsteps, body,
            (x, wx0, jnp.zeros((), dtype=self.dtype)))
        return x, acc / nsteps

    def _block_cov(self, state: ChainState, k: int):
        """The block's proposal Cholesky from the state, or None when
        population-covariance proposals are off."""
        return (state.mh_cov_chol[k] if self.config.mh.adapt_cov
                else None)

    def _prop_cov_update(self, state: ChainState) -> ChainState:
        """Re-estimate each block's proposal Cholesky from the chain
        population (MHConfig.adapt_cov; called at chunk boundaries
        while sweep < adapt_until, then never again).

        The population makes this estimate what a single chain can
        never have: ``nchains`` independent-draw-ish samples at one
        time point, with no autocorrelation window to tune. Shrinkage
        toward the diagonal plus a tiny ridge keeps a collapsed or
        small population factorable; a non-finite factor (degenerate
        population) keeps the previous one."""
        mh = self.config.mh
        x = state.x                                   # (C, p)
        C, p = x.shape
        xm = x - jnp.mean(x, axis=0)
        cov = (xm.T @ xm) / max(C - 1, 1)
        Lfull = jnp.zeros((p, p), x.dtype)
        new = []
        for k, ind in enumerate((self._ma.white_indices,
                                 self._ma.hyper_indices)):
            prev = state.mh_cov_chol[0, k]            # shared across chains
            if len(ind) == 0:
                new.append(prev)
                continue
            sub = cov[np.ix_(ind, ind)]
            dsub = jnp.diag(jnp.diagonal(sub))
            sub = (1.0 - mh.cov_shrinkage) * sub + mh.cov_shrinkage * dsub
            sub = sub + (1e-8 * jnp.mean(jnp.diagonal(sub))
                         * jnp.eye(len(ind), dtype=sub.dtype))
            L = jnp.linalg.cholesky(sub)
            Lk = Lfull.at[np.ix_(ind, ind)].set(L)
            ok = jnp.isfinite(Lk).all()
            new.append(jnp.where(ok, Lk, prev))
        stacked = jnp.broadcast_to(jnp.stack(new), (C, 2, p, p))
        return state._replace(mh_cov_chol=stacked)

    def _resolve(self, ma: ModelArrays | None):
        """(ma, row_mask, block_size, statistical_n) for a sweep stage.
        ``ma=None`` selects the backend's own (possibly padded) model; the
        ensemble passes a traced per-pulsar pytree whose padding (if any)
        is carried by ``ma.row_mask`` — its statistical n is then a traced
        scalar so each vmapped pulsar uses its own real TOA count."""
        if ma is None:
            return self._ma, self._row_mask, self._block_size, self._n_real
        if ma.row_mask is not None:
            return ma, ma.row_mask, None, jnp.sum(ma.row_mask)
        return ma, None, None, ma.n

    def _masked_nvec(self, ma, mask, xq, az):
        """alpha^z-scaled white variances; padded rows pinned to 1 so
        they add 0 to every log/quadratic reduction."""
        nv = az * ndiag(ma, xq, jnp)
        return nv if mask is None else jnp.where(mask, nv, 1.0)

    def _sweep(self, state: ChainState, key, ma: ModelArrays | None = None,
               sweep=None, fused: FusedConsts | None = None,
               block_gates=None) -> ChainState:
        """One full Gibbs sweep. ``ma`` defaults to the backend's frozen
        model (embedded as constants); the ensemble path passes a traced
        per-pulsar ModelArrays pytree instead (parallel/ensemble.py),
        optionally with ``fused`` — that pulsar's fused-MH constant
        arrays — so the traced model still reaches the fused kernels.
        ``sweep`` is the (traced) sweep index, needed only when MH
        adaptation is enabled (MHConfig.adapt_until).

        ``block_gates`` (adaptive block scans, serve/adapt.py;
        arXiv:1808.09047) is an optional traced ``(NBLOCKS,)`` 0/1
        vector enabling each conditional block this sweep: a gated-off
        block's draw is computed and DISCARDED (its state field carries
        over and every downstream conditional sees the carried value),
        which keeps the sweep a valid random-scan composition of Gibbs
        moves while the RNG key schedule stays fixed. ``None`` — every
        non-adaptive caller — emits the pre-adaptive graph verbatim
        (the gates-off bitwise pin)."""
        keys = random.split(key, 7)
        # block_span: trace-time XLA op naming (obs/tracing.py) so a
        # --trace-dir capture attributes device time per Gibbs block;
        # zero runtime cost (HLO metadata only)
        with block_span("gibbs/white_mh"):
            x, acc_w, nvec = self._sweep_white(state, keys[0], ma, fused,
                                               block_gates=block_gates)
        ma_r, _, bs, _ = self._resolve(ma)
        # per-sweep inner products (reference gibbs.py:302-304), via the
        # fused dense/blocked reduction (ops/tnt.py). The serve slot
        # pool's per-lane traced basis routes through the lanes Gram
        # dispatcher instead — native per-group kernel when available,
        # the identical per-lane jnp expressions otherwise.
        with block_span("gibbs/tnt_reduction"):
            if (self._operand_mode and ma is not None and bs is None
                    and fused is not None and fused.gid is not None):
                TNT, d, const_white = tnt_gram_lanes(ma_r.T, ma_r.y,
                                                     nvec, fused.gid)
            else:
                TNT, d, const_white = tnt_products(ma_r.T, ma_r.y, nvec,
                                                   bs)
        return self._sweep_rest(state, x, acc_w, TNT, d, const_white,
                                keys[1:], ma, sweep, fused,
                                block_gates=block_gates)

    def _sweep_white(self, state: ChainState, kw, ma: ModelArrays | None,
                     fused: FusedConsts | None = None, block_gates=None):
        """Sweep stage 1: the white-noise MH block
        (reference gibbs.py:114-143). Returns the updated parameter
        vector, the block acceptance rate, and the post-block ``nvec``.

        On a float32 model the whole block runs as ONE fused Pallas
        launch (ops/pallas_white.py) when enabled — the 20 sequential
        steps are pure elementwise work whose XLA form is bound by
        per-step fixed costs, not arithmetic (docs/PERFORMANCE.md
        roofline). The backend's own frozen model bakes the constants
        into the trace; an ensemble's traced per-pulsar model reaches
        the same kernel through ``fused``. float64 runs keep the XLA
        closure loop."""
        ma_in = ma
        ma, mask, bs, _ = self._resolve(ma)
        cfg = self.config
        x, b, z, alpha = state.x, state.b, state.z, state.alpha

        az = alpha ** z
        if len(ma.white_indices):
            Tb = matvec_blocked(ma.T, b, bs)
            jump_scale = jnp.exp(state.mh_log_scale[0])
            cov_w = self._block_cov(state, 0)
            mtm_w = (cfg.mh.mtm_tries >= 2
                     and "white" in cfg.mh.mtm_blocks)
            consts_ok = (ma_in is None
                         or (fused is not None
                             and fused.white_rows is not None))
            use_fused = (not mtm_w and self._white_block is not None
                         and consts_ok)
            use_fused_mtm = (mtm_w and self._white_mtm_block is not None
                             and consts_ok)
            if use_fused or use_fused_mtm:
                if ma_in is None:
                    wrows = self._white_consts.rows
                    wspecs = self._white_consts.specs
                else:
                    wrows, wspecs = fused.white_rows, fused.white_specs
                yred = ma.y - Tb
                if use_fused_mtm:
                    dx, dxr, gumb, logus = self._mtm_draws(
                        kw, ma.white_indices, cfg.mh.n_white_steps,
                        jump_scale, cov_w)
                    x, acc_w = self._white_mtm_block(
                        x, az, yred * yred, dx, dxr, gumb, logus,
                        wrows, wspecs)
                else:
                    dx, logus = self._mh_draws(
                        kw, ma.white_indices, cfg.mh.n_white_steps,
                        jump_scale, cov_w)
                    if (self._white_block_lanes is not None
                            and ma_in is not None
                            and fused is not None
                            and fused.gid is not None):
                        # serve slot pool: per-lane consts + gid route
                        # the native lanes kernel (fallback: the same
                        # grouped XLA loop this call always produced)
                        x, acc_w = self._white_block_lanes(
                            x, az, yred * yred, dx, logus, wrows,
                            wspecs, fused.gid)
                    else:
                        x, acc_w = self._white_block(x, az, yred * yred,
                                                     dx, logus, wrows,
                                                     wspecs)
            else:
                def ll_white(xq):
                    nvec = self._masked_nvec(ma, mask, xq, az)
                    yred = ma.y - Tb
                    return -0.5 * (jnp.sum(jnp.log(nvec))
                                   + jnp.sum(yred * yred / nvec))

                block = self._mtm_block if mtm_w else self._mh_block
                # a traced per-lane/per-pulsar model evaluates ITS
                # priors, not the template's (they ride prior_specs,
                # a data field of the stacked operand model)
                lnp = (None if ma_in is None
                       else (lambda q: lnprior(ma, q, jnp)))
                x, acc_w = block(x, kw, ma.white_indices,
                                 cfg.mh.n_white_steps, ll_white,
                                 jump_scale=jump_scale,
                                 cov_chol=cov_w, lnprior_fn=lnp)
        else:
            acc_w = jnp.zeros((), dtype=self.dtype)
        if block_gates is not None:
            # adaptive scan: a thinned white block keeps the carried x
            # (the draw above is computed and discarded — key schedule
            # untouched); nvec below is then rebuilt from the CARRIED
            # x, so the TNT reduction and every later conditional see
            # a consistent state
            g_w = block_gates[BLOCK_WHITE].astype(bool)
            x = jnp.where(g_w, x, state.x)
            acc_w = jnp.where(g_w, acc_w, jnp.zeros((), acc_w.dtype))
        return x, acc_w, self._masked_nvec(ma, mask, x, az)

    def _sweep_rest(self, state: ChainState, x, acc_w, TNT, d, const_white,
                    keys, ma: ModelArrays | None, sweep=None,
                    fused: FusedConsts | None = None,
                    block_gates=None) -> ChainState:
        """Sweep stages 2-7: everything conditioned on the TNT/d inner
        products (hyper MH, coefficient draw, theta/z/alpha/df)."""
        ma_in = ma
        ma, mask, bs, n = self._resolve(ma)
        cfg = self.config
        m = ma.m
        kh, kb, kt, kz, ka, kd = keys
        b, z, alpha, theta, df = (state.b, state.z, state.alpha,
                                  state.theta, state.df)
        # adaptive block scans: x as it entered this stage (the white
        # block's — possibly carried — output); the hyper gate selects
        # back to it so downstream conditionals see the carried value
        x_in = x

        # --- hyper MH block on the marginalized likelihood -------------
        # (reference gibbs.py:80-111, 288-329)
        jump_scale_h = jnp.exp(state.mh_log_scale[1])
        bdraw_reuse = (self._bdraw_reuse and self._schur is not None
                       and len(ma.hyper_indices))
        cov_h = self._block_cov(state, 1)
        mtm_h = (cfg.mh.mtm_tries >= 2
                 and "hyper" in cfg.mh.mtm_blocks)
        # GST_FUSE_STAGES: Schur pre-elimination, the whole hyper MH
        # block and the b-draw as ONE multi-stage FFI dispatch
        # (ops/linalg.fused_hyper_draws). Same operands and randomness
        # as the per-stage path; with the gate unresolved at
        # construction the per-stage graph below is emitted verbatim.
        # The serve slot pool (operand_mode) reaches the same megastage
        # with a traced per-lane model: the fused constants arrive as
        # call-time operands through ``fused`` and the group-id routes
        # the lanes kernel (ops/linalg._fused_hyper_lanes_dispatcher).
        serve_ops = (self._operand_mode and ma_in is not None
                     and fused is not None and fused.gid is not None)
        fuse = (self._fuse_stages and len(ma.hyper_indices) > 0
                and (ma_in is None
                     or (serve_ops and fused.hyper_K is not None)))
        if fuse:
            s_i, v_i = self._schur
            hc = self._fuse_consts
            if ma_in is None:
                Kc = jnp.asarray(hc.K, self.dtype)
                selc = jnp.asarray(hc.phi_sel, self.dtype)
                phistc = jnp.asarray(hc.phiinv_static, self.dtype)
                specsc = jnp.asarray(hc.specs, self.dtype)
                ld_static = jnp.asarray(hc.logdet_phi_static,
                                        self.dtype)
                gid = None
            else:
                Kc, selc = fused.hyper_K, fused.hyper_sel
                phistc = fused.hyper_phiinv_static
                specsc = fused.hyper_specs
                ld_static = fused.hyper_logdet_phi_static
                gid = fused.gid
            phiinv_s = phiinv_logdet(ma, x, jnp)[0][s_i]
            dxh, logus = self._mh_draws(
                kh, ma.hyper_indices, cfg.mh.n_hyper_steps,
                jump_scale_h, cov_h)
            xi = random.normal(kb, (m,), dtype=self.dtype)
            base0 = const_white - 0.5 * ld_static
            with block_span("gibbs/hyper_mh"):
                x, acc_h, y_v, isd_v, y_s, isd_a = fused_hyper_draws(
                    TNT[np.ix_(s_i, s_i)] + jnp.diag(phiinv_s),
                    TNT[np.ix_(s_i, v_i)], TNT[np.ix_(v_i, v_i)],
                    d[s_i], d[v_i], x, dxh, logus, xi, base0,
                    Kc, selc, phistc, specsc,
                    hc.hyp_idx, cfg.jitter,
                    (cfg.jitter, 1e-4, 1e-2, 1e-1), gid=gid)
            with block_span("gibbs/b_draw"):
                b = (jnp.zeros(m, dtype=self.dtype)
                     .at[s_i].set(y_s * isd_a)
                     .at[v_i].set(y_v * isd_v))
        if not fuse and self._schur is not None and len(ma.hyper_indices):
            # Once per sweep: eliminate the phi-static columns so each
            # proposal factors only the varying block — algebra and
            # failure semantics in ops/linalg.py schur_eliminate. Shared
            # by the fused and closure paths below; with b-draw reuse
            # the A-block factor pieces ride along for the coefficient
            # draw's block-assembled factorization.
            s_i, v_i = self._schur
            phiinv_s = phiinv_logdet(ma, x, jnp)[0][s_i]  # x-independent
            schur_out = schur_eliminate(
                TNT[np.ix_(s_i, s_i)] + jnp.diag(phiinv_s),
                TNT[np.ix_(s_i, v_i)], TNT[np.ix_(v_i, v_i)],
                d[s_i], d[v_i], cfg.jitter,
                return_factor=bdraw_reuse)
            S0, rt, quad_s, logdetA = schur_out[:4]
            if bdraw_reuse:
                La, isd_a, U_B, u_s = schur_out[4]
        use_fused_h = (not fuse and not mtm_h
                       and self._hyper_block is not None
                       and len(ma.hyper_indices)
                       and (ma_in is None
                            or (fused is not None
                                and fused.hyper_K is not None)))
        if use_fused_h:
            # Fused path (ops/pallas_hyper.py): draws precomputed with
            # the same key schedule, the whole block one Pallas launch.
            dxh, logus = self._mh_draws(
                kh, ma.hyper_indices, cfg.mh.n_hyper_steps, jump_scale_h,
                cov_h)
            if ma_in is None:
                hc = self._hyper_consts
                hK, hsel, hspecs = hc.K, hc.phi_sel, hc.specs
                h_phiinv_static = jnp.asarray(hc.phiinv_static,
                                              self.dtype)
                h_logdet_static = hc.logdet_phi_static
            else:
                hK, hsel, hspecs = (fused.hyper_K, fused.hyper_sel,
                                    fused.hyper_specs)
                h_phiinv_static = fused.hyper_phiinv_static
                h_logdet_static = fused.hyper_logdet_phi_static
            if self._schur is not None:
                base = (const_white + 0.5 * (quad_s - logdetA)
                        - 0.5 * h_logdet_static)
                Sh, rh = S0, rt
            else:
                Sh, rh = TNT, d
                base = const_white - 0.5 * h_logdet_static
            # phiinv_static is exactly zero on the Schur path for
            # per-block static/varying splits, but a mixed ecorr block
            # (const and sampled groups in one block) puts static-phi
            # columns inside the varying subset — their constant prior
            # precision rides on the diagonal here, matching the closure
            # path's full phiinv[v_i].
            dS0 = (jnp.diagonal(Sh, axis1=-2, axis2=-1)
                   + h_phiinv_static)
            with block_span("gibbs/hyper_mh"):
                x, acc_h = self._hyper_block(x, Sh, dS0, rh, base, dxh,
                                             logus, hK, hsel, hspecs)
        elif not fuse and len(ma.hyper_indices):
            # GST_HYPER_HOIST: the matrix block of the marginalized
            # likelihood is proposal-invariant — hoist its diagonal out
            # of the 10-step loop and build each proposal's equilibrated
            # matrix in one fused pass (precond_quad_logdet_hoisted)
            # instead of materializing S0 + diag(phiinv) then
            # re-equilibrating it. Same floats in the same association
            # order: chains are bit-identical hoist on/off.
            if self._schur is not None:
                if self._hyper_hoist:
                    dS0 = jnp.diagonal(S0, axis1=-2, axis2=-1)

                    def ll_hyper(xq):
                        phiinv, logdet_phi = phiinv_logdet(ma, xq, jnp)
                        quad_v, logdet_S = precond_quad_logdet_hoisted(
                            S0, dS0, phiinv[v_i], rt, cfg.jitter)
                        ll = const_white + 0.5 * (quad_s + quad_v
                                                  - logdetA - logdet_S
                                                  - logdet_phi)
                        return jnp.where(jnp.isfinite(ll), ll, -jnp.inf)
                else:
                    def ll_hyper(xq):
                        phiinv, logdet_phi = phiinv_logdet(ma, xq, jnp)
                        Sv = S0 + jnp.diag(phiinv[v_i])
                        quad_v, logdet_S = precond_quad_logdet(Sv, rt,
                                                               cfg.jitter)
                        ll = const_white + 0.5 * (quad_s + quad_v
                                                  - logdetA - logdet_S
                                                  - logdet_phi)
                        return jnp.where(jnp.isfinite(ll), ll, -jnp.inf)
            else:
                if self._hyper_hoist:
                    dTNT = jnp.diagonal(TNT, axis1=-2, axis2=-1)

                    def ll_hyper(xq):
                        phiinv, logdet_phi = phiinv_logdet(ma, xq, jnp)
                        quad, logdet_sigma = precond_quad_logdet_hoisted(
                            TNT, dTNT, phiinv, d, cfg.jitter)
                        ll = const_white + 0.5 * (quad - logdet_sigma
                                                  - logdet_phi)
                        return jnp.where(jnp.isfinite(ll), ll, -jnp.inf)
                else:
                    def ll_hyper(xq):
                        phiinv, logdet_phi = phiinv_logdet(ma, xq, jnp)
                        Sigma = TNT + jnp.diag(phiinv)
                        quad, logdet_sigma = precond_quad_logdet(
                            Sigma, d, cfg.jitter)
                        ll = const_white + 0.5 * (quad - logdet_sigma
                                                  - logdet_phi)
                        return jnp.where(jnp.isfinite(ll), ll, -jnp.inf)

            block = self._mtm_block if mtm_h else self._mh_block
            lnp_h = (None if ma_in is None
                     else (lambda q: lnprior(ma, q, jnp)))
            with block_span("gibbs/hyper_mh"):
                x, acc_h = block(x, kh, ma.hyper_indices,
                                 cfg.mh.n_hyper_steps, ll_hyper,
                                 jump_scale=jump_scale_h,
                                 cov_chol=cov_h, lnprior_fn=lnp_h)
        elif not fuse:
            acc_h = jnp.zeros((), dtype=self.dtype)

        if block_gates is not None:
            g_h = block_gates[BLOCK_HYPER].astype(bool)
            x = jnp.where(g_h, x, x_in)
            acc_h = jnp.where(g_h, acc_h, jnp.zeros((), acc_h.dtype))
            if fuse:
                # the megastage drew b jointly with the (possibly
                # discarded) hyper proposal — b's gate ties to hyper's
                b = jnp.where(g_h & block_gates[BLOCK_B].astype(bool),
                              b, state.b)

        # --- coefficient draw b ~ N(Sigma^-1 d, Sigma^-1) --------------
        # (reference gibbs.py:145-182; always-redraw, see numpy_backend).
        # The draw cannot MH-reject, so it uses the escalating-jitter
        # factorization (the reference's SVD->QR fallback role,
        # gibbs.py:168-178). The fused megastage above already drew b.
        if not fuse:
            with block_span("gibbs/b_draw"):
                phiinv, _ = phiinv_logdet(ma, x, jnp)
                xi = random.normal(kb, (m,), dtype=self.dtype)
                if bdraw_reuse:
                    # Block-factor reuse: the sweep already paid for
                    # chol(A) (schur_eliminate, once per sweep) and the
                    # v-block is the only part phi-varying — so factor just
                    # S_v = S0 + diag(phiinv_v) at the accepted x
                    # (escalating jitters preserve the draw's cannot-fail
                    # contract on that block) and assemble the permuted
                    # full factor blockwise (ops/linalg.py schur_eliminate
                    # docstring) instead of re-factoring Sigma from
                    # scratch through the 4-level stacked-jitter
                    # robust_precond_cholesky. Exact block algebra; the xi
                    # -> b map differs from the full-factor path by a
                    # distribution-preserving rotation, so on/off chains
                    # agree in law (and the factor reconstructs Sigma to
                    # f64 roundoff — tests/test_vchol.py pins both).
                    Sv = S0 + jnp.diag(phiinv[v_i])
                    ns = len(s_i)
                    # factor + backward draw as ONE operation: on the
                    # native path (GST_NCHOL) a single fused custom call
                    # that escalates jitters only for chain tiles whose
                    # first level failed; otherwise exactly the old
                    # stacked-jitter robust_precond_cholesky +
                    # backward_solve composition (ops/linalg.py).
                    y_v, isd_v, _ = robust_precond_draw(
                        Sv, rt, xi[ns:],
                        jitters=(cfg.jitter, 1e-4, 1e-2, 1e-1))
                    hi = jax.lax.Precision.HIGHEST
                    wty = jnp.matmul(
                        U_B, (isd_v * y_v)[..., None], precision=hi)[..., 0]
                    y_s = backward_solve(La, u_s + xi[:ns] - wty)
                    b = (jnp.zeros(m, dtype=self.dtype)
                         .at[s_i].set(y_s * isd_a)
                         .at[v_i].set(y_v * isd_v))
                else:
                    Sigma = TNT + jnp.diag(phiinv)
                    # b = mean + fluct = D^-1/2 L^-T (u + xi): the forward
                    # solve rides along with the factorization and the
                    # backward substitution is fused into the same
                    # operation (reference gibbs.py:169-180's mn + Li*xi)
                    y, isd, _ = robust_precond_draw(
                        Sigma, d, xi,
                        jitters=(cfg.jitter, 1e-4, 1e-2, 1e-1))
                    b = y * isd
            if block_gates is not None:
                # tied to the hyper gate (see BLOCK_B) so both b paths
                # thin identically — the law cannot depend on which
                # lowering a lane took
                b = jnp.where(block_gates[BLOCK_HYPER].astype(bool)
                              & block_gates[BLOCK_B].astype(bool),
                              b, state.b)

        # the (n, m) residual matvec between the draws and the z/df
        # conditionals (FUTURE.md #2's glue): dispatched through the
        # GST_NCHOL-family resid arm (GST_NRESID) for a frozen dense
        # basis; gates-off (and traced/blocked bases) keep the old
        # matmul verbatim
        if (bs is None and nresid_active()
                and not isinstance(ma.T, jax.core.Tracer)):
            resid = residual_matvec(jnp.asarray(ma.T),
                                    jnp.asarray(ma.y), b)
        elif bs is None and serve_ops and nresid_active():
            resid = residual_matvec_lanes(ma.T, ma.y, b, fused.gid)
        else:
            resid = ma.y - matvec_blocked(ma.T, b, bs)
        nvec0 = ndiag(ma, x, jnp)
        if mask is not None:
            nvec0 = jnp.where(mask, nvec0, 1.0)

        # --- outlier fraction theta ~ Beta (reference gibbs.py:185-198) -
        if cfg.is_outlier_model:
            if cfg.theta_prior == "beta":
                mk = n * cfg.outlier_mean
                k1mm = n * (1.0 - cfg.outlier_mean)
            else:
                mk = k1mm = 1.0
            sz = jnp.sum(z)
            if self._beta_pool is not None and (ma_in is None
                                                or serve_ops):
                # GST_FAST_BETA: Beta(a, b) = X / (X + Y) with
                # X ~ 0.5 chi2_2a, Y ~ 0.5 chi2_2b — exact for the
                # half-integer shapes this model produces (z sums are
                # integers, the doubled pseudo-counts were checked
                # integral at construction). 2a + 2b = pool is
                # z-independent, so ONE normal pool serves both: the
                # first 2a squares (masked sum) are X, the last 2b
                # (the flipped mask) are Y — disjoint, hence
                # independent. Replaces random.beta's two per-element
                # rejection While loops (the same CPU cost profile as
                # the GST_FAST_GAMMA alpha draw) with fixed-shape
                # masked reductions through the masked_chisq dispatch.
                pool = self._beta_pool
                xs = random.normal(kt, (pool,), dtype=self.dtype)
                a2 = (2.0 * (sz + mk)).astype(self.dtype)
                ga = masked_chisq(xs, a2)
                if ma_in is None:
                    b2 = jnp.asarray(float(pool), self.dtype) - a2
                else:
                    # serve lane: the lane's own (possibly traced) TOA
                    # count, not the template pool — identical bits for
                    # a matching tenant (all quantities are exact small
                    # integers in f32), correct law for a padded one
                    b2 = (2.0 * (n - sz + k1mm)).astype(self.dtype)
                gb = masked_chisq(jnp.flip(xs, -1), b2)
                theta = ga / (ga + gb)
            elif self._fast_theta and (ma_in is None or serve_ops):
                # GST_FAST_THETA: native fractional Beta via two
                # in-kernel Marsaglia-Tsang gammas per chain
                # (ops/linalg.beta_fractional) — the flagship beta
                # prior whose fractional pseudo-counts the chi-square
                # pool cannot represent. Exact rejection sampler,
                # different (equally exact) stream than random.beta.
                theta = beta_fractional(
                    key_bits(kt), (sz + mk).astype(self.dtype),
                    (n - sz + k1mm).astype(self.dtype))
            else:
                theta = random.beta(kt, sz + mk, n - sz + k1mm,
                                    dtype=self.dtype)
            if block_gates is not None:
                theta = jnp.where(block_gates[BLOCK_THETA].astype(bool),
                                  theta, state.theta)

        # --- outlier indicators z ~ Bernoulli (reference gibbs.py:201-226)
        pout = state.pout
        if cfg.is_outlier_model:
            p_in = _norm_pdf(resid, nvec0)
            if cfg.model == "vvh17":
                top = jnp.full_like(resid, theta / self._pspin)
            else:
                top = theta * _norm_pdf(resid, alpha * nvec0)
            bot = top + (1.0 - theta) * p_in
            q = top / bot
            q = jnp.where(jnp.isnan(q), 1.0, q)
            if mask is not None:
                q = jnp.where(mask, q, 0.0)  # pads never flag as outliers
            pout = q
            z = random.bernoulli(kz, jnp.clip(q, 0.0, 1.0)).astype(self.dtype)
            if block_gates is not None:
                g_z = block_gates[BLOCK_Z].astype(bool)
                z = jnp.where(g_z, z, state.z)
                pout = jnp.where(g_z, pout, state.pout)

        # --- auxiliary scales alpha (reference gibbs.py:229-242) --------
        if cfg.vary_alpha:
            top = (resid * resid * z / nvec0 + df) / 2.0
            if self._fast_gamma and self._fast_gamma_v2:
                # GST_FAST_GAMMA v2: Gamma(k/2) for the integer
                # k = z + df as -log prod U plus one odd-parity
                # Box-Muller plane on counter-based philox streams
                # (ops/linalg.masked_gamma_v2) — distribution-exact
                # like the chi-square arm, ~3x fewer transcendental
                # bytes than its erfinv normal pool (in-kernel RNG on
                # the native path; jnp philox twin otherwise)
                g = masked_gamma_v2(key_bits(ka),
                                    (z + df).astype(self.dtype),
                                    self._gamma_jmax)
            elif self._fast_gamma:
                # exact: Gamma(k/2, 1) = 0.5 * chi^2_k for the integer
                # k = z + df; draw df_max+1 normals per TOA and mask —
                # fixed shapes, no rejection While loop (the measured
                # CPU sweep hot spot; see _fast_gamma_env)
                # tdf covers vary_df=False runs (df pinned above the
                # grid would otherwise silently truncate the mask)
                kmax = int(max(cfg.df_max, cfg.tdf)) + 1
                xs = random.normal(ka, z.shape + (kmax,),
                                   dtype=self.dtype)
                # dispatched (ops/linalg.py masked_chisq): the native
                # fused reduction under GST_NCHOL on CPU, the identical
                # jnp mask-square-sum otherwise
                g = masked_chisq(xs, (z + df).astype(self.dtype))
            else:
                g = random.gamma(ka, (z + df) / 2.0, dtype=self.dtype)
            alpha_new = top / g
            if mask is not None:
                alpha_new = jnp.where(mask, alpha_new, 1.0)
            alpha = jnp.where(jnp.sum(z) >= 1.0, alpha_new, alpha)
            if block_gates is not None:
                alpha = jnp.where(block_gates[BLOCK_ALPHA].astype(bool),
                                  alpha, state.alpha)

        # --- degrees of freedom on the grid (reference gibbs.py:244-259)
        if cfg.vary_df:
            grid = jnp.arange(1, cfg.df_max + 1, dtype=self.dtype)
            terms = jnp.log(alpha) + 1.0 / alpha
            if mask is not None:
                terms = jnp.where(mask, terms, 0.0)
            s = jnp.sum(terms)
            logp = (-(grid / 2.0) * s
                    + n * (grid / 2.0) * jnp.log(grid / 2.0)
                    - n * gammaln(grid / 2.0))
            df = grid[random.categorical(kd, logp)]
            if block_gates is not None:
                df = jnp.where(block_gates[BLOCK_DF].astype(bool),
                               df, state.df)

        # --- Robbins-Monro jump-scale adaptation (opt-in; frozen past
        # adapt_until, so the chain is ordinary MH from that sweep on)
        mh_ls = state.mh_log_scale
        if cfg.mh.adapt_until > 0:
            if sweep is None:
                raise ValueError(
                    "MHConfig.adapt_until > 0 needs the sweep index; "
                    "drive the kernel through sample() (sweep_fn()/"
                    "direct _sweep calls cannot adapt)")
            t = jnp.asarray(sweep, dtype=self.dtype)
            eta = jnp.where(t < cfg.mh.adapt_until,
                            (t + 1.0) ** (-cfg.mh.adapt_decay), 0.0)
            # joint proposals target the multivariate RWM optimum
            target = (cfg.mh.cov_target_accept if cfg.mh.adapt_cov
                      else cfg.mh.target_accept)
            if block_gates is None:
                mh_ls = mh_ls + eta * (jnp.stack([acc_w, acc_h])
                                       - target)
            else:
                # a thinned MH block's zeroed acceptance must not read
                # as rejection: freeze its adaptation term instead
                mh_ls = mh_ls + eta * (
                    block_gates[:2].astype(self.dtype)
                    * (jnp.stack([acc_w, acc_h]) - target))

        return ChainState(x=x, b=b, z=z, alpha=alpha, theta=theta, df=df,
                          pout=pout, acc_white=acc_w, acc_hyper=acc_h,
                          mh_log_scale=mh_ls,
                          mh_cov_chol=state.mh_cov_chol)

    # ------------------------------------------------------------------
    # chunked driver
    # ------------------------------------------------------------------

    def _batched_sweep(self, states: ChainState, keys,
                       sweep=None) -> ChainState:
        """One sweep for ALL chains: vmapped MH stages around a single
        batched TNT reduction — the seam where the fused Pallas kernel
        replaces per-chain scans (ops/pallas_tnt.py)."""
        from gibbs_student_t_tpu.ops.pallas_tnt import tnt_batched

        ma = self._ma
        ks = jax.vmap(lambda k: random.split(k, 7))(keys)   # (C, 7, ...)
        x, acc_w, nvec = jax.vmap(
            lambda st, k: self._sweep_white(st, k, None))(states, ks[:, 0])
        TNT, d, const = tnt_batched(
            ma.T, ma.y, nvec, self._block_size,
            use_pallas=True, interpret=self._pallas_interpret)
        TNT = TNT.astype(self.dtype)
        d = d.astype(self.dtype)
        const = const.astype(self.dtype)
        return jax.vmap(
            lambda st, xx, aw, t, dd, cc, kk:
            self._sweep_rest(st, xx, aw, t, dd, cc, kk, None, sweep)
        )(states, x, acc_w, TNT, d, const, ks[:, 1:])

    def _make_chunk_fn(self):
        fields = self._record_fields
        casts = self._record_casts
        thin = self.record_thin
        use_tele = self._telemetry

        def rec_of(st):
            # transport casts happen on device, inside the scan, so the
            # chunk's record buffers are narrow before they ever cross
            # to host (record="compact")
            return record_tuple(st, fields, casts)

        # The scan iterates over recorded rows (every ``thin``-th sweep);
        # an inner fori_loop advances the ``thin`` sweeps in between with
        # the SAME per-sweep fold_in keying as an unthinned run, so row k
        # of a thinned chain is bit-identical to row k*thin of a full one
        # (tests/test_jax_backend.py::test_record_thin_rows_match_unthinned).
        # The Telemetry pytree rides the same carry (zeroed per chunk,
        # updated per SWEEP — including the thinned-away ones — from the
        # post-sweep state only, so the RNG stream and recorded chains
        # are untouched); the chunk returns it alongside the records and
        # it crosses to host with the same flush (obs/telemetry.py).

        def one_chain(state, chain_key, offset, length):
            def advance(st, tl, i):
                st = self._sweep(st, random.fold_in(chain_key, i),
                                 sweep=i)
                return st, (telemetry_update(tl, st) if use_tele else tl)

            def body(carry, i0):
                st, tl = carry
                rec = rec_of(st)
                if thin == 1:  # default path: no inner loop machinery
                    st, tl = advance(st, tl, i0)
                else:
                    st, tl = lax.fori_loop(
                        0, thin,
                        lambda j, c: advance(c[0], c[1], i0 + j),
                        (st, tl))
                return (st, tl), rec

            (st, tl), recs = lax.scan(
                body, (state, telemetry_init(self.dtype)),
                offset + jnp.arange(0, length, thin))
            if use_tele:
                tl = tl._replace(logpost=self._logpost_chain(st))
            return st, recs, tl

        def chunk(states, keys, offset, length):
            sts, recs, tl = jax.vmap(
                functools.partial(one_chain, offset=offset, length=length)
            )(states, keys)
            return sts, (recs, tl if use_tele else None)

        def chunk_batched(states, keys, offset, length):
            # outer scan over recorded rows; each step advances all
            # chains via the batched sweep (the Pallas TNT path)
            tele_up = jax.vmap(telemetry_update)

            def body(carry, i0):
                sts, tl = carry
                rec = rec_of(sts)

                def inner(j, c):
                    s, t = c
                    ki = jax.vmap(
                        lambda k: random.fold_in(k, i0 + j))(keys)
                    s = self._batched_sweep(s, ki, sweep=i0 + j)
                    return s, (tele_up(t, s) if use_tele else t)

                sts, tl = (inner(0, (sts, tl)) if thin == 1
                           else lax.fori_loop(0, thin, inner, (sts, tl)))
                return (sts, tl), rec

            tl0 = jax.tree.map(
                lambda a: jnp.broadcast_to(a, (self.nchains,) + a.shape),
                telemetry_init(self.dtype))
            (sts, tl), recs = lax.scan(body, (states, tl0),
                                       offset + jnp.arange(0, length, thin))
            if use_tele:
                tl = tl._replace(
                    logpost=jax.vmap(self._logpost_chain)(sts))
            # (rows, C, ...) -> (C, rows, ...) to match the vmap path
            return sts, (tuple(jnp.swapaxes(r, 0, 1) for r in recs),
                         tl if use_tele else None)

        return chunk_batched if self._use_pallas else chunk

    def sweep_fn(self):
        """Jitted vmapped single sweep — the benchmark/graft entry surface."""
        return jax.jit(jax.vmap(self._sweep))

    def lnlikelihood(self, x, z=None, alpha=None):
        """Single-point marginalized log-likelihood, for parity tests
        against the NumPy oracle (same math as the hyper-block's
        ``ll_hyper``)."""
        ma, cfg = self._ma, self.config
        x = jnp.asarray(x, dtype=self.dtype)
        z = (jnp.zeros(self._n_real, dtype=self.dtype) if z is None
             else jnp.asarray(z, dtype=self.dtype))
        alpha = (jnp.ones(self._n_real, dtype=self.dtype) if alpha is None
                 else jnp.asarray(alpha, dtype=self.dtype))
        pad_total = self._ma.n - self._n_real
        if pad_total:
            z = jnp.concatenate([z, jnp.zeros(pad_total, self.dtype)])
            alpha = jnp.concatenate(
                [alpha, jnp.ones(pad_total, self.dtype)])
        nvec = alpha ** z * ndiag(ma, x, jnp)
        if self._row_mask is not None:
            nvec = jnp.where(self._row_mask, nvec, 1.0)
        TNT, d, const_white = tnt_products(ma.T, ma.y, nvec,
                                           self._block_size)
        phiinv, logdet_phi = phiinv_logdet(ma, x, jnp)
        Sigma = TNT + jnp.diag(phiinv)
        quad, logdet_sigma = precond_quad_logdet(Sigma, d, cfg.jitter)
        ll = const_white + 0.5 * (quad - logdet_sigma - logdet_phi)
        return float(jnp.where(jnp.isfinite(ll), ll, -jnp.inf))

    def _logpost_chain(self, state: ChainState,
                       ma: ModelArrays | None = None):
        """Traced single-chain marginalized log-posterior (the hyper
        block's ``ll_hyper`` math plus ``lnprior``), at the chain's
        current z/alpha — the telemetry's running log-posterior
        (obs/telemetry.py). Evaluated once per CHUNK (after the scan),
        so its one extra TNT reduction + factorization costs
        ~1/chunk_size of a sweep. ``vmap`` for batched states; the
        ensemble passes its traced per-pulsar model as ``ma``."""
        ma_r, mask, bs, _ = self._resolve(ma)
        az = state.alpha ** state.z
        nvec = self._masked_nvec(ma_r, mask, state.x, az)
        TNT, d, const_white = tnt_products(ma_r.T, ma_r.y, nvec, bs)
        phiinv, logdet_phi = phiinv_logdet(ma_r, state.x, jnp)
        Sigma = TNT + jnp.diag(phiinv)
        quad, logdet_sigma = precond_quad_logdet(Sigma, d,
                                                 self.config.jitter)
        lp = (const_white + 0.5 * (quad - logdet_sigma - logdet_phi)
              + lnprior(ma_r, state.x, jnp))
        return jnp.where(jnp.isfinite(lp), lp,
                         -jnp.inf).astype(self.dtype)

    def sample(self, x0: Optional[np.ndarray] = None, niter: int = 1000,
               seed: int = 0, state: Optional[ChainState] = None,
               start_sweep: int = 0,
               spool_dir: Optional[str] = None,
               reinit_diverged: bool = False) -> ChainResult:
        """Run ``niter`` sweeps for all chains; spool records to host per
        chunk. Pass ``state``/``start_sweep`` (e.g. from a checkpoint) to
        resume — the per-sweep ``fold_in`` keying makes the continuation
        identical to an unbroken run. With ``spool_dir``, each chunk
        streams to native spool files + a state checkpoint (utils/spool.py)
        and host memory stays O(chunk) instead of O(niter).
        ``reinit_diverged`` re-draws numerically dead chains from the prior
        at chunk boundaries (count reported in ``stats['n_reinits']``).

        With ``MHConfig.adapt_cov``, the population proposal covariance
        is re-estimated at CHUNK boundaries while ``sweep <
        adapt_until`` — during that window the chain depends on the
        chunk grid, so a resume inside the adaptation window must keep
        the same ``chunk_size`` (and chunk-aligned ``start_sweep``) to
        reproduce an unbroken run; past ``adapt_until`` the factors are
        frozen state and any chunking resumes exactly.

        Record flushes are double-buffered: chunk k's device->host pull
        happens only after chunk k+1 is dispatched, overlapping transfer
        with the next chunk's compute (the ~30 MB/s relay link otherwise
        gates the sweep, docs/PERFORMANCE.md). The costs (ADVICE r2): a
        crash can lose up to TWO chunks of spooled progress instead of
        one, and two chunks of record buffers are live on device at once
        — size ``chunk_size`` accordingly at stress scale.
        ``reinit_diverged`` runs flush sequentially instead (its
        divergence scan needs each post-chunk state on host), restoring
        the one-chunk crash window at the cost of the overlap."""
        if niter < 1:
            raise ValueError(f"niter must be >= 1, got {niter}")
        if niter % self.record_thin:
            raise ValueError(f"niter ({niter}) must be a multiple of "
                             f"record_thin ({self.record_thin})")
        if start_sweep % self.record_thin:
            raise ValueError(
                f"start_sweep ({start_sweep}) must land on a recorded "
                f"sweep (multiple of record_thin={self.record_thin})")
        resume = start_sweep > 0
        if state is None:
            state = self.init_state(x0, seed=seed)
        elif self._donate:
            # the chunk fn donates its state argument, which would
            # invalidate the CALLER's state object on the first
            # dispatch; one up-front copy per sample() call keeps the
            # caller's (and a prior call's last_state) buffers intact
            # while every per-chunk re-allocation is still saved
            state = jax.tree.map(jnp.copy, state)
        keys = random.split(random.PRNGKey(seed), self.nchains)
        spool = None
        if spool_dir is not None:
            from gibbs_student_t_tpu.utils.spool import ChainSpool

            # Resuming from a checkpointed state appends to the existing
            # spool (truncated back to the checkpointed sweep first, in
            # case a crash left orphaned rows) instead of overwriting it.
            spool = ChainSpool(spool_dir, seed, resume=resume,
                               resume_at=start_sweep if resume else None,
                               record_mode=self.record_mode,
                               record_thin=self.record_thin)
        records = []
        fields = self._record_fields
        # cumulative across spool resumes: an interrupted run's count is
        # carried forward from run_stats.json instead of resetting
        n_reinits0 = (int(spool.load_run_stats().get("n_reinits", 0))
                      if spool is not None and resume else 0)
        tele_acc = TelemetryAccumulator() if self._telemetry else None

        def flush(recs, chunk_state, sweep_end, n_reinits):
            recs, tl = recs
            if tele_acc is not None and tl is not None:
                # rides the flush's existing host sync; the pytree is a
                # handful of per-chain scalars, so the pull is free next
                # to the record buffers
                summary = tele_acc.add(jax.device_get(tl))
                if self.metrics is not None:
                    tele_acc.emit_chunk(self.metrics, sweep_end, summary)
            host = self._materialize(jax.device_get(recs))
            if spool is not None:
                spool.append(
                    {f: self._trim(f, np.swapaxes(host[i], 0, 1))
                     for i, f in enumerate(fields)},
                    chunk_state, sweep_end,
                    run_stats=({"n_reinits": n_reinits}
                               if reinit_diverged else None))
            else:
                records.append(host)

        state, n_reinits = chunked_sweep_loop(
            state, niter, self.chunk_size, start_sweep,
            step_fn=lambda st, off, ln: self._chunk_fn(st, keys, off,
                                                       length=ln),
            flush_fn=flush,
            pre_chunk_fn=self._prop_cov_fn,
            pre_chunk_until=(self.config.mh.adapt_until
                             if self.config.mh.adapt_cov else 0),
            reinit_fn=((lambda st, end: self._reinit_diverged(
                st, seed=seed + 7919 * end)) if reinit_diverged else None),
            n_reinits=n_reinits0,
            # deferred spool flushes read the checkpoint state after
            # the next chunk has consumed its donated buffers — copy it
            # while live (in-memory flushes never touch the state)
            snapshot_fn=((lambda st: jax.tree.map(jnp.copy, st))
                         if self._donate and spool is not None else None))
        if spool is not None:
            spool.close()
            from gibbs_student_t_tpu.utils.spool import load_spool

            self.last_state = state
            res = load_spool(spool_dir)
            if reinit_diverged:
                res.stats["n_reinits"] = np.asarray(n_reinits)
            if tele_acc is not None and not tele_acc.empty:
                res.stats.update(tele_acc.stats())
            return res
        self.last_state = state

        cols = {
            f: self._trim(
                f, np.concatenate([np.swapaxes(r[i], 0, 1)
                                   for r in records]))
            for i, f in enumerate(fields)
        }
        res = self._to_result(cols)
        if reinit_diverged:
            res.stats["n_reinits"] = np.asarray(n_reinits)
        if tele_acc is not None and not tele_acc.empty:
            res.stats.update(tele_acc.stats())
        return res

    def sample_until(self, rhat_target: float = 1.01,
                     max_sweeps: int = 20000, check_every: int = 500,
                     seed: int = 0,
                     x0: Optional[np.ndarray] = None,
                     state: Optional[ChainState] = None,
                     min_sweeps: int = 0,
                     min_ess: Optional[float] = None,
                     **sample_kwargs) -> ChainResult:
        """Sample until every parameter's split-R-hat across the chain
        axis drops below ``rhat_target`` (checked every ``check_every``
        sweeps over the second half of the accumulated chains), or
        ``max_sweeps`` is reached.

        The massively-parallel chain axis is what makes online
        convergence monitoring nearly free — a per-window host-side
        split-R-hat over (rows, nchains) — and the reference (which
        tracks no diagnostics at all, SURVEY.md §5) has no analog; users
        there pick niter by folklore. ``min_ess`` adds the
        complementary criterion: R-hat says the chains agree, ESS says
        the pooled window actually holds at least that many effective
        samples of EVERY parameter — both must pass to stop. The
        returned result carries the R-hat trajectory in
        ``stats['rhat_history']`` ((checks, p) array), the final values
        in ``stats['rhat']`` (plus ``stats['ess']``/``ess_history``
        when ``min_ess`` is set), and ``stats['converged']``. Extra kwargs (``spool_dir``,
        ``reinit_diverged``, ...) pass through to ``sample``;
        ``check_every`` must be a multiple of ``record_thin`` covering
        at least 8 recorded rows (smaller windows degenerate
        split-R-hat). With ``spool_dir``, segments append to one spool
        and the returned result is the reloaded full history
        (cumulative counters included); in-memory segments are
        concatenated, with ``n_reinits`` summed across them."""
        def sample_fn(length, st, start):
            return self.sample(x0=x0 if start == 0 else None,
                               niter=length, seed=seed, state=st,
                               start_sweep=start, **sample_kwargs)

        return _sample_until_loop(
            sample_fn, lambda: self.last_state, self.record_thin,
            _rhat_per_param, rhat_target, max_sweeps, check_every,
            min_sweeps, state,
            spool_mode=bool(sample_kwargs.get("spool_dir")),
            ess_of=_ess_per_param, min_ess=min_ess)

    @staticmethod
    @jax.jit
    def _diverged_mask_device(state: ChainState):
        """(nchains,) bool computed on device — only the mask crosses to
        host, not the per-TOA state (which at stress scale is tens of MB
        per chunk, exactly what record='light' avoids transferring)."""
        def bad(a):
            return ~jnp.isfinite(a).reshape(a.shape[0], -1).all(axis=1)

        return (bad(state.x) | bad(state.b) | bad(state.theta)
                | bad(state.alpha) | bad(state.df)
                | (state.alpha <= 0).reshape(state.alpha.shape[0],
                                             -1).any(axis=1))

    def diverged_mask(self, state: ChainState) -> np.ndarray:
        """Boolean (nchains,) mask of numerically dead chains.

        The reference's failure handling is purely local (SVD->QR fallback,
        -inf on Cholesky failure, NaN clamps — reference gibbs.py:168-178,
        320-324, 224); a chain whose state still goes non-finite stays dead
        forever. With a vmapped population, chain-level recovery is cheap
        (SURVEY.md §5): detect here, re-initialize in ``sample``.
        """
        state = jax.tree.map(jnp.asarray, state)
        return np.asarray(self._diverged_mask_device(state))

    def _reinit_diverged(self, state: ChainState, seed: int
                         ) -> tuple[ChainState, int]:
        """Replace dead chains with fresh prior draws (chain-level elastic
        recovery; healthy chains are untouched bitwise)."""
        bad = self.diverged_mask(state)
        n_bad = int(bad.sum())
        if n_bad == 0:
            return state, 0
        return merge_reinit(state, bad, self.init_state(seed=seed),
                            batch_ndim=1), n_bad

    def _materialize(self, host, n_last=None):
        """Undo the record-transport casts: the narrow wire dtypes
        (record="compact") come back as float32 host arrays, so
        downstream consumers (spool files, ChainResult, analysis) see
        the same dtypes as a record="full" run. ``n_last`` overrides the
        unpacked length of bit-packed per-TOA fields — the ensemble's
        records are padded to its n_max, not this backend's own n."""
        if not self._record_casts:
            return list(host)
        out = []
        for f, h in zip(self._record_fields, host):
            c = self._record_casts.get(f)
            if c is _PACKBITS:
                out.append(_unpack_bits(h, n_last or self._ma.n))
            elif c is _U8PROB:
                out.append(np.asarray(h, np.float32) / 255.0)
            elif c is not None:
                out.append(np.asarray(h, np.float32))
            else:
                out.append(h)
        return out

    def _trim(self, field: str, arr: np.ndarray) -> np.ndarray:
        """Cut TOA padding (block padding and/or a pre-padded model's
        suffix rows) back off the recorded per-TOA chains."""
        if self._ma.n != self._n_real and field in ("z", "alpha", "pout"):
            return arr[..., :self._n_real]
        return arr

    @property
    def record_mode(self) -> str:
        """Effective recording mode: 'compact' only when the narrow wire
        casts are actually active (they are disabled for float64 runs,
        which get bit-exact chains regardless of the requested mode)."""
        if self._record_mode == "light":
            return "light"
        if not self._record_casts:
            return "full"
        return self._record_mode  # "compact" or "compact8"

    def _to_result(self, cols) -> ChainResult:
        empty = np.zeros((0,))
        stats = {k: v for k, v in cols.items() if k.startswith("acc_")}
        # quantized compact transport is discoverable downstream: host
        # arrays are float32 either way, so the dtype alone cannot tell
        # a ~2-3-digit b/alpha chain from a bit-exact one (ADVICE r2)
        stats["record_mode"] = np.asarray(self.record_mode)
        if self.record_thin != 1:
            stats["record_thin"] = np.asarray(self.record_thin)
        return ChainResult(
            chain=cols.get("x", empty), bchain=cols.get("b", empty),
            zchain=cols.get("z", empty), thetachain=cols.get("theta", empty),
            alphachain=cols.get("alpha", empty),
            poutchain=cols.get("pout", empty), dfchain=cols.get("df", empty),
            stats=stats,
        )


def _norm_pdf(x, var):
    return jnp.exp(-0.5 * x * x / var) / jnp.sqrt(2.0 * jnp.pi * var)
