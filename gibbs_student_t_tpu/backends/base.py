"""SamplerBackend seam and chain containers.

The plugin boundary named by the north star (BASELINE.json): drivers select
``--backend={cpu,jax}`` and everything behind this interface is free to be
host NumPy or a jitted TPU kernel. The chain surface mirrors the seven
chain arrays of the reference (reference gibbs.py:344-350): ``chain``
(hyper/white params), ``bchain``, ``zchain``, ``thetachain``, ``alphachain``,
``poutchain``, ``dfchain`` — with a leading chain axis in the JAX backend.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.models.pta import ModelArrays

#: ``ChainResult.stats`` keys that are run-level metadata rather than
#: per-sweep arrays: ``burn`` passes them through untouched and
#: ``select_pulsar`` reduces them instead of slicing a sweep axis.
#: ``n_toa`` is the per-pulsar real TOA count of a (padded) ensemble run;
#: ``n_reinits`` the cumulative diverged-chain re-inits; ``record_mode``
#: the recording mode the run used (so compact-transport quantization of
#: b/alpha/pout is discoverable downstream); ``record_thin`` the on-device
#: sweep-thinning factor (rows = every ``record_thin``-th sweep);
#: ``rhat``/``rhat_history``/``converged`` are ``sample_until``'s
#: convergence verdict (per-parameter / per-check, not per-sweep).
#: Keys under ``obs.telemetry.TELE_PREFIX`` (``tele_*``) are run-level
#: per-chain telemetry aggregates: ``burn`` passes them through like
#: META_STATS, and ``select_pulsar`` indexes their leading pulsar axis
#: (they are ``(npulsars, nchains)`` in ensemble results, not
#: ``(niter, ...)``).
META_STATS = ("n_toa", "n_reinits", "record_mode", "record_thin",
              "rhat", "rhat_history", "converged")

TELE_PREFIX = "tele_"


@dataclasses.dataclass
class ChainResult:
    """Sampled chains. Arrays are shaped ``(niter, ...)`` for single-chain
    backends and ``(niter, nchains, ...)`` for vmapped backends."""

    chain: np.ndarray        # parameter vectors
    bchain: np.ndarray       # basis coefficients
    zchain: np.ndarray       # outlier indicators
    thetachain: np.ndarray   # outlier fraction
    alphachain: np.ndarray   # per-TOA variance scales
    poutchain: np.ndarray    # per-TOA outlier probabilities
    dfchain: np.ndarray      # Student-t dof
    stats: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)

    def burn(self, nburn: int) -> "ChainResult":
        """Drop burn-in samples (reference run_sims.py:118-124 drops 100).
        Per-sweep stats arrays are trimmed too so they stay aligned with
        the chains."""
        return ChainResult(
            **{
                f.name: getattr(self, f.name)[nburn:]
                for f in dataclasses.fields(self)
                if f.name not in ("stats",)
            },
            # per-sweep stats stay sweep-aligned; run-level metadata
            # (META_STATS, tele_* aggregates) passes through untouched
            stats={k: (v[nburn:] if np.ndim(v) and k not in META_STATS
                       and not k.startswith(TELE_PREFIX)
                       else v)
                   for k, v in self.stats.items()},
        )

    def select_pulsar(self, i: int) -> "ChainResult":
        """Slice one pulsar out of an ensemble result (arrays shaped
        ``(niter, npulsars, nchains, ...)``, parallel/ensemble.py) into
        the ordinary ``(niter, nchains, ...)`` form drivers save.

        A heterogeneous ensemble pads every pulsar's TOA axis to the
        maximum so the stacked arrays are rectangular; the per-pulsar
        real counts ride along as ``stats['n_toa']``, and the slice cuts
        the padded suffix back off the per-TOA chains here — saved trees
        are ``(niter, nchains, n_i)``, exactly the reference's per-pulsar
        layout (reference run_sims.py:118-124)."""
        fields = {
            f.name: getattr(self, f.name)[:, i]
            for f in dataclasses.fields(self)
            if f.name not in ("stats",)
        }
        stats = {}
        for k, v in self.stats.items():
            if k.startswith(TELE_PREFIX):
                # (npulsars, nchains) per-chain aggregates -> (nchains,)
                stats[k] = v[i] if np.ndim(v) >= 2 else v
            elif k in META_STATS or np.ndim(v) < 2:
                stats[k] = v
            else:
                stats[k] = v[:, i]
        n_toa = self.stats.get("n_toa")
        if n_toa is not None:
            n_i = int(np.asarray(n_toa)[i])
            for name in ("zchain", "alphachain", "poutchain"):
                arr = fields[name]
                if arr.size and arr.shape[-1] > n_i:
                    fields[name] = arr[..., :n_i]
            stats["n_toa"] = np.asarray(n_i)
        return ChainResult(**fields, stats=stats)

    def save(self, outdir: str) -> None:
        """Persist in the reference's on-disk layout
        (reference run_sims.py:118-124)."""
        import os

        os.makedirs(outdir, exist_ok=True)
        for name in ("chain", "bchain", "zchain", "poutchain",
                     "thetachain", "alphachain", "dfchain"):
            np.save(os.path.join(outdir, f"{name}.npy"), getattr(self, name))

    def acceptance_rates(self) -> Dict[str, np.ndarray]:
        """Per-MH-block acceptance arrays present in ``stats`` — the one
        place the block list lives, shared by every driver's
        observability output (bench.py, run_sims.py)."""
        out = {}
        for blk in ("white", "hyper"):
            acc = np.asarray(self.stats.get(f"acc_{blk}", np.zeros(0)))
            if acc.size:
                out[blk] = acc
        return out


class SamplerBackend:
    """Common construction: a frozen model + config; subclasses implement
    ``sample``. ``supports_chains`` advertises a vmapped chain axis (and a
    ``nchains=`` constructor kwarg) so drivers can dispatch generically."""

    supports_chains = False

    def __init__(self, ma: ModelArrays, config: GibbsConfig):
        self.ma = ma
        self.config = config

    def sample(self, x0: np.ndarray, niter: int,
               seed: int = 0) -> ChainResult:
        raise NotImplementedError


def get_backend(name: str):
    """Resolve a backend by flag value (north-star ``--backend={cpu,jax}``)."""
    from gibbs_student_t_tpu.backends.numpy_backend import NumpyGibbs
    from gibbs_student_t_tpu.backends.jax_backend import JaxGibbs

    table = {"cpu": NumpyGibbs, "numpy": NumpyGibbs, "jax": JaxGibbs,
             "tpu": JaxGibbs}
    if name not in table:
        raise ValueError(f"unknown backend {name!r}; options: {sorted(table)}")
    return table[name]
