"""ctypes bindings for the native runtime library (``native/src``).

The reference's native layer is reached through ``libstempo`` → tempo2
(C++) for data ingestion (reference simulate_data.py:12-18,
run_sims.py:47,51); here the native side of the runtime is first-party:
``libgst_native.so`` provides the FORMAT-1 tim tokenizer and the binary
chain spooler. Everything degrades gracefully — ``available()`` is False
when the library hasn't been built (``make -C native``) and callers fall
back to the pure-Python paths, so the framework never *requires* a
compiler at runtime.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libgst_native.so")
_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                           "native")
_lib: Optional[ctypes.CDLL] = None


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    sigs = {
        "gst_last_error": ([], c.c_char_p),
        "gst_tim_read": ([c.c_char_p, c.c_int], c.c_void_p),
        "gst_tim_free": ([c.c_void_p], None),
        "gst_tim_n": ([c.c_void_p], c.c_int64),
        "gst_tim_nsites": ([c.c_void_p], c.c_int64),
        "gst_tim_nflags": ([c.c_void_p], c.c_int64),
        "gst_tim_fill": ([c.c_void_p] + [c.c_void_p] * 6, None),
        "gst_tim_name": ([c.c_void_p, c.c_int64], c.c_char_p),
        "gst_tim_site": ([c.c_void_p, c.c_int64], c.c_char_p),
        "gst_tim_flag_name": ([c.c_void_p, c.c_int64], c.c_char_p),
        "gst_tim_flag_value": ([c.c_void_p, c.c_int64, c.c_int64],
                               c.c_char_p),
        # packed exports return a raw pointer (NOT c_char_p, which would
        # stop at the first NUL and copy-convert) + byte length
        "gst_tim_names_packed": ([c.c_void_p, c.POINTER(c.c_uint64)],
                                 c.c_void_p),
        "gst_tim_flag_packed": ([c.c_void_p, c.c_int64,
                                 c.POINTER(c.c_uint64)], c.c_void_p),
        "gst_spool_open": ([c.c_char_p, c.c_uint32, c.c_uint32,
                            c.POINTER(c.c_uint64), c.c_int, c.c_uint64],
                           c.c_void_p),
        "gst_spool_append": ([c.c_void_p, c.c_void_p, c.c_uint64], c.c_int),
        "gst_spool_flush": ([c.c_void_p], c.c_int),
        "gst_spool_close": ([c.c_void_p], c.c_int),
        "gst_spool_info": ([c.c_char_p, c.POINTER(c.c_uint32),
                            c.POINTER(c.c_uint32), c.POINTER(c.c_uint64),
                            c.POINTER(c.c_uint64)], c.c_int64),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib


def load(build: bool = False) -> Optional[ctypes.CDLL]:
    """Load (optionally building) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and build:
        build_native()
    if os.path.exists(_LIB_PATH):
        _lib = _bind(ctypes.CDLL(_LIB_PATH))
    return _lib


def build_native() -> None:
    """Compile the library with the repo Makefile (g++, no deps)."""
    subprocess.run(["make", "-C", os.path.abspath(_NATIVE_DIR)], check=True,
                   capture_output=True)


def available() -> bool:
    return load() is not None


def _err(lib) -> str:
    return lib.gst_last_error().decode()


# ---------------------------------------------------------------------------
# tim reading
# ---------------------------------------------------------------------------

def read_tim_native(path: str, include_deleted: bool = False):
    """Native-parser version of :func:`data.tim.read_tim`; same TimFile."""
    from gibbs_student_t_tpu.data.tim import TimFile

    lib = load()
    if lib is None:
        raise RuntimeError("native library not built (run make -C native)")
    h = lib.gst_tim_read(path.encode(), int(include_deleted))
    if not h:
        msg = _err(lib)
        if "INCLUDE" in msg:
            raise NotImplementedError(msg)
        raise OSError(msg)
    try:
        n = lib.gst_tim_n(h)
        freqs = np.empty(n, dtype=np.float64)
        day = np.empty(n, dtype=np.float64)
        frac = np.empty(n, dtype=np.float64)
        errors = np.empty(n, dtype=np.float64)
        site_idx = np.empty(n, dtype=np.int32)
        deleted = np.empty(n, dtype=np.uint8)
        if n:
            lib.gst_tim_fill(
                h, *(a.ctypes.data_as(ctypes.c_void_p)
                     for a in (freqs, day, frac, errors, site_idx, deleted)))
        sites_tbl = [lib.gst_tim_site(h, i).decode()
                     for i in range(lib.gst_tim_nsites(h))]

        def unpack(ptr, nbytes) -> list:
            # one FFI call + one split for the whole column (the per-index
            # getters would be O(n) round-trips on 1e5-TOA files)
            blob = ctypes.string_at(ptr, nbytes.value).decode()
            return blob.split("\n") if n else []

        nb = ctypes.c_uint64()
        names = unpack(lib.gst_tim_names_packed(h, ctypes.byref(nb)), nb)
        flags: Dict[str, np.ndarray] = {}
        for j in range(lib.gst_tim_nflags(h)):
            key = lib.gst_tim_flag_name(h, j).decode()
            vals = unpack(lib.gst_tim_flag_packed(h, j, ctypes.byref(nb)),
                          nb)
            flags[key] = np.array(vals, dtype=object)
        flags = dict(sorted(flags.items()))
        mjds = day.astype(np.longdouble) + frac.astype(np.longdouble)
        return TimFile(
            names=names,
            freqs=freqs,
            mjds=mjds,
            errors=errors,
            sites=[sites_tbl[i] for i in site_idx],
            flags=flags,
            deleted=deleted.astype(bool),
        )
    finally:
        lib.gst_tim_free(h)


# ---------------------------------------------------------------------------
# chain spooler
# ---------------------------------------------------------------------------

_ITEMSIZE = {np.dtype(np.float32): 4, np.dtype(np.float64): 8}


class SpoolWriter:
    """Append-only typed array file: rows of a fixed trailing shape.

    Used to stream per-chunk sampler records to disk (SURVEY.md §5
    "checkpoint/resume": the reference holds all chains in RAM,
    reference gibbs.py:344-350). A killed run leaves a readable prefix —
    the row count is implied by file size, not a footer.
    """

    _KEEP_ALL = 2 ** 64 - 1

    def __init__(self, path: str, trailing_shape: Sequence[int],
                 dtype=np.float32, append: bool = False,
                 keep_rows: Optional[int] = None):
        """``append=True`` keeps an existing file's records (resume path);
        the on-disk header must match ``dtype``/``trailing_shape``.
        ``keep_rows`` truncates the file to that many rows before
        appending — pass the checkpointed sweep count so orphaned rows
        from a crash mid-append (or a partial row mid-write) are discarded
        rather than silently shifting every later sweep."""
        lib = load()
        if lib is None:
            raise RuntimeError("native library not built (run make -C native)")
        self._lib = lib
        self.dtype = np.dtype(dtype)
        self.trailing_shape = tuple(int(s) for s in trailing_shape)
        shape_arr = (ctypes.c_uint64 * len(self.trailing_shape))(
            *self.trailing_shape)
        self._h = lib.gst_spool_open(
            path.encode(), _ITEMSIZE[self.dtype],
            len(self.trailing_shape), shape_arr, int(append),
            self._KEEP_ALL if keep_rows is None else int(keep_rows))
        if not self._h:
            raise OSError(_err(lib))

    def append(self, rows: np.ndarray) -> None:
        rows = np.ascontiguousarray(rows, dtype=self.dtype)
        if rows.shape[1:] != self.trailing_shape:
            raise ValueError(
                f"row shape {rows.shape[1:]} != {self.trailing_shape}")
        rc = self._lib.gst_spool_append(
            self._h, rows.ctypes.data_as(ctypes.c_void_p), rows.shape[0])
        if rc != 0:
            raise OSError(_err(self._lib))

    def flush(self) -> None:
        self._lib.gst_spool_flush(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.gst_spool_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_spool(path: str) -> np.ndarray:
    """Load a spool file as one array, leading axis = appended rows."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library not built (run make -C native)")
    itemsize = ctypes.c_uint32()
    ndim = ctypes.c_uint32()
    shape = (ctypes.c_uint64 * 8)()
    header = ctypes.c_uint64()
    rows = lib.gst_spool_info(path.encode(), ctypes.byref(itemsize),
                              ctypes.byref(ndim), shape,
                              ctypes.byref(header))
    if rows < 0:
        raise OSError(_err(lib))
    dtype = np.float32 if itemsize.value == 4 else np.float64
    trailing = tuple(shape[i] for i in range(ndim.value))
    data = np.fromfile(path, dtype=dtype, offset=header.value,
                       count=rows * int(np.prod(trailing, dtype=np.int64)))
    return data.reshape((rows,) + trailing)
