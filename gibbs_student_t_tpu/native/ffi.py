"""JAX FFI bindings for the native lane-batched linalg kernels.

``native/src/gst_ffi.cpp`` exports XLA typed-FFI handlers (batched
chains-contiguous Cholesky with fused forward substitution, standalone
backward/forward substitutions for vector and matrix right-hand sides,
and the masked chi-square reduction) as plain C symbols in
``libgst_native.so``. This module registers them as XLA:CPU custom-call
targets and wraps each in a ``jax.ffi.ffi_call`` entry point consumed by
the ``GST_NCHOL`` dispatch in ``ops/linalg.py``.

Everything degrades: :func:`ready` is False — and every entry point
unreachable by the dispatch — when the library is missing, was built
without the FFI headers (``GST_NO_FFI``), was compiled for a SIMD level
this host lacks, or the installed jax has no FFI API. No runtime ever
*requires* a compiler or the jaxlib headers (the contract of
``native/__init__.py``, extended to the kernel family).

Registration is idempotent and lazy: the first :func:`ready` /
dispatch-time probe performs it; failures latch to unavailable for the
process (same never-take-down-the-sampler posture as obs/introspect.py).
"""

from __future__ import annotations

import ctypes
import os
from functools import partial
from typing import Optional

import numpy as np

#: Kernel-family ABI version this module was written against. The
#: committed library exports ``gst_abi_version()``; a mismatch (or a
#: pre-versioning library) degrades at probe time with a clear reason
#: string instead of miscalling a handler whose signature moved.
ABI_VERSION = 5

#: FFI target name -> exported C symbol. Names are versioned with a
#: ``gst_`` prefix so they cannot collide with XLA's own cpu targets.
TARGETS = {
    "gst_nchol_factor_f32": "GstNcholFactorF32",
    "gst_nchol_factor_f64": "GstNcholFactorF64",
    "gst_nchol_factor_quad_f32": "GstNcholFactorQuadF32",
    "gst_nchol_factor_quad_f64": "GstNcholFactorQuadF64",
    "gst_nchol_robust_draw_f32": "GstNcholRobustDrawF32",
    "gst_nchol_robust_draw_f64": "GstNcholRobustDrawF64",
    "gst_nchol_fwd_vec_f32": "GstNcholFwdVecF32",
    "gst_nchol_fwd_vec_f64": "GstNcholFwdVecF64",
    "gst_nchol_bwd_vec_f32": "GstNcholBwdVecF32",
    "gst_nchol_bwd_vec_f64": "GstNcholBwdVecF64",
    "gst_nchol_fwd_mat_f32": "GstNcholFwdMatF32",
    "gst_nchol_fwd_mat_f64": "GstNcholFwdMatF64",
    "gst_nchol_bwd_mat_f32": "GstNcholBwdMatF32",
    "gst_nchol_bwd_mat_f64": "GstNcholBwdMatF64",
    "gst_chisq_f32": "GstChisqF32",
    "gst_chisq_f64": "GstChisqF64",
    "gst_tnt_f32": "GstTntF32",
    "gst_tnt_f64": "GstTntF64",
    "gst_gamma_v2_f32": "GstGammaV2F32",
    "gst_gamma_v2_f64": "GstGammaV2F64",
    "gst_beta_frac_f32": "GstBetaFracF32",
    "gst_beta_frac_f64": "GstBetaFracF64",
    "gst_white_mh_f32": "GstWhiteMhF32",
    "gst_white_mh_f64": "GstWhiteMhF64",
    "gst_white_lanes_f32": "GstWhiteLanesF32",
    "gst_white_lanes_f64": "GstWhiteLanesF64",
    "gst_hyper_mh_f32": "GstHyperMhF32",
    "gst_hyper_mh_f64": "GstHyperMhF64",
    "gst_schur_f32": "GstSchurF32",
    "gst_schur_f64": "GstSchurF64",
    "gst_fused_hyper_f32": "GstFusedHyperF32",
    "gst_fused_hyper_f64": "GstFusedHyperF64",
    "gst_tnt_lanes_f32": "GstTntLanesF32",
    "gst_tnt_lanes_f64": "GstTntLanesF64",
    "gst_fused_hyper_lanes_f32": "GstFusedHyperLanesF32",
    "gst_fused_hyper_lanes_f64": "GstFusedHyperLanesF64",
    "gst_resid_f32": "GstResidF32",
    "gst_resid_f64": "GstResidF64",
    "gst_resid_lanes_f32": "GstResidLanesF32",
    "gst_resid_lanes_f64": "GstResidLanesF64",
}

# None = not yet probed; True/False = latched verdict for the process.
_READY: Optional[bool] = None
_WHY = "not probed"


def _ffi_module():
    """The installed jax FFI namespace (``jax.ffi`` moved out of
    ``jax.extend.ffi`` across releases — resolve whichever exists, the
    ``parallel/compat.py`` version-tolerance discipline)."""
    try:
        from jax import ffi as jffi  # jax >= 0.4.38

        if hasattr(jffi, "ffi_call"):
            return jffi
    except ImportError:
        pass
    from jax.extend import ffi as jffi  # jax 0.4.31 - 0.5.x

    return jffi


def _host_simd_ok(level: str) -> bool:
    """True when this host's CPU implements the SIMD level the committed
    library was compiled for (``-march=native`` on the build host; the
    guard that makes a foreign host degrade instead of SIGILL)."""
    if level in ("generic", "sse2", ""):
        return True  # baseline x86-64 / non-SIMD build: always safe
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith(("flags", "Features")):
                    return level in line.split()
    except OSError:
        pass
    return False  # no /proc to prove support: stay on the jnp path


def _probe() -> bool:
    global _WHY
    from gibbs_student_t_tpu import native

    lib = native.load()
    if lib is None:
        _WHY = "libgst_native.so not built"
        return False
    try:
        lib.gst_simd_level.restype = ctypes.c_char_p
    except AttributeError:
        _WHY = "library predates the FFI kernels (rebuild: make -C native)"
        return False
    level = (lib.gst_simd_level() or b"").decode()
    if not _host_simd_ok(level):
        _WHY = f"library built for {level}, host lacks it"
        return False
    try:
        lib.gst_abi_version.restype = ctypes.c_int
        abi = int(lib.gst_abi_version())
    except AttributeError:
        _WHY = (f"library predates gst_abi_version (ABI {ABI_VERSION} "
                "expected; rebuild: make -C native)")
        return False
    if abi != ABI_VERSION:
        _WHY = (f"library ABI v{abi} != expected v{ABI_VERSION} — "
                "kernel signatures moved; rebuild: make -C native")
        return False
    try:
        jffi = _ffi_module()
    except ImportError:
        _WHY = "installed jax has no FFI API"
        return False
    try:
        for target, symbol in TARGETS.items():
            fn = getattr(lib, symbol)  # AttributeError -> GST_NO_FFI build
            jffi.register_ffi_target(target, jffi.pycapsule(fn),
                                     platform="cpu")
    except Exception as e:  # noqa: BLE001 - any failure means "absent"
        _WHY = f"FFI registration failed: {type(e).__name__}: {e}"
        return False
    _WHY = f"registered ({level})"
    return True


def ready() -> bool:
    """Kernels registered and callable on this host (latched probe)."""
    global _READY
    if _READY is None:
        try:
            _READY = _probe()
        except Exception as e:  # noqa: BLE001
            global _WHY
            _WHY = f"probe failed: {type(e).__name__}: {e}"
            _READY = False
    return _READY


def status() -> str:
    """Human-readable probe verdict (capability line for run records)."""
    ready()
    return _WHY


def _reset_for_tests() -> None:
    """Drop the latched verdict (tests only — e.g. after deleting the
    .so to prove graceful degradation). Also unlatches the registry's
    mirror probes so both layers re-verdict together."""
    global _READY, _WHY, _TIMERS_OK, _NS_PER_TICK
    _READY = None
    _WHY = "not probed"
    _TIMERS_OK = None
    _NS_PER_TICK = None
    try:
        from gibbs_student_t_tpu.ops import registry

        registry._unlatch_probe("native")
        registry._unlatch_probe("native_timers")
    except Exception:  # noqa: BLE001 - reset stays best-effort
        pass


# ---------------------------------------------------------------------
# in-kernel stage timers (round 15, the deep profiling plane)
# ---------------------------------------------------------------------
# The kernels carry a runtime-flag timing side channel (gst_kernels.h):
# per-stage rdtsc cycle accumulators the .so exports as plain-C
# control entries. Because the flag gates brackets inside the SAME
# compiled code, chains and the lowered graph are bitwise identical
# timers on or off — the probe below only checks the control surface
# exists and the stage table matches, never changes any dispatch.

#: Stage names in the C enum order (gst_kernels.h StageId). The probe
#: cross-checks this against gst_timer_stage_name so the Python list
#: can never silently drift from the accumulators it labels.
STAGE_NAMES = ("schur", "hyper_mh", "bdraw_factor", "solves",
               "white_mh", "tnt", "resid", "draws")

_TIMERS_OK: Optional[bool] = None
_NS_PER_TICK: Optional[float] = None


def kernel_timers_env() -> str:
    """Validated ``GST_KERNEL_TIMERS`` (``auto`` when unset) — the
    in-kernel stage-timer side channel. Strict ``auto|1|0`` (the
    loud-typo contract of every GST_* gate); ``auto`` resolves to ON
    wherever the native library provides the timer surface (the
    channel is bitwise-free: same compiled code, a runtime flag).
    ``1`` forces the request but still degrades silently when the
    library lacks the exports (the forced-but-unavailable contract);
    ``0`` keeps the flag down and every consumer timer-free. Strict
    validation lives in the dispatch registry (ops/registry.py)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.value("GST_KERNEL_TIMERS")


def _lib():
    from gibbs_student_t_tpu import native

    return native.load()


def timers_available() -> bool:
    """The timer control surface exists on this host's library AND the
    kernels themselves are registered (cycle counts from a library
    whose kernels never run would always read zero). Latched like
    :func:`ready`."""
    global _TIMERS_OK
    if _TIMERS_OK is None:
        _TIMERS_OK = False
        try:
            if ready():
                lib = _lib()
                lib.gst_timer_stage_count.restype = ctypes.c_int
                lib.gst_timer_stage_name.restype = ctypes.c_char_p
                n = int(lib.gst_timer_stage_count())
                names = tuple(
                    lib.gst_timer_stage_name(i).decode()
                    for i in range(n))
                _TIMERS_OK = names == STAGE_NAMES
        except Exception:  # noqa: BLE001 - absent surface == degraded
            _TIMERS_OK = False
    return _TIMERS_OK


def timers_resolved_on() -> bool:
    """The gate verdict consumers act on: ``GST_KERNEL_TIMERS`` (auto
    -> on) AND the surface actually available — forced-but-unavailable
    degrades to off, silently, like every other native gate (the
    registry's ``mode3`` pipeline, probe ``native_timers``)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.mode3("GST_KERNEL_TIMERS")[0]


def timers_enable(on: bool) -> None:
    """Raise/lower the process-global collection flag (a no-op without
    the surface). Enabling is idempotent and thread-safe; kernels
    sample the flag once per call."""
    if timers_available():
        _lib().gst_timers_enable(1 if on else 0)


def timers_reset() -> None:
    """Zero the cumulative accumulators. Only safe with no kernel in
    flight — consumers on live servers difference cumulative
    :func:`timers_snapshot` values instead."""
    if timers_available():
        _lib().gst_timers_reset()


def timers_snapshot() -> dict:
    """Cumulative ``{stage: {"cycles": int, "calls": int}}`` since the
    last reset ({} without the surface)."""
    if not timers_available():
        return {}
    lib = _lib()
    n = len(STAGE_NAMES)
    cyc = (ctypes.c_uint64 * n)()
    calls = (ctypes.c_uint64 * n)()
    lib.gst_timers_snapshot(cyc, calls)
    return {name: {"cycles": int(cyc[i]), "calls": int(calls[i])}
            for i, name in enumerate(STAGE_NAMES)}


def timers_ns_per_tick() -> float:
    """ns per timer tick, calibrated ONCE per process against
    CLOCK_MONOTONIC (~2 ms spin in the library; rdtsc is
    constant-rate, so one calibration serves the process)."""
    global _NS_PER_TICK
    if _NS_PER_TICK is None:
        if not timers_available():
            _NS_PER_TICK = 1.0
        else:
            lib = _lib()
            lib.gst_timer_ns_per_tick.restype = ctypes.c_double
            _NS_PER_TICK = float(lib.gst_timer_ns_per_tick())
    return _NS_PER_TICK


def timers_delta_ms(prev: dict, cur: dict) -> dict:
    """``{stage: {"ms": float, "calls": int}}`` for the stages that
    advanced between two cumulative snapshots — the per-quantum /
    per-bench-window attribution helper. Stages with no new calls are
    omitted so consumers render only what actually ran."""
    scale = timers_ns_per_tick() / 1e6
    out = {}
    for name in STAGE_NAMES:
        c0 = (prev.get(name) or {"cycles": 0, "calls": 0})
        c1 = cur.get(name)
        if c1 is None:
            continue
        dcalls = c1["calls"] - c0["calls"]
        dcyc = c1["cycles"] - c0["cycles"]
        if dcalls <= 0 and dcyc <= 0:
            continue
        out[name] = {"ms": dcyc * scale, "calls": int(dcalls)}
    return out


_SFX = {np.dtype(np.float32): "f32", np.dtype(np.float64): "f64"}


def supported_dtype(dtype) -> bool:
    return np.dtype(dtype) in _SFX


def _call(base: str, out_shapes, *args, dtype=None):
    """``dtype`` overrides the output dtype / target suffix (needed by
    the draw kernels, whose first operand is the uint32 key buffer)."""
    import jax

    jffi = _ffi_module()
    if dtype is None:
        dtype = args[0].dtype
    sfx = _SFX[np.dtype(dtype)]
    fn = jffi.ffi_call(
        f"{base}_{sfx}",
        [jax.ShapeDtypeStruct(s, dtype) for s in out_shapes])
    out = fn(*args)
    return out


def nchol_factor(S, rhs):
    """``(L, logdet, u)`` with ``L L^T = S``, ``logdet = logdet S`` and
    ``L u = rhs`` — the fused factorization, one custom call."""
    L, logdet, u = _call("gst_nchol_factor",
                         (S.shape, S.shape[:-2], rhs.shape), S, rhs)
    return L, logdet, u


def nchol_factor_quad(S, rhs):
    """``(logdet, u)`` — :func:`nchol_factor` without the L output.
    Bitwise the same recurrence; skips the dense-L memset and the L
    store transpose, which dominated the kernel wall time for callers
    (the hyper-MH likelihood) that never read the factor."""
    logdet, u = _call("gst_nchol_factor_quad",
                      (S.shape[:-2], rhs.shape), S, rhs)
    return logdet, u


def nchol_robust_draw(S, rhs, xi, jitters):
    """``(y, logdet)`` with ``y = L^-T (L^-1 rhs + xi)`` for the first
    escalating-jitter level whose factor of ``S + j*I`` is finite (else
    the last level) — the b-draw's robust factorization and backward
    draw fused into one custom call; escalation beyond level 0 runs
    only for chain tiles that actually failed."""
    y, logdet = _call("gst_nchol_robust_draw",
                      (rhs.shape, S.shape[:-2]), S, rhs, xi, jitters)
    return y, logdet


def tnt(T, y, nvec):
    """``(TNT, d, const_white)`` of the marginalized likelihood for a
    chain batch sharing one basis: ``TNT = T^T diag(1/nvec) T`` (full
    symmetric), ``d = T^T (y / nvec)``, ``const = -1/2 (sum log nvec +
    y^T y / nvec)``; ``T (n, m)`` and ``y (n,)`` shared, ``nvec
    (..., n)`` per chain."""
    m = T.shape[-1]
    batch = nvec.shape[:-1]
    TNT, d, cw = _call("gst_tnt", (batch + (m, m), batch + (m,), batch),
                       T, y, nvec)
    return TNT, d, cw


def tnt_lanes(T, y, nvec, gid):
    """Multi-tenant twin of :func:`tnt`: ``T (B, n, m)`` / ``y (B, n)``
    PER LANE (the serve slot pool's call-time dataset operands), with
    the tile-uniform group-id contract — ``gid (B,)`` int32 constant
    within every aligned SIMD tile (the scheduler's admission
    granularity; the handler rejects straddles). A pool whose lanes all
    share one basis is bitwise identical to the shared-basis kernel."""
    m = T.shape[-1]
    batch = nvec.shape[:-1]
    TNT, d, cw = _call("gst_tnt_lanes",
                       (batch + (m, m), batch + (m,), batch),
                       T, y, nvec, gid, dtype=T.dtype)
    return TNT, d, cw


def resid(T, y, b):
    """``y - T @ b`` per chain with the basis shared across the batch —
    the z/df glue's (n, m) residual matvec as one fused pass
    (``T (n, m)``, ``y (n,)``, ``b (..., m)``)."""
    n = T.shape[0]
    (out,) = _call("gst_resid", (b.shape[:-1] + (n,),), T, y, b)
    return out


def resid_lanes(T, y, b, gid):
    """Multi-tenant twin of :func:`resid`: per-lane basis/residuals
    (``T (B, n, m)``, ``y (B, n)``) under the tile-uniform ``gid``
    contract; bitwise :func:`resid` for a uniform pool (same inner
    loop)."""
    n = T.shape[-2]
    (out,) = _call("gst_resid_lanes", (b.shape[:-1] + (n,),), T, y, b,
                   gid, dtype=T.dtype)
    return out


def _solve(base, L, r):
    (x,) = _call(base, (r.shape,), L, r)
    return x


fwd_vec = partial(_solve, "gst_nchol_fwd_vec")     # L x = r, r (..., m)
bwd_vec = partial(_solve, "gst_nchol_bwd_vec")     # L^T x = r
fwd_mat = partial(_solve, "gst_nchol_fwd_mat")     # L X = R, R (..., m, k)
bwd_mat = partial(_solve, "gst_nchol_bwd_mat")     # L^T X = R


def chisq(xs, counts):
    """``0.5 * sum_{j < counts} xs[..., j]^2`` — the masked
    sum-of-squared-normals chi-square reduction in one fused pass
    (``xs (..., kmax)``, ``counts (...)`` same dtype)."""
    (out,) = _call("gst_chisq", (counts.shape,), xs, counts)
    return out


def gamma_v2(keys, counts, jmax: int):
    """``Gamma(k/2)`` draws for integer ``k = counts`` (float-encoded)
    as ``-log prod U + odd * 0.5 * N^2`` with in-kernel philox
    randomness: ``keys (B, 2)`` uint32 key words per chain, ``counts
    (B, n)``, one draw per element. ``jmax`` is the static uniform-pool
    half-width (``kmax // 2``); streams are pinned against the jnp twin
    in ops/rng.py."""
    import jax.numpy as jnp

    meta = jnp.asarray([jmax], jnp.int32)
    (out,) = _call("gst_gamma_v2", (counts.shape,), keys, counts, meta,
                   dtype=counts.dtype)
    return out


def beta_frac(keys, a, b):
    """``Beta(a, b)`` draws for per-chain fractional shapes via two
    in-kernel Marsaglia-Tsang gammas (``keys (B, 2)`` uint32,
    ``a/b (B,)``)."""
    (out,) = _call("gst_beta_frac", (a.shape,), keys, a, b,
                   dtype=a.dtype)
    return out


def white_mh(x, az, yred2, dx, logu, rows, specs, var):
    """The whole white-noise MH block as one custom call — the native
    arm of ops/pallas_white.make_white_block (XLA oracle
    ``white_mh_loop_xla``). ``rows (R, n)`` / ``specs (3, p)`` shared
    across the chain batch; ``var`` the static (kind, x_index,
    row_slot) int32 table."""
    import jax.numpy as jnp

    var_arr = jnp.asarray(np.asarray(var, np.int32).reshape(-1, 3))
    xo, acc = _call("gst_white_mh", (x.shape, x.shape[:-1]), x, az,
                    yred2, dx, logu, rows, specs, var_arr)
    return xo, acc


def white_mh_lanes(x, az, yred2, dx, logu, rows, specs, gid, var):
    """Multi-tenant twin of :func:`white_mh`: the constant rows/specs
    are PER LANE (``rows (B, R, n)``, ``specs (B, 3, p)`` — the serve
    slot pool's call-time operands) under the tile-uniform ``gid``
    contract of :func:`tnt_lanes`; ``var`` stays the static
    (kind, x_index, row_slot) table, fixed by the pool template's
    model STRUCTURE. A pool whose lanes share one model is bitwise
    identical to the shared-consts kernel (same tile loop)."""
    import jax.numpy as jnp

    var_arr = jnp.asarray(np.asarray(var, np.int32).reshape(-1, 3))
    xo, acc = _call("gst_white_lanes", (x.shape, x.shape[:-1]), x, az,
                    yred2, dx, logu, rows, specs, gid, var_arr,
                    dtype=x.dtype)
    return xo, acc


def hyper_mh(x, S0, dS0, rt, base, dx, logu, K, sel, specs, hyp_idx,
             jitter):
    """The whole hyper MH block as one custom call — the native arm of
    ops/pallas_hyper.make_hyper_block (XLA oracle
    ``hyper_mh_loop_xla``): per-proposal affine-phi evaluation,
    equilibrated no-L Cholesky with fused forward solve, prior and
    masked accept, all in-kernel with S0 tile-resident."""
    import jax.numpy as jnp

    idx = jnp.asarray(np.asarray(hyp_idx, np.int32))
    jit_arr = jnp.asarray([jitter], x.dtype)
    xo, acc = _call("gst_hyper_mh", (x.shape, x.shape[:-1]), x, S0,
                    dS0, rt, base, dx, logu, K, sel, specs, idx,
                    jit_arr)
    return xo, acc


def schur(A, Bm, C, rhs_s, rhs_v, jitter):
    """Fused Schur pre-elimination (ops/linalg.py ``schur_eliminate``
    with ``return_factor=True``): equilibrated A-block factor, the
    multi-rhs solves and the S0/rt assembly matmuls in one custom
    call. Returns ``(S0, rt, quad_s, logdetA, La, isd_a, U_B, u_s)``."""
    import jax.numpy as jnp

    ns = A.shape[-1]
    nv = C.shape[-1]
    batch = A.shape[:-2]
    jit_arr = jnp.asarray([jitter], A.dtype)
    return tuple(_call("gst_schur",
                       (batch + (nv, nv), batch + (nv,), batch, batch,
                        batch + (ns, ns), batch + (ns,),
                        batch + (ns, nv), batch + (ns,)),
                       A, Bm, C, rhs_s, rhs_v, jit_arr))


def fused_hyper(A, Bm, C, rhs_s, rhs_v, x, dx, logu, xi, base0, K, sel,
                phist, specs, hyp_idx, jitter, jitters):
    """GST_FUSE_STAGES megastage: Schur pre-elimination + the whole
    hyper MH block + the b-draw's robust v-block factorization and
    block-assembled backward solves as ONE custom call. Returns
    ``(x, acc, y_v, isd_v, y_s, isd_a)`` — the caller scatters
    ``b[s] = y_s * isd_a``, ``b[v] = y_v * isd_v``."""
    import jax.numpy as jnp

    ns = A.shape[-1]
    nv = C.shape[-1]
    batch = A.shape[:-2]
    idx = jnp.asarray(np.asarray(hyp_idx, np.int32))
    jit_arr = jnp.asarray([jitter], x.dtype)
    jits = jnp.asarray(np.asarray(jitters, np.float64), x.dtype)
    return tuple(_call("gst_fused_hyper",
                       (x.shape, batch, batch + (nv,), batch + (nv,),
                        batch + (ns,), batch + (ns,)),
                       A, Bm, C, rhs_s, rhs_v, x, dx, logu, xi, base0,
                       K, sel, phist, specs, idx, jit_arr, jits))


def fused_hyper_lanes(A, Bm, C, rhs_s, rhs_v, x, dx, logu, xi, base0,
                      K, sel, phist, specs, gid, hyp_idx, jitter,
                      jitters):
    """Multi-tenant megastage: :func:`fused_hyper` with the model
    constants PER LANE (``K (B, 1+nk, v)``, ``sel/phist (B, v)``,
    ``specs (B, 3, p)``) under the tile-uniform ``gid`` contract of
    :func:`tnt_lanes`. Same tile functions as the shared form — a
    uniform pool is bitwise identical to it."""
    import jax.numpy as jnp

    ns = A.shape[-1]
    nv = C.shape[-1]
    batch = A.shape[:-2]
    idx = jnp.asarray(np.asarray(hyp_idx, np.int32))
    jit_arr = jnp.asarray([jitter], x.dtype)
    jits = jnp.asarray(np.asarray(jitters, np.float64), x.dtype)
    return tuple(_call("gst_fused_hyper_lanes",
                       (x.shape, batch, batch + (nv,), batch + (nv,),
                        batch + (ns,), batch + (ns,)),
                       A, Bm, C, rhs_s, rhs_v, x, dx, logu, xi, base0,
                       K, sel, phist, specs, idx, gid, jit_arr, jits,
                       dtype=x.dtype))
