"""Pallas TPU kernel: lane-batched small-matrix Cholesky + solves.

The Gibbs sweep factors ~14 batched ``(chains, m, m)`` systems per sweep
with m ~ 60-74 (10 marginalized-likelihood MH evaluations, reference
gibbs.py:288-329, plus the stacked escalating-jitter b-draw factorization,
gibbs.py:168-178). The FLOPs are trivial (~0.1 GFLOP per factorization at
1024 chains) but XLA lowers ``cholesky``/``triangular_solve`` to a
sequential While loop over columns with dynamic slices — ~11 ms per
factorization on a v5e, ~85% of the whole sweep
(artifacts/tpu_microbench_r02.json). A trace-time-unrolled XLA variant
(ops/unrolled_chol.py) wins standalone but schedules badly inside the
sweep (artifacts/tpu_validation_r02.json), so the production path is this
kernel, designed for how a TPU actually wants to do thousands of tiny
factorizations at once:

- **batch on the lane dimension.** Arrays live as ``(m, m, lanes)`` with
  the matrix *column* index outermost (untiled), the row index on
  sublanes, and ``chain_tile`` chains on lanes. Every step of the
  textbook right-looking recurrence becomes a full-width VPU op over 128
  chains at once — no MXU, no loop machinery, no per-chain anything.
- **everything resident in VMEM.** One chain tile's working set
  (~3 MB at m=80, 128 lanes) stays on-chip for the whole factorization;
  HBM sees exactly one read of ``S`` and one write of ``L``.
- **rank-1 trailing updates, statically unrolled.** Column ``j`` costs
  one ``(m, m, lanes)`` fused multiply-subtract masked to columns
  ``> j``; the full factorization is ~m uniform ops with identical
  static shapes (the shape discipline that ops/unrolled_chol.py's
  compile-time blowup taught).
- **fused forward substitution.** ``u = L^-1 rhs`` rides along in the
  same pass, so a marginalized-likelihood evaluation
  (``rhs^T Sigma^-1 rhs``, ``logdet Sigma``) needs no separate
  triangular solve; the matching backward kernel finishes the b-draw.

Failure semantics are branchless and identical to the XLA paths: a
non-PD pivot produces NaN via ``rsqrt``, which poisons ``logdet`` and
every later column — callers map non-finite to ``-inf`` log-likelihood /
MH rejection (reference gibbs.py:320-324).

Like ops/pallas_tnt.py, matvec-shaped ops are kept >= 2-D throughout:
this libtpu's Mosaic cannot parse the attribute a 1-D ``jnp.dot`` emits
(verified on v5e; see that module's header).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only imports on builds with the TPU extension available
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAVE_PLTPU = False

from gibbs_student_t_tpu.ops.pallas_util import (
    note_kernel_build,
    tpu_compiler_params,
)

# Above this the statically-unrolled kernel program gets large and the
# O(m^2)-per-tile VMEM working set stops fitting comfortably.
MAX_PALLAS_DIM = 160


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _spec(shape, index_map):
    if _HAVE_PLTPU:
        return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)
    return pl.BlockSpec(shape, index_map)


def _chol_kernel(S_ref, r_ref, L_ref, u_ref, ld_ref, *, mp: int):
    """Factor one chain tile: ``L L^T = S`` with fused forward solve.

    Layout (column-major-of-columns): ``S/L (mp, mp, lanes)`` indexed
    ``[matrix column, matrix row, chain]``; ``r/u (mp, lanes)``.
    Right-looking: after column ``j`` is finished, its rank-1 outer
    product is subtracted from every *later* column in one masked
    full-buffer op, so the trailing matrix always holds the Schur
    complement of the processed block.
    """
    L_ref[:] = S_ref[:]
    lanes = r_ref.shape[-1]
    racc = jnp.zeros((mp, lanes), jnp.float32)  # sum_k L[i,k] u[k]
    ld = jnp.zeros((1, lanes), jnp.float32)
    # masks built from in-kernel iota (captured host constants are not
    # allowed in pallas kernels); comparisons against the static j fold
    # into predicated vector ops
    rows2 = jax.lax.broadcasted_iota(jnp.int32, (mp, 1), 0)
    rows3 = jax.lax.broadcasted_iota(jnp.int32, (mp, 1, 1), 0)
    for j in range(mp):
        c = L_ref[j]                              # (mp, lanes)
        piv = c[j:j + 1, :]                       # (1, lanes)
        inv = jax.lax.rsqrt(piv)
        ld += jnp.log(piv)
        col = jnp.where(rows2 >= j, c * inv, 0.0)
        uj = (r_ref[j:j + 1, :] - racc[j:j + 1, :]) * inv
        u_ref[j:j + 1, :] = uj
        racc = racc + col * uj
        # rank-1 trailing update of the columns strictly after j; the
        # mask keeps finished columns (and j itself, written below) intact
        upd = col[:, None, :] * col[None, :, :]   # [j', i, chain]
        L_ref[:] = L_ref[:] - jnp.where(rows3 > j, upd, 0.0)
        L_ref[j] = col
    ld_ref[:] = ld


def _backsolve_kernel(L_ref, r_ref, x_ref, *, mp: int):
    """``L^T x = r`` for one chain tile, same layout as `_chol_kernel`.

    Descending substitution: entries above the current row are still
    zero in ``x``, so the full-column contraction is the partial sum the
    recurrence needs.
    """
    x_ref[:] = jnp.zeros_like(x_ref)
    for j in range(mp - 1, -1, -1):
        colj = L_ref[j]                           # (mp, lanes)
        dot = jnp.sum(colj * x_ref[:], axis=0, keepdims=True)
        x_ref[j:j + 1, :] = (r_ref[j:j + 1, :] - dot) / colj[j:j + 1, :]


def _pad_batch_identity(S, rhs, bpad: int):
    """Append ``bpad`` identity systems along the flat batch axis."""
    if not bpad:
        return S, rhs
    mp = S.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(mp, dtype=S.dtype), (bpad, mp, mp))
    S = jnp.concatenate([S, eye], axis=0)
    rhs = jnp.concatenate([rhs, jnp.zeros((bpad, mp), rhs.dtype)], axis=0)
    return S, rhs


def _to_lane_layout(S, rhs):
    """``(B, mp, mp) -> (mp, mp, B)`` (column, row, chain); rhs -> (mp, B)."""
    return jnp.transpose(S, (2, 1, 0)), jnp.transpose(rhs, (1, 0))


def chol_fused_lane(S, rhs, chain_tile: int = 128, interpret: bool = False
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``(L, logdet, u)`` for ``S (..., m, m)``, ``rhs (..., m)`` (f32).

    All leading dims are flattened onto the lane-batch axis. Callers
    that only consume ``logdet``/``u`` (the marginalized-likelihood MH
    path) don't pay for ``L``: its back-relayout is ordinary XLA code
    that dead-code-eliminates when unused.
    """
    if S.dtype != jnp.float32:
        raise ValueError(f"pallas chol kernel is float32-only, got {S.dtype}")
    batch = S.shape[:-2]
    m = S.shape[-1]
    # trace-time: fires once per XLA compile that embeds this kernel
    note_kernel_build("pallas_chol_fused_lane", m=int(m),
                      chain_tile=int(chain_tile),
                      interpret=bool(interpret))
    from gibbs_student_t_tpu.ops.unrolled_chol import _pad_identity

    Sf = S.reshape((-1,) + S.shape[-2:])
    rf = rhs.reshape((-1, m))
    B = Sf.shape[0]
    tile = min(chain_tile, _round_up(B, 8))
    Sf, rf, _ = _pad_identity(Sf, rf, 8)       # sublane-align m
    mp = Sf.shape[-1]
    Bp = _round_up(B, tile)
    Sf, rf = _pad_batch_identity(Sf, rf, Bp - B)
    St, rt = _to_lane_layout(Sf, rf)

    # chain tiles are independent
    kwargs = tpu_compiler_params(("parallel",))
    kernel = functools.partial(_chol_kernel, mp=mp)
    Lt, ut, ld = pl.pallas_call(
        kernel,
        grid=(Bp // tile,),
        in_specs=[
            _spec((mp, mp, tile), lambda g: (0, 0, g)),
            _spec((mp, tile), lambda g: (0, g)),
        ],
        out_specs=[
            _spec((mp, mp, tile), lambda g: (0, 0, g)),
            _spec((mp, tile), lambda g: (0, g)),
            _spec((1, tile), lambda g: (0, g)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, mp, Bp), jnp.float32),
            jax.ShapeDtypeStruct((mp, Bp), jnp.float32),
            jax.ShapeDtypeStruct((1, Bp), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(St, rt)

    logdet = ld[0, :B].reshape(batch)
    u = jnp.transpose(ut, (1, 0))[:B, :m].reshape(batch + (m,))
    L = jnp.transpose(Lt, (2, 1, 0))[:B, :m, :m].reshape(batch + (m, m))
    return L, logdet, u


def tri_solve_T_lane(L, rhs, chain_tile: int = 128,
                     interpret: bool = False) -> jnp.ndarray:
    """Backward substitution ``L^T x = rhs`` in the lane-batched layout.

    ``L (..., m, m)`` lower-triangular (as from :func:`chol_fused_lane`),
    ``rhs (..., m)``; float32 only.
    """
    if L.dtype != jnp.float32:
        raise ValueError(f"pallas solve kernel is float32-only, got {L.dtype}")
    batch = L.shape[:-2]
    m = L.shape[-1]
    from gibbs_student_t_tpu.ops.unrolled_chol import _pad_identity

    Lf = L.reshape((-1, m, m))
    rf = rhs.reshape((-1, m))
    B = Lf.shape[0]
    tile = min(chain_tile, _round_up(B, 8))
    Lf, rf, _ = _pad_identity(Lf, rf, 8)
    mp = Lf.shape[-1]
    Bp = _round_up(B, tile)
    Lf, rf = _pad_batch_identity(Lf, rf, Bp - B)
    Lt, rt = _to_lane_layout(Lf, rf)

    kwargs = tpu_compiler_params(("parallel",))
    kernel = functools.partial(_backsolve_kernel, mp=mp)
    xt = pl.pallas_call(
        kernel,
        grid=(Bp // tile,),
        in_specs=[
            _spec((mp, mp, tile), lambda g: (0, 0, g)),
            _spec((mp, tile), lambda g: (0, g)),
        ],
        out_specs=_spec((mp, tile), lambda g: (0, g)),
        out_shape=jax.ShapeDtypeStruct((mp, Bp), jnp.float32),
        interpret=interpret,
        **kwargs,
    )(Lt, rt)
    return jnp.transpose(xt, (1, 0))[:B, :m].reshape(batch + (m,))


def _check_lanes_gid(arr, gid, who: str) -> None:
    """Validate the serve slot pool's tile-uniform ``gid`` contract for
    the per-lane matrix kernels: one group id per lane, lanes in whole
    16-lane admission groups. The chol kernels are already per-lane
    (every leading dim lands on the lane batch), so ``gid`` is a
    contract witness here, not a consumed operand."""
    from gibbs_student_t_tpu.ops.pallas_util import LANES_GROUP

    if gid.ndim != 1 or gid.shape[0] != arr.shape[0]:
        raise ValueError(
            f"{who}: gid must be (lanes,) matching the leading lane "
            f"axis, got gid {gid.shape} for operand {arr.shape}")
    if arr.shape[0] % LANES_GROUP:
        raise ValueError(
            f"{who}: lane batch {arr.shape[0]} is not a multiple of "
            f"the {LANES_GROUP}-lane admission group")


def chol_fused_lanes(S, rhs, gid, chain_tile: int = 128,
                     interpret: bool = False
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Serve-lanes entry point for :func:`chol_fused_lane` — ``S (B, m,
    m)`` / ``rhs (B, m)`` per-lane operands under the slot pool's
    tile-uniform ``gid`` contract. The underlying kernel is per-lane
    already (matrices ride the lane batch), so this only validates the
    contract and notes the dispatch (``chol_lanes`` in the registry's
    declared OPS table) before delegating."""
    from gibbs_student_t_tpu.ops.linalg import _factor_fused, _note_impl
    from gibbs_student_t_tpu.ops.pallas_util import mode_from_env

    _check_lanes_gid(S, gid, "chol_fused_lanes")
    enabled, interp, _forced = mode_from_env("GST_PALLAS_CHOL")
    if not (enabled and S.dtype == jnp.float32
            and S.shape[-1] <= MAX_PALLAS_DIM):
        # clean degradation: the ordinary factor dispatch (which may
        # itself pick native/vchol/expander per its own gates)
        _note_impl("chol_lanes", "factor", S.shape)
        return _factor_fused(S, rhs)
    note_kernel_build("pallas_chol_lanes", lanes=int(S.shape[0]),
                      m=int(S.shape[-1]), interpret=bool(interpret))
    _note_impl("chol_lanes", "pallas", S.shape)
    return chol_fused_lane(S, rhs, chain_tile=chain_tile,
                           interpret=interpret or interp)


def tri_solve_T_lanes(L, rhs, gid, chain_tile: int = 128,
                      interpret: bool = False) -> jnp.ndarray:
    """Serve-lanes twin of :func:`tri_solve_T_lane` (see
    :func:`chol_fused_lanes` for the gid contract)."""
    from gibbs_student_t_tpu.ops.linalg import _backsolve_fused, _note_impl
    from gibbs_student_t_tpu.ops.pallas_util import mode_from_env

    _check_lanes_gid(L, gid, "tri_solve_T_lanes")
    enabled, interp, _forced = mode_from_env("GST_PALLAS_CHOL")
    if not (enabled and L.dtype == jnp.float32
            and L.shape[-1] <= MAX_PALLAS_DIM):
        _note_impl("chol_lanes", "factor", L.shape)
        return _backsolve_fused(L, rhs)
    _note_impl("chol_lanes", "pallas", L.shape)
    return tri_solve_T_lane(L, rhs, chain_tile=chain_tile,
                            interpret=interpret or interp)
