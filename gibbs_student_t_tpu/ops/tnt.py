"""Fused inner products of the marginalized likelihood, dense and blocked.

Every sweep needs the same three reductions over the TOA axis
(reference gibbs.py:302-311):

    TNT = T^T N^-1 T        (m, m)
    d   = T^T N^-1 y        (m,)
    c   = -1/2 (sum log N + y^T N^-1 y)     (scalar)

where ``N = diag(nvec)``. The dense form materializes the weighted basis
``T / nvec[:, None]`` — an ``(n, m)`` intermediate *per chain* under
``vmap``, which at the stress scale (n=1e5, m~74, 1024 chains) is ~30 TB
and cannot exist. :func:`tnt_products` therefore switches to a
``lax.scan`` over TOA blocks (BASELINE.json config 4): each step computes
one block's ``T_b^T (T_b / nvec_b)`` on the MXU and accumulates into the
``(m, m)`` carry, so live memory per chain is ``O(block x m)`` and the
matmuls stay big enough to tile well.

``T`` is parameter-independent in this model family, so callers pad it
once (``pad_rows``) to a block multiple; padded rows carry ``y = 0`` and
``nvec = 1`` and contribute exactly zero to all three outputs.

All contractions here run at ``lax.Precision.HIGHEST``: XLA's *default*
f32 matmul precision on TPU truncates inputs to bfloat16 (~3 decimal
digits), and that noise in TNT/d propagates into every marginalized
likelihood — measured as a reproducible posterior bias in the red-noise
spectral index on hardware (on-chip gamma mean 4.44-4.51 vs the f64
oracle's 4.13, artifacts/tpu_gate_r02.json history) while the identical
f32 program on CPU matched the oracle. These matmuls are a trivial
fraction of the sweep, so full-precision passes cost nothing here.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# full f32 matmul passes on TPU (see module docstring)
_HI = lax.Precision.HIGHEST


def pad_rows(T: np.ndarray, y: np.ndarray,
             block_size: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """Zero-pad the TOA axis to a multiple of ``block_size``.

    Returns ``(T_pad, y_pad, n_pad)``. Weight arrays built from masks
    must map the padded tail to ``nvec = 1`` (see ``JaxGibbs``): zero
    basis rows and zero residuals then contribute nothing to TNT/d, and
    ``log 1 = 0`` contributes nothing to the white constant.
    """
    n = T.shape[0]
    n_pad = (-n) % block_size
    if n_pad == 0:
        return T, y, 0
    T_pad = np.concatenate([T, np.zeros((n_pad, T.shape[1]), T.dtype)])
    y_pad = np.concatenate([y, np.zeros(n_pad, y.dtype)])
    return T_pad, y_pad, n_pad


def tnt_products(T, y, nvec, block_size: Optional[int] = None):
    """``(TNT, d, const_white)`` for one chain.

    ``block_size=None`` is the dense path (small n). With a block size,
    the TOA axis (which must be an exact multiple) is reduced by
    ``lax.scan``; results are bitwise-independent of ``block_size`` up to
    float reassociation.

    On CPU with the native kernels available (``GST_NCHOL``,
    ops/linalg.py), the dense form of a frozen model (concrete ``T``
    and ``y`` — a traced per-pulsar ensemble basis keeps the plain
    path) routes through the :func:`ops.linalg.tnt_gram` custom_vmap
    dispatcher, so the in-sweep chain batch reaches the lane-batched
    Gram kernel: the basis is shared across every chain and only
    ``nvec`` varies, which XLA's batched matmul cannot exploit (it
    materializes a (B, n, m) weighted basis per sweep). With the gate
    off this function is byte-identical to earlier rounds.
    """
    if block_size is None:
        from gibbs_student_t_tpu.ops.linalg import nchol_active, tnt_gram

        if (nchol_active() and not isinstance(T, jax.core.Tracer)
                and not isinstance(y, jax.core.Tracer)):
            return tnt_gram(jnp.asarray(T), jnp.asarray(y), nvec)
        w = 1.0 / nvec
        Tw = T * w[:, None]
        TNT = jnp.matmul(T.T, Tw, precision=_HI)
        d = jnp.matmul(Tw.T, y, precision=_HI)
        const = -0.5 * (jnp.sum(jnp.log(nvec)) + jnp.sum(y * y * w))
        return TNT, d, const

    n, m = T.shape
    if n % block_size != 0:
        raise ValueError(
            f"blocked tnt_products needs n ({n}) to be a multiple of "
            f"block_size ({block_size}); use pad_rows first")
    nb = n // block_size
    Tb = T.reshape(nb, block_size, m)
    yb = y.reshape(nb, block_size)
    nb_vec = nvec.reshape(nb, block_size)

    def step(carry, blk):
        TNT, d, const = carry
        Tk, yk, nk = blk
        w = 1.0 / nk
        Tw = Tk * w[:, None]
        TNT = TNT + jnp.matmul(Tk.T, Tw, precision=_HI)
        d = d + jnp.matmul(Tw.T, yk, precision=_HI)
        const = const - 0.5 * (jnp.sum(jnp.log(nk))
                               + jnp.sum(yk * yk * w))
        return (TNT, d, const), None

    init = (jnp.zeros((m, m), dtype=T.dtype),
            jnp.zeros((m,), dtype=T.dtype),
            jnp.zeros((), dtype=T.dtype))
    (TNT, d, const), _ = lax.scan(step, init, (Tb, yb, nb_vec))
    return TNT, d, const


def matvec_blocked(T, b, block_size: Optional[int] = None):
    """``T @ b`` with an optional row-blocked scan (same padding contract);
    used for the conditional-likelihood residual ``y - T b`` at stress
    scale."""
    if block_size is None:
        return jnp.matmul(T, b, precision=_HI)
    n, m = T.shape
    nb = n // block_size
    return lax.map(lambda Tk: jnp.matmul(Tk, b, precision=_HI),
                   T.reshape(nb, block_size, m)).reshape(n)


def auto_block_size(n: int, threshold: int = 16384,
                    block: int = 4096) -> Optional[int]:
    """Default policy: dense below ``threshold`` TOAs, blocked above."""
    return None if n < threshold else block
