"""The capability-probed dispatch registry (ROADMAP item 5).

Twelve-plus interacting ``GST_*`` gates grew up one at a time, each
re-implementing the same four-step pipeline — read the environment,
validate strictly, probe the platform/library capability, degrade
silently when forced-but-unavailable, and (since round 9) record the
decision for the run ledger. This module folds that pipeline into ONE
surface:

- :data:`GATES` declares every environment gate the package reads —
  name, owning layer, validation kind, capability requirements, the
  ``auto`` resolution probe, and the one-line description the
  OBSERVABILITY.md env-gate index is generated from
  (``tools/gates.py --markdown``). A ``GST_*`` read anywhere else in
  the package is a tier-1 guard failure (tests/test_obs_wire.py).
- :func:`value` is the single strict validation implementation (the
  loud-typo contract): per-kind rules identical to the historical
  per-gate functions, same error messages.
- :func:`mode3` / :func:`pallas_mode` / :func:`int_value` /... are the
  resolution helpers the dispatch call sites consume — each records
  provenance (gate, validated value, probes consulted, verdict,
  degradation reason) into a process-local log that rides the
  ``xla.registry`` block of every ledger record
  (obs/introspect.compile_summary) and answers ``tools/gates.py``.
- :data:`OPS` is the per-op implementation table behind
  ``ops/linalg.py``'s dispatchers — which impls exist for each op, in
  priority order, guarded by which gate/probe/shape-class — as
  *data*, so the CLI can print the host's resolved dispatch without
  tracing anything.

**The pinned contract**: the registry changes WHERE the probe →
validate → degrade → record pipeline lives, never WHAT it decides.
Every legacy ``GST_*`` value resolves exactly as before (the
``*_env()`` wrappers all delegate here and their strict-validation
tests still pass), and the gates-off lowered graph and chains are
bitwise identical pre/post refactor (tests/test_registry.py pins
cache-on/cache-off chains bitwise; the long-standing gates-off parity
pins in tests/test_nchol.py are the refactor's regression harness).

**Persistence** (the cold-start half of ROADMAP 5): probe outcomes and
first-trace autotune decisions (the linalg impl table a compiled
program chose, per-program compile walls) persist as ``gates.json``
next to the per-host AOT compile cache (:func:`host_cache_dir`),
keyed by native ABI version, the committed ``.so``'s digest (which
pins its ``gst_simd_level``), host CPU flags, jax/jaxlib versions and
the dispatch-config fingerprint (the ``fp``-marked gates' env
values). A key mismatch is a LOUD ignore — ``RuntimeWarning`` plus a
``cache_ignored`` counter — followed by a fresh probe, never a silent
reuse. With a valid cache, a spawned pool worker, a failover respawn
and ``ChainServer.recover()`` reach first dispatch with zero fresh
probe/autotune events (:func:`stats` counters, gated by
``perf_report --check``) and the AOT cache pays the compile; the
measured spawn→first-result walls live in docs/PERFORMANCE.md "Cold
starts".

Only stdlib imports at module scope (obs/ledger.py and
obs/introspect.py import this module and must stay jax-free at import
time); jax and the native FFI layer are imported lazily inside probes.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

GATE_CACHE_SCHEMA = 1
GATE_CACHE_NAME = "gates.json"


class GateSpec(NamedTuple):
    """One declared environment gate.

    ``kind`` selects the validation rule in :func:`value`;
    ``requires`` the capability probes that must ALL pass for the arm
    to be reachable even when forced (the forced-but-unavailable
    silent degradation); ``auto`` the probe an ``auto`` value resolves
    through (``None`` = on whenever ``requires`` holds); ``fp`` marks
    the gate a member of the dispatch-config fingerprint (gates whose
    value can change compiled programs or chains — bitwise-free
    observability toggles stay out so flipping them cannot orphan the
    probe cache); ``doc`` is the generated env-gate index row."""

    name: str
    layer: str                       # ops|native|backend|parallel|serve|obs
    kind: str
    doc: str
    requires: Tuple[str, ...] = ()
    auto: Optional[str] = None
    fp: bool = True
    default: Optional[object] = None


#: validation kinds (see :func:`value`):
#: ``strict3``   auto|1|0, default auto — the standard gate contract
#: ``pallas``    the pallas_util.mode_from_env vocabulary (any value;
#:               0/false/empty off, ``interpret`` forced-interpret,
#:               ``auto`` platform-resolved, anything else forced on)
#: ``truthy``    opt-in flags: unset→None, raw string otherwise (the
#:               caller treats 0/false/empty as off, else on)
#: ``choice``    one of ``default`` (a tuple of legal values)
#: ``enum01``    ''|'0'|'1' (GST_ENSEMBLE_UNROLL's contract)
#: ``posint``    strict positive integer (bytes/sizes)
#: ``int``       forgiving tuning integer (non-numeric → default),
#:               rounded up to a legal multiple
#: ``offswitch`` default-on layer toggles (0/false/empty disables)
#: ``path``      a filesystem path, no validation
_KINDS = ("strict3", "pallas", "truthy", "choice", "enum01", "posint",
          "int", "offswitch", "path")

_G = GateSpec

GATES: Dict[str, GateSpec] = {g.name: g for g in (
    # -- ops: the linalg dispatch family --------------------------------
    _G("GST_VCHOL", "ops", "strict3",
       "portable vectorized Cholesky family (auto-on off-TPU)",
       auto="not_tpu"),
    _G("GST_NCHOL", "native", "strict3",
       "native FFI lane-batched kernel family master gate",
       requires=("cpu", "native")),
    _G("GST_NWHITE", "native", "strict3",
       "native one-call white MH block (+ `gst_white_lanes` serving "
       "twin)", requires=("cpu", "native")),
    _G("GST_NHYPER", "native", "strict3",
       "native one-call hyper MH block", requires=("cpu", "native")),
    _G("GST_NRESID", "native", "strict3",
       "native residual matvec in the z/df glue (auto follows "
       "`GST_NCHOL`)", requires=("cpu", "native")),
    _G("GST_FUSE_STAGES", "backend", "strict3",
       "the schur+hyper+b-draw megastage (probe-gated auto)",
       requires=("cpu", "native")),
    _G("GST_UNROLLED_CHOL", "ops", "truthy",
       "fully-unrolled small-m Cholesky arm"),
    _G("GST_PALLAS_CHOL", "ops", "pallas",
       "Pallas TPU Cholesky kernel (`interpret` accepted)",
       auto="tpu"),
    _G("GST_PALLAS_WHITE", "ops", "pallas",
       "Pallas TPU white-MH kernel (`interpret` accepted)",
       auto="tpu"),
    _G("GST_PALLAS_HYPER", "ops", "pallas",
       "Pallas TPU hyper-MH kernel (`interpret` accepted)",
       auto="tpu"),
    _G("GST_PALLAS_TNT", "ops", "pallas",
       "Pallas TPU per-lane-basis TNT gram lanes twin — tile-uniform "
       "gid contract (`interpret` accepted)", auto="tpu"),
    _G("GST_WHITE_TILE", "ops", "int",
       "white kernel tile size (integer, rounded to a legal multiple)",
       default=256),
    _G("GST_HYPER_TILE", "ops", "int",
       "hyper kernel tile size (integer)", default=128),
    # -- backend: draw/structure arms resolved at construction ----------
    _G("GST_FAST_GAMMA", "backend", "strict3",
       "fast gamma draw path", auto="not_tpu"),
    _G("GST_FAST_GAMMA_V2", "backend", "strict3",
       "philox `-log ∏ U` alpha draw (native; jnp twin is the "
       "degradation arm)", requires=("cpu", "native")),
    _G("GST_FAST_BETA", "backend", "strict3",
       "exact chi-square theta draw (half-integer pseudo-counts)",
       auto="not_tpu"),
    _G("GST_FAST_THETA", "backend", "strict3",
       "native fractional Beta for the remaining priors",
       requires=("cpu", "native")),
    _G("GST_HYPER_HOIST", "backend", "strict3",
       "per-sweep hoisting of proposal-invariant hyper-MH pieces",
       auto="cpu"),
    _G("GST_HYPER_SCHUR", "backend", "truthy",
       "fused Schur pre-elimination in the hyper block"),
    _G("GST_BDRAW_REUSE", "backend", "strict3",
       "b-draw block-factor reuse"),
    _G("GST_DONATE_CHUNK", "backend", "strict3",
       "donate the chunk program's state buffers"),
    # -- parallel -------------------------------------------------------
    _G("GST_ENSEMBLE_UNROLL", "parallel", "enum01",
       "grouped-ensemble chunk unroll factor (integer)"),
    # -- serve ----------------------------------------------------------
    _G("GST_SERVE_PIPELINE", "serve", "strict3",
       "pipelined serving executor vs the serial reference loop",
       fp=False),
    _G("GST_SERVE_SUPERVISE", "serve", "strict3",
       "tenant-scoped fault containment vs historical fail-fast",
       fp=False),
    _G("GST_RECYCLE", "serve", "strict3",
       "recycling-Gibbs row tagging + weighted monitor moments "
       "(parallel/recycle.py; auto→on — recycled rows are "
       "RECONSTRUCTED from adjacent recorded rows, so scan-end rows, "
       "spool bytes and chains are bitwise identical on/off; `0` is "
       "the pre-round-17 drain graph verbatim)", fp=False),
    _G("GST_WARM_START", "serve", "strict3",
       "variational warm-start arm (serve/warm.py): `auto` honors "
       "per-request `warm_start` specs, `1` defaults every tenant to "
       "a pilot-mixture init, `0` force-disables (requests degrade to "
       "the cold prior init, bitwise, with a `warm_start_degraded` "
       "event)"),
    _G("GST_WARM_FLOW", "serve", "strict3",
       "normalizing-flow warm-start fits (serve/warm.py "
       "`kind='flow'`): `auto` honors each spec's requested kind, "
       "`1` upgrades every pilot fit to the masked-affine flow, `0` "
       "degrades flow requests to the moment-matched mixture (a "
       "`warm_flow_degraded` event; the init stays warm, never cold)",
       fp=False),
    _G("GST_SERVE_SCATTER", "serve", "strict3",
       "device-resident admission (serve/pool.py): boundary writes "
       "(admit/reinit/poison) land as fixed-shape jitted lane scatters "
       "and checkpoint reads gather only the owning tenant's lanes "
       "while a quantum's state is device-resident; `0` keeps the "
       "host pull/slice-write/re-upload bounce verbatim (chains, "
       "spool bytes and recovery are bitwise identical on/off — "
       "pinned)", fp=False),
    _G("GST_ADAPT_SCAN", "serve", "strict3",
       "adaptive block scans (serve/adapt.py, arXiv:1808.09047): the "
       "slot-pool chunk gains a per-lane block-enable operand and "
       "converged conditional blocks are thinned to a learned "
       "random-scan selection probability at quantum boundaries "
       "(host slice-writes, no recompile); `0` omits the operand — "
       "the pre-adaptive lowered graph and chains, bitwise (pinned)"),
    _G("GST_SERVE_WATCHDOG", "serve", "choice",
       "serving stall watchdog policy: `auto`(→`dump`)\\|`0`\\|`warn`"
       "\\|`dump`\\|`fail` (not an `auto\\|1\\|0` gate)",
       fp=False, default=("auto", "0", "warn", "dump", "fail")),
    _G("GST_RPC_MAX_FRAME", "serve", "posint",
       "RPC wire per-frame byte ceiling (positive integer, default "
       "256 MiB; not an `auto\\|1\\|0` gate) — both ends reject "
       "larger frames BEFORE allocating", fp=False,
       default=256 * 1024 * 1024),
    # -- native runtime flag -------------------------------------------
    _G("GST_KERNEL_TIMERS", "native", "strict3",
       "in-kernel per-stage cycle timers (a runtime flag in the same "
       "compiled kernels — chains and the lowered graph are bitwise "
       "identical on/off; auto-on where the .so has the timer "
       "surface)", requires=("native_timers",), fp=False),
    # -- obs ------------------------------------------------------------
    _G("GST_INTROSPECT", "obs", "offswitch",
       "XLA compile introspection layer (`0`/`false`/empty disables)",
       fp=False),
    _G("GST_LEDGER_PATH", "obs", "path",
       "run-ledger path override (a path, not a flag)", fp=False),
    _G("GST_CACHE_DIR", "obs", "path",
       "persistent cold-start cache directory override (a path; "
       "default is the per-host `.jax_cache/<fingerprint>` dir) — "
       "the AOT compile cache and `gates.json` live here", fp=False),
)}


#: Per-op implementation tables behind ops/linalg.py's dispatchers —
#: priority-ordered ``(impl, gate, shape-class guard)`` rows, as data.
#: ``tools/gates.py`` renders the host-resolved view; the dispatch
#: functions themselves keep their (pinned, bitwise) hand play-by-play
#: — this table documents it, the tests cross-check it never drifts.
OPS: Dict[str, List[Tuple[str, Optional[str], str]]] = {
    "factor": [("pallas", "GST_PALLAS_CHOL", "f32, m<=MAX_PALLAS_DIM"),
               ("nchol", "GST_NCHOL", "f32/f64, m<=MAX_VCHOL_DIM"),
               ("vchol", "GST_VCHOL", "m<=MAX_VCHOL_DIM"),
               ("expander", None, "any")],
    "factor_quad": [("nchol", "GST_NCHOL", "f32/f64, m<=MAX_VCHOL_DIM"),
                    ("factor-fallback", None, "any (L dead-coded)")],
    "bwd_vec": [("pallas", "GST_PALLAS_CHOL", "f32, m<=MAX_PALLAS_DIM"),
                ("nchol", "GST_NCHOL", "f32/f64, m<=MAX_VCHOL_DIM"),
                ("vchol", "GST_VCHOL", "m<=MAX_VCHOL_DIM"),
                ("expander", None, "any")],
    "fwd_mat": [("nchol", "GST_NCHOL", "f32/f64, m<=MAX_VCHOL_DIM"),
                ("vchol", "GST_VCHOL", "m<=MAX_VCHOL_DIM"),
                ("expander", None, "any")],
    "bwd_mat": [("nchol", "GST_NCHOL", "f32/f64, m<=MAX_VCHOL_DIM"),
                ("vchol", "GST_VCHOL", "m<=MAX_VCHOL_DIM"),
                ("expander", None, "any")],
    "schur": [("nchol", "GST_NCHOL", "batched, v<=MAX_VCHOL_DIM"),
              ("jnp", None, "any")],
    "robust_draw": [("nchol", "GST_NCHOL", "batched"),
                    ("stacked", None, "any")],
    "tnt": [("nchol", "GST_NCHOL", "shared basis, batch>=MIN_BATCH"),
            ("vmap_jnp", None, "any")],
    "tnt_lanes": [("nchol", "GST_NCHOL", "per-lane basis, tile-uniform "
                   "gid"),
                  ("pallas", "GST_PALLAS_TNT", "f32, tile-uniform gid, "
                   "lanes%16==0"),
                  ("vmap_jnp", None, "any")],
    "resid": [("nchol", "GST_NRESID", "shared basis"),
              ("vmap_jnp", None, "any")],
    "resid_lanes": [("nchol", "GST_NRESID", "per-lane basis"),
                    ("vmap_jnp", None, "any")],
    "chisq": [("nchol", "GST_NCHOL", "FORCED (=1) only — auto keeps "
               "the fused jnp reduction, measured faster"),
              ("jnp", None, "any")],
    "gamma_v2": [("nchol", "GST_FAST_GAMMA_V2", "native draws ready"),
                 ("jnp_philox", None, "any (identical streams)")],
    "beta_frac": [("nchol", "GST_FAST_THETA", "native draws ready"),
                  ("random_beta", None, "any (same law, different "
                   "stream)")],
    "white_mh": [("nwhite", "GST_NWHITE", "p<=64, nvar<=16"),
                 ("pallas", "GST_PALLAS_WHITE", "TPU"),
                 ("loop_xla", None, "any")],
    "white_lanes": [("nchol", "GST_NWHITE", "per-lane consts, "
                     "tile-uniform gid"),
                    ("pallas", "GST_PALLAS_WHITE", "f32, tile-uniform "
                     "gid, lanes%16==0"),
                    ("loop_xla", None, "any")],
    "hyper_mh": [("nchol", "GST_NHYPER", "p<=64, nk<=16"),
                 ("pallas", "GST_PALLAS_HYPER", "TPU"),
                 ("loop_xla", None, "any")],
    "fused_hyper": [("nchol", "GST_FUSE_STAGES", "fusable model "
                     "structure"), ("stages", None, "per-stage graph "
                     "verbatim")],
    "fused_hyper_lanes": [("nchol", "GST_FUSE_STAGES", "per-lane "
                           "consts, tile-uniform gid"),
                          ("pallas", "GST_PALLAS_HYPER", "f32, "
                           "tile-uniform gid, lanes%16==0, "
                           "v<=MAX_PALLAS_V (pallas hyper core inside "
                           "the per-stage composition)"),
                          ("stages", None, "per-stage graph "
                           "verbatim")],
    "chol_lanes": [("pallas", "GST_PALLAS_CHOL", "f32, "
                    "m<=MAX_PALLAS_DIM, per-lane matrices (lane batch "
                    "is the leading axis — gid validated, not "
                    "consumed)"),
                   ("factor", None, "delegates to the factor/bwd_vec "
                    "dispatch above")],
}

# the declared tables must cover every op the dispatchers ever note —
# tests/test_registry.py cross-checks at runtime; this static list is
# the grep target a new dispatcher's author will find first
assert set(OPS) >= {
    "factor", "factor_quad", "bwd_vec", "fwd_mat", "bwd_mat", "schur",
    "robust_draw", "tnt", "tnt_lanes", "resid", "resid_lanes", "chisq",
    "gamma_v2", "beta_frac", "white_mh", "white_lanes", "hyper_mh",
    "fused_hyper", "fused_hyper_lanes", "chol_lanes"}


# ----------------------------------------------------------------------
# capability probes
# ----------------------------------------------------------------------

_LOCK = threading.RLock()
_PROBE_SEEN: Dict[str, bool] = {}
_PROVENANCE: List[Dict[str, Any]] = []
_AUTOTUNE_SEEN: Dict[str, bool] = {}   # key -> predicted-by-cache
_CACHE: Optional[Dict[str, Any]] = None    # the loaded gates.json doc
_CACHE_INFO: Dict[str, Any] = {"dir": None, "loaded": False,
                               "ignored": None}
_COUNTERS = {"probes_fresh": 0, "probes_cached": 0,
             "autotune_fresh": 0, "autotune_cached": 0,
             "cache_ignored": 0, "resolutions": 0}


def _probe_cpu() -> bool:
    import jax

    return jax.default_backend() == "cpu"


def _probe_not_tpu() -> bool:
    import jax

    return jax.default_backend() not in ("tpu", "axon")


def _probe_tpu() -> bool:
    import jax

    return jax.default_backend() in ("tpu", "axon")


def _probe_native() -> bool:
    try:
        from gibbs_student_t_tpu.native import ffi as nffi

        return nffi.ready()
    except Exception:  # noqa: BLE001 - absence, not an error
        return False


def _probe_native_timers() -> bool:
    try:
        from gibbs_student_t_tpu.native import ffi as nffi

        return nffi.timers_available()
    except Exception:  # noqa: BLE001
        return False


_PROBE_FNS: Dict[str, Callable[[], bool]] = {
    "cpu": _probe_cpu,
    "not_tpu": _probe_not_tpu,
    "tpu": _probe_tpu,
    "native": _probe_native,
    "native_timers": _probe_native_timers,
}


def probe(name: str) -> bool:
    """One capability probe, latched per process. The first evaluation
    counts ``probes_cached`` when a loaded gates cache predicted the
    outcome, ``probes_fresh`` otherwise (the counter ``perf_report
    --check`` gates a recovered pool on); a cache that predicted
    WRONG warns loudly — the probe's live verdict always wins."""
    with _LOCK:
        if name in _PROBE_SEEN:
            return _PROBE_SEEN[name]
    ok = bool(_PROBE_FNS[name]())
    with _LOCK:
        if name in _PROBE_SEEN:          # lost a race: first call won
            return _PROBE_SEEN[name]
        predicted = None
        if _CACHE is not None:
            ent = (_CACHE.get("probes") or {}).get(name)
            if isinstance(ent, dict):
                predicted = ent.get("ok")
        if predicted is None:
            _COUNTERS["probes_fresh"] += 1
            src = "fresh"
        elif bool(predicted) == ok:
            _COUNTERS["probes_cached"] += 1
            src = "cache"
        else:
            _COUNTERS["probes_fresh"] += 1
            src = "fresh"
            warnings.warn(
                f"gates cache predicted probe {name!r}={predicted} "
                f"but the live probe says {ok} — cache entry ignored "
                "(host changed under the cache key?)", RuntimeWarning)
        _PROBE_SEEN[name] = ok
        _record_locked({"probe": name, "ok": ok, "source": src})
    return ok


def _unlatch_probe(name: str) -> None:
    """Drop one latched probe verdict (tests only — paired with
    native/ffi._reset_for_tests so both layers re-probe together)."""
    with _LOCK:
        _PROBE_SEEN.pop(name, None)


def probes_snapshot() -> Dict[str, Dict[str, Any]]:
    """Evaluated probes so far (the gates.json ``probes`` payload)."""
    with _LOCK:
        return {k: {"ok": v} for k, v in _PROBE_SEEN.items()}


# ----------------------------------------------------------------------
# validation — the one strict surface
# ----------------------------------------------------------------------


def value(name: str):
    """Validated environment value for ``name`` per its declared kind
    (the per-gate defaults/error messages are byte-compatible with the
    historical ``*_env()`` functions, which now all delegate here)."""
    sp = GATES[name]
    env = os.environ.get(name)
    if sp.kind == "strict3":
        if env is not None and env not in ("auto", "1", "0"):
            raise ValueError(
                f"{name} must be 'auto', '1' or '0', got {env!r}")
        return env if env is not None else "auto"
    if sp.kind == "pallas":
        return env if env is not None else "auto"
    if sp.kind == "truthy":
        return env                       # None when unset — caller's rule
    if sp.kind == "choice":
        legal = tuple(sp.default)
        if env is not None and env not in legal:
            pretty = ", ".join(f"'{v}'" for v in legal[:-1])
            raise ValueError(
                f"{name} must be {pretty} or '{legal[-1]}', got "
                f"{env!r}")
        return env if env is not None else legal[0]
    if sp.kind == "enum01":
        env = env if env is not None else ""
        if env != "" and env not in ("0", "1"):
            raise ValueError(
                f"{name} must be '0' or '1', got {env!r}")
        return env
    if sp.kind == "posint":
        if env is None:
            return sp.default
        try:
            v = int(env)
        except ValueError:
            v = -1
        if v <= 0:
            raise ValueError(
                f"{name} must be a positive integer (bytes), got "
                f"{env!r}")
        return v
    if sp.kind == "int":
        try:
            return int(env) if env else int(sp.default)
        except ValueError:
            return int(sp.default)
    if sp.kind == "offswitch":
        return (env if env is not None else "1") not in ("0", "false",
                                                         "")
    if sp.kind == "path":
        return env
    raise AssertionError(f"unknown gate kind {sp.kind!r}")  # pragma: no cover


# ----------------------------------------------------------------------
# resolution helpers (probe -> validate -> degrade -> record, once)
# ----------------------------------------------------------------------


def _record_locked(rec: Dict[str, Any]) -> None:
    if rec not in _PROVENANCE:
        _PROVENANCE.append(dict(rec))
        _COUNTERS["resolutions"] += 1


def record(gate: str, **meta) -> None:
    """Record one resolution a call site derived itself (the few gates
    whose ``auto`` folds in run-structure the registry cannot see —
    GST_HYPER_SCHUR's static-column count, GST_FUSE_STAGES' model
    fusability). Never raises; dedup by content."""
    rec = {"gate": gate}
    for k, v in sorted(meta.items()):
        rec[str(k)] = (v if isinstance(v, (int, float, bool, str,
                                           type(None))) else repr(v))
    with _LOCK:
        _record_locked(rec)


def mode3(name: str) -> Tuple[bool, bool]:
    """``(enabled, forced)`` for a ``strict3`` gate declared with
    ``requires``/``auto`` probes: ``0`` disables; missing capability
    degrades silently even when forced (no runtime ever requires a
    toolchain); ``1`` forces; ``auto`` resolves through the declared
    probe (or to on, when the gate's only condition IS availability)."""
    sp = GATES[name]
    v = value(name)
    if v == "0":
        record(name, value=v, enabled=False, forced=False,
               reason="disabled")
    elif not all(probe(p) for p in sp.requires):
        record(name, value=v, enabled=False, forced=False,
               reason="unavailable: " + "+".join(
                   p for p in sp.requires if not probe(p)))
        return False, False
    if v == "0":
        return False, False
    if v == "1":
        record(name, value=v, enabled=True, forced=True,
               reason="forced")
        return True, True
    if sp.auto is None:
        record(name, value=v, enabled=True, forced=False,
               reason="auto: capability present")
        return True, False
    on = probe(sp.auto)
    record(name, value=v, enabled=on, forced=False,
           reason=f"auto: probe {sp.auto}={on}")
    return on, False


def pallas_mode(name: str) -> Tuple[bool, bool, bool]:
    """``(enabled, interpret, forced)`` — the shared Pallas kernel
    gate vocabulary (pallas_util.mode_from_env, now registry-backed):
    ``0``/``false``/empty off, ``interpret`` forced-interpret,
    ``auto`` on for TPU backends, anything else forced on. Undeclared
    names (the tests' synthetic gates) resolve by the same vocabulary
    without a provenance row."""
    env = (value(name) if name in GATES
           else (os.environ.get(name, "auto")))
    if env in ("0", "false", ""):
        out = (False, False, False)
        reason = "disabled"
    elif env == "interpret":
        out = (True, True, True)
        reason = "forced (interpret)"
    elif env == "auto":
        out = (probe("tpu"), False, False)
        reason = f"auto: probe tpu={out[0]}"
    else:
        out = (True, False, True)
        reason = "forced"
    if name in GATES:
        record(name, value=env, enabled=out[0], forced=out[2],
               reason=reason)
    return out


def int_value(name: str, default: Optional[int] = None,
              mult: int = 8) -> int:
    """Tuning integer: ``default`` when unset/empty/non-numeric (the
    forgiving contract of the historical ``int_from_env``), rounded up
    to a legal ``mult``-multiple."""
    sp = GATES.get(name)
    raw = os.environ.get(name, "")
    base = default if default is not None else int(sp.default)
    try:
        val = int(raw) if raw else base
    except ValueError:
        val = base
    out = -(-max(val, mult) // mult) * mult
    if sp is not None:
        record(name, value=out, reason="env" if raw else "default")
    return out


# ----------------------------------------------------------------------
# provenance + counters
# ----------------------------------------------------------------------


def provenance() -> List[Dict[str, Any]]:
    """Every distinct resolution/probe decision recorded so far."""
    with _LOCK:
        return [dict(r) for r in _PROVENANCE]


def stats() -> Dict[str, int]:
    """Fresh-vs-cached decision counters — the evidence the cold-start
    gates grade: a warm spawn / failover respawn / ``recover()`` with
    a valid gates cache shows ``probes_fresh == 0`` and
    ``autotune_fresh == 0``."""
    with _LOCK:
        return dict(_COUNTERS)


def registry_summary() -> Dict[str, Any]:
    """The ``registry`` block for ledger records / ready.json: cache
    state, counters, probe verdicts, and the resolution log."""
    with _LOCK:
        return {
            "cache": dict(_CACHE_INFO),
            "counters": dict(_COUNTERS),
            "probes": {k: bool(v) for k, v in _PROBE_SEEN.items()},
            "resolutions": [dict(r) for r in _PROVENANCE],
        }


# ----------------------------------------------------------------------
# autotune decisions (first-trace evidence, persisted)
# ----------------------------------------------------------------------


def note_autotune(kind: str, key: str, val: Any = None) -> None:
    """Record one first-trace decision — a linalg dispatcher's chosen
    impl (``kind='linalg'``, ``key='factor=nchol'``) or a program's
    measured compile wall (``kind='compile'``, ``key=label``). Counts
    ``autotune_cached`` when the loaded gates cache already contains
    the identical decision (a recovered pool re-deriving NOTHING),
    ``autotune_fresh`` otherwise. Never raises (called from trace
    paths through obs/introspect)."""
    k = f"{kind}:{key}"
    with _LOCK:
        if k in _AUTOTUNE_SEEN:
            return
        known = False
        if _CACHE is not None:
            known = k in (_CACHE.get("autotune") or {})
        _AUTOTUNE_SEEN[k] = known
        if known:
            _COUNTERS["autotune_cached"] += 1
        else:
            _COUNTERS["autotune_fresh"] += 1
        _AUTOTUNE_LOG[k] = (val if isinstance(
            val, (int, float, bool, str, type(None))) else repr(val))


_AUTOTUNE_LOG: Dict[str, Any] = {}


def autotune_snapshot() -> Dict[str, Any]:
    with _LOCK:
        out = dict(_AUTOTUNE_LOG)
        # carry forward cached entries this process never re-derived,
        # so a save after a warm run does not shrink the store
        if _CACHE is not None:
            for k, v in (_CACHE.get("autotune") or {}).items():
                out.setdefault(k, v)
        return out


# ----------------------------------------------------------------------
# persistence: the gates cache next to the AOT compile cache
# ----------------------------------------------------------------------


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def host_cache_dir() -> str:
    """``<repo>/.jax_cache/<machine>-<cpu-flag-hash>-<jaxlib>`` — one
    compile-cache subdirectory per distinct (host CPU, jaxlib build),
    so an AOT executable is only ever loaded on the feature set AND
    compiler build that produced it (bench.py's r07 hardening, now the
    package-wide helper the serve pool workers share)."""
    import platform as _platform

    tag = _platform.machine() or "unknown"
    tag += "-" + _cpu_flags_hash()
    try:
        import jaxlib

        tag += "-" + getattr(jaxlib, "__version__", "unknown")
    except Exception:  # noqa: BLE001 - fingerprint stays CPU-only
        pass
    return os.path.join(os.path.dirname(_package_root()), ".jax_cache",
                        tag)


def _cpu_flags_hash() -> str:
    try:
        with open("/proc/cpuinfo") as fh:
            for cl in fh:
                if cl.startswith(("flags", "Features")):
                    feats = " ".join(sorted(cl.split(":", 1)[1].split()))
                    return hashlib.sha1(feats.encode()).hexdigest()[:12]
    except OSError:
        pass
    return "noflags"


def _so_digest() -> str:
    """Cheap content proxy for the committed native library (its
    ``gst_simd_level`` and ABI are baked into the file, so the digest
    pins both without loading it): size+mtime hash, ``absent`` when
    not built."""
    try:
        from gibbs_student_t_tpu import native

        st = os.stat(native._LIB_PATH)
        return hashlib.sha1(
            f"{st.st_size}:{int(st.st_mtime)}".encode()).hexdigest()[:12]
    except (OSError, Exception):  # noqa: BLE001
        return "absent"


def config_fingerprint_env() -> str:
    """12-hex sha1 over the ``fp``-marked gates' environment values —
    the dispatch configuration this process runs under. Two processes
    with the same fingerprint resolve every dispatch identically, so
    probe/autotune decisions transfer."""
    items = sorted((n, os.environ.get(n) or "")
                   for n, sp in GATES.items() if sp.fp)
    return hashlib.sha1(repr(items).encode()).hexdigest()[:12]


def cache_key() -> Dict[str, Any]:
    key = {
        "schema": GATE_CACHE_SCHEMA,
        "abi": None,
        "so_digest": _so_digest(),
        "cpu_flags": _cpu_flags_hash(),
        "jax": None,
        "jaxlib": None,
        "config_fp": config_fingerprint_env(),
    }
    try:
        from gibbs_student_t_tpu.native.ffi import ABI_VERSION

        key["abi"] = ABI_VERSION
    except Exception:  # noqa: BLE001
        pass
    try:
        import jax

        key["jax"] = getattr(jax, "__version__", None)
    except Exception:  # noqa: BLE001
        pass
    try:
        import jaxlib

        key["jaxlib"] = getattr(jaxlib, "__version__", None)
    except Exception:  # noqa: BLE001
        pass
    return key


def load_gate_cache(cache_dir: Optional[str] = None) -> bool:
    """Load ``gates.json`` from the (host-fingerprinted) cache dir.
    A missing file is a quiet cold start; a key mismatch is a LOUD
    ignore — ``RuntimeWarning`` naming every stale component plus the
    ``cache_ignored`` counter — followed by fresh probes. Returns
    True when the cache armed."""
    global _CACHE
    d = cache_dir or host_cache_dir()
    path = os.path.join(d, GATE_CACHE_NAME)
    with _LOCK:
        _CACHE_INFO["dir"] = d
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        with _LOCK:
            _CACHE_INFO["loaded"] = False
        return False
    key, have = cache_key(), doc.get("key") or {}
    stale = sorted(k for k in key if have.get(k) != key[k])
    if stale:
        with _LOCK:
            _COUNTERS["cache_ignored"] += 1
            _CACHE_INFO["loaded"] = False
            _CACHE_INFO["ignored"] = "+".join(stale)
        warnings.warn(
            f"gates cache at {path} ignored: stale key components "
            f"{stale} (saved {have}, host {key}) — fresh probe",
            RuntimeWarning)
        return False
    with _LOCK:
        _CACHE = doc
        _CACHE_INFO["loaded"] = True
        _CACHE_INFO["ignored"] = None
    return True


def save_gate_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Persist this process's probe outcomes + autotune decisions
    (atomic write). Returns the path, or None when the directory is
    unwritable (degrade silently: persistence is an optimization,
    never a requirement)."""
    d = cache_dir or _CACHE_INFO.get("dir") or host_cache_dir()
    path = os.path.join(d, GATE_CACHE_NAME)
    doc = {
        "schema": GATE_CACHE_SCHEMA,
        "key": cache_key(),
        "saved_t": round(time.time(), 3),
        "probes": probes_snapshot(),
        "autotune": autotune_snapshot(),
        "resolutions": provenance(),
    }
    try:
        os.makedirs(d, exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def _harden_aot_cache_writes() -> bool:
    """Make jax's filesystem compilation-cache publishes ATOMIC.

    The installed jax's ``LRUCache.put`` writes an entry with a plain
    ``write_bytes`` to its final path — no temp file, no rename, and
    (with eviction disabled, the default) no lock. Two pool workers
    compiling the same chunk program concurrently therefore interleave
    writes into ONE file, and any reader that hits the key mid-write
    deserializes a torn serialized executable — measured on this host
    as a glibc heap-corruption segfault that killed BOTH pools of a
    fleet arm and then poisoned the cache dir for every later boot (a
    torn entry never heals: ``put`` sees the path exists and returns).
    A same-directory temp + ``os.replace`` publish closes both the
    concurrent-writer and the killed-writer tear: readers only ever
    observe absent or complete entries.

    Version-tolerant (the parallel/compat.py discipline): patches only
    the module shape it recognizes, once; anything unexpected leaves
    jax untouched and returns False (callers proceed — the cache then
    simply keeps upstream semantics)."""
    try:
        from jax._src import lru_cache as _lru

        cls = _lru.LRUCache
        cache_sfx = _lru._CACHE_SUFFIX
        atime_sfx = _lru._ATIME_SUFFIX
    except Exception:  # noqa: BLE001 - unknown jax: leave it alone
        return False
    if getattr(cls, "_gst_atomic_put", False):
        return True
    orig_put = cls.put

    def put(self, key, val):
        if getattr(self, "eviction_enabled", False):
            # the evicting configuration takes a cross-process file
            # lock and does bookkeeping we must not re-implement;
            # we never enable it (no max size set)
            return orig_put(self, key, val)
        if not key:
            raise ValueError("key cannot be empty")
        try:
            cache_path = self.path / f"{key}{cache_sfx}"
            if cache_path.exists():
                return
            tmp = self.path / f"{key}.{os.getpid()}.tmp"
            tmp.write_bytes(val)
            os.replace(tmp, cache_path)
            (self.path / f"{key}{atime_sfx}").write_bytes(
                time.time_ns().to_bytes(8, "little"))
        except Exception:  # noqa: BLE001 - a failed WRITE is a lost
            pass           # optimization, never an error

    cls.put = put
    cls._gst_atomic_put = True
    return True


_AOT_ARMED = False


def aot_cache_armed() -> bool:
    """True once :func:`enable_persistent_cache` pointed jax's
    persistent compilation cache at a directory in THIS process.
    Dispatch resolutions consult it: a chunk program that DONATES its
    state buffers must not be deserialized from the AOT cache on this
    jaxlib — a deserialized donated executable loses its aliasing
    contract and corrupts the heap (measured on the graded host: both
    pools of a fleet arm segfaulting in glibc malloc at tenant
    admission) — so ``GST_DONATE_CHUNK``'s ``auto`` resolves OFF in
    cache-armed processes (forcing ``1`` remains the A/B hatch, at
    the caller's own risk)."""
    return _AOT_ARMED


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            min_compile_s: float = 1.0) -> Dict[str, Any]:
    """Arm BOTH cold-start caches for this process: point jax's
    persistent compilation cache at the per-host directory (the AOT
    half — a warm process loads compiled executables instead of
    re-lowering ~5.5 s programs) and load the gates cache beside it
    (the probe/autotune half). Idempotent; call before the first
    trace. Returns ``{dir, aot, gates}`` for the caller's ledger
    evidence. ``GST_CACHE_DIR`` overrides the per-host default (the
    cold-vs-warm bench arms point spawned workers at scratch dirs
    this way); ``GST_CACHE_DIR=0`` disables the arming entirely (the
    operational escape hatch)."""
    override = value("GST_CACHE_DIR")
    if override == "0":
        return {"dir": None, "aot": False, "gates": False,
                "disabled": True}
    global _AOT_ARMED
    d = cache_dir or override or host_cache_dir()
    aot = False
    try:
        import jax

        _harden_aot_cache_writes()
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_s))
        aot = True
        _AOT_ARMED = True
    except Exception:  # noqa: BLE001 - older jax without the knobs
        pass
    gates = load_gate_cache(d)
    return {"dir": d, "aot": aot, "gates": gates}


def _reset_for_tests() -> None:
    """Drop every latched verdict/counter (tests only)."""
    global _CACHE, _AOT_ARMED
    with _LOCK:
        _AOT_ARMED = False
        _PROBE_SEEN.clear()
        _PROVENANCE.clear()
        _AUTOTUNE_SEEN.clear()
        _AUTOTUNE_LOG.clear()
        _CACHE = None
        _CACHE_INFO.update({"dir": None, "loaded": False,
                            "ignored": None})
        for k in _COUNTERS:
            _COUNTERS[k] = 0


# ----------------------------------------------------------------------
# the generated env-gate index (tools/gates.py --markdown)
# ----------------------------------------------------------------------


def gates_markdown() -> List[str]:
    """The OBSERVABILITY.md env-gate index table rows, generated from
    :data:`GATES` (tests pin the committed docs section to exactly
    this output, so the table can never drift from the registry)."""
    lines = ["| gate | layer | what it gates |",
             "|------|-------|---------------|"]
    for name in sorted(GATES):
        sp = GATES[name]
        lines.append(f"| `{name}` | {sp.layer} | {sp.doc} |")
    return lines
