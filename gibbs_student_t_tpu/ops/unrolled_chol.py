"""Statically-unrolled batched Cholesky for small matrices on TPU.

XLA's ``cholesky`` lowers to a sequential While loop over columns with
dynamic slicing; for the (chains, m, m) batches this framework factors
every MH step (m ~ 74, reference gibbs.py:318-321), that costs ~10.5 ms
per call on a v5e — ~85% of the whole Gibbs sweep (measured:
``tools/tpu_microbench.py``, ``artifacts/tpu_microbench_r02.json``).
The matrix is tiny but the *loop machinery* dominates.

This module instead unrolls the Cholesky–Banachiewicz recurrence at
trace time (``m`` is a static model constant), in panel-blocked form
chosen for the TPU *compiler* as much as the hardware:

- ``L`` lives in a fixed-shape ``(..., m, m)`` buffer; columns are
  written with static-index ``at.set`` (lowered to in-place
  dynamic-update-slice), never by growing concatenation — an early
  variant that concatenated a ``(..., m, j)`` stack produced 74
  distinct-shaped einsums and blew TPU compile time past 10 minutes;
- cross-panel corrections are one batched GEMM per panel
  (``L @ rows^T`` on the MXU), so the per-column work only contracts
  over the ``panel``-wide in-panel stack;
- every op in the unrolled program has one of ~10 static shapes, so the
  compiled program is small and fast to build.

The forward substitution ``u = L^-1 rhs`` rides along in the same pass,
so the marginalized-likelihood evaluation (quad form + logdet,
reference gibbs.py:288-329) never touches a triangular-solve expander
either.

Non-PD inputs produce a NaN pivot that propagates through every later
column and into ``logdet`` — the branchless failure signal the callers
already map to ``-inf`` log-likelihood / MH rejection (ops/linalg.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

# Above this the unrolled program stops paying for itself (HLO count grows
# linearly with m) and callers should fall back to jnp.linalg.cholesky.
MAX_UNROLL_DIM = 160


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _pad_identity(M, rhs, panel: int):
    """Pad the trailing (m, m) block of ``M`` to a panel multiple with an
    identity tail (unit pivots: adds 0 to logdet, leaves the leading
    block untouched), and the rhs with zeros. Returns ``(M, rhs, m0)``."""
    m0 = M.shape[-1]
    m = _round_up(m0, panel)
    if m != m0:
        pad = m - m0
        M = jnp.pad(M, [(0, 0)] * (M.ndim - 2) + [(0, pad), (0, pad)])
        eye_tail = jnp.asarray(np.pad(np.zeros(m0), (0, pad),
                                      constant_values=1.0), M.dtype)
        M = M + jnp.diag(eye_tail)
        if rhs is not None:
            rhs = jnp.pad(rhs, [(0, 0)] * (rhs.ndim - 1) + [(0, pad)])
    return M, rhs, m0


def chol_forward(S, rhs=None, panel: int = 16
                 ) -> Tuple[jnp.ndarray, jnp.ndarray,
                            Optional[jnp.ndarray]]:
    """Cholesky ``L L^T = S`` with an optional fused forward solve.

    ``S (..., m, m)`` symmetric; ``rhs (..., m)`` optional. Returns
    ``(L, logdet, u)`` with ``logdet = logdet S`` and ``u = L^-1 rhs``
    (``None`` when no rhs). Unrolled statically over columns — use only
    for ``m <= MAX_UNROLL_DIM``.
    """
    dtype = S.dtype
    S, rhs, m0 = _pad_identity(S, rhs, panel)
    m = S.shape[-1]

    L = jnp.zeros_like(S)
    u = None if rhs is None else jnp.zeros_like(rhs)
    log_pivs = []
    for o in range(0, m, panel):
        rows = L[..., o:o + panel, :]                    # (..., p, m)
        # columns o..o+p corrected for every previous panel in one GEMM
        corr = jnp.einsum("...mk,...bk->...mb", L, rows)
        P = S[..., :, o:o + panel] - corr                # (..., m, p)
        Pl = jnp.zeros_like(P)
        if rhs is not None:
            rp = rhs[..., o:o + panel] - jnp.einsum(
                "...bm,...m->...b", rows, u)
            up = jnp.zeros_like(rp)
        for i in range(panel):
            j = o + i
            lj = Pl[..., j, :]                           # (..., p)
            c = P[..., :, i] - jnp.einsum("...mk,...k->...m", Pl, lj)
            piv2 = c[..., j]
            inv_piv = jnp.reciprocal(jnp.sqrt(piv2))
            log_pivs.append(jnp.log(piv2))
            mask = jnp.asarray(np.arange(m) >= j, dtype=bool)
            col = jnp.where(mask, c * inv_piv[..., None],
                            jnp.zeros((), dtype))
            if rhs is not None:
                # in-panel contributions use the same pre-update Pl row
                ui = (rp[..., i]
                      - jnp.einsum("...k,...k->...", lj, up)) * inv_piv
                up = up.at[..., i].set(ui)
            Pl = Pl.at[..., :, i].set(col)
        L = L.at[..., :, o:o + panel].set(Pl)
        if rhs is not None:
            u = u.at[..., o:o + panel].set(up)
    logdet = jnp.sum(jnp.stack(log_pivs, axis=-1), axis=-1)
    if m != m0:
        L = L[..., :m0, :m0]
        if u is not None:
            u = u[..., :m0]
    return L, logdet, u


def chol_quad_logdet(S, rhs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``(rhs^T S^-1 rhs, logdet S)`` in one fused unrolled pass — the
    whole linear-algebra payload of one marginalized-likelihood
    evaluation."""
    _, logdet, u = chol_forward(S, rhs)
    return jnp.sum(u * u, axis=-1), logdet


def tri_solve_T(L, rhs, panel: int = 16) -> jnp.ndarray:
    """Backward substitution ``L^T x = rhs`` in the same fixed-shape
    panel-unrolled style as :func:`chol_forward` — the b-draw's last
    remaining triangular-solve expander (reference gibbs.py:180's
    ``mn + Li*xi`` becomes one such solve in ops/linalg.py).

    ``L (..., m, m)`` lower-triangular, ``rhs (..., m)``.
    """
    L, rhs, m0 = _pad_identity(L, rhs, panel)
    m = L.shape[-1]

    x = jnp.zeros_like(rhs)
    for o in range(m - panel, -1, -panel):
        cols = L[..., :, o:o + panel]                  # (..., m, p)
        # contributions from already-solved entries (all in higher panels;
        # unsolved x entries are still zero so the full contraction is safe)
        rp = rhs[..., o:o + panel] - jnp.einsum(
            "...kb,...k->...b", cols, x)
        Bd = L[..., o:o + panel, o:o + panel]          # (..., p, p)
        xp = jnp.zeros_like(rp)
        for i in range(panel - 1, -1, -1):
            ci = jnp.einsum("...t,...t->...", Bd[..., :, i], xp)
            xi = (rp[..., i] - ci) / Bd[..., i, i]
            xp = xp.at[..., i].set(xi)
        x = x.at[..., o:o + panel].set(xp)
    return x[..., :m0] if m != m0 else x
