"""Pallas TPU kernel: the whole white-noise MH block in one launch.

The reference's white-noise update is 20 sequential single-coordinate
Metropolis steps (reference gibbs.py:114-143), each evaluating the
conditional-on-b likelihood ``-1/2 (sum log N + sum (y-Tb)^2/N)`` with
``N = alpha^z * Nvec0(efac, equad)`` (reference gibbs.py:262-284). On the
TPU the arithmetic is trivial — O(n * chains) elementwise per step — but
the XLA lowering pays a fixed ~120 us of kernel-launch/scheduling cost
per step across ~6 small fused kernels (threefry draws, ndiag, two
reductions, prior, masked accept), making the block ~2.4 ms of the
6.9 ms flagship sweep while using ~1% of the VPU
(docs/PERFORMANCE.md roofline: "fixed per-op cost").

This kernel runs the *entire* block — all ``nsteps`` proposals,
likelihood + prior evaluations, and masked accepts — inside one
``pallas_call``:

- **chains on sublanes, TOAs/params on lanes.** Every per-chain array is
  ``(chain_tile, n)`` / ``(chain_tile, p)``; a likelihood evaluation is a
  handful of full-width VPU ops plus one lane-axis reduction. (The
  Cholesky kernel puts chains on *lanes* because its recurrence walks
  matrix columns; here the reductions run over TOAs, so TOAs take the
  lane axis and constants broadcast naturally as ``(1, n)`` rows.)
- **randomness is an input, not kernel code.** The per-step draws
  (coordinate choice, jump, log-uniform) are precomputed OUTSIDE with
  the exact key schedule of the XLA path (``jax_backend._mh_draws``), so
  kernel-on vs kernel-off runs consume identical randoms and differ only
  by floating-point reduction order.
- **constant folding at trace time.** Selection groups pinned to
  constants (e.g. the reference's ``efac=1``, run_sims.py:57) fold into
  a fixed baseline variance row ``nv0``; only x-varying groups pay an
  in-kernel coefficient: ``nv(q) = nv0 + sum_g q[i_g]^2 * A_g +
  sum_h exp(2 ln10 q[i_h]) * B_h`` with ``A_g = efac_mask_g * sigma2``,
  ``B_h = equad_mask_h * time_scale^2`` (models/pta.py ndiag).
- **-inf semantics preserved.** Out-of-bounds proposals get ``-inf``
  prior exactly as ``models/parameter.lnprior_specs``; ``-inf - -inf =
  NaN > logu`` is False, i.e. auto-reject — identical to the XLA path.

Padding contract: TOA lanes beyond the real row mask carry
``az = 1, yred2 = 0`` and a zero ``rmask`` pins their variance to 1, so
they add exactly 0 to both reduction terms; parameter lanes beyond ``p``
are masked out of the prior sum; padded chain rows are edge-replicated
and sliced off by the caller.

Like the other kernels in ops/, matvec-shaped contractions stay >= 2-D
(this libtpu's Mosaic cannot parse 1-D ``jnp.dot`` attributes) — though
this kernel needs no dots at all: lane extraction ``x[:, i]`` is a
masked lane-reduction, which also avoids width-1 lane slicing.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.custom_batching import custom_vmap
from jax.experimental import pallas as pl

from gibbs_student_t_tpu.ops.pallas_util import (
    HAVE_PLTPU as _HAVE_PLTPU,
    LANES_GROUP,
    MIN_BATCH as _MIN_BATCH,
    int_from_env,
    mode_from_env,
    note_kernel_build,
    pltpu,
    round_up as _round_up,
    tpu_compiler_params,
    vmem_spec as _spec,
)

LN10 = float(np.log(10.0))
_LOG_2PI = float(np.log(2.0 * np.pi))

# Above this TOA count one chain tile's (tile, n) working set stops
# fitting comfortably in VMEM at the minimum 8-row tile; the XLA loop
# path handles the stress shapes (which are TNT-bound anyway).
MAX_PALLAS_N = 32768


class WhiteConsts(NamedTuple):
    """Trace-time constants of one model's white-noise likelihood.

    ``rows``: (R, n) stacked constant rows — row 0 the folded baseline
    variance ``nv0``, row 1 the real-TOA mask, rows 2+ the per-varying-
    group basis rows. ``var``: static ``(kind, x_index, row_slot)``
    triples, kind 0 = efac (coefficient ``q^2``), 1 = equad
    (``exp(2 ln10 q)``). ``specs``: (3, p) prior table rows
    (kind, a, b) from ``ModelArrays.prior_specs``.
    """

    rows: np.ndarray
    var: Tuple[Tuple[int, int, int], ...]
    specs: np.ndarray


def build_white_consts(ma, row_mask=None) -> WhiteConsts:
    """Fold a ``ModelArrays``'s white-noise structure into kernel form.

    Mirrors ``models.pta.ndiag`` exactly: constant-pinned groups
    (idx == -1) fold into the baseline row at trace time; varying groups
    keep their (n,) basis row and an in-kernel coefficient.
    """
    n = ma.y.shape[0]
    sigma2 = np.asarray(ma.sigma2, np.float64)
    nv0 = np.zeros(n, np.float64)
    var_rows = []
    var = []
    for g, idx in enumerate(ma.efac_idx):
        A = np.asarray(ma.efac_masks[g], np.float64) * sigma2
        if idx < 0:
            nv0 += float(ma.efac_const[g]) ** 2 * A
        else:
            var.append((0, int(idx), 2 + len(var_rows)))
            var_rows.append(A)
    s2 = float(ma.time_scale) ** 2
    for h, idx in enumerate(ma.equad_idx):
        B = np.asarray(ma.equad_masks[h], np.float64) * s2
        if idx < 0:
            nv0 += 10.0 ** (2.0 * float(ma.equad_const[h])) * B
        else:
            var.append((1, int(idx), 2 + len(var_rows)))
            var_rows.append(B)
    rmask = (np.ones(n) if row_mask is None
             else np.asarray(row_mask, np.float64))
    rows = np.stack([nv0, rmask] + var_rows).astype(np.float32)
    specs = np.asarray(ma.prior_specs, np.float32)[:, :3].T.copy()
    kinds = set(np.unique(specs[0].astype(int)))
    if not kinds <= {0, 1, 2}:
        # _lnprior_cols implements exactly these kinds; a new kind added
        # to models/parameter.lnprior_specs must be mirrored there or
        # the fused paths would silently -inf that parameter's prior
        raise ValueError(f"unsupported prior kinds for fused MH: {kinds}")
    return WhiteConsts(rows=rows, var=tuple(var), specs=specs)


# ---------------------------------------------------------------------------
# shared step math (XLA path; the kernel mirrors it lane-padded)
# ---------------------------------------------------------------------------


def _lnprior_cols(q, kind, a, b):
    """Per-parameter log-prior, the ``lnprior_specs`` formula on
    broadcastable (…, p) operands (models/parameter.py:126-144)."""
    out = jnp.full(q.shape, -jnp.inf, q.dtype)
    inb = (q >= a) & (q <= b)
    u = kind == 0
    out = jnp.where(u & inb, -jnp.log(jnp.where(u, b - a, 1.0)), out)
    nrm = kind == 1
    z = (q - a) / jnp.where(nrm, b, 1.0)
    out = jnp.where(nrm, -0.5 * z * z - jnp.log(jnp.where(nrm, b, 1.0))
                    - 0.5 * _LOG_2PI, out)
    lexp = kind == 2
    den = jnp.where(lexp, 10.0 ** b - 10.0 ** a, 1.0)
    out = jnp.where(lexp & inb, q * LN10 + jnp.log(LN10 / den), out)
    return out


def align_consts(c, x_batch_dims: int, core_dims: int = 2):
    """View a consts array whose leading axes are GROUP axes so it
    broadcasts against per-chain data: insert singleton axes for the
    chain-batch dims between the group axes and the core dims.

    ``c`` has shape ``G + core``; the result has ``G + (1,)*extra +
    core`` with ``extra = x_batch_dims - len(G)`` — e.g. rows (G, R, n)
    against x (G, C, p) views as (G, 1, R, n)."""
    g_dims = c.ndim - core_dims
    extra = x_batch_dims - g_dims
    if extra <= 0:
        return c
    shape = c.shape[:g_dims] + (1,) * extra + c.shape[g_dims:]
    return c.reshape(shape)


def _ll_lp_xla(q, az, yred2, rows, var, specs):
    """(ll, lp) for proposal ``q`` (…, p) with per-chain ``az``/``yred2``
    (…, n) — the array-based form of the white conditional likelihood
    (reference gibbs.py:262-284) plus the full prior. ``rows``/``specs``
    may carry leading group axes pre-aligned via :func:`align_consts`."""
    nd = rows[..., 0, :]
    for vkind, idx, slot in var:
        val = q[..., idx:idx + 1]
        c = val * val if vkind == 0 else jnp.exp(2.0 * LN10 * val)
        nd = nd + c * rows[..., slot, :]
    nv = az * nd
    rmask = rows[..., 1, :]
    nv = rmask * nv + (1.0 - rmask)
    ll = -0.5 * jnp.sum(jnp.log(nv) + yred2 / nv, axis=-1)
    lp = jnp.sum(_lnprior_cols(q, specs[..., 0, :], specs[..., 1, :],
                               specs[..., 2, :]), axis=-1)
    return ll, lp


def white_mh_loop_xla(x, az, yred2, dx, logu, rows, specs, var):
    """The full white MH block as a ``fori_loop`` over precomputed draws —
    the non-Pallas dispatch target. Batch-generic: every operand may carry
    leading batch axes (``dx`` (…, S, p), ``logu`` (…, S)); ``rows``
    (…, R, n) / ``specs`` (…, 3, p) may be per-model constants (rank 2)
    or carry leading GROUP axes matching x's leading batch axes (the
    ensemble's traced per-pulsar constants)."""
    rows = align_consts(jnp.asarray(rows, x.dtype), x.ndim - 1)
    specs = align_consts(jnp.asarray(specs, x.dtype), x.ndim - 1)
    nsteps = dx.shape[-2]
    ll0, lp0 = _ll_lp_xla(x, az, yred2, rows, var, specs)
    acc0 = jnp.zeros(ll0.shape, x.dtype)

    def body(i, carry):
        x, ll0, lp0, acc = carry
        q = x + lax.dynamic_index_in_dim(dx, i, axis=dx.ndim - 2,
                                         keepdims=False)
        ll1, lp1 = _ll_lp_xla(q, az, yred2, rows, var, specs)
        lu = lax.dynamic_index_in_dim(logu, i, axis=logu.ndim - 1,
                                      keepdims=False)
        accept = (ll1 + lp1) - (ll0 + lp0) > lu
        am = accept[..., None]
        return (jnp.where(am, q, x), jnp.where(accept, ll1, ll0),
                jnp.where(accept, lp1, lp0), acc + accept)

    x, _, _, acc = lax.fori_loop(0, nsteps, body, (x, ll0, lp0, acc0))
    return x, acc / nsteps


def white_mtm_loop_xla(x, az, yred2, dx, dxr, gumb, logu, rows, specs,
                       var):
    """The white MH block under multiple-try Metropolis, plain XLA —
    the fused white-MTM kernel's dispatch twin (MHConfig.mtm_tries;
    MTM(II), see backends.jax_backend._mtm_block for the rule). Batch-
    generic: ``dx (…, S, K, p)`` candidate jumps, ``dxr (…, S, K-1, p)``
    reference jumps, ``gumb (…, S, K)`` selection draws, ``logu
    (…, S)``; ``rows``/``specs`` as in :func:`white_mh_loop_xla`."""
    from jax.scipy.special import logsumexp

    rows = align_consts(jnp.asarray(rows, x.dtype), x.ndim - 1)
    specs = align_consts(jnp.asarray(specs, x.dtype), x.ndim - 1)
    # consts get one more singleton axis so they broadcast against the
    # candidate axis K inserted before p
    rows_k = rows[..., None, :, :]
    specs_k = specs[..., None, :, :]
    nsteps = dx.shape[-3]
    ll0, lp0 = _ll_lp_xla(x, az, yred2, rows, var, specs)
    w0 = ll0 + lp0
    acc0 = jnp.zeros(w0.shape, x.dtype)

    def body(i, carry):
        x, wx, acc = carry
        dxi = lax.dynamic_index_in_dim(dx, i, axis=dx.ndim - 3,
                                       keepdims=False)
        cands = x[..., None, :] + dxi                    # (…, K, p)
        llc, lpc = _ll_lp_xla(cands, az[..., None, :],
                              yred2[..., None, :], rows_k, var, specs_k)
        lw = llc + lpc                                   # (…, K)
        gi = lax.dynamic_index_in_dim(gumb, i, axis=gumb.ndim - 2,
                                      keepdims=False)
        j = jnp.argmax(lw + gi, axis=-1)
        y = jnp.take_along_axis(cands, j[..., None, None],
                                axis=-2)[..., 0, :]
        lwy = jnp.take_along_axis(lw, j[..., None], axis=-1)[..., 0]
        dxri = lax.dynamic_index_in_dim(dxr, i, axis=dxr.ndim - 3,
                                        keepdims=False)
        refs = y[..., None, :] + dxri                    # (…, K-1, p)
        llr, lpr = _ll_lp_xla(refs, az[..., None, :],
                              yred2[..., None, :], rows_k, var, specs_k)
        lwr = jnp.concatenate([llr + lpr, wx[..., None]], axis=-1)
        delta = logsumexp(lw, axis=-1) - logsumexp(lwr, axis=-1)
        lu = lax.dynamic_index_in_dim(logu, i, axis=logu.ndim - 1,
                                      keepdims=False)
        # -inf - -inf = NaN (every weight dead on both sides): reject
        accept = jnp.where(jnp.isnan(delta), False, delta > lu)
        am = accept[..., None]
        return (jnp.where(am, y, x), jnp.where(accept, lwy, wx),
                acc + accept)

    x, _, acc = lax.fori_loop(0, nsteps, body, (x, w0, acc0))
    return x, acc / nsteps


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _make_kernel_ll_lp(az, y2, cn_ref, sp_ref, colP, p, var):
    """The in-kernel white conditional likelihood + prior as a closure
    over one tile's loaded operands — ONE copy shared by the single-try
    and MTM kernels, so the rmask/prior/padded-lane contracts cannot
    drift between them. ``cn_ref (1, R, N)`` / ``sp_ref (1, 8, P)``:
    the leading singleton is the GROUP (pulsar) block axis — each grid
    tile reads its own group's constants via the index map (shared
    across the tile's chains). Returns ``ll_lp(q) -> (ll, lp)`` as
    (C, 1) rows."""
    C, N = az.shape
    pmask = colP < p
    kind = jnp.where(pmask, sp_ref[0, 0:1, :], -1.0)
    a = sp_ref[0, 1:2, :]
    b = sp_ref[0, 2:3, :]
    nv0 = cn_ref[0, 0:1, :]
    rmask = cn_ref[0, 1:2, :]

    def ll_lp(q):
        nd = jnp.zeros((C, N), jnp.float32) + nv0
        for vkind, idx, slot in var:
            # lane extraction q[:, idx] as a masked reduction — avoids
            # width-1 lane slicing, which Mosaic handles poorly
            val = jnp.sum(jnp.where(colP == idx, q, 0.0), axis=1,
                          keepdims=True)
            c = val * val if vkind == 0 else jnp.exp(2.0 * LN10 * val)
            nd = nd + c * cn_ref[0, slot:slot + 1, :]
        nv = az * nd
        nv = rmask * nv + (1.0 - rmask)
        ll = -0.5 * jnp.sum(jnp.log(nv) + y2 / nv, axis=1, keepdims=True)
        lp_el = jnp.where(pmask, _lnprior_cols(q, kind, a, b), 0.0)
        lp = jnp.sum(lp_el, axis=1, keepdims=True)
        return ll, lp

    return ll_lp


def _white_kernel(x_ref, az_ref, y2_ref, dx_ref, lu_ref, cn_ref, sp_ref,
                  xo_ref, ao_ref, *, nsteps: int, p: int,
                  var: Tuple[Tuple[int, int, int], ...]):
    C, P = x_ref.shape
    colP = lax.broadcasted_iota(jnp.int32, (1, P), 1)
    colS = lax.broadcasted_iota(jnp.int32, (1, lu_ref.shape[1]), 1)
    az = az_ref[:]
    y2 = y2_ref[:]
    lu_all = lu_ref[:]
    ll_lp = _make_kernel_ll_lp(az, y2, cn_ref, sp_ref, colP, p, var)

    x = x_ref[:]
    ll0, lp0 = ll_lp(x)
    acc = jnp.zeros((C, 1), jnp.float32)
    for j in range(nsteps):
        q = x + dx_ref[j]
        ll1, lp1 = ll_lp(q)
        lu = jnp.sum(jnp.where(colS == j, lu_all, 0.0), axis=1,
                     keepdims=True)
        am = (ll1 + lp1) - (ll0 + lp0) > lu
        x = jnp.where(am, q, x)
        ll0 = jnp.where(am, ll1, ll0)
        lp0 = jnp.where(am, lp1, lp0)
        acc = acc + am.astype(jnp.float32)
    xo_ref[:] = x
    ao_ref[:] = jnp.broadcast_to(acc, ao_ref.shape)


def _white_mtm_kernel(x_ref, az_ref, y2_ref, dx_ref, dxr_ref, gu_ref,
                      lu_ref, cn_ref, sp_ref, xo_ref, ao_ref, *,
                      nsteps: int, K: int, p: int,
                      var: Tuple[Tuple[int, int, int], ...]):
    """Whole white MH block under multiple-try Metropolis, one launch.

    Same layout contract as ``_white_kernel`` (chains on sublanes,
    constants as (1, R, N)/(1, 8, P) group blocks) plus the MTM draw
    arrays: ``dx (S*K, tile, P)`` candidate jumps and ``dxr
    (S*(K-1), tile, P)`` reference jumps on untiled leading axes the
    in-kernel ``fori_loop`` dynamic-indexes, ``gu (tile, SKp)`` Gumbel
    selection draws and ``lu (tile, SP)`` accept draws lane-extracted
    per step. Candidate/reference weight sums run as ONLINE logsumexp
    (max/rescale streaming) so only (tile, 1) accumulators live across
    the K-unrolled inner loops; dead weights (-inf) contribute exactly
    0 and an all-dead step rejects via the NaN > logu = False
    semantics, matching backends.jax_backend._mtm_block."""
    C, P = x_ref.shape
    neg_inf = jnp.float32(-jnp.inf)
    colP = lax.broadcasted_iota(jnp.int32, (1, P), 1)
    colSK = lax.broadcasted_iota(jnp.int32, (1, gu_ref.shape[1]), 1)
    colS = lax.broadcasted_iota(jnp.int32, (1, lu_ref.shape[1]), 1)
    az = az_ref[:]
    y2 = y2_ref[:]
    gu_all = gu_ref[:]
    lu_all = lu_ref[:]
    ll_lp_pair = _make_kernel_ll_lp(az, y2, cn_ref, sp_ref, colP, p, var)

    def ll_lp(q):
        ll, lp = ll_lp_pair(q)
        return ll + lp

    def lse_update(m, s, lw):
        # online logsumexp: fold one (C, 1) log-weight into (m, s)
        m_new = jnp.maximum(m, lw)
        s = (jnp.where(m == neg_inf, 0.0, s * jnp.exp(m - m_new))
             + jnp.where(lw == neg_inf, 0.0, jnp.exp(lw - m_new)))
        return m_new, s

    x0 = x_ref[:]
    wx0 = ll_lp(x0)

    def step(j, carry):
        x, wx, acc = carry
        m = jnp.full((C, 1), neg_inf)
        s = jnp.zeros((C, 1), jnp.float32)
        best_g = jnp.full((C, 1), neg_inf)
        best_lw = jnp.full((C, 1), neg_inf)
        best_q = x
        for k in range(K):
            q = x + dx_ref[j * K + k]
            lw = ll_lp(q)
            m, s = lse_update(m, s, lw)
            g = jnp.sum(jnp.where(colSK == j * K + k, gu_all, 0.0),
                        axis=1, keepdims=True)
            gs = lw + g
            sel = gs > best_g
            best_g = jnp.where(sel, gs, best_g)
            best_lw = jnp.where(sel, lw, best_lw)
            best_q = jnp.where(sel, q, best_q)
        num = m + jnp.log(s)
        # references seeded with the current point's weight
        m2, s2 = wx, jnp.ones((C, 1), jnp.float32)
        for k in range(K - 1):
            r = best_q + dxr_ref[j * (K - 1) + k]
            m2, s2 = lse_update(m2, s2, ll_lp(r))
        den = m2 + jnp.log(s2)
        lu = jnp.sum(jnp.where(colS == j, lu_all, 0.0), axis=1,
                     keepdims=True)
        am = (num - den) > lu                 # NaN/-inf delta rejects
        return (jnp.where(am, best_q, x), jnp.where(am, best_lw, wx),
                acc + am.astype(jnp.float32))

    x, _, acc = lax.fori_loop(
        0, nsteps, step,
        (x0, wx0, jnp.zeros((C, 1), jnp.float32)))
    xo_ref[:] = x
    ao_ref[:] = jnp.broadcast_to(acc, ao_ref.shape)


def _pad_lanes(arr, width):
    pad = width - arr.shape[-1]
    if pad <= 0:
        return arr
    return jnp.concatenate(
        [arr, jnp.zeros(arr.shape[:-1] + (pad,), arr.dtype)], axis=-1)


def _prep_grouped(x, az, yred2, rows, specs, tile):
    """Shared operand prep of the grouped white kernels: chains padded
    per group to a tile multiple (so no chain tile straddles groups)
    then flattened group-major, lanes padded to 128 multiples — with
    padded TOA lanes carrying ``az = 1`` so ``log(nv) = 0`` there (the
    rmask constant row zeroes their reduction terms) — and the constant
    rows/specs padded to their block shapes. Returns the prepared
    operands plus the ``pad_chains``/``flat`` closures so callers pad
    their own draw arrays identically, and the padded dims."""
    G, C, p = x.shape
    n = az.shape[-1]
    P = _round_up(p, 128)
    N = _round_up(n, 128)
    Cp = _round_up(C, tile)

    def pad_chains(arr):
        padn = Cp - arr.shape[1]
        if not padn:
            return arr
        return jnp.concatenate(
            [arr, jnp.broadcast_to(arr[:, :1],
                                   (G, padn) + arr.shape[2:])], axis=1)

    def flat(arr):  # (G, Cp, ...) -> (G*Cp, ...)
        return arr.reshape((G * Cp,) + arr.shape[2:])

    xp_ = flat(pad_chains(_pad_lanes(x, P)))
    azp = flat(pad_chains(_pad_lanes(az, N)))
    if N > n:
        lane = lax.broadcasted_iota(jnp.int32, (1, N), 1)
        azp = jnp.where(lane < n, azp, 1.0)
    y2p = flat(pad_chains(_pad_lanes(yred2, N)))
    rows = _pad_lanes(jnp.asarray(rows, jnp.float32), N)
    R = _round_up(rows.shape[1], 8)
    rows = jnp.concatenate(
        [rows, jnp.zeros((G, R - rows.shape[1], N), jnp.float32)],
        axis=1)
    specs = _pad_lanes(jnp.asarray(specs, jnp.float32), P)
    specs = jnp.concatenate(
        [specs, jnp.zeros((G, 8 - specs.shape[1], P), jnp.float32)],
        axis=1)
    return xp_, azp, y2p, rows, specs, pad_chains, flat, (P, N, R, Cp)


def white_mh_fused(x, az, yred2, dx, logu, rows, specs, var,
                   chain_tile: int | None = None, interpret: bool = False):
    """``(x_new, acc_rate)`` for the whole white MH block, one launch.

    GROUPED form: ``x (G, C, p)``, ``az/yred2 (G, C, n)``,
    ``dx (G, C, S, p)`` precomputed jump vectors — one-hot for the
    reference's single-coordinate kernel, DENSE under
    population-covariance proposals (MHConfig.adapt_cov), so the kernel
    must always evaluate the full ``q = x + dx[j]`` — ``logu (G, C, S)``
    log-uniform accept draws, and PER-GROUP constants ``rows (G, R, n)``
    / ``specs (G, 3, p)`` (the ensemble's traced per-pulsar constants;
    a single frozen model passes G == 1). Chains are padded per group so
    no chain tile straddles two groups, and each tile reads its group's
    constants through the index map. float32 only (the production TPU
    regime; float64 runs take the XLA path).
    """
    if x.dtype != jnp.float32:
        raise ValueError(f"pallas white kernel is float32-only, got {x.dtype}")
    G, C, p = x.shape
    n = az.shape[-1]
    S = dx.shape[-2]
    P = _round_up(p, 128)
    N = _round_up(n, 128)
    SP = _round_up(S, 128)
    # VMEM-budget the chain tile: ~6 (tile, N)-sized live buffers
    # (az, y2, nv, nd + pipelining headroom), cap ~4 MB.
    # GST_WHITE_TILE overrides for on-chip tuning (trace-time snapshot;
    # 256 measured best at the flagship shape, fused_tune_r03.json).
    tile = chain_tile or int_from_env("GST_WHITE_TILE", 256)
    while tile > 8 and 6 * tile * N * 4 > 4 * 2 ** 20:
        tile //= 2
    tile = max(8, min(tile, _round_up(C, 8)))
    xp_, azp, y2p, rows, specs, pad_chains, flat, (P, N, R, Cp) = (
        _prep_grouped(x, az, yred2, rows, specs, tile))
    tpg = Cp // tile  # tiles per group
    # (S, G*Cp, P): step index on the untiled leading axis
    dxp = jnp.moveaxis(flat(pad_chains(_pad_lanes(dx, P))), 1, 0)
    lup = flat(pad_chains(_pad_lanes(logu, SP)))

    # chain tiles are independent
    kwargs = tpu_compiler_params(("parallel",))
    kernel = functools.partial(_white_kernel, nsteps=S, p=p, var=var)
    xo, ao = pl.pallas_call(
        kernel,
        grid=(G * tpg,),
        in_specs=[
            _spec((tile, P), lambda g: (g, 0)),
            _spec((tile, N), lambda g: (g, 0)),
            _spec((tile, N), lambda g: (g, 0)),
            _spec((S, tile, P), lambda g: (0, g, 0)),
            _spec((tile, SP), lambda g: (g, 0)),
            _spec((1, R, N), lambda g: (g // tpg, 0, 0)),
            _spec((1, 8, P), lambda g: (g // tpg, 0, 0)),
        ],
        out_specs=[
            _spec((tile, P), lambda g: (g, 0)),
            _spec((tile, 8), lambda g: (g, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G * Cp, P), jnp.float32),
            jax.ShapeDtypeStruct((G * Cp, 8), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(xp_, azp, y2p, dxp, lup, rows, specs)
    xo = xo.reshape(G, Cp, P)[:, :C, :p]
    ao = ao.reshape(G, Cp, 8)[:, :C, 0] / S
    return xo, ao


def white_mtm_fused(x, az, yred2, dx, dxr, gumb, logu, rows, specs, var,
                    chain_tile: int | None = None,
                    interpret: bool = False):
    """``(x_new, acc_rate)`` for the white MTM block, one launch.

    GROUPED form like :func:`white_mh_fused`: ``x (G, C, p)``,
    ``az/yred2 (G, C, n)``, ``dx (G, C, S, K, p)``, ``dxr
    (G, C, S, K-1, p)``, ``gumb (G, C, S, K)``, ``logu (G, C, S)``,
    ``rows (G, R, n)``, ``specs (G, 3, p)``. float32 only.
    """
    if x.dtype != jnp.float32:
        raise ValueError(f"pallas white kernel is float32-only, got {x.dtype}")
    G, C, p = x.shape
    n = az.shape[-1]
    S, K = dx.shape[-3], dx.shape[-2]
    P = _round_up(p, 128)
    N = _round_up(n, 128)
    SK = _round_up(S * K, 128)
    SP = _round_up(S, 128)
    # VMEM budget: the (tile, N) likelihood buffers PLUS the per-tile
    # draw blocks ((2K-1)*S, tile, P) that the fori_loop dynamic-
    # indexes, cap ~4 MB (same ceiling as the single-try kernel).
    tile = chain_tile or int_from_env("GST_WHITE_TILE", 256)
    per_chain = (6 * N + (2 * K - 1) * S * P + SK + SP) * 4
    while tile > 8 and tile * per_chain > 4 * 2 ** 20:
        tile //= 2
    tile = max(8, min(tile, _round_up(C, 8)))
    xp_, azp, y2p, rows, specs, pad_chains, flat, (P, N, R, Cp) = (
        _prep_grouped(x, az, yred2, rows, specs, tile))
    tpg = Cp // tile
    # (S*K, G*Cp, P) / (S*(K-1), G*Cp, P): step-major untiled leading
    # axes for the in-kernel dynamic indexing
    dxp = jnp.moveaxis(
        flat(pad_chains(_pad_lanes(dx, P))).reshape(
            G * Cp, S * K, P), 1, 0)
    dxrp = jnp.moveaxis(
        flat(pad_chains(_pad_lanes(dxr, P))).reshape(
            G * Cp, S * (K - 1), P), 1, 0)
    gup = flat(pad_chains(_pad_lanes(
        gumb.reshape(G, C, S * K), SK)))
    lup = flat(pad_chains(_pad_lanes(logu, SP)))

    kwargs = tpu_compiler_params(("parallel",))
    kernel = functools.partial(_white_mtm_kernel, nsteps=S, K=K, p=p,
                               var=var)
    xo, ao = pl.pallas_call(
        kernel,
        grid=(G * tpg,),
        in_specs=[
            _spec((tile, P), lambda g: (g, 0)),
            _spec((tile, N), lambda g: (g, 0)),
            _spec((tile, N), lambda g: (g, 0)),
            _spec((S * K, tile, P), lambda g: (0, g, 0)),
            _spec((S * (K - 1), tile, P), lambda g: (0, g, 0)),
            _spec((tile, SK), lambda g: (g, 0)),
            _spec((tile, SP), lambda g: (g, 0)),
            _spec((1, R, N), lambda g: (g // tpg, 0, 0)),
            _spec((1, 8, P), lambda g: (g // tpg, 0, 0)),
        ],
        out_specs=[
            _spec((tile, P), lambda g: (g, 0)),
            _spec((tile, 8), lambda g: (g, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G * Cp, P), jnp.float32),
            jax.ShapeDtypeStruct((G * Cp, 8), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(xp_, azp, y2p, dxp, dxrp, gup, lup, rows, specs)
    xo = xo.reshape(G, Cp, P)[:, :C, :p]
    ao = ao.reshape(G, Cp, 8)[:, :C, 0] / S
    return xo, ao


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def _pallas_white_mode():
    """``(enabled, interpret, forced)`` from ``GST_PALLAS_WHITE`` — the
    shared trace-time-snapshot semantics of ops/pallas_util.py
    ``mode_from_env``: ``auto`` enables on TPU backends for batches past
    ``MIN_BATCH``; set the env var *before* constructing the backend."""
    return mode_from_env("GST_PALLAS_WHITE")


def consts_batch_vmap(block, n_data: int):
    """``custom_vmap`` rule for fused-MH dispatchers whose trailing
    operands are per-MODEL constants (``args[n_data:]``).

    Two batching levels arise in practice (backends/jax_backend.py
    ``_sweep`` under the ensemble's vmaps): the CHAIN axis maps only the
    per-chain data operands — the constants stay unbatched so the block
    keeps one shared copy — and the PULSAR axis maps constants and data
    alike, giving the constants a leading group axis the grouped kernel
    (and the align_consts XLA path) consume directly."""
    import jax.numpy as jnp

    def rule(axis_size, in_batched, *args):
        const_batched = any(in_batched[n_data:])

        def bcast(arr, bt):
            return arr if bt else jnp.broadcast_to(
                arr, (axis_size,) + arr.shape)

        if not const_batched:
            # chain-level: broadcast unbatched data, constants untouched
            out = [bcast(a, b) for a, b in zip(args[:n_data],
                                               in_batched[:n_data])]
            return block(*out, *args[n_data:]), (True, True)
        # group-level: every operand gains the mapped axis
        out = [bcast(a, b) for a, b in zip(args, in_batched)]
        return block(*out), (True, True)

    return rule


def make_white_block(var: Tuple[Tuple[int, int, int], ...]):
    """Build the dispatched white-MH block for one model STRUCTURE.

    Only the static structure (``WhiteConsts.var``: which parameters
    vary and how) is closed over; the constant arrays travel as call
    operands, so ensembles can pass traced per-pulsar ``rows``/``specs``
    (stacked along a leading group axis) through ``vmap``/``shard_map``.

    Returns ``block(x, az, yred2, dx, logu, rows, specs) ->
    (x_new, acc_rate)`` wrapped in ``jax.custom_batching.custom_vmap``:
    a chain-vmapped call collapses every mapped axis onto the kernel's
    chain-tile dimension (the same integration pattern as
    ops/linalg.py's ``_factor_fused``), a pulsar-vmapped call routes the
    per-group constants to the grouped kernel; unbatched or non-TPU
    calls run the identical-math XLA loop.
    """
    note_kernel_build("pallas_white_mh", n_varying=len(var),
                      mode=mode_from_env("GST_PALLAS_WHITE")[0])

    @custom_vmap
    def block(x, az, yred2, dx, logu, rows, specs):
        enabled, interp, forced = _pallas_white_mode()
        grouped = rows.ndim == 3
        if grouped:
            batch = x.shape[:-1]
            B = int(np.prod(batch)) if batch else 1
            ok = (_HAVE_PLTPU and x.dtype == jnp.float32
                  and az.shape[-1] <= MAX_PALLAS_N
                  and (forced or B >= _MIN_BATCH)
                  and x.ndim == 3 and rows.shape[0] == x.shape[0])
            if enabled and ok:
                return white_mh_fused(x, az, yred2, dx, logu, rows,
                                      specs, var, interpret=interp)
        elif rows.ndim == 2:
            batch = x.shape[:-1]
            B = int(np.prod(batch)) if batch else 1
            ok = (_HAVE_PLTPU and x.dtype == jnp.float32
                  and az.shape[-1] <= MAX_PALLAS_N
                  and (forced or B >= _MIN_BATCH) and x.ndim >= 2)
            if enabled and ok:
                p = x.shape[-1]
                n = az.shape[-1]
                S = dx.shape[-2]
                xf, acc = white_mh_fused(
                    x.reshape(1, B, p), az.reshape(1, B, n),
                    yred2.reshape(1, B, n), dx.reshape(1, B, S, p),
                    logu.reshape(1, B, S), rows[None], specs[None],
                    var, interpret=interp)
                return xf.reshape(batch + (p,)), acc.reshape(batch)
        if rows.ndim == 2 and x.ndim >= 2:
            # native CPU arm (GST_NWHITE): the whole block as one FFI
            # custom call — the Pallas kernel's portable counterpart,
            # same operands/randomness, XLA loop below as the oracle
            from gibbs_student_t_tpu.ops import linalg as _lin

            if _lin.nwhite_take(x.shape, x.dtype, x.shape[-1],
                                len(var)):
                from gibbs_student_t_tpu.native import ffi as nffi

                _lin._note_impl("white_mh", "nchol", x.shape)
                B = int(np.prod(x.shape[:-1]))
                p = x.shape[-1]
                n = az.shape[-1]
                S = dx.shape[-2]
                xf, acc = nffi.white_mh(
                    x.reshape(B, p), az.reshape(B, n),
                    yred2.reshape(B, n), dx.reshape(B, S, p),
                    logu.reshape(B, S), jnp.asarray(rows, x.dtype),
                    jnp.asarray(specs, x.dtype), var)
                return (xf.reshape(x.shape),
                        acc.reshape(x.shape[:-1]))
        return white_mh_loop_xla(x, az, yred2, dx, logu, rows, specs,
                                 var)

    block.def_vmap(consts_batch_vmap(block, n_data=5))
    return block


def make_white_block_lanes(var: Tuple[Tuple[int, int, int], ...]):
    """Per-lane-consts twin of :func:`make_white_block` — the serve
    slot pool's white MH block, where every lane carries its OWN
    tenant's constant rows / prior specs as call-time operands plus the
    tile-uniform group id (serve/pool.py; the last lanes-path MH stage
    that still ran on the grouped XLA loop under serving). The native
    arm (``GST_NWHITE``, native/ffi.py ``white_mh_lanes``) shares the
    solo kernel's tile loop, so a pool whose lanes share one model is
    bitwise the solo kernel; the fallback is the grouped
    :func:`white_mh_loop_xla` graph the traced-consts path always
    emitted, so gates-off (or degraded) serving keeps that graph
    verbatim. Returns ``block(x, az, yred2, dx, logu, rows, specs,
    gid) -> (x_new, acc_rate)``."""
    note_kernel_build("white_mh_lanes", n_varying=len(var))

    @custom_vmap
    def block(x, az, yred2, dx, logu, rows, specs, gid):
        from gibbs_student_t_tpu.ops import linalg as _lin

        if (rows.ndim == 3 and gid.ndim == 1 and x.ndim == 2
                and rows.shape[0] == x.shape[0]
                and _lin.nwhite_take(x.shape, x.dtype, x.shape[-1],
                                     len(var))):
            from gibbs_student_t_tpu.native import ffi as nffi

            _lin._note_impl("white_lanes", "nchol", x.shape)
            return nffi.white_mh_lanes(
                x, az, yred2, dx, logu, jnp.asarray(rows, x.dtype),
                jnp.asarray(specs, x.dtype), gid, var)
        enabled, interp, forced = _pallas_white_mode()
        B = x.shape[0] if x.ndim else 0
        if (enabled and _HAVE_PLTPU and rows.ndim == 3
                and gid.ndim == 1 and x.ndim == 2
                and rows.shape[0] == x.shape[0]
                and x.dtype == jnp.float32
                and az.shape[-1] <= MAX_PALLAS_N
                and B % LANES_GROUP == 0 and B
                and (forced or B >= _MIN_BATCH)):
            # tile-uniform gid contract: consts are constant within
            # every aligned 16-lane tile, so one stride-sliced row per
            # group is the whole consts plane and the lane batch
            # group-reduces through the grouped kernel (chains = the
            # 16 lanes of each admission group)
            _lin._note_impl("white_lanes", "pallas", x.shape)
            G = B // LANES_GROUP
            p = x.shape[-1]
            n = az.shape[-1]
            S = dx.shape[-2]
            xf, acc = white_mh_fused(
                x.reshape(G, LANES_GROUP, p),
                az.reshape(G, LANES_GROUP, n),
                yred2.reshape(G, LANES_GROUP, n),
                dx.reshape(G, LANES_GROUP, S, p),
                logu.reshape(G, LANES_GROUP, S),
                jnp.asarray(rows, x.dtype)[::LANES_GROUP],
                jnp.asarray(specs, x.dtype)[::LANES_GROUP],
                var, interpret=interp)
            return xf.reshape(B, p), acc.reshape(B)
        _lin._note_impl("white_lanes", "loop_xla", x.shape)
        return white_mh_loop_xla(x, az, yred2, dx, logu, rows, specs,
                                 var)

    @block.def_vmap
    def _block_vmap(axis_size, in_batched, *args):
        # the serve vmap maps EVERY operand (state, draws, per-lane
        # consts and gid alike); broadcast stragglers and re-enter so
        # the primal sees the full lane batch (the
        # _fused_hyper_lanes_dispatcher discipline)
        out = tuple(
            a if bt else jnp.broadcast_to(a, (axis_size,) + a.shape)
            for a, bt in zip(args, in_batched))
        return block(*out), (True, True)

    return block


def make_white_mtm_block(var: Tuple[Tuple[int, int, int], ...]):
    """Build the dispatched white-MTM block for one model STRUCTURE —
    ``block(x, az, yred2, dx, dxr, gumb, logu, rows, specs) ->
    (x_new, acc_rate)``, the multiple-try twin of
    :func:`make_white_block` (same custom_vmap constants batching,
    same ``GST_PALLAS_WHITE`` gate, XLA fallback
    :func:`white_mtm_loop_xla`)."""
    note_kernel_build("pallas_white_mtm", n_varying=len(var),
                      mode=mode_from_env("GST_PALLAS_WHITE")[0])

    @custom_vmap
    def block(x, az, yred2, dx, dxr, gumb, logu, rows, specs):
        enabled, interp, forced = _pallas_white_mode()
        grouped = rows.ndim == 3
        batch = x.shape[:-1]
        B = int(np.prod(batch)) if batch else 1
        base_ok = (_HAVE_PLTPU and x.dtype == jnp.float32
                   and az.shape[-1] <= MAX_PALLAS_N
                   and (forced or B >= _MIN_BATCH))
        if grouped:
            if (enabled and base_ok and x.ndim == 3
                    and rows.shape[0] == x.shape[0]):
                return white_mtm_fused(x, az, yred2, dx, dxr, gumb,
                                       logu, rows, specs, var,
                                       interpret=interp)
        elif rows.ndim == 2:
            if enabled and base_ok and x.ndim >= 2:
                p = x.shape[-1]
                n = az.shape[-1]
                S, K = dx.shape[-3], dx.shape[-2]
                xf, acc = white_mtm_fused(
                    x.reshape(1, B, p), az.reshape(1, B, n),
                    yred2.reshape(1, B, n), dx.reshape(1, B, S, K, p),
                    dxr.reshape(1, B, S, K - 1, p),
                    gumb.reshape(1, B, S, K), logu.reshape(1, B, S),
                    rows[None], specs[None], var, interpret=interp)
                return xf.reshape(batch + (p,)), acc.reshape(batch)
        return white_mtm_loop_xla(x, az, yred2, dx, dxr, gumb, logu,
                                  rows, specs, var)

    block.def_vmap(consts_batch_vmap(block, n_data=7))
    return block
