"""Portable vectorized batched Cholesky + unrolled triangular solves.

The graded bench has run on the CPU platform for five consecutive
rounds (BENCH_r01-r05), and there `hyper_and_draws` was 682 ms of a
~750 ms sweep — 92% — because the fused MH kernels are TPU-only and the
closure path's factorizations/solves took whatever XLA:CPU emits. This
module is the portable (any non-TPU backend, pure ``jnp``) counterpart
of the Pallas lane-batched kernel (ops/pallas_chol.py), built from a
measured decomposition of where that 682 ms actually went
(``tools/cpu_microbench.py``, artifacts/cpu_microbench_r06.json):

- ``jnp.linalg.cholesky`` on XLA:CPU is **not** a sequential expander:
  it lowers to one batched LAPACK ``?potrf`` FFI call (~28 ms for a
  (1024, 74, 74) f32 batch) — already near-optimal, not worth
  replacing. A trace-time fully-unrolled factorization (the
  ops/unrolled_chol.py recurrence) measures 200 ms on the same batch,
  and the Pallas kernel's chains-last ``(col, row, chain)`` layout is
  actively hostile to XLA:CPU, whose batched matmul wants batch
  leading (a chains-last panel GEMM measures 14x slower than the
  identical batch-first contraction). The lane-batching insight does
  NOT transfer; what transfers is the *fused-solve + fixed-shape*
  discipline below.
- ``triangular_solve`` IS a sequential expander on CPU — a While loop
  over columns with dynamic slices, ~100 ms per batched forward solve
  (~4x the factorization it follows). That is the portable hot spot.

So the portable path keeps the batched LAPACK factorization and
replaces every triangular-solve expander with a **trace-time
panel-unrolled substitution** in the batch-leading layout: ``m`` is a
static model constant, each panel's cross-panel correction is one
batched GEMM (or broadcast-multiply-sum for vector rhs), and the
in-panel recurrence is ~``m`` fixed-shape vector ops over the whole
chain batch. Measured on the flagship batch: forward solve 100 ms ->
~4 ms, backward solve 67 ms -> 12 ms; factor+logdet+forward-solve
fused 135 ms -> 32 ms (the ops/unrolled_chol.py shape rules: no
growing concats, ~10 distinct op shapes).

Failure semantics are branchless and identical to every other path:
a non-PD input makes ``jnp.linalg.cholesky`` return NaN, which the
solves and ``logdet`` propagate — callers map non-finite to ``-inf``
log-likelihood / MH rejection (ops/linalg.py).

Gated by ``GST_VCHOL=auto|1|0`` in ops/linalg.py (auto: on for
non-TPU backends, off on TPU — the sweep there runs the Pallas kernel,
and the in-sweep A/B showed long unrolled programs schedule badly in
the TPU sweep, artifacts/tpu_validation_r02.json).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

# Above this the unrolled solve program stops paying for itself (HLO
# count grows linearly with m) — same bound as ops/unrolled_chol.py.
MAX_VCHOL_DIM = 160

#: Panel width of the unrolled substitutions. 16 keeps one panel's
#: working set (panel x m x chains) inside L2 at the flagship shape and
#: the program at ~m/16 GEMMs + m small vector ops.
PANEL = 16


def _offsets(m: int, panel: int):
    """Static panel offsets; the tail panel is simply narrower (no
    padding — a second trailing shape is still a fixed shape)."""
    return [(o, min(panel, m - o)) for o in range(0, m, panel)]


def fwd_solve_vec(L, rhs, panel: int = PANEL):
    """``L x = rhs`` by panel-unrolled forward substitution.

    ``L (..., m, m)`` lower-triangular, ``rhs (..., m)``. Each panel
    subtracts the contribution of every already-solved entry with one
    broadcast-multiply-sum over the full row block (entries of ``x``
    beyond the solved prefix are still zero, so the full-width
    contraction is the partial sum the recurrence needs), then runs the
    in-panel recurrence on ``(..., p)`` slices.
    """
    m = L.shape[-1]
    x = jnp.zeros_like(rhs)
    for o, p in _offsets(m, panel):
        rp = rhs[..., o:o + p] - jnp.sum(
            L[..., o:o + p, :] * x[..., None, :], axis=-1)
        Bd = L[..., o:o + p, o:o + p]
        xp = jnp.zeros_like(rp)
        for i in range(p):
            ci = jnp.sum(Bd[..., i, :] * xp, axis=-1)
            xp = xp.at[..., i].set((rp[..., i] - ci) / Bd[..., i, i])
        x = x.at[..., o:o + p].set(xp)
    return x


def bwd_solve_vec(L, rhs, panel: int = PANEL):
    """``L^T x = rhs`` by panel-unrolled backward substitution, same
    fixed-shape discipline as :func:`fwd_solve_vec` (descending panels;
    unsolved entries are zero so full-column contractions are safe)."""
    m = L.shape[-1]
    x = jnp.zeros_like(rhs)
    for o, p in reversed(_offsets(m, panel)):
        rp = rhs[..., o:o + p] - jnp.sum(
            L[..., :, o:o + p] * x[..., :, None], axis=-2)
        Bd = L[..., o:o + p, o:o + p]
        xp = jnp.zeros_like(rp)
        for i in range(p - 1, -1, -1):
            ci = jnp.sum(Bd[..., :, i] * xp, axis=-1)
            xp = xp.at[..., i].set((rp[..., i] - ci) / Bd[..., i, i])
        x = x.at[..., o:o + p].set(xp)
    return x


def fwd_solve_mat(L, R, panel: int = PANEL):
    """``L X = R`` for a matrix right-hand side ``R (..., m, k)``.

    The cross-panel correction is a batch-leading batched GEMM (the
    layout XLA:CPU's dot_general is fast in — see the module header);
    the in-panel recurrence works on ``(..., p, k)`` slices.
    """
    m = L.shape[-1]
    X = jnp.zeros_like(R)
    for o, p in _offsets(m, panel):
        rp = R[..., o:o + p, :] - jnp.einsum(
            "...bj,...jk->...bk", L[..., o:o + p, :], X)
        Bd = L[..., o:o + p, o:o + p]
        xp = jnp.zeros_like(rp)
        for i in range(p):
            ci = jnp.sum(Bd[..., i, :, None] * xp, axis=-2)
            xp = xp.at[..., i, :].set(
                (rp[..., i, :] - ci) / Bd[..., i, i, None])
        X = X.at[..., o:o + p, :].set(xp)
    return X


def bwd_solve_mat(L, R, panel: int = PANEL):
    """``L^T X = R`` for a matrix right-hand side ``R (..., m, k)``."""
    m = L.shape[-1]
    X = jnp.zeros_like(R)
    for o, p in reversed(_offsets(m, panel)):
        rp = R[..., o:o + p, :] - jnp.einsum(
            "...jb,...jk->...bk", L[..., :, o:o + p], X)
        Bd = L[..., o:o + p, o:o + p]
        xp = jnp.zeros_like(rp)
        for i in range(p - 1, -1, -1):
            ci = jnp.sum(Bd[..., :, i, None] * xp, axis=-2)
            xp = xp.at[..., i, :].set(
                (rp[..., i, :] - ci) / Bd[..., i, i, None])
        X = X.at[..., o:o + p, :].set(xp)
    return X


def vchol_factor(S, rhs=None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray,
                            Optional[jnp.ndarray]]:
    """``(L, logdet S, L^-1 rhs | None)`` — the portable fused
    factorization: one batched LAPACK/XLA ``cholesky`` plus the
    unrolled forward substitution, no triangular-solve expander.

    Works at any dtype (the f64 parity-pin path runs it too); NaN from
    a non-PD input propagates through ``logdet`` and the solve.
    """
    L = jnp.linalg.cholesky(S)
    logdet = 2.0 * jnp.sum(
        jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)
    u = None if rhs is None else fwd_solve_vec(L, rhs)
    return L, logdet, u
