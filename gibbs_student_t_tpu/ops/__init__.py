"""TPU numerics: preconditioned factorizations and distribution draws.

The reference leans on LAPACK (``scipy.linalg`` svd/qr/cho_factor,
reference gibbs.py:169-178,321-322) with try/except fallbacks. On TPU the
equivalents must be branchless and batched; this package provides them.
"""

from gibbs_student_t_tpu.ops.linalg import (
    gaussian_draw,
    precond_cholesky,
    precond_solve_quad,
)

__all__ = ["precond_cholesky", "precond_solve_quad", "gaussian_draw"]
