"""TPU numerics: preconditioned factorizations and distribution draws.

The reference leans on LAPACK (``scipy.linalg`` svd/qr/cho_factor,
reference gibbs.py:169-178,321-322) with try/except fallbacks. On TPU the
equivalents must be branchless and batched; this package provides them.
"""

from gibbs_student_t_tpu.ops.linalg import (
    gaussian_draw,
    precond_cholesky,
    precond_quad_logdet,
    precond_solve_quad,
    robust_precond_cholesky,
)

__all__ = ["precond_cholesky", "precond_quad_logdet", "precond_solve_quad",
           "robust_precond_cholesky", "gaussian_draw"]
