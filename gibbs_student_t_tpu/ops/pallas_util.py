"""Shared plumbing for the Pallas TPU kernels (ops/pallas_*.py).

One copy of the env-flag parser, padding arithmetic, and block-spec
helper, so the per-kernel gates (``GST_PALLAS_CHOL``,
``GST_PALLAS_WHITE``, ``GST_PALLAS_HYPER``) cannot drift apart in
semantics: every flag supports ``auto`` (on for TPU backends),
``0``/``false``/empty (off), ``interpret`` (forced, interpreter mode —
the CPU testing path), and anything-else-truthy (forced on).

All flags are read at TRACE time and baked into the compiled program —
set them before constructing a backend; flipping them afterwards
silently has no effect on an existing instance (the bench fallback
ladder uses a fresh process per rung for exactly this reason).
"""

from __future__ import annotations

import os

import jax
from jax.experimental import pallas as pl

try:  # pltpu only imports on builds with the TPU extension available
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    HAVE_PLTPU = False

# Below this flattened batch size a kernel's relayout/launch overhead
# outweighs its win and the XLA path is kept.
MIN_BATCH = 16

# The serving slot pool admits tenants in 16-lane groups and the
# tile-uniform gid contract guarantees per-lane consts are constant
# within every aligned 16-lane tile — the ``*_lanes`` Pallas twins
# group-reduce on this width (stride-slicing one consts row per tile).
# Kept here (not imported from serve/) so ops/ never depends on serve/.
LANES_GROUP = 16


def round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def vmem_spec(shape, index_map) -> pl.BlockSpec:
    if HAVE_PLTPU:
        return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)
    return pl.BlockSpec(shape, index_map)


def mode_from_env(var: str):
    """``(enabled, interpret, forced)`` for one kernel gate env var —
    since round 18 a thin delegate to the dispatch registry's shared
    resolution (ops/registry.py ``pallas_mode``: same vocabulary, plus
    provenance recording)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.pallas_mode(var)


def int_from_env(var: str, default: int, mult: int = 8) -> int:
    """Tuning integer from the environment: ``default`` when unset,
    empty, or non-numeric (the same forgiving contract as the GST_*
    mode flags), rounded up to a legal ``mult``-multiple. Registry-
    backed (ops/registry.py ``int_value``)."""
    from gibbs_student_t_tpu.ops import registry

    return registry.int_value(var, default, mult)


def tpu_compiler_params(dimension_semantics) -> dict:
    """``{"compiler_params": ...}`` for a ``pl.pallas_call``, or ``{}``
    when the TPU extension is absent. The class moved names across jax
    releases (``TPUCompilerParams`` → ``CompilerParams``) — resolve
    whichever the installed build exports, same version-tolerance
    contract as ``parallel/compat.shard_map``."""
    if not HAVE_PLTPU:
        return {}
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None)
    if cls is None:  # pragma: no cover - unexpected pltpu surface
        return {}
    return {"compiler_params": cls(
        dimension_semantics=tuple(dimension_semantics))}


def note_kernel_build(name: str, **meta):
    """Log a Pallas kernel construction/trace in the introspection
    registry (obs/introspect.py), so run manifests and the durable
    ledger can say WHICH custom kernels a compiled program contained
    (and with what static shape parameters). Build-time call sites fire
    once per backend construction; trace-time call sites once per XLA
    compile — the registry deduplicates by content either way. Must
    never raise: observability cannot take down a kernel build."""
    try:
        from gibbs_student_t_tpu.obs.introspect import register_kernel

        register_kernel(name, **meta)
    except Exception:  # noqa: BLE001
        pass


def pad_chains_edge(arr, to: int):
    """Pad the leading (chain) axis to ``to`` rows by edge-replication,
    so padded rows stay finite and in-bounds for any downstream math."""
    import jax.numpy as jnp

    padn = to - arr.shape[0]
    if not padn:
        return arr
    return jnp.concatenate(
        [arr, jnp.broadcast_to(arr[:1], (padn,) + arr.shape[1:])],
        axis=0)


