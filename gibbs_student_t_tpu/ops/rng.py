"""Counter-based Philox-4x32-10 in pure jnp — the native RNG's twin.

The round-9 draw kernels (``native/src/gst_kernels.h``: fast-gamma v2,
fractional beta) generate their randomness IN-kernel from a
Philox-4x32-10 stream, so no uniform pool ever crosses the FFI
boundary. This module is the stream's jnp twin: the same key/counter
layout, the same 10-round bump-per-round schedule, and the same exact
bits->uniform map — uniforms agree BITWISE between the two arms
(pinned in tests/test_nchol.py), and downstream values agree to the
libm-vs-XLA transcendental ulp level.

Everything is plain uint32 arithmetic (wrap-around semantics), so it
runs without ``jax_enable_x64``: the 32x32 -> 64 multiply goes through
16-bit limbs.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

PHILOX_M0 = np.uint32(0xD2511F53)
PHILOX_M1 = np.uint32(0xCD9E8D57)
PHILOX_W0 = np.uint32(0x9E3779B9)
PHILOX_W1 = np.uint32(0xBB67AE85)

#: ctr2 domain tags (native kernels use the same constants so a reused
#: key can never collide across kernels)
TAG_GAMMA = np.uint32(0x67616D00)
TAG_BETA_A = np.uint32(0x62657400)
TAG_BETA_B = np.uint32(0x62657401)

_LOW16 = np.uint32(0xFFFF)


def _mulhilo(a, m):
    """(hi, lo) words of the 32x32 product via 16-bit limbs — exact
    with uint32 wrap-around arithmetic only (no x64 requirement)."""
    a = jnp.asarray(a, jnp.uint32)
    al = a & _LOW16
    ah = a >> 16
    ml = np.uint32(int(m) & 0xFFFF)
    mh = np.uint32(int(m) >> 16)
    ll = al * ml
    lh = al * mh
    hl = ah * ml
    hh = ah * mh
    mid = (ll >> 16) + (lh & _LOW16) + (hl & _LOW16)
    lo = (ll & _LOW16) | (mid << 16)
    hi = hh + (lh >> 16) + (hl >> 16) + (mid >> 16)
    return hi, lo


def philox_4x32(k0, k1, c0, c1, c2, c3):
    """One Philox-4x32-10 block per counter element; key words are
    scalars (or broadcastable arrays), counters arbitrary-shaped uint32
    arrays. Returns the four output words."""
    k0 = jnp.asarray(k0, jnp.uint32)
    k1 = jnp.asarray(k1, jnp.uint32)
    c0 = jnp.asarray(c0, jnp.uint32)
    c1 = jnp.asarray(c1, jnp.uint32)
    c2 = jnp.asarray(c2, jnp.uint32)
    c3 = jnp.asarray(c3, jnp.uint32)
    for _ in range(10):
        hi0, lo0 = _mulhilo(c0, PHILOX_M0)
        hi1, lo1 = _mulhilo(c2, PHILOX_M1)
        c0, c1, c2, c3 = hi1 ^ c1 ^ k0, lo1, hi0 ^ c3 ^ k1, lo0
        k0 = k0 + PHILOX_W0
        k1 = k1 + PHILOX_W1
    return c0, c1, c2, c3


def uniform_of_bits(bits, dtype):
    """Exact bits -> (0, 1) map shared with the kernels:
    ``(bits >> 9) * 2^-23 + 2^-24`` — every step representable, so the
    two arms' uniforms are bitwise equal (23 bits of entropy)."""
    b = (jnp.asarray(bits, jnp.uint32) >> 9).astype(dtype)
    return b * dtype(2.0 ** -23) + dtype(2.0 ** -24)


def key_bits(key):
    """The raw uint32 key words of a jax PRNG key (old-style uint32
    arrays pass through; typed keys unwrap via ``random.key_data``)."""
    import jax

    arr = jnp.asarray(key)
    if jnp.issubdtype(arr.dtype, jnp.integer):
        return arr.astype(jnp.uint32)
    return jax.random.key_data(key).astype(jnp.uint32)


def philox_uniform_pool(key2, rows: int, width: int, tag, dtype):
    """(rows, width) uniforms for ONE chain: uniform ``i`` of row ``r``
    is word ``i % 4`` of block (ctr0 = r, ctr1 = i // 4, ctr2 = tag)
    under the chain's key — the exact layout the native kernels
    consume. ``key2`` is the (2,) uint32 key-word array."""
    nblk = (width + 3) // 4
    c0 = jnp.broadcast_to(
        jnp.arange(rows, dtype=jnp.uint32)[:, None], (rows, nblk))
    c1 = jnp.broadcast_to(
        jnp.arange(nblk, dtype=jnp.uint32)[None, :], (rows, nblk))
    w = philox_4x32(key2[0], key2[1], c0, c1,
                    jnp.full((rows, nblk), tag, jnp.uint32),
                    jnp.zeros((rows, nblk), jnp.uint32))
    bits = jnp.stack(w, axis=-1).reshape(rows, nblk * 4)[:, :width]
    return uniform_of_bits(bits, dtype)


def gamma_halfint_v2(key2, counts, jmax: int):
    """``Gamma(k/2)`` for integer ``k = counts`` (float-encoded), the
    GST_FAST_GAMMA v2 construction — jnp twin of the native
    ``gamma_v2_batch`` kernel (same philox streams, chunked-product
    log instead of the kernel's full double product, Box-Muller
    odd-parity plane). One chain: ``counts (n,)`` -> draws ``(n,)``."""
    dtype = counts.dtype.type
    n = counts.shape[-1]
    u = philox_uniform_pool(key2, n, jmax + 2, TAG_GAMMA, dtype)
    k = jnp.floor(counts + counts.dtype.type(0.5)).astype(jnp.int32)
    k = jnp.maximum(k, 0)
    j = jnp.minimum(k >> 1, jmax)
    odd = (k & 1).astype(counts.dtype)
    live = jnp.arange(jmax, dtype=jnp.int32)[None, :] < j[:, None]
    up = jnp.where(live, u[:, :jmax], dtype(1.0))
    # chunked product before each log: 4 uniforms (each >= 2^-24)
    # cannot underflow f32; 8 cannot underflow f64 — the chol_tile
    # chunked-product discipline (the kernel accumulates the whole
    # product in a double and pays ONE log; values agree to ~1e-7)
    chunk = 4 if counts.dtype == jnp.float32 else 8
    pad = (-jmax) % chunk
    if pad:
        up = jnp.concatenate(
            [up, jnp.ones(up.shape[:-1] + (pad,), counts.dtype)],
            axis=-1)
    pc = jnp.prod(up.reshape(up.shape[:-1] + (-1, chunk)), axis=-1)
    g = -jnp.sum(jnp.log(pc), axis=-1)
    nrm = jnp.sqrt(dtype(-2.0) * jnp.log(u[:, jmax])) * jnp.cos(
        dtype(2.0 * np.pi) * u[:, jmax + 1])
    return g + odd * counts.dtype.type(0.5) * nrm * nrm
