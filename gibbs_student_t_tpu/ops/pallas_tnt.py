"""Pallas TPU kernel: batched fused ``T^T N^-1 T`` / ``T^T N^-1 y``.

The one op worth a hand kernel in this framework (SURVEY.md §3.1: the
``O(n m^2)`` TNT build dominates each sweep once n is large). The XLA
path (ops/tnt.py) scans TOA blocks per chain, which under ``vmap``
materializes a ``(chains, block, m)`` weighted-basis intermediate in HBM
every step. This kernel instead:

- tiles chains (``chain_tile`` per grid step) and keeps each tile's
  ``(chain_tile, mp, mp)`` accumulator resident in VMEM across the whole
  TOA sweep (grid = (chain_tiles, toa_blocks), TOA innermost, so output
  blocks get consecutive visits and are written back exactly once);
- reads the shared basis block once per chain tile and applies every
  chain's weights to it in registers — the weighted basis never exists
  in HBM;
- fuses the ``d`` matvec into the same pass over ``T``.

``m`` is zero-padded to a 128-lane multiple (the MXU pads internally
anyway); padded columns produce zero rows/cols that are sliced off.
The scalar piece of the likelihood constant (``sum log nvec``,
``y^T N^-1 y``) stays in XLA — elementwise reductions the VPU/fusion
already handle.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu only imports on builds with the TPU extension available
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAVE_PLTPU = False

from gibbs_student_t_tpu.ops.pallas_util import (
    note_kernel_build,
    tpu_compiler_params,
)


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _tnt_kernel(T_ref, w_ref, wy_ref, tnt_ref, d_ref, *, chain_tile: int):
    """One grid step: fold one TOA block into one chain tile's accumulators.

    Block shapes: ``T (B, mp)``, ``w/wy (chain_tile, B)``,
    ``tnt (chain_tile, mp, mp)``, ``d (chain_tile, mp)``.
    """
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        tnt_ref[:] = jnp.zeros_like(tnt_ref)
        d_ref[:] = jnp.zeros_like(d_ref)

    T = T_ref[:]                       # (B, mp) — shared across the tile
    # contract axis 0 (TOAs) of both operands: (B, mp) x (B, mp) -> (mp, mp)
    contract = (((0,), (0,)), ((), ()))
    # HIGHEST: full f32 passes on the MXU — the default truncates inputs
    # to bfloat16, and TNT/d noise biases the hyper posteriors (see
    # ops/tnt.py module docstring)
    hi = jax.lax.Precision.HIGHEST
    for j in range(chain_tile):        # static unroll over the chain tile
        Tw = T * w_ref[j, :][:, None]  # weighted basis, registers/VMEM only
        tnt_ref[j] += jax.lax.dot_general(
            T, Tw, contract, preferred_element_type=jnp.float32,
            precision=hi)
        # keep the matvec 2-D (1, B) @ (B, mp): a 1-D lhs emits a
        # dot_dimension_numbers attribute this libtpu's Mosaic fails to
        # parse (verified on TPU v5e: "[1, 1]" for lhs_non_contracting)
        d_ref[j:j + 1] += jnp.dot(wy_ref[j:j + 1, :], T,
                                  preferred_element_type=jnp.float32,
                                  precision=hi)


def _auto_chain_tile(block_size: int, mp: int, C: int) -> int:
    """Default chain tile under a ~6 MB VMEM budget.

    The unrolled per-chain loop materializes a ``(block_size, mp)`` f32
    weighted-basis temporary per chain, and Mosaic keeps several alive
    at once — at block 4096, mp 128 a 32-chain tile blew the 16 MB
    scoped-VMEM stack (measured: 22.13 MB requested,
    artifacts/BENCH_STRESS_r03.err). The grid's chain axis absorbs what
    the tile gives up.
    """
    per_chain = block_size * mp * 4
    return max(1, min(32, C, (6 << 20) // per_chain))


def tnt_batched_pallas(T, y, nvec, block_size: int = 256,
                       chain_tile: Optional[int] = None,
                       interpret: bool = False):
    """``(TNT, d, const)`` for a batch of chains in one fused pass.

    ``T (n, m)``, ``y (n,)`` shared; ``nvec (C, n)`` per chain. Returns
    ``TNT (C, m, m)``, ``d (C, m)``, ``const (C,)`` matching
    ``ops.tnt.tnt_products`` per chain. ``n`` must be a multiple of
    ``block_size`` (use ``ops.tnt.pad_rows``; padded rows must carry
    ``nvec = 1`` exactly as on the XLA path).
    """
    C, n = nvec.shape
    m = T.shape[1]
    if n % block_size != 0:
        raise ValueError(f"n ({n}) must be a multiple of block_size "
                         f"({block_size}); use ops.tnt.pad_rows")
    # trace-time: fires once per XLA compile that embeds this kernel
    note_kernel_build("pallas_tnt_batched", n=int(n), m=int(m),
                      block_size=int(block_size),
                      interpret=bool(interpret))
    mp = _round_up(m, 128)
    if chain_tile is None:
        chain_tile = _auto_chain_tile(block_size, mp, C)
    cpad = _round_up(C, chain_tile) - C
    w = 1.0 / nvec
    wy = y[None, :] * w
    if cpad:
        # padded chains: weight zero -> zero outputs, sliced off below
        w = jnp.concatenate([w, jnp.zeros((cpad, n), w.dtype)])
        wy = jnp.concatenate([wy, jnp.zeros((cpad, n), wy.dtype)])
    Tp = jnp.pad(T, ((0, 0), (0, mp - m)))
    Ct = chain_tile
    grid = ((C + cpad) // Ct, n // block_size)

    kernel = functools.partial(_tnt_kernel, chain_tile=Ct)
    vmem = pltpu.VMEM if _HAVE_PLTPU else None
    # chain tiles are independent ("parallel"); the TOA dimension
    # accumulates in order ("arbitrary")
    kwargs = tpu_compiler_params(("parallel", "arbitrary"))

    def spec(shape, index_map):
        if vmem is None:
            return pl.BlockSpec(shape, index_map)
        return pl.BlockSpec(shape, index_map, memory_space=vmem)

    TNT_p, d_p = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            spec((block_size, mp), lambda c, i: (i, 0)),    # T block
            spec((Ct, block_size), lambda c, i: (c, i)),    # w tile
            spec((Ct, block_size), lambda c, i: (c, i)),    # wy tile
        ],
        out_specs=[
            spec((Ct, mp, mp), lambda c, i: (c, 0, 0)),
            spec((Ct, mp), lambda c, i: (c, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(((C + cpad), mp, mp), jnp.float32),
            jax.ShapeDtypeStruct(((C + cpad), mp), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(Tp, w, wy)

    TNT = TNT_p[:C, :m, :m]
    d = d_p[:C, :m]
    # scalar constant: pure elementwise reductions, left to XLA fusion
    const = -0.5 * (jnp.sum(jnp.log(nvec), axis=-1)
                    + jnp.sum(y[None, :] * wy[:C], axis=-1))
    return TNT, d, const.astype(TNT.dtype)


def tnt_lanes_pallas(T, y, nvec, gid, interpret: bool = False):
    """Per-lane-basis lanes twin of :func:`tnt_batched_pallas` under
    the serve slot pool's tile-uniform ``gid`` contract.

    ``T (B, n, m)`` / ``y (B, n)`` / ``nvec (B, n)`` are per-lane
    operands, but admission is 16-lane-group granular (``LANES_GROUP``),
    so the basis and residuals are CONSTANT within every aligned
    16-lane tile — one stride-slice row per group is the whole basis
    plane, and each group reduces through the shared-basis kernel with
    its 16 lanes as the chain batch. ``gid`` is the contract witness
    (validated for shape by the dispatcher); its values are not
    consumed here. ``n`` is zero-padded to a 128 multiple under the
    ``pad_rows`` contract (zero basis rows, zero residual, unit
    ``nvec``), which contributes exactly zero to every output.
    """
    from gibbs_student_t_tpu.ops.pallas_util import LANES_GROUP

    B, n, m = T.shape
    G = B // LANES_GROUP
    note_kernel_build("pallas_tnt_lanes", lanes=int(B), n=int(n),
                      m=int(m), groups=int(G), interpret=bool(interpret))
    bs = 128
    npad = _round_up(n, bs) - n
    Tg = T[::LANES_GROUP]                       # (G, n, m) group bases
    yg = y[::LANES_GROUP]                       # (G, n)
    nvg = nvec.reshape(G, LANES_GROUP, n)
    if npad:
        Tg = jnp.pad(Tg, ((0, 0), (0, npad), (0, 0)))
        yg = jnp.pad(yg, ((0, 0), (0, npad)))
        nvg = jnp.pad(nvg, ((0, 0), (0, 0), (0, npad)),
                      constant_values=1.0)
    outs = [tnt_batched_pallas(Tg[g], yg[g], nvg[g], block_size=bs,
                               interpret=interpret) for g in range(G)]
    TNT = jnp.concatenate([o[0] for o in outs]).reshape(B, m, m)
    d = jnp.concatenate([o[1] for o in outs]).reshape(B, m)
    const = jnp.concatenate([o[2] for o in outs]).reshape(B)
    return TNT, d, const


def tnt_batched_xla(T, y, nvec,
                    block_size: Optional[int] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """vmap of the XLA reduction — reference implementation and fallback."""
    from gibbs_student_t_tpu.ops.tnt import tnt_products

    return jax.vmap(lambda nv: tnt_products(T, y, nv, block_size))(nvec)


def tnt_batched(T, y, nvec, block_size: Optional[int] = None,
                use_pallas: Optional[bool] = None, interpret: bool = False):
    """Dispatch: the Pallas kernel when asked for, the XLA scan otherwise.

    ``use_pallas=None`` resolves to the XLA scan: the on-chip A/B
    measured it faster than this kernel in every blocked regime
    (artifacts/pallas_tnt_tpu_r02.json), so the kernel is opt-in A/B
    material, not a default.
    """
    if use_pallas is None:
        use_pallas = False
    if jnp.result_type(T, y, nvec) == jnp.float64:
        # the kernel accumulates in f32; silently degrading an f64 run's
        # TNT/d precision would be worse than the slower XLA path
        use_pallas = False
    if use_pallas and block_size:
        return tnt_batched_pallas(T, y, nvec, block_size=block_size,
                                  interpret=interpret)
    return tnt_batched_xla(T, y, nvec, block_size)
