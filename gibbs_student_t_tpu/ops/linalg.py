"""Diagonally-preconditioned Cholesky solves.

``Sigma = T^T N^-1 T + diag(phiinv)`` mixes scales across ~15 decades when
the red-noise amplitude is small (SURVEY.md §7 "hard parts: float64"): the
large ``phiinv`` entries sit on the diagonal, so symmetric diagonal
equilibration ``S' = D^-1/2 Sigma D^-1/2`` brings the matrix to unit
diagonal and float32-friendly conditioning. All identities:

    Sigma          = D^1/2 S' D^1/2,        L' L'^T = S'
    Sigma^-1 d     = D^-1/2 S'^-1 (D^-1/2 d)
    logdet Sigma   = logdet S' + sum log D
    A A^T = Sigma^-1  for  A = D^-1/2 L'^-T   (Gaussian draws)

This replaces the reference's LAPACK calls *and* its failure handling: a
non-PD matrix makes ``jnp.linalg.cholesky`` return NaN, which flows to a
non-finite log-likelihood and an automatic MH rejection — the branchless
equivalent of the reference's try/except -> -inf (reference
gibbs.py:320-324) and SVD->QR fallback (gibbs.py:168-178). A small
``jitter`` on the unit diagonal plays the fallback's regularizing role.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular


def precond_cholesky(Sigma, jitter: float = 0.0):
    """Factor ``Sigma`` with diagonal equilibration.

    Returns ``(L, inv_sqrt_d, logdet)`` where ``L`` is the lower Cholesky
    factor of the equilibrated matrix (plus ``jitter`` on its unit
    diagonal), ``inv_sqrt_d = D^-1/2``, and ``logdet = logdet Sigma``.
    """
    d = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
    inv_sqrt_d = 1.0 / jnp.sqrt(d)
    S = Sigma * inv_sqrt_d[..., :, None] * inv_sqrt_d[..., None, :]
    if jitter:
        S = S + jitter * jnp.eye(S.shape[-1], dtype=S.dtype)
    L = jnp.linalg.cholesky(S)
    logdet = (2.0 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)),
                            axis=-1)
              + jnp.sum(jnp.log(d), axis=-1))
    return L, inv_sqrt_d, logdet


def robust_precond_cholesky(Sigma, jitters=(1e-6, 1e-4, 1e-2)):
    """Escalating-jitter factorization for draws that cannot reject.

    When nearly all TOAs carry huge outlier variances (e.g. the vvh17
    transient where z starts all-ones, reference gibbs.py:50-51), Sigma is
    numerically singular in float32: the inlier contribution is rank-one and
    the 1e-10-relative outlier terms vanish below f32 eps. The b-draw still
    needs *a* factorization, so candidates are computed at increasing jitter
    and the first finite one is selected branchlessly. The final jitter is
    large enough that a unit-diagonal PSD-up-to-rounding matrix always
    factors in f32.
    """
    d = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
    inv_sqrt_d = 1.0 / jnp.sqrt(d)
    S = Sigma * inv_sqrt_d[..., :, None] * inv_sqrt_d[..., None, :]
    eye = jnp.eye(S.shape[-1], dtype=S.dtype)
    L = jnp.linalg.cholesky(S + jitters[0] * eye)
    for j in jitters[1:]:
        ok = jnp.isfinite(L).all()
        Lj = jnp.linalg.cholesky(S + j * eye)
        L = jnp.where(ok, L, Lj)
    logdet = (2.0 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)),
                            axis=-1)
              + jnp.sum(jnp.log(d), axis=-1))
    return L, inv_sqrt_d, logdet


def precond_solve_quad(L, inv_sqrt_d, rhs):
    """Given the factorization from :func:`precond_cholesky`, return
    ``(Sigma^-1 rhs, rhs^T Sigma^-1 rhs)``."""
    r = rhs * inv_sqrt_d
    u = solve_triangular(L, r, lower=True)
    quad = jnp.sum(u * u, axis=-1)
    v = solve_triangular(L, u, lower=True, trans="T")
    return v * inv_sqrt_d, quad


def gaussian_draw(L, inv_sqrt_d, mean, xi):
    """Draw ``b ~ N(mean, Sigma^-1)`` from a standard-normal ``xi`` — the
    conditional coefficient draw of reference gibbs.py:180 with covariance
    ``Sigma^-1``: fluctuation = D^-1/2 L^-T xi."""
    fluct = solve_triangular(L, xi, lower=True, trans="T") * inv_sqrt_d
    return mean + fluct
