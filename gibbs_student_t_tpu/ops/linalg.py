"""Diagonally-preconditioned Cholesky solves.

``Sigma = T^T N^-1 T + diag(phiinv)`` mixes scales across ~15 decades when
the red-noise amplitude is small (SURVEY.md §7 "hard parts: float64"): the
large ``phiinv`` entries sit on the diagonal, so symmetric diagonal
equilibration ``S' = D^-1/2 Sigma D^-1/2`` brings the matrix to unit
diagonal and float32-friendly conditioning. All identities:

    Sigma          = D^1/2 S' D^1/2,        L' L'^T = S'
    Sigma^-1 d     = D^-1/2 S'^-1 (D^-1/2 d)
    logdet Sigma   = logdet S' + sum log D
    A A^T = Sigma^-1  for  A = D^-1/2 L'^-T   (Gaussian draws)

This replaces the reference's LAPACK calls *and* its failure handling: a
non-PD matrix makes the factorization produce NaN, which flows to a
non-finite log-likelihood and an automatic MH rejection — the branchless
equivalent of the reference's try/except -> -inf (reference
gibbs.py:320-324) and SVD->QR fallback (gibbs.py:168-178). A small
``jitter`` on the unit diagonal plays the fallback's regularizing role.

For the small per-chain systems this model factors (m ~ 74), XLA's
While-loop ``cholesky``/``triangular_solve`` expanders dominate the whole
Gibbs sweep on TPU. The production TPU path is the Pallas lane-batched
kernel (ops/pallas_chol.py), reached through ``jax.custom_batching``:
the factorizations sit *inside* the chain-``vmap``, so ``_factor_fused``
/ ``_backsolve_fused`` carry a custom vmap rule that collapses all batch
axes onto the kernel's lane dimension — an unbatched call (the CPU
oracle-parity paths) still lowers to the plain XLA expander.
``GST_PALLAS_CHOL=auto|1|interpret|0`` gates it; the trace-unrolled XLA
replacement (ops/unrolled_chol.py) stays opt-in via
``GST_UNROLLED_CHOL=1`` only (wins standalone, loses in-sweep).

On non-TPU backends the production path is the portable vectorized one
(ops/vchol.py, ``GST_VCHOL=auto|1|0``): the batched LAPACK/XLA
factorization kept as-is, every triangular-solve EXPANDER replaced by
trace-time panel-unrolled substitutions — dispatched through the same
``custom_vmap`` fold so the in-sweep chain batch is visible, with the
same MIN_BATCH floor so unbatched oracle-parity calls stay on the
expander (docs/PERFORMANCE.md "The portable path").

On **CPU** specifically there is a fourth implementation ABOVE vchol in
priority: the first-party native lane-batched kernels
(``native/src/gst_ffi.cpp``, reached as XLA FFI custom calls through
``gibbs_student_t_tpu/native/ffi.py``; ``GST_NCHOL=auto|1|0``). They
apply the TPU Pallas insight to the host ISA — a 1024-chain batch of
60-column factorizations is ONE factorization whose every scalar is a
SIMD vector over a chains-contiguous tile — where batched LAPACK loops
over matrices each too small for BLAS-3 (~4.7 GFLOP/s measured,
artifacts/cpu_microbench_r06.json). ``auto``: on when the platform is
CPU *and* the library loads with its handlers (the capability probe
checks the .so, the jax FFI API, and the host SIMD level); anything
missing degrades silently to the vchol path, so no runtime ever
requires a C toolchain.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.custom_batching import custom_vmap
from jax.scipy.linalg import solve_triangular

from gibbs_student_t_tpu.ops import registry
from gibbs_student_t_tpu.ops.pallas_chol import (
    MAX_PALLAS_DIM,
    chol_fused_lane,
    tri_solve_T_lane,
)
from gibbs_student_t_tpu.ops.unrolled_chol import chol_forward, tri_solve_T
from gibbs_student_t_tpu.ops.vchol import (
    MAX_VCHOL_DIM,
    bwd_solve_mat,
    bwd_solve_vec,
    fwd_solve_mat,
    vchol_factor,
)


def vchol_env() -> str:
    """Validated ``GST_VCHOL`` value (``auto`` when unset).

    Raises on anything outside ``auto|1|0`` WHENEVER the variable is
    set, independent of which dispatch path ultimately wins — a typo'd
    override must fail loudly, not silently measure the wrong arm (the
    ``GST_ENSEMBLE_UNROLL`` validation contract, parallel/ensemble.py).
    Since round 18 the validation itself lives in the dispatch
    registry (ops/registry.py — ONE strict surface for every gate);
    this wrapper is the stable public name."""
    return registry.value("GST_VCHOL")


def _vchol_mode():
    """``(enabled, forced)`` for the portable vectorized path.

    ``auto`` resolves per-platform from the measured A/B
    (tools/cpu_microbench.py, docs/PERFORMANCE.md "The portable
    path"): ON for non-TPU backends, where the triangular-solve
    expander is the hot spot; OFF on TPU, where the production path is
    the Pallas lane kernel and the unrolled-program experiment already
    measured long unrolled programs scheduling badly inside the sweep
    (artifacts/tpu_validation_r02.json). Read at TRACE time, same
    snapshot semantics as ``GST_PALLAS_CHOL``; resolved (and its
    provenance recorded) by the registry."""
    return registry.mode3("GST_VCHOL")


def _vchol_ok(shape, forced: bool) -> bool:
    """Batch/size guard: below the shared Pallas threshold the
    (unbatched) CPU oracle-parity paths keep the plain expander, so
    their numbers stay byte-stable vs earlier rounds."""
    batch = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
    return (shape[-1] <= MAX_VCHOL_DIM
            and (forced or batch >= _PALLAS_MIN_BATCH))


def nchol_env() -> str:
    """Validated ``GST_NCHOL`` value (``auto`` when unset) — the native
    lane-batched CPU kernel gate. Strict ``auto|1|0``, raising whenever
    the variable is set to anything else (the loud-typo contract of
    every GST_* gate, implemented once in ops/registry.py). Note the
    asymmetry with availability: the VALUE is validated strictly, but
    a well-formed ``1`` on a host without the library degrades
    silently to the vchol path — forcing the arm must never make a
    toolchain a runtime requirement."""
    return registry.value("GST_NCHOL")


def _nchol_ready() -> bool:
    """Capability probe (latched per process, through the registry):
    library built with the FFI kernels, host SIMD level sufficient,
    jax FFI API present, targets registered. Never raises — an
    import/probe failure means the kernels are simply absent."""
    return registry.probe("native")


def _nchol_mode():
    """``(enabled, forced)`` for the native kernel path. The kernels
    are XLA:**CPU** custom calls, so even a forced ``1`` requires the
    CPU backend (on TPU the Pallas kernel is the production path and
    the custom-call target simply does not exist there). Read at TRACE
    time, same snapshot semantics as every other linalg gate; the
    probe→validate→degrade→record pipeline is the registry's."""
    return registry.mode3("GST_NCHOL")


def nwhite_env() -> str:
    """Validated ``GST_NWHITE`` (``auto`` when unset) — the native
    white-MH block arm. Strict ``auto|1|0`` (the loud-typo contract,
    registry-implemented); a well-formed ``1`` on a host without the
    library degrades silently to the XLA loop, which IS the CPU
    production path, so the graph is unchanged."""
    return registry.value("GST_NWHITE")


def _nwhite_mode():
    """``(enabled, forced)`` for the native white-MH arm — CPU custom
    call, same trace-time snapshot semantics as ``GST_NCHOL``."""
    return registry.mode3("GST_NWHITE")


def nwhite_take(shape, dtype, p: int, nvar: int) -> bool:
    """Trace-time: should the white-MH dispatch choose the native
    kernel for this call? Caps mirror the handler's validation so a
    shape it would reject is never dispatched."""
    enabled, forced = _nwhite_mode()
    if not enabled:
        return False
    batch = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    return (dtype in (jnp.float32, jnp.float64) and p <= 64
            and nvar <= 16 and (forced or batch >= _PALLAS_MIN_BATCH))


def nhyper_env() -> str:
    """Validated ``GST_NHYPER`` (``auto`` when unset) — the native
    fused hyper-MH block arm (one custom call for the whole 10-step
    block, S0 tile-resident across proposals). Strict ``auto|1|0``."""
    return registry.value("GST_NHYPER")


def _nhyper_mode():
    """``(enabled, forced)`` for the native hyper-MH arm."""
    return registry.mode3("GST_NHYPER")


def nhyper_take(shape, dtype, p: int, v: int, nk: int) -> bool:
    """Trace-time guard for the native hyper-MH dispatch."""
    enabled, forced = _nhyper_mode()
    if not enabled:
        return False
    batch = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    return (dtype in (jnp.float32, jnp.float64) and p <= 64
            and nk <= 16 and v <= MAX_VCHOL_DIM
            and (forced or batch >= _PALLAS_MIN_BATCH))


def fuse_stages_env() -> str:
    """Validated ``GST_FUSE_STAGES`` (``auto`` when unset) — the
    hyper+draws megastage: Schur pre-elimination, the whole hyper MH
    block and the coefficient draw's robust factorization + assembled
    solves as ONE multi-stage FFI dispatch. Strict ``auto|1|0``;
    ``auto`` resolves at backend construction (CPU + library + Schur +
    b-draw reuse + fusable model structure); anything missing keeps the
    per-stage graph, byte-identically with every gate off."""
    return registry.value("GST_FUSE_STAGES")


def nresid_env() -> str:
    """Validated ``GST_NRESID`` (``auto`` when unset) — the z/df glue's
    native residual-matvec arm (:func:`residual_matvec`). Strict
    ``auto|1|0``; ``auto`` follows the ``GST_NCHOL`` resolution (the
    arm is part of the native kernel family), ``0`` keeps the plain
    matmul even with the family active — the knob that lets a serve
    bit-identity pin align arms with the traced-basis pool path, which
    has no native resid form."""
    return registry.value("GST_NRESID")


def _nresid_mode():
    """``(enabled, forced)`` for the native residual-matvec arm —
    the one gate whose ``auto`` follows ANOTHER gate's resolution
    (the arm is part of the native kernel family), so its resolver
    stays here and records through the registry."""
    env = nresid_env()
    if env == "0":
        registry.record("GST_NRESID", value=env, enabled=False,
                        forced=False, reason="disabled")
        return False, False
    n_on, n_forced = _nchol_mode()
    if not n_on:
        registry.record("GST_NRESID", value=env, enabled=False,
                        forced=False,
                        reason="follows GST_NCHOL: inactive")
        return False, False
    registry.record("GST_NRESID", value=env, enabled=True,
                    forced=env == "1" or n_forced,
                    reason="follows GST_NCHOL: active")
    return True, env == "1" or n_forced


def nresid_active() -> bool:
    """Trace-time: should the sweep route its residual matvec through
    the dispatcher at all? Mirrors :func:`nchol_active`'s contract —
    with the arm off the caller emits the old matmul verbatim."""
    return _nresid_mode()[0]


def nchol_active() -> bool:
    """Trace-time: could the native kernel family be dispatched at all
    on this platform? Callers that must keep their gates-off graph
    byte-identical to earlier rounds (ops/tnt.py's dense reduction, the
    b-draw's robust factorization) branch on this BEFORE entering the
    dispatchers — with the gate off the old code path is emitted
    verbatim, not a dispatcher whose fallback merely computes the same
    values."""
    return _nchol_mode()[0]


def _nchol_ok(shape, dtype, forced: bool) -> bool:
    """Same MIN_BATCH floor and size ceiling as the vchol guard (one
    shared threshold keeps the three-way dispatch matrix coherent);
    f32/f64 only — the two dtypes the kernel family instantiates."""
    batch = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
    return (dtype in (jnp.float32, jnp.float64)
            and shape[-1] <= MAX_VCHOL_DIM
            and (forced or batch >= _PALLAS_MIN_BATCH))


def _note_impl(op: str, impl: str, shape) -> None:
    """Trace-time record of which implementation a dispatcher chose —
    lands on the current compile record (obs/introspect.py), so every
    run ledger entry can say WHICH linalg each compiled program used.
    Must never raise (the note_kernel_build contract)."""
    try:
        from gibbs_student_t_tpu.obs.introspect import register_linalg_impl

        register_linalg_impl(op, impl, shape=tuple(int(s) for s in shape))
    except Exception:  # noqa: BLE001
        pass


def _unrolled_wanted(m: int) -> bool:
    """Opt-in only (``GST_UNROLLED_CHOL=1``): hardware A/B on the v5e
    (artifacts/tpu_validation_r02.json) showed the trace-unrolled kernel
    wins standalone (4.1 ms vs 11.5 ms per batched factorization) but
    *loses 4x inside the full jitted sweep* (510 ms vs 127 ms per sweep
    with the XLA expander) — the long unrolled program schedules badly in
    the sweep's fori_loop context. The expander is the production path;
    the flag is kept for A/B measurement."""
    env = registry.value("GST_UNROLLED_CHOL")
    if env is not None:
        return env not in ("0", "false", "")
    return False


def _equilibrate(Sigma, jitter: float):
    """``(S', inv_sqrt_d, sum log D)`` with ``jitter`` on S's unit diag."""
    d = jnp.diagonal(Sigma, axis1=-2, axis2=-1)
    inv_sqrt_d = 1.0 / jnp.sqrt(d)
    S = Sigma * inv_sqrt_d[..., :, None] * inv_sqrt_d[..., None, :]
    if jitter:
        S = S + jitter * jnp.eye(S.shape[-1], dtype=S.dtype)
    return S, inv_sqrt_d, jnp.sum(jnp.log(d), axis=-1)


def _pallas_chol_mode():
    """``(enabled, interpret, forced)`` from ``GST_PALLAS_CHOL``:
    ``auto`` (default) enables the Pallas kernel on TPU backends for
    batches past ``_PALLAS_MIN_BATCH``; ``interpret`` forces it in
    interpreter mode (CPU testing); ``0``/``false``/empty disables; any
    other value forces it regardless of platform or batch size — the
    same anything-truthy-is-on rule as ``GST_UNROLLED_CHOL``.

    Read at TRACE time: the value is baked into a backend's jitted sweep
    when that function is first traced, so set the env var *before*
    constructing ``JaxGibbs`` (same for ``GST_HYPER_SCHUR``, snapshotted
    in ``JaxGibbs.__init__``). Flipping it afterwards silently has no
    effect on an existing backend instance — construct a new one for an
    A/B (the pattern bench.py's fallback ladder uses: fresh process per
    rung)."""
    from gibbs_student_t_tpu.ops.pallas_util import mode_from_env

    return mode_from_env("GST_PALLAS_CHOL")


# Below this flattened batch size the relayout overhead outweighs the
# kernel win and the expander is kept — the shared threshold of every
# Pallas kernel gate (ops/pallas_util.py), imported so the fused-MH
# dispatchers' fallback assumptions cannot drift from this one.
from gibbs_student_t_tpu.ops.pallas_util import MIN_BATCH as _PALLAS_MIN_BATCH  # noqa: E402
from gibbs_student_t_tpu.ops.pallas_util import LANES_GROUP as _LANES_GROUP  # noqa: E402


def _pallas_tnt_mode():
    """``(enabled, interpret, forced)`` from ``GST_PALLAS_TNT`` — the
    per-lane-basis TNT lanes twin's gate, same vocabulary and trace-time
    snapshot semantics as ``GST_PALLAS_CHOL`` (:func:`_pallas_chol_mode`)."""
    from gibbs_student_t_tpu.ops.pallas_util import mode_from_env

    return mode_from_env("GST_PALLAS_TNT")


def _pallas_ok(shape, dtype, forced: bool) -> bool:
    batch = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
    return (dtype == jnp.float32 and shape[-1] <= MAX_PALLAS_DIM
            and (forced or batch >= _PALLAS_MIN_BATCH))


@custom_vmap
def _factor_fused(S, rhs):
    """``(L, logdet S, L^-1 rhs)`` — Pallas lane-batched kernel when
    enabled and the (flattened) batch is big enough, XLA expander
    otherwise. The vmap rule below folds mapped axes into the batch
    *before* this dispatch runs, so a chain-vmapped call sees the full
    chain batch here."""
    enabled, interp, forced = _pallas_chol_mode()
    v_on, v_forced = _vchol_mode()  # validates GST_VCHOL even when
    # the Pallas kernel wins the dispatch below
    n_on, n_forced = _nchol_mode()  # ... and GST_NCHOL likewise
    if enabled and _pallas_ok(S.shape, S.dtype, forced):
        L, logdet, u = chol_fused_lane(S, rhs, interpret=interp)
        _note_impl("factor", "pallas", S.shape)
        return L, logdet, u
    if n_on and _nchol_ok(S.shape, S.dtype, n_forced):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("factor", "nchol", S.shape)
        return nffi.nchol_factor(S, rhs)
    if v_on and _vchol_ok(S.shape, v_forced):
        _note_impl("factor", "vchol", S.shape)
        return vchol_factor(S, rhs)
    _note_impl("factor", "expander", S.shape)
    L = jnp.linalg.cholesky(S)
    logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)),
                           axis=-1)
    u = solve_triangular(L, rhs[..., None], lower=True)[..., 0]
    return L, logdet, u


@_factor_fused.def_vmap
def _factor_fused_vmap(axis_size, in_batched, S, rhs):
    if not in_batched[0]:
        S = jnp.broadcast_to(S, (axis_size,) + S.shape)
    if not in_batched[1]:
        rhs = jnp.broadcast_to(rhs, (axis_size,) + rhs.shape)
    return _factor_fused(S, rhs), (True, True, True)


@custom_vmap
def _factor_quad_fused(S, rhs):
    """``(logdet S, L^-1 rhs)`` — the factorization WITHOUT the dense-L
    output. The hyper-MH likelihood consumes only logdet and the
    forward-solved rhs; XLA cannot dead-code an FFI result buffer, so
    routing those callers through the full factor kernel paid a
    B*m*m memset plus the L store transpose per proposal (measured:
    ~5/6 of the factor kernel's wall time at the flagship shape,
    artifacts/cpu_microbench_r08.json). Values are bitwise identical to
    :func:`_factor_fused`'s logdet/u — same recurrence, L simply never
    stored. Falls back to :func:`_factor_fused` (whose jnp branches let
    XLA DCE the unused L) whenever the native kernel is not chosen."""
    n_on, n_forced = _nchol_mode()
    if n_on and _nchol_ok(S.shape, S.dtype, n_forced):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("factor_quad", "nchol", S.shape)
        return nffi.nchol_factor_quad(S, rhs)
    _, logdet, u = _factor_fused(S, rhs)
    return logdet, u


@_factor_quad_fused.def_vmap
def _factor_quad_fused_vmap(axis_size, in_batched, S, rhs):
    if not in_batched[0]:
        S = jnp.broadcast_to(S, (axis_size,) + S.shape)
    if not in_batched[1]:
        rhs = jnp.broadcast_to(rhs, (axis_size,) + rhs.shape)
    return _factor_quad_fused(S, rhs), (True, True)


@custom_vmap
def _backsolve_fused(L, rhs):
    """``L^T x = rhs`` — Pallas lane-batched backward substitution or the
    XLA triangular-solve, same dispatch as :func:`_factor_fused`."""
    enabled, interp, forced = _pallas_chol_mode()
    v_on, v_forced = _vchol_mode()
    n_on, n_forced = _nchol_mode()
    if enabled and _pallas_ok(L.shape, L.dtype, forced):
        _note_impl("bwd_vec", "pallas", L.shape)
        return tri_solve_T_lane(L, rhs, interpret=interp)
    if n_on and _nchol_ok(L.shape, L.dtype, n_forced):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("bwd_vec", "nchol", L.shape)
        return nffi.bwd_vec(L, rhs)
    if v_on and _vchol_ok(L.shape, v_forced):
        _note_impl("bwd_vec", "vchol", L.shape)
        return bwd_solve_vec(L, rhs)
    _note_impl("bwd_vec", "expander", L.shape)
    return solve_triangular(L, rhs, lower=True, trans="T")


@_backsolve_fused.def_vmap
def _backsolve_fused_vmap(axis_size, in_batched, L, rhs):
    if not in_batched[0]:
        L = jnp.broadcast_to(L, (axis_size,) + L.shape)
    if not in_batched[1]:
        rhs = jnp.broadcast_to(rhs, (axis_size,) + rhs.shape)
    return _backsolve_fused(L, rhs), True


@custom_vmap
def _fwd_mat_fused(L, R):
    """``L X = R`` for matrix rhs ``R (..., m, k)`` — the unrolled
    vectorized substitution when the vchol gate is on (the Schur
    pre-elimination's solves are per-sweep multi-rhs expander calls
    otherwise), XLA triangular-solve else. Same fold-the-mapped-axis
    dispatch as :func:`_factor_fused`; no Pallas variant exists (the
    TPU sweep reaches these solves once per sweep, not per proposal)."""
    v_on, v_forced = _vchol_mode()
    n_on, n_forced = _nchol_mode()
    if n_on and _nchol_ok(L.shape, L.dtype, n_forced):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("fwd_mat", "nchol", L.shape)
        return nffi.fwd_mat(L, R)
    if v_on and _vchol_ok(L.shape, v_forced):
        _note_impl("fwd_mat", "vchol", L.shape)
        return fwd_solve_mat(L, R)
    _note_impl("fwd_mat", "expander", L.shape)
    return solve_triangular(L, R, lower=True)


@_fwd_mat_fused.def_vmap
def _fwd_mat_fused_vmap(axis_size, in_batched, L, R):
    if not in_batched[0]:
        L = jnp.broadcast_to(L, (axis_size,) + L.shape)
    if not in_batched[1]:
        R = jnp.broadcast_to(R, (axis_size,) + R.shape)
    return _fwd_mat_fused(L, R), True


@custom_vmap
def _bwd_mat_fused(L, R):
    """``L^T X = R`` for matrix rhs, same dispatch as
    :func:`_fwd_mat_fused`."""
    v_on, v_forced = _vchol_mode()
    n_on, n_forced = _nchol_mode()
    if n_on and _nchol_ok(L.shape, L.dtype, n_forced):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("bwd_mat", "nchol", L.shape)
        return nffi.bwd_mat(L, R)
    if v_on and _vchol_ok(L.shape, v_forced):
        _note_impl("bwd_mat", "vchol", L.shape)
        return bwd_solve_mat(L, R)
    _note_impl("bwd_mat", "expander", L.shape)
    return solve_triangular(L, R, lower=True, trans="T")


@_bwd_mat_fused.def_vmap
def _bwd_mat_fused_vmap(axis_size, in_batched, L, R):
    if not in_batched[0]:
        L = jnp.broadcast_to(L, (axis_size,) + L.shape)
    if not in_batched[1]:
        R = jnp.broadcast_to(R, (axis_size,) + R.shape)
    return _bwd_mat_fused(L, R), True


def _factor(S, rhs=None):
    """``(L, logdet S, L^-1 rhs | None)`` via the Pallas/XLA dispatch, or
    the opt-in trace-unrolled kernel (``GST_UNROLLED_CHOL=1``)."""
    if _unrolled_wanted(S.shape[-1]):
        return chol_forward(S, rhs)
    # rhs=None callers pass zeros: on the XLA expander branch the dead
    # solve (and unused L relayout) is DCE'd when only logdet/u are
    # consumed; on the Pallas branch the fused forward solve lives inside
    # one pallas_call and IS executed — measured in the hardware A/B as
    # noise at the m=74 flagship shape (the factorization dominates), so
    # no separate no-rhs kernel variant exists. Revisit if a profile ever
    # shows precond_cholesky (the only zero-rhs caller) hot on TPU.
    L, logdet, u = _factor_fused(
        S, rhs if rhs is not None else jnp.zeros(S.shape[:-1], S.dtype))
    return L, logdet, (u if rhs is not None else None)


def precond_cholesky(Sigma, jitter: float = 0.0):
    """Factor ``Sigma`` with diagonal equilibration.

    Returns ``(L, inv_sqrt_d, logdet)`` where ``L`` is the lower Cholesky
    factor of the equilibrated matrix (plus ``jitter`` on its unit
    diagonal), ``inv_sqrt_d = D^-1/2``, and ``logdet = logdet Sigma``.
    """
    S, inv_sqrt_d, logd = _equilibrate(Sigma, jitter)
    L, logdet_S, _ = _factor(S)
    return L, inv_sqrt_d, logdet_S + logd


def _factor_quad(S, rhs):
    """``(logdet S, L^-1 rhs)`` through the same gates as
    :func:`_factor` for callers that never read L: the no-L native
    kernel when the nchol dispatch would choose the native factor, the
    ordinary dispatch (L dead-coded by XLA) otherwise. Bitwise
    identical to dropping L from :func:`_factor`'s result."""
    if _unrolled_wanted(S.shape[-1]):
        _, logdet, u = chol_forward(S, rhs)
        return logdet, u
    if nchol_active():
        return _factor_quad_fused(S, rhs)
    _, logdet, u = _factor_fused(S, rhs)
    return logdet, u


def precond_quad_logdet(Sigma, rhs, jitter: float = 0.0):
    """``(rhs^T Sigma^-1 rhs, logdet Sigma)`` in one fused pass — the
    linear-algebra payload of a marginalized-likelihood evaluation
    (reference gibbs.py:309-327) without materializing solves the MH
    accept/reject never looks at."""
    S, inv_sqrt_d, logd = _equilibrate(Sigma, jitter)
    logdet_S, u = _factor_quad(S, rhs * inv_sqrt_d)
    return jnp.sum(u * u, axis=-1), logdet_S + logd


def precond_quad_logdet_hoisted(S0, dS0, pv, rhs, jitter: float = 0.0):
    """``precond_quad_logdet(S0 + diag(pv), rhs, jitter)`` restructured
    for a per-proposal loop whose matrix block ``S0`` (and its
    precomputed diagonal ``dS0``) are sweep constants and only the
    diagonal increment ``pv`` (the prior precision at the proposal)
    varies: the ``S0 + diag(pv)`` intermediate is never materialized —
    the equilibrated matrix is built in ONE fused elementwise pass from
    ``S0`` and the updated diagonal. Every float operation matches
    :func:`_equilibrate` on the materialized sum (same values, same
    association order), so hoist on/off chains are bit-identical
    (pinned in tests/test_nchol.py)."""
    d = dS0 + pv
    inv_sqrt_d = 1.0 / jnp.sqrt(d)
    S = S0 * inv_sqrt_d[..., :, None] * inv_sqrt_d[..., None, :]
    # the diagonal of the materialized form is (Sv_ii * isd_i) * isd_i;
    # replicate that exact association on the precomputed diagonal
    eye_b = jnp.eye(S.shape[-1], dtype=bool)
    S = jnp.where(
        eye_b,
        d[..., :, None] * inv_sqrt_d[..., :, None] * inv_sqrt_d[..., :, None],
        S)
    if jitter:
        S = S + jitter * jnp.eye(S.shape[-1], dtype=S.dtype)
    logd = jnp.sum(jnp.log(d), axis=-1)
    logdet_S, u = _factor_quad(S, rhs * inv_sqrt_d)
    return jnp.sum(u * u, axis=-1), logdet_S + logd


def robust_precond_cholesky(Sigma, jitters=(1e-6, 1e-4, 1e-2), rhs=None):
    """Escalating-jitter factorization for draws that cannot reject.

    When nearly all TOAs carry huge outlier variances (e.g. the vvh17
    transient where z starts all-ones, reference gibbs.py:50-51), Sigma is
    numerically singular in float32: the inlier contribution is rank-one and
    the 1e-10-relative outlier terms vanish below f32 eps. The b-draw still
    needs *a* factorization, so every jitter level is factored in one
    batched pass (stacked along a new leading axis — same sequential
    depth as a single factorization) and the first finite candidate is
    selected branchlessly. The final jitter is large enough that a
    unit-diagonal PSD-up-to-rounding matrix always factors in f32.

    Returns ``(L, inv_sqrt_d, logdet)``; with ``rhs`` given, appends
    ``u = L^-1 (D^-1/2 rhs)`` for the selected factor.
    """
    S, inv_sqrt_d, logd = _equilibrate(Sigma, 0.0)
    eye = jnp.eye(S.shape[-1], dtype=S.dtype)
    Ss = jnp.stack([S + j * eye for j in jitters], axis=0)
    rs = None
    if rhs is not None:
        r = rhs * inv_sqrt_d
        rs = jnp.broadcast_to(r, Ss.shape[:1] + r.shape)
    Ls, logdets, us = _factor(Ss, rs)

    L, logdet_S = Ls[0], logdets[0]
    u = None if us is None else us[0]
    for k in range(1, len(jitters)):
        # keep the selected candidate wherever it is finite; otherwise
        # escalate to the next jitter level
        ok = jnp.isfinite(L).all(axis=(-2, -1)) & jnp.isfinite(logdet_S)
        L = jnp.where(ok[..., None, None], L, Ls[k])
        logdet_S = jnp.where(ok, logdet_S, logdets[k])
        if u is not None:
            u = jnp.where(ok[..., None], u, us[k])
    out = (L, inv_sqrt_d, logdet_S + logd)
    return out + (u,) if rhs is not None else out


def backward_solve(L, rhs):
    """``L^T x = rhs`` through the same gates as the factorization:
    the Pallas/XLA dispatch, or unrolled under ``GST_UNROLLED_CHOL=1``."""
    if _unrolled_wanted(L.shape[-1]):
        return tri_solve_T(L, rhs)
    return _backsolve_fused(L, rhs)


def schur_eliminate(Sigma_ss, Sigma_sv, Sigma_vv, rhs_s, rhs_v,
                    jitter: float = 0.0, return_factor: bool = False):
    """Pre-eliminate a fixed block of ``Sigma`` for repeated solves.

    For ``Sigma = [[A, B], [B^T, C + D]]`` where only the diagonal ``D``
    on the v-block changes between evaluations (the hyper-MH structure:
    phi-static columns s, phi-varying columns v), returns
    ``(S0, rt, quad_s, logdetA)`` with ``S0 = C - B^T A^-1 B`` and
    ``rt = rhs_v - B^T A^-1 rhs_s`` such that for any diagonal ``D``:

        rhs^T Sigma^-1 rhs = quad_s + rt^T (S0 + D)^-1 rt
        logdet Sigma       = logdetA + logdet(S0 + D)

    — evaluated downstream via :func:`precond_quad_logdet` on the
    smaller ``S0 + D``. ``A`` is a principal submatrix of every
    ``Sigma`` sharing it, so a non-PD ``A`` (NaN here) poisons every
    evaluation — the same reject-all failure semantics as factoring the
    full matrix per evaluation.

    With ``return_factor``, appends ``(La, isd_a, U_B, u_s)`` — the
    A-block's preconditioned Cholesky factor, ``U_B = La^-1 D_a^-1/2
    B`` and ``u_s = La^-1 D_a^-1/2 rhs_s`` — the pieces the b-draw's
    block-assembled factorization reuses (backends/jax_backend.py
    ``_sweep_rest``): for any v-block factor ``S0 + D = D_v^1/2 Ls
    Ls^T D_v^1/2``, the permuted ``Sigma`` factors exactly as

        Sigma_perm = Dd^1/2 [[La, 0], [W, Ls]] [[La, 0], [W, Ls]]^T Dd^1/2

    with ``Dd = blockdiag(D_a, D_v)`` and ``W = D_v^-1/2 B^T D_a^-1/2
    La^-T = (U_B * D_v^-1/2)^T`` — no full m x m refactorization.

    On the native path (``GST_NCHOL``, return_factor calls) the whole
    elimination — equilibrated A factor, multi-rhs solves, and the
    S0/rt assembly matmuls XLA lowers as B small per-chain matmuls —
    is ONE fused custom call (``gst_schur``); with the gate off this
    composition is emitted verbatim.
    """
    if return_factor and nchol_active():
        S0, rt, quad_s, logdetA, La, isd_a, U_B, u_s = _schur_dispatcher(
            float(jitter))(Sigma_ss, Sigma_sv, Sigma_vv, rhs_s, rhs_v)
        return S0, rt, quad_s, logdetA, (La, isd_a, U_B, u_s)
    S0, rt, quad_s, logdetA, La, isd_a, U_B, u_s = _schur_jnp(
        Sigma_ss, Sigma_sv, Sigma_vv, rhs_s, rhs_v, jitter)
    out = (S0, rt, quad_s, logdetA)
    if return_factor:
        out = out + ((La, isd_a, U_B, u_s),)
    return out


def _schur_jnp(Sigma_ss, Sigma_sv, Sigma_vv, rhs_s, rhs_v, jitter):
    """The pre-dispatch :func:`schur_eliminate` composition, flat
    8-tuple — the gates-off graph (emitted verbatim) and the native
    schur kernel's parity oracle / degradation target."""
    La, isd_a, logdetA = precond_cholesky(Sigma_ss, jitter)
    rhsM = jnp.concatenate([Sigma_sv, rhs_s[..., :, None]], axis=-1)
    u = _fwd_mat_fused(La, rhsM * isd_a[..., :, None])
    w = _bwd_mat_fused(La, u) * isd_a[..., :, None]
    Ainv_rs = w[..., :, -1]
    quad_s = jnp.sum(rhs_s * Ainv_rs, axis=-1)
    mT = jnp.swapaxes(Sigma_sv, -1, -2)
    # full f32 passes: TPU's default matmul precision is bfloat16-input
    # and the eliminated block feeds every hyper-MH likelihood this sweep
    hi = jax.lax.Precision.HIGHEST
    S0 = Sigma_vv - jnp.matmul(mT, w[..., :, :-1], precision=hi)
    rt = rhs_v - jnp.matmul(mT, Ainv_rs[..., None], precision=hi)[..., 0]
    return (S0, rt, quad_s, logdetA, La, isd_a, u[..., :, :-1],
            u[..., :, -1])


@functools.lru_cache(maxsize=None)
def _schur_dispatcher(jitter: float):
    """Per-jitter ``custom_vmap`` dispatcher behind the native
    :func:`schur_eliminate` arm (jitter is trace-static)."""

    @custom_vmap
    def sd(A, Bm, C, rs, rv):
        n_on, n_forced = _nchol_mode()
        if (n_on and A.ndim >= 3
                and _nchol_ok(A.shape, A.dtype, n_forced)
                and C.shape[-1] <= MAX_VCHOL_DIM):
            from gibbs_student_t_tpu.native import ffi as nffi

            _note_impl("schur", "nchol", A.shape)
            return nffi.schur(A, Bm, C, rs, rv, jitter)
        _note_impl("schur", "jnp", A.shape)
        return _schur_jnp(A, Bm, C, rs, rv, jitter)

    @sd.def_vmap
    def _sd_vmap(axis_size, in_batched, *args):
        args = tuple(
            a if b else jnp.broadcast_to(a, (axis_size,) + a.shape)
            for a, b in zip(args, in_batched))
        return sd(*args), (True,) * 8

    return sd


@functools.lru_cache(maxsize=None)
def _robust_draw_dispatcher(jitters: tuple):
    """Per-jitter-schedule ``custom_vmap`` dispatcher behind
    :func:`robust_precond_draw` (the schedule is trace-static, so one
    dispatcher per distinct tuple, cached)."""

    @custom_vmap
    def rd(Sigma, rhs, xi):
        n_on, n_forced = _nchol_mode()
        if (n_on and Sigma.ndim >= 3
                and _nchol_ok(Sigma.shape, Sigma.dtype, n_forced)):
            from gibbs_student_t_tpu.native import ffi as nffi

            _note_impl("robust_draw", "nchol", Sigma.shape)
            S, inv_sqrt_d, logd = _equilibrate(Sigma, 0.0)
            jits = jnp.asarray(np.asarray(jitters, dtype=np.float64),
                               dtype=Sigma.dtype)
            y, logdet_S = nffi.nchol_robust_draw(S, rhs * inv_sqrt_d, xi,
                                                 jits)
            return y, inv_sqrt_d, logdet_S + logd
        _note_impl("robust_draw", "stacked", Sigma.shape)
        L, inv_sqrt_d, logdet, u = robust_precond_cholesky(
            Sigma, jitters=jitters, rhs=rhs)
        return backward_solve(L, u + xi), inv_sqrt_d, logdet

    @rd.def_vmap
    def _rd_vmap(axis_size, in_batched, Sigma, rhs, xi):
        if not in_batched[0]:
            Sigma = jnp.broadcast_to(Sigma, (axis_size,) + Sigma.shape)
        if not in_batched[1]:
            rhs = jnp.broadcast_to(rhs, (axis_size,) + rhs.shape)
        if not in_batched[2]:
            xi = jnp.broadcast_to(xi, (axis_size,) + xi.shape)
        return rd(Sigma, rhs, xi), (True, True, True)

    return rd


def robust_precond_draw(Sigma, rhs, xi,
                        jitters=(1e-6, 1e-4, 1e-2, 1e-1)):
    """``(y, inv_sqrt_d, logdet)`` with ``y = L^-T (u + xi)`` for the
    escalating-jitter factorization of :func:`robust_precond_cholesky`
    — the b-draw's factor-then-backward-substitute pair as one
    operation, so the native path (``GST_NCHOL``) can run it as a
    single fused custom call: the stacked-jitter XLA form materializes
    every jitter level of ``S`` and factors all of them every sweep,
    while the kernel escalates only the chain tiles whose first level
    actually failed (the selection predicate — all-finite L and logdet
    — and the escalate-else-last cascade are identical). With the
    native path inactive this IS the old composition, emitted verbatim
    (the gates-off graphs are byte-identical to rounds 6/7)."""
    if not nchol_active():
        L, inv_sqrt_d, logdet, u = robust_precond_cholesky(
            Sigma, jitters=jitters, rhs=rhs)
        return backward_solve(L, u + xi), inv_sqrt_d, logdet
    jitters = tuple(float(j) for j in jitters)
    return _robust_draw_dispatcher(jitters)(Sigma, rhs, xi)


def _tnt_gram_jnp(T, y, nvec):
    """One chain's dense TNT reduction — EXACTLY ops/tnt.py's dense
    expressions, so the dispatcher's fallback lowers to the same HLO
    the pre-dispatch path produced under ``vmap``."""
    w = 1.0 / nvec
    Tw = T * w[:, None]
    hi = jax.lax.Precision.HIGHEST
    TNT = jnp.matmul(T.T, Tw, precision=hi)
    d = jnp.matmul(Tw.T, y, precision=hi)
    const = -0.5 * (jnp.sum(jnp.log(nvec)) + jnp.sum(y * y * w))
    return TNT, d, const


@custom_vmap
def tnt_gram(T, y, nvec):
    """``(TNT, d, const_white)`` of ops/tnt.py's dense reduction with
    the basis ``T (n, m)`` / residuals ``y (n,)`` SHARED across the
    chain batch and only ``nvec (..., n)`` per-chain — the structure
    the native lane-batched Gram kernel exploits (XLA's batched-matmul
    lowering materializes the (B, n, m) weighted basis and loops B
    small matmuls instead). Dispatched under ``GST_NCHOL`` like the
    factor kernels; the fallback re-enters the plain per-chain
    expressions under ``vmap`` so a small batch lowers exactly as the
    pre-dispatch path did. Only reached when ``nchol_active()`` (see
    ops/tnt.py) — gates-off sweeps never route here."""
    if nvec.ndim == 1:
        return _tnt_gram_jnp(T, y, nvec)
    n_on, n_forced = _nchol_mode()
    batch = int(np.prod(nvec.shape[:-1]))
    if (n_on and T.ndim == 2 and y.ndim == 1
            and nvec.dtype in (jnp.float32, jnp.float64)
            and T.dtype == nvec.dtype and y.dtype == nvec.dtype
            and (n_forced or batch >= _PALLAS_MIN_BATCH)):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("tnt", "nchol", nvec.shape)
        return nffi.tnt(T, y, nvec)
    _note_impl("tnt", "vmap_jnp", nvec.shape)
    f = _tnt_gram_jnp
    for _ in range(nvec.ndim - 1):
        f = jax.vmap(f, in_axes=(None, None, 0))
    return f(T, y, nvec)


@tnt_gram.def_vmap
def _tnt_gram_vmap(axis_size, in_batched, T, y, nvec):
    if in_batched[0] or in_batched[1]:
        # batched basis (a traced per-pulsar model): not the shared-T
        # structure — peel every axis with plain vmap over the jnp form
        def g(Tb, yb, nvb):
            f = _tnt_gram_jnp
            for _ in range(nvb.ndim - 1):
                f = jax.vmap(f, in_axes=(None, None, 0))
            return f(Tb, yb, nvb)

        out = jax.vmap(g, in_axes=tuple(0 if b else None
                                        for b in in_batched))(T, y, nvec)
        return out, (True, True, True)
    if not in_batched[2]:
        nvec = jnp.broadcast_to(nvec, (axis_size,) + nvec.shape)
    return tnt_gram(T, y, nvec), (True, True, True)


@custom_vmap
def tnt_gram_lanes(T, y, nvec, gid):
    """Per-lane-basis twin of :func:`tnt_gram` — the serve slot pool's
    TNT reduction, where every lane carries its OWN tenant's dataset as
    a call-time operand (``T (..., n, m)``, ``y (..., n)``) plus the
    tile-uniform group id. The native lanes kernel re-transposes the
    basis only at group boundaries, so a tenant spanning many tiles
    pays one transpose; the fallback is the identical per-lane jnp
    expression the grouped ensemble path emits, so gates-off serving
    keeps the traced-basis graph verbatim."""
    if nvec.ndim == 1:
        return _tnt_gram_jnp(T, y, nvec)
    n_on, n_forced = _nchol_mode()
    batch = int(np.prod(nvec.shape[:-1]))
    if (n_on and T.ndim == 3 and y.ndim == 2 and nvec.ndim == 2
            and gid.ndim == 1
            and nvec.dtype in (jnp.float32, jnp.float64)
            and T.dtype == nvec.dtype and y.dtype == nvec.dtype
            and (n_forced or batch >= _PALLAS_MIN_BATCH)):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("tnt_lanes", "nchol", nvec.shape)
        return tuple(nffi.tnt_lanes(T, y, nvec, gid))
    p_on, p_interp, p_forced = _pallas_tnt_mode()
    if (p_on and T.ndim == 3 and y.ndim == 2 and nvec.ndim == 2
            and gid.ndim == 1 and nvec.dtype == jnp.float32
            and T.dtype == nvec.dtype and y.dtype == nvec.dtype
            and T.shape[0] % _LANES_GROUP == 0
            and (p_forced or batch >= _PALLAS_MIN_BATCH)):
        from gibbs_student_t_tpu.ops.pallas_tnt import tnt_lanes_pallas

        _note_impl("tnt_lanes", "pallas", nvec.shape)
        return tnt_lanes_pallas(T, y, nvec, gid, interpret=p_interp)
    _note_impl("tnt_lanes", "vmap_jnp", nvec.shape)
    f = _tnt_gram_jnp
    for _ in range(nvec.ndim - 1):
        f = jax.vmap(f)
    return f(T, y, nvec)


@tnt_gram_lanes.def_vmap
def _tnt_gram_lanes_vmap(axis_size, in_batched, T, y, nvec, gid):
    out = tuple(
        a if bt else jnp.broadcast_to(a, (axis_size,) + a.shape)
        for a, bt in zip((T, y, nvec, gid), in_batched))
    return tnt_gram_lanes(*out), (True, True, True)


@custom_vmap
def residual_matvec(T, y, b):
    """``y - T @ b`` per chain with the basis/residuals shared across
    the batch — the z/df glue's (n, m) matvec between the coefficient
    draw and the outlier/df conditionals (docs/FUTURE.md #2), behind
    the ``GST_NCHOL`` dispatch like :func:`tnt_gram`. The fallback is
    the exact pre-dispatch expression under ``vmap``, and callers only
    route here when ``nchol_active()`` — gates-off sweeps keep the old
    matmul verbatim."""
    hi = jax.lax.Precision.HIGHEST
    if b.ndim == 1:
        return y - jnp.matmul(T, b, precision=hi)
    n_on, n_forced = _nresid_mode()
    batch = int(np.prod(b.shape[:-1]))
    if (n_on and T.ndim == 2 and y.ndim == 1
            and b.dtype in (jnp.float32, jnp.float64)
            and T.dtype == b.dtype and y.dtype == b.dtype
            and (n_forced or batch >= _PALLAS_MIN_BATCH)):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("resid", "nchol", b.shape)
        return nffi.resid(T, y, b)
    _note_impl("resid", "vmap_jnp", b.shape)
    f = lambda bb: y - jnp.matmul(T, bb, precision=hi)  # noqa: E731
    for _ in range(b.ndim - 1):
        f = jax.vmap(f)
    return f(b)


@custom_vmap
def residual_matvec_lanes(T, y, b, gid):
    """Per-lane-basis twin of :func:`residual_matvec` — the serve slot
    pool's z/df-glue matvec, with the tenant basis/residuals as
    call-time operands under the tile-uniform group-id contract. The
    native arm shares :func:`residual_matvec`'s inner loop, so a
    uniform pool is bitwise the solo kernel; the fallback is the
    per-lane matmul the traced-basis path always computed."""
    hi = jax.lax.Precision.HIGHEST
    if b.ndim == 1:
        return y - jnp.matmul(T, b, precision=hi)
    n_on, n_forced = _nresid_mode()
    batch = int(np.prod(b.shape[:-1]))
    if (n_on and T.ndim == 3 and y.ndim == 2 and b.ndim == 2
            and gid.ndim == 1
            and b.dtype in (jnp.float32, jnp.float64)
            and T.dtype == b.dtype and y.dtype == b.dtype
            and (n_forced or batch >= _PALLAS_MIN_BATCH)):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("resid_lanes", "nchol", b.shape)
        return nffi.resid_lanes(T, y, b, gid)
    _note_impl("resid_lanes", "vmap_jnp", b.shape)

    def one(Tb, yb, bb):
        return yb - jnp.matmul(Tb, bb, precision=hi)

    f = one
    for _ in range(b.ndim - 1):
        f = jax.vmap(f)
    return f(T, y, b)


@residual_matvec_lanes.def_vmap
def _residual_matvec_lanes_vmap(axis_size, in_batched, T, y, b, gid):
    out = tuple(
        a if bt else jnp.broadcast_to(a, (axis_size,) + a.shape)
        for a, bt in zip((T, y, b, gid), in_batched))
    return residual_matvec_lanes(*out), True


@residual_matvec.def_vmap
def _residual_matvec_vmap(axis_size, in_batched, T, y, b):
    if in_batched[0] or in_batched[1]:
        # traced per-lane basis (the serve operand path): the identical
        # per-lane expression under plain vmap
        hi = jax.lax.Precision.HIGHEST

        def g(Tb, yb, bb):
            f = lambda v: yb - jnp.matmul(Tb, v, precision=hi)  # noqa: E731
            for _ in range(bb.ndim - 1):
                f = jax.vmap(f)
            return f(bb)

        out = jax.vmap(g, in_axes=tuple(0 if bt else None
                                        for bt in in_batched))(T, y, b)
        return out, True
    if not in_batched[2]:
        b = jnp.broadcast_to(b, (axis_size,) + b.shape)
    return residual_matvec(T, y, b), True


def precond_solve_quad(L, inv_sqrt_d, rhs):
    """Given the factorization from :func:`precond_cholesky`, return
    ``(Sigma^-1 rhs, rhs^T Sigma^-1 rhs)``."""
    r = rhs * inv_sqrt_d
    u = solve_triangular(L, r, lower=True)
    quad = jnp.sum(u * u, axis=-1)
    v = solve_triangular(L, u, lower=True, trans="T")
    return v * inv_sqrt_d, quad


@custom_vmap
def masked_chisq(xs, counts):
    """``0.5 * sum_{j < counts} xs[..., j]^2`` — the exact chi-square
    construction behind the fast alpha draw (``Gamma(k/2) = 0.5 *
    chi^2_k``, backends/jax_backend.py). Not linear algebra, but it
    shares the native kernel family's dispatch: the jnp formulation
    materializes the mask and the squared array before reducing, the
    FFI kernel is one fused pass per row. FORCED (``GST_NCHOL=1``)
    only: the measured A/B on the graded host has XLA's fused
    mask-square-sum already at memory bandwidth (2.1 ms vs the
    kernel's 2.8 ms at the (1024, 130, 31) flagship shape,
    artifacts/cpu_microbench_r07.json — the FFI boundary pays an extra
    buffer round trip the fusion avoids), so ``auto`` keeps the jnp
    path; the kernel is the A/B arm and the escape hatch for hosts
    whose XLA reduction underperforms. The jnp fallback is the exact
    expression the backend used before, so the off-path is unchanged
    math."""
    kmax = xs.shape[-1]
    n_on, n_forced = _nchol_mode()
    rows_shape = xs.shape[:-1] + (1, 1)  # reuse the matrix batch guard
    if (n_on and n_forced and xs.dtype in (jnp.float32, jnp.float64)
            and xs.dtype == counts.dtype
            and _nchol_ok(rows_shape, xs.dtype, n_forced)):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("chisq", "nchol", xs.shape)
        return nffi.chisq(xs, counts)
    _note_impl("chisq", "jnp", xs.shape)
    live = jnp.arange(kmax, dtype=xs.dtype) < counts[..., None]
    return 0.5 * jnp.sum(jnp.where(live, xs * xs, 0.0), axis=-1)


@masked_chisq.def_vmap
def _masked_chisq_vmap(axis_size, in_batched, xs, counts):
    if not in_batched[0]:
        xs = jnp.broadcast_to(xs, (axis_size,) + xs.shape)
    if not in_batched[1]:
        counts = jnp.broadcast_to(counts, (axis_size,) + counts.shape)
    return masked_chisq(xs, counts), True


def _native_draws_ok() -> bool:
    """Trace-time availability of the native draw kernels (CPU custom
    calls): platform + library probe. The WHETHER of a draw arm
    (gamma v2, fractional theta) is the backend's gate; this only
    selects native-vs-jnp-twin for an already-chosen arm — both
    compute the same distribution."""
    return jax.default_backend() == "cpu" and _nchol_ready()


@functools.lru_cache(maxsize=None)
def _gamma_v2_dispatcher(jmax: int):
    """Per-pool-width dispatcher behind :func:`masked_gamma_v2`."""

    @custom_vmap
    def gd(keys, counts):
        batch = int(np.prod(counts.shape[:-1])) if counts.ndim > 1 else 1
        if (_native_draws_ok() and counts.ndim >= 2 and batch >= 1
                and counts.dtype in (jnp.float32, jnp.float64)):
            from gibbs_student_t_tpu.native import ffi as nffi

            _note_impl("gamma_v2", "nchol", counts.shape)
            return nffi.gamma_v2(keys.reshape(-1, 2),
                                 counts.reshape(batch, -1),
                                 jmax).reshape(counts.shape)
        from gibbs_student_t_tpu.ops import rng as _rng

        _note_impl("gamma_v2", "jnp_philox", counts.shape)
        f = lambda k2, c: _rng.gamma_halfint_v2(k2, c, jmax)  # noqa: E731
        for _ in range(counts.ndim - 1):
            f = jax.vmap(f)
        return f(keys, counts)

    @gd.def_vmap
    def _gd_vmap(axis_size, in_batched, keys, counts):
        if not in_batched[0]:
            keys = jnp.broadcast_to(keys, (axis_size,) + keys.shape)
        if not in_batched[1]:
            counts = jnp.broadcast_to(counts,
                                      (axis_size,) + counts.shape)
        return gd(keys, counts), True

    return gd


def masked_gamma_v2(keys, counts, jmax: int):
    """``Gamma(k/2)`` draws for integer ``k = counts`` — the
    GST_FAST_GAMMA **v2** construction (``-log prod U`` plus one
    odd-parity Box-Muller plane, counter-based philox randomness;
    distribution-exact like the chi-square arm but ~3x fewer
    transcendental bytes). ``keys (..., 2)`` uint32 PRNG key words per
    chain, ``counts (..., n)``; the native kernel generates its
    uniforms in-kernel, the jnp twin (ops/rng.py) draws the identical
    philox streams — the two arms agree to transcendental ulp."""
    return _gamma_v2_dispatcher(int(jmax))(keys, counts)


@custom_vmap
def beta_fractional(keys, a, b):
    """``theta ~ Beta(a, b)`` for per-chain FRACTIONAL pseudo-counts —
    the flagship beta prior that the half-integer ``GST_FAST_BETA``
    construction measured out. Native arm: two in-kernel
    Marsaglia-Tsang gammas per chain (one custom call for the whole
    chain batch); fallback: ``random.beta`` on the same key (identical
    law, different stream — the dispatcher contract of every draw
    arm). ``keys (..., 2)`` uint32 key words, ``a``/``b`` (...)."""
    from jax import random

    if (_native_draws_ok() and a.ndim >= 1
            and a.dtype in (jnp.float32, jnp.float64)):
        from gibbs_student_t_tpu.native import ffi as nffi

        _note_impl("beta_frac", "nchol", a.shape)
        return nffi.beta_frac(keys.reshape(-1, 2), a.reshape(-1),
                              b.reshape(-1)).reshape(a.shape)
    _note_impl("beta_frac", "random_beta", a.shape)

    def one(k2, av, bv):
        return random.beta(random.wrap_key_data(k2), av, bv,
                           dtype=a.dtype)

    f = one
    for _ in range(a.ndim):
        f = jax.vmap(f)
    return f(keys, a, b)


@beta_fractional.def_vmap
def _beta_fractional_vmap(axis_size, in_batched, keys, a, b):
    if not in_batched[0]:
        keys = jnp.broadcast_to(keys, (axis_size,) + keys.shape)
    if not in_batched[1]:
        a = jnp.broadcast_to(a, (axis_size,) + a.shape)
    if not in_batched[2]:
        b = jnp.broadcast_to(b, (axis_size,) + b.shape)
    return beta_fractional(keys, a, b), True


def _fused_stages_jnp(hyp_idx, jitter, jitters, A, Bm, C, rs, rv, x,
                      dx, logu, xi, base0, K, sel, phist, specs,
                      hyper_core=None):
    """The per-stage composition — the megastage's gates-off-
    equivalent graph, parity oracle and degradation target (shared by
    the single-model and lanes dispatchers; the constant operands may
    be rank-2 shared arrays or carry a leading lane axis — the
    align_consts batch-generic contract of hyper_mh_loop_xla). The
    b-draw evaluates phi through the same affine K rows the hyper
    block (and the kernel) uses, so fused on/off agree to rounding."""
    from gibbs_student_t_tpu.ops.pallas_hyper import (
        _phi_eval_xla,
        hyper_mh_loop_xla,
    )
    from gibbs_student_t_tpu.ops.pallas_white import align_consts

    ns = A.shape[-1]
    (S0, rt, quad_s, logdetA, La, isd_a, U_B, u_s) = _schur_jnp(
        A, Bm, C, rs, rv, jitter)
    phist_a = align_consts(jnp.asarray(phist, x.dtype), x.ndim - 1,
                           core_dims=1)
    dS0 = jnp.diagonal(S0, axis1=-2, axis2=-1) + phist_a
    base = base0 + 0.5 * (quad_s - logdetA)
    # ``hyper_core`` swaps the MH-block stage only (the Pallas lanes
    # arm passes a group-reduced kernel closure); everything around it
    # — Schur, phi re-eval, draws — is this graph either way
    if hyper_core is None:
        xh, acc = hyper_mh_loop_xla(x, S0, dS0, rt, base, dx, logu, K,
                                    sel, specs, hyp_idx, jitter)
    else:
        xh, acc = hyper_core(x, S0, dS0, rt, base, dx, logu, K, sel,
                             specs)
    Ka = align_consts(jnp.asarray(K, x.dtype), x.ndim - 1)
    sela = align_consts(jnp.asarray(sel, x.dtype), x.ndim - 1,
                        core_dims=1)
    phiv, _ = _phi_eval_xla(xh, Ka, sela, hyp_idx)
    eye = jnp.eye(S0.shape[-1], dtype=S0.dtype)
    Sv = S0 + eye * (phiv + phist_a)[..., None, :]
    y_v, isd_v, _ = robust_precond_draw(Sv, rt, xi[..., ns:],
                                        jitters=jitters)
    hi = jax.lax.Precision.HIGHEST
    wty = jnp.matmul(U_B, (isd_v * y_v)[..., None],
                     precision=hi)[..., 0]
    y_s = backward_solve(La, u_s + xi[..., :ns] - wty)
    return xh, acc, y_v, isd_v, y_s, isd_a


@functools.lru_cache(maxsize=None)
def _fused_hyper_dispatcher(hyp_idx: tuple, jitter: float,
                            jitters: tuple):
    """Dispatcher behind :func:`fused_hyper_draws` (the static phi
    structure, MH jitter and escalation schedule are trace-static)."""

    _stages_jnp = functools.partial(_fused_stages_jnp, hyp_idx, jitter,
                                    jitters)

    @custom_vmap
    def fh(A, Bm, C, rs, rv, x, dx, logu, xi, base0, K, sel, phist,
           specs):
        # the WHETHER of the megastage is the backend's construction-
        # time GST_FUSE_STAGES resolution; here only availability and
        # shape caps pick native vs the per-stage jnp composition
        nk = len(hyp_idx)
        if (_native_draws_ok() and A.ndim >= 3 and K.ndim == 2
                and _nchol_ok(A.shape, A.dtype, False)
                and C.shape[-1] <= MAX_VCHOL_DIM
                and x.shape[-1] <= 64 and nk <= 16):
            from gibbs_student_t_tpu.native import ffi as nffi

            _note_impl("fused_hyper", "nchol", C.shape)
            dt = x.dtype
            return nffi.fused_hyper(
                A, Bm, C, rs, rv, x, dx, logu, xi, base0,
                jnp.asarray(K, dt), jnp.asarray(sel, dt),
                jnp.asarray(phist, dt), jnp.asarray(specs, dt),
                hyp_idx, jitter, jitters)
        _note_impl("fused_hyper", "stages", C.shape)
        return _stages_jnp(A, Bm, C, rs, rv, x, dx, logu, xi, base0,
                           K, sel, phist, specs)

    @fh.def_vmap
    def _fh_vmap(axis_size, in_batched, *args):
        # the trailing 4 operands (K, sel, phist, specs) are per-model
        # constants: a chain-level vmap maps only the data operands and
        # the constants stay shared (the consts_batch_vmap discipline)
        data, consts = args[:10], args[10:]
        data = tuple(
            a if bt else jnp.broadcast_to(a, (axis_size,) + a.shape)
            for a, bt in zip(data, in_batched[:10]))
        if any(in_batched[10:]):
            consts = tuple(
                a if bt else jnp.broadcast_to(a, (axis_size,) + a.shape)
                for a, bt in zip(consts, in_batched[10:]))
        return fh(*data, *consts), (True,) * 6

    return fh


@functools.lru_cache(maxsize=None)
def _fused_hyper_lanes_dispatcher(hyp_idx: tuple, jitter: float,
                                  jitters: tuple):
    """Lanes twin of :func:`_fused_hyper_dispatcher` — the serve slot
    pool's megastage, with the model constants PER LANE (call-time
    operands instead of trace literals) plus the tile-uniform group-id
    operand (native/ffi.py ``fused_hyper_lanes``). The fallback is the
    same per-stage jnp composition with the constants batched, which is
    exactly the graph the grouped traced-consts path emits."""

    _stages_jnp = functools.partial(_fused_stages_jnp, hyp_idx, jitter,
                                    jitters)

    @custom_vmap
    def fh(A, Bm, C, rs, rv, x, dx, logu, xi, base0, K, sel, phist,
           specs, gid):
        nk = len(hyp_idx)
        if (_native_draws_ok() and A.ndim == 3 and K.ndim == 3
                and gid.ndim == 1
                and _nchol_ok(A.shape, A.dtype, False)
                and C.shape[-1] <= MAX_VCHOL_DIM
                and x.shape[-1] <= 64 and nk <= 16):
            from gibbs_student_t_tpu.native import ffi as nffi

            _note_impl("fused_hyper_lanes", "nchol", C.shape)
            dt = x.dtype
            return tuple(nffi.fused_hyper_lanes(
                A, Bm, C, rs, rv, x, dx, logu, xi, base0,
                jnp.asarray(K, dt), jnp.asarray(sel, dt),
                jnp.asarray(phist, dt), jnp.asarray(specs, dt), gid,
                hyp_idx, jitter, jitters))
        from gibbs_student_t_tpu.ops.pallas_hyper import (
            MAX_PALLAS_V as _MAX_PV,
            _pallas_hyper_mode,
            hyper_mh_fused,
        )
        from gibbs_student_t_tpu.ops.pallas_util import (
            HAVE_PLTPU as _have_pltpu,
        )

        p_on, p_interp, p_forced = _pallas_hyper_mode()
        B = x.shape[0] if x.ndim else 0
        if (p_on and _have_pltpu and A.ndim == 3 and K.ndim == 3
                and gid.ndim == 1 and x.dtype == jnp.float32
                and C.shape[-1] <= _MAX_PV
                and B % _LANES_GROUP == 0 and B
                and (p_forced or B >= _PALLAS_MIN_BATCH)):
            # Pallas lanes arm: the per-stage composition verbatim with
            # only the MH-block stage swapped for the grouped TPU
            # kernel — the tile-uniform gid contract makes the per-lane
            # consts constant within every aligned 16-lane tile, so one
            # stride-sliced consts row per group feeds the grouped form
            _note_impl("fused_hyper_lanes", "pallas", C.shape)

            def _pallas_core(xc, S0c, dS0c, rtc, basec, dxc, loguc,
                             Kc, selc, specsc):
                p = xc.shape[-1]
                v = S0c.shape[-1]
                S = dxc.shape[-2]
                Gn = B // _LANES_GROUP
                dt = xc.dtype
                xf, acc = hyper_mh_fused(
                    xc.reshape(Gn, _LANES_GROUP, p),
                    S0c.reshape(Gn, _LANES_GROUP, v, v),
                    dS0c.reshape(Gn, _LANES_GROUP, v),
                    rtc.reshape(Gn, _LANES_GROUP, v),
                    basec.reshape(Gn, _LANES_GROUP),
                    dxc.reshape(Gn, _LANES_GROUP, S, p),
                    loguc.reshape(Gn, _LANES_GROUP, S),
                    jnp.asarray(Kc, dt)[::_LANES_GROUP],
                    jnp.asarray(selc, dt)[::_LANES_GROUP],
                    jnp.asarray(specsc, dt)[::_LANES_GROUP],
                    hyp_idx, jitter, interpret=p_interp)
                return xf.reshape(B, p), acc.reshape(B)

            return _stages_jnp(A, Bm, C, rs, rv, x, dx, logu, xi,
                               base0, K, sel, phist, specs,
                               hyper_core=_pallas_core)
        _note_impl("fused_hyper_lanes", "stages", C.shape)
        return _stages_jnp(A, Bm, C, rs, rv, x, dx, logu, xi, base0,
                           K, sel, phist, specs)

    @fh.def_vmap
    def _fh_vmap(axis_size, in_batched, *args):
        # the serve vmap maps EVERY operand (state, draws, per-lane
        # consts and gid alike); broadcast any stragglers and re-enter
        # so the primal sees the full lane batch
        out = tuple(
            a if bt else jnp.broadcast_to(a, (axis_size,) + a.shape)
            for a, bt in zip(args, in_batched))
        return fh(*out), (True,) * 6

    return fh


def fused_hyper_draws(A, Bm, C, rs, rv, x, dx, logu, xi, base0, K, sel,
                      phist, specs, hyp_idx, jitter, jitters, gid=None):
    """``(x, acc_hyper, y_v, isd_v, y_s, isd_a)`` — the hyper+draws
    megastage (``GST_FUSE_STAGES``): Schur pre-elimination, the whole
    hyper MH block over precomputed draws, and the coefficient draw's
    robust v-block factorization + block-assembled backward solves as
    ONE multi-stage FFI dispatch. The caller scatters ``b[s] = y_s *
    isd_a``, ``b[v] = y_v * isd_v`` (backends/jax_backend.py). The
    fallback is the per-stage jnp composition with identical operands
    and randomness — the parity oracle, and what a
    forced-but-unavailable gate silently degrades to.

    With ``gid`` (the serve slot pool's per-lane group ids), the model
    constants ``K/sel/phist/specs`` are PER-LANE call-time operands —
    uniform within each aligned SIMD tile — and the call routes through
    the lanes kernel; a pool whose lanes share one model is bitwise
    identical to the single-model megastage (same tile functions)."""
    hyp_idx = tuple(int(i) for i in hyp_idx)
    jitters = tuple(float(j) for j in jitters)
    if gid is not None:
        return _fused_hyper_lanes_dispatcher(
            hyp_idx, float(jitter), jitters)(
            A, Bm, C, rs, rv, x, dx, logu, xi, base0, K, sel, phist,
            specs, gid)
    return _fused_hyper_dispatcher(hyp_idx, float(jitter), jitters)(
        A, Bm, C, rs, rv, x, dx, logu, xi, base0, K, sel, phist, specs)


def gaussian_draw(L, inv_sqrt_d, mean, xi):
    """Draw ``b ~ N(mean, Sigma^-1)`` from a standard-normal ``xi`` — the
    conditional coefficient draw of reference gibbs.py:180 with covariance
    ``Sigma^-1``: fluctuation = D^-1/2 L^-T xi."""
    fluct = solve_triangular(L, xi, lower=True, trans="T") * inv_sqrt_d
    return mean + fluct
