"""Pallas TPU kernel: the whole hyper-parameter MH block in one launch.

The reference's red/hyper update is 10 sequential Metropolis steps on the
``b``-marginalized likelihood (reference gibbs.py:80-111, 288-329), each
paying an m x m factorization. The production path already runs the
factorizations through the lane-batched Pallas Cholesky
(ops/pallas_chol.py), Schur-reduced to the phi-varying columns
(ops/linalg.py schur_eliminate) — but every one of the 10 steps still
pays XLA-level glue *around* its factorization: the (chains, v, v)
matrix is re-read from HBM, diag-added, equilibrated, and re-laid-out to
the kernel's (col, row, chain) form, ~4 full passes over a ~15 MB buffer
per step (docs/PERFORMANCE.md roofline: the hyper block's non-
factorization 2/3).

This kernel hoists all of that out of the step loop: the Schur block
``S0`` crosses HBM once per sweep (already in lane layout), and the
entire MH block — per-proposal prior-precision evaluation, equilibrated
Cholesky with fused forward solve, prior, masked accept — runs on-chip:

- **phi is two broadcast rows, not a model walk.** Every varying phi
  block's log-precision is affine in the sampled hypers:
  ``logphi_col = K0_col + sum_k K_k_col * x[i_k]`` (powerlaw in
  log10_A/gamma, ecorr in each log10_ecorr — models/pta.py
  phiinv_logdet). The K rows are trace-time constants; a proposal's
  phi eval is ``nk`` fused multiply-adds.
- **the equilibrated matrix is never materialized in HBM.** With
  ``d = diag(S0) + phiinv``, the preconditioned matrix is
  ``S' = isd_i isd_j S0`` off-diagonal and exactly ``1 + jitter`` on
  the diagonal (ops/linalg.py ``_equilibrate`` algebra), built directly
  into a VMEM scratch buffer each step.
- **same recurrence as ops/pallas_chol.py**, statically unrolled over
  the v real columns with the forward solve fused (only
  ``logdet``/``quad`` leave the recurrence — L is never stored); the
  10-step MH loop is an in-kernel ``fori_loop`` so the program size
  stays one factorization, not ten.
- **failure semantics unchanged**: a non-PD proposal makes ``rsqrt``
  produce NaN, the log-likelihood goes non-finite, and
  ``NaN > logu = False`` rejects — the reference's try/except -> -inf
  (gibbs.py:320-324), per lane.

Layout is the Cholesky kernel's: matrix column index outermost, row on
sublanes, chains on lanes; per-chain scalars are (1, chains) rows, and
per-step draws index on the (untiled) leading axis. Constants that the
(row, chain) planes consume are pre-broadcast over the chain axis
outside the kernel (a few hundred KB of HBM) — cheaper than fighting
width-1 lane slices, which Mosaic handles poorly.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.custom_batching import custom_vmap
from jax.experimental import pallas as pl

from gibbs_student_t_tpu.models.pta import (
    ConstBlock,
    EcorrBlock,
    ImproperBlock,
    PowerlawBlock,
)
from gibbs_student_t_tpu.ops.pallas_util import (
    HAVE_PLTPU as _HAVE_PLTPU,
    MIN_BATCH as _MIN_BATCH,
    int_from_env,
    mode_from_env,
    note_kernel_build,
    pad_chains_edge,
    pltpu,
    round_up as _round_up,
    tpu_compiler_params,
    vmem_spec as _spec,
)
from gibbs_student_t_tpu.ops.pallas_white import _lnprior_cols

LN10 = float(np.log(10.0))
_LOG_2PI = float(np.log(2.0 * np.pi))

# Past this column count the (vp, vp, 128) working set — the S0 block
# double-buffered across grid steps by the pipeline, plus the scratch
# factor buffer — stops fitting in the ~16 MB VMEM at the MINIMUM legal
# lane tile: the chain axis lives on lanes, so Mosaic requires the tile
# be a multiple of 128 (or the whole array); it cannot shrink below 128
# the way a sublane tile can. 3 * 80^2 * 128 * 4 B ~= 9.8 MB leaves
# headroom; larger models fall back to the XLA loop (still reaching the
# Pallas *Cholesky* through the closure path, so nothing is lost).
MAX_PALLAS_V = 80


class HyperConsts(NamedTuple):
    """Trace-time constants of one model's marginalized likelihood over
    a column subset ``cols`` (the Schur varying block, or all m).

    ``K``: (1 + nk, v) — row 0 the constant part of ``logphi`` on the
    varying columns, row 1+k the coefficient of ``x[hyp_idx[k]]``.
    ``hyp_idx``: the x-indices the K rows multiply.
    ``phi_sel``: (v,) 1.0 where the column's phi varies with x (its
    phiinv is evaluated in-kernel), 0.0 where static or improper.
    ``phiinv_static``: (v,) constant phiinv of static-phi columns in the
    subset (zero for improper columns). On the Schur path this is zero
    for every per-block static/varying split, but NOT necessarily for a
    mixed ecorr block (const and sampled groups in one block land whole
    in the varying subset) — callers must always add it to the diagonal.
    ``logdet_phi_static``: scalar — sum of logphi over ALL static-phi
    columns of the model (inside or outside the subset; the eliminated
    Schur block's phi lives here).
    ``specs``: (3, p) prior table rows (kind, a, b).
    """

    K: np.ndarray
    hyp_idx: Tuple[int, ...]
    phi_sel: np.ndarray
    phiinv_static: np.ndarray
    logdet_phi_static: float
    specs: np.ndarray


def build_hyper_consts(ma, cols) -> HyperConsts:
    """Decompose ``models.pta.phiinv_logdet`` into affine-in-x form.

    For every phi block, ``logphi_col = const_col + sum_k coef_col *
    x[idx_k]`` exactly (the powerlaw and ecorr formulas are
    log-linear in the sampled hypers); improper blocks carry no phi at
    all (zero phiinv, zero logdet — models/signals.ImproperPhi).
    """
    from gibbs_student_t_tpu.models.signals import FYR

    m = ma.m
    s2 = float(ma.time_scale) ** 2
    const_col = np.zeros(m)
    has_phi = np.zeros(m, bool)
    varying = np.zeros(m, bool)
    coefs: dict[int, np.ndarray] = {}

    def coef_row(idx):
        if idx not in coefs:
            coefs[idx] = np.zeros(m)
        return coefs[idx]

    for blk in ma.phi_blocks:
        sl = slice(blk.start, blk.stop)
        if isinstance(blk, ImproperBlock):
            continue
        if isinstance(blk, ConstBlock):
            const_col[sl] = np.log(np.asarray(blk.phi, np.float64))
            has_phi[sl] = True
            continue
        if isinstance(blk, PowerlawBlock):
            freqs = np.asarray(blk.freqs, np.float64)
            const_col[sl] = (-np.log(12.0 * np.pi ** 2)
                             - 3.0 * np.log(FYR)
                             + np.log(float(blk.df)) + np.log(s2))
            gam_vec = np.log(FYR) - np.log(freqs)
            if blk.idx_log10A >= 0:
                coef_row(blk.idx_log10A)[sl] += 2.0 * LN10
                varying[sl] = True
            else:
                const_col[sl] += 2.0 * LN10 * float(blk.const_log10A)
            if blk.idx_gamma >= 0:
                coef_row(blk.idx_gamma)[sl] += gam_vec
                varying[sl] = True
            else:
                const_col[sl] += float(blk.const_gamma) * gam_vec
            has_phi[sl] = True
            continue
        if isinstance(blk, EcorrBlock):
            group = np.asarray(blk.col_group)
            const_col[sl] += np.log(s2)
            for g, idx in enumerate(blk.idx):
                gcols = blk.start + np.flatnonzero(group == g)
                if idx >= 0:
                    coef_row(idx)[gcols] += 2.0 * LN10
                    varying[gcols] = True
                else:
                    const_col[gcols] += 2.0 * LN10 * float(blk.const[g])
            has_phi[sl] = True
            continue
        raise TypeError(f"unknown phi block {type(blk)}")  # pragma: no cover

    cols = np.asarray(cols, int)
    hyp_idx = tuple(sorted(coefs))
    K = np.zeros((1 + len(hyp_idx), len(cols)))
    K[0] = np.where(varying[cols], const_col[cols], 0.0)
    for k, idx in enumerate(hyp_idx):
        K[1 + k] = coefs[idx][cols]
    static = has_phi & ~varying
    phiinv_static = np.where(static[cols], np.exp(-const_col[cols]), 0.0)
    logdet_static = float(const_col[static].sum())
    specs = np.asarray(ma.prior_specs, np.float32)[:, :3].T.copy()
    kinds = set(np.unique(specs[0].astype(int)))
    if not kinds <= {0, 1, 2}:
        # mirror of pallas_white.build_white_consts's guard: the fused
        # prior only implements the lnprior_specs kinds known today
        raise ValueError(f"unsupported prior kinds for fused MH: {kinds}")
    return HyperConsts(K=K.astype(np.float32), hyp_idx=hyp_idx,
                       phi_sel=varying[cols].astype(np.float32),
                       phiinv_static=phiinv_static.astype(np.float32),
                       logdet_phi_static=logdet_static, specs=specs)


# ---------------------------------------------------------------------------
# shared step math (XLA path; the kernel mirrors it lane-padded)
# ---------------------------------------------------------------------------


def _phi_eval_xla(q, K, sel, hyp_idx):
    """(phiinv_varying, sum_logphi_varying) on (…, v) operands.
    ``K (…, 1+nk, v)`` / ``sel (…, v)`` pre-aligned via
    ``pallas_white.align_consts`` so leading group axes broadcast."""
    lph = K[..., 0, :]
    for k, idx in enumerate(hyp_idx):
        lph = lph + K[..., 1 + k, :] * q[..., idx:idx + 1]
    phiinv = sel * jnp.exp(-lph)
    return phiinv, jnp.sum(sel * lph, axis=-1)


def _lnprior_sum_xla(q, sp):
    return jnp.sum(_lnprior_cols(q, sp[..., 0, :], sp[..., 1, :],
                                 sp[..., 2, :]), axis=-1)


def hyper_mh_loop_xla(x, S0, dS0, rt, base, dx, logu, K, sel, specs,
                      hyp_idx, jitter: float):
    """The full hyper MH block over precomputed draws, plain XLA — the
    non-Pallas dispatch target. Batch-generic. ``S0 (…, v, v)`` is the
    proposal-independent matrix block (Schur complement, or TNT), ``dS0``
    its diagonal plus any static phiinv, ``base`` the per-chain constant
    part of the log-likelihood (white const + Schur quad/logdet + static
    phi logdet). ``K (…, 1+nk, v)``, ``sel (…, v)``, ``specs (…, 3, p)``
    are per-model constants — rank 2 (1 for sel) for one frozen model,
    or with leading group axes matching x's leading batch axes (the
    ensemble's traced per-pulsar constants)."""
    from gibbs_student_t_tpu.ops.pallas_white import align_consts

    xb = x.ndim - 1
    K = align_consts(jnp.asarray(K, x.dtype), xb)
    sel = align_consts(jnp.asarray(sel, x.dtype), xb, core_dims=1)
    specs = align_consts(jnp.asarray(specs, x.dtype), xb)
    v = S0.shape[-1]
    eye = jnp.eye(v, dtype=S0.dtype)

    def ll_lp(q):
        phiinv, sum_lph = _phi_eval_xla(q, K, sel, hyp_idx)
        d = dS0 + phiinv
        isd = 1.0 / jnp.sqrt(d)
        Ssc = S0 * isd[..., :, None] * isd[..., None, :]
        Ssc = jnp.where(eye == 1.0, 1.0 + jitter, Ssc)
        L = jnp.linalg.cholesky(Ssc)
        logdet_S = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)
        from jax.scipy.linalg import solve_triangular

        u = solve_triangular(L, (rt * isd)[..., None], lower=True)[..., 0]
        quad = jnp.sum(u * u, axis=-1)
        ll = base + 0.5 * (quad - (logdet_S + jnp.sum(jnp.log(d), axis=-1))
                           - sum_lph)
        ll = jnp.where(jnp.isfinite(ll), ll, -jnp.inf)
        return ll, _lnprior_sum_xla(q, specs)

    nsteps = dx.shape[-2]
    ll0, lp0 = ll_lp(x)
    acc0 = jnp.zeros(ll0.shape, x.dtype)

    def body(i, carry):
        x, ll0, lp0, acc = carry
        q = x + lax.dynamic_index_in_dim(dx, i, axis=dx.ndim - 2,
                                         keepdims=False)
        ll1, lp1 = ll_lp(q)
        lu = lax.dynamic_index_in_dim(logu, i, axis=logu.ndim - 1,
                                      keepdims=False)
        accept = (ll1 + lp1) - (ll0 + lp0) > lu
        am = accept[..., None]
        return (jnp.where(am, q, x), jnp.where(accept, ll1, ll0),
                jnp.where(accept, lp1, lp0), acc + accept)

    x, _, _, acc = lax.fori_loop(0, nsteps, body, (x, ll0, lp0, acc0))
    return x, acc / nsteps


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _hyper_kernel(S0_ref, dS0_ref, rt_ref, x_ref, dx_ref, lu_ref, K_ref,
                  sel_ref, sp_ref, base_ref, xo_ref, ao_ref, A_ref, *,
                  nsteps: int, v: int, p: int,
                  hyp_idx: Tuple[int, ...], jitter: float):
    """One chain tile. Layouts: ``S0/A (vp, vp, lanes)`` indexed
    [matrix column, matrix row, chain]; ``dS0/rt/K*/sel (vp, lanes)``;
    ``x (pp, lanes)``; ``dx (nsteps, pp, lanes)``; ``lu (Sp, lanes)``;
    ``sp (4, pp, lanes)`` prior rows; ``base (1, lanes)``."""
    vp = S0_ref.shape[0]
    lanes = x_ref.shape[-1]
    rows2 = lax.broadcasted_iota(jnp.int32, (vp, 1), 0)
    rows3 = lax.broadcasted_iota(jnp.int32, (vp, 1, 1), 0)
    cols3 = lax.broadcasted_iota(jnp.int32, (1, vp, 1), 1)
    prow = lax.broadcasted_iota(jnp.int32, (x_ref.shape[0], 1), 0)
    vmask = rows2 < v
    pmask = prow < p
    kind = jnp.where(pmask, sp_ref[0], -1.0)
    a = sp_ref[1]
    b = sp_ref[2]
    base = base_ref[0:1, :]
    sel = sel_ref[:]
    dS0 = dS0_ref[:]
    rt = rt_ref[:]

    def ll_lp(q):
        # phi eval: affine logphi rows, then the masked exp
        lph = K_ref[0]
        for k, idx in enumerate(hyp_idx):
            lph = lph + K_ref[1 + k] * q[idx:idx + 1, :]
        phiinv = sel * jnp.exp(-lph)
        sum_lph = jnp.sum(sel * lph, axis=0, keepdims=True)
        d = dS0 + phiinv
        isd = lax.rsqrt(d)
        sum_logd = jnp.sum(jnp.where(vmask, jnp.log(d), 0.0), axis=0,
                           keepdims=True)
        # equilibrated matrix straight into VMEM scratch: unit diagonal
        # by construction, so the diagonal is written as 1 + jitter
        A_ref[:] = jnp.where(
            rows3 == cols3, 1.0 + jitter,
            S0_ref[:] * isd[:, None, :] * isd[None, :, :])
        rp = rt * isd
        racc = jnp.zeros((vp, lanes), jnp.float32)
        ld = jnp.zeros((1, lanes), jnp.float32)
        quad = jnp.zeros((1, lanes), jnp.float32)
        for j in range(v):
            c = A_ref[j]                          # (vp, lanes)
            piv = c[j:j + 1, :]
            inv = lax.rsqrt(piv)
            ld += jnp.log(piv)
            col = jnp.where(rows2 >= j, c * inv, 0.0)
            uj = (rp[j:j + 1, :] - racc[j:j + 1, :]) * inv
            racc = racc + col * uj
            quad += uj * uj
            upd = col[:, None, :] * col[None, :, :]
            A_ref[:] = A_ref[:] - jnp.where(rows3 > j, upd, 0.0)
        ll = base + 0.5 * (quad - (ld + sum_logd) - sum_lph)
        ll = jnp.where(jnp.isfinite(ll), ll, -jnp.inf)
        # prior over the full parameter vector (reference gibbs.py:99)
        lp_el = jnp.where(pmask, _lnprior_cols(q, kind, a, b), 0.0)
        lp = jnp.sum(lp_el, axis=0, keepdims=True)
        return ll, lp

    x = x_ref[:]
    ll0, lp0 = ll_lp(x)

    def step(j, carry):
        x, ll0, lp0, acc = carry
        q = x + dx_ref[j]
        ll1, lp1 = ll_lp(q)
        lu = lu_ref[j]                            # (1, lanes)
        am = (ll1 + lp1) - (ll0 + lp0) > lu
        return (jnp.where(am, q, x), jnp.where(am, ll1, ll0),
                jnp.where(am, lp1, lp0), acc + am.astype(jnp.float32))

    x, _, _, acc = lax.fori_loop(
        0, nsteps, step,
        (x, ll0, lp0, jnp.zeros((1, lanes), jnp.float32)))
    xo_ref[:] = x
    ao_ref[:] = jnp.broadcast_to(acc, ao_ref.shape)


def hyper_mh_fused(x, S0, dS0, rt, base, dx, logu, K, sel, specs,
                   hyp_idx, jitter: float, chain_tile: int | None = None,
                   interpret: bool = False):
    """``(x_new, acc_rate)`` for the whole hyper MH block, one launch.

    GROUPED form: ``x (G, C, p)``, ``S0 (G, C, v, v)``, ``dS0/rt
    (G, C, v)``, ``base (G, C)``, ``dx (G, C, S, p)``, ``logu
    (G, C, S)``, with PER-GROUP constants ``K (G, 1+nk, v)``,
    ``sel (G, v)``, ``specs (G, 3, p)`` (a single frozen model passes
    G == 1). The chain axis is the LANE axis and the constants are
    pre-broadcast per lane anyway, so the grouped call simply repeats
    each group's constant planes over its own chains — chain tiles may
    straddle groups freely. float32 only.
    """
    if x.dtype != jnp.float32:
        raise ValueError(f"pallas hyper kernel is float32-only, got {x.dtype}")
    G, C, p = x.shape
    v = S0.shape[-1]
    S = dx.shape[-2]
    vp = _round_up(v, 8)
    pp = _round_up(p, 8)

    def gflat(arr):  # (G, C, ...) -> (G*C, ...), group-major chains
        return arr.reshape((G * C,) + arr.shape[2:])

    x, S0, dS0, rt, base, dx, logu = (
        gflat(a) for a in (x, S0, dS0, rt, base, dx, logu))
    C_per = C
    C = G * C_per
    # GST_HYPER_TILE overrides for on-chip tuning (trace-time snapshot).
    # The chain axis is the LANE dimension, so the tile must be a
    # multiple of 128 — or the whole (padded) chain axis for small C;
    # it cannot be shrunk for VMEM the way a sublane tile can (the
    # MAX_PALLAS_V cap keeps the 128-lane working set inside VMEM), and
    # an explicit sub-128 ``chain_tile`` is therefore rounded UP to 128
    # (unlike the white kernel, whose sublane tile honors any multiple
    # of 8). Measured on-chip: 128 beats 256 at the flagship shape
    # (artifacts/fused_tune_r03.json).
    tile = chain_tile or int_from_env("GST_HYPER_TILE", 128)
    tile = max(128, _round_up(tile, 128))
    small = _round_up(C, 8)
    if small < tile:
        tile = small          # single whole-array block: legal for any size
    Cp = _round_up(C, tile)

    def padc(arr):
        return pad_chains_edge(arr, Cp)

    def padax(arr, axis, to):
        padn = to - arr.shape[axis]
        if not padn:
            return arr
        shape = list(arr.shape)
        shape[axis] = padn
        return jnp.concatenate(
            [arr, jnp.zeros(shape, arr.dtype)], axis=axis)

    # identity-pad the matrix block so padded columns factor to 1
    S0p = padax(padax(S0, -1, vp), -2, vp)
    if vp > v:
        eyepad = (jnp.arange(vp) >= v)
        S0p = S0p + jnp.where(
            eyepad[:, None] & eyepad[None, :],
            jnp.eye(vp, dtype=S0.dtype), 0.0)
    dS0p = padax(dS0, -1, vp) + (jnp.arange(vp) >= v).astype(S0.dtype)
    # lane layout: [col, row, chain] / [row, chain]
    S0t = jnp.transpose(padc(S0p), (2, 1, 0))
    dS0t = jnp.transpose(padc(dS0p), (1, 0))
    rtt = jnp.transpose(padc(padax(rt, -1, vp)), (1, 0))
    xt = jnp.transpose(padc(padax(x, -1, pp)), (1, 0))
    dxt = jnp.transpose(padc(padax(dx, -1, pp)), (1, 2, 0))  # (S, pp, Cp)
    # (S, 1, Cp): the step index lands on an untiled leading axis, so the
    # in-kernel fori_loop can dynamic-index it
    lut = jnp.transpose(padc(logu), (1, 0))[:, None, :]
    bt = padc(base)[None, :]                                 # (1, Cp)

    # constants pre-broadcast over the chain lane axis (cheap HBM, and it
    # sidesteps width-1 lane slicing in-kernel): each group's constant
    # planes repeat over its own chains, so a chain tile always reads
    # the right group's values regardless of group boundaries
    def lanes(arr):
        # (G, ..., k) -> (..., k, Cp): per-group chain repeat, edge-pad
        rep = jnp.repeat(jnp.moveaxis(arr, 0, -1), C_per, axis=-1)
        padn = Cp - rep.shape[-1]
        if padn:
            rep = jnp.concatenate(
                [rep, jnp.broadcast_to(rep[..., -1:],
                                       rep.shape[:-1] + (padn,))],
                axis=-1)
        return rep

    K = jnp.asarray(K, jnp.float32)
    nk = K.shape[1]
    Kt = lanes(padax(K, -1, vp))
    selt = lanes(padax(jnp.asarray(sel, jnp.float32), -1, vp))
    sp = jnp.asarray(specs, jnp.float32)
    sp = jnp.concatenate(
        [sp, jnp.zeros((G, 4 - sp.shape[1], sp.shape[2]), jnp.float32)],
        axis=1)
    spt = lanes(padax(sp, -1, pp))

    if not _HAVE_PLTPU:  # pragma: no cover - no-TPU-extension builds
        raise RuntimeError("pallas TPU extension unavailable")
    kwargs = tpu_compiler_params(("parallel",))
    scratch = [pltpu.VMEM((vp, vp, tile), jnp.float32)]
    kernel = functools.partial(_hyper_kernel, nsteps=S, v=v, p=p,
                               hyp_idx=hyp_idx, jitter=jitter)
    xo, ao = pl.pallas_call(
        kernel,
        grid=(Cp // tile,),
        in_specs=[
            _spec((vp, vp, tile), lambda g: (0, 0, g)),
            _spec((vp, tile), lambda g: (0, g)),
            _spec((vp, tile), lambda g: (0, g)),
            _spec((pp, tile), lambda g: (0, g)),
            _spec((S, pp, tile), lambda g: (0, 0, g)),
            _spec((S, 1, tile), lambda g: (0, 0, g)),
            _spec((nk, vp, tile), lambda g: (0, 0, g)),
            _spec((vp, tile), lambda g: (0, g)),
            _spec((4, pp, tile), lambda g: (0, 0, g)),
            _spec((1, tile), lambda g: (0, g)),
        ],
        out_specs=[
            _spec((pp, tile), lambda g: (0, g)),
            _spec((8, tile), lambda g: (0, g)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((pp, Cp), jnp.float32),
            jax.ShapeDtypeStruct((8, Cp), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(S0t, dS0t, rtt, xt, dxt, lut, Kt, selt, spt, bt)
    xf = jnp.transpose(xo, (1, 0))[:C, :p].reshape(G, C_per, p)
    return xf, (ao[0, :C] / S).reshape(G, C_per)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def _pallas_hyper_mode():
    """``(enabled, interpret, forced)`` from ``GST_PALLAS_HYPER`` — the
    shared trace-time snapshot semantics of ops/pallas_util.py
    ``mode_from_env`` (same contract as GST_PALLAS_CHOL/WHITE)."""
    return mode_from_env("GST_PALLAS_HYPER")


def make_hyper_block(hyp_idx: Tuple[int, ...], jitter: float):
    """Build the dispatched hyper-MH block for one model STRUCTURE —
    ``block(x, S0, dS0, rt, base, dx, logu, K, sel, specs) ->
    (x_new, acc_rate)``, custom-vmapped like
    ops/pallas_white.make_white_block: only ``HyperConsts.hyp_idx`` (the
    static affine-phi structure) is closed over; the constant arrays
    ``K``/``phi_sel``/``specs`` travel as call operands so ensembles can
    pass traced per-pulsar constants (leading group axis) through
    ``vmap``/``shard_map``."""
    from gibbs_student_t_tpu.ops.pallas_white import consts_batch_vmap

    note_kernel_build("pallas_hyper_mh", n_hyper=len(hyp_idx),
                      jitter=float(jitter),
                      mode=mode_from_env("GST_PALLAS_HYPER")[0])

    @custom_vmap
    def block(x, S0, dS0, rt, base, dx, logu, K, sel, specs):
        enabled, interp, forced = _pallas_hyper_mode()
        grouped = K.ndim == 3
        if grouped:
            batch = x.shape[:-1]
            B = int(np.prod(batch)) if batch else 1
            ok = (_HAVE_PLTPU and x.dtype == jnp.float32
                  and S0.shape[-1] <= MAX_PALLAS_V
                  and (forced or B >= _MIN_BATCH)
                  and x.ndim == 3 and K.shape[0] == x.shape[0])
            if enabled and ok:
                return hyper_mh_fused(x, S0, dS0, rt, base, dx, logu,
                                      K, sel, specs, hyp_idx, jitter,
                                      interpret=interp)
        elif K.ndim == 2:
            batch = x.shape[:-1]
            B = int(np.prod(batch)) if batch else 1
            ok = (_HAVE_PLTPU and x.dtype == jnp.float32
                  and S0.shape[-1] <= MAX_PALLAS_V
                  and (forced or B >= _MIN_BATCH) and x.ndim >= 2)
            if enabled and ok:
                p = x.shape[-1]
                v = S0.shape[-1]
                S = dx.shape[-2]
                xf, acc = hyper_mh_fused(
                    x.reshape(1, B, p), S0.reshape(1, B, v, v),
                    dS0.reshape(1, B, v), rt.reshape(1, B, v),
                    base.reshape(1, B), dx.reshape(1, B, S, p),
                    logu.reshape(1, B, S), K[None], sel[None],
                    specs[None], hyp_idx, jitter, interpret=interp)
                return xf.reshape(batch + (p,)), acc.reshape(batch)
        if K.ndim == 2 and x.ndim >= 2:
            # native CPU arm (GST_NHYPER): the whole block as one FFI
            # custom call with S0 tile-resident across all proposals —
            # the Pallas kernel's portable counterpart; the XLA loop
            # below is its oracle
            from gibbs_student_t_tpu.ops import linalg as _lin

            if _lin.nhyper_take(x.shape, x.dtype, x.shape[-1],
                                S0.shape[-1], len(hyp_idx)):
                from gibbs_student_t_tpu.native import ffi as nffi

                _lin._note_impl("hyper_mh", "nchol", S0.shape)
                B = int(np.prod(x.shape[:-1]))
                p = x.shape[-1]
                v = S0.shape[-1]
                S = dx.shape[-2]
                dt = x.dtype
                xf, acc = nffi.hyper_mh(
                    x.reshape(B, p), S0.reshape(B, v, v),
                    dS0.reshape(B, v), rt.reshape(B, v),
                    base.reshape(B), dx.reshape(B, S, p),
                    logu.reshape(B, S), jnp.asarray(K, dt),
                    jnp.asarray(sel, dt), jnp.asarray(specs, dt),
                    hyp_idx, jitter)
                return (xf.reshape(x.shape),
                        acc.reshape(x.shape[:-1]))
        return hyper_mh_loop_xla(x, S0, dS0, rt, base, dx, logu,
                                 K, sel, specs, hyp_idx, jitter)

    block.def_vmap(consts_batch_vmap(block, n_data=7))
    return block
