"""Real-dataset ingestion tests against the reference J1713+0747 files.

The reference's J1713+0747 is a DD binary (reference J1713+0747.par:13-19:
PB/T0/A1/OM/ECC + SINI/M2 Shapiro); round 1 had no binary timing model, so
these files were effectively unusable (VERDICT r1 missing #1). The DD
delays now live in ``data/timing_model.py``; these tests read the actual
reference paths and validate:

- the par parses the full DD block;
- the implemented DD delay is self-consistent to sub-ns: ideal TOAs built
  at the reference epochs round-trip through ``prefit_residuals`` at the
  numerical-noise level;
- the analytic binary design-matrix columns match finite differences of
  the implemented delay;
- the *reference pipeline's own* use of these files — epochs + par into
  ``fakepulsar`` + red noise + outliers (reference simulate_data.py:12-26,
  run_sims.py:41-51) — produces a dataset whose post-fit residuals sit at
  the white/red level (~us), i.e. BASELINE configs 1/3 are now runnable.

On the committed tim's *absolute* TOA values (investigated for VERDICT r1
task 4, full analysis in docs/J1713_INGESTION.md): they carry large smooth
barycentric structure beyond the DD delays — site ``AXIS`` TOAs idealized
by tempo2 include geocentric solar-system corrections that require a
planetary ephemeris (the par pins ``EPHEM DE414``) to reproduce below the
pulse period; no ephemeris exists in this offline image, and phase-
coherence analysis (the docs note) shows no single sub-50-ms term closes
the gap. The reference code itself never consumes those absolute values:
``simulate_data`` reads only the *epochs* (``pt.stoas[:]``) and
re-idealizes with ``fakepulsar`` — the path reproduced (and tested) here.
"""

import dataclasses
import glob
import os

import numpy as np
import pytest

from gibbs_student_t_tpu.data.par import Par, read_par
from gibbs_student_t_tpu.data.pulsar import Pulsar
from gibbs_student_t_tpu.data.simulate import FakePulsar, simulate_data
from gibbs_student_t_tpu.data.tim import read_tim
from gibbs_student_t_tpu.data.timing_model import (
    binary_delay,
    design_matrix,
    has_binary,
    prefit_residuals,
)

REF_PAR = "/root/reference/J1713+0747.par"
REF_TIM = "/root/reference/J1713+0747.tim"

pytestmark = pytest.mark.skipif(
    not (os.path.exists(REF_PAR) and os.path.exists(REF_TIM)),
    reason="reference J1713+0747 files not present")


@pytest.fixture(scope="module")
def ref():
    return read_par(REF_PAR), read_tim(REF_TIM)


def test_par_parses_dd_binary(ref):
    par, tim = ref
    assert par.get("BINARY") == "DD"
    assert has_binary(par)
    assert float(par.getfloat("PB")) == pytest.approx(67.8251309, rel=1e-8)
    assert float(par.getfloat("A1")) == pytest.approx(32.3424215, rel=1e-8)
    assert float(par.getfloat("ECC")) == pytest.approx(7.494e-5, rel=1e-3)
    assert 0 < float(par.getfloat("SINI")) < 1
    assert float(par.getfloat("M2")) == pytest.approx(0.28)
    assert tim.n == 130
    # fitted binary parameters drive analytic design columns
    for name in ("A1", "T0", "PB", "OM", "ECC", "SINI"):
        assert name in par.fit_params()


def test_binary_delay_magnitude_and_period(ref):
    par, tim = ref
    d = np.asarray(binary_delay(par, tim.mjds), dtype=np.float64)
    x = float(par.getfloat("A1"))
    assert np.max(np.abs(d)) <= x * 1.001
    assert np.max(np.abs(d)) > 0.9 * x  # epochs sample most of the orbit
    # delay is PB-periodic (OMDOT/PBDOT are zero in this par)
    t0 = np.asarray([53100.0], dtype=np.longdouble)
    pb = par.getfloat("PB")
    d0 = binary_delay(par, t0)
    d1 = binary_delay(par, t0 + pb)
    assert abs(float(d1[0] - d0[0])) < 1e-7


def test_ideal_toas_roundtrip_sub_ns(ref):
    """FakePulsar idealization at the reference epochs followed by
    prefit_residuals must close to numerical noise — the same
    idealize/evaluate contract tempo2's fakepulsar+residuals pair
    satisfies (reference simulate_data.py:18)."""
    par, tim = ref
    psr = FakePulsar(par, tim.mjds, np.full(tim.n, 0.04))
    r = prefit_residuals(par, psr.stoas)
    assert np.abs(r).max() < 1e-9  # < 1 ns against a 4.57 ms period


def _perturbed(par: Par, name: str, h: float) -> Par:
    params = dict(par.params)
    p = params[name]
    params[name] = dataclasses.replace(p, value=p.value + np.longdouble(h))
    return Par(params)


@pytest.mark.parametrize("name,h", [
    ("A1", 1e-6), ("T0", 1e-6), ("PB", 1e-8), ("OM", 1e-5),
    ("ECC", 1e-9), ("SINI", 1e-6), ("M2", 1e-4),
])
def test_binary_design_columns_match_finite_difference(ref, name, h):
    """Analytic d(delay)/d(param) columns vs central differences of the
    implemented delay (evaluated at arrival epochs; the design matrix
    consumes only the normalized direction)."""
    par, tim = ref
    if name == "M2":
        # M2 is not fitted in the reference par; fit it for this check
        params = dict(par.params)
        params["M2"] = dataclasses.replace(params["M2"], fit=1)
        par = Par(params)
    M, labels = design_matrix(par, tim.mjds)
    assert name in labels
    col = M[:, labels.index(name)]
    dp = np.asarray(
        binary_delay(_perturbed(par, name, +h), tim.mjds)
        - binary_delay(_perturbed(par, name, -h), tim.mjds),
        dtype=np.float64) / (2 * h)
    # compare directions (columns are unit-RMS normalized downstream)
    cn = col / np.linalg.norm(col)
    dn = dp / np.linalg.norm(dp)
    corr = abs(float(cn @ dn))
    assert corr > 0.9999, f"{name}: corr {corr}"


def test_reference_pipeline_simulated_j1713_white_level(ref, tmp_path):
    """The reference's actual consumption of these files: epochs + par ->
    idealize -> inject red noise + outliers -> load -> fit (reference
    simulate_data.py:10-39, run_sims.py:41-51). Post-fit residuals must
    sit at the white/red noise level, not the unmodeled-binary ~1243 us
    of round 1."""
    rng = np.random.default_rng(1713)
    out1, out2 = simulate_data(REF_PAR, REF_TIM, theta=0.1, idx=0,
                               sigma_out=1e-6, outdir=str(tmp_path),
                               rng=rng)
    psr = Pulsar(glob.glob(out1 + "/*.par")[0],
                 glob.glob(out1 + "/*.tim")[0])
    assert psr.n == 130
    rms = psr.residuals.std()
    # white ~0.1 us + red ~0.3 us + theta=0.1 outliers at 1 us
    assert rms < 2e-6, f"post-fit RMS {rms * 1e6:.2f} us"
    # and the fit must actually have removed the binary signature: the
    # prefit (delay-corrected, unfitted) residuals are already sub-period
    pre = prefit_residuals(psr.par, psr._mjds)
    assert np.abs(pre).max() < 1e-4  # well inside +-P/2 = 2.3 ms


def test_absolute_toa_scope_decision_pinned(ref):
    """Scope decision (VERDICT r2 missing #1, closed as out-of-scope with
    this pin): the committed tim's *absolute* TOA values are NOT
    reproduced by direct evaluation, and measurably cannot be without a
    planetary ephemeris. tempo2 idealized them at the fictitious
    geocentric site AXIS against EPHEM DE414 (reference J1713+0747.par:11),
    so predicting them needs Earth's barycentric position to ~300 m
    (~1 us); an analytic from-first-principles Earth orbit reaches only
    ~10^3 km (~ms), which cannot unwrap 130 points against the 4.57 ms
    pulse period — adding it would NOT reduce the residual RMS below the
    wrapped-uniform-phase floor P/sqrt(12), so none ships (full analysis:
    docs/J1713_INGESTION.md). This test pins exactly that floor: direct
    ingestion of the committed tim post-fit sits at wrapped-phase noise,
    and any future ephemeris capability that actually unwraps phase will
    break this assertion (at which point flip it to a tight bound).

    The reference pipeline itself never consumes these absolute values
    (reference simulate_data.py:12-18 reads only the epochs) — that
    consumption path is tested above at the sub-us level."""
    psr = Pulsar(REF_PAR, REF_TIM)
    assert psr.n == 130
    rms = float(np.sqrt(np.mean(np.asarray(psr.residuals,
                                           dtype=np.float64) ** 2)))
    period = 0.00457  # s; J1713+0747 spin period
    floor = period / np.sqrt(12.0)
    assert 0.6 * floor < rms < 1.4 * floor, (
        f"absolute-TOA post-fit RMS {rms * 1e3:.3f} ms moved off the "
        f"wrapped-phase floor {floor * 1e3:.3f} ms — ephemeris handling "
        "changed; revisit docs/J1713_INGESTION.md")


def _j1713_ma(tmp_path, theta=0.1, tree="outlier", seed=1713,
              components=30):
    """ModelArrays for the reference-equivalent simulated J1713 dataset:
    the exact model run_sims builds over it (reference run_sims.py:57-83)."""
    from gibbs_student_t_tpu.data.demo import make_reference_pta

    rng = np.random.default_rng(seed)
    out1, out2 = simulate_data(REF_PAR, REF_TIM, theta=theta, idx=0,
                               sigma_out=1e-6, outdir=str(tmp_path),
                               rng=rng)
    out = out1 if tree == "outlier" else out2
    psr = Pulsar(glob.glob(out + "/*.par")[0], glob.glob(out + "/*.tim")[0])
    return make_reference_pta(psr, components).frozen()


@pytest.mark.slow
def test_posterior_gate_j1713_gaussian(ref, tmp_path):
    """North-star acceptance on the J1713 dataset (BASELINE config 1
    territory): JAX-kernel posteriors match the NumPy oracle on the
    clean (no_outlier) tree under the gaussian model."""
    from gibbs_student_t_tpu.config import GibbsConfig
    from tests.test_jax_backend import _posterior_gate

    ma = _j1713_ma(tmp_path, tree="no_outlier")
    _posterior_gate(ma, GibbsConfig(model="gaussian", vary_df=False))


@pytest.mark.slow
def test_posterior_gate_j1713_mixture(ref, tmp_path):
    """Same gate through the full outlier machinery on the contaminated
    tree (BASELINE config 3's dataset), with an artifact of posterior
    summaries written when GST_GATE_ARTIFACT is set."""
    import json

    from gibbs_student_t_tpu.config import GibbsConfig
    from tests.test_jax_backend import _posterior_gate

    ma = _j1713_ma(tmp_path, tree="outlier")
    cfg = GibbsConfig(model="mixture", theta_prior="beta")
    res_n, res_j = _posterior_gate(ma, cfg)

    artifact = os.environ.get("GST_GATE_ARTIFACT")
    if artifact:
        from scipy import stats as sstats

        rows = []
        for pi, name in enumerate(ma.param_names):
            a = res_n.chain[1000:, pi][::20]
            b = res_j.chain[150::20, :, pi].ravel()
            rows.append({
                "param": name,
                "numpy_mean": round(float(a.mean()), 5),
                "numpy_sd": round(float(a.std()), 5),
                "jax_mean": round(float(b.mean()), 5),
                "jax_sd": round(float(b.std()), 5),
                "mean_gap_sd": round(float(abs(a.mean() - b.mean())
                                           / max(a.std(), b.std())), 4),
                "ks_p": round(float(sstats.ks_2samp(a, b).pvalue), 5),
            })
        with open(artifact, "w") as fh:
            json.dump({"dataset": "J1713+0747 reference-equivalent "
                                  "(epochs+par from /root/reference)",
                       "model": "mixture/beta", "params": rows}, fh,
                      indent=1)


def test_no_outlier_twin_flags_deleted(ref, tmp_path):
    rng = np.random.default_rng(7)
    out1, out2 = simulate_data(REF_PAR, REF_TIM, theta=0.15, idx=1,
                               outdir=str(tmp_path), rng=rng)
    truth = np.loadtxt(os.path.join(out1, "outliers.txt"), dtype=int,
                       ndmin=1)
    tim2 = read_tim(glob.glob(out2 + "/*.tim")[0], include_deleted=True)
    assert np.array_equal(np.flatnonzero(tim2.deleted), truth)
