"""Fused hyper-MH block (ops/pallas_hyper.py), interpret mode on CPU.

Covers the affine logphi decomposition against ``models.pta
.phiinv_logdet`` (powerlaw and ecorr varying blocks, constant folding,
static logdet), kernel-vs-XLA-loop parity on identical draws, non-PD
reject semantics, and whole-sweep chain equivalence against the closure
path through the backend on identical keys.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gibbs_student_t_tpu.backends import JaxGibbs
from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.data.demo import make_demo_model_arrays
from tests.conftest import make_demo_pulsar
from gibbs_student_t_tpu.models.pta import PTA, phiinv_logdet, static_phi_columns
from gibbs_student_t_tpu.ops.pallas_hyper import (
    build_hyper_consts,
    hyper_mh_fused,
    hyper_mh_loop_xla,
    make_hyper_block,
)


def _ecorr_ma(n=40, seed=6):
    from gibbs_student_t_tpu.models.parameter import Uniform
    from gibbs_student_t_tpu.models.signals import (
        EcorrBasisModel,
        FourierBasisGP,
        MeasurementNoise,
        TimingModel,
        powerlaw,
    )

    psr, _ = make_demo_pulsar(seed=seed, n=n)
    toas = psr.toas.copy()
    toas = np.repeat(toas[::4][:n // 4], 4) + np.tile(
        [0.0, 30.0, 60.0, 90.0], n // 4)
    psr.toas = toas
    s = (MeasurementNoise()
         + EcorrBasisModel(Uniform(-10, -5))
         + FourierBasisGP(powerlaw(log10_A=Uniform(-18, -12),
                                   gamma=Uniform(1, 7)), components=4)
         + TimingModel())
    return PTA([s(psr)]).frozen()


def _reconstruct_phi(ma, consts, cols, x):
    """phiinv/logdet on the subset from the affine K rows (float64)."""
    K = consts.K.astype(np.float64)
    lph = K[0].copy()
    for k, idx in enumerate(consts.hyp_idx):
        lph += K[1 + k] * x[idx]
    sel = consts.phi_sel.astype(bool)
    phiinv = np.where(sel, np.exp(-lph), 0.0) + consts.phiinv_static
    logdet = consts.logdet_phi_static + lph[sel].sum()
    return phiinv, logdet


@pytest.mark.parametrize("make_ma", [
    lambda: make_demo_model_arrays(n=40, components=5, seed=2),
    _ecorr_ma,
])
def test_affine_decomposition_matches_phiinv_logdet(make_ma):
    ma = make_ma()
    cols = np.arange(ma.m)
    consts = build_hyper_consts(ma, cols)
    rng = np.random.default_rng(7)
    for _ in range(3):
        x = ma.x_init(rng)
        pinv_ref, ld_ref = phiinv_logdet(ma, x, np)
        pinv, ld = _reconstruct_phi(ma, consts, cols, x)
        np.testing.assert_allclose(pinv, pinv_ref, rtol=1e-5)
        np.testing.assert_allclose(ld, ld_ref, rtol=1e-6, atol=1e-6)


def test_affine_decomposition_schur_subset():
    """On the Schur varying subset every column is varying, the static
    logdet carries the eliminated block, and the two pieces reassemble
    the full logdet."""
    ma = make_demo_model_arrays(n=40, components=5, seed=3)
    smask = static_phi_columns(ma)
    v_i = np.flatnonzero(~smask)
    consts = build_hyper_consts(ma, v_i)
    assert consts.phi_sel.all()
    assert np.all(consts.phiinv_static == 0.0)
    rng = np.random.default_rng(1)
    x = ma.x_init(rng)
    pinv_ref, ld_ref = phiinv_logdet(ma, x, np)
    pinv, ld = _reconstruct_phi(ma, consts, v_i, x)
    np.testing.assert_allclose(pinv, pinv_ref[v_i], rtol=1e-5)
    np.testing.assert_allclose(ld, ld_ref, rtol=1e-6, atol=1e-6)


def _loop2(*args, consts, jitter):
    """Single-model convenience wrapper over the consts-as-operands
    signature."""
    return hyper_mh_loop_xla(*args, consts.K, consts.phi_sel,
                             consts.specs, consts.hyp_idx, jitter)


def _fused2(*args, consts, jitter, **kw):
    """Single-model (G == 1) wrapper over the grouped fused kernel."""
    xf, acc = hyper_mh_fused(
        *(a[None] for a in args), jnp.asarray(consts.K)[None],
        jnp.asarray(consts.phi_sel)[None],
        jnp.asarray(consts.specs)[None], consts.hyp_idx, jitter, **kw)
    return xf[0], acc[0]


def _block_inputs(ma, cols, C, S=5, seed=4):
    rng = np.random.default_rng(seed)
    p = ma.nparam
    v = len(cols)
    x = np.stack([ma.x_init(rng) for _ in range(C)]).astype(np.float32)
    A = rng.standard_normal((C, v, 2 * v))
    S0 = (A @ np.swapaxes(A, -1, -2) / v
          + 2.0 * np.eye(v)).astype(np.float32)
    dS0 = np.einsum("bii->bi", S0).copy()
    rt = rng.standard_normal((C, v)).astype(np.float32)
    base = rng.standard_normal(C).astype(np.float32)
    hyper = ma.hyper_indices
    dx = np.zeros((C, S, p), np.float32)
    for c in range(C):
        for s in range(S):
            dx[c, s, hyper[rng.integers(0, len(hyper))]] = (
                rng.standard_normal() * 0.3)
    logu = np.log(rng.uniform(size=(C, S))).astype(np.float32)
    return tuple(jnp.asarray(a) for a in (x, S0, dS0, rt, base, dx, logu))


@pytest.mark.parametrize("make_ma", [
    lambda: make_demo_model_arrays(n=40, components=5, seed=2),
    _ecorr_ma,
])
@pytest.mark.slow
def test_kernel_matches_xla_loop(make_ma):
    ma = make_ma()
    cols = np.arange(ma.m)
    consts = build_hyper_consts(ma, cols)
    args = _block_inputs(ma, cols, C=9)
    x1, a1 = jax.jit(lambda *a: _fused2(
        *a, consts=consts, jitter=1e-6, chain_tile=8,
        interpret=True))(*args)
    x0, a0 = jax.jit(lambda *a: _loop2(
        *a, consts=consts, jitter=1e-6))(*args)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0))


@pytest.mark.slow
def test_non_pd_proposals_reject():
    """A matrix block that goes non-PD under every proposal must reject
    all of them (NaN -> -inf -> reject, reference gibbs.py:320-324)."""
    ma = make_demo_model_arrays(n=30, components=4, seed=5)
    cols = np.arange(ma.m)
    consts = build_hyper_consts(ma, cols)
    x, S0, dS0, rt, base, dx, logu = _block_inputs(ma, cols, C=4)
    S0 = -jnp.asarray(np.broadcast_to(
        np.eye(len(cols), dtype=np.float32), S0.shape))
    dS0 = -jnp.ones_like(dS0) * 5.0  # negative diagonal: rsqrt -> NaN
    logu = jnp.full_like(logu, -1e30)
    for fn in (lambda: _loop2(x, S0, dS0, rt, base, dx, logu,
                              consts=consts, jitter=1e-6),
               lambda: _fused2(x, S0, dS0, rt, base, dx, logu,
                               consts=consts, jitter=1e-6, chain_tile=8,
                               interpret=True)):
        x1, acc = fn()
        np.testing.assert_array_equal(np.asarray(x1), np.asarray(x))
        assert float(jnp.max(acc)) == 0.0


@pytest.mark.slow
def test_dispatch_under_vmap(monkeypatch):
    ma = make_demo_model_arrays(n=30, components=4, seed=6)
    cols = np.arange(ma.m)
    consts = build_hyper_consts(ma, cols)
    block = make_hyper_block(consts.hyp_idx, jitter=1e-6)
    args = _block_inputs(ma, cols, C=8, seed=11)
    carr = (jnp.asarray(consts.K), jnp.asarray(consts.phi_sel),
            jnp.asarray(consts.specs))
    axes = (0,) * 7 + (None,) * 3
    monkeypatch.setenv("GST_PALLAS_HYPER", "interpret")
    x1, a1 = jax.vmap(block, in_axes=axes)(*args, *carr)
    monkeypatch.setenv("GST_PALLAS_HYPER", "0")
    x0, a0 = jax.vmap(block, in_axes=axes)(*args, *carr)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x0),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0))


@pytest.mark.slow
def test_grouped_kernel_matches_per_group_loop():
    """The grouped (per-pulsar constants) hyper kernel must reproduce
    the per-group XLA loop: G models with different phi constants, one
    launch with per-lane constant planes."""
    G, C = 3, 5
    mas = [make_demo_model_arrays(n=30, components=4, seed=40 + g)
           for g in range(G)]
    cols = np.arange(mas[0].m)
    hcs = [build_hyper_consts(ma, cols) for ma in mas]
    assert all(hc.hyp_idx == hcs[0].hyp_idx for hc in hcs)
    per = [_block_inputs(ma, cols, C=C, seed=50 + g)
           for g, ma in enumerate(mas)]
    grouped = tuple(jnp.stack([p[i] for p in per]) for i in range(7))
    K = jnp.asarray(np.stack([hc.K for hc in hcs]))
    sel = jnp.asarray(np.stack([hc.phi_sel for hc in hcs]))
    specs = jnp.asarray(np.stack([hc.specs for hc in hcs]))

    xf, af = hyper_mh_fused(*grouped, K, sel, specs, hcs[0].hyp_idx,
                            1e-6, chain_tile=8, interpret=True)
    for g in range(G):
        x0, a0 = _loop2(*per[g], consts=hcs[g], jitter=1e-6)
        np.testing.assert_allclose(np.asarray(xf[g]), np.asarray(x0),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(af[g]), np.asarray(a0))


def test_auto_mode_stays_off_on_cpu(monkeypatch):
    from gibbs_student_t_tpu.ops import pallas_hyper

    monkeypatch.delenv("GST_PALLAS_HYPER", raising=False)
    enabled, _, _ = pallas_hyper._pallas_hyper_mode()
    assert not enabled


@pytest.mark.slow
@pytest.mark.parametrize("schur", ["auto", False])
def test_sweep_chains_identical_fused_vs_closure(monkeypatch, schur):
    """Whole-sweep equivalence through the backend: closure path vs the
    fused hyper block on identical keys, Schur on and off."""
    ma = make_demo_model_arrays(n=40, components=6, seed=3)
    cfg = GibbsConfig(model="mixture", vary_df=True, theta_prior="beta")

    def run(flag):
        monkeypatch.setenv("GST_PALLAS_HYPER", flag)
        monkeypatch.setenv("GST_PALLAS_WHITE", "0")
        gb = JaxGibbs(ma, cfg, nchains=6, chunk_size=5, record="full",
                      hyper_schur=schur)
        return gb.sample(niter=10, seed=0)

    r0 = run("0")
    r1 = run("interpret")
    np.testing.assert_allclose(np.asarray(r1.chain),
                               np.asarray(r0.chain),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_array_equal(np.asarray(r1.zchain),
                                  np.asarray(r0.zchain))
    np.testing.assert_allclose(
        np.asarray(r1.stats["acc_hyper"]),
        np.asarray(r0.stats["acc_hyper"]), atol=1e-6)
