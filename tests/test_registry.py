"""The capability-probed dispatch registry (ops/registry.py).

The acceptance pins of ROADMAP item 5 / round 18:

- ONE strict config surface: every legacy ``GST_*`` value resolves
  exactly as the historical per-gate functions did — the probe matrix
  covers forced / unavailable-degrades / disabled per family, and the
  strict ``auto|1|0`` typo contract for EVERY declared strict gate
  (plus the choice/posint/enum kinds' messages).
- The persistent gates cache is keyed by ABI / library digest / CPU
  flags / jax+jaxlib / dispatch-config fingerprint, and any stale
  component is a LOUD ignore (RuntimeWarning + counter) followed by a
  fresh probe — never a silent reuse.
- The cache can never change numerics: chains sampled with the
  cold-start caches armed are bitwise the cache-less chains (this
  also pins donation-on/off bitwise, since arming degrades
  ``GST_DONATE_CHUNK`` — see backends/jax_backend.donate_resolved).
- jax's filesystem AOT cache writes publish atomically after the
  registry's hardening (the measured two-pools-tear-one-entry
  segfault, docs/OBSERVABILITY.md).
"""

import json
import os
import sys
import warnings

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from gibbs_student_t_tpu.ops import registry  # noqa: E402


@pytest.fixture()
def fresh_registry():
    """Isolate latched probes/counters/cache state per test, and
    restore the process to cache-less defaults afterwards (other
    tests' backends must not silently construct donation-off)."""
    registry._reset_for_tests()
    yield registry
    registry._reset_for_tests()
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # noqa: BLE001
        pass


STRICT3 = sorted(n for n, sp in registry.GATES.items()
                 if sp.kind == "strict3")


@pytest.mark.parametrize("gate", STRICT3)
def test_strict3_validation_matrix(gate, monkeypatch, fresh_registry):
    """Every declared strict gate keeps the loud-typo contract: unset
    -> 'auto', each legal value accepted verbatim, anything else
    raises naming the gate."""
    monkeypatch.delenv(gate, raising=False)
    assert registry.value(gate) == "auto"
    for v in ("auto", "1", "0"):
        monkeypatch.setenv(gate, v)
        assert registry.value(gate) == v
    monkeypatch.setenv(gate, "banana")
    with pytest.raises(ValueError, match=gate):
        registry.value(gate)


def test_other_kinds_validation(monkeypatch, fresh_registry):
    monkeypatch.setenv("GST_SERVE_WATCHDOG", "loud")
    with pytest.raises(ValueError, match="GST_SERVE_WATCHDOG"):
        registry.value("GST_SERVE_WATCHDOG")
    monkeypatch.setenv("GST_RPC_MAX_FRAME", "-1")
    with pytest.raises(ValueError, match="positive integer"):
        registry.value("GST_RPC_MAX_FRAME")
    monkeypatch.setenv("GST_ENSEMBLE_UNROLL", "2")
    with pytest.raises(ValueError, match="GST_ENSEMBLE_UNROLL"):
        registry.value("GST_ENSEMBLE_UNROLL")
    # forgiving kinds stay forgiving
    monkeypatch.setenv("GST_WHITE_TILE", "not-a-number")
    assert registry.int_value("GST_WHITE_TILE") == 256
    monkeypatch.setenv("GST_INTROSPECT", "0")
    assert registry.value("GST_INTROSPECT") is False


def test_legacy_wrappers_still_validate(monkeypatch, fresh_registry):
    """The public ``*_env`` names all route through the registry and
    keep raising on typos — the compatibility surface of the
    refactor."""
    from gibbs_student_t_tpu.backends.jax_backend import _fast_gamma_env
    from gibbs_student_t_tpu.native.ffi import kernel_timers_env
    from gibbs_student_t_tpu.ops import linalg
    from gibbs_student_t_tpu.serve.rpc import rpc_max_frame_env
    from gibbs_student_t_tpu.serve.server import serve_pipeline_env

    for var, fn in (("GST_VCHOL", linalg.vchol_env),
                    ("GST_NCHOL", linalg.nchol_env),
                    ("GST_NRESID", linalg.nresid_env),
                    ("GST_FUSE_STAGES", linalg.fuse_stages_env),
                    ("GST_KERNEL_TIMERS", kernel_timers_env),
                    ("GST_FAST_GAMMA", _fast_gamma_env),
                    ("GST_SERVE_PIPELINE", serve_pipeline_env)):
        monkeypatch.setenv(var, "nope")
        with pytest.raises(ValueError, match=var):
            fn()
        monkeypatch.delenv(var)
    monkeypatch.setenv("GST_RPC_MAX_FRAME", "12")
    assert rpc_max_frame_env() == 12


def _force_probe(monkeypatch, name, outcome):
    registry._unlatch_probe(name)
    monkeypatch.setitem(registry._PROBE_FNS, name, lambda: outcome)


@pytest.mark.parametrize("gate", ["GST_NCHOL", "GST_NWHITE",
                                  "GST_NHYPER"])
def test_probe_matrix_native_family(gate, monkeypatch, fresh_registry):
    """Forced / unavailable / disabled for the native kernel family:
    a well-formed ``1`` on a host without the capability degrades
    SILENTLY (no toolchain ever becomes a runtime requirement), ``0``
    never probes, availability + auto resolves on."""
    _force_probe(monkeypatch, "cpu", True)
    _force_probe(monkeypatch, "native", True)
    monkeypatch.setenv(gate, "1")
    assert registry.mode3(gate) == (True, True)
    monkeypatch.setenv(gate, "auto")
    assert registry.mode3(gate) == (True, False)
    _force_probe(monkeypatch, "native", False)
    monkeypatch.setenv(gate, "1")
    assert registry.mode3(gate) == (False, False)   # degraded, silent
    monkeypatch.setenv(gate, "0")
    # disabled never evaluates the probes at all
    registry._unlatch_probe("native")
    monkeypatch.setitem(
        registry._PROBE_FNS, "native",
        lambda: (_ for _ in ()).throw(AssertionError("probed")))
    assert registry.mode3(gate) == (False, False)


def test_probe_matrix_vchol_and_nresid(monkeypatch, fresh_registry):
    """GST_VCHOL: forced needs NO capability; auto follows the
    platform probe. GST_NRESID: auto follows GST_NCHOL's resolution
    (the one gate that chains through another's verdict)."""
    from gibbs_student_t_tpu.ops import linalg

    _force_probe(monkeypatch, "not_tpu", False)
    monkeypatch.setenv("GST_VCHOL", "auto")
    assert registry.mode3("GST_VCHOL") == (False, False)
    monkeypatch.setenv("GST_VCHOL", "1")
    assert registry.mode3("GST_VCHOL") == (True, True)
    _force_probe(monkeypatch, "cpu", True)
    _force_probe(monkeypatch, "native", True)
    monkeypatch.setenv("GST_NCHOL", "0")
    monkeypatch.delenv("GST_NRESID", raising=False)
    assert linalg._nresid_mode() == (False, False)
    monkeypatch.setenv("GST_NCHOL", "1")
    assert linalg._nresid_mode() == (True, True)   # inherits forced
    monkeypatch.setenv("GST_NCHOL", "auto")
    assert linalg._nresid_mode() == (True, False)


def test_provenance_and_registry_summary(monkeypatch, fresh_registry):
    _force_probe(monkeypatch, "cpu", True)
    _force_probe(monkeypatch, "native", True)
    monkeypatch.delenv("GST_NCHOL", raising=False)
    registry.mode3("GST_NCHOL")
    summ = registry.registry_summary()
    assert summ["probes"] == {"cpu": True, "native": True}
    gates = {r.get("gate") for r in summ["resolutions"]}
    assert "GST_NCHOL" in gates
    assert summ["counters"]["probes_fresh"] == 2
    # the introspect ledger block carries the same summary
    from gibbs_student_t_tpu.obs.introspect import compile_summary

    assert compile_summary()["registry"]["probes"]["native"] is True


# ----------------------------------------------------------------------
# the persistent gates cache
# ----------------------------------------------------------------------


def _prime_and_save(tmp_path, monkeypatch):
    d = str(tmp_path / "cache")
    registry.probe("native")
    registry.note_autotune("compile", "chunk", 5.5)
    registry.note_autotune("linalg", "factor=nchol")
    path = registry.save_gate_cache(d)
    assert path and os.path.exists(path)
    return d, path


def test_gate_cache_roundtrip_counts_cached(tmp_path, monkeypatch,
                                            fresh_registry):
    d, _ = _prime_and_save(tmp_path, monkeypatch)
    registry._reset_for_tests()
    assert registry.load_gate_cache(d)
    registry.probe("native")
    registry.note_autotune("compile", "chunk", 0.1)
    registry.note_autotune("linalg", "factor=nchol")
    st = registry.stats()
    assert st["probes_fresh"] == 0 and st["probes_cached"] == 1
    assert st["autotune_fresh"] == 0 and st["autotune_cached"] == 2
    # a save after a warm run carries the store forward undiminished
    registry.save_gate_cache(d)
    doc = json.load(open(os.path.join(d, registry.GATE_CACHE_NAME)))
    assert "compile:chunk" in doc["autotune"]


@pytest.mark.parametrize("field", ["abi", "so_digest", "cpu_flags",
                                   "jax", "jaxlib", "config_fp"])
def test_gate_cache_staleness_is_loud(field, tmp_path, monkeypatch,
                                      fresh_registry):
    """Every key component independently invalidates the cache, and
    the ignore is LOUD: RuntimeWarning naming the stale field, the
    ``cache_ignored`` counter, and fully fresh probes afterwards —
    an ABI bump / SIMD-level (.so) change / jaxlib upgrade / config
    flip can never silently reuse stale decisions."""
    d, path = _prime_and_save(tmp_path, monkeypatch)
    registry._reset_for_tests()
    doc = json.load(open(path))
    doc["key"][field] = "something-else"
    json.dump(doc, open(path, "w"))
    with pytest.warns(RuntimeWarning, match=field):
        assert not registry.load_gate_cache(d)
    st = registry.stats()
    assert st["cache_ignored"] == 1
    registry.probe("native")
    assert registry.stats()["probes_fresh"] == 1   # fresh, not cached


def test_gate_cache_wrong_prediction_warns(tmp_path, monkeypatch,
                                           fresh_registry):
    d, path = _prime_and_save(tmp_path, monkeypatch)
    registry._reset_for_tests()
    doc = json.load(open(path))
    doc["probes"]["native"] = {"ok": not doc["probes"]["native"]["ok"]}
    json.dump(doc, open(path, "w"))
    assert registry.load_gate_cache(d)
    with pytest.warns(RuntimeWarning, match="live probe"):
        registry.probe("native")
    assert registry.stats()["probes_fresh"] == 1


def test_config_fingerprint_tracks_dispatch_gates_only(monkeypatch,
                                                       fresh_registry):
    base = registry.config_fingerprint_env()
    monkeypatch.setenv("GST_NCHOL", "0")        # dispatch gate: moves
    assert registry.config_fingerprint_env() != base
    monkeypatch.delenv("GST_NCHOL")
    monkeypatch.setenv("GST_LEDGER_PATH", "/tmp/x")  # obs: must not
    assert registry.config_fingerprint_env() == base


def test_aot_cache_writes_are_atomic(tmp_path, fresh_registry):
    """The registry's hardening of jax's filesystem cache: publishes
    go through a same-dir temp + rename, double-puts of one key are
    stable, and no temp litter survives — the stock write_bytes
    publish let two concurrent pool workers tear one entry and then
    segfault every later reader (measured; the reason this patch
    exists)."""
    assert registry._harden_aot_cache_writes()
    from jax._src.lru_cache import LRUCache

    c = LRUCache(str(tmp_path), max_size=-1)
    c.put("k1", b"A" * 1024)
    c.put("k1", b"B" * 2048)            # first write wins, no tear
    assert c.get("k1") == b"A" * 1024
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


@pytest.mark.slow  # round-18 re-tier (~29 s: two full cold-start compiles; cache key/probe/degrade pins stay tier-1)
def test_chains_bitwise_with_and_without_cold_start_caches(
        tmp_path, fresh_registry):
    """THE pinned contract: arming the persistent cold-start caches
    (AOT dir + gates.json — including the donation degradation it
    implies) changes nothing about the numbers. Chains from a
    cache-less backend are bitwise the chains from a cache-armed
    one."""
    from tests.conftest import make_demo_pta
    from gibbs_student_t_tpu.backends.jax_backend import JaxGibbs
    from gibbs_student_t_tpu.config import GibbsConfig

    pta = make_demo_pta()
    ma, cfg = pta.frozen(0), GibbsConfig(model="mixture")

    def run():
        res = JaxGibbs(ma, cfg, nchains=2).sample(niter=6, seed=11)
        return np.asarray(res.chain)

    cold = run()
    info = registry.enable_persistent_cache(str(tmp_path / "aot"))
    assert info["aot"] and registry.aot_cache_armed()
    warm_writer = run()                 # compiles + writes the cache
    warm_reader = run()                 # loads the AOT entry
    assert np.array_equal(cold, warm_writer)
    assert np.array_equal(cold, warm_reader)


def test_donation_degrades_only_when_cache_armed(monkeypatch,
                                                 fresh_registry):
    from gibbs_student_t_tpu.backends.jax_backend import donate_resolved

    monkeypatch.delenv("GST_DONATE_CHUNK", raising=False)
    assert donate_resolved() is True
    registry._AOT_ARMED = True
    assert donate_resolved() is False   # deserialized donated
    monkeypatch.setenv("GST_DONATE_CHUNK", "1")   # executables corrupt
    assert donate_resolved() is True    # the A/B hatch still forces
    reasons = [r for r in registry.provenance()
               if r.get("gate") == "GST_DONATE_CHUNK"]
    assert any("AOT cache" in (r.get("reason") or "") for r in reasons)


# ----------------------------------------------------------------------
# tools/gates.py
# ----------------------------------------------------------------------


def _gates_tool():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gates_tool", os.path.join(REPO, "tools", "gates.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_gates_cli_resolves_every_gate(fresh_registry, capsys):
    tool = _gates_tool()
    doc = tool.resolve_all()
    assert set(doc["gates"]) == set(registry.GATES)
    assert set(doc["ops"]) == set(registry.OPS)
    for name, row in doc["gates"].items():
        assert "error" not in row, (name, row)
    assert tool.main([]) == 0
    out = capsys.readouterr().out
    assert "GST_NCHOL" in out and "per-op dispatch" in out
    assert tool.main(["--markdown"]) == 0
    md = capsys.readouterr().out.strip("\n")
    assert md == "\n".join(registry.gates_markdown())


def test_ops_table_matches_dispatcher_reality(fresh_registry):
    """The declared per-op impl tables must keep naming real
    dispatchers: every op name any ops/ module passes to
    ``_note_impl`` (the trace-time decision record) is a declared
    OPS key, AND every runtime decision recorded so far in this
    process resolves to one — a new dispatcher without a table row
    fails here."""
    import re

    from gibbs_student_t_tpu.obs import introspect

    known = set(registry.OPS)
    noted = set()
    ops_dir = os.path.join(REPO, "gibbs_student_t_tpu", "ops")
    for f in os.listdir(ops_dir):
        if f.endswith(".py"):
            noted |= set(re.findall(
                r'_note_impl\("([a-z_0-9]+)"',
                open(os.path.join(ops_dir, f)).read()))
    assert noted, "the _note_impl scan went blind"
    assert noted <= known, (
        f"ops noted by dispatchers but undeclared in registry.OPS: "
        f"{sorted(noted - known)}")
    for rec in introspect.linalg_impls():
        assert rec["op"] in known, (
            f"runtime records op {rec['op']!r} that ops/registry.OPS "
            "does not declare")
