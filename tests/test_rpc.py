"""RPC-wire unit tests: framing, rejection paths, the RpcServer over a
stub chain server, and FleetRouter placement/failover logic over fake
pools — no pool compiles, no subprocesses (the jax-heavy fleet
end-to-end arms live in tests/test_fleet.py).

The rejection contract pinned here (docs/SERVING.md "The wire"):
malformed magic/version/kind answers one error frame and closes;
an oversized declared length is rejected BEFORE allocation; a peer
disconnect mid-frame is contained to that connection and the server
keeps answering the next one; an injected ``rpc_sever`` closes a
stream abruptly and the client's handle resolves to a
ConnectionError instead of hanging.
"""

import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from gibbs_student_t_tpu.serve import faults as faults_mod
from gibbs_student_t_tpu.serve.rpc import (
    _HEADER,
    MAGIC,
    FrameError,
    Pickled,
    RemoteChainServer,
    RpcError,
    RpcServer,
    decode_payload,
    encode_frame,
    recv_frame,
    rpc_max_frame_env,
    send_frame,
)
from gibbs_student_t_tpu.serve.scheduler import TenantRequest

pytestmark = pytest.mark.fleet


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def _decode(data: bytes) -> dict:
    magic, ver, kind, length = _HEADER.unpack(data[:_HEADER.size])
    assert magic == MAGIC and length == len(data) - _HEADER.size
    return decode_payload(kind, data[_HEADER.size:])


def test_frame_roundtrip_json_arrays_pickles():
    body = {
        "op": "x", "n": 3, "f": 1.5, "none": None, "flag": True,
        "arr_f32": np.arange(12, dtype=np.float32).reshape(3, 4),
        "arr_i64": np.array([[1, -2], [3, 4]], np.int64),
        "blob": Pickled({"k": np.ones(5), "s": "v"}),
        "nested": [{"deep": np.arange(3, dtype=np.uint8)},
                   np.float64(2.25)],
    }
    back = _decode(encode_frame(body))
    assert back["op"] == "x" and back["n"] == 3 and back["none"] is None
    assert back["arr_f32"].dtype == np.float32
    assert np.array_equal(back["arr_f32"], body["arr_f32"])
    assert np.array_equal(back["arr_i64"], body["arr_i64"])
    assert np.array_equal(back["blob"]["k"], np.ones(5))
    assert back["blob"]["s"] == "v"
    assert np.array_equal(back["nested"][0]["deep"],
                          np.arange(3, dtype=np.uint8))
    assert back["nested"][1] == 2.25   # np scalars -> plain JSON


def test_frame_roundtrip_pure_json_stays_json_kind():
    data = encode_frame({"op": "status"})
    _, _, kind, _ = _HEADER.unpack(data[:_HEADER.size])
    assert kind == b"j"
    assert _decode(data) == {"op": "status"}


def test_malformed_frames_raise():
    # composite whose declared JSON length overruns the payload
    with pytest.raises(FrameError, match="JSON length"):
        decode_payload(b"m", b"\x00\x00\x00\xffxx")
    # unknown kind
    with pytest.raises(FrameError, match="kind"):
        decode_payload(b"q", b"{}")
    # non-object body
    with pytest.raises(FrameError, match="not a JSON object"):
        decode_payload(b"j", b"[1,2]")
    # dangling buffer reference
    with pytest.raises(FrameError, match="dangling"):
        decode_payload(b"m", struct.pack(">I", 26)
                       + b'{"a":{"$nd":7},"op":"x"}  ')
    # buffer table overrunning the payload
    bad = {"__buffers__": [["<f4", [64], 256]], "a": {"$nd": 0}}
    import json as _json

    jb = _json.dumps(bad).encode()
    with pytest.raises(FrameError, match="overruns"):
        decode_payload(b"m", struct.pack(">I", len(jb)) + jb + b"xx")


def test_max_frame_env_validation(monkeypatch):
    monkeypatch.setenv("GST_RPC_MAX_FRAME", "bogus")
    with pytest.raises(ValueError, match="GST_RPC_MAX_FRAME"):
        rpc_max_frame_env()
    monkeypatch.setenv("GST_RPC_MAX_FRAME", "-3")
    with pytest.raises(ValueError, match="GST_RPC_MAX_FRAME"):
        rpc_max_frame_env()
    monkeypatch.setenv("GST_RPC_MAX_FRAME", "4096")
    assert rpc_max_frame_env() == 4096
    monkeypatch.delenv("GST_RPC_MAX_FRAME")
    assert rpc_max_frame_env() == 256 * 1024 * 1024


def test_oversized_frames_rejected_both_directions():
    a, b = socket.socketpair()
    try:
        big = {"op": "x", "arr": np.zeros(100000, np.float64)}
        with pytest.raises(FrameError, match="exceeds"):
            send_frame(a, big, max_frame=1024)
        # receiver-side: a header declaring more than the ceiling is
        # rejected before any payload allocation
        a.sendall(_HEADER.pack(MAGIC, b"\x01", b"j", 1 << 30))
        with pytest.raises(FrameError, match="ceiling"):
            recv_frame(b, max_frame=1024)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# the RpcServer over a stub chain server (no jax, no pool)
# ---------------------------------------------------------------------------

class _StubHandle:
    def __init__(self, tenant_id, request):
        self.tenant_id = tenant_id
        self.request = request
        self._done = threading.Event()
        self._result = None

    def progress(self):
        return {"tenant_id": self.tenant_id, "status":
                ("done" if self._done.is_set() else "running"),
                "name": self.request.name}

    def cost(self):
        return {"device_ms": 1.25, "lane_quanta": 4,
                "ess_per_core_s": None}

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout if timeout is not None else 30):
            raise TimeoutError("stub tenant not done")
        return self._result

    def _finish(self, res):
        self._result = res
        self._done.set()


class _StubServer:
    """Duck-typed ChainServer: submit/cancel/status/healthz/_handles.
    ``chunks`` > 0 makes submit serve that many on_chunk callbacks
    from a worker thread, then finish — the streaming test bed."""

    def __init__(self, chunks=0):
        self._handles = {}
        self._next = 0
        self.chunks = chunks
        self.cancelled = []

    def submit(self, request, timeout=None):
        h = _StubHandle(self._next, request)
        self._handles[h.tenant_id] = h
        self._next += 1

        def run():
            for i in range(self.chunks):
                if request.on_chunk is not None:
                    request.on_chunk(
                        h, (i + 1) * 5,
                        {"x": np.full((5, 2), i, np.float32)})
            h._finish({"rows": self.chunks * 5,
                       "seed": request.seed})

        threading.Thread(target=run, daemon=True).start()
        return h

    def cancel(self, h):
        self.cancelled.append(h.tenant_id)
        return True

    def status(self):
        return {"schema": 1, "queue_depth": 0, "tenants": []}

    def healthz(self):
        return {"ok": True}

    def reset_counters(self):
        self.reset = True


@pytest.fixture()
def stub_rpc():
    stub = _StubServer(chunks=2)
    rpc = RpcServer(stub)
    yield stub, rpc, RemoteChainServer(rpc.address, timeout=10.0)
    rpc.close()


def test_rpc_ops_over_stub(stub_rpc):
    stub, rpc, cli = stub_rpc
    req = TenantRequest(ma={"m": 1}, niter=10, nchains=4, seed=7,
                        name="tA")
    h = cli.submit(req)
    res = h.result(timeout=10)
    assert res == {"rows": 10, "seed": 7}
    assert h.progress()["status"] == "done"
    assert h.cost()["lane_quanta"] == 4
    assert cli.status()["schema"] == 1
    assert cli.healthz()["ok"] is True
    assert h.cancel() is True and stub.cancelled == [h.tenant_id]
    cli.reset_counters()
    assert getattr(stub, "reset", False) is True
    # unknown tenant and unknown op answer error frames, not hangs
    with pytest.raises(RpcError, match="unknown tenant"):
        cli._call({"op": "progress", "tenant": 999})
    with pytest.raises(RpcError, match="unknown op"):
        cli._call({"op": "frobnicate"})
    # shutdown without a callback is an error, never an exit
    with pytest.raises(RpcError, match="shutdown not armed"):
        cli.shutdown()


def test_rpc_streaming_chunks_over_stub(stub_rpc):
    stub, rpc, cli = stub_rpc
    got = []

    def on_chunk(h, sweep_end, records):
        got.append((sweep_end, records["x"].copy()))

    h = cli.submit(TenantRequest(ma={"m": 1}, niter=10, nchains=4,
                                 seed=3, name="tS", on_chunk=on_chunk))
    res = h.result(timeout=10)
    assert res["seed"] == 3
    assert [s for s, _ in got] == [5, 10]
    assert got[0][1].dtype == np.float32
    assert np.array_equal(got[1][1], np.full((5, 2), 1, np.float32))


def test_malformed_and_disconnect_contained(stub_rpc):
    stub, rpc, cli = stub_rpc
    # garbage magic: one error frame, closed connection
    s = socket.create_connection(("127.0.0.1", rpc.port), timeout=5)
    s.sendall(b"XX" + b"\x00" * 30)
    reply = recv_frame(s)
    assert reply["op"] == "error" and "bad frame" in reply["error"]
    try:
        assert s.recv(1) == b""   # server closed after the error frame
    except ConnectionResetError:
        pass  # RST instead of FIN: unread garbage was still buffered
    s.close()
    # disconnect mid-frame (header promises more than is sent)
    s2 = socket.create_connection(("127.0.0.1", rpc.port), timeout=5)
    s2.sendall(_HEADER.pack(MAGIC, b"\x01", b"j", 100) + b"{}")
    s2.close()
    time.sleep(0.05)
    # the server survives and answers the next connection
    assert cli.healthz()["ok"] is True


def test_rpc_sever_closes_stream_and_result_survives(stub_rpc):
    """A severed stream resolves the client handle to a
    ConnectionError — and because the SERVER kept serving, the result
    is still fetchable over a fresh connection by tenant id."""
    stub, rpc, cli = stub_rpc
    got = []
    with faults_mod.inject(
            faults_mod.FaultSpec("rpc_sever", tenant="tV", after=1)):
        h = cli.submit(TenantRequest(
            ma={"m": 1}, niter=10, nchains=4, seed=5, name="tV",
            on_chunk=lambda hh, s, r: got.append(s)))
        with pytest.raises(ConnectionError, match="severed"):
            h.result(timeout=10)
    assert faults_mod.fired_counts()[("rpc_sever", "tV")] == 1
    # a fresh handle to the same tenant id gets the full result
    from gibbs_student_t_tpu.serve.rpc import RemoteTenantHandle

    h2 = RemoteTenantHandle(cli, h.tenant_id, h.request)
    assert h2.result(timeout=10)["seed"] == 5


def test_severed_stream_detaches_server_callback():
    """A connection that dies mid-stream must DETACH the server-side
    on_chunk: the tenant keeps producing chunks with nobody draining
    the bounded per-stream queue, and a blocking put there would wedge
    the pool's shared drain worker — every co-resident tenant with it.
    Pinned with more chunks than queue slots: the producer finishes,
    and the result stays fetchable over a fresh connection."""
    stub = _StubServer(chunks=30)
    rpc = RpcServer(stub, chunk_queue=4)
    cli = RemoteChainServer(rpc.address, timeout=10.0)
    try:
        with faults_mod.inject(
                faults_mod.FaultSpec("rpc_sever", tenant="tW",
                                     after=1)):
            h = cli.submit(TenantRequest(
                ma={"m": 1}, niter=10, nchains=4, seed=11, name="tW",
                on_chunk=lambda hh, s, r: None))
            with pytest.raises(ConnectionError, match="severed"):
                h.result(timeout=10)
        # the producer (the drain worker in a real pool) must not be
        # wedged behind the dead stream's full queue: the tenant
        # finishes and a fresh handle fetches its result
        from gibbs_student_t_tpu.serve.rpc import RemoteTenantHandle

        h2 = RemoteTenantHandle(cli, h.tenant_id, h.request)
        assert h2.result(timeout=10)["seed"] == 11
    finally:
        rpc.close()


def test_stream_reader_honors_client_max_frame(monkeypatch):
    """A client constructed with an explicit frame ceiling applies it
    to streamed chunk/result frames too — not the env default, which
    would spuriously sever streams carrying frames between the two
    limits."""
    monkeypatch.setenv("GST_RPC_MAX_FRAME", "2048")
    big = 8 * 1024 * 1024
    stub = _StubServer(chunks=2)
    # chunk frames ≈ 40 KiB: over the env default, under the explicit
    stub_submit = stub.submit

    def submit_big(request, timeout=None):
        orig = request.on_chunk

        def wrap(h, s, r):
            orig(h, s, {"x": np.zeros((5, 2048), np.float32)})

        request.on_chunk = wrap if orig is not None else None
        return stub_submit(request, timeout)

    stub.submit = submit_big
    rpc = RpcServer(stub, max_frame=big)
    cli = RemoteChainServer(rpc.address, timeout=10.0, max_frame=big)
    got = []
    try:
        h = cli.submit(TenantRequest(
            ma={"m": 1}, niter=10, nchains=4, seed=13, name="tF",
            on_chunk=lambda hh, s, r: got.append(r["x"].shape)))
        assert h.result(timeout=10)["seed"] == 13
        assert got == [(5, 2048), (5, 2048)]
    finally:
        rpc.close()


# ---------------------------------------------------------------------------
# FleetRouter placement + failover logic over fake pools
# ---------------------------------------------------------------------------

class _FakePool:
    alive = True        # class attr so _DyingPool can shadow with a
                        # property (liveness from its fake Popen)

    def __init__(self, label, queue_depth=0, free_groups=2,
                 occupancy=0.5):
        self.label = label
        self.proc = None           # the watch loop skips local pools
        self.queue_depth = queue_depth
        self.free_groups = free_groups
        self.occupancy = occupancy
        self.submitted = []

    def submit(self, request, timeout=None):
        self.submitted.append(request)
        return _StubHandle(len(self.submitted), request)

    def cancel(self, h):
        return True

    def status(self):
        return {"schema": 1, "queue_depth": self.queue_depth,
                "staged": 0, "free_groups": self.free_groups,
                "group": 16, "occupancy_now": self.occupancy,
                "nlanes": 64, "busy_lanes": 32, "faults": {},
                "slo": {"admission_ms": None},
                "slo_raw": {"admission_ms": [1.0 * self.queue_depth]},
                "tenants": []}

    def healthz(self):
        return {"ok": True, "error": None}

    def reset_counters(self):
        pass

    def close(self, grace=0):
        pass


def _router(pools, **kw):
    from gibbs_student_t_tpu.serve.router import FleetRouter

    kw.setdefault("failover", False)
    return FleetRouter(pools, **kw)


def test_router_places_by_load_and_counts():
    light = _FakePool("light", queue_depth=0, free_groups=3,
                      occupancy=0.2)
    heavy = _FakePool("heavy", queue_depth=5, free_groups=0,
                      occupancy=0.9)
    r = _router([heavy, light])
    req = TenantRequest(ma={}, niter=5, nchains=4, name="a")
    for _ in range(3):
        r.submit(req)
    assert len(light.submitted) == 3 and not heavy.submitted
    assert r.placements == {"light": 3}
    snap = r.fleet_status()
    assert snap["router"]["placements"] == {"light": 3}
    assert snap["n_reachable"] == 2
    assert r.healthz()["ok"] is True
    r.close()


def test_router_round_robin_spreads_deterministically():
    a, b = _FakePool("a"), _FakePool("b")
    r = _router([a, b], placement="round_robin")
    for i in range(4):
        r.submit(TenantRequest(ma={}, niter=5, nchains=4,
                               name=f"t{i}"))
    assert len(a.submitted) == 2 and len(b.submitted) == 2
    with pytest.raises(ValueError, match="placement"):
        _router([a], placement="fastest")
    r.close()


def test_router_uses_stale_snapshot_for_busy_pool():
    """A pool that stops answering status (its server lock is held for
    the whole quantum under load) is still PLACED ON through its
    cached snapshot — exclusion would bias every submit toward
    whichever pool is idle enough to answer. The cached queue_depth is
    bumped per placement so a burst still joins the shortest queue."""
    busy = _FakePool("busy", queue_depth=0, free_groups=4)
    other = _FakePool("other", queue_depth=1, free_groups=4)
    r = _router([busy, other])
    r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="warm"))
    assert len(busy.submitted) == 1     # busy was the lighter pool

    def timeout_now():
        raise TimeoutError("server lock held mid-quantum")

    busy.status = timeout_now
    # cached snapshot (queue 0 + 1 placed) still beats other's queue=1
    # exactly once; the bump then tips the balance to `other`
    r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="a"))
    r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="b"))
    assert len(busy.submitted) + len(other.submitted) == 3
    assert len(other.submitted) >= 1    # no starvation of the pollable
    # with the cache expired, the busy pool is finally excluded
    r.status_stale_s = 0.0
    r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="c"))
    assert len(other.submitted) >= 2
    snap = r.fleet_status()
    rows = {p["source"]: p["reachable"] for p in snap["pools"]}
    assert rows["other"] is True
    r.close()


def test_router_skips_unreachable_pool():
    ok = _FakePool("ok")
    down = _FakePool("down")

    def boom():
        raise ConnectionError("refused")

    down.status = boom
    r = _router([down, ok])
    r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="x"))
    assert len(ok.submitted) == 1 and not down.submitted
    snap = r.fleet_status()
    rows = {p["source"]: p["reachable"] for p in snap["pools"]}
    assert rows == {"down": False, "ok": True}
    r.close()


class _DyingPool(_FakePool):
    """A fake subprocess pool: 'dies' on demand, recovers into a
    replacement that knows one spooled tenant's new id."""

    def __init__(self, label):
        super().__init__(label)

        class _P:   # a Popen-shaped corpse detector
            def __init__(s):
                s.dead = False

            def poll(s):
                return 9 if s.dead else None

        self.proc = _P()
        self.recovered_into = None

    @property
    def alive(self):
        return self.proc.poll() is None

    def kill(self):
        self.proc.dead = True

    def recover(self):
        new = _FakePool(self.label + "'")
        new.ready = {"recovered": {"spooled": 77}}
        new.handle_for = lambda tid, request: _StubHandle(tid, request)
        self.recovered_into = new
        return new


def test_router_failover_rebinds_and_resubmits():
    """The failover unit: victims on the dead pool rebind (spooled ->
    the recovered pool's advertised id; unspooled -> replayed on a
    healthy pool); survivors and their pool are untouched."""
    dying = _DyingPool("dying")
    healthy = _FakePool("healthy", queue_depth=9)  # load prefers dying
    r = _router([dying, healthy], watch_poll_s=0.05, failover=True)
    spooled = r.submit(TenantRequest(ma={}, niter=5, nchains=4,
                                     name="spooled"))
    mem = r.submit(TenantRequest(ma={}, niter=5, nchains=4,
                                 name="mem"))
    # pin the bystander onto the healthy pool (make dying look loaded
    # for one placement decision)
    dying.queue_depth = 99
    bystander = r.submit(TenantRequest(ma={}, niter=5, nchains=4,
                                       name="by"))
    assert bystander.pool_idx == 1
    by_inner = bystander._inner
    dying.proc.dead = True
    deadline = time.monotonic() + 5
    while r.failovers == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert r.failovers == 1
    assert spooled._inner.tenant_id == 77          # rebound to recover
    assert spooled.pool_idx == 0
    # the unspooled victim was REPLAYED somewhere healthy
    assert r.resubmitted == 1
    assert mem._rebound.is_set()
    replay_targets = (healthy.submitted
                      + dying.recovered_into.submitted)
    assert any(q.name == "mem" for q in replay_targets)
    # the bystander on the co-resident pool is untouched
    assert bystander._inner is by_inner
    assert not any(q.name == "by" for q in
                   dying.recovered_into.submitted)
    assert r.pools[0] is dying.recovered_into
    snap = r.fleet_status()
    assert snap["router"]["failovers"] == 1
    r.close()


def test_finished_counts_severed_stream_as_victim():
    """A streamed RemoteTenantHandle on a crashed pool has _done SET
    (its stream reader resolved it to a ConnectionError before the
    watch thread saw the death) — the failover victim filter must NOT
    mistake that for a served tenant, or the handle is never
    rebound/resubmitted and its caller waits out the full
    failover_timeout for nothing."""
    from gibbs_student_t_tpu.serve.router import FleetRouter, RoutedHandle

    req = TenantRequest(ma={}, niter=5, nchains=4, name="v")
    done = _StubHandle(1, req)
    done._finish({"ok": True})
    rh_done = RoutedHandle(None, req, 0, done)
    assert FleetRouter._finished(rh_done) is True
    severed = _StubHandle(2, req)
    severed._error = ConnectionError("stream severed")
    severed._done.set()
    rh_severed = RoutedHandle(None, req, 0, severed)
    assert FleetRouter._finished(rh_severed) is False
    # a handle resolved to a TENANT failure is genuinely finished
    failed = _StubHandle(3, req)
    failed._error = RuntimeError("rejected")
    failed._done.set()
    assert FleetRouter._finished(RoutedHandle(None, req, 0,
                                              failed)) is True


def test_retryable_rechecks_generation_after_wait_timeout():
    """The lost-wakeup race: a rebind landing between _retryable's gen
    check and its _rebound.clear() has its set() discarded — after the
    wait times out the handle must re-check the generation and retry
    on the rebound inner instead of raising a ConnectionError for a
    failover that DID happen."""
    from gibbs_student_t_tpu.serve.router import RoutedHandle

    class _Router:
        failover_timeout = 0.05

    req = TenantRequest(ma={}, niter=5, nchains=4, name="r")
    rh = RoutedHandle(_Router(), req, 0, "old")

    class _RacingEvent:
        """clear() lands the rebind first — so its set() is exactly
        the wakeup the real clear() would discard — then reports an
        unset event whose wait() times out."""

        def clear(self):
            RoutedHandle._rebind(rh, 1, "new")

        def wait(self, timeout=None):
            return False

        def set(self):
            pass

    rh._rebound = _RacingEvent()
    calls = []

    def fn(inner):
        calls.append(inner)
        if inner == "old":
            raise ConnectionError("severed")
        return "served"

    assert rh._retryable(fn) == "served"
    assert calls == ["old", "new"]


# ---------------------------------------------------------------------------
# cost-aware placement (ROADMAP 1b) + the content-addressed model cache
# (ROADMAP 1c) + the warm-start wire (round 17)
# ---------------------------------------------------------------------------


def _tenant_entry(niter, sweeps_done, nchains, est=None, eff=None):
    t = {"niter": niter, "sweeps_done": sweeps_done,
         "nchains": nchains, "cost": {"ess_per_core_s": eff}}
    if est is not None:
        t["est_sweeps_to_target"] = est
    return t


def test_cost_aware_placement_prefers_draining_pool():
    """Equal queue/lanes/occupancy: the pool whose resident tenants
    are nearly converged (small est_sweeps_to_target) wins over one
    that just admitted its residents — and without tenant evidence
    the legacy ordering is untouched."""
    from gibbs_student_t_tpu.serve.router import FleetRouter

    near = _FakePool("near")
    far = _FakePool("far")
    near.status = lambda: dict(_FakePool.status(near), tenants=[
        _tenant_entry(200, 100, 16, est=10)])
    far.status = lambda: dict(_FakePool.status(far), tenants=[
        _tenant_entry(200, 100, 16, est=90)])
    r = _router([far, near])
    req = TenantRequest(ma={}, niter=5, nchains=4, name="c")
    r.submit(req)
    assert near.submitted and not far.submitted
    # est capped by the remaining budget (an evict tenant never
    # serves past either)
    st = dict(_FakePool.status(near), tenants=[
        _tenant_entry(200, 190, 16, est=500)])
    assert FleetRouter._est_backlog(st) == 10 * 16
    # no est -> remaining budget; no tenants -> 0 (legacy ordering)
    st2 = dict(_FakePool.status(near), tenants=[
        _tenant_entry(200, 150, 8)])
    assert FleetRouter._est_backlog(st2) == 50 * 8
    assert FleetRouter._est_backlog(_FakePool.status(far)) == 0.0


def test_cost_aware_placement_efficiency_and_tiebreak():
    """Backlog equal: higher pool ess_per_core_s wins; everything
    equal: the LOWEST pool index wins (the pinned deterministic
    tie-break)."""
    from gibbs_student_t_tpu.serve.router import FleetRouter

    slow = _FakePool("slow")
    fast = _FakePool("fast")
    slow.status = lambda: dict(_FakePool.status(slow), tenants=[
        _tenant_entry(100, 50, 16, est=20, eff=100.0)])
    fast.status = lambda: dict(_FakePool.status(fast), tenants=[
        _tenant_entry(100, 50, 16, est=20, eff=900.0)])
    r = _router([slow, fast])
    r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="e"))
    assert fast.submitted and not slow.submitted
    # the full tie: identical snapshots -> index order
    a, b = _FakePool("a"), _FakePool("b")
    r2 = _router([a, b])
    r2.submit(TenantRequest(ma={}, niter=5, nchains=4, name="t"))
    assert a.submitted and not b.submitted
    assert (FleetRouter._load_score(a.status())
            == FleetRouter._load_score(b.status()))


def test_warm_start_rides_the_wire():
    from gibbs_student_t_tpu.serve.rpc import (
        _request_body,
        _request_from_body,
    )
    from gibbs_student_t_tpu.serve.warm import (
        WarmStartFit,
        WarmStartSpec,
    )

    spec = WarmStartSpec(pilot_sweeps=12, pilot_chains=3,
                         burn_frac=0.25)
    req = TenantRequest(ma={"m": 1}, niter=5, nchains=4, name="w",
                        warm_start=spec)
    body = _request_body(req)
    req2 = _request_from_body(dict(body, ma={"m": 1}))
    assert isinstance(req2.warm_start, WarmStartSpec)
    assert req2.warm_start.pilot_sweeps == 12
    assert req2.warm_start.burn_frac == 0.25
    # a journaled fit passes through as its JSON dict (staging
    # reconstructs it — the recovery replay path)
    fit = WarmStartFit(means=np.zeros((1, 2)), stds=np.ones((1, 2)),
                       weights=np.ones(1))
    req3 = TenantRequest(ma={"m": 1}, niter=5, nchains=4, name="f",
                         warm_start=fit)
    body3 = _request_body(req3)
    req4 = _request_from_body(dict(body3, ma={"m": 1}))
    assert isinstance(req4.warm_start, dict)
    assert req4.warm_start["kind"] == "gmm"


def test_model_digest_negotiation_over_stub():
    """Submit the same model twice: the second submit omits the
    pickled model (digest hit). A fresh server that never saw the
    digest answers ``need_model`` and the client falls back — no
    caller-visible difference either way."""
    from gibbs_student_t_tpu.serve.rpc import (
        RemoteChainServer,
        RpcServer,
    )

    stub = _StubServer()
    seen = []
    orig = stub.submit

    def spy(request, timeout=None):
        seen.append(request.ma)
        return orig(request, timeout)

    stub.submit = spy
    rs = RpcServer(stub)
    try:
        cl = RemoteChainServer((rs.host, rs.port))
        ma = {"data": np.arange(4).tolist()}
        req = TenantRequest(ma=ma, niter=5, nchains=4, name="m1")
        cl.submit(req)
        cl.submit(req)          # digest hit: model not re-sent
        assert len(seen) == 2 and seen[0] == ma and seen[1] == ma
        assert len(cl._server_has) == 1
        # a NEW client against the same server: first submit already
        # omits nothing, but a client that WRONGLY believes the
        # server has a digest recovers through need_model
        cl2 = RemoteChainServer((rs.host, rs.port))
        d = cl2._digest_of(ma)
        with rs._model_lock:
            rs._model_cache.clear()    # force the miss
        cl2._server_has.add(d)
        cl2.submit(req)
        assert len(seen) == 3 and seen[2] == ma
    finally:
        rs.close()


def test_manifest_model_store_content_addressed(tmp_path):
    """One blob per distinct model, shared across admits; compaction
    prunes unreferenced digests (ROADMAP 1c)."""
    from gibbs_student_t_tpu.serve.manifest import (
        MODELS_DIR,
        ServerManifest,
        load_tenant_model,
        outstanding_tenants,
    )

    d = str(tmp_path / "man")
    man = ServerManifest(d)
    man.record_server({"t": 1}, {"c": 2}, {"nlanes": 32})
    ma = {"model": list(range(16))}
    req1 = TenantRequest(ma=ma, niter=5, nchains=4, name="a",
                         spool_dir=str(tmp_path / "s1"))
    req2 = TenantRequest(ma=ma, niter=5, nchains=4, name="b",
                         spool_dir=str(tmp_path / "s2"))
    man.record_admit(0, req1, model=ma,
                     warm={"kind": "gmm", "means": [[0.0]],
                           "stds": [[1.0]], "weights": [1.0]})
    man.record_admit(1, req2, model=ma)
    mdir = os.path.join(d, MODELS_DIR)
    assert len(os.listdir(mdir)) == 1      # stored once
    recoverable, _ = outstanding_tenants(d)
    assert len(recoverable) == 2
    assert recoverable[0]["model_digest"] == \
        recoverable[1]["model_digest"]
    assert recoverable[0]["warm"]["kind"] == "gmm"
    assert load_tenant_model(d, recoverable[0]) == ma
    # tenant 1 finishes; compaction keeps the digest tenant 0 (still
    # outstanding) references
    man.record_done(1, "done", 5)
    man.compact()
    assert len(os.listdir(mdir)) == 1
    recoverable2, _ = outstanding_tenants(d)
    assert [r["tenant"] for r in recoverable2] == [0]
    assert load_tenant_model(d, recoverable2[0]) == ma
    # last one done: the blob is pruned
    man.record_done(0, "done", 5)
    man.compact()
    assert os.listdir(mdir) == []


# ---------------------------------------------------------------------------
# live migration: router logic over fakes (round 18)
# ---------------------------------------------------------------------------


class _MigFakePool(_FakePool):
    """A fake whose cancel resolves the handle the way a real pool's
    cancel-freeze does (the migration fencing wait polls done())."""

    def cancel(self, h):
        h._done.set()
        return True


def test_migrate_queued_replay_over_fakes():
    src = _MigFakePool("src", queue_depth=3, free_groups=0)
    dst = _MigFakePool("dst", queue_depth=0, free_groups=2)
    r = _router([src, dst])
    rh = r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="j"),
                  pool=0)
    assert len(src.submitted) == 1
    assert r.migrate(rh, 1) is True
    # a queued victim (nothing served, no spool) is REPLAYED verbatim
    assert len(dst.submitted) == 1
    assert dst.submitted[0] is rh.request
    assert rh.pool_idx == 1 and r.migrations == 1
    assert not rh._migrating.is_set()
    # nothing to migrate twice: the handle now lives on dst
    assert r.migrate(rh, 1) is False
    r.close()


def test_migrate_invalidates_both_status_caches():
    """The respawn/migration staleness fix (ISSUE 15 satellite): after
    a migration both pools' cached snapshots are dropped AND fenced
    against an in-flight poll re-caching the pre-migration load — a
    freshly drained/loaded pool must never hide behind its old
    snapshot for a full TTL."""
    src = _MigFakePool("src", queue_depth=2, free_groups=0)
    dst = _MigFakePool("dst", queue_depth=0, free_groups=2)
    spare = _MigFakePool("spare", queue_depth=9, free_groups=0)
    r = _router([src, dst, spare])
    with r._lock:
        r._statuses()                       # seed every cache entry
    assert set(r._status_cache) == {0, 1, 2}
    rh = r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="j"),
                  pool=0)
    gen0 = r._status_gen.get(0, 0)
    assert r.migrate(rh, 1)
    assert 0 not in r._status_cache and 1 not in r._status_cache
    assert 2 in r._status_cache             # untouched pool keeps its
    assert r._status_gen[0] == gen0 + 1     # snapshot; src is fenced
    # the fence: a poll that STARTED before the invalidation cannot
    # write its stale snapshot back afterwards
    with r._lock:
        gen_now = r._status_gen[0]
        r._status_gen[0] = gen_now + 1      # invalidation lands mid-poll
        if r._status_gen.get(0, 0) == gen_now:   # the _statuses guard
            r._status_cache[0] = (0.0, {"stale": True})
    assert 0 not in r._status_cache
    r.close()


def test_rebalance_policy_steals_queued_from_loaded_pool():
    """The drained pool (free groups, empty queue) steals from the
    most-loaded pool; a queued victim is preferred (replay beats a
    checkpoint round-trip)."""
    src = _MigFakePool("src", queue_depth=4, free_groups=0,
                       occupancy=1.0)
    dst = _MigFakePool("dst", queue_depth=0, free_groups=2,
                       occupancy=0.5)
    r = _router([src, dst])
    rh = r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="q"),
                  pool=0)
    assert r._rebalance_once() is True
    assert rh.pool_idx == 1 and len(dst.submitted) == 1
    # balanced fleet: no candidates, no churn
    src.queue_depth = 0
    assert r._rebalance_once() is False
    r.close()


def test_rebalance_policy_skips_streamed_and_oversized():
    src = _MigFakePool("src", queue_depth=4, free_groups=0)
    dst = _MigFakePool("dst", queue_depth=0, free_groups=2)
    r = _router([src, dst])
    # streamed tenants are pinned to their pool; an oversized tenant
    # cannot fit the destination's free lanes (2 groups x 16)
    r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="s",
                           on_chunk=lambda *a: None), pool=0)
    r.submit(TenantRequest(ma={}, niter=5, nchains=64, name="big"),
             pool=0)
    assert r._rebalance_once() is False
    assert not dst.submitted
    r.close()


def test_migration_failure_poisons_the_handle():
    """A migration that cancelled the tenant and then could not
    resume it anywhere must not pass the served prefix off as the
    result: the handle raises, the failure is counted."""
    src = _MigFakePool("src")
    dst = _MigFakePool("dst")
    r = _router([src, dst])
    rh = r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="j"),
                  pool=0)

    def refuse(request, timeout=None):
        raise RuntimeError("pool full")

    src.submit = refuse
    dst.submit = refuse
    with pytest.raises(RuntimeError, match="could not be resumed"):
        r.migrate(rh, 1)
    assert r.migration_failures == 1 and r.migrations == 0
    with pytest.raises(RuntimeError, match="served prefix"):
        rh.result(timeout=0.5)
    r.close()


def test_routed_handle_rides_through_migration_latch():
    """A caller blocked in result() while the source's cancel-freeze
    resolves the OLD inner must NOT receive the prefix: the latch
    discards pre-migration outcomes until the rebind lands."""
    src = _MigFakePool("src")
    dst = _MigFakePool("dst")
    r = _router([src, dst])
    rh = r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="j"),
                  pool=0)
    old = rh._inner
    out = {}
    waiter = threading.Thread(
        target=lambda: out.update(res=rh.result(timeout=30)),
        daemon=True)
    rh._migrating.set()
    waiter.start()
    old._finish("PREFIX")
    old._done.set()
    time.sleep(0.3)
    assert "res" not in out          # the prefix was discarded
    new = _StubHandle(99, rh.request)
    rh._rebind(1, new)
    rh._migrating.clear()
    new._finish("REAL")
    new._done.set()
    waiter.join(timeout=10)
    assert out.get("res") == "REAL"
    r.close()
