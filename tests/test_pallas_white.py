"""Fused white-noise MH block (ops/pallas_white.py), interpret mode on CPU.

Covers the trace-time constant folding against ``models.pta.ndiag``, the
kernel-vs-XLA-loop parity on identical precomputed draws, the
out-of-bounds -inf prior reject semantics, the padded-row contract, the
custom-vmap dispatch, and whole-sweep chain equivalence through the
backend on identical keys.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gibbs_student_t_tpu.backends import JaxGibbs
from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.data.demo import make_demo_model_arrays
from gibbs_student_t_tpu.models.pta import lnprior, ndiag
from gibbs_student_t_tpu.ops.pallas_white import (
    build_white_consts,
    make_white_block,
    white_mh_fused,
    white_mh_loop_xla,
)


def _varying_efac_ma(n=24, seed=0):
    """A ModelArrays with BOTH a varying efac and a varying equad group,
    exercising the kind-0 (q^2) and kind-1 (10^2q) kernel coefficients
    (the demo model pins efac to the reference's Constant(1))."""
    import dataclasses

    ma = make_demo_model_arrays(n=n, components=4, seed=seed)
    # turn the constant efac group into a sampled parameter appended at
    # the end of the vector, with a uniform prior like the notebook model
    specs = np.vstack([np.asarray(ma.prior_specs),
                       [0.0, 0.2, 10.0, 1.0]])
    return dataclasses.replace(
        ma,
        efac_idx=(len(ma.param_names),),
        param_names=ma.param_names + ("B0000_efac",),
        prior_specs=specs,
    )


def _loop2(x, az, yred2, dx, logu, wc):
    """Single-model convenience wrapper over the consts-as-operands
    signature."""
    return white_mh_loop_xla(x, az, yred2, dx, logu, wc.rows, wc.specs,
                             wc.var)


def _fused2(x, az, yred2, dx, logu, wc, **kw):
    """Single-model (G == 1) wrapper over the grouped fused kernel."""
    xf, acc = white_mh_fused(
        x[None], az[None], yred2[None], dx[None], logu[None],
        jnp.asarray(wc.rows)[None], jnp.asarray(wc.specs)[None],
        wc.var, **kw)
    return xf[0], acc[0]


def _rand_inputs(ma, C, S=7, seed=1):
    rng = np.random.default_rng(seed)
    p = ma.nparam
    n = ma.n
    x = np.stack([ma.x_init(rng) for _ in range(C)]).astype(np.float32)
    az = np.exp(rng.standard_normal((C, n)) * 0.1).astype(np.float32)
    yred2 = (rng.standard_normal((C, n)) ** 2).astype(np.float32)
    white = ma.white_indices
    pars = rng.integers(0, len(white), (C, S))
    jumps = rng.standard_normal((C, S)).astype(np.float32) * 0.3
    dx = np.zeros((C, S, p), np.float32)
    for c in range(C):
        for s in range(S):
            dx[c, s, white[pars[c, s]]] = jumps[c, s]
    logu = np.log(rng.uniform(size=(C, S))).astype(np.float32)
    return (jnp.asarray(x), jnp.asarray(az), jnp.asarray(yred2),
            jnp.asarray(dx), jnp.asarray(logu))


def test_consts_fold_matches_ndiag():
    """nv0 + varying coefficients must reproduce models.pta.ndiag."""
    ma = _varying_efac_ma()
    wc = build_white_consts(ma)
    assert len(wc.var) == 2  # one varying efac + one varying equad
    rng = np.random.default_rng(3)
    x = ma.x_init(rng)
    nd_ref = ndiag(ma, x, np)
    nd = wc.rows[0].astype(np.float64).copy()
    for vkind, idx, slot in wc.var:
        c = x[idx] ** 2 if vkind == 0 else 10.0 ** (2.0 * x[idx])
        nd += c * wc.rows[slot].astype(np.float64)
    np.testing.assert_allclose(nd, nd_ref, rtol=1e-5)


def test_consts_fold_constant_groups_into_baseline():
    ma = make_demo_model_arrays(n=16, components=3, seed=2)
    wc = build_white_consts(ma)
    kinds = [v[0] for v in wc.var]
    assert kinds == [1]  # only the equad varies; constant efac folded
    # the folded baseline is efac_const^2 * sigma2
    np.testing.assert_allclose(
        wc.rows[0], np.asarray(ma.sigma2, np.float32), rtol=1e-6)


@pytest.mark.parametrize("varying_efac", [False, True])
def test_kernel_matches_xla_loop(varying_efac):
    ma = _varying_efac_ma() if varying_efac else make_demo_model_arrays(
        n=24, components=4, seed=0)
    wc = build_white_consts(ma)
    args = _rand_inputs(ma, C=11, seed=4)
    x1, a1 = jax.jit(lambda *a: _fused2(
        *a, wc=wc, chain_tile=8, interpret=True))(*args)
    x0, a0 = jax.jit(lambda *a: _loop2(*a, wc=wc))(*args)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0))


def test_out_of_bounds_proposal_always_rejected():
    ma = make_demo_model_arrays(n=16, components=3, seed=1)
    wc = build_white_consts(ma)
    x, az, yred2, dx, logu = _rand_inputs(ma, C=4, S=3, seed=5)
    # every proposal jumps the equad coordinate far past its prior bound
    big = np.zeros(np.asarray(dx).shape, np.float32)
    big[:, :, ma.white_indices[0]] = 1e4
    logu = jnp.full_like(logu, -1e30)  # accept anything with finite delta
    x1, acc = _loop2(x, az, yred2, jnp.asarray(big), logu, wc)
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x))
    assert float(jnp.max(acc)) == 0.0
    x2, acc2 = _fused2(x, az, yred2, jnp.asarray(big), logu, wc,
                       chain_tile=8, interpret=True)
    np.testing.assert_array_equal(np.asarray(x2), np.asarray(x))
    assert float(jnp.max(acc2)) == 0.0


@pytest.mark.slow
def test_padded_rows_contribute_nothing():
    """A suffix-padded model (rmask zeros) must give the same block
    output as the unpadded model: pads carry az=1, yred2=0, rmask=0."""
    import dataclasses

    ma = make_demo_model_arrays(n=20, components=3, seed=6)
    wc = build_white_consts(ma)
    x, az, yred2, dx, logu = _rand_inputs(ma, C=6, seed=7)

    pad = 12
    ma_p = dataclasses.replace(
        ma,
        y=np.concatenate([ma.y, np.zeros(pad)]),
        T=np.vstack([ma.T, np.zeros((pad, ma.m))]),
        sigma2=np.concatenate([ma.sigma2, np.zeros(pad)]),
        efac_masks=np.hstack([ma.efac_masks,
                              np.zeros((ma.efac_masks.shape[0], pad))]),
        equad_masks=np.hstack([ma.equad_masks,
                               np.zeros((ma.equad_masks.shape[0], pad))]),
    )
    rmask = np.concatenate([np.ones(20), np.zeros(pad)])
    wc_p = build_white_consts(ma_p, row_mask=rmask)
    az_p = jnp.concatenate(
        [az, jnp.ones((az.shape[0], pad), az.dtype)], axis=1)
    y2_p = jnp.concatenate(
        [yred2, jnp.zeros((yred2.shape[0], pad), yred2.dtype)], axis=1)

    x0, a0 = _loop2(x, az, yred2, dx, logu, wc)
    x1, a1 = _loop2(x, az_p, y2_p, dx, logu, wc_p)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x0), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a0))
    x2, a2 = _fused2(x, az_p, y2_p, dx, logu, wc_p,
                     chain_tile=8, interpret=True)
    np.testing.assert_allclose(np.asarray(x2), np.asarray(x0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(a2), np.asarray(a0))


def test_loop_matches_closure_semantics():
    """The array-based loop must agree with a straightforward
    closure-based MH loop over the same draws (the reference block
    semantics, gibbs.py:114-143)."""
    ma = _varying_efac_ma(n=18, seed=8)
    wc = build_white_consts(ma)
    x, az, yred2, dx, logu = _rand_inputs(ma, C=3, S=9, seed=9)
    x1, a1 = _loop2(x, az, yred2, dx, logu, wc)

    specs = jnp.asarray(ma.prior_specs, jnp.float32)
    for c in range(3):
        xc = np.asarray(x[c], np.float64)
        ll0 = None
        acc = 0
        for s in range(dx.shape[1]):
            q = xc + np.asarray(dx[c, s], np.float64)

            def llp(v):
                nv = np.asarray(az[c], np.float64) * ndiag(ma, v, np)
                ll = -0.5 * float(
                    np.sum(np.log(nv))
                    + np.sum(np.asarray(yred2[c], np.float64) / nv))
                return ll + float(lnprior(ma, v, np))

            if ll0 is None:
                ll0 = llp(xc)
            ll1 = llp(q)
            if ll1 - ll0 > float(logu[c, s]):
                xc, ll0 = q, ll1
                acc += 1
        np.testing.assert_allclose(np.asarray(x1[c]), xc,
                                   rtol=1e-4, atol=1e-5)
        assert acc == round(float(a1[c]) * dx.shape[1])


@pytest.mark.slow
def test_dispatch_under_vmap(monkeypatch):
    ma = make_demo_model_arrays(n=24, components=4, seed=0)
    wc = build_white_consts(ma)
    block = make_white_block(wc.var)
    args = _rand_inputs(ma, C=9, seed=10)
    rows = jnp.asarray(wc.rows)
    specs = jnp.asarray(wc.specs)

    # constants unbatched under the chain vmap (the backend's pattern)
    monkeypatch.setenv("GST_PALLAS_WHITE", "interpret")
    x1, a1 = jax.vmap(block, in_axes=(0, 0, 0, 0, 0, None, None))(
        *args, rows, specs)
    monkeypatch.setenv("GST_PALLAS_WHITE", "0")
    x0, a0 = jax.vmap(block, in_axes=(0, 0, 0, 0, 0, None, None))(
        *args, rows, specs)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0))


@pytest.mark.slow
def test_grouped_kernel_matches_per_group_loop(monkeypatch):
    """The grouped (per-pulsar constants) kernel path must reproduce the
    per-group XLA loop: G models with different variance structure, one
    launch."""
    G, C = 3, 6
    mas = [make_demo_model_arrays(n=24, components=4, seed=20 + g)
           for g in range(G)]
    wcs = [build_white_consts(ma) for ma in mas]
    assert all(wc.var == wcs[0].var for wc in wcs)
    per = [_rand_inputs(ma, C=C, seed=30 + g) for g, ma in enumerate(mas)]
    gx, gaz, gy2, gdx, glu = (jnp.stack([p[i] for p in per])
                              for i in range(5))
    rows = jnp.asarray(np.stack([wc.rows for wc in wcs]))
    specs = jnp.asarray(np.stack([wc.specs for wc in wcs]))

    xf, af = white_mh_fused(gx, gaz, gy2, gdx, glu, rows, specs,
                            wcs[0].var, chain_tile=8, interpret=True)
    for g in range(G):
        x0, a0 = _loop2(*per[g], wc=wcs[g])
        np.testing.assert_allclose(np.asarray(xf[g]), np.asarray(x0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(af[g]), np.asarray(a0))

    # the same route through the dispatcher's two-level vmap (chain axis
    # leaves constants unbatched; group axis batches them)
    block = make_white_block(wcs[0].var)
    monkeypatch.setenv("GST_PALLAS_WHITE", "interpret")
    xv, av = jax.vmap(jax.vmap(block, in_axes=(0, 0, 0, 0, 0, None,
                                               None)))(
        gx, gaz, gy2, gdx, glu, rows, specs)
    np.testing.assert_allclose(np.asarray(xv), np.asarray(xf),
                               rtol=1e-5, atol=1e-6)
    monkeypatch.setenv("GST_PALLAS_WHITE", "0")
    x2, a2 = jax.vmap(jax.vmap(block, in_axes=(0, 0, 0, 0, 0, None,
                                               None)))(
        gx, gaz, gy2, gdx, glu, rows, specs)
    np.testing.assert_allclose(np.asarray(x2), np.asarray(xf),
                               rtol=1e-5, atol=1e-6)


def test_auto_mode_stays_on_loop_on_cpu(monkeypatch):
    from gibbs_student_t_tpu.ops import pallas_white

    monkeypatch.delenv("GST_PALLAS_WHITE", raising=False)
    enabled, _, _ = pallas_white._pallas_white_mode()
    assert not enabled


def _rand_mtm_inputs(ma, C, S=5, K=3, seed=1):
    rng = np.random.default_rng(seed)
    x, az, yred2, _, _ = _rand_inputs(ma, C, S=1, seed=seed)
    p = ma.nparam
    white = ma.white_indices

    def jump_batch(m):
        pars = rng.integers(0, len(white), m)
        jumps = rng.standard_normal(m).astype(np.float32) * 0.3
        dx = np.zeros((m, p), np.float32)
        dx[np.arange(m), np.asarray(white)[pars]] = jumps
        return dx

    dx = np.stack([jump_batch(S * K) for _ in range(C)]).reshape(
        C, S, K, p)
    dxr = np.stack([jump_batch(S * (K - 1)) for _ in range(C)]).reshape(
        C, S, K - 1, p)
    gumb = rng.gumbel(size=(C, S, K)).astype(np.float32)
    logu = np.log(rng.uniform(size=(C, S))).astype(np.float32)
    return (x, az, yred2, jnp.asarray(dx), jnp.asarray(dxr),
            jnp.asarray(gumb), jnp.asarray(logu))


@pytest.mark.slow
def test_mtm_kernel_matches_xla_loop():
    """The fused white-MTM kernel (interpret) must reproduce the XLA
    MTM twin on identical precomputed draws — selection, weight-sum
    acceptance, and acceptance counting."""
    from gibbs_student_t_tpu.ops.pallas_white import (
        white_mtm_fused, white_mtm_loop_xla)

    ma = _varying_efac_ma()
    wc = build_white_consts(ma)
    args = _rand_mtm_inputs(ma, C=9, seed=21)
    x0, a0 = white_mtm_loop_xla(*args, wc.rows, wc.specs, wc.var)
    x1, a1 = white_mtm_fused(
        *(a[None] for a in args), jnp.asarray(wc.rows)[None],
        jnp.asarray(wc.specs)[None], wc.var, chain_tile=8,
        interpret=True)
    np.testing.assert_allclose(np.asarray(x1[0]), np.asarray(x0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a1[0]), np.asarray(a0))


@pytest.mark.slow
def test_mtm_grouped_kernel_matches_per_group_loop():
    from gibbs_student_t_tpu.ops.pallas_white import (
        white_mtm_fused, white_mtm_loop_xla)

    G, C = 2, 6
    mas = [make_demo_model_arrays(n=24, components=4, seed=60 + g)
           for g in range(G)]
    wcs = [build_white_consts(ma) for ma in mas]
    per = [_rand_mtm_inputs(ma, C=C, seed=70 + g)
           for g, ma in enumerate(mas)]
    grouped = tuple(jnp.stack([p[i] for p in per]) for i in range(7))
    rows = jnp.asarray(np.stack([wc.rows for wc in wcs]))
    specs = jnp.asarray(np.stack([wc.specs for wc in wcs]))
    xf, af = white_mtm_fused(*grouped, rows, specs, wcs[0].var,
                             chain_tile=8, interpret=True)
    for g in range(G):
        x0, a0 = white_mtm_loop_xla(*per[g], wcs[g].rows, wcs[g].specs,
                                    wcs[g].var)
        np.testing.assert_allclose(np.asarray(xf[g]), np.asarray(x0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(af[g]), np.asarray(a0))


@pytest.mark.slow
def test_sweep_chains_identical_mtm_fused_vs_closure(monkeypatch):
    """Whole-sweep MTM equivalence across all THREE implementations on
    identical keys: the validated _mtm_block closure (the reference
    semantics, forced by disabling the fused dispatcher), the XLA
    consts twin (kernel off), and the fused kernel (interpret)."""
    ma = make_demo_model_arrays(n=40, components=6, seed=3)
    cfg = GibbsConfig(model="mixture", vary_df=True,
                      theta_prior="beta").with_mtm(3, blocks=("white",))

    def run(flag, force_closure=False):
        monkeypatch.setenv("GST_PALLAS_WHITE", flag)
        gb = JaxGibbs(ma, cfg, nchains=6, chunk_size=5, record="full")
        assert gb._white_mtm_block is not None
        if force_closure:
            gb._white_mtm_block = None  # dispatch falls to _mtm_block
        return gb.sample(niter=10, seed=0)

    rc = run("0", force_closure=True)   # _mtm_block closure reference
    r0 = run("0")                       # white_mtm_loop_xla twin
    r1 = run("interpret")               # fused kernel
    for r in (r0, r1):
        np.testing.assert_allclose(np.asarray(r.chain),
                                   np.asarray(rc.chain),
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_array_equal(np.asarray(r.zchain),
                                      np.asarray(rc.zchain))


@pytest.mark.slow
def test_sweep_chains_identical_fused_vs_loop(monkeypatch):
    """Whole-sweep equivalence through the backend: same keys, kernel on
    (interpret) vs off. The fused path and the XLA loop consume the same
    precomputed draw arrays, so chains should agree to f32 rounding —
    and on this small case, exactly."""
    ma = make_demo_model_arrays(n=40, components=6, seed=3)
    cfg = GibbsConfig(model="mixture", vary_df=True, theta_prior="beta")

    def run(flag):
        monkeypatch.setenv("GST_PALLAS_WHITE", flag)
        gb = JaxGibbs(ma, cfg, nchains=6, chunk_size=5, record="full")
        return gb.sample(niter=10, seed=0)

    r0 = run("0")
    r1 = run("interpret")
    np.testing.assert_allclose(np.asarray(r1.chain),
                               np.asarray(r0.chain),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_array_equal(np.asarray(r1.zchain),
                                  np.asarray(r0.zchain))
    np.testing.assert_allclose(
        np.asarray(r1.stats["acc_white"]),
        np.asarray(r0.stats["acc_white"]), atol=1e-6)
