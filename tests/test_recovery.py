"""Chain-level failure recovery: dead chains re-drawn from the prior."""

import numpy as np

import jax.numpy as jnp

from gibbs_student_t_tpu.backends import JaxGibbs
from gibbs_student_t_tpu.config import GibbsConfig


def _backend(demo_ma, nchains=4):
    return JaxGibbs(demo_ma, GibbsConfig(model="mixture", vary_df=True),
                    nchains=nchains, chunk_size=5)


def test_diverged_mask_flags_nonfinite_and_nonpositive(demo_ma):
    gb = _backend(demo_ma)
    state = gb.init_state(seed=0)
    assert not gb.diverged_mask(state).any()
    state = state._replace(
        x=state.x.at[1, 0].set(jnp.nan),
        alpha=state.alpha.at[3, 2].set(-1.0),
    )
    np.testing.assert_array_equal(gb.diverged_mask(state),
                                  [False, True, False, True])


def test_reinit_replaces_only_dead_chains(demo_ma):
    gb = _backend(demo_ma)
    state = gb.init_state(seed=0)
    broken = state._replace(x=state.x.at[2].set(jnp.inf),
                            mh_log_scale=state.mh_log_scale + 0.7)
    fixed, n_bad = gb._reinit_diverged(broken, seed=123)
    assert n_bad == 1
    assert np.isfinite(np.asarray(fixed.x)).all()
    # healthy chains bitwise untouched
    for i in (0, 1, 3):
        np.testing.assert_array_equal(np.asarray(fixed.x)[i],
                                      np.asarray(state.x)[i])
    # adapted MH jump scales survive re-init: Robbins-Monro may already
    # be frozen, and a zeroed scale would run un-adapted forever after
    np.testing.assert_array_equal(np.asarray(fixed.mh_log_scale),
                                  np.asarray(broken.mh_log_scale))


def test_sample_recovers_injected_divergence(demo_ma):
    gb = _backend(demo_ma)
    state = gb.init_state(seed=0)
    # NaN in x is sticky: every MH proposal from it evaluates to a NaN
    # likelihood and never accepts (b, by contrast, is redrawn fresh every
    # sweep, so it self-heals without recovery)
    state = state._replace(x=state.x.at[0].set(jnp.nan))
    res = gb.sample(niter=10, seed=0, state=state, reinit_diverged=True)
    assert int(res.stats["n_reinits"]) >= 1
    # after recovery the population is healthy again
    assert not gb.diverged_mask(gb.last_state).any()
    assert np.isfinite(res.chain[-1]).all()
