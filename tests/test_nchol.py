"""Native lane-batched FFI kernels: parity pins, the GST_NCHOL
dispatch, graceful degradation, and the committed-.so staleness guard
(ISSUE 4).

All CPU-fast. The backend arms reuse the vchol module's arm-sharing
pattern (one compiled backend per gate arm, shared by every pin) to
stay inside the 870 s / 1-core tier-1 budget.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular

from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.ops import linalg

from tests.conftest import make_demo_pta, make_demo_pulsar

pytestmark = pytest.mark.nchol

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

nffi = pytest.importorskip("gibbs_student_t_tpu.native.ffi")


def _require_kernels():
    if not nffi.ready():
        pytest.skip(f"native FFI kernels unavailable: {nffi.status()}")


def _spd(C, m, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((C, m, max(m // 2, 4)))
    S = A @ np.swapaxes(A, -1, -2) + 10.0 * np.eye(m)
    return (jnp.asarray(S, dtype),
            jnp.asarray(rng.standard_normal((C, m)), dtype),
            jnp.asarray(rng.standard_normal((C, m, 5)), dtype))


@pytest.fixture(scope="module")
def small_ma():
    psr, _ = make_demo_pulsar(seed=3, n=50, theta=0.1)
    return make_demo_pta(psr, components=6).frozen()


# ----------------------------------------------------------------------
# f64 parity pins: every kernel vs the LAPACK/expander path
# ----------------------------------------------------------------------


@pytest.mark.parametrize("m", [16, 21, 60])  # lane-exact, odd, flagship-v
def test_nchol_f64_parity(m):
    """|dL|, |dlogdet|, |du| and every solve orientation <= 1e-9 against
    the LAPACK/expander path on identical inputs (measured agreement is
    ~1e-14 — different reduction order, same math)."""
    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        C = 19  # odd batch: exercises the pad-lane tail tile
        S, r, R = _spd(C, m)
        L0 = jnp.linalg.cholesky(S)
        ld0 = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(L0, axis1=-2, axis2=-1)), -1)
        u0 = solve_triangular(L0, r[..., None], lower=True)[..., 0]
        L1, ld1, u1 = nffi.nchol_factor(S, r)
        np.testing.assert_allclose(L1, L0, atol=1e-9)
        np.testing.assert_allclose(ld1, ld0, atol=1e-9)
        np.testing.assert_allclose(u1, u0, atol=1e-9)
        np.testing.assert_allclose(
            nffi.fwd_vec(L0, r), u0, atol=1e-9)
        np.testing.assert_allclose(
            nffi.bwd_vec(L0, r),
            solve_triangular(L0, r, lower=True, trans="T"), atol=1e-9)
        np.testing.assert_allclose(
            nffi.fwd_mat(L0, R), solve_triangular(L0, R, lower=True),
            atol=1e-9)
        np.testing.assert_allclose(
            nffi.bwd_mat(L0, R),
            solve_triangular(L0, R, lower=True, trans="T"), atol=1e-9)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_nchol_stacked_jitter_batch_shape():
    """The robust_precond_cholesky shape (jitter levels stacked on a new
    leading axis): rank-4 batches flatten correctly."""
    _require_kernels()
    S, r, _ = _spd(9, 12, dtype=np.float32)
    Ss = jnp.broadcast_to(S, (4,) + S.shape)
    rs = jnp.broadcast_to(r, (4,) + r.shape)
    Ls, lds, us = nffi.nchol_factor(Ss, rs)
    assert Ls.shape == Ss.shape and lds.shape == (4, 9)
    L1, ld1, u1 = nffi.nchol_factor(S, r)
    for k in range(4):
        np.testing.assert_array_equal(Ls[k], L1)
        np.testing.assert_array_equal(lds[k], ld1)


def test_nchol_chisq_parity_f64():
    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.default_rng(2)
        kmax = 31
        xs = jnp.asarray(rng.standard_normal((64, 13, kmax)))
        cnt = jnp.asarray(rng.integers(0, kmax + 1, (64, 13)),
                          jnp.float64)
        ref = 0.5 * jnp.sum(
            jnp.where(jnp.arange(kmax) < cnt[..., None], xs * xs, 0.0),
            -1)
        np.testing.assert_allclose(nffi.chisq(xs, cnt), ref, atol=1e-9)
        # short rows take the scalar path (kmax < lane width)
        xs4 = xs[..., :4]
        cnt4 = jnp.minimum(cnt, 4.0)
        ref4 = 0.5 * jnp.sum(
            jnp.where(jnp.arange(4) < cnt4[..., None], xs4 * xs4, 0.0),
            -1)
        np.testing.assert_allclose(nffi.chisq(xs4, cnt4), ref4,
                                   atol=1e-9)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_nchol_nonpd_nan_propagation():
    """A non-PD batch member poisons ITS logdet/solve with non-finite
    values (the branchless -inf -> MH-reject signal) and leaves the
    other chains alone — including members in the same SIMD lane tile."""
    _require_kernels()
    m = 12
    S = np.eye(m)[None].repeat(3, 0)
    S[1, 0, 0] = -1.0  # non-PD in chain 1 only
    L, ld, u = nffi.nchol_factor(jnp.asarray(S, jnp.float32),
                                 jnp.ones((3, m), jnp.float32))
    ld = np.asarray(ld)
    assert np.isfinite(ld[0]) and np.isfinite(ld[2])
    assert np.isnan(ld[1])
    assert np.isnan(np.asarray(u[1])).all()
    assert np.isfinite(np.asarray(u[0])).all()
    assert np.isfinite(np.asarray(u[2])).all()
    # zero pivot: logdet -inf (still non-finite, still rejects)
    S0 = np.eye(m)[None].copy()
    S0[0, -1, -1] = 0.0
    _, ld0, _ = nffi.nchol_factor(jnp.asarray(S0, jnp.float32),
                                  jnp.ones((1, m), jnp.float32))
    assert not np.isfinite(np.asarray(ld0[0]))


def test_nchol_factor_quad_bitwise_matches_factor():
    """The no-L kernel is the same recurrence with the L store skipped:
    logdet/u must be BITWISE identical to the full factor kernel's."""
    _require_kernels()
    S, r, _ = _spd(37, 21, dtype=np.float32)  # odd batch: pad-lane tile
    L, ld0, u0 = jax.jit(nffi.nchol_factor)(S, r)
    ld1, u1 = jax.jit(nffi.nchol_factor_quad)(S, r)
    np.testing.assert_array_equal(np.asarray(ld1), np.asarray(ld0))
    np.testing.assert_array_equal(np.asarray(u1), np.asarray(u0))


def test_nchol_robust_draw_f64_parity():
    """The fused escalating-jitter factor+draw vs the stacked
    robust_precond_cholesky + backward_solve composition on identical
    inputs at f64 1e-9 — including members that escalate past level 0
    and a member no level can rescue (NaN propagates, others alone)."""
    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.default_rng(7)
        C, m = 37, 21
        A = rng.standard_normal((C, m, 12))
        S = jnp.asarray(A @ np.swapaxes(A, -1, -2) + 5.0 * np.eye(m))
        # chains 3 and 17: 2*ones - I has unit diagonal (so the
        # equilibration is finite) but eigenvalues {2m-1, -1}: non-PD
        # until the final jitter level (j > 1) — the full escalation
        # cascade. chain 30: negative diagonal, hopeless at every level.
        hard = jnp.asarray(2.0 * np.ones((m, m)) - np.eye(m))
        S = (S.at[3].set(hard).at[17].set(hard)
             .at[30].add(-1e6 * jnp.eye(m)))
        r = jnp.asarray(rng.standard_normal((C, m)))
        xi = jnp.asarray(rng.standard_normal((C, m)))
        jitters = (0.0, 1e-4, 1e-2, 40.0)
        L0, isd0, ld0, u0 = linalg.robust_precond_cholesky(
            S, jitters=jitters, rhs=r)
        y0 = linalg.backward_solve(L0, u0 + xi)
        # force the dispatcher's native branch (batch 37 > floor)
        y1, isd1, ld1 = jax.jit(lambda s, rr, x: linalg.robust_precond_draw(
            s, rr, x, jitters=jitters))(S, r, xi)
        ok = np.isfinite(np.asarray(y0)).all(axis=-1)
        assert ok[3] and ok[17] and not ok[30]
        np.testing.assert_allclose(np.asarray(y1)[ok], np.asarray(y0)[ok],
                                   atol=1e-9)
        np.testing.assert_allclose(np.asarray(ld1)[ok],
                                   np.asarray(ld0)[ok], atol=1e-9)
        np.testing.assert_allclose(isd1, isd0, atol=1e-12)
        assert not np.isfinite(np.asarray(y1)[30]).all()
    finally:
        jax.config.update("jax_enable_x64", False)


def test_nchol_tnt_f64_parity():
    """The lane-batched Gram reduction vs the dense jnp expressions at
    f64 1e-9, on odd batch/width shapes that exercise the pad-lane
    tile and the overlapped transpose tails."""
    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.default_rng(11)
        C, n, m = 37, 53, 19
        T = jnp.asarray(rng.standard_normal((n, m)))
        y = jnp.asarray(rng.standard_normal((n,)))
        nvec = jnp.asarray(rng.uniform(0.5, 3.0, (C, n)))
        TNT0, d0, c0 = jax.vmap(
            lambda nv: linalg._tnt_gram_jnp(T, y, nv))(nvec)
        TNT1, d1, c1 = jax.jit(nffi.tnt)(T, y, nvec)
        np.testing.assert_allclose(TNT1, TNT0, atol=1e-9)
        np.testing.assert_allclose(d1, d0, atol=1e-9)
        np.testing.assert_allclose(c1, c0, atol=1e-9)
        # full symmetric output (both triangles written)
        np.testing.assert_array_equal(
            np.asarray(TNT1), np.swapaxes(np.asarray(TNT1), -1, -2))
    finally:
        jax.config.update("jax_enable_x64", False)


def test_nchol_tnt_nonfinite_propagation():
    """A non-positive nvec entry poisons ITS chain's const (log of a
    negative) while the other chains' outputs stay finite — the same
    per-chain containment contract as the factor kernels."""
    _require_kernels()
    rng = np.random.default_rng(13)
    C, n, m = 5, 40, 9
    T = jnp.asarray(rng.standard_normal((n, m)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((n,)), jnp.float32)
    nvec = np.asarray(rng.uniform(0.5, 2.0, (C, n)), np.float32)
    nvec[2, 7] = -1.0
    TNT, d, c = jax.jit(nffi.tnt)(T, y, jnp.asarray(nvec))
    c = np.asarray(c)
    assert not np.isfinite(c[2])
    keep = np.asarray([0, 1, 3, 4])
    assert np.isfinite(c[keep]).all()
    assert np.isfinite(np.asarray(TNT)[keep]).all()
    assert np.isfinite(np.asarray(d)[keep]).all()


# ----------------------------------------------------------------------
# gate validation + dispatch
# ----------------------------------------------------------------------


def test_nchol_env_validation(monkeypatch, small_ma):
    """Strict auto|1|0 whenever set (the loud-typo contract), enforced
    both by nchol_env() and at backend construction."""
    from gibbs_student_t_tpu.backends import JaxGibbs

    monkeypatch.delenv("GST_NCHOL", raising=False)
    assert linalg.nchol_env() == "auto"
    monkeypatch.setenv("GST_NCHOL", "interpret")  # pallas-ism: rejected
    with pytest.raises(ValueError, match="GST_NCHOL"):
        linalg.nchol_env()
    monkeypatch.setenv("GST_NCHOL", "bogus")
    with pytest.raises(ValueError, match="GST_NCHOL"):
        JaxGibbs(small_ma, GibbsConfig(model="mixture"), nchains=2)
    for ok in ("auto", "1", "0"):
        monkeypatch.setenv("GST_NCHOL", ok)
        JaxGibbs(small_ma, GibbsConfig(model="mixture"), nchains=2)


def test_dispatch_prefers_nchol_on_cpu(monkeypatch):
    """Through the custom_vmap fold at an in-sweep shape, auto resolves
    to the native kernel on CPU (and the introspection log records it)."""
    _require_kernels()
    from gibbs_student_t_tpu.obs import introspect

    monkeypatch.delenv("GST_NCHOL", raising=False)
    introspect.clear_introspection()
    S, r, _ = _spd(32, 20, dtype=np.float32)
    q, ld = jax.jit(jax.vmap(
        lambda s, rr: linalg.precond_quad_logdet(s, rr, 1e-6)))(S, r)
    assert np.isfinite(np.asarray(q)).all()
    impls = {(rec["op"], rec["impl"])
             for rec in introspect.linalg_impls()}
    # r08: quad/logdet callers dispatch to the no-L kernel
    assert ("factor_quad", "nchol") in impls


def test_dispatch_degrades_without_library(monkeypatch):
    """The acceptance contract: with the library unavailable (absent
    .so / unregistered handlers), every entry point silently falls back
    — even under a forced GST_NCHOL=1 — and produces the same numbers
    as the portable path."""
    from gibbs_student_t_tpu import native as native_mod
    from gibbs_student_t_tpu.native import ffi as nffi_mod

    S, r, _ = _spd(32, 20, dtype=np.float32)
    f = lambda s, rr: linalg.precond_quad_logdet(s, rr, 1e-6)  # noqa: E731

    monkeypatch.setenv("GST_NCHOL", "0")
    q_off, ld_off = jax.jit(jax.vmap(f))(S, r)

    # simulate the deleted-.so / no-handlers host: the probe fails
    monkeypatch.setattr(native_mod, "load", lambda build=False: None)
    nffi_mod._reset_for_tests()
    try:
        assert not nffi_mod.ready()
        monkeypatch.setenv("GST_NCHOL", "1")  # forced AND unavailable
        q_forced, ld_forced = jax.jit(jax.vmap(f))(S, r)
        np.testing.assert_array_equal(q_forced, q_off)
        np.testing.assert_array_equal(ld_forced, ld_off)
        # chisq dispatcher degrades identically
        xs = jnp.asarray(np.random.default_rng(0).standard_normal(
            (32, 7, 18)), jnp.float32)
        cnt = jnp.full((32, 7), 5.0, jnp.float32)
        g = linalg.masked_chisq(xs, cnt)
        assert np.isfinite(np.asarray(g)).all()
    finally:
        monkeypatch.undo()
        nffi_mod._reset_for_tests()
        assert nffi_mod.ready() == nffi_mod.ready()  # re-probe is clean


def test_masked_chisq_forced_native_matches_jnp(monkeypatch):
    """GST_NCHOL=1 routes masked_chisq to the kernel; auto keeps the
    jnp fusion (the measured A/B, cpu_microbench_r07). Both compute the
    same reduction."""
    _require_kernels()
    rng = np.random.default_rng(5)
    xs = jnp.asarray(rng.standard_normal((64, 9, 31)), jnp.float32)
    cnt = jnp.asarray(rng.integers(0, 32, (64, 9)), jnp.float32)
    monkeypatch.setenv("GST_NCHOL", "0")
    g_jnp = linalg.masked_chisq(xs, cnt)
    monkeypatch.setenv("GST_NCHOL", "1")
    g_nat = linalg.masked_chisq(xs, cnt)
    np.testing.assert_allclose(g_nat, g_jnp, rtol=2e-6, atol=2e-6)


def test_hyper_hoist_and_fast_beta_env_validation(monkeypatch, small_ma):
    """GST_HYPER_HOIST / GST_FAST_BETA follow the strict auto|1|0
    loud-typo contract of every GST_* gate, enforced at backend
    construction."""
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.backends.jax_backend import (
        _fast_beta_env,
        _hyper_hoist_env,
    )

    for var, fn in (("GST_HYPER_HOIST", _hyper_hoist_env),
                    ("GST_FAST_BETA", _fast_beta_env)):
        monkeypatch.delenv(var, raising=False)
        assert fn() == "auto"
        monkeypatch.setenv(var, "yes")
        with pytest.raises(ValueError, match=var):
            fn()
        with pytest.raises(ValueError, match=var):
            JaxGibbs(small_ma, GibbsConfig(model="mixture"), nchains=2)
        for ok in ("auto", "1", "0"):
            monkeypatch.setenv(var, ok)
            JaxGibbs(small_ma, GibbsConfig(model="mixture"), nchains=2)
        monkeypatch.delenv(var, raising=False)


def test_fast_beta_requires_half_integer_counts(small_ma, monkeypatch):
    """The chi-square Beta construction is exact only for half-integer
    shapes: a prior whose doubled pseudo-counts are fractional must
    keep random.beta even when the gate is forced on."""
    from gibbs_student_t_tpu.backends import JaxGibbs

    monkeypatch.setenv("GST_FAST_BETA", "1")
    n = small_ma.n
    # uniform prior: a = sz + 1 — always half-integer-exact
    gb = JaxGibbs(small_ma, GibbsConfig(model="mixture",
                                        theta_prior="uniform"), nchains=2)
    assert gb._beta_pool == 2 * (n + 2)
    # beta prior with fractional n * outlier_mean: must fall back
    gb2 = JaxGibbs(small_ma,
                   GibbsConfig(model="mixture", theta_prior="beta",
                               outlier_mean=0.013), nchains=2)
    assert gb2._beta_pool is None


def test_fast_beta_distribution():
    """The disjointly-masked chi-square construction IS Beta(a, b):
    moment pin over many draws against the analytic mean/variance."""
    import jax.numpy as jnp
    from jax import random

    from gibbs_student_t_tpu.ops.linalg import masked_chisq

    a, b = 4.0, 14.0        # half-integer-exact (2a, 2b integers)
    pool = int(2 * (a + b))

    def draw(key):
        xs = random.normal(key, (pool,), dtype=jnp.float32)
        ga = masked_chisq(xs, jnp.float32(2.0 * a))
        gb = masked_chisq(jnp.flip(xs, -1), jnp.float32(2.0 * b))
        return ga / (ga + gb)

    th = np.asarray(jax.jit(jax.vmap(draw))(
        random.split(random.PRNGKey(0), 4000)))
    mean = a / (a + b)
    var = a * b / ((a + b) ** 2 * (a + b + 1.0))
    # 4000 draws: se(mean) ~ sqrt(var/4000) ~ 1.6e-3; pin at ~4 sigma
    assert abs(th.mean() - mean) < 7e-3
    assert abs(th.var() - var) < var * 0.15
    assert ((th > 0) & (th < 1)).all()


# ----------------------------------------------------------------------
# backend arms: one compiled backend per gate arm (vchol pattern)
# ----------------------------------------------------------------------

_ARMS = {
    "nchol_off": {"GST_NCHOL": "0"},
    "nchol_on": {"GST_NCHOL": "1"},
}


@pytest.fixture(scope="module")
def arm_runs(small_ma):
    """{arm: (backend, ChainResult)} — 24 sweeps, 4 chains, seed 5.
    GST_NCHOL=1 forces the kernels past the MIN_BATCH floor so the
    4-chain tier-1 model exercises them in-sweep."""
    from gibbs_student_t_tpu.backends import JaxGibbs

    saved = os.environ.get("GST_NCHOL")
    out = {}
    try:
        for arm, env in _ARMS.items():
            os.environ.pop("GST_NCHOL", None)
            os.environ.update(env)
            gb = JaxGibbs(small_ma,
                          GibbsConfig(model="mixture",
                                      theta_prior="beta"),
                          nchains=4, chunk_size=6)
            out[arm] = (gb, gb.sample(niter=24, seed=5))
    finally:
        if saved is None:
            os.environ.pop("GST_NCHOL", None)
        else:
            os.environ["GST_NCHOL"] = saved
    return out


def test_nchol_backend_chains_track_vchol(arm_runs):
    """GST_NCHOL on vs off: same math with a different reduction order —
    f32 trajectories track tightly over a short window (the same
    tolerance contract as the vchol-vs-expander pin)."""
    if not nffi.ready():
        pytest.skip(f"native FFI kernels unavailable: {nffi.status()}")
    _, r0 = arm_runs["nchol_off"]
    _, r1 = arm_runs["nchol_on"]
    np.testing.assert_allclose(r1.chain[:10], r0.chain[:10],
                               rtol=1e-4, atol=1e-4)
    # bchain rides the compact wire at bf16 (quantum ~0.008 at these
    # magnitudes): one-ulp wire flips from the reassociated solve are
    # expected, so the pin is at the quantization scale
    np.testing.assert_allclose(r1.bchain[:10], r0.bchain[:10],
                               rtol=5e-2, atol=1e-2)
    assert np.isfinite(r1.chain).all() and (r1.alphachain > 0).all()


def test_nchol_backend_deterministic(arm_runs, small_ma):
    """Same seed, same gate -> bit-identical chains (the kernels are
    deterministic single-threaded code; rerunning the compiled sweep
    must reproduce every bit)."""
    if not nffi.ready():
        pytest.skip(f"native FFI kernels unavailable: {nffi.status()}")
    gb, r1 = arm_runs["nchol_on"]
    r2 = gb.sample(niter=24, seed=5)
    np.testing.assert_array_equal(r1.chain, r2.chain)
    np.testing.assert_array_equal(r1.bchain, r2.bchain)
    np.testing.assert_array_equal(r1.alphachain, r2.alphachain)


# ----------------------------------------------------------------------
# GST_HYPER_HOIST arms: bit-identical on/off + per-arm determinism
# ----------------------------------------------------------------------

_HOIST_ARMS = {
    "hoist_off": {"GST_HYPER_HOIST": "0"},
    "hoist_on": {"GST_HYPER_HOIST": "1"},
}


@pytest.fixture(scope="module")
def hoist_arm_runs(small_ma):
    """{arm: (backend, ChainResult)} — 24 sweeps, 4 chains, seed 5,
    everything else at defaults (the arm-shared-backend pattern that
    keeps the marker inside tier-1's budget)."""
    from gibbs_student_t_tpu.backends import JaxGibbs

    saved = os.environ.get("GST_HYPER_HOIST")
    out = {}
    try:
        for arm, env in _HOIST_ARMS.items():
            os.environ.update(env)
            gb = JaxGibbs(small_ma,
                          GibbsConfig(model="mixture",
                                      theta_prior="beta"),
                          nchains=4, chunk_size=6)
            out[arm] = (gb, gb.sample(niter=24, seed=5))
    finally:
        if saved is None:
            os.environ.pop("GST_HYPER_HOIST", None)
        else:
            os.environ["GST_HYPER_HOIST"] = saved
    return out


def test_hyper_hoist_chains_bit_identical(hoist_arm_runs):
    """The hoist is a pure restructuring — same floats, same
    association order — so on/off chains must agree BITWISE, not just
    track: any reassociation sneaking into the hoisted likelihood
    (or its factor dispatch) fails this immediately."""
    _, r0 = hoist_arm_runs["hoist_off"]
    _, r1 = hoist_arm_runs["hoist_on"]
    np.testing.assert_array_equal(r1.chain, r0.chain)
    np.testing.assert_array_equal(r1.bchain, r0.bchain)
    np.testing.assert_array_equal(r1.alphachain, r0.alphachain)
    np.testing.assert_array_equal(r1.thetachain, r0.thetachain)


def test_hyper_hoist_deterministic(hoist_arm_runs):
    """Same seed, same gate -> bit-identical chains on rerun, for each
    arm (the test_nchol_backend_deterministic contract extended to the
    hoist gate)."""
    for arm in _HOIST_ARMS:
        gb, r1 = hoist_arm_runs[arm]
        r2 = gb.sample(niter=24, seed=5)
        np.testing.assert_array_equal(r1.chain, r2.chain)
        np.testing.assert_array_equal(r1.thetachain, r2.thetachain)


def test_robust_draw_and_tnt_degrade_without_library(monkeypatch):
    """Graceful-degradation extended to the round-8 entry points: with
    the library unreachable and GST_NCHOL forced on, the b-draw's
    fused robust path and the TNT Gram dispatch must fall back to the
    portable compositions and reproduce their numbers exactly."""
    from gibbs_student_t_tpu import native as native_mod
    from gibbs_student_t_tpu.native import ffi as nffi_mod
    from gibbs_student_t_tpu.ops.tnt import tnt_products

    rng = np.random.default_rng(3)
    C, m, n = 24, 11, 31
    A = rng.standard_normal((C, m, 6))
    S = jnp.asarray(A @ np.swapaxes(A, -1, -2) + 4.0 * np.eye(m),
                    jnp.float32)
    r = jnp.asarray(rng.standard_normal((C, m)), jnp.float32)
    xi = jnp.asarray(rng.standard_normal((C, m)), jnp.float32)
    T = jnp.asarray(rng.standard_normal((n, m)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((n,)), jnp.float32)
    nvec = jnp.asarray(rng.uniform(0.5, 2.0, (C, n)), jnp.float32)

    monkeypatch.setenv("GST_NCHOL", "0")
    y_off = jax.jit(lambda: linalg.robust_precond_draw(S, r, xi))()[0]
    tnt_off = jax.jit(jax.vmap(lambda nv: tnt_products(T, y, nv)))(nvec)

    monkeypatch.setattr(native_mod, "load", lambda build=False: None)
    nffi_mod._reset_for_tests()
    try:
        monkeypatch.setenv("GST_NCHOL", "1")  # forced AND unavailable
        assert not nffi_mod.ready()
        y_f = jax.jit(lambda: linalg.robust_precond_draw(S, r, xi))()[0]
        tnt_f = jax.jit(jax.vmap(lambda nv: tnt_products(T, y, nv)))(nvec)
        np.testing.assert_array_equal(y_f, y_off)
        for a, b in zip(tnt_f, tnt_off):
            np.testing.assert_array_equal(a, b)
    finally:
        monkeypatch.undo()
        nffi_mod._reset_for_tests()


# ----------------------------------------------------------------------
# committed-.so staleness guard
# ----------------------------------------------------------------------


def _exported_symbols(so_path):
    out = subprocess.run(["nm", "-D", "--defined-only", so_path],
                         capture_output=True, text=True, check=True)
    return {ln.split()[-1] for ln in out.stdout.splitlines()
            if ln.strip() and " T " in f" {ln} "}


def test_committed_so_symbol_set_fresh(tmp_path):
    """Rebuild native/src into a scratch .so and assert the committed
    library exports the same symbol set — a stale committed .so would
    silently drop the FFI kernels (every entry point then degrades to
    vchol: correct but slow) or, worse, ship old kernel semantics."""
    import shutil

    if not (shutil.which("make") and shutil.which("g++")
            and shutil.which("nm")):
        pytest.skip("native toolchain unavailable (no make/g++/nm)")
    committed = os.path.join(
        REPO, "gibbs_student_t_tpu", "native", "libgst_native.so")
    if not os.path.exists(committed):
        pytest.skip("no committed libgst_native.so")
    fresh = str(tmp_path / "fresh.so")
    # -O0 keeps the rebuild fast; the exported symbol set is
    # optimization-independent
    subprocess.run(
        ["make", "-C", os.path.join(REPO, "native"),
         f"OUT={fresh}", f"OBJDIR={tmp_path / 'obj'}",
         "CXXFLAGS=-O0 -std=c++17 -fPIC"],
        check=True, capture_output=True, timeout=300)
    want = _exported_symbols(fresh)
    have = _exported_symbols(committed)
    assert want == have, (
        f"committed .so is stale: missing {sorted(want - have)}, "
        f"extra {sorted(have - want)} — rebuild with make -C native "
        "and commit the result")


# ----------------------------------------------------------------------
# round 9: philox streams, draw kernels, MH blocks, schur, megastage
# ----------------------------------------------------------------------


def test_philox_stream_pinned_against_jnp_twin():
    """The in-kernel counter-based RNG and the jnp twin (ops/rng.py)
    produce BITWISE-equal words and uniforms: same key/counter layout,
    same round schedule, same exact bits->uniform map. This is the pin
    that makes the native and jnp arms of every draw kernel the same
    distribution by construction, not by statistics."""
    import ctypes

    from gibbs_student_t_tpu import native as native_mod
    from gibbs_student_t_tpu.ops import rng as grng

    _require_kernels()
    lib = native_mod.load()
    k0, k1, row, tag = 0xDEADBEEF, 0x12345678, 7, int(grng.TAG_GAMMA)
    count = 37
    out = np.zeros(count, np.uint32)
    lib.gst_philox_fill(
        ctypes.c_uint32(k0), ctypes.c_uint32(k1), ctypes.c_uint32(row),
        ctypes.c_uint32(tag),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        ctypes.c_longlong(count))
    nblk = (count + 3) // 4
    w = grng.philox_4x32(np.uint32(k0), np.uint32(k1),
                         np.full(nblk, row, np.uint32),
                         np.arange(nblk, dtype=np.uint32),
                         np.full(nblk, tag, np.uint32),
                         np.zeros(nblk, np.uint32))
    bits = np.stack([np.asarray(x) for x in w], -1).reshape(-1)[:count]
    np.testing.assert_array_equal(out, bits)
    # the uniform map is exact float arithmetic: bitwise too
    u_j = np.asarray(grng.uniform_of_bits(bits, jnp.float32))
    u_ref = ((bits >> 9).astype(np.float32) * np.float32(2.0 ** -23)
             + np.float32(2.0 ** -24))
    np.testing.assert_array_equal(u_j, u_ref)
    assert (u_ref > 0.0).all() and (u_ref < 1.0).all()


def test_gamma_v2_kernel_matches_jnp_twin():
    """Native gamma-v2 vs the jnp philox twin on identical keys: same
    streams, values agree to the transcendental-ulp level (the kernel
    accumulates the uniform product in a double and pays one log; the
    twin chunks in the working dtype)."""
    from gibbs_student_t_tpu.ops import rng as grng

    _require_kernels()
    rng = np.random.default_rng(0)
    B, n, jmax = 33, 21, 15
    keys = jnp.asarray(rng.integers(0, 2 ** 32, (B, 2), dtype=np.uint32))
    counts = jnp.asarray(rng.integers(1, 32, (B, n)), jnp.float32)
    gk = np.asarray(nffi.gamma_v2(keys, counts, jmax))
    gt = np.asarray(jax.vmap(
        lambda k2, c: grng.gamma_halfint_v2(k2, c, jmax))(keys, counts))
    np.testing.assert_allclose(gk, gt, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("k", [1, 2, 7, 31])
def test_gamma_v2_distribution_pins(k):
    """Moment + KS pins of the v2 construction against the chi-square
    law it must reproduce exactly: Gamma(k/2) = 0.5 * chi^2_k, per
    integer k (even: pure -log prod U; odd: + the Box-Muller plane)."""
    from scipy import stats

    _require_kernels()
    rng = np.random.default_rng(100 + k)
    N = 40000
    keys = jnp.asarray(rng.integers(0, 2 ** 32, (N, 2), dtype=np.uint32))
    counts = jnp.full((N, 1), float(k), jnp.float32)
    g = np.asarray(nffi.gamma_v2(keys, counts, 15))[:, 0]
    mean, var = k / 2.0, k / 2.0
    assert abs(g.mean() - mean) < 5.0 * np.sqrt(var / N) + 0.01
    assert abs(g.var() - var) < 0.08 * var + 0.02
    ks = stats.kstest(2.0 * g, stats.chi2(df=k).cdf)
    assert ks.pvalue > 1e-3, f"KS p={ks.pvalue} for k={k}"


def test_beta_frac_distribution_pin():
    """The native fractional-Beta kernel (Marsaglia-Tsang, exact
    rejection) IS Beta(a, b): KS against the analytic CDF at the
    flagship-like fractional shapes, plus a < 1 boost coverage."""
    from scipy import stats

    _require_kernels()
    rng = np.random.default_rng(7)
    for a, b in ((2.3, 14.7), (0.4, 3.1)):
        N = 20000
        keys = jnp.asarray(
            rng.integers(0, 2 ** 32, (N, 2), dtype=np.uint32))
        av = jnp.full((N,), a, jnp.float32)
        bv = jnp.full((N,), b, jnp.float32)
        th = np.asarray(nffi.beta_frac(keys, av, bv))
        assert (th > 0).all() and (th < 1).all()
        ks = stats.kstest(th, stats.beta(a, b).cdf)
        assert ks.pvalue > 1e-3, f"KS p={ks.pvalue} for ({a},{b})"


def _white_operands(dtype, B=19, S=7, seed=0):
    from gibbs_student_t_tpu.ops.pallas_white import build_white_consts

    psr, _ = make_demo_pulsar(seed=3, n=50, theta=0.1)
    ma = make_demo_pta(psr, components=6).frozen()
    wc = build_white_consts(ma)
    rng = np.random.default_rng(seed)
    p, n = ma.nparam, ma.n
    x = jnp.asarray(np.stack([ma.x_init(rng) for _ in range(B)]), dtype)
    az = jnp.asarray(rng.uniform(0.5, 2.0, (B, n)), dtype)
    y2 = jnp.asarray(rng.uniform(0.0, 3.0, (B, n)), dtype)
    dx = jnp.asarray(rng.normal(0, 0.05, (B, S, p)), dtype)
    logu = jnp.asarray(np.log(rng.uniform(size=(B, S))), dtype)
    return ma, wc, x, az, y2, dx, logu


def test_white_mh_kernel_f64_parity_and_nan():
    """The native white-MH block vs white_mh_loop_xla on identical
    draws at f64: identical accepts, identical x (the accepted
    coordinates are the same dx values). A non-finite chain's variance
    poisons ITS likelihood (reject-all) without touching lane
    neighbours — the branchless contract."""
    from gibbs_student_t_tpu.ops.pallas_white import white_mh_loop_xla

    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        ma, wc, x, az, y2, dx, logu = _white_operands(np.float64)
        rows = jnp.asarray(wc.rows, jnp.float64)
        specs = jnp.asarray(wc.specs, jnp.float64)
        x0, a0 = white_mh_loop_xla(x, az, y2, dx, logu, rows, specs,
                                   wc.var)
        x1, a1 = nffi.white_mh(x, az, y2, dx, logu, rows, specs, wc.var)
        np.testing.assert_allclose(x1, x0, atol=1e-9)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a0))
        assert 0.0 < np.asarray(a1).mean() < 1.0
        # NaN az in chain 0: rejects every step there, neighbours alone
        az_bad = az.at[0, 3].set(jnp.nan)
        xb, ab = nffi.white_mh(x, az_bad, y2, dx, logu, rows, specs,
                               wc.var)
        np.testing.assert_array_equal(np.asarray(xb)[0],
                                      np.asarray(x)[0])
        assert np.asarray(ab)[0] == 0.0
        np.testing.assert_array_equal(np.asarray(xb)[1:],
                                      np.asarray(x1)[1:])
    finally:
        jax.config.update("jax_enable_x64", False)


def test_white_lanes_kernel_f64_parity_uniform_and_straddle():
    """The per-lane-consts white-MH twin (gst_white_lanes, round 11):
    vs the grouped white_mh_loop_xla on identical draws at f64 with
    two tile-aligned groups carrying DIFFERENT constants; a uniform
    pool is bitwise the shared-consts kernel (same tile loop); a gid
    straddling an aligned SIMD tile is rejected with a diagnostic (the
    scheduler contract, not silent corruption)."""
    from gibbs_student_t_tpu.ops.pallas_white import (
        build_white_consts,
        white_mh_loop_xla,
    )

    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        ma, wc, x, az, y2, dx, logu = _white_operands(np.float64, B=48)
        B = 48
        rows = np.repeat(wc.rows[None].astype(np.float64), B, 0)
        specs = np.repeat(wc.specs[None].astype(np.float64), B, 0)
        # group 1 (lanes 24+, W=8-aligned): perturbed baseline variance
        # and a shifted uniform-prior window — really different consts
        rows[24:, 0, :] *= 1.7
        specs[24:, 1, :] -= 0.25
        rows_j = jnp.asarray(rows)
        specs_j = jnp.asarray(specs)
        gid = jnp.asarray(np.repeat([0, 1], 24).astype(np.int32))
        x0, a0 = white_mh_loop_xla(x, az, y2, dx, logu, rows_j,
                                   specs_j, wc.var)
        x1, a1 = nffi.white_mh_lanes(x, az, y2, dx, logu, rows_j,
                                     specs_j, gid, wc.var)
        np.testing.assert_allclose(x1, x0, atol=1e-9)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a0))
        assert 0.0 < np.asarray(a1).mean() < 1.0
        # uniform pool == the shared-consts kernel, bitwise
        ru = jnp.asarray(np.repeat(wc.rows[None].astype(np.float64),
                                   B, 0))
        su = jnp.asarray(np.repeat(wc.specs[None].astype(np.float64),
                                   B, 0))
        xs, as_ = nffi.white_mh(x, az, y2, dx, logu,
                                jnp.asarray(wc.rows, jnp.float64),
                                jnp.asarray(wc.specs, jnp.float64),
                                wc.var)
        xl, al = nffi.white_mh_lanes(x, az, y2, dx, logu, ru, su,
                                     jnp.zeros(B, jnp.int32), wc.var)
        np.testing.assert_array_equal(np.asarray(xs), np.asarray(xl))
        np.testing.assert_array_equal(np.asarray(as_), np.asarray(al))
        # tile-straddling gid: loud rejection
        with pytest.raises(Exception, match="straddles"):
            nffi.white_mh_lanes(
                x, az, y2, dx, logu, rows_j, specs_j,
                jnp.asarray(np.arange(B, dtype=np.int32)), wc.var)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_hyper_mh_kernel_f64_parity_and_nonpd():
    """The native hyper-MH block vs hyper_mh_loop_xla at f64: identical
    accepts/x. A non-PD S0 chain rejects every proposal (NaN factor ->
    -inf likelihood) and leaves its lane neighbours untouched."""
    from gibbs_student_t_tpu.ops.pallas_hyper import (
        build_hyper_consts,
        hyper_mh_loop_xla,
    )

    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        ma, wc, x, az, y2, dx, logu = _white_operands(np.float64)
        hc = build_hyper_consts(ma, np.arange(ma.m))
        B, v = x.shape[0], ma.m
        rng = np.random.default_rng(1)
        A = rng.standard_normal((B, v, 2 * v))
        S0 = jnp.asarray(A @ np.swapaxes(A, -1, -2) + 10 * np.eye(v),
                         jnp.float64)
        dS0 = (jnp.diagonal(S0, axis1=-2, axis2=-1)
               + jnp.asarray(hc.phiinv_static, jnp.float64))
        rt = jnp.asarray(rng.standard_normal((B, v)), jnp.float64)
        base = jnp.asarray(rng.standard_normal(B), jnp.float64)
        K = jnp.asarray(hc.K, jnp.float64)
        sel = jnp.asarray(hc.phi_sel, jnp.float64)
        specs = jnp.asarray(wc.specs, jnp.float64)
        x0, a0 = hyper_mh_loop_xla(x, S0, dS0, rt, base, dx, logu, K,
                                   sel, specs, hc.hyp_idx, 1e-6)
        x1, a1 = nffi.hyper_mh(x, S0, dS0, rt, base, dx, logu, K, sel,
                               specs, hc.hyp_idx, 1e-6)
        np.testing.assert_allclose(x1, x0, atol=1e-9)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a0))
        # non-PD S0 in chain 0: every proposal (and the initial point)
        # evaluates to -inf; -inf - -inf = NaN > logu is False
        S0b = S0.at[0].set(-jnp.eye(v, dtype=jnp.float64))
        dS0b = (jnp.diagonal(S0b, axis1=-2, axis2=-1)
                + jnp.asarray(hc.phiinv_static, jnp.float64))
        xb, ab = nffi.hyper_mh(x, S0b, dS0b, rt, base, dx, logu, K,
                               sel, specs, hc.hyp_idx, 1e-6)
        np.testing.assert_array_equal(np.asarray(xb)[0],
                                      np.asarray(x)[0])
        assert np.asarray(ab)[0] == 0.0
        np.testing.assert_array_equal(np.asarray(xb)[1:],
                                      np.asarray(x1)[1:])
    finally:
        jax.config.update("jax_enable_x64", False)


def test_schur_kernel_f64_parity_and_nan():
    """The fused native schur_eliminate vs the jnp composition at f64
    1e-9 on every output (factor pieces bitwise-critical: the b-draw
    consumes them), and non-PD A poisons only its own chain."""
    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.default_rng(0)
        B, ns, nv = 19, 9, 14
        m = ns + nv
        A_ = rng.standard_normal((B, m, 40))
        Sig = jnp.asarray(A_ @ np.swapaxes(A_, -1, -2) + 10 * np.eye(m),
                          jnp.float64)
        rs = jnp.asarray(rng.standard_normal((B, ns)), jnp.float64)
        rv = jnp.asarray(rng.standard_normal((B, nv)), jnp.float64)
        Ass, Asv = Sig[:, :ns, :ns], Sig[:, :ns, ns:]
        Avv = Sig[:, ns:, ns:]
        ref = jax.vmap(lambda a, b, c, x, y: linalg._schur_jnp(
            a, b, c, x, y, 1e-8))(Ass, Asv, Avv, rs, rv)
        out = nffi.schur(Ass, Asv, Avv, rs, rv, 1e-8)
        for got, want in zip(out, ref):
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(want), atol=1e-9)
        # non-PD A in chain 1: its logdetA/quad/S0 go non-finite,
        # chain 0 and 2 stay bitwise identical
        Abad = Ass.at[1, 0, 0].set(-1.0)
        outb = nffi.schur(Abad, Asv, Avv, rs, rv, 1e-8)
        assert not np.isfinite(np.asarray(outb[3])[1])  # logdetA
        for got, clean in zip(outb, out):
            np.testing.assert_array_equal(np.asarray(got)[0],
                                          np.asarray(clean)[0])
            np.testing.assert_array_equal(np.asarray(got)[2],
                                          np.asarray(clean)[2])
    finally:
        jax.config.update("jax_enable_x64", False)


def test_fused_hyper_kernel_f64_parity():
    """The hyper+draws megastage vs the per-stage jnp composition
    (the _fused_hyper_dispatcher fallback) at f64: x/acc bitwise, draw
    pieces <= 1e-9 — fuse on/off is the same math in the same order."""
    from gibbs_student_t_tpu.ops.pallas_hyper import build_hyper_consts
    from gibbs_student_t_tpu.models.pta import (
        phiinv_logdet,
        static_phi_columns,
    )

    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        ma, wc, x, az, y2, dx, logu = _white_operands(np.float64)
        smask = static_phi_columns(ma)
        s_i, v_i = np.flatnonzero(smask), np.flatnonzero(~smask)
        hc = build_hyper_consts(ma, v_i)
        B, mm = x.shape[0], ma.m
        rng = np.random.default_rng(1)
        T_ = rng.standard_normal((B, mm, 2 * mm))
        TNT = jnp.asarray(T_ @ np.swapaxes(T_, -1, -2) + 10 * np.eye(mm),
                          jnp.float64)
        d = jnp.asarray(rng.standard_normal((B, mm)), jnp.float64)
        xi = jnp.asarray(rng.standard_normal((B, mm)), jnp.float64)
        base0 = jnp.asarray(rng.standard_normal(B), jnp.float64)
        K = jnp.asarray(hc.K, jnp.float64)
        sel = jnp.asarray(hc.phi_sel, jnp.float64)
        phist = jnp.asarray(hc.phiinv_static, jnp.float64)
        specs = jnp.asarray(wc.specs, jnp.float64)
        phiinv_s = jax.vmap(
            lambda q: phiinv_logdet(ma, q, jnp)[0])(x)[:, s_i]
        A = TNT[:, s_i][:, :, s_i] + jax.vmap(jnp.diag)(phiinv_s)
        Bm = TNT[:, s_i][:, :, v_i]
        C = TNT[:, v_i][:, :, v_i]
        args = (A, Bm, C, d[:, s_i], d[:, v_i], x, dx, logu, xi, base0,
                K, sel, phist, specs)
        jitters = (1e-8, 1e-4, 1e-2, 1e-1)
        kern = nffi.fused_hyper(*args[:14], hc.hyp_idx, 1e-8, jitters)
        # the per-stage composition the dispatcher degrades to (built
        # explicitly here rather than poking the dispatcher's privates)
        from gibbs_student_t_tpu.ops.pallas_hyper import (
            _phi_eval_xla,
            hyper_mh_loop_xla,
        )

        (S0r, rtr, qr, ldr, Lar, isdr, UBr, usr) = jax.vmap(
            lambda a, b, c, xx, yy: linalg._schur_jnp(
                a, b, c, xx, yy, 1e-8))(A, Bm, C, d[:, s_i], d[:, v_i])
        dS0 = jnp.diagonal(S0r, axis1=-2, axis2=-1) + phist
        base = base0 + 0.5 * (qr - ldr)
        xh, acch = hyper_mh_loop_xla(x, S0r, dS0, rtr, base, dx, logu,
                                     K, sel, specs, hc.hyp_idx, 1e-8)
        phiv, _ = _phi_eval_xla(xh, K, sel, hc.hyp_idx)
        eye = jnp.eye(S0r.shape[-1], dtype=S0r.dtype)
        Sv = S0r + eye * (phiv + phist)[..., None, :]
        yv, isdv, _ = jax.vmap(
            lambda s, r, z: linalg.robust_precond_draw(
                s, r, z, jitters=jitters))(Sv, rtr, xi[:, len(s_i):])
        hi = jax.lax.Precision.HIGHEST
        wty = jnp.matmul(UBr, (isdv * yv)[..., None],
                         precision=hi)[..., 0]
        ys = jax.vmap(linalg.backward_solve)(
            Lar, usr + xi[:, :len(s_i)] - wty)
        want = (xh, acch, yv, isdv, ys, isdr)
        for got, exp in zip(kern[:2], want[:2]):
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(exp))
        for got, exp in zip(kern[2:], want[2:]):
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(exp), atol=1e-9)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_round9_env_validation(monkeypatch, small_ma):
    """The five new gates follow the strict auto|1|0 loud-typo contract
    — at the env helper and at backend construction."""
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.backends.jax_backend import (
        _fast_gamma_v2_env,
        _fast_theta_env,
    )

    helpers = {
        "GST_NWHITE": linalg.nwhite_env,
        "GST_NHYPER": linalg.nhyper_env,
        "GST_FUSE_STAGES": linalg.fuse_stages_env,
        "GST_FAST_GAMMA_V2": _fast_gamma_v2_env,
        "GST_FAST_THETA": _fast_theta_env,
    }
    for var, fn in helpers.items():
        monkeypatch.delenv(var, raising=False)
        assert fn() == "auto"
        monkeypatch.setenv(var, "yes")
        with pytest.raises(ValueError, match=var):
            fn()
        monkeypatch.delenv(var, raising=False)
    # one construction-time raise per gate keeps this inside budget
    monkeypatch.setenv("GST_FUSE_STAGES", "bogus")
    with pytest.raises(ValueError, match="GST_FUSE_STAGES"):
        JaxGibbs(small_ma, GibbsConfig(model="mixture"), nchains=2)
    monkeypatch.delenv("GST_FUSE_STAGES", raising=False)


def test_custom_call_count_introspection():
    """custom_call_count_of parses the optimized HLO's dispatch count
    (the fusion metric perf_report --check gates) and degrades to None
    on API drift."""
    from gibbs_student_t_tpu.obs.introspect import custom_call_count_of

    class Fake:
        def as_text(self):
            return ("a = f32[2] custom-call(b), custom_call_target=\"x\"\n"
                    "c = f32[2] add(a, a)\n"
                    "d = f32[2] custom-call(c), custom_call_target=\"y\"\n")

    class Broken:
        def as_text(self):
            raise RuntimeError("no text")

    assert custom_call_count_of(Fake()) == 2
    assert custom_call_count_of(Broken()) is None


# ----------------------------------------------------------------------
# round 9: backend arms, degradation, graph pins, ABI + symbol guards
# ----------------------------------------------------------------------

_R9_OFF = {"GST_FAST_GAMMA_V2": "0", "GST_FAST_THETA": "0",
           "GST_NWHITE": "0", "GST_NHYPER": "0", "GST_FUSE_STAGES": "0"}


def _small_backend_run(small_ma, env, monkeypatch, niter=12, seed=5):
    from gibbs_student_t_tpu.backends import JaxGibbs

    for k in _R9_OFF:
        monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    gb = JaxGibbs(small_ma, GibbsConfig(model="mixture",
                                        theta_prior="beta"),
                  nchains=4, chunk_size=6)
    return gb, gb.sample(niter=niter, seed=seed)


def test_fuse_backend_runs_and_deterministic(small_ma, monkeypatch):
    """GST_FUSE_STAGES=1: the megastage sweeps produce finite,
    law-plausible chains and are bit-identical on rerun (the per-arm
    determinism contract). The fuse-off arm with the same per-stage
    native kernels tracks it over a short window."""
    _require_kernels()
    gb_on, r_on = _small_backend_run(small_ma, {"GST_FUSE_STAGES": "1"},
                                     monkeypatch)
    assert gb_on._fuse_stages
    assert np.isfinite(r_on.chain).all()
    assert (r_on.alphachain > 0).all()
    assert (r_on.thetachain > 0).all() and (r_on.thetachain < 1).all()
    r_on2 = gb_on.sample(niter=12, seed=5)
    np.testing.assert_array_equal(r_on.chain, r_on2.chain)
    np.testing.assert_array_equal(r_on.thetachain, r_on2.thetachain)
    gb_off, r_off = _small_backend_run(small_ma,
                                       {"GST_FUSE_STAGES": "0"},
                                       monkeypatch)
    assert not gb_off._fuse_stages
    # same kernels, same order, different only by the b-draw's phi
    # association (K rows vs the model walk): short-window tracking
    np.testing.assert_allclose(r_off.chain[:6], r_on.chain[:6],
                               rtol=1e-3, atol=1e-3)


@pytest.mark.slow  # round-11 re-tier: ~21 s of end-to-end sweeps; the
# tier-1 budget keeps the bitwise parity pins and the cheap
# dispatcher-level degradation checks (test_dispatch_degrades_without
# _library, test_serve white-lanes) — this full-sweep sibling runs in
# tier 2
def test_round9_forced_but_unavailable_degrades(small_ma, monkeypatch):
    """The graph-preserving gates (FUSE_STAGES / NWHITE / NHYPER /
    FAST_THETA) forced on with the library unreachable must reproduce
    the gates-off chains BITWISE — forcing an arm never changes the
    math when the arm cannot exist. GST_FAST_GAMMA_V2 degrades to the
    jnp philox twin instead (same distribution, different stream), so
    it is pinned to run finite, not to match."""
    from gibbs_student_t_tpu import native as native_mod
    from gibbs_student_t_tpu.native import ffi as nffi_mod

    _, r_off = _small_backend_run(small_ma, _R9_OFF, monkeypatch)

    monkeypatch.setattr(native_mod, "load", lambda build=False: None)
    nffi_mod._reset_for_tests()
    try:
        assert not nffi_mod.ready()
        forced = {"GST_FUSE_STAGES": "1", "GST_NWHITE": "1",
                  "GST_NHYPER": "1", "GST_FAST_THETA": "1",
                  "GST_FAST_GAMMA_V2": "0", "GST_NCHOL": "0"}
        gb_f, r_f = _small_backend_run(small_ma, forced, monkeypatch)
        assert not gb_f._fuse_stages and not gb_f._fast_theta
        np.testing.assert_array_equal(r_f.chain, r_off.chain)
        np.testing.assert_array_equal(r_f.thetachain, r_off.thetachain)
        np.testing.assert_array_equal(r_f.alphachain, r_off.alphachain)
        # the v2 gamma arm: jnp twin when forced without the library
        gb_v2, r_v2 = _small_backend_run(
            small_ma, dict(_R9_OFF, GST_FAST_GAMMA_V2="1"), monkeypatch)
        assert gb_v2._fast_gamma_v2
        assert np.isfinite(r_v2.chain).all()
        assert (r_v2.alphachain > 0).all()
    finally:
        monkeypatch.undo()
        nffi_mod._reset_for_tests()
        monkeypatch.delenv("GST_NCHOL", raising=False)


def test_round9_gates_off_graph_contains_no_new_targets(small_ma,
                                                        monkeypatch):
    """Graph-level pin of the gates-off byte-identity contract: with
    every round-9 gate off, the lowered sweep contains NONE of the new
    custom-call targets (the dispatchers cannot have rerouted the
    off-graph); with the gates on, the megastage target is present."""
    import jax

    from gibbs_student_t_tpu.backends import JaxGibbs

    _require_kernels()
    for k, v in _R9_OFF.items():
        monkeypatch.setenv(k, v)
    gb = JaxGibbs(small_ma, GibbsConfig(model="mixture",
                                        theta_prior="beta"),
                  nchains=4, chunk_size=6)
    state = gb.init_state(seed=0)
    from jax import random
    keys = random.split(random.PRNGKey(0), 4)
    txt = jax.jit(gb._make_chunk_fn(), static_argnames=("length",)).lower(
        state, keys, 0, length=6).as_text()
    for target in ("gst_gamma_v2", "gst_beta_frac", "gst_white_mh",
                   "gst_hyper_mh", "gst_schur", "gst_fused_hyper"):
        assert target not in txt, f"{target} leaked into gates-off graph"
    for k in _R9_OFF:
        monkeypatch.delenv(k, raising=False)
    # 16 chains: the dispatchers' shared MIN_BATCH floor — below it the
    # megastage correctly keeps the per-stage graph. outlier_mean
    # fractional so the theta draw takes the native beta arm (a
    # half-integer prior would correctly keep the GST_FAST_BETA pool).
    gb2 = JaxGibbs(small_ma, GibbsConfig(model="mixture",
                                         theta_prior="beta",
                                         outlier_mean=0.013),
                   nchains=16, chunk_size=6)
    assert gb2._fuse_stages and gb2._fast_theta
    state16 = gb2.init_state(seed=0)
    keys16 = random.split(random.PRNGKey(0), 16)
    txt2 = jax.jit(gb2._make_chunk_fn(),
                   static_argnames=("length",)).lower(
        state16, keys16, 0, length=6).as_text()
    assert "gst_fused_hyper" in txt2
    assert "gst_gamma_v2" in txt2
    assert "gst_beta_frac" in txt2
    assert "gst_white_mh" in txt2


def test_abi_version_guard(monkeypatch):
    """A committed .so whose kernel-family ABI does not match this
    module's expectation degrades at probe time with a reason naming
    the versions — never miscalls a moved handler signature."""
    from gibbs_student_t_tpu.native import ffi as nffi_mod

    _require_kernels()
    monkeypatch.setattr(nffi_mod, "ABI_VERSION", 999)
    nffi_mod._reset_for_tests()
    try:
        assert not nffi_mod.ready()
        assert "ABI" in nffi_mod.status()
        assert "999" in nffi_mod.status()
    finally:
        monkeypatch.undo()
        nffi_mod._reset_for_tests()
        assert nffi_mod.ready()


def test_registered_targets_match_exported_symbols():
    """Registration/export drift guard: every handler in
    native/ffi.py TARGETS resolves in the committed .so, and every
    exported FFI handler symbol (Gst*) is registered — in BOTH
    directions, so adding a kernel without registering it (or
    registering one the .so lacks) fails fast instead of silently
    degrading."""
    import ctypes

    from gibbs_student_t_tpu import native as native_mod
    from gibbs_student_t_tpu.native import ffi as nffi_mod

    _require_kernels()
    lib = native_mod.load()
    for target, symbol in nffi_mod.TARGETS.items():
        assert getattr(lib, symbol, None) is not None, (
            f"registered target {target} has no exported symbol "
            f"{symbol} in the committed libgst_native.so — rebuild "
            "with make -C native and commit the result")
    # reverse direction via the dynamic symbol table
    so = os.path.join(REPO, "gibbs_student_t_tpu", "native",
                      "libgst_native.so")
    import shutil

    if shutil.which("nm") is None:
        pytest.skip("nm unavailable for the reverse-direction scan")
    out = subprocess.run(["nm", "-D", "--defined-only", so],
                         capture_output=True, text=True, check=True)
    exported = {ln.split()[-1] for ln in out.stdout.splitlines()
                if ln.strip()}
    handlers = {s for s in exported
                if s.startswith("Gst") and s[3:4].isupper()}
    registered = set(nffi_mod.TARGETS.values())
    assert handlers == registered, (
        f"exported-but-unregistered: {sorted(handlers - registered)}; "
        f"registered-but-unexported: {sorted(registered - handlers)}")
    # the plain-C gst_* surface the probe/benches rely on
    for sym in ("gst_simd_level", "gst_abi_version", "gst_philox_fill",
                "gst_bench_chisq", "gst_bench_transpose_reg",
                "gst_timer_stage_count", "gst_timer_stage_name",
                "gst_timers_enable", "gst_timers_enabled",
                "gst_timers_reset", "gst_timers_snapshot",
                "gst_timer_ns_per_tick"):
        assert sym in exported, f"plain-C entry {sym} missing"


# ----------------------------------------------------------------------
# multi-tenant lanes kernels (serve slot pool, ABI v3)
# ----------------------------------------------------------------------


def test_tnt_lanes_and_resid_kernels():
    """The per-lane-consts twins: a uniform pool is BITWISE the shared
    kernel (same tile functions), heterogeneous tiles match the f64
    einsum oracle, and a group straddling a SIMD tile is rejected by
    the handler (the admission-granularity contract)."""
    _require_kernels()
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.default_rng(0)
        B, n, m = 48, 37, 9
        for dt, W in ((np.float64, 8), (np.float32, 16)):
            T1 = rng.standard_normal((n, m)).astype(dt)
            y1 = rng.standard_normal(n).astype(dt)
            nvec = (0.5 + rng.random((B, n))).astype(dt)
            Tb = np.broadcast_to(T1, (B, n, m)).copy()
            yb = np.broadcast_to(y1, (B, n)).copy()
            gid = np.zeros(B, np.int32)
            a = nffi.tnt(jnp.asarray(T1), jnp.asarray(y1),
                         jnp.asarray(nvec))
            b = nffi.tnt_lanes(jnp.asarray(Tb), jnp.asarray(yb),
                               jnp.asarray(nvec), jnp.asarray(gid))
            for got, exp in zip(b, a):
                np.testing.assert_array_equal(np.asarray(got),
                                              np.asarray(exp))
            # heterogeneous groups at tile boundaries vs the oracle
            T2 = rng.standard_normal((n, m)).astype(dt)
            y2 = rng.standard_normal(n).astype(dt)
            Tb2, yb2, gid2 = Tb.copy(), yb.copy(), gid.copy()
            Tb2[W:2 * W] = T2
            yb2[W:2 * W] = y2
            gid2[W:2 * W] = 1
            out = nffi.tnt_lanes(jnp.asarray(Tb2), jnp.asarray(yb2),
                                 jnp.asarray(nvec), jnp.asarray(gid2))
            w = 1.0 / nvec.astype(np.float64)
            T64 = Tb2.astype(np.float64)
            tol = 1e-9 if dt == np.float64 else 2e-3
            np.testing.assert_allclose(
                np.asarray(out[0]),
                np.einsum("bni,bn,bnj->bij", T64, w, T64),
                rtol=tol, atol=tol)
            np.testing.assert_allclose(
                np.asarray(out[1]),
                np.einsum("bni,bn,bn->bi", T64, w,
                          yb2.astype(np.float64)),
                rtol=tol, atol=tol)
            # resid + its lanes twin: bitwise vs each other, oracle tol
            bvec = rng.standard_normal((B, m)).astype(dt)
            r = nffi.resid(jnp.asarray(T1), jnp.asarray(y1),
                           jnp.asarray(bvec))
            rl = nffi.resid_lanes(jnp.asarray(Tb), jnp.asarray(yb),
                                  jnp.asarray(bvec), jnp.asarray(gid))
            np.testing.assert_array_equal(np.asarray(r),
                                          np.asarray(rl))
            np.testing.assert_allclose(
                np.asarray(r),
                y1[None].astype(np.float64)
                - bvec.astype(np.float64) @ T1.T.astype(np.float64),
                rtol=tol, atol=tol)
        # tile-straddle rejection (f32 tile width 16)
        bad = np.zeros(B, np.int32)
        bad[3] = 1
        with pytest.raises(Exception, match="straddles"):
            jax.block_until_ready(nffi.tnt_lanes(
                jnp.asarray(Tb.astype(np.float32)),
                jnp.asarray(yb.astype(np.float32)),
                jnp.asarray(nvec.astype(np.float32)),
                jnp.asarray(bad)))
    finally:
        jax.config.update("jax_enable_x64", False)


def test_fused_hyper_lanes_uniform_bitwise():
    """fused_hyper_lanes with every lane carrying the same constants is
    BITWISE the single-model megastage (they share the tile functions —
    the serve bit-identity pin rests on this), and a tile whose
    constants differ changes only its own lanes."""
    _require_kernels()
    rng = np.random.default_rng(1)
    B, ns, nv, p, nk, S = 33, 4, 6, 8, 2, 3
    dt = np.float32

    def spd(k):
        M = rng.standard_normal((B, k, k))
        return (np.einsum("bij,bkj->bik", M, M)
                + 5 * np.eye(k)).astype(dt)

    A, C = spd(ns), spd(nv)
    Bm = (0.1 * rng.standard_normal((B, ns, nv))).astype(dt)
    rs = rng.standard_normal((B, ns)).astype(dt)
    rv = rng.standard_normal((B, nv)).astype(dt)
    x = rng.standard_normal((B, p)).astype(dt)
    dx = (0.1 * rng.standard_normal((B, S, p))).astype(dt)
    logu = np.log(rng.random((B, S))).astype(dt)
    xi = rng.standard_normal((B, ns + nv)).astype(dt)
    base0 = rng.standard_normal(B).astype(dt)
    K = (0.3 * rng.standard_normal((1 + nk, nv))).astype(dt)
    sel = (rng.random(nv) > 0.3).astype(dt)
    phist = (rng.random(nv) * (1 - sel)).astype(dt)
    specs = np.zeros((3, p), dt)
    specs[1], specs[2] = -50, 50
    hyp_idx, jitter = (1, 4), 1e-6
    jitters = (1e-6, 1e-4, 1e-2, 1e-1)
    args = [jnp.asarray(a)
            for a in (A, Bm, C, rs, rv, x, dx, logu, xi, base0)]
    shared = nffi.fused_hyper(
        *args, jnp.asarray(K), jnp.asarray(sel), jnp.asarray(phist),
        jnp.asarray(specs), hyp_idx, jitter, jitters)
    Kb = np.broadcast_to(K, (B,) + K.shape).copy()
    selb = np.broadcast_to(sel, (B, nv)).copy()
    phb = np.broadcast_to(phist, (B, nv)).copy()
    spb = np.broadcast_to(specs, (B, 3, p)).copy()
    gid = np.zeros(B, np.int32)
    lanes = nffi.fused_hyper_lanes(
        *args, jnp.asarray(Kb), jnp.asarray(selb), jnp.asarray(phb),
        jnp.asarray(spb), jnp.asarray(gid), hyp_idx, jitter, jitters)
    for got, exp in zip(lanes, shared):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(exp))
    # heterogeneous consts in tile 1 (lanes 16..32): other tiles bitwise
    Kb[16:32] *= 0.5
    gid[16:32] = 1
    het = nffi.fused_hyper_lanes(
        *args, jnp.asarray(Kb), jnp.asarray(selb), jnp.asarray(phb),
        jnp.asarray(spb), jnp.asarray(gid), hyp_idx, jitter, jitters)
    iv_s, iv_h = np.asarray(shared[3]), np.asarray(het[3])
    np.testing.assert_array_equal(iv_h[:16], iv_s[:16])
    np.testing.assert_array_equal(iv_h[32:], iv_s[32:])
    assert not np.array_equal(iv_h[16:32], iv_s[16:32])


# ----------------------------------------------------------------------
# in-kernel stage timers (round 15, the deep profiling plane)
# ----------------------------------------------------------------------


def _fused_timer_operands(B=1024, ns=44, nv=16, p=24, S=10, seed=5):
    """Synthetic fused-megastage operands at flagship-like shapes (the
    test_fused_hyper_lanes construction, bigger), plus a jitted
    single-dispatch callable."""
    rng = np.random.default_rng(seed)
    nk, dt = 2, np.float32

    def spd(k):
        M = rng.standard_normal((B, k, max(k // 2, 4)))
        return (np.einsum("bij,bkj->bik", M, M)
                + 5 * np.eye(k)).astype(dt)

    ops = [jnp.asarray(a) for a in (
        spd(ns), (0.1 * rng.standard_normal((B, ns, nv))).astype(dt),
        spd(nv), rng.standard_normal((B, ns)).astype(dt),
        rng.standard_normal((B, nv)).astype(dt),
        rng.standard_normal((B, p)).astype(dt),
        (0.1 * rng.standard_normal((B, S, p))).astype(dt),
        np.log(rng.random((B, S))).astype(dt),
        rng.standard_normal((B, ns + nv)).astype(dt),
        rng.standard_normal(B).astype(dt))]
    K = (0.3 * rng.standard_normal((1 + nk, nv))).astype(dt)
    sel = (rng.random(nv) > 0.3).astype(dt)
    phist = (rng.random(nv) * (1 - sel)).astype(dt)
    specs = np.zeros((3, p), dt)
    specs[1], specs[2] = -50, 50
    fn = jax.jit(lambda *a: nffi.fused_hyper(
        *a, jnp.asarray(K), jnp.asarray(sel), jnp.asarray(phist),
        jnp.asarray(specs), (1, 4), 1e-6, (1e-6, 1e-4, 1e-2, 1e-1)))
    return fn, ops


def test_kernel_timers_env_and_probe(monkeypatch):
    """GST_KERNEL_TIMERS follows the strict auto|1|0 loud-typo
    contract; the probe cross-checks the Python stage list against the
    C enum; '0' keeps the resolution off even with the surface
    present."""
    monkeypatch.delenv("GST_KERNEL_TIMERS", raising=False)
    assert nffi.kernel_timers_env() == "auto"
    monkeypatch.setenv("GST_KERNEL_TIMERS", "yes")
    with pytest.raises(ValueError, match="GST_KERNEL_TIMERS"):
        nffi.kernel_timers_env()
    monkeypatch.setenv("GST_KERNEL_TIMERS", "0")
    assert nffi.timers_resolved_on() is False
    monkeypatch.delenv("GST_KERNEL_TIMERS", raising=False)
    _require_kernels()
    assert nffi.timers_available()
    assert nffi.timers_resolved_on()
    # calibration is cached and sane: rdtsc ticks are sub-10ns on any
    # host this runs on (the clock_gettime fallback reads exactly 1.0)
    npt = nffi.timers_ns_per_tick()
    assert npt == nffi.timers_ns_per_tick()
    assert 0.01 <= npt <= 10.0


def test_kernel_timers_bitwise_and_lowered_graph():
    """The side-channel contract: timers on/off runs the SAME compiled
    kernel code behind the SAME lowered graph — outputs bitwise equal,
    lowering text identical (no operand, no attribute, nothing for the
    flag to change), and off-mode accumulates nothing."""
    _require_kernels()
    fn, ops = _fused_timer_operands(B=64, ns=8, nv=8, p=10, S=4)
    txt_off = jax.jit(fn).lower(*ops).as_text()
    nffi.timers_enable(False)
    nffi.timers_reset()
    out_off = [np.asarray(a) for a in fn(*ops)]
    assert not nffi.timers_delta_ms({}, nffi.timers_snapshot())
    nffi.timers_enable(True)
    out_on = [np.asarray(a) for a in fn(*ops)]
    d = nffi.timers_delta_ms({}, nffi.timers_snapshot())
    assert set(d) <= {"schur", "hyper_mh", "bdraw_factor", "solves"}
    assert d, "timers on accumulated nothing"
    nffi.timers_enable(False)
    txt_on = jax.jit(fn).lower(*ops).as_text()
    assert txt_on == txt_off
    for a, b in zip(out_on, out_off):
        np.testing.assert_array_equal(a, b)


def test_kernel_timers_reconcile_fused_dispatch_wall():
    """THE reconciliation pin (ISSUE 12 acceptance): the four fused
    stage segments (schur / hyper-MH / b-draw factor / solves, with
    scratch setup folded into the first) sum to within 15% of the
    fused dispatch wall at flagship-like shapes — the timers measure
    the dispatch they claim to decompose."""
    _require_kernels()
    fn, ops = _fused_timer_operands()
    import time

    jax.block_until_ready(fn(*ops))   # compile + warm outside timing
    nffi.timers_enable(True)
    try:
        prev = nffi.timers_snapshot()
        t0 = time.perf_counter()
        for _ in range(6):
            jax.block_until_ready(fn(*ops))
        wall_ms = (time.perf_counter() - t0) * 1e3
        delta = nffi.timers_delta_ms(prev, nffi.timers_snapshot())
    finally:
        nffi.timers_enable(False)
    fused = {k: v["ms"] for k, v in delta.items()
             if k in ("schur", "hyper_mh", "bdraw_factor", "solves")}
    assert set(fused) == {"schur", "hyper_mh", "bdraw_factor",
                          "solves"}
    total = sum(fused.values())
    ratio = total / wall_ms
    assert abs(1.0 - ratio) <= 0.15, (
        f"stage sum {total:.2f}ms vs dispatch wall {wall_ms:.2f}ms "
        f"(ratio {ratio:.3f}) — the timers no longer reconcile")


def test_residual_matvec_dispatch_forced(monkeypatch):
    """The GST_NRESID dispatcher arm: forced native matches the plain
    matmul at f32 tolerance even below the MIN_BATCH floor, and
    GST_NRESID=0 keeps the jnp expression with the family active."""
    _require_kernels()
    rng = np.random.default_rng(2)
    n, m, B = 40, 12, 4
    T = jnp.asarray(rng.standard_normal((n, m)), jnp.float32)
    y = jnp.asarray(rng.standard_normal(n), jnp.float32)
    b = jnp.asarray(rng.standard_normal((B, m)), jnp.float32)
    want = np.asarray(y)[None] - np.asarray(b) @ np.asarray(T).T
    monkeypatch.setenv("GST_NCHOL", "1")
    got = jax.jit(lambda: linalg.residual_matvec(T, y, b))()
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5,
                               atol=2e-5)
    monkeypatch.setenv("GST_NRESID", "0")
    got_off = jax.jit(lambda: linalg.residual_matvec(T, y, b))()
    np.testing.assert_allclose(np.asarray(got_off), want, rtol=2e-5,
                               atol=2e-5)
