"""Analysis module: the notebook's validation surface as library functions."""

import numpy as np
import pytest

from gibbs_student_t_tpu import analysis
from gibbs_student_t_tpu.backends.base import ChainResult


def _fake_result(niter=400, nchains=4, n=20, m=6, p=3, seed=0):
    rng = np.random.default_rng(seed)
    pout = np.zeros((niter, nchains, n))
    pout[..., :3] = 0.97          # three hot TOAs
    pout[..., 3:] = 0.05
    return ChainResult(
        chain=rng.standard_normal((niter, nchains, p)) + [1.0, -2.0, 0.5],
        bchain=rng.standard_normal((niter, nchains, m)),
        zchain=(pout > 0.5).astype(float),
        thetachain=rng.beta(2.0, 18.0, (niter, nchains)),
        alphachain=np.ones((niter, nchains, n)),
        poutchain=pout,
        dfchain=rng.integers(1, 10, (niter, nchains)).astype(float),
        stats={"acc_white": np.full((niter, nchains), 0.3),
               "acc_hyper": np.full((niter, nchains), 0.2)},
    )


def test_summarize_multichain():
    res = _fake_result()
    s = analysis.summarize(res, ["a", "b", "c"])
    np.testing.assert_allclose(s.mean, [1.0, -2.0, 0.5], atol=0.1)
    assert s.rhat is not None and np.all(s.rhat < 1.05)
    assert np.all(s.ess > 100)
    assert "a" in s.table() and "R-hat" in s.table()


def test_summarize_single_chain():
    res = _fake_result(nchains=1)
    squeezed = ChainResult(
        chain=res.chain[:, 0], bchain=res.bchain[:, 0],
        zchain=res.zchain[:, 0], thetachain=res.thetachain[:, 0],
        alphachain=res.alphachain[:, 0], poutchain=res.poutchain[:, 0],
        dfchain=res.dfchain[:, 0], stats={})
    s = analysis.summarize(squeezed, ["a", "b", "c"])
    assert s.rhat is None
    assert np.isfinite(s.mean).all()


def test_outlier_identification_and_confusion():
    res = _fake_result()
    idx = analysis.identify_outliers(res)
    np.testing.assert_array_equal(idx, [0, 1, 2])
    z_true = np.zeros(20)
    z_true[[0, 1, 5]] = 1
    c = analysis.outlier_confusion(res, z_true)
    assert c == {"true_positive": 2, "false_positive": 1,
                 "false_negative": 1, "true_negative": 16}


def test_theta_posterior_check_matches_beta_moments():
    res = _fake_result(niter=2000)
    centers, hist, prior = analysis.theta_posterior_check(res, n=20,
                                                          outlier_mean=0.1)
    # the analytic density must normalize to ~1 over the histogram support
    width = centers[1] - centers[0]
    assert 0.5 < hist.sum() * width <= 1.01
    assert np.all(np.isfinite(prior))


def test_df_posterior_pmf():
    res = _fake_result()
    pmf = analysis.df_posterior(res, df_max=30)
    assert pmf.shape == (30,)
    np.testing.assert_allclose(pmf.sum(), 1.0)
    assert pmf[10:].sum() == 0.0  # draws were 1..9


def test_acceptance_report():
    res = _fake_result()
    rep = analysis.acceptance_report(res)
    assert rep == {"acc_white": pytest.approx(0.3),
                   "acc_hyper": pytest.approx(0.2)}


def test_waveform_reconstruction_shapes(demo_ma):
    niter, nchains = 50, 2
    rng = np.random.default_rng(1)
    res = _fake_result(niter=niter, nchains=nchains, n=demo_ma.n,
                       m=demo_ma.m)
    res.bchain = rng.standard_normal((niter, nchains, demo_ma.m))
    draws, med, lo, hi = analysis.reconstruct_waveform(res, demo_ma,
                                                       ndraws=30)
    assert draws.shape == (30, demo_ma.n)
    assert med.shape == (demo_ma.n,)
    assert np.all(lo <= hi)


def test_plots_write_files(tmp_path, demo_ma):
    pytest.importorskip("matplotlib")
    res = _fake_result(niter=60, nchains=2, n=demo_ma.n, m=demo_ma.m,
                       p=len(demo_ma.param_names))
    mjds = np.linspace(53000, 54800, demo_ma.n)
    analysis.plot_posteriors(res, demo_ma.param_names,
                             str(tmp_path / "p.png"))
    analysis.plot_outlier_map(res, mjds, str(tmp_path / "o.png"),
                              z_true=np.zeros(demo_ma.n))
    analysis.plot_waveform(res, demo_ma, mjds, str(tmp_path / "w.png"))
    analysis.plot_df_posterior(res, str(tmp_path / "d.png"))
    analysis.plot_corner(res, demo_ma.param_names[:3],
                         str(tmp_path / "c.png"),
                         truths={demo_ma.param_names[0]: 0.0})
    for f in ("p.png", "o.png", "w.png", "d.png", "c.png"):
        assert (tmp_path / f).stat().st_size > 0
