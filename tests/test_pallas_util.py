"""Shared Pallas kernel plumbing (ops/pallas_util.py)."""

import numpy as np

import jax.numpy as jnp

from gibbs_student_t_tpu.ops import pallas_util as pu


def test_mode_from_env_semantics(monkeypatch):
    for off in ("0", "false", ""):
        monkeypatch.setenv("GST_TEST_FLAG", off)
        assert pu.mode_from_env("GST_TEST_FLAG") == (False, False, False)
    monkeypatch.setenv("GST_TEST_FLAG", "interpret")
    assert pu.mode_from_env("GST_TEST_FLAG") == (True, True, True)
    monkeypatch.setenv("GST_TEST_FLAG", "1")
    assert pu.mode_from_env("GST_TEST_FLAG") == (True, False, True)
    # auto resolves by backend: off on the CPU test platform
    monkeypatch.delenv("GST_TEST_FLAG", raising=False)
    assert pu.mode_from_env("GST_TEST_FLAG")[0] is False


def test_int_from_env_forgiving(monkeypatch):
    monkeypatch.delenv("GST_TEST_TILE", raising=False)
    assert pu.int_from_env("GST_TEST_TILE", 256) == 256
    # set-but-empty and garbage fall back to the default, like the
    # mode flags' forgiving contract — not a trace-time crash
    monkeypatch.setenv("GST_TEST_TILE", "")
    assert pu.int_from_env("GST_TEST_TILE", 256) == 256
    monkeypatch.setenv("GST_TEST_TILE", "banana")
    assert pu.int_from_env("GST_TEST_TILE", 256) == 256
    # values round up to a legal multiple and never go below it
    monkeypatch.setenv("GST_TEST_TILE", "100")
    assert pu.int_from_env("GST_TEST_TILE", 256) == 104
    monkeypatch.setenv("GST_TEST_TILE", "3")
    assert pu.int_from_env("GST_TEST_TILE", 256) == 8
    monkeypatch.setenv("GST_TEST_TILE", "64")
    assert pu.int_from_env("GST_TEST_TILE", 128, mult=128) == 128


def test_pad_chains_edge_replicates():
    a = jnp.asarray(np.arange(12.0).reshape(3, 4))
    out = pu.pad_chains_edge(a, 5)
    assert out.shape == (5, 4)
    np.testing.assert_array_equal(np.asarray(out[:3]), np.asarray(a))
    np.testing.assert_array_equal(np.asarray(out[3]), np.asarray(a[0]))
    np.testing.assert_array_equal(np.asarray(out[4]), np.asarray(a[0]))
    assert pu.pad_chains_edge(a, 3) is a


def test_round_up():
    assert pu.round_up(1, 8) == 8
    assert pu.round_up(8, 8) == 8
    assert pu.round_up(129, 128) == 256
