"""Multi-process distributed runtime test (VERDICT r1 weak #7).

Round 1 exercised only the single-process degenerate paths of
``parallel/multihost.py``. Here two real OS processes bring up
``jax.distributed`` over a localhost coordinator (the DCN-tier analog on
CPU devices — the same initialization/mesh code paths a TPU pod slice
uses), build the hybrid host x chip mesh, and run a cross-process ``psum``
so the collective actually crosses a process boundary.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_CHILD = r"""
import os, sys
pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
from gibbs_student_t_tpu.parallel.multihost import (
    initialize_distributed, local_shard, make_hybrid_mesh)

ok = initialize_distributed(coordinator_address=f"127.0.0.1:{port}",
                            num_processes=nproc, process_id=pid)
assert ok, "expected a multi-process runtime"
assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == 2 * nproc          # global view
assert len(jax.local_devices()) == 2

# hybrid mesh: the DCN axis (pulsar) spans processes, ICI axis (chain)
# stays process-local
mesh = make_hybrid_mesh({"chain": 2}, {"pulsar": nproc})
assert mesh.axis_names == ("pulsar", "chain")
assert mesh.devices.shape == (nproc, 2)
own = [d.process_index for d in mesh.devices[pid]]
assert own == [pid, pid], "DCN axis must align with process boundaries"

# collective across the process boundary: psum over every device
import jax.numpy as jnp
out = jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
    jnp.ones(len(jax.local_devices())))
assert float(out[0]) == 2.0 * nproc, out

# per-process data sharding covers [0, n) exactly once across processes
sl = local_shard(10, nproc, pid)
print("MULTIHOST_OK", pid, sl.indices(10)[0], sl.indices(10)[1], flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_psum():
    nproc = 2
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CHILD, str(pid), str(nproc), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    # both processes reached the end, and their shards tile [0, 10)
    spans = []
    for out in outs:
        line = [ln for ln in out.splitlines()
                if ln.startswith("MULTIHOST_OK")][0]
        _, pid, a, b = line.split()
        spans.extend(range(int(a), int(b)))
    assert sorted(spans) == list(range(10))
