"""Numerics tests: preconditioned Cholesky against reference LAPACK."""

import numpy as np
import scipy.linalg as sl

import jax
import jax.numpy as jnp

from gibbs_student_t_tpu.ops.linalg import (
    gaussian_draw,
    precond_cholesky,
    precond_solve_quad,
)


def _spd(m, diag_spread, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, m))
    S = A @ A.T + m * np.eye(m)
    d = 10.0 ** rng.uniform(0, diag_spread, m)
    return S * np.sqrt(d[:, None] * d[None, :])


def test_precond_cholesky_logdet_and_solve():
    # 12 decades of diagonal spread — the Sigma regime of small-amplitude
    # red noise (SURVEY.md §7 float64 hard part)
    S = _spd(40, 12)
    rhs = np.random.default_rng(1).standard_normal(40)

    L, isd, logdet = precond_cholesky(jnp.asarray(S))
    sol, quad = precond_solve_quad(L, isd, jnp.asarray(rhs))

    sign, logdet_ref = np.linalg.slogdet(S)
    sol_ref = sl.solve(S, rhs)
    np.testing.assert_allclose(float(logdet), logdet_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(sol), sol_ref, rtol=1e-3)
    np.testing.assert_allclose(float(quad), rhs @ sol_ref, rtol=1e-4)


def test_non_pd_yields_nan_not_crash():
    """Branchless failure path: non-PD input -> NaN (feeds -inf / MH reject),
    replacing the reference's try/except (reference gibbs.py:320-324)."""
    S = np.eye(4)
    S[0, 1] = S[1, 0] = 2.0  # indefinite
    L, isd, logdet = precond_cholesky(jnp.asarray(S))
    assert not bool(jnp.isfinite(L).all())


def test_gaussian_draw_moments():
    S = _spd(6, 3, seed=2)
    L, isd, _ = precond_cholesky(jnp.asarray(S))
    mean = jnp.zeros(6)
    xi = jax.random.normal(jax.random.PRNGKey(0), (20000, 6))
    draws = jax.vmap(lambda e: gaussian_draw(L, isd, mean, e))(xi)
    cov = np.cov(np.asarray(draws).T)
    np.testing.assert_allclose(cov, np.linalg.inv(S), atol=5e-2 * np.abs(
        np.linalg.inv(S)).max())


# --- statically-unrolled Cholesky (ops/unrolled_chol.py) ----------------

from gibbs_student_t_tpu.ops.linalg import (  # noqa: E402
    precond_quad_logdet,
    robust_precond_cholesky,
)
from gibbs_student_t_tpu.ops.unrolled_chol import chol_forward  # noqa: E402


def test_unrolled_chol_matches_lapack():
    S = _spd(37, 0, seed=3)  # odd size, unit-ish diagonal
    rhs = np.random.default_rng(4).standard_normal(37)
    L, logdet, u = chol_forward(jnp.asarray(S), jnp.asarray(rhs))
    L_ref = np.linalg.cholesky(S)
    np.testing.assert_allclose(np.asarray(L), L_ref, rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(float(logdet), np.linalg.slogdet(S)[1],
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(u),
                               sl.solve_triangular(L_ref, rhs, lower=True),
                               rtol=2e-4, atol=1e-5)


def test_unrolled_chol_batched_and_vmapped():
    Ss = np.stack([_spd(12, 2, seed=s) for s in range(5)])
    Ls, logdets, _ = chol_forward(jnp.asarray(Ss))
    Lv, logdetv, _ = jax.vmap(lambda s: chol_forward(s))(jnp.asarray(Ss))
    for k in range(5):
        np.testing.assert_allclose(np.asarray(Ls[k]),
                                   np.linalg.cholesky(Ss[k]), rtol=2e-4,
                                   atol=1e-6)
    np.testing.assert_allclose(np.asarray(Lv), np.asarray(Ls), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(logdetv), np.asarray(logdets),
                               rtol=1e-6)


def test_unrolled_chol_nan_on_non_pd():
    S = np.eye(4)
    S[0, 1] = S[1, 0] = 2.0
    L, logdet, _ = chol_forward(jnp.asarray(S))
    assert not bool(jnp.isfinite(logdet))


def test_precond_quad_logdet_fused():
    S = _spd(40, 12)
    rhs = np.random.default_rng(1).standard_normal(40)
    quad, logdet = precond_quad_logdet(jnp.asarray(S), jnp.asarray(rhs))
    sol_ref = sl.solve(S, rhs)
    np.testing.assert_allclose(float(quad), rhs @ sol_ref, rtol=1e-4)
    np.testing.assert_allclose(float(logdet), np.linalg.slogdet(S)[1],
                               rtol=1e-5)


def test_robust_cholesky_fused_rhs_matches_plain():
    S = _spd(20, 6, seed=5)
    rhs = np.random.default_rng(6).standard_normal(20)
    L, isd, logdet, u = robust_precond_cholesky(jnp.asarray(S), rhs=jnp.asarray(rhs))
    L2, isd2, logdet2 = robust_precond_cholesky(jnp.asarray(S))
    np.testing.assert_allclose(np.asarray(L), np.asarray(L2), rtol=1e-6)
    np.testing.assert_allclose(float(logdet), float(logdet2), rtol=1e-6)
    # u = L^-1 (isd * rhs); full solve through both triangles == Sigma^-1 rhs.
    # jitter j on the equilibrated unit diagonal maps back to Sigma + j*diag(Sigma)
    from jax.scipy.linalg import solve_triangular
    v = solve_triangular(L, u, lower=True, trans="T") * isd
    np.testing.assert_allclose(
        np.asarray(v),
        sl.solve(S + 1e-6 * np.diag(np.diag(S)), rhs), rtol=2e-4)


def test_robust_cholesky_escalates_to_finite():
    """A singular matrix must still yield a finite factorization at some
    jitter level (the b-draw cannot reject; reference gibbs.py:168-178)."""
    v = np.ones(8)
    S = np.outer(v, v) + 1e-9 * np.eye(8)  # numerically rank-one
    L, isd, logdet = robust_precond_cholesky(
        jnp.asarray(S, jnp.float32), jitters=(1e-6, 1e-4, 1e-2, 1e-1))
    assert bool(jnp.isfinite(L).all())
    assert bool(jnp.isfinite(logdet))


def test_unrolled_gate_env_override(monkeypatch):
    """GST_UNROLLED_CHOL forces the unrolled path on/off regardless of
    platform, and both paths agree."""
    from gibbs_student_t_tpu.ops import linalg
    S = jnp.asarray(_spd(20, 6, seed=7))
    rhs = jnp.asarray(np.random.default_rng(8).standard_normal(20))
    monkeypatch.setenv("GST_UNROLLED_CHOL", "1")
    assert linalg._unrolled_wanted(20)
    q1, l1 = linalg.precond_quad_logdet(S, rhs)
    monkeypatch.setenv("GST_UNROLLED_CHOL", "0")
    assert not linalg._unrolled_wanted(20)
    q0, l0 = linalg.precond_quad_logdet(S, rhs)
    np.testing.assert_allclose(float(q1), float(q0), rtol=1e-4)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-5)


def test_unrolled_tri_solve_T_matches_scipy():
    from gibbs_student_t_tpu.ops.unrolled_chol import tri_solve_T
    S = _spd(37, 0, seed=9)
    L = np.linalg.cholesky(S)
    rhs = np.random.default_rng(10).standard_normal(37)
    x = tri_solve_T(jnp.asarray(L), jnp.asarray(rhs))
    x_ref = sl.solve_triangular(L.T, rhs, lower=False)
    np.testing.assert_allclose(np.asarray(x), x_ref, rtol=2e-4, atol=1e-6)
    # batched + vmapped agree
    Ls = jnp.asarray(np.stack([L, L * 1.5]))
    rs = jnp.asarray(np.stack([rhs, rhs * 2.0]))
    xb = tri_solve_T(Ls, rs)
    xv = jax.vmap(tri_solve_T)(Ls, rs)
    np.testing.assert_allclose(np.asarray(xb), np.asarray(xv), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(xb[0]), x_ref, rtol=2e-4,
                               atol=1e-6)
