"""Numerics tests: preconditioned Cholesky against reference LAPACK."""

import numpy as np
import scipy.linalg as sl

import jax
import jax.numpy as jnp

from gibbs_student_t_tpu.ops.linalg import (
    gaussian_draw,
    precond_cholesky,
    precond_solve_quad,
)


def _spd(m, diag_spread, seed=0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((m, m))
    S = A @ A.T + m * np.eye(m)
    d = 10.0 ** rng.uniform(0, diag_spread, m)
    return S * np.sqrt(d[:, None] * d[None, :])


def test_precond_cholesky_logdet_and_solve():
    # 12 decades of diagonal spread — the Sigma regime of small-amplitude
    # red noise (SURVEY.md §7 float64 hard part)
    S = _spd(40, 12)
    rhs = np.random.default_rng(1).standard_normal(40)

    L, isd, logdet = precond_cholesky(jnp.asarray(S))
    sol, quad = precond_solve_quad(L, isd, jnp.asarray(rhs))

    sign, logdet_ref = np.linalg.slogdet(S)
    sol_ref = sl.solve(S, rhs)
    np.testing.assert_allclose(float(logdet), logdet_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(sol), sol_ref, rtol=1e-3)
    np.testing.assert_allclose(float(quad), rhs @ sol_ref, rtol=1e-4)


def test_non_pd_yields_nan_not_crash():
    """Branchless failure path: non-PD input -> NaN (feeds -inf / MH reject),
    replacing the reference's try/except (reference gibbs.py:320-324)."""
    S = np.eye(4)
    S[0, 1] = S[1, 0] = 2.0  # indefinite
    L, isd, logdet = precond_cholesky(jnp.asarray(S))
    assert not bool(jnp.isfinite(L).all())


def test_gaussian_draw_moments():
    S = _spd(6, 3, seed=2)
    L, isd, _ = precond_cholesky(jnp.asarray(S))
    mean = jnp.zeros(6)
    xi = jax.random.normal(jax.random.PRNGKey(0), (20000, 6))
    draws = jax.vmap(lambda e: gaussian_draw(L, isd, mean, e))(xi)
    cov = np.cov(np.asarray(draws).T)
    np.testing.assert_allclose(cov, np.linalg.inv(S), atol=5e-2 * np.abs(
        np.linalg.inv(S)).max())
