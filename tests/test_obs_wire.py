"""Observability-wire unit tests (round 14): the HTTP endpoint server
as a standalone unit, Prometheus exposition conformance against
hostile label values, fleet aggregation over canned pools, the
serve_top golden snapshot (file mode and ``--url`` against a stub
endpoint), and the ``GST_*`` env-gate doc-drift guard.

Everything here is jax-light — no pool compiles, no ChainServer; the
live-server integration rides the shared plane run in
tests/test_serve_obs.py (the ONE-compile budget rule).
"""

import io
import json
import os
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

from gibbs_student_t_tpu.obs import MetricsRegistry
from gibbs_student_t_tpu.obs import schema as obs_schema
from gibbs_student_t_tpu.obs.aggregate import fleet_status, read_status
from gibbs_student_t_tpu.obs.export import prometheus_text
from gibbs_student_t_tpu.obs.http import ObsHttpServer

pytestmark = pytest.mark.obswire

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture(scope="module")
def schemas():
    return obs_schema.load_schemas()


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ----------------------------------------------------------------------
# ObsHttpServer as a unit (no ChainServer behind it)
# ----------------------------------------------------------------------


def test_http_server_routes_and_failure_modes():
    """Routing, ephemeral-port bind, 503 healthz, 404 for missing
    callbacks/unknown routes, 500 + warn-once for a raising callback,
    idempotent close."""
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise RuntimeError("injected handler failure")

    srv = ObsHttpServer(
        port=0,
        status_fn=lambda: {"schema": 1, "fake": True},
        healthz_fn=lambda: {"ok": False, "reason": "draining"},
        postmortem_fn=lambda: {"schema": 1, "reason": "stub"},
        trace_fn=boom)
    try:
        assert srv.port > 0
        code, body = _get(srv.url + "/")
        assert code == 200 and "/healthz" in body
        assert "/postmortem" in body
        code, body = _get(srv.url + "/postmortem")
        assert code == 200 and json.loads(body)["reason"] == "stub"
        code, body = _get(srv.url + "/status")
        assert code == 200 and json.loads(body)["fake"] is True
        code, _ = _get(srv.url + "/healthz")
        assert code == 503            # ok: False -> not ready
        code, _ = _get(srv.url + "/metrics")
        assert code == 404            # no metrics_fn mounted
        code, _ = _get(srv.url + "/tenants/0/progress")
        assert code == 404            # no progress_fn mounted
        code, _ = _get(srv.url + "/bogus/route")
        assert code == 404
        # a raising callback: 500 body, one warning, server survives
        with pytest.warns(RuntimeWarning, match="endpoint"):
            code, body = _get(srv.url + "/trace")
        assert code == 500 and "injected" in body
        code, body = _get(srv.url + "/trace")   # warned once only
        assert code == 500 and calls["n"] == 2
        code, _ = _get(srv.url + "/status")     # still serving
        assert code == 200
    finally:
        srv.close()
    srv.close()   # idempotent
    with pytest.raises((urllib.error.URLError, OSError)):
        urllib.request.urlopen(srv.url + "/status", timeout=1.0)


# ----------------------------------------------------------------------
# Prometheus exposition conformance
# ----------------------------------------------------------------------


def test_prometheus_exposition_conformance():
    """The round-14 conformance satellite: hostile label values are
    escaped per the exposition format, HELP/TYPE appear exactly once
    per family before its samples, hostile metric names sanitize, and
    histogram buckets are cumulative-monotone with a +Inf terminal."""
    reg = MetricsRegistry()
    reg.counter("serve_admissions").inc(3)
    reg.gauge('weird name {"x"}').set(1.5)
    h = reg.histogram("lat_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 5.0, 50.0, 500.0):
        h.observe(v)
    hostile = {"pool": 'a\\b"c\nd', "bad label!": "v"}
    text = prometheus_text(reg.snapshot(), labels=hostile)
    lines = text.splitlines()
    # label escaping: backslash, quote, newline — one physical line
    row = next(ln for ln in lines
               if ln.startswith("gst_serve_admissions{"))
    assert 'pool="a\\\\b\\"c\\nd"' in row
    assert 'bad_label_="v"' in row
    assert row.split()[-1] == "3.0" or "3.0" in row
    # hostile metric name sanitized into the family name
    assert any(ln.startswith("# TYPE gst_weird_name___x__ gauge")
               for ln in lines)
    # HELP + TYPE exactly once per family, HELP before samples
    for family, kind in (("gst_serve_admissions", "counter"),
                         ("gst_lat_ms", "histogram")):
        assert text.count(f"# TYPE {family} {kind}") == 1
        helps = [i for i, ln in enumerate(lines)
                 if ln.startswith(f"# HELP {family} ")]
        assert len(helps) == 1
        first_sample = min(i for i, ln in enumerate(lines)
                           if ln.startswith(family)
                           and not ln.startswith("#"))
        assert helps[0] < first_sample
    # histogram: cumulative monotone buckets, +Inf terminal == count
    bucket_re = re.compile(r'gst_lat_ms_bucket\{.*le="([^"]+)".*\} '
                           r"(\d+)")
    rows = [bucket_re.match(ln) for ln in lines
            if ln.startswith("gst_lat_ms_bucket")]
    assert all(rows)
    counts = [int(m.group(2)) for m in rows]
    assert counts == sorted(counts)
    assert rows[-1].group(1) == "+Inf"
    assert counts[-1] == 5
    count_row = next(ln for ln in lines
                     if ln.startswith("gst_lat_ms_count"))
    assert count_row.split()[-1] == "5"


# ----------------------------------------------------------------------
# fleet aggregation over canned pools (no server)
# ----------------------------------------------------------------------


def _canned_status(nlanes=32, busy=16, admission=(10.0, 30.0),
                   pool_failures=0):
    return {
        "schema": 1, "t": 1.0, "uptime_s": 5.0, "quanta": 10,
        "nlanes": nlanes, "group": 16, "quantum": 5,
        "busy_lanes": busy, "free_groups": 1,
        "occupancy_now": busy / nlanes, "occupancy": 0.8,
        "queue_depth": 1, "staged": 0, "pipeline": True,
        "supervise": True,
        "backend": {"platform": "cpu",
                    "native": "libgst_native.so not built",
                    "scatter": True},
        "faults": {"tenant_failures": 0, "quarantined_lanes": 0,
                   "reinits": 0, "worker_restarts": 0,
                   "pool_failures": pool_failures},
        "slo": {"admission_ms": None, "first_result_ms": None,
                "converged_ms": None, "n_converged": 1},
        "slo_raw": {"admission_ms": list(admission),
                    "first_result_ms": [], "converged_ms": []},
        "tenants": [],
    }


def test_fleet_status_merges_raw_series_and_flags_sick_pools(
        tmp_path, schemas):
    """Percentiles merge from the CONCATENATED raw series (not from
    per-pool percentiles), totals sum, a pool with pool_failures is
    reachable-but-sick, and a missing file is unreachable-not-fatal."""
    a = tmp_path / "a.json"
    b = tmp_path / "b"
    os.makedirs(b)
    a.write_text(json.dumps(_canned_status(admission=(10.0, 30.0))))
    (b / "status.json").write_text(json.dumps(_canned_status(
        nlanes=64, busy=48, admission=(50.0, 70.0),
        pool_failures=1)))
    snap = fleet_status([str(a), str(b), str(tmp_path / "gone.json")],
                        timeout=0.2)
    obs_schema.assert_valid(snap, schemas["fleet_status"],
                            "fleet snapshot", defs=schemas)
    assert snap["n_pools"] == 3 and snap["n_reachable"] == 2
    assert snap["totals"]["nlanes"] == 96
    assert snap["totals"]["busy_lanes"] == 64
    assert snap["totals"]["occupancy_now"] == pytest.approx(64 / 96)
    # merged over [10, 30, 50, 70] — NOT the mean of per-pool p50s
    merged = snap["slo"]["admission_ms"]
    ref = np.asarray([10.0, 30.0, 50.0, 70.0])
    assert merged["p50"] == pytest.approx(np.percentile(ref, 50))
    assert merged["p99"] == pytest.approx(np.percentile(ref, 99))
    assert snap["slo"]["n_converged"] == 2
    by_src = {p["source"]: p for p in snap["pools"]}
    # the execution-backend probe flows onto the pool row (round 21)
    assert by_src[str(a)]["platform"] == "cpu"
    assert by_src[str(a)]["native"] == "libgst_native.so not built"
    assert by_src[str(a)]["scatter"] is True
    assert by_src[str(a)]["healthy"] is True
    assert by_src[str(b)]["healthy"] is False   # pool_failures > 0
    assert by_src[str(tmp_path / "gone.json")]["reachable"] is False
    # read_status raises on the bad source; fleet_status degraded it
    with pytest.raises(Exception):
        read_status(str(tmp_path / "gone.json"))


# ----------------------------------------------------------------------
# serve_top golden snapshot: file mode and --url against a stub
# ----------------------------------------------------------------------


CANNED_TOP = {
    "schema": 1, "t": 1700000000.0, "uptime_s": 12.5, "quanta": 40,
    "nlanes": 64, "group": 16, "quantum": 5, "busy_lanes": 48,
    "free_groups": 1, "occupancy_now": 0.75, "occupancy": 0.8123,
    "queue_depth": 2, "staged": 1, "pipeline": True, "supervise": True,
    "backend": {"platform": "cpu", "native": "registered (avx512f)",
                "scatter": True},
    "faults": {"tenant_failures": 1, "quarantined_lanes": 0,
               "reinits": 0, "worker_restarts": 0, "pool_failures": 0},
    "watchdog": {"enabled": True, "policy": "dump", "state": "ok",
                 "trip": None,
                 "heartbeat_age_s": {"dispatch": 0.1, "drain": 0.2},
                 "deadline_s": 1.0, "quanta_seen": 40},
    "stages": {"hyper_mh": {"device_ms": 300.0, "ms_per_quantum": 7.5,
                            "share_of_dispatch": 0.31},
               "tnt": {"device_ms": 120.0, "ms_per_quantum": 3.0,
                       "share_of_dispatch": 0.12}},
    "sched": {"policy": "priority", "age_boost_s": 30.0,
              "preemptions": 1, "sheds": 2, "sheds_by_tier": {"2": 2},
              "queue_tiers": {"0": 1, "2": 1}, "queue_max": 4,
              "queue_depth_peak": 3},
    "slo": {"admission_ms": {"p50": 10.0, "p90": 20.0, "p99": 30.0,
                             "max": 31.5, "mean": 12.0},
            "first_result_ms": None, "converged_ms": None,
            "n_converged": 0,
            "tiers": {"0": {"admission_ms": {"p50": 5.0, "p90": 8.0,
                                             "p99": 9.0, "max": 9.5,
                                             "mean": 6.0}}}},
    "slo_raw": {"admission_ms": [10.0, 20.0], "first_result_ms": [],
                "converged_ms": []},
    "tenants": [
        {"tenant_id": 0, "name": "t0", "status": "running",
         "nchains": 16, "sweeps_done": 100, "niter": 200, "rows": 100,
         "ess_min": 12.34, "rhat_max": 1.01, "ess_per_s": 5.6,
         "converged_at": None, "quarantined": 0, "reinits": 0,
         "priority": 0, "deadline_sweep": 180, "slack_sweeps": 30.0,
         "cost": {"device_ms": 1234.5, "lane_quanta": 320,
                  "ess_per_core_s": 10.0}},
        {"tenant_id": 1, "name": "t1", "status": "running",
         "nchains": 32, "sweeps_done": 50, "niter": 150,
         "cost": {"device_ms": 2469.0, "lane_quanta": 640,
                  "ess_per_core_s": None}},
    ],
}

GOLDEN_TOP = (
    "serve_top  quanta=40 uptime=12s lanes=48/64 (75% now, 81.2% run)"
    " queue=2 staged=1 pipeline=on\n"
    "backend: cpu native[registered (avx512f)] admission=scatter\n"
    "faults: tenant_failures=1\n"
    "watchdog: ok [policy dump] beats dispatch=0.1s drain=0.2s\n"
    "stages: hyper_mh 7.5ms/q(31%) tnt 3.0ms/q(12%)\n"
    "sched: priority queue_tiers[t0=1 t2=1] peak=3/4 preempt=1 "
    "sheds=2\n"
    "slo admission_ms     p50=    10.0 p90=    20.0 p99=    30.0 "
    "max=    31.5\n"
    "slo tier 0 admission p50=     5.0 p90=     8.0 p99=     9.0\n"
    "  ID       NAME   STATUS PRI   SLACK CHAINS      SWEEPS   ROWS"
    "      ESS    RHAT    ESS/s  CONV@   Q\n"
    "   0         t0  running   0      30     16     100/200    100"
    "     12.3   1.010      5.6      -   0\n"
    "   1         t1  running   -       -     32      50/150      -"
    "        -       -        -      -   -\n"
)


def _serve_top():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "serve_top", os.path.join(REPO, "tools", "serve_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_serve_top_golden_file_and_url_modes(tmp_path):
    """Golden snapshot of the one-shot render: file mode from a canned
    status.json, and the new --url mode against a stub HTTP endpoint
    serving the same snapshot — byte-identical output, plus the
    unreachable-URL note path."""
    st_mod = _serve_top()
    (tmp_path / "status.json").write_text(json.dumps(CANNED_TOP))
    out = io.StringIO()
    assert st_mod.render(str(tmp_path), out=out)
    assert out.getvalue() == GOLDEN_TOP
    # --url mode against a stub wire: same golden, byte for byte
    stub = ObsHttpServer(port=0, status_fn=lambda: CANNED_TOP)
    try:
        out = io.StringIO()
        assert st_mod.render_url(stub.url, out=out)
        assert out.getvalue() == GOLDEN_TOP
        assert st_mod.main(["--url", stub.url]) == 0
    finally:
        stub.close()
    out = io.StringIO()
    assert not st_mod.render_url("http://127.0.0.1:9", out=out,
                                 timeout=0.5)
    assert "unreachable" in out.getvalue()


def test_fleet_status_tool_renders_without_jax(tmp_path):
    """tools/fleet_status.py end-to-end over file sources: loads the
    aggregator by path (no package import), renders the table and the
    --json snapshot, exits 0 with >=1 reachable pool and 1 with
    none."""
    import importlib.util
    import contextlib

    spec = importlib.util.spec_from_file_location(
        "fleet_status_tool",
        os.path.join(REPO, "tools", "fleet_status.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    (tmp_path / "status.json").write_text(
        json.dumps(_canned_status()))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = tool.main([str(tmp_path)])
    assert rc == 0
    text = buf.getvalue()
    assert "fleet_status" in text and "pools=1/1" in text
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = tool.main([str(tmp_path), "--json"])
    assert rc == 0
    snap = json.loads(buf.getvalue())
    assert snap["n_reachable"] == 1
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = tool.main([str(tmp_path / "nope"), "--json"])
    assert rc == 1


# ----------------------------------------------------------------------
# watchdog + flight recorder units (round 15; jax-light, no server)
# ----------------------------------------------------------------------


def test_watchdog_detectors_and_validation():
    """The three detectors as units — monotone backlog growth,
    sustained throughput collapse (adjacent rolling medians: a noisy
    point cannot trip it), and the strict validation surfaces — plus
    the one-shot latch."""
    from gibbs_student_t_tpu.obs.watchdog import (
        Watchdog,
        WatchdogSpec,
        serve_watchdog_env,
    )

    with pytest.raises(ValueError, match="collapse_drop"):
        WatchdogSpec(collapse_drop=1.5)
    with pytest.raises(ValueError, match="policy"):
        Watchdog(policy="explode")
    with pytest.raises(ValueError, match="GST_SERVE_WATCHDOG"):
        os.environ["GST_SERVE_WATCHDOG"] = "bogus"
        try:
            serve_watchdog_env()
        finally:
            del os.environ["GST_SERVE_WATCHDOG"]
    trips = []
    w = Watchdog(policy="warn",
                 spec=WatchdogSpec(backlog_quanta=3, backlog_min=2,
                                   min_deadline_s=99, tick_s=9),
                 on_trip=trips.append)
    for b in (1, 2, 2):               # non-strict growth below min: no
        w.note_quantum(10.0, backlog=b)
    assert w.check() is None
    for b in (3, 4):
        w.note_quantum(10.0, backlog=b)
    t = w.check()
    assert t["cause"] == "drain_backlog" and trips == [t]
    assert w.check() is t             # latched, on_trip fired once
    w2 = Watchdog(policy="warn",
                  spec=WatchdogSpec(collapse_window=2,
                                    collapse_drop=0.5,
                                    min_deadline_s=99, tick_s=9))
    for v in (100, 100, 90, 10):      # one bad point: medians hold
        w2.note_quantum(10.0, sweeps_per_s=v)
    # recent median sits exactly AT the threshold (50 = 0.5*100):
    # strict comparison — a borderline noisy point does not trip
    assert w2.check() is None
    w3 = Watchdog(policy="warn",
                  spec=WatchdogSpec(collapse_window=2,
                                    collapse_drop=0.5,
                                    min_deadline_s=99, tick_s=9))
    for v in (100, 100, 10, 10):
        w3.note_quantum(10.0, sweeps_per_s=v)
    t3 = w3.check()
    assert t3 is not None and t3["cause"] == "throughput_collapse"
    snap = w3.snapshot()
    assert snap["state"] == "tripped"
    assert snap["trip"]["cause"] == "throughput_collapse"


def test_flight_recorder_ring_bounds_and_dump(tmp_path, schemas):
    """Unit: the ring drops oldest past capacity (and accounts the
    drops), events bound independently, context/span providers that
    raise degrade to error markers, dumps are atomic + schema-valid,
    and the periodic sync fires spanless every sync_every quanta."""
    from gibbs_student_t_tpu.obs.flight import (
        FlightRecorder,
        read_bundle,
    )

    sync = str(tmp_path / "flight.json")
    rec = FlightRecorder(
        capacity=4, events_capacity=3, sync_path=sync, sync_every=2,
        context_fn=lambda: {"quantum_idx": 9, "kernel_timers": False},
        spans_fn=lambda: [{"name": "s", "role": "drain", "t0": 0.0,
                           "dur": 0.1, "tenant": None, "quantum": 0,
                           "thread": "t"}])
    for q in range(7):
        rec.note_quantum({"q": q, "t": 1.0, "dispatch_ms": 10.0,
                          "drain_ms": 1.0, "busy_lanes": 8,
                          "occupancy_now": 0.5, "queue_depth": 0,
                          "faults": {}, "stage_device_ms": None})
        rec.note_event("admit", tenant=q)
    doc = rec.bundle("unit")
    assert [e["q"] for e in doc["quanta"]] == [3, 4, 5, 6]
    assert doc["quanta_recorded"] == 7 and doc["quanta_dropped"] == 3
    assert len(doc["events"]) == 3 and doc["events_dropped"] == 4
    assert doc["quantum_idx"] == 9          # context merged
    assert doc["spans"]                     # provider included
    obs_schema.assert_valid(doc, schemas["postmortem"], "unit bundle",
                            defs=schemas)
    # the periodic sync fired (spanless) and parses via read_bundle
    fj = read_bundle(sync)
    assert fj["reason"] == "sync" and "spans" not in fj
    obs_schema.assert_valid(fj, schemas["postmortem"], "sync bundle",
                            defs=schemas)
    # broken providers degrade inside the bundle, never raise out
    rec2 = FlightRecorder(
        context_fn=lambda: 1 / 0, spans_fn=lambda: 1 / 0)
    rec2.note_quantum({"q": 0, "dispatch_ms": 1.0, "busy_lanes": 1,
                       "queue_depth": 0})
    d2 = rec2.bundle("broken")
    assert "context_error" in d2 and "spans_error" in d2
    p = rec2.dump(str(tmp_path / "pm.json"), reason="broken")
    assert p and read_bundle(p)["reason"] == "broken"
    # unreadable target: warn-once, None return, recorder survives
    bad = str(tmp_path / "pm.json" / "nope" / "x.json")
    with pytest.warns(RuntimeWarning, match="flight-recorder"):
        assert rec2.dump(bad, reason="x") is None
    assert rec2.dump(bad, reason="x") is None   # quiet second time


# ----------------------------------------------------------------------
# env-gate doc drift guard (ROADMAP item 5's sprawl, at least indexed)
# ----------------------------------------------------------------------


def _package_env_gates():
    """Every GST_* name the package reads from the environment:
    direct ``os.environ`` reads plus quoted gate-name literals (the
    indirection through helpers like pallas_util.mode_from_env passes
    the name as a string literal)."""
    pkg = os.path.join(REPO, "gibbs_student_t_tpu")
    env_line = re.compile(r"GST_[A-Z0-9_]+")
    literal = re.compile(r"""["'](GST_[A-Z0-9_]+)["']""")
    gates = set()
    for root, _, files in os.walk(pkg):
        if "__pycache__" in root:
            continue
        for f in files:
            if not f.endswith(".py"):
                continue
            src = open(os.path.join(root, f)).read()
            for line in src.splitlines():
                if "environ" in line:
                    gates.update(env_line.findall(line))
            gates.update(literal.findall(src))
    return gates


def test_every_env_gate_is_documented():
    """docs/OBSERVABILITY.md's env-gate index must name every GST_*
    gate the package reads — a new gate without a doc row fails here,
    next to the sprawl ROADMAP item 5 wants folded."""
    gates = _package_env_gates()
    # sanity: the extractor sees the well-known gates, so an empty
    # set can never vacuously pass
    for known in ("GST_NCHOL", "GST_SERVE_PIPELINE", "GST_FUSE_STAGES",
                  "GST_LEDGER_PATH"):
        assert known in gates, f"extractor lost {known}"
    docs = open(os.path.join(REPO, "docs", "OBSERVABILITY.md")).read()
    missing = sorted(g for g in gates if g not in docs)
    assert not missing, (
        f"env gates read by the package but absent from "
        f"docs/OBSERVABILITY.md: {missing} — add them to the "
        "'Env-gate index' table")


def test_no_env_gate_read_bypasses_the_registry():
    """Round 18's hard guard: the ONLY package file allowed to read a
    ``GST_*`` variable from the environment is the dispatch registry
    itself (ops/registry.py) — everything else must resolve through
    its one probe→validate→degrade→record surface. A new feature that
    sneaks in a bare ``os.environ.get("GST_...")`` fails here."""
    pkg = os.path.join(REPO, "gibbs_student_t_tpu")
    env_line = re.compile(r"GST_[A-Z0-9_]+")
    offenders = []
    for root, _, files in os.walk(pkg):
        if "__pycache__" in root:
            continue
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            if path.endswith(os.path.join("ops", "registry.py")):
                continue
            for ln, line in enumerate(open(path).read().splitlines(),
                                      1):
                if "environ" in line and env_line.search(line):
                    offenders.append(f"{path}:{ln}: {line.strip()}")
    assert not offenders, (
        "GST_* environment reads bypassing ops/registry.py:\n"
        + "\n".join(offenders))


def test_env_gate_index_is_generated_output():
    """The committed OBSERVABILITY.md env-gate table between the
    markers must be byte-identical to ``tools/gates.py --markdown``'s
    output (i.e. to the registry's declared table) — the index cannot
    drift from the registry that enforces it."""
    from gibbs_student_t_tpu.ops.registry import gates_markdown

    docs = open(os.path.join(REPO, "docs", "OBSERVABILITY.md")).read()
    start = docs.index("<!-- gates-table-start")
    start = docs.index("\n", start) + 1
    end = docs.index("<!-- gates-table-end -->")
    committed = docs[start:end].strip("\n")
    assert committed == "\n".join(gates_markdown()), (
        "docs/OBSERVABILITY.md env-gate table is stale — regenerate "
        "with: python tools/gates.py --markdown")
