"""Scheduler policy-layer tests (round 20; docs/SERVING.md
"Scheduling & overload").

Three tiers in one module, selected by the ``sched`` marker:

- scheduler-core property tests over FAKES (no pool, no compiles):
  FIFO degeneration of the priority score, tier/slack/aging ordering,
  the bounded queue's shed + displaced-bypass semantics, and the
  shed/deadline handle-resolution contract (satellite of round 20 —
  ``result()`` can never hang on a job the server refused or expired);
- tiny-pool tier-1 arms: the preemption bitwise-lossless pin (a
  preempted spooled tenant's final chains are bitwise the
  uninterrupted run's — the checkpoint/resume contract under
  scheduling) and the structured server-side shed;
- a slow RPC arm: priority/deadline ride the submit frame, preemption
  stays bitwise over the wire, and a deadline-armed victim resolves
  with a structured ``DeadlineExceeded`` carrying the spooled prefix.
"""

import time

import numpy as np
import pytest

from gibbs_student_t_tpu.serve.scheduler import (
    AdmissionQueue,
    DeadlineExceeded,
    QueueFull,
    RetryAfter,
    TenantError,
    TenantHandle,
    TenantRequest,
    schedule_score,
)

pytestmark = pytest.mark.sched


def _native_available() -> bool:
    from gibbs_student_t_tpu import native

    return native.available()


# ---------------------------------------------------------------------------
# fakes: a TenantRequest never validates ``ma`` at construction, so the
# policy layer is testable without a model or a pool
# ---------------------------------------------------------------------------

class _FakeMA:
    pass


def _handle(tid=0, *, niter=20, priority=1, deadline=None, **kw):
    req = TenantRequest(ma=_FakeMA(), niter=niter, nchains=4,
                        priority=priority, **kw)
    h = TenantHandle(tid, req)
    if deadline is not None:
        # what ChainServer.submit arms: the ABSOLUTE deadline sweep
        h._deadline_sweep = req.start_sweep + deadline
    return h


def _drain(q, score=None, fits=lambda h: True):
    out = []
    while True:
        h = q.pop_first_fit(fits)
        if h is None:
            return out
        out.append(h)


# ---------------------------------------------------------------------------
# schedule_score ordering properties
# ---------------------------------------------------------------------------

def test_retry_after_is_a_structured_queuefull():
    e = RetryAfter("full", retry_after_s=1.5, queue_depth=7, tier=2,
                   where="router")
    assert isinstance(e, QueueFull)
    assert e.retry_after_s == 1.5 and e.queue_depth == 7
    assert e.tier == 2 and e.where == "router"
    # defaults: a server-side shed with no estimate is still structured
    e2 = RetryAfter("full")
    assert e2.retry_after_s is None and e2.queue_depth is None
    assert e2.where == "server"


def test_fifo_degeneration_with_default_requests():
    """The stability pin: equal priority + no deadline pops in EXACT
    arrival order under the scored queue — the priority scheduler is
    bitwise the historical FIFO until someone asks for more."""
    scored = AdmissionQueue(maxsize=16, score=schedule_score)
    plain = AdmissionQueue(maxsize=16)
    hs = [_handle(i) for i in range(6)]
    for h in hs:
        scored.put(h)
    for h in [_handle(100 + i) for i in range(6)]:
        plain.put(h)
    assert [h.tenant_id for h in _drain(scored)] == [0, 1, 2, 3, 4, 5]
    assert [h.tenant_id for h in _drain(plain)] == list(range(100, 106))


def test_priority_tiers_order_pops():
    q = AdmissionQueue(maxsize=16,
                       score=lambda h: schedule_score(h, age_boost_s=0))
    for tid, pr in [(0, 2), (1, 0), (2, 1), (3, 0), (4, 3)]:
        q.put(_handle(tid, priority=pr))
    # tier first (0 before 1 before 2...), arrival seq within a tier
    assert [h.tenant_id for h in _drain(q)] == [1, 3, 2, 0, 4]


def test_deadline_slack_orders_within_a_tier():
    """Within a tier the tightest deadline pops first, and any armed
    deadline outranks an open-ended job (slack +inf)."""
    q = AdmissionQueue(maxsize=16,
                       score=lambda h: schedule_score(h, age_boost_s=0))
    q.put(_handle(0, niter=20))                  # no deadline -> +inf
    q.put(_handle(1, niter=20, deadline=100))    # slack 80
    q.put(_handle(2, niter=20, deadline=25))     # slack 5
    assert [h.tenant_id for h in _drain(q)] == [2, 1, 0]
    # the slack a fresh handle reports is budget-based: niter left
    h = _handle(9, niter=20, deadline=25)
    assert h.slack_sweeps() == pytest.approx(5.0)
    assert _handle(9, niter=20).slack_sweeps() is None


def test_aging_bounds_starvation():
    """A batch job left queued long enough outranks a FRESH interactive
    arrival — one tier boost per ``age_boost_s`` waited — and aging
    off (None/0) keeps raw tiers."""
    old_batch = _handle(0, priority=2)
    old_batch._age_t = time.monotonic() - 95.0   # ~3 boosts at 30 s
    fresh_hi = _handle(1, priority=0)
    s_old = schedule_score(old_batch, age_boost_s=30.0)
    s_hi = schedule_score(fresh_hi, age_boost_s=30.0)
    assert s_old < s_hi
    assert schedule_score(old_batch, age_boost_s=None)[0] == 2.0
    assert schedule_score(old_batch, age_boost_s=0)[0] == 2.0


def test_scored_first_fit_skips_nonfitting_best():
    """Best-score-fit: the best-scored job that does not fit is passed
    over for a fitting lower-tier one (backfill survives the priority
    scheduler); the big job pops once capacity is claimed."""
    q = AdmissionQueue(maxsize=16,
                       score=lambda h: schedule_score(h, age_boost_s=0))
    big_hi = _handle(0, priority=0)
    big_hi.request.nchains = 32
    small_lo = _handle(1, priority=2)
    q.put(big_hi)
    q.put(small_lo)
    got = q.pop_first_fit(lambda h: h.request.nchains <= 4)
    assert got is small_lo
    assert q.pop_first_fit(lambda h: True) is big_hi


# ---------------------------------------------------------------------------
# the bounded queue: shed, displaced bypass, per-tier depth
# ---------------------------------------------------------------------------

def test_reject_policy_sheds_at_capacity():
    q = AdmissionQueue(maxsize=2, policy="reject")
    q.put(_handle(0))
    q.put(_handle(1))
    with pytest.raises(QueueFull):
        q.put(_handle(2))
    assert len(q) == 2


def test_block_policy_times_out_loudly():
    q = AdmissionQueue(maxsize=1, policy="block")
    q.put(_handle(0))
    with pytest.raises(QueueFull, match="still full"):
        q.put(_handle(1), timeout=0.05)


def test_put_displaced_bypasses_capacity():
    """The lossless-preemption contract: a preempted continuation is
    requeued even through a FULL reject queue (it was admitted once —
    shedding it would turn a preemption into data loss), and it keeps
    its aging anchor so it carries waited time into the next pop."""
    q = AdmissionQueue(maxsize=1, policy="reject",
                       score=lambda h: schedule_score(
                           h, age_boost_s=30.0))
    q.put(_handle(0))
    displaced = _handle(7, priority=2)
    displaced._age_t = time.monotonic() - 120.0
    q.put_displaced(displaced)
    assert len(q) == 2
    assert displaced._queue_seq > 0
    # the preserved anchor outranks the fresh default-tier head
    assert q.pop_first_fit(lambda h: True) is displaced


def test_depth_by_tier():
    q = AdmissionQueue(maxsize=16)
    for pr in (0, 2, 2, 1, 2):
        q.put(_handle(pr, priority=pr))
    assert q.depth_by_tier() == {0: 1, 1: 1, 2: 3}
    q.pop_first_fit(lambda h: h.request.priority == 2)
    assert q.depth_by_tier() == {0: 1, 1: 1, 2: 2}


# ---------------------------------------------------------------------------
# handle resolution: a shed or expired job's result() NEVER hangs
# ---------------------------------------------------------------------------

def test_shed_handle_resolves_promptly():
    h = _handle(3, priority=2)
    err = RetryAfter("admission queue full", retry_after_s=0.5,
                     queue_depth=4, tier=2)
    h._fail_shed(err)
    assert h.done() and h.status == "rejected"
    with pytest.raises(RetryAfter) as ei:
        h.result(timeout=0.1)   # resolved -> returns without waiting
    assert ei.value is err
    assert ei.value.retry_after_s == 0.5 and ei.value.queue_depth == 4
    assert ei.value.tier == 2


def test_deadline_exceeded_structure():
    h = _handle(5, deadline=40)
    err = DeadlineExceeded(5, deadline_sweep=40, served_sweeps=15,
                           partial="prefix-stub")
    assert isinstance(err, TenantError)
    assert err.deadline_sweep == 40 and err.served_sweeps == 15
    assert err.partial == "prefix-stub" and err.where == "deadline"
    h._fail_tenant(err)
    assert h.done() and h.status == "failed"
    with pytest.raises(DeadlineExceeded) as ei:
        h.result(timeout=0.1)
    assert ei.value.partial == "prefix-stub"


def test_submit_validates_priority_and_deadline_types():
    """The wire-field validation lives in ChainServer.submit; pin the
    score's tolerance here: a handle with the DEFAULTS scores finite
    and orderable (no deadline -> +inf slack, never a TypeError)."""
    s = schedule_score(_handle(0))
    assert s[1] == float("inf") and isinstance(s[0], float)


# ---------------------------------------------------------------------------
# tiny-pool tier-1 arms (one server, one compile)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def demo():
    from tests.conftest import make_demo_pta
    from gibbs_student_t_tpu.config import GibbsConfig

    pta = make_demo_pta()
    return pta.frozen(0), GibbsConfig(model="mixture")


EXACT_FIELDS = ("chain", "zchain", "thetachain", "dfchain")
ROUNDOFF_FIELDS = ("bchain", "alphachain", "poutchain")


@pytest.mark.serve
@pytest.mark.skipif(not _native_available(),
                    reason="preemption needs spooling (native library)")
def test_preemption_bitwise_lossless(demo, tmp_path):
    """The tentpole pin: a spooled low-tier tenant preempted by a
    priority-0 arrival finishes with final chains BITWISE identical to
    the same request served uninterrupted — preemption is the cancel
    freeze + the checkpoint-resume continuation, and the per-sweep
    fold-in keying makes the splice invisible."""
    from gibbs_student_t_tpu.serve import ChainServer

    ma, cfg = demo
    srv = ChainServer(ma, cfg, nlanes=32, quantum=5, record="full",
                      scheduler="priority")
    # arm 1: the uninterrupted reference (same request shape, spooled)
    ref = srv.submit(TenantRequest(
        ma=ma, niter=20, nchains=32, seed=5, priority=2,
        spool_dir=str(tmp_path / "ref")))
    srv.run()
    ref_res = ref.result()
    # arm 2: same job; a priority-0 arrival needs the WHOLE pool, so
    # admission must preempt the running spooled tenant
    low = srv.submit(TenantRequest(
        ma=ma, niter=20, nchains=32, seed=5, priority=2,
        spool_dir=str(tmp_path / "low")))
    hi_box = []

    def on_q(server):
        # only once the victim is RUNNING with a checkpoint behind it —
        # a hi arrival while low is still queued is (correctly) just
        # admitted first, no preemption needed
        if low.sweeps_done >= 5 and not hi_box:
            hi_box.append(server.submit(TenantRequest(
                ma=ma, niter=10, nchains=32, seed=99, priority=0)))

    srv.run(on_quantum=on_q)
    for _ in range(20):
        if low.done() and hi_box and hi_box[0].done():
            break
        srv.run(on_quantum=on_q)
    hi_box[0].result()
    low_res = low.result()
    assert low.preemptions >= 1
    assert srv.summary()["sched"]["preemptions"] >= 1
    for f in EXACT_FIELDS + ROUNDOFF_FIELDS:
        assert np.array_equal(np.asarray(getattr(ref_res, f)),
                              np.asarray(getattr(low_res, f))), f
    st = srv.status()
    assert st["sched"]["policy"] == "priority"


@pytest.mark.serve
def test_server_shed_is_structured(demo):
    """A bounded reject-policy server sheds with the STRUCTURED signal
    (retry_after_s + queue_depth + tier) and counts it per tier — and
    the shed happens at submit, before any placement, so the queue
    never grows past its bound."""
    from gibbs_student_t_tpu.serve import ChainServer

    ma, cfg = demo
    srv = ChainServer(ma, cfg, nlanes=32, quantum=5, max_queue=1,
                      backpressure="reject", pipeline=False)
    srv.submit(TenantRequest(ma=ma, niter=10, nchains=16, seed=0))
    with pytest.raises(RetryAfter) as ei:
        srv.submit(TenantRequest(ma=ma, niter=10, nchains=16, seed=1,
                                 priority=2))
    e = ei.value
    assert e.retry_after_s is not None and e.retry_after_s >= 0.5
    assert e.queue_depth >= 1 and e.tier == 2 and e.where == "server"
    sched = srv.summary()["sched"]
    assert sched["sheds"] == 1
    assert sched["sheds_by_tier"] in ({"2": 1}, {2: 1})
    assert srv.status()["queue_depth"] <= 1


# ---------------------------------------------------------------------------
# the wire: priority/deadline on the submit frame (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.fleet
@pytest.mark.skipif(not _native_available(),
                    reason="preemption needs spooling (native library)")
def test_rpc_priority_preemption_and_deadline(demo, tmp_path):
    """Over a REAL RpcServer/RemoteChainServer edge: priority and
    deadline_sweeps ride the submit frame; a remote spooled tenant
    preempted by a remote priority-0 arrival still finishes bitwise
    the uninterrupted run; and a deadline-armed victim whose deadline
    passed at the freeze resolves with a structured DeadlineExceeded
    carrying the spooled prefix — the wire adds transport, not
    semantics."""
    from gibbs_student_t_tpu.serve import ChainServer
    from gibbs_student_t_tpu.serve.rpc import (
        RemoteChainServer,
        RpcServer,
    )

    ma, cfg = demo
    srv = ChainServer(ma, cfg, nlanes=32, quantum=5, record="full",
                      scheduler="priority")
    rpc = RpcServer(srv)
    cli = RemoteChainServer(rpc.address)
    try:
        ref = cli.submit(TenantRequest(
            ma=ma, niter=20, nchains=32, seed=11, priority=2,
            spool_dir=str(tmp_path / "ref")))
        srv.run()
        ref_res = ref.result(timeout=300)
        # priority + deadline land server-side via the wire
        low = cli.submit(TenantRequest(
            ma=ma, niter=20, nchains=32, seed=11, priority=2,
            deadline_sweeps=100, spool_dir=str(tmp_path / "low")))
        p = low.progress()
        assert p["priority"] == 2 and p["deadline_sweep"] == 100
        hi_box = []

        def on_q(server):
            if (not hi_box
                    and low.progress()["sweeps_done"] >= 5):
                hi_box.append(cli.submit(TenantRequest(
                    ma=ma, niter=10, nchains=32, seed=77, priority=0)))

        srv.run(on_quantum=on_q)
        for _ in range(20):
            if low.done() and hi_box and hi_box[0].done():
                break
            srv.run(on_quantum=on_q)
        hi_box[0].result(timeout=300)
        low_res = low.result(timeout=300)
        assert low.progress().get("preemptions", 0) >= 1
        for f in EXACT_FIELDS:
            assert np.array_equal(np.asarray(getattr(ref_res, f)),
                                  np.asarray(getattr(low_res, f))), f
        # deadline at sweep 5: any preemption freeze lands at/after the
        # first quantum boundary, so the requeue check must expire it
        dead = cli.submit(TenantRequest(
            ma=ma, niter=20, nchains=32, seed=11, priority=2,
            deadline_sweeps=5, spool_dir=str(tmp_path / "dead")))
        hi2 = []

        def on_q2(server):
            if (not hi2
                    and dead.progress()["sweeps_done"] >= 5):
                hi2.append(cli.submit(TenantRequest(
                    ma=ma, niter=10, nchains=32, seed=78, priority=0)))

        srv.run(on_quantum=on_q2)
        for _ in range(20):
            if dead.done() and hi2 and hi2[0].done():
                break
            srv.run(on_quantum=on_q2)
        hi2[0].result(timeout=300)
        with pytest.raises(DeadlineExceeded) as ei:
            dead.result(timeout=300)
        err = ei.value
        assert err.deadline_sweep == 5 and err.served_sweeps >= 5
        assert err.partial is not None
        # the prefix is bitwise the uninterrupted run's first sweeps
        n = np.asarray(err.partial.chain).shape[0]
        assert n >= 5
        assert np.array_equal(np.asarray(err.partial.chain),
                              np.asarray(ref_res.chain)[:n])
    finally:
        srv.close()
        rpc.close()
        cli.close()
