"""Fleet tracing, explainable placement and the capacity timeline
(round 19, docs/OBSERVABILITY.md "Fleet tracing & the capacity
timeline").

Tier-1 arms run over fakes and canned documents — no pool compiles:

- the NTP-style clock-offset estimator (skewed fake clocks,
  asymmetric RTT error bound, min-RTT sample selection, degraded
  inputs),
- trace stitching over canned Chrome docs (pool pid striding, label
  prefixes, offset-corrected cross-pool ordering, schema validity
  against the ``fleet_trace`` schema),
- the router observability plane over fake pools: trace-id minting,
  the placement-event journal + ``explain()`` query, the capacity
  sample/ring/sampler thread, the fleet Prometheus exposition, the
  fleet postmortem bundle,
- the watchdog fold in ``fleet_merge`` / ``render_fleet`` (a tripped
  pool must render sick, not healthy),
- the ``perf_report --check`` trace-completeness gate over canned
  ledger records.

The slow arm drives a real 2-pool subprocess fleet and pins the
acceptance contract: one stitched, schema-valid doc in which every
completed job has >=1 router span AND >=1 pool span sharing its
``trace_id``, with a clock block per pool.
"""

import io
import json
import os
import time

import pytest

from gibbs_student_t_tpu.obs import schema as obs_schema
from gibbs_student_t_tpu.obs.aggregate import (
    POOL_PID_STRIDE,
    estimate_clock_offset,
    fleet_merge,
    render_fleet,
    stitch_fleet_trace,
    trace_coverage,
)
from gibbs_student_t_tpu.serve.scheduler import TenantRequest

from tests.test_rpc import _FakePool, _router

pytestmark = pytest.mark.fleet

SCHEMAS = obs_schema.load_schemas()


def _valid(doc, name, label):
    obs_schema.assert_valid(doc, SCHEMAS[name], label, defs=SCHEMAS)


# ---------------------------------------------------------------------------
# the clock-offset estimator
# ---------------------------------------------------------------------------

def test_clock_offset_recovers_skewed_clock():
    """Symmetric RTT, server clock 5s ahead: the estimator recovers
    the skew exactly."""
    t0 = 100.0
    samples = []
    for rtt in (0.010, 0.004, 0.020):
        mid = t0 + rtt / 2.0
        samples.append((t0, mid + 5.0, t0 + rtt))
        t0 += 1.0
    est = estimate_clock_offset(samples)
    assert est["n"] == 3
    assert est["offset_s"] == pytest.approx(5.0, abs=1e-6)
    assert est["rtt_s"] == pytest.approx(0.004, abs=1e-6)


def test_clock_offset_prefers_min_rtt_sample():
    """The min-RTT sample wins: a high-RTT sample with a wildly wrong
    midpoint estimate must not contaminate the answer."""
    good = (10.0, 10.0005 + 2.0, 10.001)      # rtt 1ms, offset 2s
    bad = (11.0, 11.25 + 3.7, 11.5)           # rtt 500ms, asymmetric
    est = estimate_clock_offset([bad, good])
    assert est["offset_s"] == pytest.approx(2.0, abs=1e-6)
    assert est["rtt_s"] == pytest.approx(0.001, abs=1e-6)


def test_clock_offset_asymmetric_rtt_error_is_bounded():
    """Fully asymmetric path (all delay on the send leg): the
    midpoint estimate is off by exactly rtt/2 — the estimator's
    documented error bound."""
    rtt = 0.030
    # server reads its (true-synced) clock only after the full send
    # delay: ts = t0 + rtt, reply returns instantly
    est = estimate_clock_offset([(50.0, 50.0 + rtt, 50.0 + rtt)])
    assert abs(est["offset_s"]) <= rtt / 2.0 + 1e-9
    assert est["offset_s"] == pytest.approx(rtt / 2.0, abs=1e-6)


def test_clock_offset_degrades_on_garbage():
    """Empty or malformed samples (negative RTT, wrong arity, NaN-free
    junk) degrade to the identity offset, never raise."""
    assert estimate_clock_offset([]) == {
        "offset_s": 0.0, "rtt_s": None, "n": 0}
    est = estimate_clock_offset(
        [(5.0, 4.0, 3.0), ("x",), None, (1.0,)])
    assert est == {"offset_s": 0.0, "rtt_s": None, "n": 0}


# ---------------------------------------------------------------------------
# stitching canned docs
# ---------------------------------------------------------------------------

def _doc(events, epoch_wall, dropped=0):
    return {"traceEvents": list(events), "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": dropped,
                          "epoch_wall": epoch_wall}}


def _xev(name, ts, pid=0, tid=0, **args):
    return {"name": name, "ph": "X", "pid": pid, "tid": tid,
            "ts": ts, "dur": 100.0, "args": args}


def test_stitch_remaps_pids_and_labels_pools():
    """Pool swimlanes land on their own pid stride beside the router
    lane, metadata process names carry the pool label, and the doc
    validates against the ``fleet_trace`` schema."""
    router = _doc([
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "serve"}},
        _xev("place", 10.0, trace_id="t1"),
    ], epoch_wall=1000.0)
    pool = _doc([
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "dispatch"}},
        _xev("quantum", 20.0, pid=1, trace_id="t1"),
    ], epoch_wall=1000.0)
    doc = stitch_fleet_trace(router, [
        {"label": "pool0", "doc": pool,
         "clock": {"offset_s": 0.0, "rtt_s": 0.001, "n": 3}}])
    _valid(doc, "fleet_trace", "stitched doc")
    _valid(doc, "chrome_trace", "stitched doc (chrome shape)")
    pids = {ev["pid"] for ev in doc["traceEvents"]}
    assert pids == {0, POOL_PID_STRIDE, POOL_PID_STRIDE + 1}
    names = {ev["args"]["name"] for ev in doc["traceEvents"]
             if ev["name"] == "process_name"}
    assert names == {"router", "pool0/dispatch"}
    clocks = doc["otherData"]["clocks"]
    assert clocks["pool0"]["offset_s"] == 0.0
    assert doc["otherData"]["n_pools"] == 1


def test_stitch_corrects_cross_pool_ordering():
    """A pool whose wall clock runs 10s AHEAD: after offset
    correction, a pool event that truly happened at router-epoch
    +3s lands at ts=3e6 us — same timeline as a router event at
    +3s, despite the skewed epochs."""
    # true router epoch 1000.0; pool process started at true 1002.0,
    # so its (skewed) epoch_wall reads 1012.0; a pool span at true
    # 1003.0 has local ts (1013.0 - 1012.0) s = 1e6 us
    router = _doc([_xev("submit", 3_000_000.0, trace_id="j")],
                  epoch_wall=1000.0)
    pool = _doc([_xev("quantum", 1_000_000.0, pid=0, trace_id="j")],
                epoch_wall=1012.0)
    doc = stitch_fleet_trace(router, [
        {"label": "p", "doc": pool,
         "clock": {"offset_s": 10.0, "rtt_s": 0.001, "n": 5}}])
    evs = {ev["pid"]: ev for ev in doc["traceEvents"]
           if ev["ph"] == "X"}
    assert evs[0]["ts"] == pytest.approx(3_000_000.0)
    assert evs[POOL_PID_STRIDE]["ts"] == pytest.approx(3_000_000.0)
    shift = doc["otherData"]["clocks"]["p"]["shift_us"]
    assert shift == pytest.approx(2_000_000.0)


def test_trace_coverage_counts_both_sides():
    router = _doc([_xev("place", 0.0, trace_id="a"),
                   _xev("submit", 1.0, trace_id="a"),
                   _xev("noise", 2.0)], epoch_wall=1.0)
    pool = _doc([_xev("quantum", 0.0, pid=0, trace_id="a"),
                 _xev("quantum", 5.0, pid=0, trace_id="b")],
                epoch_wall=1.0)
    doc = stitch_fleet_trace(router, [
        {"label": "p", "doc": pool,
         "clock": {"offset_s": 0.0, "rtt_s": 0.0, "n": 1}}])
    cov = trace_coverage(doc)
    assert cov["a"] == {"router": 2, "pool": 1}
    assert cov["b"] == {"router": 0, "pool": 1}


# ---------------------------------------------------------------------------
# the router plane over fake pools
# ---------------------------------------------------------------------------

def test_router_mints_trace_ids_and_journals_placements(tmp_path):
    """Submit through the router: every job gets a trace id (the
    caller's request object untouched), exactly one schema-valid
    placement event per placement lands in the journal, and
    ``explain()`` answers per job."""
    light = _FakePool("light", queue_depth=0, free_groups=3,
                      occupancy=0.2)
    heavy = _FakePool("heavy", queue_depth=5, free_groups=0,
                      occupancy=0.9)
    r = _router([heavy, light], obs_dir=str(tmp_path / "obs"))
    reqs = [TenantRequest(ma={}, niter=5, nchains=4, name=f"job{i}")
            for i in range(3)]
    handles = [r.submit(rq) for rq in reqs]
    assert all(rq.trace_id is None for rq in reqs)  # caller untouched
    tids = [h.request.trace_id for h in handles]
    assert all(tids) and len(set(tids)) == 3
    # one event per placement, reconciling 1:1 with the counters
    assert r.placement_events == sum(r.placements.values()) == 3
    jpath = tmp_path / "obs" / "placements.jsonl"
    events = [json.loads(l) for l in
              jpath.read_text().splitlines()]
    assert len(events) == 3
    for ev in events:
        _valid(ev, "placement_event", "journal event")
        assert ev["reason"] == "submit" and ev["pool"] == "light"
        assert ev["job"] in {"job0", "job1", "job2"}
        assert ev["won"] == "score"
        cands = {c["pool"]: c for c in ev["candidates"]}
        assert set(cands) == {"light", "heavy"}
        assert cands["heavy"]["score"]["queue_staged"] == 5
    # explain() by handle and by trace id find the same event
    ex = r.explain(handles[0])
    assert len(ex) == 1 and ex[0]["trace_id"] == tids[0]
    assert r.explain(tids[1])[0]["trace_id"] == tids[1]
    # the tail answers too when no journal is armed
    r2 = _router([_FakePool("only")])
    h2 = r2.submit(reqs[0])
    assert r2.explain(h2)[0]["won"] == "round_robin" or \
        r2.explain(h2)[0]["won"] in ("score", "fallback")
    r2.close()
    r.close()


def test_router_spans_share_trace_id_and_export_degrades(tmp_path):
    """The router's own spans (place/submit/result) carry the job's
    trace id; ``export_trace`` over fakes (no trace surface) degrades
    to ``missing_pools`` notes and still returns a schema-valid doc."""
    p = _FakePool("p0")
    r = _router([p], obs_dir=str(tmp_path / "obs"))
    h = r.submit(TenantRequest(ma={}, niter=5, nchains=4, name="jX"))
    h._inner._finish({"ok": True})
    assert h.result(timeout=5) == {"ok": True}
    tid = h.request.trace_id
    spans = r.spans.spans()
    for s in spans:
        _valid(s, "span", "router span")
    named = {s["name"] for s in spans if s.get("trace_id") == tid}
    assert {"place", "submit", "result"} <= named
    assert all(s["role"] == "router" for s in spans
               if s["name"] in ("place", "submit", "result"))
    out = str(tmp_path / "fleet_trace.json")
    doc = r.export_trace(path=out)
    _valid(doc, "fleet_trace", "degraded fleet trace")
    assert [m["pool"] for m in
            doc["otherData"]["missing_pools"]] == ["p0"]
    assert json.load(open(out)) == doc
    cov = trace_coverage(doc)
    assert cov[tid]["router"] >= 3 and cov[tid]["pool"] == 0
    # trace=False: no recorder, no spans, submission identical
    r2 = _router([_FakePool("p1")], trace=False)
    h2 = r2.submit(TenantRequest(ma={}, niter=5, nchains=4,
                                 name="jY"))
    assert r2.spans is None and h2.request.trace_id
    _valid(r2.export_trace(), "fleet_trace", "spanless fleet trace")
    r2.close()
    r.close()


def test_capacity_sampler_ring_jsonl_and_metrics(tmp_path):
    """The sampler thread fills the bounded ring + JSONL series with
    schema-valid samples (watchdog health + per-tenant slack folded
    in), the Prometheus exposition renders per-pool gauges exactly
    once per family, and the postmortem bundle validates."""
    p = _FakePool("p0")
    orig_status = p.status

    def status():
        st = orig_status()
        st["watchdog"] = {"state": "ok",
                          "heartbeat_age_s": {"dispatch": 0.25}}
        st["tenants"] = [{"tenant_id": 7, "name": "jZ",
                          "trace_id": "abc123", "sweeps_done": 40,
                          "niter": 100, "est_sweeps_to_target": 45.0}]
        return st

    p.status = status
    r = _router([p], obs_dir=str(tmp_path / "obs"),
                capacity_sample_s=0.02)
    try:
        deadline = time.monotonic() + 10.0
        while r.capacity_samples < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert r.capacity_samples >= 2
        ring = r.capacity_timeline()
        assert ring and len(ring) <= 512
        for s in ring:
            _valid(s, "capacity_sample", "ring sample")
        row = ring[-1]["pools"][0]
        assert row["watchdog_state"] == "ok" and row["healthy"]
        assert row["heartbeat_age_max_s"] == pytest.approx(0.25)
        ten = ring[-1]["tenants"][0]
        assert ten["trace_id"] == "abc123"
        assert ten["remaining_sweeps"] == 60
        assert ten["slack_sweeps"] == pytest.approx(15.0)
        lines = (tmp_path / "obs" / "capacity.jsonl").read_text()
        for line in lines.splitlines():
            _valid(json.loads(line), "capacity_sample",
                   "jsonl sample")
        text = r.metrics_text()
        assert text.count("# TYPE gst_fleet_placements counter") == 1
        assert 'gst_fleet_pool_queue_depth{pool="p0"}' in text
        assert 'gst_fleet_pool_healthy{pool="p0"} 1.0' in text
        assert "# TYPE gst_fleet_capacity_samples counter" in text
        pm = r.fleet_postmortem()
        _valid(pm, "fleet_postmortem", "fleet postmortem")
        assert pm["pools"][0]["pool"] == "p0"
    finally:
        r.close()
    # the sampler thread is joined by close()
    assert not any(t.name == "gst-fleet-capacity"
                   for t in __import__("threading").enumerate())


def test_fleet_merge_folds_watchdog_state():
    """A pool answering healthz 200 but with a TRIPPED watchdog must
    not render healthy: the fleet row folds the watchdog state and
    heartbeat ages, and ``render_fleet`` shows the trip + cause."""
    def st(state, cause=None, beat=0.1):
        s = {"schema": 1, "queue_depth": 0, "staged": 0,
             "free_groups": 2, "group": 16, "occupancy_now": 0.5,
             "nlanes": 64, "busy_lanes": 32, "faults": {},
             "slo": {"admission_ms": None},
             "slo_raw": {"admission_ms": []}, "tenants": [],
             "watchdog": {"state": state,
                          "trip": ({"cause": cause} if cause
                                   else None),
                          "heartbeat_age_s": {"dispatch": beat}}}
        return s

    snap = fleet_merge([("good", st("ok")),
                        ("stuck", st("tripped", cause="dispatch_stall",
                                     beat=42.0))])
    _valid(snap, "fleet_status", "fleet snapshot")
    rows = {p["source"]: p for p in snap["pools"]}
    assert rows["good"]["healthy"] is True
    assert rows["good"]["watchdog_state"] == "ok"
    assert rows["stuck"]["healthy"] is False
    assert rows["stuck"]["watchdog_state"] == "tripped"
    assert rows["stuck"]["watchdog_cause"] == "dispatch_stall"
    assert rows["stuck"]["heartbeat_age_max_s"] == pytest.approx(42.0)
    out = io.StringIO()
    render_fleet(snap, out)
    text = out.getvalue()
    assert "TRIP" in text and "wd:dispatch_stall" in text
    # the healthy pool renders ok, not tripped
    good_line = next(l for l in text.splitlines()
                     if l.strip().startswith("good"))
    assert "TRIP" not in good_line


# ---------------------------------------------------------------------------
# the perf_report trace-completeness gate
# ---------------------------------------------------------------------------

def _perf_report():
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "perf_report", os.path.join(repo, "tools", "perf_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fleet_rec(trace):
    return {"tool": "fleet_bench", "metrics": {
        "metric": "fleet_aggregate_chain_sweeps_per_s",
        "value": 100.0, "trace": trace}}


def test_perf_report_fleet_gate_watchdog_trip_vs_pool_failure(capsys):
    """The round-16 outright-fail leg means POOL FAILURES: a round-19
    record whose pool tripped its watchdog (timesharing collapse on a
    1-core bench host) notes the trip and passes; a counted pool
    failure still fails; legacy records keep the healthy proxy."""
    pr = _perf_report()

    def rec(pool):
        return [{"tool": "fleet_bench", "metrics": {
            "value": 100.0, "fleet_ratio": None,
            "pools_detail": [dict({"source": "pool0",
                                   "reachable": True}, **pool)]}}]

    tripped = rec({"healthy": False, "pool_failures": 0,
                   "watchdog_state": "tripped",
                   "watchdog_cause": "throughput_collapse"})
    assert pr.check_fleet(tripped, 3.5, 1e9) == 0
    assert pr.check_fleet(rec({"healthy": False,
                               "pool_failures": 1}), 3.5, 1e9) == 2
    # legacy record: no pool_failures key, healthy False IS the proxy
    assert pr.check_fleet(rec({"healthy": False}), 3.5, 1e9) == 2
    out = capsys.readouterr().out
    assert "watchdog" in out and "pool_failures counted" in out


def test_perf_report_fleet_trace_gate(capsys):
    pr = _perf_report()
    good = {"jobs": 4, "jobs_traced_end_to_end": 4,
            "schema_valid": True, "schema_errors": [],
            "placement_events": 5, "placements_total": 5,
            "capacity_samples": 7}
    assert pr.check_fleet_trace([_fleet_rec(good)]) == 0
    # records that predate the evidence skip, not fail
    assert pr.check_fleet_trace(
        [{"tool": "fleet_bench", "metrics": {}}]) == 0
    assert pr.check_fleet_trace([]) == 0
    # an untraced job fails
    assert pr.check_fleet_trace([_fleet_rec(
        dict(good, jobs_traced_end_to_end=3))]) == 2
    # schema drift fails
    assert pr.check_fleet_trace([_fleet_rec(
        dict(good, schema_valid=False,
             schema_errors=["$.x: boom"]))]) == 2
    # a placement without its journal event fails the reconciliation
    assert pr.check_fleet_trace([_fleet_rec(
        dict(good, placement_events=4))]) == 2
    # a dead sampler fails
    assert pr.check_fleet_trace([_fleet_rec(
        dict(good, capacity_samples=0))]) == 2
    # evidence collection errors fail loudly
    assert pr.check_fleet_trace(
        [_fleet_rec({"error": "RuntimeError: x"})]) == 2
    out = capsys.readouterr().out
    assert "fleet trace" in out and "FAIL" in out


# ---------------------------------------------------------------------------
# the slow arm: a real 2-pool subprocess fleet, stitched end to end
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_two_pool_subprocess_fleet_stitches_end_to_end(tmp_path):
    """The acceptance pin: drive a real 2-pool subprocess fleet,
    export ONE stitched Chrome trace, and require that it is
    schema-valid, that every completed job has >=1 router span and
    >=1 pool span sharing its trace id, that each pool contributed a
    clock block, and that the placement journal reconciles with the
    router's counters."""
    from tests.conftest import make_demo_pta
    from gibbs_student_t_tpu.config import GibbsConfig
    from gibbs_student_t_tpu.serve.router import (
        spawn_fleet,
        teardown_fleet,
    )

    pta = make_demo_pta()
    ma = pta.frozen(0)
    cfg = GibbsConfig(model="mixture")
    obs = str(tmp_path / "router_obs")
    fleet = spawn_fleet(str(tmp_path / "fleet"), 2, ma, cfg,
                        pool_kwargs=dict(nlanes=32, quantum=5),
                        placement="round_robin", obs_dir=obs,
                        capacity_sample_s=0.25)
    try:
        handles = [fleet.submit(TenantRequest(
            ma=ma, niter=10, nchains=16, seed=s, name=f"job{s}"))
            for s in range(4)]
        for h in handles:
            h.result(timeout=600)
        doc = fleet.export_trace(
            path=str(tmp_path / "fleet_trace.json"))
        _valid(doc, "fleet_trace", "stitched 2-pool trace")
        assert not (doc["otherData"].get("missing_pools"))
        clocks = doc["otherData"]["clocks"]
        assert set(clocks) == {"pool0", "pool1"}
        for c in clocks.values():
            # in-flight RPC sampling really happened (subprocess
            # pools answer the time op; offsets are sub-second on
            # one host)
            assert c["n"] >= 1 and abs(c["offset_s"]) < 1.0
        cov = trace_coverage(doc)
        for h in handles:
            tid = h.request.trace_id
            assert cov[tid]["router"] >= 1, tid
            assert cov[tid]["pool"] >= 1, tid
        # both pools contributed swimlanes
        pool_pids = {ev["pid"] // POOL_PID_STRIDE
                     for ev in doc["traceEvents"]
                     if ev["pid"] >= POOL_PID_STRIDE}
        assert pool_pids == {1, 2}
        # the journal reconciles 1:1 with the router counters
        snap = fleet.fleet_status()
        assert snap["router"]["placement_events"] == \
            sum(snap["router"]["placements"].values()) == 4
        events = [json.loads(l) for l in open(
            os.path.join(obs, "placements.jsonl"))]
        assert len(events) == 4
        assert {e["trace_id"] for e in events} == \
            {h.request.trace_id for h in handles}
        assert fleet.capacity_samples >= 1
        for s in fleet.capacity_timeline():
            _valid(s, "capacity_sample", "live capacity sample")
    finally:
        teardown_fleet(fleet, remove_dirs=True)
