"""Native library (C++ tim tokenizer + chain spooler) vs. Python paths."""

import numpy as np
import pytest

from gibbs_student_t_tpu import native
from gibbs_student_t_tpu.data.tim import _read_tim_python, read_tim

from tests.conftest import make_demo_pulsar


@pytest.fixture(scope="module", autouse=True)
def built():
    import shutil

    if not (shutil.which("make") and shutil.which("g++")):
        pytest.skip("native toolchain unavailable (no make/g++)")
    # toolchain present: a build failure is a real failure, not a skip
    native.load(build=True)
    assert native.available(), "native build failed"


TIM_TEXT = """\
FORMAT 1
MODE 1
fake 1440.00000000 53012.00012345678901 0.04000000 AXIS -f L-wide -be ASP
fake 1440.00000000 53026.10012345678902 0.05000000 AXIS -be GUPPI
C fake 1440.00000000 53040.20012345678903 0.06000000 AXIS -f L-wide
# a freeform comment line that is not a TOA
fake 430.00000000 53054.30012345678904 0.07000000 ao
"""


def _write(tmp_path, text):
    p = tmp_path / "test.tim"
    p.write_text(text)
    return str(p)


@pytest.mark.parametrize("include_deleted", [False, True])
def test_native_matches_python(tmp_path, include_deleted):
    path = _write(tmp_path, TIM_TEXT)
    ref = _read_tim_python(path, include_deleted)
    nat = native.read_tim_native(path, include_deleted)
    assert nat.names == ref.names
    assert nat.sites == ref.sites
    np.testing.assert_array_equal(nat.freqs, ref.freqs)
    np.testing.assert_array_equal(nat.errors, ref.errors)
    np.testing.assert_array_equal(nat.deleted, ref.deleted)
    assert sorted(nat.flags) == sorted(ref.flags)
    for k in ref.flags:
        assert list(nat.flags[k]) == list(ref.flags[k])
    # day+frac split loses <0.1 ns; compare at 1e-15 days (~0.1 ns)
    np.testing.assert_allclose(
        np.asarray(nat.mjds, dtype=np.float64),
        np.asarray(ref.mjds, dtype=np.float64), rtol=0, atol=1e-15)
    assert float(np.max(np.abs(nat.mjds - ref.mjds))) < 2e-15


def test_read_tim_auto_prefers_native(tmp_path):
    path = _write(tmp_path, TIM_TEXT)
    tim = read_tim(path, engine="auto")
    assert tim.n == 3


def test_native_roundtrip_demo_pulsar(tmp_path):
    """Full simulator round trip through the native parser."""
    psr_py, _ = make_demo_pulsar(tmpdir=str(tmp_path), seed=7, n=40)
    timfile = [str(p) for p in tmp_path.rglob("*.tim")][0]
    nat = native.read_tim_native(timfile)
    ref = _read_tim_python(timfile)
    assert nat.n == ref.n
    assert float(np.max(np.abs(nat.mjds - ref.mjds))) < 2e-15


def test_native_include_raises(tmp_path):
    path = _write(tmp_path, "FORMAT 1\nINCLUDE other.tim\n")
    with pytest.raises(NotImplementedError):
        native.read_tim_native(path)


def test_spool_roundtrip(tmp_path):
    path = str(tmp_path / "x.spool")
    rng = np.random.default_rng(0)
    a = rng.standard_normal((5, 3, 2)).astype(np.float32)
    b = rng.standard_normal((2, 3, 2)).astype(np.float32)
    with native.SpoolWriter(path, trailing_shape=(3, 2)) as w:
        w.append(a)
        w.append(b)
    out = native.read_spool(path)
    np.testing.assert_array_equal(out, np.concatenate([a, b]))


def test_spool_scalar_rows_float64(tmp_path):
    path = str(tmp_path / "s.spool")
    vals = np.arange(7, dtype=np.float64)
    with native.SpoolWriter(path, trailing_shape=(), dtype=np.float64) as w:
        w.append(vals)
    np.testing.assert_array_equal(native.read_spool(path), vals)


def test_spool_interrupted_prefix_readable(tmp_path):
    """A dead writer (no close) must leave a readable file — the crash
    resume story."""
    path = str(tmp_path / "p.spool")
    w = native.SpoolWriter(path, trailing_shape=(4,))
    data = np.ones((10, 4), dtype=np.float32)
    w.append(data)
    w.flush()
    # no close: simulates a killed process
    out = native.read_spool(path)
    np.testing.assert_array_equal(out, data)
    w.close()


def test_spool_append_resume_keeps_history(tmp_path):
    path = str(tmp_path / "r.spool")
    a = np.full((3, 2), 1.0, dtype=np.float32)
    b = np.full((2, 2), 2.0, dtype=np.float32)
    with native.SpoolWriter(path, trailing_shape=(2,)) as w:
        w.append(a)
    with native.SpoolWriter(path, trailing_shape=(2,), append=True) as w:
        w.append(b)
    np.testing.assert_array_equal(native.read_spool(path),
                                  np.concatenate([a, b]))
    # header mismatch on resume is refused, not silently corrupted
    with pytest.raises(OSError, match="mismatch"):
        native.SpoolWriter(path, trailing_shape=(3,), append=True)


def test_spool_append_truncates_orphaned_rows(tmp_path):
    """keep_rows discards rows past the checkpoint — including a torn
    partial row — so a crash mid-append cannot shift later sweeps."""
    path = str(tmp_path / "t.spool")
    a = np.arange(10, dtype=np.float32).reshape(5, 2)
    with native.SpoolWriter(path, trailing_shape=(2,)) as w:
        w.append(a)
    # simulate a torn write: 3 checkpointed rows + 2 orphans + half a row
    with open(path, "ab") as fh:
        fh.write(b"\x00\x00\x00\x00")
    with native.SpoolWriter(path, trailing_shape=(2,), append=True,
                            keep_rows=3) as w:
        w.append(np.full((1, 2), 9.0, dtype=np.float32))
    out = native.read_spool(path)
    np.testing.assert_array_equal(
        out, np.concatenate([a[:3], np.full((1, 2), 9.0, np.float32)]))
    # a checkpoint claiming more rows than the file holds is refused
    with pytest.raises(OSError, match="fewer rows"):
        native.SpoolWriter(path, trailing_shape=(2,), append=True,
                           keep_rows=99)


@pytest.mark.slow  # round-18 re-tier (~17 s: spool append; thin-resume keeps the spool contract tier-1)
def test_jax_sample_spool_resume_appends(tmp_path, demo_ma):
    """Kill/resume flow: run 6 sweeps, 'crash', resume 4 more from the
    checkpoint — the spool must contain all 10 and match an unbroken run."""
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.config import GibbsConfig
    from gibbs_student_t_tpu.utils.spool import load_spool, load_spool_state

    cfg = GibbsConfig(model="mixture", vary_df=True)
    gb = JaxGibbs(demo_ma, cfg, nchains=2, chunk_size=3)
    ref = gb.sample(niter=10, seed=5)
    d = str(tmp_path / "spool")
    gb.sample(niter=6, seed=5, spool_dir=d)
    state, sweep, seed = load_spool_state(d)
    assert sweep == 6
    import jax

    state = jax.tree.map(jnp_asarray, state)
    gb.sample(niter=4, seed=seed, state=state, start_sweep=sweep,
              spool_dir=d)
    out = load_spool(d)
    assert out.chain.shape[0] == 10
    np.testing.assert_allclose(out.chain, ref.chain, rtol=1e-5, atol=1e-6)


def jnp_asarray(x):
    import jax.numpy as jnp

    return jnp.asarray(x)


@pytest.mark.slow  # round-18 re-tier (~17 s: spool dedup under sample_until)
def test_sample_until_spool_no_duplication(tmp_path, demo_ma):
    """sample_until with a spool: each segment's sample() reloads the
    FULL spool, so the implementation must keep only the latest result —
    the final chain has exactly done-sweeps rows, no duplicated prefix,
    and matches a plain run of the same length."""
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.config import GibbsConfig

    cfg = GibbsConfig(model="gaussian", vary_df=False)
    gb = JaxGibbs(demo_ma, cfg, nchains=4, chunk_size=25)
    res = gb.sample_until(rhat_target=1.5, max_sweeps=150, check_every=50,
                          seed=7, spool_dir=str(tmp_path / "spool"))
    total = res.chain.shape[0]
    assert total in (100, 150)  # first possible stop is 2 checks
    plain = JaxGibbs(demo_ma, cfg, nchains=4, chunk_size=25).sample(
        niter=total, seed=7)
    np.testing.assert_allclose(res.chain, plain.chain, rtol=1e-6,
                               atol=1e-7)
    assert res.stats["rhat"].shape == (res.chain.shape[-1],)


def test_jax_sample_spool_thin_resume(tmp_path, demo_ma):
    """Spooled runs with record_thin keep sweep-indexed bookkeeping
    (meta base / checkpoint sweeps) while spool rows are recorded rows;
    kill/resume still reproduces the unbroken thinned run exactly."""
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.config import GibbsConfig
    from gibbs_student_t_tpu.utils.spool import load_spool, load_spool_state

    cfg = GibbsConfig(model="mixture", vary_df=True)
    gb = JaxGibbs(demo_ma, cfg, nchains=2, chunk_size=4, record_thin=2)
    ref = gb.sample(niter=12, seed=5)
    d = str(tmp_path / "spool")
    gb.sample(niter=8, seed=5, spool_dir=d)
    state, sweep, seed = load_spool_state(d)
    assert sweep == 8  # checkpoint is in SWEEPS
    import jax

    state = jax.tree.map(jnp_asarray, state)
    gb.sample(niter=4, seed=seed, state=state, start_sweep=sweep,
              spool_dir=d)
    out = load_spool(d)
    assert out.chain.shape[0] == 6  # rows are RECORDED sweeps (12 / 2)
    np.testing.assert_allclose(out.chain, ref.chain, rtol=1e-5, atol=1e-6)
    assert int(out.stats["record_thin"]) == 2


@pytest.mark.slow  # round-18 re-tier (~15 s: spooled-vs-inmemory parity; thin-rows parity stays tier-1)
def test_jax_sample_spooled_matches_inmemory(tmp_path, demo_ma):
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.config import GibbsConfig
    from gibbs_student_t_tpu.utils.spool import load_spool_state

    cfg = GibbsConfig(model="mixture", vary_df=True)
    gb = JaxGibbs(demo_ma, cfg, nchains=3, chunk_size=4)
    res_mem = gb.sample(niter=10, seed=11)
    spool_dir = str(tmp_path / "spool")
    res_sp = gb.sample(niter=10, seed=11, spool_dir=spool_dir)
    np.testing.assert_allclose(res_sp.chain, res_mem.chain, rtol=1e-6)
    np.testing.assert_allclose(res_sp.thetachain, res_mem.thetachain,
                               rtol=1e-6)
    np.testing.assert_allclose(res_sp.stats["acc_hyper"],
                               res_mem.stats["acc_hyper"], rtol=1e-6)
    state, sweep, seed = load_spool_state(spool_dir)
    assert sweep == 10 and seed == 11
    np.testing.assert_allclose(np.asarray(state.x),
                               np.asarray(gb.last_state.x), rtol=1e-6)
