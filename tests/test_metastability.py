"""vvh17 metastable-trap ESCAPE pinning (VERDICT r4 weak #5 / next #7).

The reference initializes z = 1 everywhere (reference gibbs.py:50-51).
Under vvh17's fixed alpha=1e10 that start is METASTABLE on
outlier-contaminated data: every TOA's variance is inflated by alpha,
the coefficient draw is prior-dominated, p_in underflows and the z-draw
posterior q -> 1 keeps z pinned (the full analysis lives on
``GibbsConfig.z_init``).  The distributional gates deliberately start
both backends in the dominant mode (``z_init='zeros'``) — which means a
kernel change that DEEPENED the trap (e.g. a likelihood underflow that
never recovers, or an f32 path that kills the red-noise-amplitude
excursions that trigger the unflagging cascade) would pass every gate.

These tests run the reference initialization itself and assert the
escape happens inside a seed-bracketed sweep budget, on both backends:

- measured escape sweeps (J1713 dataset, bench.build(130, 30)):
  NumPy oracle ~1700 (seed 3, not within 8000 for seed 11); the f32 JAX
  kernel escapes at sweeps ~70-150 per chain.  Budgets below carry
  >= 2x margin over those measurements.

Escape is witnessed, not assumed: the trap must actually hold early
(z_frac == 1 over the first sweeps) before the all-inlier mode is
reached, so the assertions fail loudly if the dynamics change in either
direction in a way that invalidates the z_init='zeros' gate rationale.
"""

import os
import sys

import numpy as np
import pytest

from gibbs_student_t_tpu.backends import JaxGibbs, NumpyGibbs

REF_PAR = "/root/reference/J1713+0747.par"
REF_TIM = "/root/reference/J1713+0747.tim"

pytestmark = pytest.mark.skipif(
    not (os.path.exists(REF_PAR) and os.path.exists(REF_TIM)),
    reason="reference J1713+0747 files not present")


@pytest.fixture(scope="module")
def ma():
    """The benchmark J1713 workload (reference epochs + par, simulated
    red noise + 10% outliers) — the same dataset the gates run on."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        import bench
    finally:
        sys.path.remove(root)
    return bench.build(130, 30)


@pytest.fixture(scope="module")
def cfg():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        from run_sims import model_configs
    finally:
        sys.path.remove(root)
    cfg = model_configs()["vvh17"]
    assert cfg.z_init_ones  # the reference initialization under test
    return cfg


@pytest.mark.slow
def test_oracle_escapes_reference_z_init(ma, cfg):
    """NumPy oracle, z=1 start: trapped early, escaped and settled in
    the dominant all-inlier mode within the bracketed budget (measured
    escape ~1700 sweeps at this seed; budget 4000 = 2.3x margin)."""
    niter = 4000
    rng = np.random.default_rng(3)
    res = NumpyGibbs(ma, cfg).sample(ma.x_init(rng), niter, seed=3)
    zfrac = np.asarray(res.zchain, np.float64).mean(axis=1)  # (niter,)

    # the trap is real: the reference init pins z == 1 at the start
    assert zfrac[:10].min() > 0.95, (
        "vvh17 z=1 start no longer traps — the z_init='zeros' gate "
        f"rationale needs re-examination (early z_frac {zfrac[:10]})")
    escape = int(np.argmax(zfrac < 0.5))
    assert zfrac.min() < 0.5 and escape < niter, (
        f"oracle never escaped the all-outlier mode in {niter} sweeps "
        "(measured escape ~1700 at seed 3): the metastable trap has "
        "deepened")
    # settled: after escape the dominant mode holds (z_frac near the
    # true ~10% contamination, nowhere near the trap)
    tail = zfrac[max(escape, 3 * niter // 4):]
    assert tail.mean() < 0.3, (
        f"oracle escaped at sweep {escape} but did not settle "
        f"(tail z_frac {tail.mean():.3f})")


@pytest.mark.slow
def test_jax_kernel_escapes_reference_z_init(ma, cfg):
    """f32 JAX kernel, z=1 start, 16 chains: nearly all chains escape
    well inside the budget (measured per-chain escape ~70-150 sweeps;
    budget 800 = >5x margin).  A numerics change that deepened the trap
    (underflow in the z posterior, dead amplitude excursions) shows up
    here as chains still pinned at z == 1."""
    nchains, niter = 16, 800
    gb = JaxGibbs(ma, cfg, nchains=nchains, chunk_size=100,
                  record="compact")
    res = gb.sample(niter=niter, seed=7)
    # (niter, nchains, n) -> per-chain outlier fraction per sweep
    zfrac = np.asarray(res.zchain, np.float64).mean(axis=-1)

    assert zfrac[0].min() > 0.95, (
        f"z=1 start did not trap the kernel (sweep-0 z_frac {zfrac[0]})")
    final = zfrac[-50:].mean(axis=0)  # (nchains,)
    n_escaped = int((final < 0.5).sum())
    assert n_escaped >= int(0.75 * nchains), (
        f"only {n_escaped}/{nchains} chains escaped the all-outlier "
        f"trap within {niter} sweeps (measured escape ~70-150): the "
        "metastable trap has deepened under the f32 kernel")
    # settled chains sit in the same dominant mode the gates compare
    assert final[final < 0.5].mean() < 0.3
