"""Test harness configuration.

Tests run on a virtual 8-device CPU backend
(``--xla_force_host_platform_device_count=8``) — the standard fake-cluster
trick for exercising ``vmap``/``shard_map``/collective code without TPU
hardware (SURVEY.md §4). Env vars must be set before JAX initializes, which
is why this happens at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

# The container's sitecustomize imports jax at interpreter start (before
# this conftest runs) with JAX_PLATFORMS=axon, so the env mutation above is
# too late for jax's import-time config capture — force the platform through
# the live config as well.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from gibbs_student_t_tpu.data.demo import (
    make_contaminated_pulsar,
    make_reference_pta,
)
from gibbs_student_t_tpu.models import PTA


def make_demo_pulsar(tmpdir=None, seed=42, n=130, theta=0.0,
                     sigma_out=1e-6):
    """Simulated pulsar with injected red + white noise (and optional
    outliers), round-tripped through par/tim files when ``tmpdir`` given."""
    return make_contaminated_pulsar(n=n, components=30, theta=theta,
                                    sigma_out=sigma_out, seed=seed,
                                    roundtrip_dir=tmpdir)


def make_demo_pta(psr=None, components=30, seed=42) -> PTA:
    """The reference's simulated-data model (reference run_sims.py:57-76)."""
    if psr is None:
        psr, _ = make_demo_pulsar(seed=seed)
    return make_reference_pta(psr, components)


@pytest.fixture(scope="session")
def demo_pulsar():
    return make_demo_pulsar()[0]


@pytest.fixture(scope="session")
def demo_pta(demo_pulsar):
    return make_demo_pta(demo_pulsar)


@pytest.fixture(scope="session")
def demo_ma(demo_pta):
    return demo_pta.frozen()
