"""Pallas fused TNT kernel vs. the XLA reduction (interpret mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from gibbs_student_t_tpu.ops.pallas_tnt import (
    tnt_batched,
    tnt_batched_pallas,
    tnt_batched_xla,
)


def _problem(C=5, n=512, m=7, seed=0):
    rng = np.random.default_rng(seed)
    T = jnp.asarray(rng.standard_normal((n, m)), dtype=jnp.float32)
    y = jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)
    nvec = jnp.asarray(10.0 ** rng.uniform(-1.5, 1.5, (C, n)),
                       dtype=jnp.float32)
    return T, y, nvec


@pytest.mark.parametrize("C,chain_tile", [(5, 2), (4, 4), (1, 1), (6, 32)])
def test_pallas_matches_xla(C, chain_tile):
    T, y, nvec = _problem(C=C)
    TNT_p, d_p, c_p = tnt_batched_pallas(T, y, nvec, block_size=128,
                                         chain_tile=chain_tile,
                                         interpret=True)
    TNT_x, d_x, c_x = tnt_batched_xla(T, y, nvec)
    np.testing.assert_allclose(TNT_p, TNT_x, rtol=2e-4, atol=1e-4)
    np.testing.assert_allclose(d_p, d_x, rtol=2e-4, atol=1e-4)
    np.testing.assert_allclose(c_p, c_x, rtol=2e-4, atol=1e-4)


def test_pallas_padded_rows_are_inert():
    """The pad_rows contract (zero rows, nvec=1) holds for the kernel."""
    from gibbs_student_t_tpu.ops.tnt import pad_rows

    T, y, nvec = _problem(C=3, n=500)
    ref = tnt_batched_xla(T, y, nvec)
    T_p, y_p, n_pad = pad_rows(np.asarray(T), np.asarray(y), 128)
    nvec_p = jnp.concatenate(
        [nvec, jnp.ones((3, n_pad), nvec.dtype)], axis=1)
    out = tnt_batched_pallas(jnp.asarray(T_p), jnp.asarray(y_p), nvec_p,
                             block_size=128, chain_tile=2, interpret=True)
    for a, b in zip(out, ref):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-4)


def test_pallas_rejects_ragged_n():
    T, y, nvec = _problem(n=500)
    with pytest.raises(ValueError, match="multiple"):
        tnt_batched_pallas(T, y, nvec, block_size=128)


def test_dispatch_prefers_xla_off_tpu():
    T, y, nvec = _problem()
    out = tnt_batched(T, y, nvec, block_size=None)  # cpu -> xla path
    ref = tnt_batched_xla(T, y, nvec)
    for a, b in zip(out, ref):
        np.testing.assert_allclose(a, b, rtol=1e-5)


def test_backend_auto_never_picks_pallas_tnt():
    """``use_pallas="auto"`` must resolve to the XLA scan even where the
    blocked path is active: the on-chip A/B measured the scan faster in
    that whole regime, and at the 1e5-TOA stress shape the kernel
    VMEM-OOMed on hardware (artifacts/BENCH_STRESS_r03.err) — auto
    selecting it turned the stress bench into a CPU fallback."""
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.config import GibbsConfig
    from tests.conftest import make_demo_pta, make_demo_pulsar

    psr, _ = make_demo_pulsar(seed=11, n=40, theta=0.1)
    ma = make_demo_pta(psr, components=5).frozen()
    gb = JaxGibbs(ma, GibbsConfig(model="mixture"), nchains=2,
                  tnt_block_size=32)  # blocked path active, auto pallas
    assert gb._use_pallas is False


def test_auto_chain_tile_respects_vmem_budget():
    """The default chain tile shrinks with block_size so the unrolled
    per-chain weighted-basis temporaries stay inside the ~6 MB budget
    (32 chains x (4096, 128) f32 temporaries blew the 16 MB scoped-VMEM
    stack on hardware)."""
    from gibbs_student_t_tpu.ops.pallas_tnt import _auto_chain_tile

    # block 4096, mp 128 -> per-chain temp 2 MB -> tile capped at 3
    assert _auto_chain_tile(4096, 128, C=64) == 3
    # the stress shape that OOMed: must now fit well under 16 MB
    assert _auto_chain_tile(4096, 128, C=64) * 4096 * 128 * 4 <= 6 << 20
    # small blocks keep the old wide tile; tiny batches never exceed C
    assert _auto_chain_tile(256, 128, C=64) == 32
    assert _auto_chain_tile(256, 128, C=5) == 5
    # pathological: never below one chain
    assert _auto_chain_tile(65536, 256, C=8) == 1

    # and a capped-tile run still computes the right answer
    T, y, nvec = _problem(C=6, n=512, m=7)
    out = tnt_batched_pallas(
        jnp.tile(T, (8, 1)), jnp.tile(y, 8), jnp.tile(nvec, (1, 8)),
        block_size=4096, interpret=True)
    assert out[0].shape == (6, 7, 7)
    ref = tnt_batched_xla(jnp.tile(T, (8, 1)), jnp.tile(y, 8),
                          jnp.tile(nvec, (1, 8)))
    np.testing.assert_allclose(out[0], ref[0], rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_backend_pallas_sweep_matches_vmap_path():
    """The batched-sweep chunk driver (Pallas TNT between vmapped stages)
    must reproduce the per-chain vmap path — same keys, same math."""
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.config import GibbsConfig
    from tests.conftest import make_demo_pta, make_demo_pulsar

    psr, _ = make_demo_pulsar(seed=11, n=40, theta=0.1)
    ma = make_demo_pta(psr, components=5).frozen()
    cfg = GibbsConfig(model="mixture", vary_df=True)
    ref = JaxGibbs(ma, cfg, nchains=3, tnt_block_size=32,
                   use_pallas=False)
    pal = JaxGibbs(ma, cfg, nchains=3, tnt_block_size=32,
                   use_pallas=True, pallas_interpret=True)
    r_ref = ref.sample(niter=6, seed=2)
    r_pal = pal.sample(niter=6, seed=2)
    np.testing.assert_allclose(r_pal.chain, r_ref.chain, rtol=2e-3,
                               atol=2e-3)
    np.testing.assert_allclose(r_pal.zchain, r_ref.zchain)
    np.testing.assert_allclose(r_pal.dfchain, r_ref.dfchain)


@pytest.mark.slow
def test_backend_pallas_sweep_record_thin_rows_match():
    """record_thin on the batched (Pallas TNT) chunk driver: thinned
    rows must be bit-identical to every t-th row of the unthinned
    batched run — the stress path is exactly where thinning is used,
    so its keying cannot go untested (chunk_batched's inner loop)."""
    from gibbs_student_t_tpu.backends import JaxGibbs
    from gibbs_student_t_tpu.config import GibbsConfig
    from tests.conftest import make_demo_pta, make_demo_pulsar

    psr, _ = make_demo_pulsar(seed=11, n=40, theta=0.1)
    ma = make_demo_pta(psr, components=5).frozen()
    cfg = GibbsConfig(model="mixture", vary_df=True)
    full = JaxGibbs(ma, cfg, nchains=3, tnt_block_size=32,
                    use_pallas=True, pallas_interpret=True,
                    chunk_size=6).sample(niter=6, seed=2)
    thin = JaxGibbs(ma, cfg, nchains=3, tnt_block_size=32,
                    use_pallas=True, pallas_interpret=True,
                    chunk_size=6, record_thin=3).sample(niter=6, seed=2)
    assert thin.chain.shape[0] == 2
    np.testing.assert_array_equal(thin.chain, full.chain[::3])
    np.testing.assert_array_equal(thin.zchain, full.zchain[::3])
    np.testing.assert_array_equal(thin.dfchain, full.dfchain[::3])
