"""Live serving observability plane tests (round 13): per-tenant span
tracing, the streaming convergence monitor, the SLO/status surface,
and the schema-drift guard.

The acceptance pins (ISSUE 10 / docs/OBSERVABILITY.md "Live serving
observability"):

- ``TenantHandle.progress()`` ESS / split-R-hat match the post-hoc
  ``parallel/diagnostics.py`` values on the same rows to 1e-6;
- ``ChainServer.export_trace()`` validates as Chrome trace-event JSON
  and shows >= one span per (tenant, quantum, thread-role) for a
  4-tenant run;
- chains are bitwise identical with the plane on vs off;
- every observability failure path (span sink IO error, monitor
  exception, obs_dir refresh failure) degrades warn-and-continue —
  the tenant and the pool never fail.

Round 14 (the observability wire) rides the SAME shared run: the
plane fixture also mounts ``http_port=0`` and fetches every endpoint
mid-run and post-drain, so the HTTP/cost/fleet tests add zero extra
pool compiles — and the existing plane-off bitwise arm now doubles as
the HTTP-server-on-vs-off bitwise pin.

Budget note: the module runs THREE pool compiles total — one shared
4-tenant plane run (module fixture, reused by the span/progress/
status/schema/http/cost/fleet tests), one plane-off server (the
bitwise A/B), one failure-path server.
"""

import glob
import json
import os
import threading

import numpy as np
import pytest

from tests.conftest import make_demo_pta
from gibbs_student_t_tpu.config import GibbsConfig
from gibbs_student_t_tpu.obs import schema as obs_schema
from gibbs_student_t_tpu.serve import (
    ChainServer,
    MonitorSpec,
    TenantRequest,
)

pytestmark = pytest.mark.obsplane

MON_PARAMS = [0, 1, 2]
NITERS = (15, 10, 15, 10)


@pytest.fixture(scope="module")
def demo():
    pta = make_demo_pta()
    return pta.frozen(0), GibbsConfig(model="mixture")


@pytest.fixture(scope="module")
def schemas():
    return obs_schema.load_schemas()


def _http_get(url, timeout=10.0):
    """(status_code, body_text) — 4xx/5xx are data here, not raises."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture(scope="module")
def plane_run(demo, tmp_path_factory):
    """ONE 4-tenant run with the full plane armed (spans + JSONL sink,
    monitor, obs_dir, metrics run_dir, crash manifest, round-14 HTTP
    wire on an ephemeral port) — shared by the span/progress/status/
    schema/http/cost tests so tier-1 pays a single pool compile for
    all of them. Endpoints are fetched MID-RUN (first boundary with
    busy lanes, on the driving thread's on_quantum hook) and again
    after the drain-down, so both a live and an idle server are
    covered."""
    from gibbs_student_t_tpu.obs import MetricsRegistry

    ma, cfg = demo
    root = tmp_path_factory.mktemp("plane")
    obs_dir = str(root / "obs")
    run_dir = str(root / "run")
    man_dir = str(root / "manifest")
    reg = MetricsRegistry(run_dir=run_dir)
    reg.write_manifest(config=cfg, seeds=list(range(len(NITERS))))
    srv = ChainServer(ma, cfg, nlanes=32, quantum=5, record="full",
                      metrics=reg, obs_dir=obs_dir,
                      manifest_dir=man_dir,
                      trace_jsonl=os.path.join(obs_dir, "spans.jsonl"),
                      http_port=0)
    url = srv.http.url
    spec = MonitorSpec(params=MON_PARAMS, ess_target=4.0,
                       rhat_target=50.0)
    hs = [srv.submit(TenantRequest(ma=ma, niter=n, nchains=16, seed=i,
                                   name=f"t{i}", monitor=spec))
          for i, n in enumerate(NITERS)]
    live = {}

    def fetch_live(server):
        if live or not server.quanta:
            return
        for route in ("/healthz", "/status", "/metrics", "/trace",
                      "/postmortem",
                      "/tenants/0/progress", "/tenants/t1/progress",
                      "/tenants/nope/progress", "/nope"):
            live[route] = _http_get(url + route)

    srv.run(on_quantum=fetch_live)
    idle = {route: _http_get(url + route)
            for route in ("/healthz", "/status", "/metrics", "/trace",
                          "/postmortem")}
    trace_path = srv.export_trace(os.path.join(obs_dir, "trace.json"))
    status = srv.status()
    summary = srv.summary()
    pm_path = srv.dump_postmortem(reason="fixture")
    srv.close()
    reg.close()
    results = [h.result() for h in hs]
    return {"server": srv, "handles": hs, "results": results,
            "obs_dir": obs_dir, "run_dir": run_dir, "man_dir": man_dir,
            "trace_path": trace_path, "status": status,
            "summary": summary, "url": url, "live": live, "idle": idle,
            "pm_path": pm_path}


# ----------------------------------------------------------------------
# streaming convergence monitor
# ----------------------------------------------------------------------


def test_progress_matches_posthoc_diagnostics(plane_run):
    """The acceptance pin: the streaming monitor's final ESS and
    split-R-hat equal the post-hoc ``parallel/diagnostics`` values on
    the same rows to 1e-6 — the monitor feeds on wire slices, the
    post-hoc path on the materialized ChainResult, and the two must be
    the same numbers."""
    from gibbs_student_t_tpu.parallel.diagnostics import (
        ess_per_param,
        split_rhat_per_param,
    )

    for h, res, niter in zip(plane_run["handles"], plane_run["results"],
                             NITERS):
        p = h.progress()
        assert p["status"] == "done" and p["rows"] == niter
        window = np.asarray(res.chain)[:, :, MON_PARAMS]
        ess_ref = ess_per_param(window)
        rhat_ref = split_rhat_per_param(window)
        assert abs(p["ess_min"] - ess_ref.min()) <= 1e-6 * ess_ref.min()
        np.testing.assert_allclose(np.asarray(p["ess"], float), ess_ref,
                                   rtol=1e-6)
        fin = rhat_ref[np.isfinite(rhat_ref)]
        assert abs(p["rhat_max"] - fin.max()) <= 1e-6 * fin.max()
        assert p["ess_per_s"] is not None and p["ess_per_s"] > 0
        # loose targets: every tenant converged in-flight, and the
        # verdict rides the result stats too
        assert p["converged_at"] is not None
        assert res.stats["converged_at"] == p["converged_at"]
        assert res.stats["monitor"]["ess_min"] == p["ess_min"]
        assert h.converged_at == p["converged_at"]


def test_monitor_spec_validation(demo):
    ma, cfg = demo
    with pytest.raises(ValueError, match="every"):
        MonitorSpec(every=0)
    from gibbs_student_t_tpu.serve.monitor import resolve_params

    with pytest.raises(ValueError, match="not in"):
        resolve_params(MonitorSpec(params=["nope"]), ["a", "b"])
    with pytest.raises(ValueError, match="out of range"):
        resolve_params(MonitorSpec(params=[7]), ["a", "b"])
    assert list(resolve_params(MonitorSpec(params=["b", 0]),
                               ["a", "b"])) == [1, 0]
    assert list(resolve_params(MonitorSpec(), ["a", "b"])) == [0, 1]


# ----------------------------------------------------------------------
# span tracing
# ----------------------------------------------------------------------


def test_export_trace_is_valid_and_complete(plane_run, schemas):
    """Chrome trace-event validity (schema-pinned) plus the coverage
    pin: >= one span per (tenant, quantum, thread-role) for the
    4-tenant run, for both per-quantum roles (dispatch + drain), and
    at least one staging span per tenant."""
    with open(plane_run["trace_path"]) as fh:
        doc = json.load(fh)
    obs_schema.assert_valid(doc, schemas["chrome_trace"],
                            "chrome trace", defs=schemas)
    ev = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert ev, "no complete events in the trace"
    # pid 0 is the pool; tenants are pid = tenant_id + 1
    per_tenant_q = {}
    staged = set()
    for e in ev:
        if e["pid"] == 0:
            continue
        tid = e["pid"] - 1
        if e["cat"] == "staging":
            staged.add(tid)
        q = e["args"].get("quantum")
        if q is not None:
            per_tenant_q.setdefault((tid, q), set()).add(e["cat"])
    assert staged == {0, 1, 2, 3}
    # every tenant advanced niter/quantum quanta; each (tenant,
    # quantum) shows BOTH the dispatch-role and drain-role span
    expected = {t for t in range(4)}
    seen_tenants = {t for (t, _) in per_tenant_q}
    assert seen_tenants == expected
    for (t, q), roles in per_tenant_q.items():
        if "dispatch" in roles:
            assert "drain" in roles, (t, q, roles)
    n_quanta = {t: sum(1 for (tt, _) in per_tenant_q if tt == t)
                for t in range(4)}
    for t, niter in enumerate(NITERS):
        assert n_quanta[t] >= niter // 5, (t, n_quanta)
    # process_name metadata names the tenants for the swimlane view
    names = {e["pid"]: e["args"]["name"]
             for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names[0] == "pool" and names[1] == "tenant t0"


def test_span_recorder_ring_and_sink(tmp_path, schemas):
    """Unit (undersized ring): drops are drop-oldest and ACCOUNTED —
    the dropped counter counts them, a serve_spans_dropped metrics
    counter mirrors them, the first drop warns exactly once, and the
    Chrome export carries the total in otherData. The JSONL sink lines
    validate against the span schema, and a sink that starts failing
    disables itself with a warning while recording continues in
    memory."""
    import warnings as _warnings

    from gibbs_student_t_tpu.obs import MetricsRegistry
    from gibbs_student_t_tpu.obs.spans import SpanRecorder

    path = str(tmp_path / "spans.jsonl")
    reg = MetricsRegistry()
    rec = SpanRecorder(capacity=8, jsonl_path=path, metrics=reg)
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        for i in range(12):
            with rec.span("step", "drain", tenant=i % 2, quantum=i):
                pass
    overflow = [w for w in caught if "overflowed" in str(w.message)]
    assert len(overflow) == 1   # warn once, not once per drop
    assert len(rec.spans()) == 8
    assert rec.dropped == 4
    assert reg.counter("serve_spans_dropped").value == 4
    doc = rec.chrome_trace_doc()
    assert doc["otherData"]["dropped_spans"] == 4
    lines = [json.loads(x) for x in open(path)]
    assert len(lines) == 12
    for ln in lines:
        obs_schema.assert_valid(ln, schemas["span"], "span line",
                                defs=schemas)
    # break the sink: one RuntimeWarning, then memory-only recording
    rec._sink.close()
    with pytest.warns(RuntimeWarning, match="sink"):
        rec.record("after", "drain", 0.0, 0.1)
    rec.record("after2", "drain", 0.0, 0.1)  # quiet, still ringed
    assert [s["name"] for s in rec.spans()][-2:] == ["after", "after2"]
    rec.close()


# ----------------------------------------------------------------------
# SLO / status / exposition surface
# ----------------------------------------------------------------------


def test_status_slo_and_exposition(plane_run, schemas):
    st = plane_run["status"]
    obs_schema.assert_valid(st, schemas["serve_status"],
                            "ChainServer.status()", defs=schemas)
    # the obs_dir pull surface carries the same (schema-valid) shape
    with open(os.path.join(plane_run["obs_dir"], "status.json")) as fh:
        disk = json.load(fh)
    obs_schema.assert_valid(disk, schemas["serve_status"],
                            "status.json", defs=schemas)
    slo = plane_run["summary"]["slo"]
    for leg in ("admission_ms", "first_result_ms", "converged_ms"):
        obs_schema.assert_valid(slo[leg], schemas["percentiles"],
                                f"slo.{leg}", defs=schemas)
        assert slo[leg]["p50"] <= slo[leg]["p99"] <= slo[leg]["max"]
    assert slo["n_converged"] == 4
    # prometheus text exposition: counters + the latency histograms
    prom = open(os.path.join(plane_run["obs_dir"],
                             "metrics.prom")).read()
    assert "# TYPE gst_serve_admissions counter" in prom
    assert 'gst_serve_admission_ms_bucket{le="+Inf"}' in prom
    assert "gst_serve_first_result_ms_count" in prom
    assert "gst_serve_converged_ms_count" in prom
    # serve_top renders both surfaces without touching jax
    import io
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import serve_top

    out = io.StringIO()
    assert serve_top.render(plane_run["obs_dir"], out=out)
    text = out.getvalue()
    assert "slo admission_ms" in text and "serve_top" in text
    out = io.StringIO()
    assert serve_top.render(plane_run["man_dir"], out=out)
    assert "manifest" in out.getvalue()
    out = io.StringIO()
    assert not serve_top.render(str(plane_run["obs_dir"]) + "_nope",
                                out=out)


# ----------------------------------------------------------------------
# schema-drift guard (the CI tripwire for docs/OBSERVABILITY.md)
# ----------------------------------------------------------------------


def test_emitted_records_validate_against_schemas(plane_run, schemas,
                                                 tmp_path):
    """Every record the smoke run emitted — events.jsonl lines, the
    run manifest, the serve crash-manifest journal, span JSONL — plus
    a freshly built ledger record and every record in the COMMITTED
    artifacts/ledger.jsonl validate against the checked-in schemas.
    A field rename in any emitter fails here, next to the docs it
    drifted from."""
    from gibbs_student_t_tpu.obs import ledger as ledger_mod
    from gibbs_student_t_tpu.obs.metrics import read_events

    for e in read_events(plane_run["run_dir"]):
        obs_schema.assert_valid(e, schemas["event"], "event line",
                                defs=schemas)
    with open(os.path.join(plane_run["run_dir"],
                           "manifest.json")) as fh:
        obs_schema.assert_valid(json.load(fh), schemas["manifest"],
                                "manifest.json", defs=schemas)
    from gibbs_student_t_tpu.serve.manifest import read_manifest

    recs = read_manifest(plane_run["man_dir"])
    assert recs, "serve manifest journaled nothing"
    for r in recs:
        obs_schema.assert_valid(r, schemas["serve_manifest_record"],
                                "serve manifest record", defs=schemas)
    for line in open(os.path.join(plane_run["obs_dir"],
                                  "spans.jsonl")):
        obs_schema.assert_valid(json.loads(line), schemas["span"],
                                "span line", defs=schemas)
    # the bench record path: a fresh record through make_record +
    # append_record + read_ledger round-trips schema-valid
    lpath = str(tmp_path / "ledger.jsonl")
    rec = ledger_mod.make_record(
        "bench", {"metric": "chain_sweeps_per_s", "value": 1.0},
        platform="cpu", config={"x": 1}, argv=["bench.py"])
    ledger_mod.append_record(rec, lpath)
    (back,) = ledger_mod.read_ledger(lpath)
    obs_schema.assert_valid(back, schemas["ledger_record"],
                            "fresh ledger record", defs=schemas)
    # the committed evidence trail stays valid too — the guard that
    # catches a schema change breaking historical readers
    committed = os.path.join(os.path.dirname(__file__), "..",
                             "artifacts", "ledger.jsonl")
    n = 0
    for r in ledger_mod.read_ledger(committed):
        obs_schema.assert_valid(r, schemas["ledger_record"],
                                f"committed ledger record "
                                f"({r.get('tool')})", defs=schemas)
        n += 1
    assert n >= 10


# ----------------------------------------------------------------------
# bitwise + failure-path contracts
# ----------------------------------------------------------------------


def test_plane_on_off_chains_bitwise(demo, plane_run):
    """The plane is pure host bookkeeping: the SAME 4-tenant schedule
    with spans/monitor/obs_dir all disabled — and no HTTP server,
    where the plane run serves one, so this is also the round-14
    wire-on-vs-off pin — produces bitwise-identical per-tenant
    results (every field, incl. per-TOA)."""
    ma, cfg = demo
    # the ENTIRE plane off: no spans, no flight recorder, no watchdog,
    # kernel timers down — vs the fixture's everything-on run
    srv = ChainServer(ma, cfg, nlanes=32, quantum=5, record="full",
                      spans=False, flight=False, watchdog=False,
                      kernel_timers=False)
    hs = [srv.submit(TenantRequest(ma=ma, niter=n, nchains=16, seed=i,
                                   name=f"t{i}"))
          for i, n in enumerate(NITERS)]
    srv.run()
    srv.close()
    for h, ref in zip(hs, plane_run["results"]):
        res = h.result()
        for f in ("chain", "zchain", "thetachain", "dfchain", "bchain",
                  "alphachain", "poutchain"):
            assert np.array_equal(np.asarray(getattr(res, f)),
                                  np.asarray(getattr(ref, f))), f
        for k in ("acc_white", "acc_hyper"):
            assert np.array_equal(res.stats[k], ref.stats[k]), k


def test_observability_failures_warn_and_continue(demo, tmp_path,
                                                  monkeypatch):
    """Sink IO error + monitor exception + obs refresh failure, all in
    one run: every tenant still completes 'done' with intact results,
    faults counters stay zero — observability never fails a tenant or
    the pool (the PR 1 contract, serving edition)."""
    from gibbs_student_t_tpu.serve.monitor import TenantMonitor

    ma, cfg = demo
    obs_dir = str(tmp_path / "obs")

    def boom(self, *a, **k):
        raise RuntimeError("injected monitor failure")

    monkeypatch.setattr(TenantMonitor, "update", boom)
    srv = ChainServer(ma, cfg, nlanes=32, quantum=5, record="full",
                      obs_dir=obs_dir,
                      trace_jsonl=str(tmp_path / "spans.jsonl"))
    # break the span sink AND the obs_dir refresh mid-flight
    srv.spans._sink.close()
    import shutil

    shutil.rmtree(obs_dir)
    # a file where the directory should be makes the atomic replace
    # fail on every refresh, not just the first
    with open(obs_dir, "w") as fh:
        fh.write("not a directory")
    hs = [srv.submit(TenantRequest(
        ma=ma, niter=10, nchains=16, seed=i, name=f"f{i}",
        monitor=MonitorSpec(params=[0])))
        for i in range(2)]
    with pytest.warns(RuntimeWarning):
        srv.run()
        srv.close()
    for h in hs:
        assert h.status == "done"
        res = h.result()
        assert res.chain.shape[0] == 10
        # the monitor was detached, not the tenant
        assert h._monitor is None
        assert res.stats.get("converged_at") is None
    s = srv.summary()
    assert s["faults"]["tenant_failures"] == 0
    assert s["faults"]["pool_failures"] == 0


# ----------------------------------------------------------------------
# the observability wire (round 14): HTTP endpoints, cost, fleet merge
# ----------------------------------------------------------------------


def test_http_endpoints_serve_schema_valid(plane_run, schemas):
    """The acceptance pin: /healthz, /status, /metrics, /trace and
    /tenants/<id>/progress all serve schema-valid bodies from the live
    4-tenant run (and again once idle), with id-or-name tenant lookup
    and 404s for unknown tenants/routes."""
    live, idle = plane_run["live"], plane_run["idle"]
    assert live, "mid-run fetch never fired"
    for phase in (live, idle):
        code, body = phase["/healthz"]
        h = json.loads(body)
        assert code == 200 and h["ok"] is True
        obs_schema.assert_valid(h, schemas["healthz"], "healthz",
                                defs=schemas)
        code, body = phase["/status"]
        assert code == 200
        st = json.loads(body)
        obs_schema.assert_valid(st, schemas["serve_status"],
                                "GET /status", defs=schemas)
        code, body = phase["/metrics"]
        assert code == 200
        assert "# TYPE gst_serve_queue_depth gauge" in body
        assert "# HELP gst_serve_queue_depth" in body
        code, body = phase["/trace"]
        assert code == 200
        obs_schema.assert_valid(json.loads(body),
                                schemas["chrome_trace"], "GET /trace",
                                defs=schemas)
    # the live snapshot really was live: lanes busy, tenants listed
    st = json.loads(live["/status"][1])
    assert st["busy_lanes"] > 0 and st["tenants"]
    assert st["slo_raw"]["admission_ms"]  # raw series for fleet merge
    # tenant progress: by id and by name, same tenant shapes
    code, body = live["/tenants/0/progress"]
    assert code == 200
    p0 = json.loads(body)
    assert p0["tenant_id"] == 0 and p0["name"] == "t0"
    obs_schema.assert_valid(p0["cost"], schemas["cost"],
                            "progress cost", defs=schemas)
    code, body = live["/tenants/t1/progress"]
    assert code == 200 and json.loads(body)["tenant_id"] == 1
    assert live["/tenants/nope/progress"][0] == 404
    assert live["/nope"][0] == 404


def test_http_server_down_after_close(plane_run):
    """close() tears the wire down deterministically — the port stops
    accepting (the fixture already closed the server)."""
    import urllib.error
    import urllib.request

    with pytest.raises((urllib.error.URLError, OSError)):
        urllib.request.urlopen(plane_run["url"] + "/healthz",
                               timeout=2.0)


def test_cost_accounting_reconciles(plane_run, schemas):
    """Per-tenant cost: the active-lane-share attributions sum back to
    the measured dispatch wall (within 5% — exact by construction),
    lane_quanta counts chains x quanta, the block rides progress() AND
    result().stats, and monitored tenants price their ESS per
    core-second."""
    handles = plane_run["handles"]
    wall = plane_run["summary"]["cost"]["dispatch_wall_ms"]
    assert wall > 0
    total = sum(h.cost()["device_ms"] for h in handles)
    assert abs(total - wall) <= 0.05 * wall, (total, wall)
    for h, res, niter in zip(handles, plane_run["results"], NITERS):
        c = h.cost()
        obs_schema.assert_valid(c, schemas["cost"], "handle cost",
                                defs=schemas)
        assert c["device_ms"] > 0
        # 16 active chains x (niter/quantum) quanta, no quarantines
        assert c["lane_quanta"] == 16 * (niter // 5)
        assert c["ess_per_core_s"] is not None \
            and c["ess_per_core_s"] > 0
        assert res.stats["cost"] == c   # the finalize-time snapshot
        assert h.progress()["cost"] == c


def test_fleet_status_merges_pools_and_reports_unreachable(plane_run,
                                                           schemas):
    """The 2-pool fleet merge acceptance pin: two pools (the shared
    run's obs_dir, once as a directory and once as a status.json
    path) merge into a schema-valid fleet snapshot with summed totals
    and SLO percentiles recomputed from the concatenated raw series;
    a third, deliberately unreachable pool is REPORTED, never
    fatal."""
    from gibbs_student_t_tpu.obs.aggregate import fleet_status

    obs_dir = plane_run["obs_dir"]
    dead = "http://127.0.0.1:9"   # discard port: connection refused
    snap = fleet_status(
        [obs_dir, os.path.join(obs_dir, "status.json"), dead],
        timeout=0.5)
    obs_schema.assert_valid(snap, schemas["fleet_status"],
                            "fleet snapshot", defs=schemas)
    assert snap["n_pools"] == 3 and snap["n_reachable"] == 2
    down = [p for p in snap["pools"] if not p["reachable"]]
    assert len(down) == 1 and down[0]["source"] == dead
    assert down[0]["error"]
    for p in snap["pools"]:
        if p["reachable"]:
            assert p["healthy"] is True
    # totals sum over the two reachable copies of the same pool
    assert snap["totals"]["nlanes"] == 64
    # merged percentiles come from the concatenated raw series: the
    # doubled series has the same p50 as one pool's
    with open(os.path.join(obs_dir, "status.json")) as fh:
        st = json.load(fh)
    series = st["slo_raw"]["admission_ms"]
    assert series, "pool status carries no raw admission series"
    assert snap["slo"]["admission_ms"]["p50"] == pytest.approx(
        float(np.percentile(np.asarray(series + series, float), 50)),
        abs=1e-3)   # the aggregator rounds percentiles to 3 decimals
    assert snap["slo"]["n_converged"] == 2 * st["slo"]["n_converged"]


# ----------------------------------------------------------------------
# the deep profiling plane (round 15): stage timings, flight recorder
# ----------------------------------------------------------------------


def _timers_on(plane_run):
    return plane_run["summary"].get("stages") is not None


def test_stage_timings_and_watchdog_blocks(plane_run, schemas):
    """status()/summary() carry the round-15 blocks: the per-stage
    device-time view (schema ``stage_timings``; shares of dispatch sum
    below 1 — device time can never exceed the wall that contains it)
    and the watchdog block (untripped on a clean run); per-tenant cost
    stage shares sum back to the server's stage totals (the same
    reconciliation discipline as device_ms)."""
    st = plane_run["status"]
    obs_schema.assert_valid(st["watchdog"], schemas["watchdog"],
                            "status watchdog", defs=schemas)
    assert st["watchdog"]["enabled"] and st["watchdog"]["state"] == "ok"
    hb = st["watchdog"]["heartbeat_age_s"]
    assert "dispatch" in hb and "drain" in hb
    if not _timers_on(plane_run):
        pytest.skip("native kernel timers unavailable on this host")
    stages = plane_run["summary"]["stages"]
    obs_schema.assert_valid(stages, schemas["stage_timings"],
                            "summary stages", defs=schemas)
    assert stages, "timers on but no stage accumulated"
    share = sum(v["share_of_dispatch"] or 0.0 for v in stages.values())
    assert 0.0 < share <= 1.0, share
    # per-tenant attribution reconciles with the totals stage by stage
    per_tenant = {}
    for h in plane_run["handles"]:
        for k, v in (h.cost().get("stage_device_ms") or {}).items():
            per_tenant[k] = per_tenant.get(k, 0.0) + v
    assert set(per_tenant) == set(stages)
    for k, v in stages.items():
        assert abs(per_tenant[k] - v["device_ms"]) \
            <= 0.02 * v["device_ms"] + 0.01, (k, per_tenant[k], v)


def test_postmortem_bundle_endpoint_and_flight_sync(plane_run,
                                                    schemas):
    """GET /postmortem serves a schema-valid bundle live AND idle;
    dump_postmortem() leaves the same (schema-valid) document on disk
    with the span tail; the periodic spanless flight.json sync exists
    after a multi-quantum run and validates too (the os._exit
    durability arm's artifact)."""
    for phase in (plane_run["live"], plane_run["idle"]):
        code, body = phase["/postmortem"]
        assert code == 200
        doc = json.loads(body)
        obs_schema.assert_valid(doc, schemas["postmortem"],
                                "GET /postmortem", defs=schemas)
    pm = json.load(open(plane_run["pm_path"]))
    obs_schema.assert_valid(pm, schemas["postmortem"], "postmortem",
                            defs=schemas)
    assert pm["reason"] == "fixture"
    assert pm["quanta"], "no quantum entries in the ring"
    assert "spans" in pm and pm["spans"]
    # ring entries tell the quantum story: dispatch wall + occupancy
    # + (with timers) the stage split
    q0 = pm["quanta"][0]
    assert q0["dispatch_ms"] > 0 and q0["busy_lanes"] > 0
    if _timers_on(plane_run):
        assert q0["stage_device_ms"]
    kinds = {e["kind"] for e in pm["events"]}
    assert "admit" in kinds and "evict" in kinds
    fj_path = os.path.join(plane_run["obs_dir"], "flight.json")
    assert os.path.exists(fj_path), "periodic flight sync never fired"
    fj = json.load(open(fj_path))
    obs_schema.assert_valid(fj, schemas["postmortem"], "flight.json",
                            defs=schemas)
    assert fj["reason"] == "sync" and "spans" not in fj
    # the renderer tool reads both, no jax import
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "postmortem_tool",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "postmortem.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)
    import io

    out = io.StringIO()
    doc, path = tool.load_bundle(plane_run["obs_dir"])
    tool.render(doc, path, out=out)
    text = out.getvalue()
    assert "postmortem" in text and "timeline:" in text
    assert tool.main([plane_run["obs_dir"]]) == 0
    assert tool.main([plane_run["obs_dir"] + "_nope"]) == 1


def test_metrics_auto_created_for_obs_dir(demo, tmp_path):
    """obs_dir without an explicit registry still gets an exposition
    (an in-memory MetricsRegistry is created) — cheap: no server run,
    construction + one refresh only."""
    ma, cfg = demo
    srv = ChainServer(ma, cfg, nlanes=32, quantum=5,
                      obs_dir=str(tmp_path / "o"))
    assert srv.metrics is not None
    srv._refresh_obs()
    assert os.path.exists(str(tmp_path / "o" / "status.json"))
    srv.close()
